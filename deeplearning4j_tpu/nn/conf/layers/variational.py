"""Variational autoencoder, RBM, and center-loss output layers — the pretraining family.

Parity targets:
- ref nn/conf/layers/variational/VariationalAutoencoder.java:47 (config surface:
  encoderLayerSizes/decoderLayerSizes/pzxActivationFn/numSamples/reconstructionDistribution)
  and nn/layers/variational/VariationalAutoencoder.java (1,151 LoC of hand-written
  forward/backprop) — here the ELBO is a pure function and `jax.grad` replaces the whole
  backprop half.
- ref nn/conf/layers/variational/{Gaussian,Bernoulli,Exponential,Composite,
  LossFunctionWrapper}ReconstructionDistribution.java + ReconstructionDistribution.java.
- ref nn/conf/layers/RBM.java:65 + nn/layers/feedforward/rbm/RBM.java (CD-k gibbs chain,
  contrastiveDivergence at :102). CD is not the gradient of a tractable scalar, so RBM
  exposes `pretrain_grads` (direct positive-phase − negative-phase statistics) instead of
  `pretrain_score`; the gibbs chain is a fixed-k unrolled jittable loop.
- ref nn/conf/layers/CenterLossOutputLayer.java:63 (alpha/lambda/gradientCheck) +
  nn/layers/training/CenterLossOutputLayer.java + params/CenterLossParamInitializer.java:52
  (CENTER_KEY "cL", centers shape [numClasses, nIn]).

TPU notes: every distribution's log-prob is elementwise math over the decoder's fused
matmul output; num_samples Monte-Carlo samples are batched via a leading sample axis so
the decoder matmuls stay large on the MXU instead of looping.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.common.enums import Activation, LossFunction
from deeplearning4j_tpu.nn.activations import apply_activation
from deeplearning4j_tpu.nn.conf.input_type import InputType
from deeplearning4j_tpu.nn.conf.layers.base import (
    FeedForwardLayerConf, register_layer)
from deeplearning4j_tpu.nn.conf.layers.feedforward import OutputLayer
from deeplearning4j_tpu.nn.losses import compute_loss

DIST_REGISTRY: dict[str, type] = {}

_HALF_LOG_2PI = 0.5 * float(jnp.log(2 * jnp.pi))


def register_dist(cls):
    DIST_REGISTRY[cls.__name__] = cls
    return cls


class ReconstructionDistribution:
    """p(x|z) head for the VAE decoder (ref ReconstructionDistribution.java).

    `param_size(data_size)` gives the decoder output width; `neg_log_prob` consumes the
    decoder pre-activations and returns a per-example negative log-likelihood."""

    def param_size(self, data_size: int) -> int:
        raise NotImplementedError

    def neg_log_prob(self, x: jnp.ndarray, preout: jnp.ndarray) -> jnp.ndarray:
        """Per-example -log p(x|z); x (batch, d), preout (batch, param_size(d))."""
        raise NotImplementedError

    def generate_at_mean(self, preout: jnp.ndarray) -> jnp.ndarray:
        raise NotImplementedError

    def generate_random(self, rng: jax.Array, preout: jnp.ndarray) -> jnp.ndarray:
        raise NotImplementedError

    def has_log_prob(self) -> bool:
        """False for LossFunctionWrapper (ref hasLossFunction semantics)."""
        return True

    # ------------- serde -------------
    def to_dict(self) -> dict:
        d = dict(self.__dict__)
        d["@dist"] = type(self).__name__
        return d

    @staticmethod
    def from_dict(d: dict) -> "ReconstructionDistribution":
        d = dict(d)
        cls = DIST_REGISTRY[d.pop("@dist")]
        return cls._from_fields(d)

    @classmethod
    def _from_fields(cls, d: dict):
        import enum as _enum
        import typing
        kwargs = {}
        hints = typing.get_type_hints(cls.__init__)
        import inspect
        sig = inspect.signature(cls.__init__)
        for k, v in d.items():
            if k not in sig.parameters:
                continue
            hint = hints.get(k)
            if isinstance(hint, type) and issubclass(hint, _enum.Enum) and v is not None:
                v = hint(v)
            kwargs[k] = v
        return cls(**kwargs)


@register_dist
class GaussianReconstructionDistribution(ReconstructionDistribution):
    """N(mu, sigma^2) per output; decoder emits [mu_preact | log(sigma^2)]
    (ref GaussianReconstructionDistribution.java — activation applies to the mean half
    only, log-variance half stays identity)."""

    def __init__(self, activation: Activation = Activation.IDENTITY):
        self.activation = Activation(activation)

    def param_size(self, data_size):
        return 2 * data_size

    def _split(self, preout):
        d = preout.shape[-1] // 2
        mu = apply_activation(self.activation, preout[..., :d])
        log_var = preout[..., d:]
        return mu, log_var

    def neg_log_prob(self, x, preout):
        mu, log_var = self._split(preout)
        nll = _HALF_LOG_2PI + 0.5 * log_var + 0.5 * jnp.square(x - mu) / jnp.exp(log_var)
        return jnp.sum(nll, axis=-1)

    def generate_at_mean(self, preout):
        return self._split(preout)[0]

    def generate_random(self, rng, preout):
        mu, log_var = self._split(preout)
        return mu + jnp.exp(0.5 * log_var) * jax.random.normal(rng, mu.shape, mu.dtype)


@register_dist
class BernoulliReconstructionDistribution(ReconstructionDistribution):
    """Bernoulli(p) per output with p = act(preout); default sigmoid
    (ref BernoulliReconstructionDistribution.java)."""

    def __init__(self, activation: Activation = Activation.SIGMOID):
        self.activation = Activation(activation)

    def param_size(self, data_size):
        return data_size

    def neg_log_prob(self, x, preout):
        if self.activation == Activation.SIGMOID:
            # fused stable form: softplus(z) - x*z
            nll = jax.nn.softplus(preout) - x * preout
        else:
            p = jnp.clip(apply_activation(self.activation, preout), 1e-7, 1 - 1e-7)
            nll = -(x * jnp.log(p) + (1 - x) * jnp.log1p(-p))
        return jnp.sum(nll, axis=-1)

    def generate_at_mean(self, preout):
        return apply_activation(self.activation, preout)

    def generate_random(self, rng, preout):
        p = apply_activation(self.activation, preout)
        return jax.random.bernoulli(rng, p).astype(p.dtype)


@register_dist
class ExponentialReconstructionDistribution(ReconstructionDistribution):
    """Exp(lambda) with lambda = exp(act(preout)) so the rate stays positive
    (ref ExponentialReconstructionDistribution.java: gamma = activation output,
    log p(x) = gamma - x*exp(gamma))."""

    def __init__(self, activation: Activation = Activation.IDENTITY):
        self.activation = Activation(activation)

    def param_size(self, data_size):
        return data_size

    def neg_log_prob(self, x, preout):
        gamma = apply_activation(self.activation, preout)
        return jnp.sum(x * jnp.exp(gamma) - gamma, axis=-1)

    def generate_at_mean(self, preout):
        gamma = apply_activation(self.activation, preout)
        return jnp.exp(-gamma)  # mean = 1/lambda

    def generate_random(self, rng, preout):
        gamma = apply_activation(self.activation, preout)
        u = jax.random.uniform(rng, gamma.shape, gamma.dtype, 1e-7, 1.0)
        return -jnp.log(u) * jnp.exp(-gamma)  # inverse-CDF


@register_dist
class CompositeReconstructionDistribution(ReconstructionDistribution):
    """Different distributions over slices of the data vector
    (ref CompositeReconstructionDistribution.java). `components` is a list of
    (data_size, distribution) pairs in data order."""

    def __init__(self, components: Sequence[Tuple[int, Any]] = ()):
        comps = []
        for size, dist in components:
            if isinstance(dist, dict):
                dist = ReconstructionDistribution.from_dict(dist)
            comps.append((int(size), dist))
        self.components = comps

    def param_size(self, data_size):
        assert data_size == sum(s for s, _ in self.components), \
            f"composite sizes {self.components} != data size {data_size}"
        return sum(d.param_size(s) for s, d in self.components)

    def _slices(self):
        xo = po = 0
        for size, dist in self.components:
            ps = dist.param_size(size)
            yield (xo, size, po, ps, dist)
            xo += size
            po += ps

    def neg_log_prob(self, x, preout):
        total = 0.0
        for xo, xs, po, ps, dist in self._slices():
            total = total + dist.neg_log_prob(x[..., xo:xo + xs], preout[..., po:po + ps])
        return total

    def generate_at_mean(self, preout):
        return jnp.concatenate([d.generate_at_mean(preout[..., po:po + ps])
                                for _, _, po, ps, d in self._slices()], axis=-1)

    def generate_random(self, rng, preout):
        outs = []
        for _, _, po, ps, d in self._slices():
            rng, sub = jax.random.split(rng)
            outs.append(d.generate_random(sub, preout[..., po:po + ps]))
        return jnp.concatenate(outs, axis=-1)

    def has_log_prob(self):
        return all(d.has_log_prob() for _, d in self.components)

    def to_dict(self):
        return {"@dist": "CompositeReconstructionDistribution",
                "components": [[s, d.to_dict()] for s, d in self.components]}


@register_dist
class LossFunctionWrapper(ReconstructionDistribution):
    """Arbitrary loss function as a pseudo reconstruction 'distribution'
    (ref LossFunctionWrapper.java — hasLossFunction()=true; reconstruction
    *probability* is unavailable, only the loss)."""

    def __init__(self, activation: Activation = Activation.IDENTITY,
                 loss_fn: LossFunction = LossFunction.MSE):
        self.activation = Activation(activation)
        self.loss_fn = LossFunction(loss_fn)

    def param_size(self, data_size):
        return data_size

    def has_log_prob(self):
        return False

    def neg_log_prob(self, x, preout):
        # per-example loss; compute_loss is mean-over-examples so scale back up per row
        # by computing it row-wise via vmap-free elementwise math: reuse compute_loss on
        # each example is wasteful — instead compute on full batch with examples kept.
        act = apply_activation(self.activation, preout)
        if self.loss_fn == LossFunction.MSE:
            per = jnp.sum(jnp.square(x - act), axis=-1)
        elif self.loss_fn == LossFunction.L1:
            per = jnp.sum(jnp.abs(x - act), axis=-1)
        elif self.loss_fn == LossFunction.XENT:
            p = jnp.clip(act, 1e-7, 1 - 1e-7)
            per = -jnp.sum(x * jnp.log(p) + (1 - x) * jnp.log1p(-p), axis=-1)
        else:
            raise ValueError(f"LossFunctionWrapper: unsupported {self.loss_fn}")
        return per

    def generate_at_mean(self, preout):
        return apply_activation(self.activation, preout)

    def generate_random(self, rng, preout):
        return self.generate_at_mean(preout)


# ======================================================================= VAE


@register_layer
@dataclass
class VariationalAutoencoder(FeedForwardLayerConf):
    """VAE as a single layer (ref conf/layers/variational/VariationalAutoencoder.java:47).

    Supervised forward = encoder -> mean of q(z|x) (ref impl activate()); pretraining
    maximizes the ELBO: E_q[log p(x|z)] - KL(q(z|x) || N(0,I)), with `num_samples`
    Monte-Carlo samples batched on a leading axis. n_out is the latent size.

    Param keys use the W_*/b_* convention so WEIGHT_KEY_PREFIXES-based l1/l2 applies to
    weights only, mirroring ref VariationalAutoencoderParamInitializer."""
    encoder_layer_sizes: Tuple[int, ...] = (100,)
    decoder_layer_sizes: Tuple[int, ...] = (100,)
    pzx_activation: Activation = Activation.IDENTITY
    num_samples: int = 1
    reconstruction_distribution: Optional[Any] = None  # ReconstructionDistribution

    def __post_init__(self):
        self.encoder_layer_sizes = tuple(self.encoder_layer_sizes)
        self.decoder_layer_sizes = tuple(self.decoder_layer_sizes)
        if self.reconstruction_distribution is None:
            # ref Builder default: Gaussian with TANH
            self.reconstruction_distribution = GaussianReconstructionDistribution(
                Activation.TANH)
        elif isinstance(self.reconstruction_distribution, dict):
            self.reconstruction_distribution = ReconstructionDistribution.from_dict(
                self.reconstruction_distribution)

    @property
    def dist_head(self) -> ReconstructionDistribution:
        return self.reconstruction_distribution

    def is_pretrain_layer(self):
        return True

    # ---------------- params ----------------
    def init_params(self, key, input_type, dtype=jnp.float32):
        p = {}
        sizes = [self.n_in] + list(self.encoder_layer_sizes)
        keys = jax.random.split(key, len(self.encoder_layer_sizes)
                                + len(self.decoder_layer_sizes) + 3)
        ki = 0
        for i in range(len(self.encoder_layer_sizes)):
            fi, fo = sizes[i], sizes[i + 1]
            p[f"W_e{i}"] = self._winit(keys[ki], (fi, fo), fi, fo, dtype)
            p[f"b_e{i}"] = jnp.full((fo,), self.bias_init, dtype)
            ki += 1
        enc_out = sizes[-1]
        p["W_zm"] = self._winit(keys[ki], (enc_out, self.n_out), enc_out, self.n_out,
                                dtype)
        p["b_zm"] = jnp.zeros((self.n_out,), dtype)
        ki += 1
        p["W_zv"] = self._winit(keys[ki], (enc_out, self.n_out), enc_out, self.n_out,
                                dtype)
        p["b_zv"] = jnp.zeros((self.n_out,), dtype)
        ki += 1
        dsizes = [self.n_out] + list(self.decoder_layer_sizes)
        for i in range(len(self.decoder_layer_sizes)):
            fi, fo = dsizes[i], dsizes[i + 1]
            p[f"W_d{i}"] = self._winit(keys[ki], (fi, fo), fi, fo, dtype)
            p[f"b_d{i}"] = jnp.full((fo,), self.bias_init, dtype)
            ki += 1
        px = self.dist_head.param_size(self.n_in)
        p["W_x"] = self._winit(keys[ki], (dsizes[-1], px), dsizes[-1], px, dtype)
        p["b_x"] = jnp.zeros((px,), dtype)
        return p

    # ---------------- compute ----------------
    def _encode(self, params, x):
        h = x
        for i in range(len(self.encoder_layer_sizes)):
            h = self._act(h @ params[f"W_e{i}"] + params[f"b_e{i}"])
        mu = apply_activation(self.pzx_activation, h @ params["W_zm"] + params["b_zm"])
        log_var = h @ params["W_zv"] + params["b_zv"]
        return mu, log_var

    def _decode(self, params, z):
        h = z
        for i in range(len(self.decoder_layer_sizes)):
            h = self._act(h @ params[f"W_d{i}"] + params[f"b_d{i}"])
        return h @ params["W_x"] + params["b_x"]

    def forward(self, params, state, x, *, train, rng=None, mask=None):
        mu, _ = self._encode(params, x)
        return mu, state, mask

    def pretrain_score(self, params, x, rng):
        """-ELBO, mean over the minibatch (ref impl computeGradientAndScore for
        pretrain mode — negated since we minimize)."""
        mu, log_var = self._encode(params, x)
        kl = -0.5 * jnp.sum(1.0 + log_var - jnp.square(mu) - jnp.exp(log_var), axis=-1)
        if rng is None:
            rng = jax.random.PRNGKey(0)
        # (num_samples, batch, latent): batched sampling keeps decoder matmuls MXU-sized
        eps = jax.random.normal(rng, (self.num_samples,) + mu.shape, mu.dtype)
        z = mu[None] + jnp.exp(0.5 * log_var)[None] * eps
        preout = self._decode(params, z)
        nll = self.dist_head.neg_log_prob(x[None], preout)  # (num_samples, batch)
        return jnp.mean(kl + jnp.mean(nll, axis=0))

    # ---------------- inference-time utilities (ref impl public API) ----------------
    def reconstruction_log_probability(self, params, x, num_samples: int = 5,
                                       rng: Optional[jax.Array] = None):
        """log (1/S sum_s p(x|z_s)), z_s ~ q(z|x) — ref reconstructionLogProbability."""
        if not self.dist_head.has_log_prob():
            raise ValueError("reconstruction distribution has no log probability "
                             "(LossFunctionWrapper); use reconstruction_error")
        if rng is None:
            rng = jax.random.PRNGKey(0)
        mu, log_var = self._encode(params, x)
        eps = jax.random.normal(rng, (num_samples,) + mu.shape, mu.dtype)
        z = mu[None] + jnp.exp(0.5 * log_var)[None] * eps
        log_p = -self.dist_head.neg_log_prob(x[None], self._decode(params, z))
        return jax.scipy.special.logsumexp(log_p, axis=0) - jnp.log(float(num_samples))

    def reconstruction_error(self, params, x):
        """Deterministic reconstruction loss at the posterior mean
        (ref reconstructionError, defined for LossFunctionWrapper)."""
        mu, _ = self._encode(params, x)
        return self.dist_head.neg_log_prob(x, self._decode(params, mu))

    def generate_at_mean_given_z(self, params, z):
        return self.dist_head.generate_at_mean(self._decode(params, z))

    def generate_random_given_z(self, params, z, rng):
        return self.dist_head.generate_random(rng, self._decode(params, z))


# ======================================================================= RBM


@register_layer
@dataclass
class RBM(FeedForwardLayerConf):
    """Restricted Boltzmann machine (ref conf/layers/RBM.java:65, impl
    nn/layers/feedforward/rbm/RBM.java). Supervised forward = propUp through the layer
    activation; pretraining = CD-k via `pretrain_grads` (gibbs chain at ref :102-151,
    unrolled for static k — each step is two fused matmuls on the MXU).

    hidden_unit/visible_unit in {binary, gaussian, rectified, softmax}
    (ref RBM.HiddenUnit/VisibleUnit enums)."""
    hidden_unit: str = "binary"
    visible_unit: str = "binary"
    k: int = 1
    sparsity: float = 0.0

    def is_pretrain_layer(self):
        return True

    def init_params(self, key, input_type, dtype=jnp.float32):
        return {
            "W": self._winit(key, (self.n_in, self.n_out), self.n_in, self.n_out, dtype),
            "b": jnp.full((self.n_out,), self.bias_init, dtype),   # hidden bias
            "vb": jnp.zeros((self.n_in,), dtype),                   # visible bias
        }

    def forward(self, params, state, x, *, train, rng=None, mask=None):
        return self._act(x @ params["W"] + params["b"]), state, mask

    # ---------------- gibbs machinery (ref propUp/propDown at :224/:276) ------------
    def _unit_mean(self, kind, z):
        if kind == "binary":
            return jax.nn.sigmoid(z)
        if kind == "gaussian":
            return z
        if kind == "rectified":
            return jnp.maximum(z, 0.0)
        if kind == "softmax":
            return jax.nn.softmax(z, axis=-1)
        raise ValueError(f"unknown RBM unit type {kind!r}")

    def _unit_sample(self, kind, mean, z, rng):
        if kind == "binary":
            return jax.random.bernoulli(rng, mean).astype(mean.dtype)
        if kind == "gaussian":
            return mean + jax.random.normal(rng, mean.shape, mean.dtype)
        if kind == "rectified":
            # NReLU sampling (ref :241): max(0, z + N(0, sigmoid(z)))
            noise = jax.random.normal(rng, z.shape, z.dtype) * jnp.sqrt(
                jax.nn.sigmoid(z) + 1e-8)
            return jnp.maximum(z + noise, 0.0)
        if kind == "softmax":
            return jax.nn.one_hot(
                jax.random.categorical(rng, jnp.log(mean + 1e-12), axis=-1),
                mean.shape[-1], dtype=mean.dtype)
        raise ValueError(kind)

    def prop_up(self, params, v):
        z = v @ params["W"] + params["b"]
        return self._unit_mean(self.hidden_unit, z), z

    def prop_down(self, params, h):
        z = h @ params["W"].T + params["vb"]
        return self._unit_mean(self.visible_unit, z), z

    def pretrain_grads(self, params, x, rng):
        """CD-k gradient estimate: positive phase stats minus negative phase stats
        (ref contrastiveDivergence :102 / computeGradientAndScore :114). Returns
        (grads_dict, monitoring_score). Gradients point in the *descent* direction
        (they are subtracted by the updater, like autodiff grads)."""
        n = x.shape[0]
        h0_mean, h0_z = self.prop_up(params, x)
        rng, sub = jax.random.split(rng)
        h = self._unit_sample(self.hidden_unit, h0_mean, h0_z, sub)
        v_mean = x
        for _ in range(self.k):  # static k: unrolled, each iter two MXU matmuls
            v_mean, v_z = self.prop_down(params, h)
            rng, sub = jax.random.split(rng)
            v = self._unit_sample(self.visible_unit, v_mean, v_z, sub)
            hk_mean, hk_z = self.prop_up(params, v)
            rng, sub = jax.random.split(rng)
            h = self._unit_sample(self.hidden_unit, hk_mean, hk_z, sub)
        # gradient of -log p(v): -(positive - negative)
        gW = -(x.T @ h0_mean - v.T @ hk_mean) / n
        gb = -jnp.mean(h0_mean - hk_mean, axis=0)
        gvb = -jnp.mean(x - v, axis=0)
        if self.sparsity > 0:
            # sparsity penalty pushes mean hidden activation toward the target
            gb = gb + (jnp.mean(h0_mean, axis=0) - self.sparsity)
        score = jnp.mean(jnp.sum(jnp.square(x - v_mean), axis=-1))
        return {"W": gW, "b": gb, "vb": gvb}, score


# ======================================================== CenterLossOutputLayer


@register_layer
@dataclass
class CenterLossOutputLayer(OutputLayer):
    """Output layer with an auxiliary center loss (ref conf/layers/
    CenterLossOutputLayer.java:63, impl nn/layers/training/CenterLossOutputLayer.java).

    Centers `cL` have shape (n_out classes, n_in features)
    (ref CenterLossParamInitializer.java:52,80). Total score = base loss +
    lambda/2 * mean_i ||f_i - c_{y_i}||^2.

    gradient_check=True (default): centers are ordinary params of the combined scalar —
    exactly finite-difference checkable. gradient_check=False mirrors the reference's
    deployed behavior where centers move by an alpha-scaled EMA toward class feature
    means, decoupled from lambda (stop-gradient split form)."""
    alpha: float = 0.05
    lambda_: float = 2e-4
    gradient_check: bool = True

    def init_params(self, key, input_type, dtype=jnp.float32):
        p = super().init_params(key, input_type, dtype)
        p["cL"] = jnp.zeros((self.n_out, self.n_in), dtype)
        return p

    def regularization_score(self, params):
        # centers are never regularized (ref getL1ByParam/getL2ByParam return 0 for cL)
        return super().regularization_score({k: v for k, v in params.items()
                                             if k != "cL"})

    def compute_score(self, params, x, labels, mask=None):
        base = compute_loss(self.loss_fn, labels, self.preout(params, x),
                            self.activation, mask)
        centers = params["cL"]
        idx = jnp.argmax(labels, axis=-1)
        c = centers[idx]  # (batch, n_in) gather
        n = x.shape[0]

        def _row_term(a, b):
            per_row = jnp.sum(jnp.square(a - b), axis=-1)
            if mask is not None:
                # same masked-loss policy as compute_loss: zero masked rows, divide
                # by minibatch size — padding rows must not drag their class center
                m = jnp.reshape(mask, (n, -1))[:, 0].astype(per_row.dtype)
                per_row = per_row * m
            return jnp.sum(per_row) / n

        if self.gradient_check:
            center_term = 0.5 * self.lambda_ * _row_term(x, c)
        else:
            # split form: features feel lambda, centers feel alpha (ref backprop :63
            # applies alpha directly to the center delta, any updater on top)
            feat = 0.5 * self.lambda_ * _row_term(x, jax.lax.stop_gradient(c))
            cent = 0.5 * self.alpha * _row_term(jax.lax.stop_gradient(x), c)
            center_term = feat + cent
        return base + center_term
