"""Layer base classes: declarative config + pure-functional compute in one class.

Design note (TPU-first): the reference splits each layer into a config class
(nn/conf/layers/*) and an imperative implementation with hand-written backprop
(nn/layers/*, ref nn/api/Layer.java:38 activate/backpropGradient). Here a layer is a
single declarative object whose `forward` is a *pure function* — the network traces all
layers into one XLA computation and `jax.grad` replaces `backpropGradient` entirely.
There is no per-layer op dispatch at runtime.

Serde parity: like the reference's Jackson JSON round-trip
(nn/conf/NeuralNetConfiguration.java:328-349), every layer serializes to a dict with an
"@class" discriminator via LAYER_REGISTRY.
"""
from __future__ import annotations

import dataclasses
from dataclasses import dataclass, field
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.common.enums import Activation, GradientNormalization, WeightInit
from deeplearning4j_tpu.nn.activations import apply_activation
from deeplearning4j_tpu.nn.conf.input_type import InputType
from deeplearning4j_tpu.nn.weights import init_weights

LAYER_REGISTRY: dict[str, type] = {}

# Param keys regularized by l1/l2 (weights only, not biases — matching reference
# LayerValidation/BaseLayer l1/l2 semantics).
WEIGHT_KEY_PREFIXES = ("W", "RW", "gamma_w", "w_")


def register_layer(cls):
    """Register for serde AND wrap __init__ to record explicitly-passed kwargs.

    Explicit-set tracking is what lets the builder's global defaults apply only to
    fields the user did not set (ref NeuralNetConfiguration.Builder semantics, where
    unset layer fields are null until the global conf fills them). Without it, an
    explicit value equal to the class default would be silently overridden."""
    orig_init = cls.__init__
    field_names = [f.name for f in dataclasses.fields(cls)]

    def __init__(self, *args, **kwargs):
        orig_init(self, *args, **kwargs)
        explicit = set(kwargs.keys()) | set(field_names[:len(args)])
        object.__setattr__(self, "_explicit", explicit)

    cls.__init__ = __init__
    LAYER_REGISTRY[cls.__name__] = cls
    return cls


def _serde_value(v):
    import enum
    if isinstance(v, enum.Enum):
        return v.value
    if isinstance(v, InputType):
        return {"@input_type": v.to_dict()}
    if isinstance(v, BaseLayerConf):
        return v.to_dict()
    if isinstance(v, (list, tuple)):
        return [_serde_value(x) for x in v]
    if isinstance(v, dict):
        return {k: _serde_value(x) for k, x in v.items()}
    if hasattr(v, "to_dict"):
        return v.to_dict()
    return v


@dataclass
class BaseLayerConf:
    """Common fields mirroring ref nn/conf/layers/Layer + BaseLayer builders."""
    name: Optional[str] = None
    activation: Activation = Activation.IDENTITY
    weight_init: WeightInit = WeightInit.XAVIER
    dist: Optional[dict] = None
    bias_init: float = 0.0
    l1: float = 0.0
    l2: float = 0.0
    l1_bias: float = 0.0
    l2_bias: float = 0.0
    dropout: float = 0.0  # retain probability; 0 disables (ref util/Dropout.java semantics)
    updater: Optional[dict] = None  # per-layer updater override (serialized BaseUpdater)
    frozen: bool = False  # FrozenLayer semantics (ref nn/layers/FrozenLayer.java)
    gradient_normalization: GradientNormalization = GradientNormalization.NoNormalization
    gradient_normalization_threshold: float = 1.0
    # Per-param partition specs for model-parallel training: param name -> a
    # per-dimension list of mesh-axis names (or None), e.g. {"W": [None, "model"]}
    # for a Megatron column-parallel Dense kernel. None = use the trainer's auto
    # policy (parallel/sharded.py). JSON round-trips as plain dict-of-lists, so
    # sharded configs ship across processes like every other conf field.
    weight_sharding: Optional[Dict[str, Any]] = None

    # ---------------- shape / params ----------------
    def get_output_type(self, input_type: InputType) -> InputType:
        raise NotImplementedError

    def set_n_in(self, input_type: InputType, override: bool = False) -> None:
        """Infer nIn from the previous layer's output type (ListBuilder pass)."""
        return None

    def init_params(self, key: jax.Array, input_type: InputType, dtype=jnp.float32
                    ) -> Dict[str, jnp.ndarray]:
        return {}

    def init_state(self, input_type: InputType, dtype=jnp.float32) -> Dict[str, Any]:
        return {}

    # ---------------- compute ----------------
    def forward(self, params: Dict[str, jnp.ndarray], state: Dict[str, Any],
                x: jnp.ndarray, *, train: bool, rng: Optional[jax.Array] = None,
                mask: Optional[jnp.ndarray] = None
                ) -> Tuple[jnp.ndarray, Dict[str, Any], Optional[jnp.ndarray]]:
        """Returns (output, new_state, output_mask)."""
        raise NotImplementedError

    # Loss layers override these.
    def is_output_layer(self) -> bool:
        return False

    def has_params(self) -> bool:
        return True

    # ---------------- regularization ----------------
    def regularization_score(self, params: Dict[str, jnp.ndarray]) -> jnp.ndarray:
        s = jnp.asarray(0.0, jnp.float32)
        if self.frozen:
            return s  # frozen layers contribute no regularization (FrozenLayer)
        for k, p in params.items():
            is_weight = any(k.startswith(pref) for pref in WEIGHT_KEY_PREFIXES)
            l1 = self.l1 if is_weight else self.l1_bias
            l2 = self.l2 if is_weight else self.l2_bias
            if l1:
                s = s + l1 * jnp.sum(jnp.abs(p))
            if l2:
                s = s + 0.5 * l2 * jnp.sum(jnp.square(p))
        return s

    # ---------------- helpers ----------------
    def _act(self, z):
        return apply_activation(self.activation, z)

    def _winit(self, key, shape, fan_in, fan_out, dtype):
        return init_weights(key, shape, fan_in, fan_out, self.weight_init,
                            distribution=self.dist, dtype=dtype)

    # ---------------- serde ----------------
    def to_dict(self) -> dict:
        d = {}
        for f in dataclasses.fields(self):
            d[f.name] = _serde_value(getattr(self, f.name))
        d["@class"] = type(self).__name__
        return d

    @staticmethod
    def from_dict(d: dict) -> "BaseLayerConf":
        d = dict(d)
        cls = LAYER_REGISTRY[d.pop("@class")]
        kwargs = {}
        fields = {f.name: f for f in dataclasses.fields(cls)}
        hints = _resolved_hints(cls)
        for k, v in d.items():
            if k not in fields:
                continue
            kwargs[k] = _deserde_value(hints.get(k), v)
        return cls(**kwargs)


def _resolved_hints(cls):
    import typing
    try:
        return typing.get_type_hints(cls)
    except Exception:
        return {}


def _deserde_value(hint, v):
    import enum as _enum
    import typing
    if v is None:
        return None
    if isinstance(v, dict) and "@input_type" in v:
        return InputType.from_dict(v["@input_type"])
    if isinstance(v, dict) and "@class" in v:
        name = v["@class"]
        if name in LAYER_REGISTRY:
            return BaseLayerConf.from_dict(v)
        from deeplearning4j_tpu.nn.updater.updaters import UPDATER_REGISTRY, BaseUpdater
        if name in UPDATER_REGISTRY:
            return BaseUpdater.from_dict(v)
    origin = typing.get_origin(hint)
    if origin is typing.Union:
        args = [a for a in typing.get_args(hint) if a is not type(None)]
        hint = args[0] if len(args) == 1 else None
        return _deserde_value(hint, v)
    if isinstance(hint, type) and issubclass(hint, _enum.Enum):
        return hint(v)
    if isinstance(v, list):
        return tuple(v) if origin is tuple else [
            _deserde_value(None, x) for x in v]
    return v


def apply_dropout(x: jnp.ndarray, retain_prob: float, rng: jax.Array) -> jnp.ndarray:
    """Inverted dropout on layer *input* (ref util/Dropout.java applied in
    applyDropOutIfNecessary before the layer op)."""
    keep = jax.random.bernoulli(rng, retain_prob, x.shape)
    return jnp.where(keep, x / retain_prob, 0.0).astype(x.dtype)


@dataclass
class FeedForwardLayerConf(BaseLayerConf):
    """Base for layers with explicit n_in/n_out (ref nn/conf/layers/FeedForwardLayer)."""
    n_in: int = 0
    n_out: int = 0

    def set_n_in(self, input_type: InputType, override: bool = False) -> None:
        if self.n_in == 0 or override:
            self.n_in = input_type.flat_size() if input_type.kind in ("cnn", "cnn_flat") \
                else input_type.size

    def get_output_type(self, input_type: InputType) -> InputType:
        return InputType.feed_forward(self.n_out)
