"""Multi-head self-attention over recurrent streams.

Beyond-reference (the 2017 reference predates attention entirely — SURVEY §5
long-context: "no attention layer at all"); this is the long-context primitive
the TPU framework adds: a layer over the framework's recurrent activation
layout (batch, size, time) that composes with configs, masking, serialization,
and ShardedTrainer. Context parallelism comes in two forms:

- GSPMD: ShardedTrainer.Builder().sequence_axis("seq") shards the TIME
  dimension of recurrent inputs over a mesh axis; the attention einsums then
  partition across chips with XLA inserting the collectives (correct for
  causal + masked attention — softmax normalizers reduce over the sharded
  axis).
- hand-scheduled: parallel/sequence_parallel.py's ring_attention (k/v blocks
  rotating via ppermute with online softmax) remains the explicitly-scheduled
  alternative for very long sequences.
"""
from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.nn.conf.input_type import InputType
from deeplearning4j_tpu.nn.conf.layers.base import (
    FeedForwardLayerConf, register_layer)
from deeplearning4j_tpu.parallel.sequence_parallel import NEG_INF as _NEG_INF


@register_layer
@dataclass
class SelfAttentionLayer(FeedForwardLayerConf):
    """(batch, n_in, time) -> (batch, n_out, time); n_out % n_heads == 0.
    Pre-softmax masking drops padded timesteps (the framework's (batch, time)
    feature masks); `causal` gives autoregressive attention."""
    n_heads: int = 4
    causal: bool = False

    def set_n_in(self, input_type, override=False):
        if self.n_in == 0 or override:
            self.n_in = input_type.size
        if self.n_out == 0:
            self.n_out = self.n_in

    def get_output_type(self, input_type):
        return InputType.recurrent(self.n_out,
                                   getattr(input_type, "timeseries_length", -1))

    def init_params(self, key, input_type, dtype=jnp.float32):
        if self.n_out % self.n_heads != 0:
            raise ValueError(f"n_out {self.n_out} % n_heads {self.n_heads} != 0")
        kq, kk, kv, ko = jax.random.split(key, 4)
        shape = (self.n_in, self.n_out)
        w = lambda k: self._winit(k, shape, self.n_in, self.n_out, dtype)
        return {"w_q": w(kq), "w_k": w(kk), "w_v": w(kv),
                "w_o": self._winit(ko, (self.n_out, self.n_out), self.n_out,
                                   self.n_out, dtype),
                "b": jnp.full((self.n_out,), self.bias_init, dtype)}

    def forward(self, params, state, x, *, train, rng=None, mask=None):
        if x.ndim != 3:
            raise ValueError("SelfAttentionLayer expects (batch, size, time)")
        B, _, T = x.shape
        H = self.n_heads
        Dh = self.n_out // H
        xt = jnp.swapaxes(x, 1, 2)                       # (B, T, n_in)

        def heads(w):
            return jnp.reshape(xt @ w, (B, T, H, Dh)).transpose(0, 2, 1, 3)

        q, k, v = heads(params["w_q"]), heads(params["w_k"]), heads(params["w_v"])
        scores = jnp.einsum("bhqd,bhkd->bhqk", q, k) / np.sqrt(Dh)
        if self.causal:
            scores = jnp.where(jnp.tril(jnp.ones((T, T), bool)), scores,
                               _NEG_INF)
        if mask is not None:  # (B, T) padding mask: keys at padded steps drop
            scores = jnp.where(mask[:, None, None, :] > 0, scores, _NEG_INF)
        attn = jax.nn.softmax(scores, axis=-1)
        out = jnp.einsum("bhqk,bhkv->bhqv", attn, v)     # (B, H, T, Dh)
        out = out.transpose(0, 2, 1, 3).reshape(B, T, self.n_out)
        out = out @ params["w_o"] + params["b"]
        out = self._act(out)
        if mask is not None:  # zero padded query positions like RnnOutputLayer
            out = out * mask[:, :, None].astype(out.dtype)
        return jnp.swapaxes(out, 1, 2), state, mask
