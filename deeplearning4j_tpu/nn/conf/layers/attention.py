"""Multi-head self-attention over recurrent streams.

Beyond-reference (the 2017 reference predates attention entirely — SURVEY §5
long-context: "no attention layer at all"); this is the long-context primitive
the TPU framework adds: a layer over the framework's recurrent activation
layout (batch, size, time) that composes with configs, masking, serialization,
and ShardedTrainer.

Long sequences never materialize the (B, H, T, T) score tensor: past
`block_size` timesteps the layer computes attention through the online-softmax
block recurrence (`blockwise_attention`, lax.scan over k/v blocks — peak
activation memory O(T * block), flash-attention's recurrence on one device).
Context parallelism comes in two forms:

- GSPMD: ShardedTrainer.Builder().sequence_axis("seq") shards the TIME
  dimension of recurrent inputs over a mesh axis; the attention einsums then
  partition across chips with XLA inserting the collectives (correct for
  causal + masked attention — softmax normalizers reduce over the sharded
  axis).
- hand-scheduled ring: ShardedTrainer.Builder().sequence_axis("seq")
  .ring_attention(True) routes this layer through
  parallel/sequence_parallel.py's ring_attention — k/v (+ key-mask) blocks
  rotate via ppermute with the same online-softmax accumulator, so per-chip
  memory is O((T/n_chips) * block) and communication is nearest-neighbor ICI.
"""
from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.nn.conf.input_type import InputType
from deeplearning4j_tpu.nn.conf.layers.base import (
    FeedForwardLayerConf, register_layer)
from deeplearning4j_tpu.parallel.sequence_parallel import (
    NEG_INF as _NEG_INF, blockwise_attention, current_attention_context,
    ring_attention)


@register_layer
@dataclass
class SelfAttentionLayer(FeedForwardLayerConf):
    """(batch, n_in, time) -> (batch, n_out, time); n_out % n_heads == 0.
    Pre-softmax masking drops padded timesteps (the framework's (batch, time)
    feature masks); `causal` gives autoregressive attention. `block_size`:
    sequences longer than this use the O(T * block) online-softmax path
    (0 disables blockwise and forces the dense score tensor)."""
    n_heads: int = 4
    causal: bool = False
    block_size: int = 128
    # sliding-window (local) attention: > 0 limits each query to the
    # trailing `attention_window` keys (causal) or the symmetric band
    # (non-causal) — flash_attention semantics; cost scales with T*window
    attention_window: int = 0
    # grouped-query attention: 0 -> n_heads (plain MHA); otherwise k/v are
    # projected to n_kv_heads heads and query head h reads kv head
    # h // (n_heads // n_kv_heads) — the same grouping as
    # ops/flash_attention._kv_row. Shrinks the k/v params and, above all,
    # the serving KV cache (serving/kv_cache.py) by the group factor; the
    # training forward broadcasts k/v back to n_heads, so every attention
    # path (dense/blockwise/ring/flash) and its backward stay unchanged
    n_kv_heads: int = 0

    def set_n_in(self, input_type, override=False):
        if self.n_in == 0 or override:
            self.n_in = input_type.size
        if self.n_out == 0:
            self.n_out = self.n_in

    def get_output_type(self, input_type):
        return InputType.recurrent(self.n_out,
                                   getattr(input_type, "timeseries_length", -1))

    @property
    def kv_heads(self) -> int:
        return self.n_kv_heads or self.n_heads

    def init_params(self, key, input_type, dtype=jnp.float32):
        if self.n_out % self.n_heads != 0:
            raise ValueError(f"n_out {self.n_out} % n_heads {self.n_heads} != 0")
        if self.n_heads % self.kv_heads != 0:
            raise ValueError(f"n_heads {self.n_heads} % n_kv_heads "
                             f"{self.kv_heads} != 0")
        kq, kk, kv, ko = jax.random.split(key, 4)
        kv_out = self.kv_heads * (self.n_out // self.n_heads)
        w = lambda k, o: self._winit(k, (self.n_in, o), self.n_in, o, dtype)
        return {"w_q": w(kq, self.n_out), "w_k": w(kk, kv_out),
                "w_v": w(kv, kv_out),
                "w_o": self._winit(ko, (self.n_out, self.n_out), self.n_out,
                                   self.n_out, dtype),
                "b": jnp.full((self.n_out,), self.bias_init, dtype)}

    def forward(self, params, state, x, *, train, rng=None, mask=None):
        if x.ndim != 3:
            raise ValueError("SelfAttentionLayer expects (batch, size, time)")
        B, _, T = x.shape
        H = self.n_heads
        Dh = self.n_out // H
        xt = jnp.swapaxes(x, 1, 2)                       # (B, T, n_in)

        Hk = self.kv_heads

        def heads(w, h=H):
            return jnp.reshape(xt @ w, (B, T, h, Dh)).transpose(0, 2, 1, 3)

        q = heads(params["w_q"])
        k, v = heads(params["w_k"], Hk), heads(params["w_v"], Hk)
        if Hk != H:   # broadcast kv groups to full heads (see n_kv_heads doc)
            k = jnp.repeat(k, H // Hk, axis=1)
            v = jnp.repeat(v, H // Hk, axis=1)
        ctx = current_attention_context()
        seq_sharded = (ctx.mesh is not None and ctx.seq_axis is not None
                       and ctx.seq_axis in ctx.mesh.axis_names
                       and ctx.mesh.shape[ctx.seq_axis] > 1)
        ring = seq_sharded and ctx.use_ring
        if ring and T % ctx.mesh.shape[ctx.seq_axis] != 0:
            # fall through to a single-device path, but say so: the user asked
            # for ring CP and would otherwise discover the fallback as an OOM
            import warnings
            warnings.warn(
                f"ring attention disabled: T={T} not divisible by mesh axis "
                f"{ctx.seq_axis!r} ({ctx.mesh.shape[ctx.seq_axis]}); "
                f"falling back to the unsharded attention path")
            ring = False
        if ring:
            out = ring_attention(q, k, v, ctx.mesh, ctx.seq_axis,
                                 causal=self.causal, mask=mask,
                                 batch_axis=ctx.data_axis,
                                 window=self.attention_window)
        elif self.block_size and T > self.block_size and not seq_sharded:
            # single-device long-context path. Preferred impl: the fused
            # flash-attention Pallas kernel (ops/flash_attention.py,
            # default-on for TPU) — the whole online-softmax recurrence in
            # one kernel with an fp32-exact custom VJP that recomputes p
            # per tile. Fallback: the lax.scan blockwise recurrence (same
            # math, XLA-scheduled). Both skipped under GSPMD context
            # parallelism: there the DENSE einsums are what XLA partitions
            # over the seq axis — a lax.scan over reshaped k/v blocks
            # would force cross-shard gathers instead
            from deeplearning4j_tpu.ops.helpers import (
                helpers_enabled_for, registered_helpers)
            if "flash_attention" in registered_helpers() \
                    and helpers_enabled_for("flash_attention"):
                from deeplearning4j_tpu.ops.flash_attention import (
                    flash_attention)
                # the kernel picks its own MXU-sized tiles; the layer's
                # block_size only governs the fallback scan granularity
                out = flash_attention(q, k, v, mask, self.causal, None,
                                      0, 0, self.attention_window)
            else:
                out = blockwise_attention(q, k, v, self.block_size,
                                          causal=self.causal, mask=mask,
                                          window=self.attention_window)
        else:
            # dense path: small T, or GSPMD CP (ctx.seq_axis sharding — the
            # einsums partition across chips with XLA inserting collectives)
            scores = jnp.einsum("bhqd,bhkd->bhqk", q, k) / np.sqrt(Dh)
            if self.causal:
                scores = jnp.where(jnp.tril(jnp.ones((T, T), bool)), scores,
                                   _NEG_INF)
            if self.attention_window:
                qi = jnp.arange(T)[:, None]
                kj = jnp.arange(T)[None, :]
                wm = qi - kj < self.attention_window
                if not self.causal:
                    wm = wm & (kj - qi < self.attention_window)
                scores = jnp.where(wm[None, None], scores, _NEG_INF)
            if mask is not None:  # (B, T) padding mask: padded keys drop
                scores = jnp.where(mask[:, None, None, :] > 0, scores,
                                   _NEG_INF)
            attn = jax.nn.softmax(scores, axis=-1)
            out = jnp.einsum("bhqk,bhkv->bhqv", attn, v)  # (B, H, T, Dh)
        out = out.transpose(0, 2, 1, 3).reshape(B, T, self.n_out)
        out = out @ params["w_o"] + params["b"]
        out = self._act(out)
        if mask is not None:  # zero padded query positions like RnnOutputLayer
            out = out * mask[:, :, None].astype(out.dtype)
        return jnp.swapaxes(out, 1, 2), state, mask
