"""Dense / output / embedding / activation / dropout / autoencoder layers.

Parity: ref nn/conf/layers/{DenseLayer,OutputLayer,LossLayer,EmbeddingLayer,
ActivationLayer,DropoutLayer,AutoEncoder}.java and their implementations under
nn/layers/feedforward/. Forward math is a single fused matmul+bias+activation per layer —
XLA maps it straight onto the MXU.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.common.enums import Activation, LossFunction, WeightInit
from deeplearning4j_tpu.nn.conf.input_type import InputType
from deeplearning4j_tpu.nn.conf.layers.base import (
    BaseLayerConf, FeedForwardLayerConf, register_layer)
from deeplearning4j_tpu.nn.losses import compute_loss


@register_layer
@dataclass
class DenseLayer(FeedForwardLayerConf):
    """Fully connected layer (ref nn/layers/feedforward/dense/DenseLayer.java)."""
    has_bias: bool = True

    def init_params(self, key, input_type, dtype=jnp.float32):
        kw, _ = jax.random.split(key)
        p = {"W": self._winit(kw, (self.n_in, self.n_out), self.n_in, self.n_out, dtype)}
        if self.has_bias:
            p["b"] = jnp.full((self.n_out,), self.bias_init, dtype)
        return p

    def forward(self, params, state, x, *, train, rng=None, mask=None):
        z = x @ params["W"]
        if self.has_bias:
            z = z + params["b"]
        return self._act(z), state, mask


@register_layer
@dataclass
class OutputLayer(DenseLayer):
    """Dense + loss head (ref nn/conf/layers/OutputLayer.java). `compute_score` consumes
    pre-activations so softmax+MCXENT stays numerically fused."""
    loss_fn: LossFunction = LossFunction.MCXENT
    activation: Activation = Activation.SOFTMAX

    def is_output_layer(self):
        return True

    def preout(self, params, x):
        z = x @ params["W"]
        if self.has_bias:
            z = z + params["b"]
        return z

    def compute_score(self, params, x, labels, mask=None):
        return compute_loss(self.loss_fn, labels, self.preout(params, x),
                            self.activation, mask)

    def compute_score_per_example(self, params, x, labels, mask=None):
        """(batch,) per-example losses (ref MultiLayerNetwork.scoreExamples)."""
        from deeplearning4j_tpu.nn.losses import compute_loss_per_example
        return compute_loss_per_example(self.loss_fn, labels,
                                        self.preout(params, x),
                                        self.activation, mask)


@register_layer
@dataclass
class LossLayer(BaseLayerConf):
    """Parameterless loss head (ref nn/conf/layers/LossLayer.java)."""
    loss_fn: LossFunction = LossFunction.MCXENT
    activation: Activation = Activation.SOFTMAX

    def is_output_layer(self):
        return True

    def has_params(self):
        return False

    def get_output_type(self, input_type):
        return input_type

    def forward(self, params, state, x, *, train, rng=None, mask=None):
        return self._act(x), state, mask

    def preout(self, params, x):
        return x

    def compute_score(self, params, x, labels, mask=None):
        return compute_loss(self.loss_fn, labels, x, self.activation, mask)

    def compute_score_per_example(self, params, x, labels, mask=None):
        from deeplearning4j_tpu.nn.losses import compute_loss_per_example
        return compute_loss_per_example(self.loss_fn, labels, x,
                                        self.activation, mask)


@register_layer
@dataclass
class EmbeddingLayer(FeedForwardLayerConf):
    """Index → vector lookup (ref nn/layers/feedforward/embedding/EmbeddingLayer.java).
    Input: (batch, 1) or (batch,) integer indices. On TPU this lowers to a gather —
    no one-hot matmul."""
    has_bias: bool = True

    def init_params(self, key, input_type, dtype=jnp.float32):
        p = {"W": self._winit(key, (self.n_in, self.n_out), self.n_in, self.n_out, dtype)}
        if self.has_bias:
            p["b"] = jnp.full((self.n_out,), self.bias_init, dtype)
        return p

    def forward(self, params, state, x, *, train, rng=None, mask=None):
        idx = x.astype(jnp.int32)
        if idx.ndim == 2:
            idx = idx[:, 0]
        z = params["W"][idx]
        if self.has_bias:
            z = z + params["b"]
        return self._act(z), state, mask


@register_layer
@dataclass
class ActivationLayer(BaseLayerConf):
    """Pure activation (ref nn/conf/layers/ActivationLayer.java)."""
    def has_params(self):
        return False

    def get_output_type(self, input_type):
        return input_type

    def forward(self, params, state, x, *, train, rng=None, mask=None):
        return self._act(x), state, mask


@register_layer
@dataclass
class DropoutLayer(BaseLayerConf):
    """Dropout as an explicit layer (ref nn/conf/layers/DropoutLayer.java). The `dropout`
    field (retain prob) is applied by the network's input-dropout pass; this layer is
    identity at inference."""
    dropout: float = 0.5

    def has_params(self):
        return False

    def get_output_type(self, input_type):
        return input_type

    def forward(self, params, state, x, *, train, rng=None, mask=None):
        return self._act(x), state, mask


@register_layer
@dataclass
class AutoEncoder(FeedForwardLayerConf):
    """Denoising autoencoder layer (ref nn/layers/feedforward/autoencoder/AutoEncoder.java).
    Supervised forward = encoder only; `reconstruct` gives decode path; pretraining uses
    reconstruction loss with input corruption."""
    corruption_level: float = 0.3
    sparsity: float = 0.0
    pretrain_loss: LossFunction = LossFunction.MSE

    def init_params(self, key, input_type, dtype=jnp.float32):
        kw, _ = jax.random.split(key)
        return {
            "W": self._winit(kw, (self.n_in, self.n_out), self.n_in, self.n_out, dtype),
            "b": jnp.full((self.n_out,), self.bias_init, dtype),
            "vb": jnp.zeros((self.n_in,), dtype),  # visible bias for decode
        }

    def forward(self, params, state, x, *, train, rng=None, mask=None):
        return self._act(x @ params["W"] + params["b"]), state, mask

    def encode(self, params, x):
        return self._act(x @ params["W"] + params["b"])

    def decode(self, params, h):
        return self._act(h @ params["W"].T + params["vb"])

    def pretrain_score(self, params, x, rng):
        xc = x
        if self.corruption_level > 0 and rng is not None:
            keep = jax.random.bernoulli(rng, 1.0 - self.corruption_level, x.shape)
            xc = jnp.where(keep, x, 0.0)
        recon_z = self.encode(params, xc) @ params["W"].T + params["vb"]
        return compute_loss(self.pretrain_loss, x, recon_z, self.activation)
