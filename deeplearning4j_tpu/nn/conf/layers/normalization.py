"""Batch normalization and local response normalization.

Parity: ref nn/layers/normalization/{BatchNormalization,LocalResponseNormalization}.java
(+ cuDNN helpers BatchNormalizationHelper / LocalResponseNormalizationHelper — here XLA
fuses the whole normalization into neighbouring ops, so no helper seam is needed).
Running mean/var live in the network's mutable `state` pytree and are updated functionally
inside the jitted train step.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

import jax.numpy as jnp
from dataclasses import field

from deeplearning4j_tpu.common.enums import Activation
from deeplearning4j_tpu.nn.conf.input_type import InputType
from deeplearning4j_tpu.nn.conf.layers.base import (
    BaseLayerConf, FeedForwardLayerConf, register_layer)


@register_layer
@dataclass
class BatchNormalization(FeedForwardLayerConf):
    """BN over features (FF input) or channels (CNN input, NCHW axis 1)."""
    decay: float = 0.9  # running-average momentum (ref BatchNormalization decay param)
    eps: float = 1e-5
    gamma: float = 1.0
    beta: float = 0.0
    lock_gamma_beta: bool = False

    def set_n_in(self, input_type, override=False):
        if self.n_in == 0 or override:
            self.n_in = input_type.size  # channels for CNN, size for FF
        if self.n_out == 0 or override:
            self.n_out = self.n_in

    def get_output_type(self, input_type):
        return input_type

    def init_params(self, key, input_type, dtype=jnp.float32):
        n = self.n_in
        if self.lock_gamma_beta:
            return {}
        return {"gamma_w": jnp.full((n,), self.gamma, dtype),
                "beta": jnp.full((n,), self.beta, dtype)}

    def init_state(self, input_type, dtype=jnp.float32):
        n = self.n_in
        return {"mean": jnp.zeros((n,), dtype), "var": jnp.ones((n,), dtype)}

    def forward(self, params, state, x, *, train, rng=None, mask=None):
        if x.ndim == 4:
            axes, shape = (0, 2, 3), (1, -1, 1, 1)
        elif x.ndim == 3:
            axes, shape = (0, 2), (1, -1, 1)
        else:
            axes, shape = (0,), (1, -1)
        if train:
            # one-pass statistics (E[x^2]-E[x]^2, siblings fused by XLA into a
            # SINGLE activation read, ~9% of ResNet50 step time) ONLY for
            # sub-fp32 inputs, where fp32 accumulation has ~16 bits of
            # headroom over the data. For fp32/fp64 inputs the one-pass
            # formula in same-width arithmetic cancels catastrophically when
            # |mean| >> std (ADVICE r3 low#1) — keep the shifted two-pass
            # jnp.var there; those runs are not the HBM-bound bench path.
            if jnp.dtype(x.dtype).itemsize < 4:
                xf = x.astype(jnp.float32)
                mean32 = jnp.mean(xf, axis=axes)
                var32 = jnp.maximum(
                    jnp.mean(xf * xf, axis=axes) - mean32 * mean32, 0.0)
                mean = mean32.astype(x.dtype)
                var = var32.astype(x.dtype)
            else:
                mean = jnp.mean(x, axis=axes)
                var = jnp.var(x, axis=axes)
            d = self.decay
            new_state = {"mean": d * state["mean"] + (1 - d) * mean,
                         "var": d * state["var"] + (1 - d) * var}
        else:
            mean, var = state["mean"], state["var"]
            new_state = state
        xhat = (x - mean.reshape(shape)) / jnp.sqrt(var.reshape(shape) + self.eps)
        if self.lock_gamma_beta:
            out = self.gamma * xhat + self.beta
        else:
            out = params["gamma_w"].reshape(shape) * xhat + params["beta"].reshape(shape)
        return self._act(out), new_state, mask


@register_layer
@dataclass
class LocalResponseNormalization(BaseLayerConf):
    """Cross-channel LRN (ref nn/layers/normalization/LocalResponseNormalization.java):
    out = x / (k + alpha*sum_{j in window} x_j^2)^beta over the channel axis."""
    k: float = 2.0
    n: float = 5.0
    alpha: float = 1e-4
    beta: float = 0.75

    def has_params(self):
        return False

    def get_output_type(self, input_type):
        return input_type

    def forward(self, params, state, x, *, train, rng=None, mask=None):
        half = int(self.n) // 2
        sq = jnp.square(x)
        # windowed sum over channels: pad then sliding sum (static window → XLA fuses)
        padded = jnp.pad(sq, ((0, 0), (half, half), (0, 0), (0, 0)))
        acc = sum(padded[:, i:i + x.shape[1]] for i in range(2 * half + 1))
        denom = (self.k + self.alpha * acc) ** self.beta
        return self._act(x / denom), state, mask
