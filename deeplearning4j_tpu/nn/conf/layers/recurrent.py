"""Recurrent layers: LSTM, GravesLSTM (peepholes), GravesBidirectionalLSTM, RnnOutputLayer.

Parity: ref nn/layers/recurrent/{LSTM,GravesLSTM,GravesBidirectionalLSTM,RnnOutputLayer}.java
with the shared time-loop in LSTMHelpers.java:200-340 (fwd) / :403-700 (bwd). The reference
iterates per-timestep issuing an mmul each step — its #1 hot loop, replaced by cuDNN when
available. Here the whole sequence is a single `lax.scan`: XLA compiles one fused loop with
the input projection batched over all timesteps up front (one big MXU matmul), and autodiff
differentiates through the scan — no hand-written BPTT.

Layout: DL4J RNN activations are (batch, size, time); internally we scan over (time, batch,
size). Gate order within the fused weight matrices: [input, forget, output, cell(g)].

Masking: per-(example, timestep) mask (batch, time). Masked steps produce zero output and
hold the recurrent state (so variable-length sequences behave as if right-padded).
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from deeplearning4j_tpu.common.enums import Activation, LossFunction
from deeplearning4j_tpu.nn.activations import apply_activation
from deeplearning4j_tpu.nn.conf.input_type import InputType
from deeplearning4j_tpu.nn.conf.layers.base import (
    BaseLayerConf, FeedForwardLayerConf, register_layer)
from deeplearning4j_tpu.nn.losses import compute_loss


@register_layer
@dataclass
class LSTM(FeedForwardLayerConf):
    """LSTM without peepholes (ref nn/layers/recurrent/LSTM.java — the cuDNN-compatible
    formulation)."""
    activation: Activation = Activation.TANH
    gate_activation: Activation = Activation.SIGMOID
    forget_gate_bias_init: float = 1.0
    peephole: bool = False

    def get_output_type(self, input_type):
        return InputType.recurrent(self.n_out, input_type.timeseries_length)

    def set_n_in(self, input_type, override=False):
        if self.n_in == 0 or override:
            self.n_in = input_type.size

    def init_params(self, key, input_type, dtype=jnp.float32):
        k1, k2 = jax.random.split(key)
        n_in, n_out = self.n_in, self.n_out
        p = {
            "W": self._winit(k1, (n_in, 4 * n_out), n_in, n_out, dtype),
            "RW": self._winit(k2, (n_out, 4 * n_out), n_out, n_out, dtype),
            "b": jnp.zeros((4 * n_out,), dtype).at[n_out:2 * n_out].set(
                self.forget_gate_bias_init),
        }
        if self.peephole:
            p["pi"] = jnp.zeros((n_out,), dtype)
            p["pf"] = jnp.zeros((n_out,), dtype)
            p["po"] = jnp.zeros((n_out,), dtype)
        return p

    # single timestep; xw = x_t @ W + b precomputed
    def _step(self, params, xw_t, h, c):
        n = self.n_out
        gates = xw_t + h @ params["RW"]
        std_acts = (self.gate_activation == Activation.SIGMOID
                    and self.activation == Activation.TANH)
        if not self.peephole and std_acts:
            # helper seam (ref LSTMHelper.java fast path): fused Pallas gate
            # kernel when enabled, identical math either way
            from deeplearning4j_tpu.ops.helpers import helper_for
            from deeplearning4j_tpu.ops.pallas_kernels import lstm_gates_xla
            c_new, h_new = helper_for("lstm_gates", lstm_gates_xla)(gates, c)
            return h_new, c_new
        if self.peephole and std_acts:
            # Graves/peephole fast path (ref CudnnLSTMHelper.java:175)
            from deeplearning4j_tpu.ops.helpers import helper_for
            from deeplearning4j_tpu.ops.pallas_kernels import graves_gates_xla
            c_new, h_new = helper_for("graves_lstm_gates", graves_gates_xla)(
                gates, c, params["pi"], params["pf"], params["po"])
            return h_new, c_new
        zi, zf, zo, zg = (gates[:, :n], gates[:, n:2 * n],
                          gates[:, 2 * n:3 * n], gates[:, 3 * n:])
        gact = lambda v: apply_activation(self.gate_activation, v)
        if self.peephole:
            i = gact(zi + c * params["pi"])
            f = gact(zf + c * params["pf"])
        else:
            i, f = gact(zi), gact(zf)
        g = apply_activation(self.activation, zg)
        c_new = f * c + i * g
        o = gact(zo + c_new * params["po"]) if self.peephole else gact(zo)
        h_new = o * apply_activation(self.activation, c_new)
        return h_new, c_new

    def _scan(self, params, x, mask, h0=None, c0=None, reverse=False):
        """x: (batch, size, time) → outputs (batch, n_out, time), final (h, c)."""
        b = x.shape[0]
        n = self.n_out
        dtype = x.dtype
        h = jnp.zeros((b, n), dtype) if h0 is None else h0
        c = jnp.zeros((b, n), dtype) if c0 is None else c0
        xt = jnp.moveaxis(x, 2, 0)  # (time, batch, size)
        # one big batched input projection — single MXU matmul over all timesteps
        xw = xt @ params["W"] + params["b"]
        # helper seam, whole-sequence form (the cuDNN-LSTM analog, ref
        # CudnnLSTMHelper.java:175): the ENTIRE recurrence as one Pallas
        # kernel with h/c resident in VMEM (ops/lstm_scan_fused.py). Zero
        # peepholes reduce exactly to the plain-LSTM math. Masked sequences
        # keep the lax.scan path (the kernel has no state-hold select).
        if mask is None and self.gate_activation == Activation.SIGMOID \
                and self.activation == Activation.TANH:
            from deeplearning4j_tpu.ops.helpers import (
                helpers_enabled_for, registered_helpers)
            from deeplearning4j_tpu.ops.lstm_scan_fused import fits_vmem
            if helpers_enabled_for("graves_lstm_scan") \
                    and "graves_lstm_scan" in registered_helpers() \
                    and fits_vmem(b, n, jnp.dtype(dtype).itemsize):
                fused = registered_helpers()["graves_lstm_scan"]
                zero = jnp.zeros((n,), dtype)
                pi = params.get("pi", zero)
                pf = params.get("pf", zero)
                po = params.get("po", zero)
                xw_k = xw[::-1] if reverse else xw
                ys, cs = fused(xw_k, params["RW"], pi, pf, po, h, c)
                h_f, c_f = ys[-1], cs[-1]
                if reverse:
                    ys = ys[::-1]
                return jnp.moveaxis(ys, 0, 2), (h_f, c_f)
        mt = None if mask is None else jnp.moveaxis(mask, 1, 0)[..., None].astype(dtype)

        def body(carry, inp):
            h, c = carry
            if mask is None:
                xw_t = inp
                h_new, c_new = self._step(params, xw_t, h, c)
                return (h_new, c_new), h_new
            xw_t, m = inp
            h_new, c_new = self._step(params, xw_t, h, c)
            h_keep = m * h_new + (1 - m) * h
            c_keep = m * c_new + (1 - m) * c
            return (h_keep, c_keep), m * h_new

        xs = xw if mask is None else (xw, mt)
        (h, c), ys = lax.scan(body, (h, c), xs, reverse=reverse)
        return jnp.moveaxis(ys, 0, 2), (h, c)  # (batch, n_out, time)

    def forward(self, params, state, x, *, train, rng=None, mask=None):
        out, _ = self._scan(params, x, mask)
        return out, state, mask

    def step_forward(self, params, x_t, h, c):
        """Single streaming step for rnnTimeStep (ref BaseRecurrentLayer stateMap)."""
        xw = x_t @ params["W"] + params["b"]
        return self._step(params, xw, h, c)


@register_layer
@dataclass
class GravesLSTM(LSTM):
    """LSTM with peephole connections (ref nn/layers/recurrent/GravesLSTM.java,
    Graves 2013 formulation)."""
    peephole: bool = True


@register_layer
@dataclass
class GravesBidirectionalLSTM(LSTM):
    """Bidirectional Graves LSTM; forward and backward passes are *summed*
    (ref GravesBidirectionalLSTM.java:227-228)."""
    peephole: bool = True

    def init_params(self, key, input_type, dtype=jnp.float32):
        kf, kb = jax.random.split(key)
        fwd = super().init_params(kf, input_type, dtype)
        bwd = super().init_params(kb, input_type, dtype)
        p = {f"{k}_f": v for k, v in fwd.items()}
        p.update({f"{k}_b": v for k, v in bwd.items()})
        return p

    def forward(self, params, state, x, *, train, rng=None, mask=None):
        pf = {k[:-2]: v for k, v in params.items() if k.endswith("_f")}
        pb = {k[:-2]: v for k, v in params.items() if k.endswith("_b")}
        out_f, _ = self._scan(pf, x, mask)
        out_b, _ = self._scan(pb, x, mask, reverse=True)
        return out_f + out_b, state, mask


@register_layer
@dataclass
class RnnOutputLayer(FeedForwardLayerConf):
    """Per-timestep dense + loss head over (batch, size, time)
    (ref nn/layers/recurrent/RnnOutputLayer.java)."""
    loss_fn: LossFunction = LossFunction.MCXENT
    activation: Activation = Activation.SOFTMAX
    has_bias: bool = True

    def is_output_layer(self):
        return True

    def set_n_in(self, input_type, override=False):
        if self.n_in == 0 or override:
            self.n_in = input_type.size

    def get_output_type(self, input_type):
        return InputType.recurrent(self.n_out, input_type.timeseries_length)

    def init_params(self, key, input_type, dtype=jnp.float32):
        p = {"W": self._winit(key, (self.n_in, self.n_out), self.n_in, self.n_out, dtype)}
        if self.has_bias:
            p["b"] = jnp.full((self.n_out,), self.bias_init, dtype)
        return p

    def preout(self, params, x):
        # (batch, size, time) → (batch, time, size) @ W → back
        z = jnp.einsum("bst,so->bot", x, params["W"])
        if self.has_bias:
            z = z + params["b"][None, :, None]
        return z

    def forward(self, params, state, x, *, train, rng=None, mask=None):
        z = self.preout(params, x)
        # softmax over the feature axis (axis=1 in NCT layout)
        if self.activation == Activation.SOFTMAX:
            out = jax.nn.softmax(z, axis=1)
        else:
            out = self._act(z)
        if mask is not None:
            out = out * mask[:, None, :].astype(out.dtype)
        return out, state, mask

    def compute_score(self, params, x, labels, mask=None):
        z = self.preout(params, x)  # (batch, n_out, time)
        # move feature axis last for the loss ((batch, time, n_out))
        z2 = jnp.moveaxis(z, 1, 2).reshape(-1, self.n_out)
        l2 = jnp.moveaxis(labels, 1, 2).reshape(-1, self.n_out)
        m2 = None if mask is None else mask.reshape(-1)
        return compute_loss(self.loss_fn, l2, z2, self.activation, m2)

    def compute_score_per_example(self, params, x, labels, mask=None):
        """(batch,) scores: each example's loss summed over its (unmasked)
        timesteps (ref scoreExamples time-series semantics; the scalar score
        normalizes by batch*time, so mean(per_example)/T == score)."""
        from deeplearning4j_tpu.nn.losses import compute_loss_per_example
        B = x.shape[0]
        z = self.preout(params, x)
        z2 = jnp.moveaxis(z, 1, 2).reshape(-1, self.n_out)
        l2 = jnp.moveaxis(labels, 1, 2).reshape(-1, self.n_out)
        m2 = None if mask is None else mask.reshape(-1)
        per_bt = compute_loss_per_example(self.loss_fn, l2, z2,
                                          self.activation, m2)
        return per_bt.reshape(B, -1).sum(axis=1)


@register_layer
@dataclass
class SimpleRnn(FeedForwardLayerConf):
    """Vanilla RNN: h_t = act(x_t W + h_{t-1} RW + b)
    (ref nn/conf/layers/recurrent/SimpleRnn.java). Input projection batched over
    all timesteps up front, recurrence as one lax.scan — same TPU shape as LSTM."""
    activation: Activation = Activation.TANH

    def get_output_type(self, input_type):
        return InputType.recurrent(self.n_out,
                                   getattr(input_type, "timeseries_length", -1))

    def set_n_in(self, input_type, override=False):
        if self.n_in == 0 or override:
            self.n_in = input_type.size

    def init_params(self, key, input_type, dtype=jnp.float32):
        kw, kr = jax.random.split(key)
        return {
            "W": self._winit(kw, (self.n_in, self.n_out), self.n_in, self.n_out,
                             dtype),
            "RW": self._winit(kr, (self.n_out, self.n_out), self.n_out,
                              self.n_out, dtype),
            "b": jnp.full((self.n_out,), self.bias_init, dtype),
        }

    def _scan(self, params, x, mask, h0=None, reverse=False):
        b = x.shape[0]
        dtype = x.dtype
        h = jnp.zeros((b, self.n_out), dtype) if h0 is None else h0
        xt = jnp.moveaxis(x, 2, 0)
        xw = xt @ params["W"] + params["b"]
        mt = None if mask is None else \
            jnp.moveaxis(mask, 1, 0)[..., None].astype(dtype)

        def body(h, inp):
            if mask is None:
                h_new = apply_activation(self.activation,
                                         inp + h @ params["RW"])
                return h_new, h_new
            xw_t, m = inp
            h_new = apply_activation(self.activation, xw_t + h @ params["RW"])
            h_keep = m * h_new + (1 - m) * h
            return h_keep, m * h_new

        xs = xw if mask is None else (xw, mt)
        h, ys = lax.scan(body, h, xs, reverse=reverse)
        return jnp.moveaxis(ys, 0, 2), h

    def forward(self, params, state, x, *, train, rng=None, mask=None):
        out, _ = self._scan(params, x, mask)
        return out, state, mask


@register_layer
@dataclass
class Bidirectional(BaseLayerConf):
    """Bidirectional wrapper around any recurrent layer
    (ref nn/conf/layers/recurrent/Bidirectional.java). Modes: CONCAT (default),
    ADD, MUL, AVERAGE — applied to the forward and time-reversed passes."""
    fwd: Optional[FeedForwardLayerConf] = None  # the wrapped RNN layer conf
    mode: str = "concat"

    def __post_init__(self):
        from deeplearning4j_tpu.nn.conf.layers.base import BaseLayerConf as _B
        if isinstance(self.fwd, dict):
            self.fwd = _B.from_dict(self.fwd)

    @property
    def n_out(self):
        base = self.fwd.n_out
        return 2 * base if self.mode == "concat" else base

    def set_n_in(self, input_type, override=False):
        self.fwd.set_n_in(input_type, override)

    def get_output_type(self, input_type):
        base = self.fwd.get_output_type(input_type)
        if self.mode == "concat":
            return InputType.recurrent(
                base.size * 2, getattr(base, "timeseries_length", -1))
        return base

    def init_params(self, key, input_type, dtype=jnp.float32):
        kf, kb = jax.random.split(key)
        f = self.fwd.init_params(kf, input_type, dtype)
        b = self.fwd.init_params(kb, input_type, dtype)
        p = {f"{k}_f": v for k, v in f.items()}
        p.update({f"{k}_b": v for k, v in b.items()})
        return p

    def forward(self, params, state, x, *, train, rng=None, mask=None):
        pf = {k[:-2]: v for k, v in params.items() if k.endswith("_f")}
        pb = {k[:-2]: v for k, v in params.items() if k.endswith("_b")}
        out_f, _ = self.fwd._scan(pf, x, mask)
        out_b, _ = self.fwd._scan(pb, x, mask, reverse=True)
        if self.mode == "concat":
            out = jnp.concatenate([out_f, out_b], axis=1)
        elif self.mode == "add":
            out = out_f + out_b
        elif self.mode == "mul":
            out = out_f * out_b
        elif self.mode == "average":
            out = 0.5 * (out_f + out_b)
        else:
            raise ValueError(f"unknown Bidirectional mode {self.mode!r}")
        return out, state, mask


@register_layer
@dataclass
class LastTimeStep(BaseLayerConf):
    """Wrapper returning only the last (unmasked) timestep of the wrapped RNN
    layer's output as feed-forward activations
    (ref nn/conf/layers/recurrent/LastTimeStep.java)."""
    underlying: Optional[FeedForwardLayerConf] = None

    def __post_init__(self):
        from deeplearning4j_tpu.nn.conf.layers.base import BaseLayerConf as _B
        if isinstance(self.underlying, dict):
            self.underlying = _B.from_dict(self.underlying)

    @property
    def n_out(self):
        return self.underlying.n_out

    def set_n_in(self, input_type, override=False):
        self.underlying.set_n_in(input_type, override)

    def get_output_type(self, input_type):
        base = self.underlying.get_output_type(input_type)
        return InputType.feed_forward(base.size)

    def init_params(self, key, input_type, dtype=jnp.float32):
        return self.underlying.init_params(key, input_type, dtype)

    def forward(self, params, state, x, *, train, rng=None, mask=None):
        out, ns, out_mask = self.underlying.forward(
            params, state, x, train=train, rng=rng, mask=mask)
        if out_mask is None:
            last = out[:, :, -1]
        else:
            idx = jnp.maximum(
                jnp.sum(out_mask.astype(jnp.int32), axis=1) - 1, 0)  # (batch,)
            last = jnp.take_along_axis(
                out, idx[:, None, None], axis=2)[:, :, 0]
        return last, ns, None  # pure selection — underlying already activated
