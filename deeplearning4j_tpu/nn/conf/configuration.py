"""NeuralNetConfiguration builder + MultiLayerConfiguration.

Parity: ref nn/conf/NeuralNetConfiguration.java:72 (Builder; ListBuilder :220-244;
toJson/fromJson :328-349) and nn/conf/MultiLayerConfiguration.java. Configs are pure data
with JSON round-trip — the property that makes replica reconstruction and multi-process
config shipping trivial (ref DefaultTrainer.java:255-257) — while execution is a single
traced XLA computation built by the network classes.

The ListBuilder performs the same two config-time passes as the reference:
nIn inference from the running InputType, and automatic preprocessor insertion between
layer families (ref InputTypeUtil / MultiLayerConfiguration.Builder#inputType handling).
"""
from __future__ import annotations

import copy
import dataclasses
import json
from dataclasses import dataclass, field as dc_field
from typing import Any, Dict, List, Optional, Union

from deeplearning4j_tpu.common.enums import (
    Activation, BackpropType, CacheMode, GradientNormalization,
    OptimizationAlgorithm, WeightInit, WorkspaceMode)
from deeplearning4j_tpu.nn.conf.input_type import InputType
from deeplearning4j_tpu.nn.conf.layers.base import BaseLayerConf, FeedForwardLayerConf
from deeplearning4j_tpu.nn.conf.preprocessors import (
    CnnToFeedForwardPreProcessor, CnnToRnnPreProcessor, FeedForwardToCnnPreProcessor,
    FeedForwardToRnnPreProcessor, InputPreProcessor, RnnToCnnPreProcessor,
    RnnToFeedForwardPreProcessor)
from deeplearning4j_tpu.nn.updater.updaters import BaseUpdater, Sgd, updater_from_name

# Which input-kind each layer family expects; None = accepts anything as-is.
_EXPECTED_KIND = {
    "DenseLayer": "ff", "OutputLayer": "ff", "EmbeddingLayer": "ff",
    "AutoEncoder": "ff", "CenterLossOutputLayer": "ff", "VariationalAutoencoder": "ff",
    "RBM": "ff",
    "ConvolutionLayer": "cnn", "SubsamplingLayer": "cnn", "ZeroPaddingLayer": "cnn",
    "LocalResponseNormalization": "cnn", "SpaceToDepthLayer": "cnn", "Upsampling2D": "cnn",
    "DepthwiseConvolutionLayer": "cnn", "SeparableConvolution2D": "cnn",
    "Deconvolution2D": "cnn", "Cropping2D": "cnn",
    "LSTM": "rnn", "GravesLSTM": "rnn", "GravesBidirectionalLSTM": "rnn",
    "RnnOutputLayer": "rnn", "Convolution1DLayer": "rnn", "Subsampling1DLayer": "rnn",
    "SimpleRnn": "rnn", "Bidirectional": "rnn", "LastTimeStep": "rnn",
}


def make_preprocessor(from_type: InputType, to_kind: str) -> Optional[InputPreProcessor]:
    fk = from_type.kind
    if fk == to_kind or (fk == "cnn_flat" and to_kind == "ff"):
        return None
    if fk == "cnn" and to_kind == "ff":
        return CnnToFeedForwardPreProcessor(from_type.height, from_type.width,
                                            from_type.channels)
    if fk == "ff" and to_kind == "cnn":
        raise ValueError("Cannot infer CNN dims from FF input; set an explicit "
                         "FeedForwardToCnnPreProcessor")
    if fk == "cnn_flat" and to_kind == "cnn":
        return FeedForwardToCnnPreProcessor(from_type.height, from_type.width,
                                            from_type.channels)
    if fk == "rnn" and to_kind == "ff":
        return RnnToFeedForwardPreProcessor()
    if fk == "ff" and to_kind == "rnn":
        return FeedForwardToRnnPreProcessor()
    if fk == "cnn" and to_kind == "rnn":
        return CnnToRnnPreProcessor(from_type.height, from_type.width, from_type.channels)
    if fk == "rnn" and to_kind == "cnn":
        raise ValueError("rnn→cnn requires explicit RnnToCnnPreProcessor dims")
    return None


@dataclass
class GlobalConf:
    """Network-wide defaults + training hyper-settings (subset of
    NeuralNetConfiguration fields that aren't per-layer)."""
    seed: int = 12345
    optimization_algo: OptimizationAlgorithm = OptimizationAlgorithm.STOCHASTIC_GRADIENT_DESCENT
    updater: Optional[dict] = None  # serialized BaseUpdater; default Sgd
    max_num_line_search_iterations: int = 5
    mini_batch: bool = True
    minimize: bool = True
    dtype: str = "float32"
    # bf16 mixed precision: layer compute in this dtype, params/updater state and
    # output-layer score stay in `dtype`. None = pure `dtype` (reference behavior).
    compute_dtype: Optional[str] = None
    # gradient checkpointing: rematerialize per-layer activations in backward
    # (jax.checkpoint around each hidden layer) — HBM for FLOPs, the workspace
    # knob's TPU analog (ref WorkspaceMode controls activation memory reuse)
    remat: bool = False

    def to_dict(self):
        d = dataclasses.asdict(self)
        d["optimization_algo"] = self.optimization_algo.value
        return d

    @staticmethod
    def from_dict(d):
        d = dict(d)
        d["optimization_algo"] = OptimizationAlgorithm(d.get("optimization_algo", "sgd"))
        return GlobalConf(**d)


class MultiLayerConfiguration:
    """Ordered layer stack + preprocessors + training-time settings
    (ref nn/conf/MultiLayerConfiguration.java)."""

    def __init__(self, layers: List[BaseLayerConf],
                 preprocessors: Dict[int, InputPreProcessor],
                 global_conf: GlobalConf,
                 input_type: Optional[InputType] = None,
                 backprop_type: BackpropType = BackpropType.Standard,
                 tbptt_fwd_length: int = 20,
                 tbptt_back_length: int = 20,
                 pretrain: bool = False,
                 backprop: bool = True):
        self.layers = layers
        self.preprocessors = preprocessors
        self.global_conf = global_conf
        self.input_type = input_type
        self.backprop_type = backprop_type
        self.tbptt_fwd_length = tbptt_fwd_length
        self.tbptt_back_length = tbptt_back_length
        self.pretrain = pretrain
        self.backprop = backprop

    # ---- serde (ref NeuralNetConfiguration.java:328-349 toJson/fromJson) ----
    def to_dict(self) -> dict:
        return {
            "layers": [l.to_dict() for l in self.layers],
            "preprocessors": {str(k): v.to_dict() for k, v in self.preprocessors.items()},
            "global_conf": self.global_conf.to_dict(),
            "input_type": self.input_type.to_dict() if self.input_type else None,
            "backprop_type": self.backprop_type.value,
            "tbptt_fwd_length": self.tbptt_fwd_length,
            "tbptt_back_length": self.tbptt_back_length,
            "pretrain": self.pretrain,
            "backprop": self.backprop,
        }

    def to_json(self, **kw) -> str:
        return json.dumps(self.to_dict(), indent=kw.pop("indent", 2), **kw)

    @staticmethod
    def from_dict(d: dict) -> "MultiLayerConfiguration":
        return MultiLayerConfiguration(
            layers=[BaseLayerConf.from_dict(x) for x in d["layers"]],
            preprocessors={int(k): InputPreProcessor.from_dict(v)
                           for k, v in (d.get("preprocessors") or {}).items()},
            global_conf=GlobalConf.from_dict(d["global_conf"]),
            input_type=InputType.from_dict(d["input_type"]) if d.get("input_type") else None,
            backprop_type=BackpropType(d.get("backprop_type", "standard")),
            tbptt_fwd_length=d.get("tbptt_fwd_length", 20),
            tbptt_back_length=d.get("tbptt_back_length", 20),
            pretrain=d.get("pretrain", False),
            backprop=d.get("backprop", True),
        )

    @staticmethod
    def from_json(s: str) -> "MultiLayerConfiguration":
        return MultiLayerConfiguration.from_dict(json.loads(s))

    def to_yaml(self) -> str:
        """(ref MultiLayerConfiguration.toYaml)"""
        import yaml
        return yaml.safe_dump(json.loads(self.to_json()), sort_keys=False)

    @staticmethod
    def from_yaml(s: str) -> "MultiLayerConfiguration":
        import yaml
        return MultiLayerConfiguration.from_dict(yaml.safe_load(s))
    toYaml = to_yaml
    fromYaml = from_yaml

    def get_updater(self) -> BaseUpdater:
        if self.global_conf.updater is None:
            return Sgd()
        return BaseUpdater.from_dict(self.global_conf.updater)

    def input_types_per_layer(self) -> List[InputType]:
        """InputType *into* each layer (after its preprocessor)."""
        if self.input_type is None:
            raise ValueError("Configuration has no input type set")
        cur = self.input_type
        result = []
        for i, layer in enumerate(self.layers):
            if i in self.preprocessors:
                cur = self.preprocessors[i].get_output_type(cur)
            result.append(cur)
            cur = layer.get_output_type(cur)
        return result


class NeuralNetConfiguration:
    """Namespace matching the reference entry point: NeuralNetConfiguration.Builder."""

    class Builder:
        def __init__(self):
            self._global = GlobalConf()
            self._layer_defaults: Dict[str, Any] = {}

        # ---- global training settings ----
        def seed(self, s: int):
            self._global.seed = int(s)
            return self

        def optimization_algo(self, algo: OptimizationAlgorithm):
            self._global.optimization_algo = algo
            return self
        optimizationAlgo = optimization_algo

        def updater(self, u: Union[BaseUpdater, str]):
            if isinstance(u, str):
                lr = self._layer_defaults.get("learning_rate", 0.1)
                u = updater_from_name(u, learning_rate=lr)
            self._global.updater = u.to_dict()
            return self

        def learning_rate(self, lr: float):
            self._layer_defaults["learning_rate"] = float(lr)
            if self._global.updater is not None:
                self._global.updater["learning_rate"] = float(lr)
            return self
        learningRate = learning_rate

        def mini_batch(self, b: bool):
            self._global.mini_batch = bool(b)
            return self

        def dtype(self, dt: str):
            self._global.dtype = dt
            return self

        def remat(self, b: bool = True):
            """Enable per-layer gradient checkpointing (rematerialization)."""
            self._global.remat = bool(b)
            return self

        def compute_dtype(self, dt: Optional[str]):
            """Mixed precision: run layer compute in `dt` (e.g. "bfloat16") while
            params/updater state/score stay in `dtype`."""
            self._global.compute_dtype = dt
            return self
        computeDtype = compute_dtype

        def regularization(self, use: bool):  # API parity; l1/l2 values drive behavior
            return self

        # ---- per-layer defaults (applied where a layer didn't override) ----
        def activation(self, a):
            self._layer_defaults["activation"] = Activation(a) if isinstance(a, str) else a
            return self

        def weight_init(self, w):
            self._layer_defaults["weight_init"] = WeightInit(w) if isinstance(w, str) else w
            return self
        weightInit = weight_init

        def dist(self, d: dict):
            self._layer_defaults["dist"] = d
            return self

        def bias_init(self, b: float):
            self._layer_defaults["bias_init"] = float(b)
            return self

        def l1(self, v: float):
            self._layer_defaults["l1"] = float(v)
            return self

        def l2(self, v: float):
            self._layer_defaults["l2"] = float(v)
            return self

        def drop_out(self, v: float):
            self._layer_defaults["dropout"] = float(v)
            return self
        dropOut = drop_out

        def convolution_mode(self, m):
            from deeplearning4j_tpu.common.enums import ConvolutionMode
            self._layer_defaults["convolution_mode"] = (
                ConvolutionMode(m) if isinstance(m, str) else m)
            return self
        convolutionMode = convolution_mode

        def gradient_normalization(self, g: GradientNormalization):
            self._layer_defaults["gradient_normalization"] = g
            return self

        def gradient_normalization_threshold(self, t: float):
            self._layer_defaults["gradient_normalization_threshold"] = float(t)
            return self

        # no-op parity knobs (XLA owns memory/workspaces)
        def training_workspace_mode(self, m: WorkspaceMode):
            return self

        def inference_workspace_mode(self, m: WorkspaceMode):
            return self

        def cache_mode(self, m: CacheMode):
            return self

        def iterations(self, n: int):  # legacy DL4J "iterations per fit call" — always 1
            return self

        def list(self) -> "ListBuilder":
            return ListBuilder(self)

        def graph_builder(self):
            try:
                from deeplearning4j_tpu.nn.conf.graph_configuration import GraphBuilder
            except ImportError as e:
                raise NotImplementedError(
                    "ComputationGraph configuration is not available yet") from e
            return GraphBuilder(self)
        graphBuilder = graph_builder

        def _apply_defaults(self, layer: BaseLayerConf) -> BaseLayerConf:
            layer = copy.deepcopy(layer)
            explicit = getattr(layer, "_explicit", set())
            for k, v in self._layer_defaults.items():
                if k == "learning_rate":
                    continue
                if hasattr(layer, k) and k not in explicit:
                    setattr(layer, k, copy.deepcopy(v))
            return layer


class ListBuilder:
    """Sequential-network builder (ref NeuralNetConfiguration.ListBuilder :220-244)."""

    def __init__(self, parent: NeuralNetConfiguration.Builder):
        self._parent = parent
        self._layers: Dict[int, BaseLayerConf] = {}
        self._preprocessors: Dict[int, InputPreProcessor] = {}
        self._input_type: Optional[InputType] = None
        self._backprop_type = BackpropType.Standard
        self._tbptt_fwd = 20
        self._tbptt_back = 20
        self._pretrain = False
        self._backprop = True

    def layer(self, index_or_layer, layer: Optional[BaseLayerConf] = None):
        if layer is None:
            index, layer = len(self._layers), index_or_layer
        else:
            index = int(index_or_layer)
        self._layers[index] = layer
        return self

    def input_pre_processor(self, index: int, pp: InputPreProcessor):
        self._preprocessors[int(index)] = pp
        return self
    inputPreProcessor = input_pre_processor

    def set_input_type(self, it: InputType):
        self._input_type = it
        return self
    setInputType = set_input_type

    def backprop_type(self, t: BackpropType):
        self._backprop_type = t
        return self
    backpropType = backprop_type

    def t_bptt_forward_length(self, n: int):
        self._tbptt_fwd = int(n)
        return self
    tBPTTForwardLength = t_bptt_forward_length

    def t_bptt_backward_length(self, n: int):
        self._tbptt_back = int(n)
        return self
    tBPTTBackwardLength = t_bptt_backward_length

    def pretrain(self, b: bool):
        self._pretrain = bool(b)
        return self

    def backprop(self, b: bool):
        self._backprop = bool(b)
        return self

    def build(self) -> MultiLayerConfiguration:
        n = len(self._layers)
        layers = []
        for i in range(n):
            if i not in self._layers:
                raise ValueError(f"Missing layer index {i}")
            layers.append(self._parent._apply_defaults(self._layers[i]))

        if self._input_type is not None:
            cur = self._input_type
            if cur.kind == "cnn_flat":
                # reference behavior: flat CNN input auto-reshapes to NCHW at layer 0
                expected0 = _EXPECTED_KIND.get(type(layers[0]).__name__)
                if expected0 == "cnn" and 0 not in self._preprocessors:
                    self._preprocessors[0] = FeedForwardToCnnPreProcessor(
                        cur.height, cur.width, cur.channels)
            for i, layer in enumerate(layers):
                expected = _EXPECTED_KIND.get(type(layer).__name__)
                if i not in self._preprocessors and expected is not None:
                    pp = make_preprocessor(cur, expected)
                    if pp is not None:
                        self._preprocessors[i] = pp
                if i in self._preprocessors:
                    cur = self._preprocessors[i].get_output_type(cur)
                layer.set_n_in(cur, override=False)
                cur = layer.get_output_type(cur)

        gc = self._parent._global
        # propagate builder-level learning rate into the default updater
        if gc.updater is None and "learning_rate" in self._parent._layer_defaults:
            gc = copy.deepcopy(gc)
            gc.updater = Sgd(
                learning_rate=self._parent._layer_defaults["learning_rate"]).to_dict()
        return MultiLayerConfiguration(
            layers=layers, preprocessors=dict(self._preprocessors), global_conf=gc,
            input_type=self._input_type, backprop_type=self._backprop_type,
            tbptt_fwd_length=self._tbptt_fwd, tbptt_back_length=self._tbptt_back,
            pretrain=self._pretrain, backprop=self._backprop)
