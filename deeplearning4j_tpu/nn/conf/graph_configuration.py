"""ComputationGraph configuration + GraphBuilder.

Parity: ref nn/conf/ComputationGraphConfiguration.java (833 LoC, GraphBuilder) —
addInputs/addLayer/addVertex/setOutputs/setInputTypes, JSON round-trip, topological sort
at config time (ref ComputationGraph.java:393 topologicalSortOrder — here the sort lives
in the config because execution is a trace, not an interpreter).
"""
from __future__ import annotations

import copy
import json
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple, Union

from deeplearning4j_tpu.common.enums import BackpropType
from deeplearning4j_tpu.nn.conf.configuration import (
    _EXPECTED_KIND, GlobalConf, NeuralNetConfiguration, make_preprocessor)
from deeplearning4j_tpu.nn.conf.input_type import InputType
from deeplearning4j_tpu.nn.conf.layers.base import BaseLayerConf
from deeplearning4j_tpu.nn.conf.preprocessors import InputPreProcessor
from deeplearning4j_tpu.nn.graph.vertices import GraphVertex


@dataclass
class GraphNode:
    name: str
    kind: str  # "layer" | "vertex"
    conf: Union[BaseLayerConf, GraphVertex]
    inputs: List[str]
    preprocessor: Optional[InputPreProcessor] = None

    def to_dict(self):
        return {
            "name": self.name, "kind": self.kind, "conf": self.conf.to_dict(),
            "inputs": list(self.inputs),
            "preprocessor": self.preprocessor.to_dict() if self.preprocessor else None,
        }

    @staticmethod
    def from_dict(d):
        kind = d["kind"]
        conf = (BaseLayerConf.from_dict(d["conf"]) if kind == "layer"
                else GraphVertex.from_dict(d["conf"]))
        pp = InputPreProcessor.from_dict(d["preprocessor"]) if d.get("preprocessor") else None
        return GraphNode(d["name"], kind, conf, list(d["inputs"]), pp)


class ComputationGraphConfiguration:
    def __init__(self, inputs: List[str], outputs: List[str],
                 nodes: Dict[str, GraphNode], global_conf: GlobalConf,
                 input_types: Optional[List[InputType]] = None,
                 backprop_type: BackpropType = BackpropType.Standard,
                 tbptt_fwd_length: int = 20, tbptt_back_length: int = 20):
        self.inputs = inputs
        self.outputs = outputs
        self.nodes = nodes
        self.global_conf = global_conf
        self.input_types = input_types
        self.backprop_type = backprop_type
        self.tbptt_fwd_length = tbptt_fwd_length
        self.tbptt_back_length = tbptt_back_length
        self.topo_order = self._topological_sort()

    # ---- topo sort (ref ComputationGraph.java:393/:1172) ----
    def _topological_sort(self) -> List[str]:
        indeg = {n: 0 for n in self.nodes}
        dependents: Dict[str, List[str]] = {n: [] for n in self.nodes}
        for name, node in self.nodes.items():
            for inp in node.inputs:
                if inp in self.nodes:
                    indeg[name] += 1
                    dependents[inp].append(name)
                elif inp not in self.inputs:
                    raise ValueError(f"Node '{name}' references unknown input '{inp}'")
        from collections import deque
        ready = deque(sorted(n for n, d in indeg.items() if d == 0))
        order = []
        while ready:
            n = ready.popleft()
            order.append(n)
            for dep in dependents[n]:
                indeg[dep] -= 1
                if indeg[dep] == 0:
                    ready.append(dep)
        if len(order) != len(self.nodes):
            cyc = set(self.nodes) - set(order)
            raise ValueError(f"Graph contains a cycle involving: {sorted(cyc)}")
        return order

    # ---- shape inference over the DAG ----
    def node_input_types(self) -> Dict[str, List[InputType]]:
        """InputTypes flowing *into* each node (post-preprocessor for layers)."""
        if self.input_types is None:
            raise ValueError("Configuration has no input types set")
        known: Dict[str, InputType] = dict(zip(self.inputs, self.input_types))
        result: Dict[str, List[InputType]] = {}
        for name in self.topo_order:
            node = self.nodes[name]
            in_types = [known[i] for i in node.inputs]
            if node.kind == "layer":
                if node.preprocessor is not None:
                    in_types = [node.preprocessor.get_output_type(in_types[0])]
                result[name] = in_types
                known[name] = node.conf.get_output_type(in_types[0])
            else:
                result[name] = in_types
                known[name] = node.conf.get_output_type(in_types)
        return result

    # ---- serde ----
    def to_dict(self):
        return {
            "inputs": list(self.inputs), "outputs": list(self.outputs),
            "nodes": {k: v.to_dict() for k, v in self.nodes.items()},
            "global_conf": self.global_conf.to_dict(),
            "input_types": [t.to_dict() for t in self.input_types]
            if self.input_types else None,
            "backprop_type": self.backprop_type.value,
            "tbptt_fwd_length": self.tbptt_fwd_length,
            "tbptt_back_length": self.tbptt_back_length,
            "network_type": "ComputationGraph",
        }

    def to_json(self, **kw) -> str:
        return json.dumps(self.to_dict(), indent=kw.pop("indent", 2), **kw)

    @staticmethod
    def from_dict(d):
        return ComputationGraphConfiguration(
            inputs=list(d["inputs"]), outputs=list(d["outputs"]),
            nodes={k: GraphNode.from_dict(v) for k, v in d["nodes"].items()},
            global_conf=GlobalConf.from_dict(d["global_conf"]),
            input_types=[InputType.from_dict(t) for t in d["input_types"]]
            if d.get("input_types") else None,
            backprop_type=BackpropType(d.get("backprop_type", "standard")),
            tbptt_fwd_length=d.get("tbptt_fwd_length", 20),
            tbptt_back_length=d.get("tbptt_back_length", 20))

    @staticmethod
    def from_json(s: str) -> "ComputationGraphConfiguration":
        return ComputationGraphConfiguration.from_dict(json.loads(s))

    def to_yaml(self) -> str:
        """(ref ComputationGraphConfiguration.toYaml)"""
        import yaml
        return yaml.safe_dump(json.loads(self.to_json()), sort_keys=False)

    @staticmethod
    def from_yaml(s: str) -> "ComputationGraphConfiguration":
        import yaml
        return ComputationGraphConfiguration.from_dict(yaml.safe_load(s))
    toYaml = to_yaml
    fromYaml = from_yaml

    def get_updater(self):
        from deeplearning4j_tpu.nn.updater.updaters import BaseUpdater, Sgd
        if self.global_conf.updater is None:
            return Sgd()
        return BaseUpdater.from_dict(self.global_conf.updater)


class GraphBuilder:
    """ref ComputationGraphConfiguration.GraphBuilder (via
    NeuralNetConfiguration.Builder().graphBuilder())."""

    def __init__(self, parent: "NeuralNetConfiguration.Builder"):
        self._parent = parent
        self._inputs: List[str] = []
        self._outputs: List[str] = []
        self._nodes: Dict[str, GraphNode] = {}
        self._input_types: Optional[List[InputType]] = None
        self._backprop_type = BackpropType.Standard
        self._tbptt_fwd = 20
        self._tbptt_back = 20

    def add_inputs(self, *names: str):
        self._inputs.extend(names)
        return self
    addInputs = add_inputs

    def add_layer(self, name: str, layer: BaseLayerConf, *inputs: str,
                  preprocessor: Optional[InputPreProcessor] = None):
        if name in self._nodes or name in self._inputs:
            raise ValueError(f"Duplicate node name '{name}'")
        layer = self._parent._apply_defaults(layer)
        layer.name = name
        self._nodes[name] = GraphNode(name, "layer", layer, list(inputs), preprocessor)
        return self
    addLayer = add_layer

    def add_vertex(self, name: str, vertex: GraphVertex, *inputs: str):
        if name in self._nodes or name in self._inputs:
            raise ValueError(f"Duplicate node name '{name}'")
        self._nodes[name] = GraphNode(name, "vertex", vertex, list(inputs))
        return self
    addVertex = add_vertex

    def set_outputs(self, *names: str):
        self._outputs = list(names)
        return self
    setOutputs = set_outputs

    def set_input_types(self, *types: InputType):
        self._input_types = list(types)
        return self
    setInputTypes = set_input_types

    def backprop_type(self, t: BackpropType):
        self._backprop_type = t
        return self

    def t_bptt_forward_length(self, n: int):
        self._tbptt_fwd = int(n)
        return self

    def t_bptt_backward_length(self, n: int):
        self._tbptt_back = int(n)
        return self

    def pretrain(self, b: bool):
        return self

    def backprop(self, b: bool):
        return self

    def build(self) -> ComputationGraphConfiguration:
        conf = ComputationGraphConfiguration(
            inputs=list(self._inputs), outputs=list(self._outputs),
            nodes=self._nodes, global_conf=copy.deepcopy(self._parent._global),
            input_types=self._input_types, backprop_type=self._backprop_type,
            tbptt_fwd_length=self._tbptt_fwd, tbptt_back_length=self._tbptt_back)

        gc = conf.global_conf
        if gc.updater is None and "learning_rate" in self._parent._layer_defaults:
            from deeplearning4j_tpu.nn.updater.updaters import Sgd
            gc.updater = Sgd(
                learning_rate=self._parent._layer_defaults["learning_rate"]).to_dict()

        for out in conf.outputs:
            if out not in conf.nodes:
                raise ValueError(f"Output '{out}' is not a node in the graph")

        if conf.input_types is not None:
            if len(conf.input_types) != len(conf.inputs):
                raise ValueError("setInputTypes count must match addInputs count")
            # two passes like ListBuilder: auto preprocessors + nIn inference, in topo order
            known: Dict[str, InputType] = dict(zip(conf.inputs, conf.input_types))
            for name in conf.topo_order:
                node = conf.nodes[name]
                in_types = [known[i] for i in node.inputs]
                if node.kind == "layer":
                    cur = in_types[0]
                    expected = _EXPECTED_KIND.get(type(node.conf).__name__)
                    if node.preprocessor is None and expected is not None:
                        node.preprocessor = make_preprocessor(cur, expected)
                    if node.preprocessor is not None:
                        cur = node.preprocessor.get_output_type(cur)
                    node.conf.set_n_in(cur, override=False)
                    known[name] = node.conf.get_output_type(cur)
                else:
                    known[name] = node.conf.get_output_type(in_types)
        return conf
