"""Input type / shape inference.

Parity with ref nn/conf/inputs/InputType.java: the config-time shape algebra that lets
ListBuilder infer nIn for each layer and insert preprocessors automatically.

Layout conventions (API-parity with the reference, which is channels-first NCHW):
- feed-forward: (batch, size)
- recurrent:    (batch, size, time)          [DL4J RNN layout: NCT]
- convolutional:(batch, channels, h, w)      [NCHW]
XLA/Mosaic re-lays these out for the MXU at compile time; keeping the reference layout at
the API boundary costs one fused transpose at most.
"""
from __future__ import annotations

from dataclasses import dataclass, asdict
from typing import Optional


@dataclass(frozen=True)
class InputType:
    kind: str  # "ff" | "rnn" | "cnn" | "cnn_flat"
    size: int = 0  # ff size or rnn feature size or cnn channels
    height: int = 0
    width: int = 0
    timeseries_length: int = -1  # -1 = unknown/variable

    @staticmethod
    def feed_forward(size: int) -> "InputType":
        return InputType("ff", size=int(size))

    @staticmethod
    def recurrent(size: int, timeseries_length: int = -1) -> "InputType":
        return InputType("rnn", size=int(size), timeseries_length=int(timeseries_length))

    @staticmethod
    def convolutional(height: int, width: int, channels: int) -> "InputType":
        return InputType("cnn", size=int(channels), height=int(height), width=int(width))

    @staticmethod
    def convolutional_flat(height: int, width: int, channels: int) -> "InputType":
        return InputType("cnn_flat", size=int(channels), height=int(height), width=int(width))

    @property
    def channels(self) -> int:
        return self.size

    def flat_size(self) -> int:
        if self.kind == "ff":
            return self.size
        if self.kind in ("cnn", "cnn_flat"):
            return self.size * self.height * self.width
        if self.kind == "rnn":
            return self.size
        raise ValueError(self.kind)

    def to_dict(self) -> dict:
        return asdict(self)

    @staticmethod
    def from_dict(d: dict) -> "InputType":
        return InputType(**d)

    def example_shape(self, batch: int = 1, time: int = 8) -> tuple:
        """A concrete array shape for this input type (variable time → `time`)."""
        if self.kind == "ff":
            return (batch, self.size)
        if self.kind == "rnn":
            t = self.timeseries_length if self.timeseries_length > 0 else time
            return (batch, self.size, t)
        if self.kind == "cnn":
            return (batch, self.size, self.height, self.width)
        if self.kind == "cnn_flat":
            return (batch, self.size * self.height * self.width)
        raise ValueError(self.kind)
