"""ComputationGraph: the DAG network.

Parity: ref nn/graph/ComputationGraph.java (3,234 LoC) — topological sort (:393),
init + param views (:418-470), fit (:852, :972-1055), feedForward in topo order
(:1403-1498), calcBackpropGradients (:1604), multi-input/multi-output. TPU-first
redesign: the topological-order interpreter with its per-vertex workspace choreography
disappears — the DAG is traced once into a single XLA computation (topo order fixed at
config time) and jax.grad provides the backward pass; the jitted train step donates
params/opt-state.
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.common.enums import BackpropType
from deeplearning4j_tpu.nn.conf.graph_configuration import ComputationGraphConfiguration
from deeplearning4j_tpu.nn.conf.layers.base import BaseLayerConf, apply_dropout
from deeplearning4j_tpu.nn.divergence import DivergenceSentinelMixin
from deeplearning4j_tpu.nn.multilayer import (
    _apply_updates, _compute_updates, _normalize_gradients)
from deeplearning4j_tpu.nn.updater.updaters import BaseUpdater
from deeplearning4j_tpu.telemetry import health as _health
from deeplearning4j_tpu.util.flat_params import flatten_params, num_params, unflatten_params


def _as_list(x) -> List:
    if x is None:
        return []
    if isinstance(x, (list, tuple)):
        return list(x)
    return [x]


class ComputationGraph(DivergenceSentinelMixin, _health.HealthMonitorMixin):
    def __init__(self, conf: ComputationGraphConfiguration):
        self.conf = conf
        # layer nodes in topo order define the flat-param-view ordering
        self.layer_names: List[str] = [n for n in conf.topo_order
                                       if conf.nodes[n].kind == "layer"]
        self.params_tree: List[Dict[str, jnp.ndarray]] = []
        self.state_tree: List[Dict[str, Any]] = []
        self._updaters: List[BaseUpdater] = []
        self._opt_state: List[Any] = []
        self._step = 0
        self._score = float("nan")
        self._listeners: List[Any] = []
        self._rng = None
        self._initialized = False
        self._train_step_fn = None
        self._accumulator = None
        self._last_etl_ms = 0.0
        self.dtype = jnp.dtype(conf.global_conf.dtype)
        gc = conf.global_conf
        self.compute_dtype = (jnp.dtype(gc.compute_dtype)
                              if getattr(gc, "compute_dtype", None) else self.dtype)

    # ------------------------------------------------------------------ init
    def init(self, params: Optional[Sequence[Dict[str, jnp.ndarray]]] = None):
        gc = self.conf.global_conf
        key = jax.random.PRNGKey(gc.seed)
        self._rng = jax.random.PRNGKey(gc.seed + 1)
        in_types = self.conf.node_input_types()
        self.params_tree, self.state_tree = [], []
        for idx, name in enumerate(self.layer_names):
            layer = self.conf.nodes[name].conf
            key, sub = jax.random.split(key)
            it = in_types[name][0]
            if params is not None:
                p = {k: jnp.array(v, copy=True) for k, v in params[idx].items()}
            else:
                p = layer.init_params(sub, it, self.dtype) if layer.has_params() else {}
            self.params_tree.append(p)
            self.state_tree.append(layer.init_state(it, self.dtype))

        global_updater = self.conf.get_updater()
        self._updaters = []
        for name in self.layer_names:
            layer = self.conf.nodes[name].conf
            if layer.frozen:
                from deeplearning4j_tpu.nn.updater.updaters import NoOp
                self._updaters.append(NoOp())  # FrozenLayer: params never step
            elif layer.updater is not None:
                self._updaters.append(BaseUpdater.from_dict(layer.updater))
            else:
                self._updaters.append(global_updater)
        self._opt_state = [u.init(p) for u, p in zip(self._updaters, self.params_tree)]
        self._initialized = True
        self._train_step_fn = None
        self._output_jit = None
        return self

    @property
    def layers(self) -> List[BaseLayerConf]:
        return [self.conf.nodes[n].conf for n in self.layer_names]

    # ----------------------------------------------------------- flat views
    def params(self) -> jnp.ndarray:
        return flatten_params(self.params_tree)

    def set_params(self, flat):
        self.params_tree = unflatten_params(self.params_tree, jnp.asarray(flat))

    def num_params(self) -> int:
        return num_params(self.params_tree)

    def get_updater_state_view(self):
        return flatten_params(self._opt_state)

    def set_updater_state_view(self, flat):
        self._opt_state = unflatten_params(self._opt_state, jnp.asarray(flat))

    # ------------------------------------------------------------- forward
    def _conv_bn_fusable(self):
        """{conv_vertex_name: bn_vertex_name} for 1x1-conv -> BatchNorm pairs
        eligible for the fused Pallas helper (ops/conv_fused.py — the
        CudnnConvolutionHelper-analog plug point): identity-activation 1x1
        conv, no dropout, whose ONLY consumer is a BN layer with IDENTITY or
        RELU activation. Computed once per net."""
        cached = getattr(self, "_conv_bn_fusable_cache", None)
        if cached is not None:
            return cached
        from deeplearning4j_tpu.common.enums import Activation
        from deeplearning4j_tpu.nn.conf.layers.convolutional import (
            ConvolutionLayer)
        from deeplearning4j_tpu.nn.conf.layers.normalization import (
            BatchNormalization)
        nodes = self.conf.nodes
        consumers: Dict[str, List[str]] = {}
        for name, node in nodes.items():
            for src in node.inputs:
                consumers.setdefault(src, []).append(name)
        fusable: Dict[str, str] = {}
        for name, node in nodes.items():
            if node.kind != "layer" or not isinstance(node.conf,
                                                      ConvolutionLayer):
                continue
            conv = node.conf
            if type(conv) is not ConvolutionLayer:
                continue  # subclasses (1D/depthwise/...) keep the plain path
            if tuple(conv.kernel_size) != (1, 1) \
                    or tuple(conv.dilation) != (1, 1) \
                    or tuple(conv.padding) != (0, 0) \
                    or conv.stride[0] != conv.stride[1] \
                    or conv.activation != Activation.IDENTITY \
                    or conv.dropout > 0 or node.preprocessor is not None:
                continue
            outs = consumers.get(name, [])
            if len(outs) != 1 or name in self.conf.outputs:
                continue  # a declared graph output must stay materialized
            bn_node = nodes[outs[0]]
            if bn_node.kind != "layer" \
                    or type(bn_node.conf) is not BatchNormalization \
                    or bn_node.conf.lock_gamma_beta \
                    or bn_node.conf.dropout > 0 \
                    or bn_node.preprocessor is not None \
                    or bn_node.conf.activation not in (Activation.IDENTITY,
                                                       Activation.RELU):
                continue
            fusable[name] = outs[0]
        self._conv_bn_fusable_cache = fusable
        return fusable

    def _forward_all(self, params_tree, state_tree, inputs: List[jnp.ndarray], *,
                     train: bool, rng=None, fmasks: Optional[List] = None,
                     stop_at_scores: bool = False, labels=None, lmasks=None,
                     rnn_init_states: Optional[List] = None):
        """Trace the whole DAG in topo order. If stop_at_scores, output-layer nodes
        contribute their loss instead of activations. Returns
        (activations dict, new_states list, total_loss or None); with
        `rnn_init_states` (tBPTT: per-LSTM (h0, c0) in layer-name order, None
        entries allowed) a 4th element — the final RNN states — is appended."""
        from deeplearning4j_tpu.nn.conf.layers.feedforward import EmbeddingLayer
        from deeplearning4j_tpu.util.dtypes import cast_floats
        cd = self.compute_dtype
        mixed = cd != self.dtype
        params_full = params_tree  # storage-dtype originals (score + regularization)
        if mixed:
            params_tree = cast_floats(params_tree, cd)
        nodes = self.conf.nodes
        fmasks = fmasks or [None] * len(self.conf.inputs)
        values: Dict[str, jnp.ndarray] = dict(zip(self.conf.inputs, inputs))
        masks: Dict[str, Optional[jnp.ndarray]] = dict(zip(self.conf.inputs, fmasks))
        new_states = [None] * len(self.layer_names)
        layer_idx = {n: i for i, n in enumerate(self.layer_names)}
        label_map = {}
        lmask_map = {}
        if labels is not None:
            label_map = dict(zip(self.conf.outputs, labels))
            lmask_map = dict(zip(self.conf.outputs, lmasks or [None] * len(labels)))
        total_loss = jnp.asarray(0.0, self.dtype) if stop_at_scores else None
        from deeplearning4j_tpu.nn.conf.layers.recurrent import LSTM as _LSTM
        # conv+BN fused fast path (train only; eval BN uses running stats)
        from deeplearning4j_tpu.ops.helpers import helpers_enabled, \
            registered_helpers
        fusable = {}
        if train and helpers_enabled() \
                and "conv1x1_bn_act" in registered_helpers():
            fusable = self._conv_bn_fusable()
        pending_fused: Dict[str, tuple] = {}  # conv name -> (conv input, idx)
        final_rnn: List = []
        if rnn_init_states is not None:
            from deeplearning4j_tpu.util.dtypes import cast_floats as _cf
            if mixed:
                rnn_init_states = _cf(rnn_init_states, cd)

        for name in self.conf.topo_order:
            node = nodes[name]
            if name in fusable:
                # stash the conv's input; the (sole-consumer) BN node below
                # runs the fused kernel over it
                i = layer_idx[name]
                cur = values[node.inputs[0]]
                if mixed:
                    cur = cur.astype(cd)
                pending_fused[name] = (cur, i, node.conf)
                values[name] = None  # guarded by the single-consumer check
                masks[name] = masks.get(node.inputs[0])
                new_states[i] = state_tree[i]
                continue
            if node.kind == "layer" and node.inputs \
                    and node.inputs[0] in pending_fused:
                from deeplearning4j_tpu.ops.conv_fused import conv1x1_bn_act
                from deeplearning4j_tpu.common.enums import Activation as _Act
                x0, ci, conv = pending_fused.pop(node.inputs[0])
                bn = node.conf
                i = layer_idx[name]
                cp, bp = params_tree[ci], params_tree[i]
                w = cp["W"][:, :, 0, 0]
                bias = cp.get("b")
                if bias is None:
                    bias = jnp.zeros((w.shape[0],), w.dtype)
                out, m_b, v_b = conv1x1_bn_act(
                    x0, w, bp["gamma_w"], bp["beta"], bias, bn.eps,
                    bn.activation == _Act.RELU, conv.stride[0])
                d = bn.decay
                st = state_tree[i]
                # match BatchNormalization.forward's running update exactly
                # (batch stats cast to activation dtype before the blend)
                mb, vb = m_b.astype(x0.dtype), v_b.astype(x0.dtype)
                new_states[i] = {"mean": d * st["mean"] + (1 - d) * mb,
                                 "var": d * st["var"] + (1 - d) * vb}
                values[name] = out
                masks[name] = masks.get(node.inputs[0])
                continue
            in_vals = [values[i] for i in node.inputs]
            in_masks = [masks.get(i) for i in node.inputs]
            if node.kind == "vertex":
                out, m = node.conf.forward(in_vals, in_masks)
                values[name], masks[name] = out, m
                continue
            layer = node.conf
            i = layer_idx[name]
            cur, mask = in_vals[0], in_masks[0]
            if mixed and not isinstance(layer, EmbeddingLayer):
                cur = cur.astype(cd)
            if node.preprocessor is not None:
                cur = node.preprocessor.preprocess(cur)
                mask = node.preprocessor.feed_forward_mask(mask)
            if train and layer.dropout > 0 and rng is not None:
                rng, sub = jax.random.split(rng)
                cur = apply_dropout(cur, layer.dropout, sub)
            lrng = None
            if rng is not None:
                rng, lrng = jax.random.split(rng)
            if stop_at_scores and name in label_map and layer.is_output_layer():
                lm = lmask_map.get(name)
                if lm is None and mask is not None and cur.ndim == 3:
                    lm = mask
                # output-layer matmul + loss in storage dtype for stability
                total_loss = total_loss + layer.compute_score(
                    params_full[i], cur.astype(self.dtype), label_map[name], lm)
                new_states[i] = state_tree[i]
                # still produce activation in case downstream nodes consume it
                out, ns, m = layer.forward(params_tree[i], state_tree[i], cur,
                                           train=train, rng=lrng, mask=mask)
                values[name], masks[name] = out, m
            else:
                from deeplearning4j_tpu.nn.conf.layers.recurrent import (
                    GravesBidirectionalLSTM as _BiLSTM)
                if isinstance(layer, _LSTM) \
                        and not isinstance(layer, _BiLSTM) \
                        and rnn_init_states is not None:
                    # tBPTT segment: scan from the carried state, export final
                    init = rnn_init_states[len(final_rnn)]
                    out, (h, c) = layer._scan(
                        params_tree[i], cur, mask,
                        h0=None if init is None else init[0],
                        c0=None if init is None else init[1])
                    final_rnn.append((h, c))
                    ns, m = state_tree[i], mask
                else:
                    if isinstance(layer, _LSTM):
                        final_rnn.append(None)
                    out, ns, m = layer.forward(params_tree[i], state_tree[i],
                                               cur, train=train, rng=lrng,
                                               mask=mask)
                new_states[i] = ns
                values[name], masks[name] = out, m
        if mixed:
            new_states = cast_floats(new_states, self.dtype)
        if rnn_init_states is not None:
            return values, new_states, total_loss, final_rnn
        return values, new_states, total_loss

    def output(self, *inputs, train: bool = False) -> Union[jnp.ndarray, List[jnp.ndarray]]:
        """Inference forward; returns one array per configured output
        (single array if one output) (ref ComputationGraph.output). Jitted: the whole
        DAG is one cached XLA computation per input shape."""
        self._check_init()
        if len(inputs) == 1 and isinstance(inputs[0], (list, tuple)):
            inputs = tuple(inputs[0])  # output([a, b]) == output(a, b)
        ins = tuple(jnp.asarray(x, self.dtype) for x in inputs)
        if train:
            values, _, _ = self._forward_all(self.params_tree, self.state_tree,
                                             list(ins), train=True)
            outs = [values[o].astype(self.dtype) for o in self.conf.outputs]
            return outs[0] if len(outs) == 1 else outs
        if getattr(self, "_output_jit", None) is None:
            def f(params, states, ins):
                values, _, _ = self._forward_all(params, states, list(ins),
                                                 train=False)
                return tuple(values[o].astype(self.dtype)
                             for o in self.conf.outputs)
            self._output_jit = jax.jit(f)
        outs = list(self._output_jit(self.params_tree, self.state_tree, ins))
        return outs[0] if len(outs) == 1 else outs

    def feed_forward(self, *inputs, train: bool = False) -> Dict[str, jnp.ndarray]:
        """All node activations by name."""
        self._check_init()
        ins = [jnp.asarray(x, self.dtype) for x in inputs]
        values, _, _ = self._forward_all(self.params_tree, self.state_tree, ins,
                                         train=train)
        return values

    # ------------------------------------------------------------- loss
    def _loss_fn(self, params_tree, state_tree, x, y, fmask, lmask, rng, train=True,
                 rnn_init_states=None):
        inputs = _as_list(x)
        labels = _as_list(y)
        fmasks = _as_list(fmask) if fmask is not None else None
        lmasks = _as_list(lmask) if lmask is not None else None
        if rnn_init_states is not None:
            _, new_states, loss, final_rnn = self._forward_all(
                params_tree, state_tree, inputs, train=train, rng=rng,
                fmasks=fmasks, stop_at_scores=True, labels=labels,
                lmasks=lmasks, rnn_init_states=rnn_init_states)
        else:
            _, new_states, loss = self._forward_all(
                params_tree, state_tree, inputs, train=train, rng=rng,
                fmasks=fmasks, stop_at_scores=True, labels=labels,
                lmasks=lmasks)
            final_rnn = None
        reg = sum((self.conf.nodes[n].conf.regularization_score(p)
                   for n, p in zip(self.layer_names, params_tree)), jnp.asarray(0.0))
        # aux-loss seam (see MultiLayerNetwork._loss_fn): e.g. MoE load balancing
        aux = sum((jnp.sum(ns["__aux_loss__"]) for ns in new_states
                   if isinstance(ns, dict) and "__aux_loss__" in ns),
                  jnp.asarray(0.0))
        return loss + reg + aux, (new_states, final_rnn)

    # ------------------------------------------------------------- training
    def _build_train_step(self):
        updaters = self._updaters
        layer_confs = self.layers
        hc = self.health_config  # snapshot; configure_health retraces
        health_on = hc is not None and hc.enabled
        protect = health_on and hc.protects

        def train_step(params_tree, opt_state, state_tree, step, rng, x, y,
                       fmask, lmask, rnn_init_states, health_nf_in):
            (loss, (new_states, final_rnn)), grads = jax.value_and_grad(
                self._loss_fn, has_aux=True)(params_tree, state_tree, x, y, fmask,
                                             lmask, rng, True, rnn_init_states)
            if not health_on:
                new_params, new_opt = _apply_updates(layer_confs, updaters, grads,
                                                     opt_state, params_tree, step)
                return new_params, new_opt, new_states, loss, final_rnn, None
            # health side-output — see MultiLayerNetwork._build_train_step
            upds, new_opt = _compute_updates(layer_confs, updaters, grads,
                                             opt_state, params_tree, step)
            new_params = [jax.tree_util.tree_map(lambda p, d: p - d, pt, ut)
                          for pt, ut in zip(params_tree, upds)]
            stats, bad = _health.summarize(params_tree, grads, upds, loss)
            if protect:
                keep = lambda new, old: jax.tree_util.tree_map(
                    lambda a, b: jnp.where(bad, b, a), new, old)
                new_params = keep(new_params, params_tree)
                new_opt = keep(new_opt, opt_state)
                new_states = keep(new_states, state_tree)
            stash = _health.step_stash(stats, bad, step, health_nf_in)
            return new_params, new_opt, new_states, loss, final_rnn, stash

        self._train_step_fn = jax.jit(train_step, donate_argnums=(0, 1, 2))
        return self._train_step_fn

    def fit_batch(self, x, y, fmask=None, lmask=None, rnn_init_states=None):
        self._check_init()
        x = tuple(jnp.asarray(v, self.dtype) for v in _as_list(x))
        y = tuple(jnp.asarray(v, self.dtype) for v in _as_list(y))
        fmask = None if fmask is None else tuple(_as_list(fmask))
        lmask = None if lmask is None else tuple(_as_list(lmask))
        if self._train_step_fn is None:
            self._build_train_step()
        self._rng, sub = jax.random.split(self._rng)

        if self._accumulator is not None:
            return self._fit_batch_accumulated(x, y, fmask, lmask, sub)

        step_args = (self.params_tree, self._opt_state, self.state_tree,
                     jnp.asarray(self._step, jnp.int32), sub, x, y, fmask,
                     lmask, rnn_init_states, self._health_nf_in())
        # profiler cost registry (ISSUE 6): register BEFORE the donated
        # dispatch (see MultiLayerNetwork.fit_batch)
        from deeplearning4j_tpu.telemetry import profiler as _profiler
        if _profiler.enabled() \
                and not getattr(self, "_profiled_fit_batch", False):
            self._profiled_fit_batch = True
            try:
                _profiler.register("train_step", self._train_step_fn,
                                   step_args, meta={"loop": "fit_batch"})
            except Exception:
                pass
        new_params, new_opt, new_states, loss, final_rnn, health_stash = \
            self._train_step_fn(*step_args)
        self.params_tree = new_params
        self._opt_state = new_opt
        self.state_tree = new_states
        self._step += 1
        self._score = loss
        if health_stash is not None:
            self._stash_health(health_stash, steps=1)  # raises under policy="raise"
        for lst in self._listeners:
            lst.iteration_done(self, self._step)
        return final_rnn

    def _fit_batch_accumulated(self, x, y, fmask, lmask, sub):
        (loss, (new_states, _)), grads = jax.value_and_grad(
            self._loss_fn, has_aux=True)(self.params_tree, self.state_tree,
                                         x, y, fmask, lmask, sub, True, None)
        self.state_tree = new_states
        self._accumulator.store_update(flatten_params(grads))
        grads = unflatten_params(grads, self._accumulator.get_update())
        self.params_tree, self._opt_state = _apply_updates(
            self.layers, self._updaters, grads, self._opt_state, self.params_tree,
            self._step)
        self._step += 1
        self._score = loss
        for lst in self._listeners:
            lst.iteration_done(self, self._step)

    def fit_on_device(self, x, y, steps: Optional[int] = None, fmask=None, lmask=None,
                      sync: bool = True, vary_batch: bool = False):
        """Jitted lax.scan training loop (see MultiLayerNetwork.fit_on_device,
        including `sync=False` deferred-readback and `vary_batch` anti-hoisting
        semantics). Benchmark mode only here: the same batch is reused `steps`
        times (rotated per step when vary_batch)."""
        self._check_init()
        x = tuple(jnp.asarray(v, self.dtype) for v in _as_list(x))
        y = tuple(jnp.asarray(v, self.dtype) for v in _as_list(y))
        if steps is None:
            raise ValueError("steps is required (single-batch device loop)")

        run = self._get_device_loop(vary_batch)

        self._rng, sub = jax.random.split(self._rng)
        args = (self.params_tree, self._opt_state, self.state_tree,
                jnp.asarray(self._step, jnp.int32), sub, x, y, fmask, lmask,
                self._health_nf_in())
        # profiler cost registry (ISSUE 6): register BEFORE the dispatch
        # donates params/opt/state; see MultiLayerNetwork.fit_on_device
        import time as _time
        from deeplearning4j_tpu import telemetry as _telemetry
        from deeplearning4j_tpu.telemetry import profiler as _profiler
        warm = _profiler.register_train_loop(
            self, ("cg", vary_batch, self._health_key()), run, args,
            int(steps))
        t_run = _time.perf_counter()
        with _telemetry.span("fit_on_device", steps=int(steps), model="cg"):
            (self.params_tree, self._opt_state, self.state_tree, _, _, div), \
                losses, health_out = run(*args, n=int(steps))
        self._step += int(steps)
        # sticky device-side stash (see DivergenceSentinelMixin)
        self._stash_pending_div(div)
        if health_out is not None:
            self._stash_health(health_out, steps=int(steps))
        if not sync:
            self._score = losses[-1]      # device scalar; host sync deferred
            return losses                 # divergence resolves on _diverged_at
        losses, div = jax.device_get((losses, self._pending_div))  # ONE readback
        if warm:
            # warm + sync only: compile excluded, readback already paid for
            _profiler.observe("train_step", (_time.perf_counter() - t_run)
                              * 1e3 / max(1, int(steps)))
        self._score = float(losses[-1])
        self._resolve_divergence(int(div))
        return losses

    def _get_device_loop(self, vary_batch: bool = False):
        """Build (or fetch from cache) the jitted scan loop used by fit_on_device /
        train_step_flops. Data (x/y/masks) is passed as jit arguments — never
        captured as traced constants — so a warm cache cannot replay the first
        call's batch. vary_batch: see MultiLayerNetwork.fit_on_device (defeats
        loop-invariant hoisting of frozen-vertex forwards)."""
        import functools

        cache_key = ("cg", vary_batch, self._health_key())
        if not hasattr(self, "_device_loop_cache"):
            self._device_loop_cache = {}
        run = self._device_loop_cache.get(cache_key)
        if run is None:
            updaters = self._updaters
            layer_confs = self.layers
            hc = self.health_config
            health_on = hc is not None and hc.enabled
            protect = health_on and hc.protects

            @functools.partial(jax.jit, donate_argnums=(0, 1, 2),
                               static_argnames=("n",))
            def run(params, opt, states, step, rng, x, y, fmask, lmask,
                    health_nf_in, n):
                def body(carry, _):
                    params_c, opt_c, states_c, step_c, rng_c, div_c, acc = carry
                    rng_c, sub = jax.random.split(rng_c)
                    if vary_batch:
                        roll = lambda t: jax.tree_util.tree_map(
                            lambda a: jnp.roll(a, step_c, axis=0), t)
                        bx, by, bfm, blm = roll(x), roll(y), roll(fmask), \
                            roll(lmask)
                    else:
                        bx, by, bfm, blm = x, y, fmask, lmask

                    def loss_fn(p):
                        loss, (ns, _) = self._loss_fn(p, states_c, bx, by, bfm,
                                                      blm, sub, True, None)
                        return loss, ns

                    (loss, ns), grads = jax.value_and_grad(loss_fn, has_aux=True)(
                        params_c)
                    if health_on:
                        # health side-output — see MultiLayerNetwork._get_device_loop
                        upds, newo = _compute_updates(layer_confs, updaters,
                                                      grads, opt_c, params_c,
                                                      step_c)
                        newp = [jax.tree_util.tree_map(lambda p, d: p - d, pt, ut)
                                for pt, ut in zip(params_c, upds)]
                        stats, badg = _health.summarize(params_c, grads, upds,
                                                        loss)
                        acc = _health.accumulate(acc, stats, badg, step_c)
                    else:
                        newp, newo = _apply_updates(layer_confs, updaters, grads,
                                                    opt_c, params_c, step_c)
                    if protect:
                        # skip/raise policy: drop only the nonfinite step
                        bad = badg
                    else:
                        # divergence sentinel — see MultiLayerNetwork.fit_on_device
                        bad = jnp.logical_or(~jnp.isfinite(loss), div_c >= 0)
                    keep = lambda new, old: jax.tree_util.tree_map(
                        lambda a, b: jnp.where(bad, b, a), new, old)
                    newp = keep(newp, params_c)
                    newo = keep(newo, opt_c)
                    ns = keep(ns, states_c)
                    if not protect:
                        div_c = jnp.where(jnp.logical_and(div_c < 0,
                                                          ~jnp.isfinite(loss)),
                                          step_c, div_c)
                    return (newp, newo, ns, step_c + 1, rng_c, div_c, acc), loss

                div0 = jnp.asarray(-1, jnp.int32)
                acc0 = _health.init_accum(len(layer_confs)) if health_on else None
                carry, losses = jax.lax.scan(
                    body, (params, opt, states, step, rng, div0, acc0), None,
                    length=n)
                newp, newo, ns, stepf, rngf, divf, accf = carry
                health_out = _health.finalize(accf, n, health_nf_in) \
                    if health_on else None
                return (newp, newo, ns, stepf, rngf, divf), losses, health_out
            self._device_loop_cache[cache_key] = run
        return run

    def train_step_flops(self, x, y) -> Optional[float]:
        """XLA cost-analysis FLOPs of ONE fit_on_device training step (see
        MultiLayerNetwork.train_step_flops)."""
        self._check_init()
        x = tuple(jnp.asarray(v, self.dtype) for v in _as_list(x))
        y = tuple(jnp.asarray(v, self.dtype) for v in _as_list(y))
        from deeplearning4j_tpu.util.costs import lowered_flops
        run = self._get_device_loop()
        return lowered_flops(
            run, self.params_tree, self._opt_state, self.state_tree,
            jnp.asarray(self._step, jnp.int32), self._rng, x, y, None, None,
            self._health_nf_in(), n=1)

    def fit(self, data, labels=None, epochs: int = 1):
        """fit(x(s), y(s)) | fit(DataSet/MultiDataSet) | fit(iterator[, epochs])
        (ref ComputationGraph.fit :852/:972)."""
        import time
        from deeplearning4j_tpu.datasets.dataset import DataSet, MultiDataSet
        self._check_init()
        if labels is not None:
            for _ in range(epochs):
                self.fit_batch(data, labels)
            return self
        if isinstance(data, (DataSet, MultiDataSet)):
            for _ in range(epochs):
                self._fit_one(data)
            return self
        from deeplearning4j_tpu.datasets.iterators import AsyncDataSetIterator
        for _ in range(epochs):
            for lst in self._listeners:
                if hasattr(lst, "on_epoch_start"):
                    lst.on_epoch_start(self)
            it = data
            if hasattr(it, "reset"):
                it.reset()
            if getattr(it, "async_supported", True):
                it = AsyncDataSetIterator(it)
            t0 = time.time()
            for ds in it:
                self._last_etl_ms = (time.time() - t0) * 1e3
                self._fit_one(ds)
                t0 = time.time()
            for lst in self._listeners:
                if hasattr(lst, "on_epoch_end"):
                    lst.on_epoch_end(self)
        return self

    def fit_tbptt(self, x, y, fmask=None, lmask=None):
        """Truncated BPTT for graph nets (ref ComputationGraph.doTruncatedBPTT):
        split the time axis into fwd-length segments, carry LSTM states across
        segments (stop-gradient), backprop within each."""
        from deeplearning4j_tpu.nn.conf.layers.recurrent import LSTM as _LSTM
        xs = _as_list(x)
        ys = _as_list(y)
        T = xs[0].shape[2]
        L = self.conf.tbptt_fwd_length
        n_rnn = sum(1 for l in self.layers if isinstance(l, _LSTM))
        carry = [None] * n_rnn

        def seg(a, s, e):
            return a[:, :, s:e] if a is not None and np.ndim(a) == 3 else a

        def seg_mask(m, s, e):
            return None if m is None else m[:, s:e]

        for start in range(0, T, L):
            end = min(start + L, T)
            sx = [seg(v, start, end) for v in xs]
            sy = [seg(v, start, end) for v in ys]
            fm = None if fmask is None else [seg_mask(m, start, end)
                                             for m in _as_list(fmask)]
            lm = None if lmask is None else [seg_mask(m, start, end)
                                             for m in _as_list(lmask)]
            final = self.fit_batch(sx, sy, fm, lm, rnn_init_states=carry)
            if final is not None:
                carry = [None if s is None else
                         (jax.lax.stop_gradient(s[0]),
                          jax.lax.stop_gradient(s[1]))
                         for s in final]

    def _fit_one(self, ds):
        from deeplearning4j_tpu.datasets.dataset import MultiDataSet
        if isinstance(ds, MultiDataSet):
            feats, labs = ds.features, ds.labels
            fm, lm = ds.features_masks, ds.labels_masks
        else:
            feats, labs = ds.features, ds.labels
            fm, lm = ds.features_mask, ds.labels_mask
        if self.conf.backprop_type == BackpropType.TruncatedBPTT \
                and np.ndim(_as_list(feats)[0]) == 3:
            self.fit_tbptt(feats, labs, fm, lm)
        else:
            self.fit_batch(feats, labs, fm, lm)

    # ------------------------------------------------------------- rnn API
    def rnn_time_step(self, *inputs):
        """Streaming inference with persistent LSTM state
        (ref ComputationGraph.rnnTimeStep)."""
        from deeplearning4j_tpu.nn.conf.layers.recurrent import LSTM as _LSTM
        self._check_init()
        if len(inputs) == 1 and isinstance(inputs[0], (list, tuple)):
            inputs = tuple(inputs[0])
        ins = [jnp.asarray(v, self.dtype) for v in inputs]
        squeeze = ins[0].ndim == 2
        if squeeze:
            ins = [v[:, :, None] for v in ins]
        n_rnn = sum(1 for l in self.layers if isinstance(l, _LSTM))
        if getattr(self, "_rnn_state", None) is None:
            self._rnn_state = [None] * n_rnn
        if getattr(self, "_rnn_step_jit", None) is None:
            def f(params, states, ins, rnn_states):
                values, _, _, final = self._forward_all(
                    params, states, list(ins), train=False,
                    rnn_init_states=rnn_states)
                return tuple(values[o] for o in self.conf.outputs), final
            self._rnn_step_jit = jax.jit(f)
        outs, final = self._rnn_step_jit(self.params_tree, self.state_tree,
                                         tuple(ins), self._rnn_state)
        self._rnn_state = final
        outs = [o[:, :, 0] if squeeze and o.ndim == 3 else o for o in outs]
        return outs[0] if len(outs) == 1 else list(outs)
    rnnTimeStep = rnn_time_step

    def rnn_clear_previous_state(self):
        self._rnn_state = None
    rnnClearPreviousState = rnn_clear_previous_state

    # ------------------------------------------------------------- scoring
    def score(self, ds=None, training: bool = False) -> float:
        self._check_init()
        if ds is None:
            return float(self._score)
        from deeplearning4j_tpu.datasets.dataset import MultiDataSet
        if isinstance(ds, MultiDataSet):
            x, y, fm, lm = ds.features, ds.labels, ds.features_masks, ds.labels_masks
        else:
            x, y, fm, lm = ds.features, ds.labels, ds.features_mask, ds.labels_mask
        x = tuple(jnp.asarray(v, self.dtype) for v in _as_list(x))
        y = tuple(jnp.asarray(v, self.dtype) for v in _as_list(y))
        loss, _ = self._loss_fn(self.params_tree, self.state_tree, x, y,
                                fm, lm, None, training, None)
        return float(loss)

    def gradient_and_score(self, x, y, fmask=None, lmask=None):
        self._check_init()
        x = tuple(jnp.asarray(v, self.dtype) for v in _as_list(x))
        y = tuple(jnp.asarray(v, self.dtype) for v in _as_list(y))
        (loss, _), grads = jax.value_and_grad(self._loss_fn, has_aux=True)(
            self.params_tree, self.state_tree, x, y, fmask, lmask, None, True, None)
        return flatten_params(grads), float(loss)

    # ------------------------------------------------------------- misc
    def score_examples(self, ds, add_regularization: bool = False):
        """(batch,) per-example scores for SINGLE-output graphs (ref
        SparkComputationGraph.scoreExamples): the output head's loss per
        example (summed over unmasked timesteps for RNN heads);
        `add_regularization` adds the net's L1/L2 penalty to every entry."""
        self._check_init()
        if len(self.conf.outputs) != 1:
            raise NotImplementedError(
                "score_examples supports single-output graphs")
        out_name = self.conf.outputs[0]
        node = self.conf.nodes[out_name]
        out_layer = node.conf
        fn = getattr(out_layer, "compute_score_per_example", None)
        if fn is None:
            raise NotImplementedError(
                f"{type(out_layer).__name__} has no per-example scoring")
        xs = [jnp.asarray(v, self.dtype) for v in _as_list(ds.features)]
        y = _as_list(ds.labels)[0]
        from deeplearning4j_tpu.parallel.sharded import _ds_masks
        fm, lm = _ds_masks(ds)
        fmasks = None if fm is None else list(_as_list(fm))
        lmask = None if lm is None else _as_list(lm)[0]
        values, _, _ = self._forward_all(self.params_tree, self.state_tree,
                                         xs, train=False, fmasks=fmasks)
        cur = values[node.inputs[0]].astype(self.dtype)
        if node.preprocessor is not None:
            cur = node.preprocessor.preprocess(cur)
        li = self.layer_names.index(out_name)
        per = fn(self.params_tree[li], cur, jnp.asarray(y, self.dtype), lmask)
        if add_regularization:
            reg = sum((layer.regularization_score(p) for layer, p in
                       zip(self.layers, self.params_tree)), jnp.asarray(0.0))
            per = per + reg
        return per
    scoreExamples = score_examples

    def evaluate(self, iterator):
        from deeplearning4j_tpu.eval.evaluation import Evaluation
        ev = Evaluation()
        if hasattr(iterator, "reset"):
            iterator.reset()
        for ds in iterator:
            out = self.output(*_as_list(ds.features))
            out0 = out[0] if isinstance(out, list) else out
            labels = _as_list(ds.labels)[0]
            mask = ds.labels_mask if hasattr(ds, "labels_mask") else None
            ev.eval(labels, np.asarray(out0), mask=mask)
        return ev

    def set_listeners(self, *listeners):
        self._listeners = list(listeners)

    def set_gradients_accumulator(self, acc):
        self._accumulator = acc

    def clone(self) -> "ComputationGraph":
        other = ComputationGraph(
            ComputationGraphConfiguration.from_json(self.conf.to_json()))
        other.init(params=self.params_tree)
        other.set_updater_state_view(self.get_updater_state_view())
        return other

    def _check_init(self):
        if not self._initialized:
            raise RuntimeError("Call init() before using the network")

    @property
    def last_etl_ms(self):
        return self._last_etl_ms
