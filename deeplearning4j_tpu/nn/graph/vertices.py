"""Graph vertices: the non-layer nodes of a ComputationGraph.

Parity: ref nn/graph/vertex/impl/ — MergeVertex, ElementWiseVertex, SubsetVertex,
StackVertex, UnstackVertex, ScaleVertex, ShiftVertex, ReshapeVertex, L2Vertex,
L2NormalizeVertex, PoolHelperVertex, rnn/{LastTimeStepVertex,DuplicateToTimeSeriesVertex}
(+ mirror conf classes in nn/conf/graph/). In the reference each vertex implements
doForward/doBackward imperatively; here a vertex is a pure function of its input arrays —
the graph traces to one XLA computation and autodiff handles the backward pass
(the topological-order interpreter of ComputationGraph.java:1414-1491 disappears at
trace time, SURVEY §3.2).
"""
from __future__ import annotations

import dataclasses
from dataclasses import dataclass
from typing import List, Optional, Sequence, Tuple

import jax.numpy as jnp

from deeplearning4j_tpu.nn.conf.input_type import InputType

VERTEX_REGISTRY: dict[str, type] = {}


def register_vertex(cls):
    VERTEX_REGISTRY[cls.__name__] = cls
    return cls


@dataclass
class GraphVertex:
    """Base: pure function of input arrays. Vertices are parameterless (layers carry the
    params)."""

    def forward(self, inputs: List[jnp.ndarray], masks: List[Optional[jnp.ndarray]]
                ) -> Tuple[jnp.ndarray, Optional[jnp.ndarray]]:
        raise NotImplementedError

    def get_output_type(self, input_types: List[InputType]) -> InputType:
        raise NotImplementedError

    def to_dict(self) -> dict:
        d = dataclasses.asdict(self)
        d["@class"] = type(self).__name__
        return d

    @staticmethod
    def from_dict(d: dict) -> "GraphVertex":
        d = dict(d)
        cls = VERTEX_REGISTRY[d.pop("@class")]
        for k, v in list(d.items()):
            if isinstance(v, list):
                d[k] = tuple(v)
        return cls(**d)


def _first_mask(masks):
    for m in masks:
        if m is not None:
            return m
    return None


@register_vertex
@dataclass
class MergeVertex(GraphVertex):
    """Concatenate along the feature/channel axis (axis 1 in NC*/NCHW/NCT layouts)
    (ref nn/graph/vertex/impl/MergeVertex.java)."""

    def forward(self, inputs, masks):
        return jnp.concatenate(inputs, axis=1), _first_mask(masks)

    def get_output_type(self, input_types):
        t0 = input_types[0]
        total = sum(t.size for t in input_types)
        if t0.kind == "cnn":
            return InputType.convolutional(t0.height, t0.width, total)
        if t0.kind == "rnn":
            return InputType.recurrent(total, t0.timeseries_length)
        return InputType.feed_forward(total)


@register_vertex
@dataclass
class ElementWiseVertex(GraphVertex):
    """Elementwise Add/Subtract/Product/Average/Max of same-shaped inputs
    (ref ElementWiseVertex.java)."""
    op: str = "Add"

    def forward(self, inputs, masks):
        op = self.op.lower()
        out = inputs[0]
        if op == "add":
            for x in inputs[1:]:
                out = out + x
        elif op == "subtract":
            out = inputs[0] - inputs[1]
        elif op == "product":
            for x in inputs[1:]:
                out = out * x
        elif op == "average":
            out = sum(inputs) / len(inputs)
        elif op == "max":
            for x in inputs[1:]:
                out = jnp.maximum(out, x)
        else:
            raise ValueError(f"Unknown ElementWise op {self.op}")
        return out, _first_mask(masks)

    def get_output_type(self, input_types):
        return input_types[0]


@register_vertex
@dataclass
class SubsetVertex(GraphVertex):
    """Feature-axis slice [from, to] inclusive (ref SubsetVertex.java)."""
    from_idx: int = 0
    to_idx: int = 0

    def forward(self, inputs, masks):
        return inputs[0][:, self.from_idx:self.to_idx + 1], _first_mask(masks)

    def get_output_type(self, input_types):
        n = self.to_idx - self.from_idx + 1
        t = input_types[0]
        if t.kind == "cnn":
            return InputType.convolutional(t.height, t.width, n)
        if t.kind == "rnn":
            return InputType.recurrent(n, t.timeseries_length)
        return InputType.feed_forward(n)


@register_vertex
@dataclass
class StackVertex(GraphVertex):
    """Stack along the batch axis (ref StackVertex.java)."""

    def forward(self, inputs, masks):
        ms = [m for m in masks if m is not None]
        mask = jnp.concatenate(ms, axis=0) if len(ms) == len(inputs) else None
        return jnp.concatenate(inputs, axis=0), mask

    def get_output_type(self, input_types):
        return input_types[0]


@register_vertex
@dataclass
class UnstackVertex(GraphVertex):
    """Take batch-slice `from_idx` of `stack_size` equal chunks (ref UnstackVertex.java)."""
    from_idx: int = 0
    stack_size: int = 1

    def forward(self, inputs, masks):
        x = inputs[0]
        step = x.shape[0] // self.stack_size
        sl = slice(self.from_idx * step, (self.from_idx + 1) * step)
        m = _first_mask(masks)
        return x[sl], None if m is None else m[sl]

    def get_output_type(self, input_types):
        return input_types[0]


@register_vertex
@dataclass
class ScaleVertex(GraphVertex):
    scale_factor: float = 1.0

    def forward(self, inputs, masks):
        return inputs[0] * self.scale_factor, _first_mask(masks)

    def get_output_type(self, input_types):
        return input_types[0]


@register_vertex
@dataclass
class ShiftVertex(GraphVertex):
    shift_factor: float = 0.0

    def forward(self, inputs, masks):
        return inputs[0] + self.shift_factor, _first_mask(masks)

    def get_output_type(self, input_types):
        return input_types[0]


@register_vertex
@dataclass
class ReshapeVertex(GraphVertex):
    """Reshape to (batch, *new_shape[1:]) (ref ReshapeVertex.java)."""
    new_shape: tuple = ()

    def forward(self, inputs, masks):
        return inputs[0].reshape(self.new_shape), _first_mask(masks)

    def get_output_type(self, input_types):
        if len(self.new_shape) == 2:
            return InputType.feed_forward(self.new_shape[1])
        if len(self.new_shape) == 3:
            return InputType.recurrent(self.new_shape[1])
        if len(self.new_shape) == 4:
            return InputType.convolutional(self.new_shape[2], self.new_shape[3],
                                           self.new_shape[1])
        raise ValueError(self.new_shape)


@register_vertex
@dataclass
class L2Vertex(GraphVertex):
    """Pairwise L2 distance between two inputs, per example (ref L2Vertex.java)."""
    eps: float = 1e-8

    def forward(self, inputs, masks):
        a = inputs[0].reshape(inputs[0].shape[0], -1)
        b = inputs[1].reshape(inputs[1].shape[0], -1)
        d = jnp.sqrt(jnp.sum(jnp.square(a - b), axis=1) + self.eps)
        return d[:, None], None

    def get_output_type(self, input_types):
        return InputType.feed_forward(1)


@register_vertex
@dataclass
class L2NormalizeVertex(GraphVertex):
    """Normalize each example to unit L2 norm (ref L2NormalizeVertex.java)."""
    eps: float = 1e-8

    def forward(self, inputs, masks):
        x = inputs[0]
        flat = x.reshape(x.shape[0], -1)
        norm = jnp.sqrt(jnp.sum(jnp.square(flat), axis=1) + self.eps)
        norm = norm.reshape((-1,) + (1,) * (x.ndim - 1))
        return x / norm, _first_mask(masks)

    def get_output_type(self, input_types):
        return input_types[0]


@register_vertex
@dataclass
class PoolHelperVertex(GraphVertex):
    """Strips the first row/column of a CNN activation (GoogLeNet import compat,
    ref PoolHelperVertex.java)."""

    def forward(self, inputs, masks):
        return inputs[0][:, :, 1:, 1:], _first_mask(masks)

    def get_output_type(self, input_types):
        t = input_types[0]
        return InputType.convolutional(t.height - 1, t.width - 1, t.channels)


@register_vertex
@dataclass
class LastTimeStepVertex(GraphVertex):
    """(batch, size, time) → (batch, size) at the last *unmasked* step
    (ref rnn/LastTimeStepVertex.java)."""

    def forward(self, inputs, masks):
        x = inputs[0]
        m = _first_mask(masks)
        if m is None:
            return x[:, :, -1], None
        idx = jnp.sum(m > 0, axis=1).astype(jnp.int32) - 1  # (batch,)
        idx = jnp.clip(idx, 0, x.shape[2] - 1)
        out = jnp.take_along_axis(x, idx[:, None, None], axis=2)[:, :, 0]
        return out, None

    def get_output_type(self, input_types):
        return InputType.feed_forward(input_types[0].size)


@register_vertex
@dataclass
class DuplicateToTimeSeriesVertex(GraphVertex):
    """(batch, size) → (batch, size, time), copying across time; the time dimension is
    taken from a reference input at forward time (ref rnn/DuplicateToTimeSeriesVertex.java).
    Here the second input supplies the time axis."""

    def forward(self, inputs, masks):
        x, ref = inputs[0], inputs[1]
        t = ref.shape[2]
        return jnp.broadcast_to(x[:, :, None], x.shape + (t,)), masks[1]

    def get_output_type(self, input_types):
        t = input_types[1].timeseries_length if len(input_types) > 1 else -1
        return InputType.recurrent(input_types[0].size, t)
