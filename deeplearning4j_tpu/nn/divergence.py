"""Divergence-sentinel bookkeeping shared by MultiLayerNetwork and
ComputationGraph (SURVEY §5 failure detection).

The fit_on_device scan carries a first-bad-step index (`div`, -1 = clean)
computed entirely on device. `sync=True` resolves it at the end of the call
(one host readback, immediate warning — the reference's
InvalidScoreIterationTerminationCondition semantics). `sync=False` defers:
the index is STASHED as a device scalar and materialized on the first
`_diverged_at` access, so benchmark loops never pay the ~100 ms tunneled
host-readback per call.

Back-to-back deferred calls merge STICKILY on device (`jnp.where(prev >= 0,
prev, new)`): a later clean call must not clobber an unobserved divergence —
the first bad step survives until somebody looks, then the warning fires
exactly once and subsequent stashes can clear the state again."""
from __future__ import annotations


class DivergenceSentinelMixin:
    _pending_div = None       # device scalar: first bad step, -1 = clean
    _diverged_at_v = None     # resolved host value (int step or None)

    def _stash_pending_div(self, div):
        """Record a new device-side sentinel, preserving any unobserved one."""
        if self._pending_div is not None:
            import jax.numpy as jnp
            prev = self._pending_div
            div = jnp.where(prev >= 0, prev, div)
        self._pending_div = div

    def _resolve_divergence(self, div: int):
        self._pending_div = None
        self._diverged_at_v = div if div >= 0 else None
        if self._diverged_at_v is not None:
            import warnings
            warnings.warn(
                f"Training diverged: non-finite loss at step "
                f"{self._diverged_at_v}; parameters frozen at the last "
                f"finite step (ref InvalidScoreIterationTerminationCondition "
                f"semantics)")

    @property
    def _diverged_at(self):
        if self._pending_div is not None:
            self._resolve_divergence(int(self._pending_div))
        return self._diverged_at_v

    @_diverged_at.setter
    def _diverged_at(self, v):
        self._pending_div = None
        self._diverged_at_v = v
