"""Loss functions.

Parity with the reference's ILossFunction set (consumed by output layers; ref
nn/conf/layers OutputLayer / LossLayer configs). Each loss takes the *pre-activation*
output `z` of the output layer plus the layer's activation, so that
softmax+MCXENT and sigmoid+XENT use numerically-stable fused forms — the same
special-casing the reference does inside LossMCXENT/LossBinaryXENT.

Conventions (matching the reference scoring semantics):
- per-example loss is summed over output dimensions;
- `score` is the mean over examples (plus any L1/L2 regularization terms added by the
  network);
- `mask` is broadcastable to the label shape; masked-out entries contribute zero and
  the example-mean divides by the number of *unmasked* examples (time-series masking,
  ref util/MaskedReductionUtil.java).
"""
from __future__ import annotations

from typing import Optional, Union

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.common.enums import Activation, LossFunction
from deeplearning4j_tpu.nn.activations import apply_activation

_EPS = 1e-7


def _sum_per_example(x: jnp.ndarray) -> jnp.ndarray:
    """Sum all dims except the leading (example) dim."""
    return jnp.sum(x.reshape(x.shape[0], -1), axis=-1)


def compute_loss(
    loss_fn: Union[LossFunction, str],
    labels: jnp.ndarray,
    z: jnp.ndarray,
    activation: Union[Activation, str, None],
    mask: Optional[jnp.ndarray] = None,
) -> jnp.ndarray:
    """Mean-over-examples scalar loss from pre-activations `z`."""
    return jnp.mean(compute_loss_per_example(loss_fn, labels, z, activation,
                                             mask))


def compute_loss_per_example(
    loss_fn: Union[LossFunction, str],
    labels: jnp.ndarray,
    z: jnp.ndarray,
    activation: Union[Activation, str, None],
    mask: Optional[jnp.ndarray] = None,
) -> jnp.ndarray:
    """(batch,) per-example losses from pre-activations `z` — the reference's
    scoreExamples semantics (ref SparkDl4jMultiLayer.scoreExamples /
    impl/multilayer/scoring): each example's loss summed over its outputs/
    timesteps (masked entries dropped); the scalar score is exactly the mean
    of this vector."""
    if isinstance(loss_fn, str):
        loss_fn = LossFunction(loss_fn.lower())
    if isinstance(activation, str):
        activation = Activation(activation.lower())

    per_elem = None  # elementwise loss (same shape as labels)

    if loss_fn in (LossFunction.MCXENT, LossFunction.NEGATIVELOGLIKELIHOOD):
        if activation == Activation.SOFTMAX:
            logp = jax.nn.log_softmax(z, axis=-1)
            per_elem = -labels * logp
        else:
            out = jnp.clip(apply_activation(activation, z), _EPS, 1.0 - _EPS)
            per_elem = -labels * jnp.log(out)
    elif loss_fn == LossFunction.SPARSE_MCXENT:
        # labels are integer class ids with shape out.shape[:-1]
        logp = jax.nn.log_softmax(z, axis=-1)
        nll = -jnp.take_along_axis(logp, labels[..., None].astype(jnp.int32), axis=-1)
        per_elem = nll[..., 0:1] if nll.ndim == z.ndim else nll
    elif loss_fn == LossFunction.XENT:
        if activation == Activation.SIGMOID:
            # stable: max(z,0) - z*y + log(1+exp(-|z|))
            per_elem = jnp.maximum(z, 0) - z * labels + jnp.log1p(jnp.exp(-jnp.abs(z)))
        else:
            out = jnp.clip(apply_activation(activation, z), _EPS, 1.0 - _EPS)
            per_elem = -(labels * jnp.log(out) + (1 - labels) * jnp.log1p(-out))
    else:
        out = apply_activation(activation, z)
        if loss_fn == LossFunction.MSE:
            per_elem = jnp.square(labels - out)
        elif loss_fn == LossFunction.L2:
            per_elem = jnp.square(labels - out)
        elif loss_fn == LossFunction.L1:
            per_elem = jnp.abs(labels - out)
        elif loss_fn == LossFunction.HINGE:
            # labels in {-1, +1}
            per_elem = jnp.maximum(0.0, 1.0 - labels * out)
        elif loss_fn == LossFunction.SQUARED_HINGE:
            per_elem = jnp.square(jnp.maximum(0.0, 1.0 - labels * out))
        elif loss_fn == LossFunction.KL_DIVERGENCE:
            lc = jnp.clip(labels, _EPS, 1.0)
            oc = jnp.clip(out, _EPS, 1.0)
            per_elem = lc * (jnp.log(lc) - jnp.log(oc))
        elif loss_fn == LossFunction.POISSON:
            per_elem = out - labels * jnp.log(jnp.clip(out, _EPS, None))
        elif loss_fn == LossFunction.MEAN_ABSOLUTE_PERCENTAGE_ERROR:
            per_elem = 100.0 * jnp.abs((labels - out) / jnp.clip(jnp.abs(labels), _EPS, None))
        elif loss_fn == LossFunction.MEAN_SQUARED_LOGARITHMIC_ERROR:
            per_elem = jnp.square(jnp.log1p(jnp.clip(out, -1 + _EPS, None))
                                  - jnp.log1p(jnp.clip(labels, -1 + _EPS, None)))
        elif loss_fn == LossFunction.COSINE_PROXIMITY:
            ln = labels / jnp.clip(jnp.linalg.norm(labels, axis=-1, keepdims=True), _EPS)
            on = out / jnp.clip(jnp.linalg.norm(out, axis=-1, keepdims=True), _EPS)
            # per-example; broadcast back to elementwise/num-outputs not meaningful here
            per_ex = -jnp.sum((ln * on).reshape(labels.shape[0], -1), axis=-1)
            if mask is not None:
                per_ex = per_ex * mask.reshape(mask.shape[0], -1)[:, 0]
            return per_ex
        else:
            raise ValueError(f"Unsupported loss function: {loss_fn}")

    if mask is not None:
        # Reference scoring semantics: sum masked loss over all outputs/
        # timesteps per example (the scalar score divides by MINIBATCH size,
        # so masked and unmasked training see the same loss scale)
        m = jnp.broadcast_to(mask.reshape(mask.shape + (1,) * (per_elem.ndim - mask.ndim)),
                             per_elem.shape).astype(per_elem.dtype)
        per_elem = per_elem * m
    return _sum_per_example(per_elem)
