"""StatsListener: per-iteration training statistics into a StatsStorage.

Parity: ref deeplearning4j-ui-model/.../stats/BaseStatsListener.java:44 —
initialization records (hardware/software/model info) + per-iteration updates (score,
per-layer parameter/update summary stats: mean, stdev, mean magnitude, histograms;
learning rates; memory; timing). TPU-first delta: all numeric summaries are computed
ON DEVICE in one fused jitted computation per report (one host transfer). "updates"
summary stats come from applied parameter deltas (previous snapshot minus current);
since ISSUE 5 the gradient norms and update:param ratios come from the in-step
training-health monitor (telemetry/health.py) when the model has it enabled —
exact per-step values computed inside the jitted train step, read back lagged
(sync-free) — with the param-delta ratio as the fallback. The score is the
one-step-stale materialized loss (lagged_score), never a forced device sync.
"""
from __future__ import annotations

import time
import uuid
from typing import Any, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.optimize.listeners import TrainingListener
from deeplearning4j_tpu.telemetry.training import lagged_score, mark_iteration
from deeplearning4j_tpu.ui.storage import StatsStorageRouter

_HIST_BINS = 20


def _summary_tree(tree, hist: bool):
    """Per-leaf-group summaries; returns a dict name -> stats arrays (device)."""
    out = {}
    for i, layer_params in enumerate(tree):
        if not layer_params:
            continue
        flat = jnp.concatenate([jnp.ravel(v).astype(jnp.float32)
                                for v in layer_params.values()])
        s = {
            "mean": jnp.mean(flat),
            "stdev": jnp.std(flat),
            "mean_magnitude": jnp.mean(jnp.abs(flat)),
            "min": jnp.min(flat),
            "max": jnp.max(flat),
        }
        if hist:
            counts, edges = jnp.histogram(flat, bins=_HIST_BINS)
            s["histogram_counts"] = counts
            s["histogram_edges"] = edges
        out[str(i)] = s
    return out


class StatsListener(TrainingListener):
    """(ref BaseStatsListener.java:44 / StatsListener.java)

    update_config flags mirror the reference's StatsUpdateConfiguration: histograms,
    update stats, and memory reporting can each be disabled."""

    def __init__(self, storage: StatsStorageRouter, frequency: int = 1,
                 session_id: Optional[str] = None, worker_id: str = "0",
                 collect_histograms: bool = True, collect_updates: bool = True,
                 collect_memory: bool = True, collect_health: bool = True):
        self.storage = storage
        self.frequency = max(1, int(frequency))
        self.session_id = session_id or f"session-{uuid.uuid4().hex[:12]}"
        self.worker_id = worker_id
        self.collect_histograms = collect_histograms
        self.collect_updates = collect_updates
        self.collect_memory = collect_memory
        self.collect_health = collect_health
        self._static_posted = False
        self._prev_params = None
        self._summary_jit = None

    # ------------- static info (ref listener initialization records) -------------
    def _post_static(self, model):
        devs = jax.devices()
        try:
            conf_json = model.conf.to_json()
        except Exception:
            conf_json = None
        layer_names = []
        for i, layer in enumerate(getattr(model, "layers", [])):
            layer_names.append(getattr(layer, "name", None) or
                               f"{i}_{type(layer).__name__}")
        record = {
            "session_id": self.session_id, "type_id": "StatsListener",
            "worker_id": self.worker_id, "timestamp": time.time(),
            "hardware": {
                "device_kind": devs[0].device_kind if devs else "unknown",
                "device_count": len(devs),
                "process_count": jax.process_count(),
                "platform": devs[0].platform if devs else "unknown",
            },
            "software": {"jax_version": jax.__version__,
                         "backend": jax.default_backend()},
            "model": {
                "config_json": conf_json,
                "num_params": int(model.num_params()),
                "num_layers": len(layer_names),
                "layer_names": layer_names,
            },
        }
        self.storage.put_static_info(record)
        self._static_posted = True

    # ------------- per-iteration -------------
    def _build_summary(self, model):
        hist = self.collect_histograms
        upd = self.collect_updates

        def f(params, prev):
            res = {"params": _summary_tree(params, hist)}
            if upd and prev is not None:
                deltas = jax.tree_util.tree_map(lambda a, b: a - b, prev, params)
                res["updates"] = _summary_tree(deltas, hist)
            return res

        return jax.jit(f)

    def iteration_done(self, model, iteration: int):
        # iteration timing comes from the telemetry registry's canonical
        # per-iteration bookkeeping (telemetry/training.py) instead of a
        # private `_last_report_time` stopwatch — mark EVERY iteration
        # (idempotent: a co-attached TelemetryListener and this listener
        # together still time each iteration once), report every Nth
        it_rec = mark_iteration(iteration, store=model)
        # in-step training-health monitor (ISSUE 5): opt the model in once so
        # the jitted step emits true gradient/update diagnostics; explicit
        # user/env configuration always wins over this listener default
        if self.collect_health and hasattr(model, "configure_health") \
                and not getattr(model, "_health_explicit", True) \
                and model.health_config is None:
            model.configure_health(policy="record")
        # sync-free score (satellite 1): the previous iteration's
        # materialized loss, not a float(model.score()) pipeline flush
        score = lagged_score(self, model)
        if iteration % self.frequency != 0:
            return
        if not self._static_posted:
            self._post_static(model)
        if self._summary_jit is None:
            self._summary_jit = self._build_summary(model)
        params = model.params_tree
        prev = self._prev_params if self.collect_updates else None
        if self.collect_updates and prev is None:
            # first report: no delta yet — jit signature needs a consistent prev
            stats = jax.jit(lambda p: {"params": _summary_tree(
                p, self.collect_histograms)})(params)
        else:
            stats = self._summary_jit(params, prev)
        stats = jax.device_get(stats)  # ONE host transfer for the whole report
        if self.collect_updates:
            # deep copy: the train step donates param buffers, so holding the
            # originals would leave deleted arrays in the snapshot
            self._prev_params = jax.tree_util.tree_map(
                lambda a: jnp.array(a, copy=True), params)

        now = time.time()
        stats_py = _to_python(stats)
        # update:param mean-magnitude ratio per layer — the TrainModule ratio
        # chart (ref module/train/TrainModule.java ratio tab); healthy training
        # sits around 1e-3
        if "updates" in stats_py:
            ratios = {}
            for k, u in stats_py["updates"].items():
                p = stats_py["params"].get(k)
                if p and p.get("mean_magnitude"):
                    ratios[k] = u["mean_magnitude"] / p["mean_magnitude"]
            stats_py["update_ratios"] = ratios
        # true in-step diagnostics (ISSUE 5): when the model's health monitor
        # is on, the lagged device-computed record replaces the param-delta
        # approximation for gradient/update stats — exact per-step gradient
        # norms and post-updater update:param ratios, still sync-free (the
        # stash materialized while the latest step ran)
        health_rec = model.health_report() \
            if (self.collect_health and hasattr(model, "health_report")) else None
        if health_rec is not None:
            stats_py["gradient_norms"] = {
                str(i): g for i, (g, pm) in enumerate(
                    zip(health_rec["grad_norm"], health_rec["param_mag"]))
                if pm > 0}
            stats_py["update_ratios"] = {
                str(i): r for i, (r, pm) in enumerate(
                    zip(health_rec["update_ratio"], health_rec["param_mag"]))
                if pm > 0}
        record: Dict[str, Any] = {
            "session_id": self.session_id, "type_id": "StatsListener",
            "worker_id": self.worker_id, "timestamp": now,
            "iteration": int(iteration),
            "score": score,            # one step stale, None on iteration 1
            "stats": stats_py,
            "learning_rates": self._learning_rates(model),
        }
        if health_rec is not None:
            record["health"] = health_rec
        if it_rec["iteration_ms"] is not None:
            record["iteration_ms"] = it_rec["iteration_ms"]
        if self.collect_memory:
            record["memory"] = _memory_stats()
        self.storage.put_update(record)

    def _learning_rates(self, model) -> Dict[str, float]:
        out = {}
        for i, u in enumerate(getattr(model, "_updaters", [])):
            try:
                # sync-ok: scalar LR schedule evaluation
                out[str(i)] = float(u.lr(model._step))
            except Exception:
                pass
        return out


def _to_python(obj):
    if isinstance(obj, dict):
        return {k: _to_python(v) for k, v in obj.items()}
    a = np.asarray(obj)  # sync-ok: input already device_get
    if a.ndim == 0:
        return float(a)  # sync-ok: input already device_get
    return a.tolist()


def _memory_stats() -> Dict[str, Any]:
    """Device HBM stats (the reference's JVM/off-heap memory block, TPU rendering)."""
    out = {}
    for d in jax.local_devices():
        try:
            ms = d.memory_stats()
        except Exception:
            ms = None
        if ms:
            out[str(d.id)] = {
                "bytes_in_use": ms.get("bytes_in_use"),
                "peak_bytes_in_use": ms.get("peak_bytes_in_use"),
                "bytes_limit": ms.get("bytes_limit"),
            }
    return out


class ProfilerListener(TrainingListener):
    """XLA profiler session hook (SURVEY §5 tracing): captures a trace of iterations
    [start_iteration, end_iteration) into `log_dir`, viewable with TensorBoard/XProf.
    The reference's analog is its Spark per-phase timing + JVM profiler hooks."""

    def __init__(self, log_dir: str, start_iteration: int = 10,
                 end_iteration: int = 15):
        self.log_dir = log_dir
        self.start_iteration = int(start_iteration)
        self.end_iteration = int(end_iteration)
        self._active = False

    def iteration_done(self, model, iteration: int):
        if not self._active and iteration >= self.start_iteration \
                and iteration < self.end_iteration:
            jax.profiler.start_trace(self.log_dir)
            self._active = True
        elif self._active and iteration >= self.end_iteration:
            jax.profiler.stop_trace()
            self._active = False

    def on_epoch_end(self, model):
        if self._active:
            jax.profiler.stop_trace()
            self._active = False
