"""Training dashboard server + remote stats routing.

Parity: ref deeplearning4j-ui/.../play/PlayUIServer.java (UIServer.getInstance().
attach(statsStorage) + web dashboard) and ui-model's RemoteUIStatsStorageRouter
(HTTP POST of stats records to a remote UI). TPU-first rendering: a stdlib
ThreadingHTTPServer over the JSON StatsStorage records and a self-contained HTML page
that polls and draws the score chart + layer summaries with inline SVG — no Play
framework, no websockets, zero dependencies.
"""
from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional
from urllib.parse import parse_qs, urlparse

from deeplearning4j_tpu.ui.storage import StatsStorage, StatsStorageRouter

_PAGE = """<!DOCTYPE html>
<html><head><title>deeplearning4j_tpu training UI</title>
<style>
body{font-family:sans-serif;margin:20px;background:#fafafa}
h2{margin:8px 0}.row{display:flex;gap:24px;flex-wrap:wrap}
.card{background:#fff;border:1px solid #ddd;border-radius:6px;padding:12px}
svg{background:#fff}table{border-collapse:collapse}
td,th{border:1px solid #ccc;padding:3px 8px;font-size:13px}
</style></head><body>
<h2>Training sessions</h2><div id="sessions"></div>
<div class="row">
 <div class="card"><h3>Score vs iteration</h3><svg id="chart" width="640" height="320"></svg></div>
 <div class="card"><h3>Model</h3><pre id="info" style="font-size:12px"></pre>
 <h3>Last update</h3><table id="layers"></table></div>
</div>
<script>
let sid=null;
async function j(u){const r=await fetch(u);return r.json()}
async function refresh(){
 const sessions=await j('/train/sessions');
 document.getElementById('sessions').textContent=sessions.join(', ');
 if(!sid&&sessions.length)sid=sessions[0];
 if(!sid)return;
 const info=await j('/train/sessions/'+sid+'/info');
 if(info&&info.model)document.getElementById('info').textContent=
   'params: '+info.model.num_params+'\\nlayers: '+info.model.num_layers+
   '\\ndevice: '+(info.hardware?info.hardware.device_kind:'?');
 const ups=await j('/train/sessions/'+sid+'/updates');
 if(!ups.length)return;
 drawChart(ups.map(u=>[u.iteration,u.score]));
 const last=ups[ups.length-1];
 let html='<tr><th>layer</th><th>param mean</th><th>stdev</th><th>|mean|</th></tr>';
 const ps=(last.stats&&last.stats.params)||{};
 for(const k of Object.keys(ps)){const s=ps[k];
  html+='<tr><td>'+k+'</td><td>'+s.mean.toExponential(3)+'</td><td>'+
    s.stdev.toExponential(3)+'</td><td>'+s.mean_magnitude.toExponential(3)+'</td></tr>'}
 document.getElementById('layers').innerHTML=html;
}
function drawChart(pts){
 const svg=document.getElementById('chart'),W=640,H=320,P=40;
 const xs=pts.map(p=>p[0]),ys=pts.map(p=>p[1]).filter(isFinite);
 const x0=Math.min(...xs),x1=Math.max(...xs),y0=Math.min(...ys),y1=Math.max(...ys);
 const sx=v=>P+(W-2*P)*(v-x0)/Math.max(1e-12,x1-x0);
 const sy=v=>H-P-(H-2*P)*(v-y0)/Math.max(1e-12,y1-y0);
 let d='';pts.forEach((p,i)=>{if(isFinite(p[1]))d+=(d?'L':'M')+sx(p[0])+' '+sy(p[1])});
 svg.innerHTML='<path d="'+d+'" stroke="#36c" fill="none" stroke-width="1.5"/>'+
  '<text x="'+(W/2)+'" y="'+(H-8)+'" font-size="12">iteration</text>'+
  '<text x="6" y="'+(P-10)+'" font-size="12">'+y1.toPrecision(4)+'</text>'+
  '<text x="6" y="'+(H-P)+'" font-size="12">'+y0.toPrecision(4)+'</text>';
}
setInterval(refresh,2000);refresh();
</script></body></html>"""


class _Handler(BaseHTTPRequestHandler):
    server_version = "dl4jtpu-ui/1.0"
    storage: Optional[StatsStorage] = None

    def log_message(self, *a):  # quiet
        pass

    def _json(self, obj, code=200):
        body = json.dumps(obj, default=str).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self):
        st = self.server.stats_storage  # type: ignore[attr-defined]
        url = urlparse(self.path)
        parts = [p for p in url.path.split("/") if p]
        if not parts:
            body = _PAGE.encode()
            self.send_response(200)
            self.send_header("Content-Type", "text/html")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
            return
        if parts[0] != "train" or st is None:
            self._json({"error": "not found"}, 404)
            return
        if len(parts) == 2 and parts[1] == "sessions":
            self._json(st.list_session_ids())
        elif len(parts) == 4 and parts[1] == "sessions" and parts[3] == "info":
            self._json(st.get_static_info(parts[2]))
        elif len(parts) == 4 and parts[1] == "sessions" and parts[3] == "updates":
            q = parse_qs(url.query)
            after = float(q.get("after", ["0"])[0])
            ups = st.get_all_updates(parts[2])
            self._json([u for u in ups if u.get("timestamp", 0) > after])
        else:
            self._json({"error": "not found"}, 404)

    def do_POST(self):
        """Remote stats sink (ref RemoteUIStatsStorageRouter receiving endpoint)."""
        st = self.server.stats_storage  # type: ignore[attr-defined]
        if self.path != "/remote/receive" or st is None:
            self._json({"error": "not found"}, 404)
            return
        n = int(self.headers.get("Content-Length", "0"))
        entry = json.loads(self.rfile.read(n).decode())
        if entry.get("kind") == "static":
            st.put_static_info(entry["record"])
        else:
            st.put_update(entry["record"])
        self._json({"ok": True})


class UIServer:
    """(ref api/UIServer.java getInstance/attach/detach) — serves the dashboard and
    the JSON stats API on localhost."""

    _instance: Optional["UIServer"] = None

    def __init__(self, port: int = 9000):
        self._httpd = ThreadingHTTPServer(("localhost", port), _Handler)
        self._httpd.stats_storage = None  # type: ignore[attr-defined]
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        daemon=True)
        self._thread.start()

    @classmethod
    def get_instance(cls, port: int = 9000) -> "UIServer":
        if cls._instance is None:
            cls._instance = cls(port)
        return cls._instance
    getInstance = get_instance

    @property
    def port(self) -> int:
        return self._httpd.server_address[1]

    def attach(self, storage: StatsStorage) -> None:
        self._httpd.stats_storage = storage  # type: ignore[attr-defined]

    def detach(self, storage: StatsStorage = None) -> None:
        self._httpd.stats_storage = None  # type: ignore[attr-defined]

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        if UIServer._instance is self:
            UIServer._instance = None


class RemoteUIStatsStorageRouter(StatsStorageRouter):
    """Client-side router POSTing records to a UIServer's /remote/receive
    (ref impl/RemoteUIStatsStorageRouter.java)."""

    def __init__(self, address: str):
        # address like "http://localhost:9000"
        self.address = address.rstrip("/")

    def _post(self, kind: str, record: dict):
        import urllib.request
        data = json.dumps({"kind": kind, "record": record},
                          default=str).encode()
        req = urllib.request.Request(
            self.address + "/remote/receive", data=data,
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=10) as resp:
            return json.loads(resp.read().decode())

    def put_static_info(self, record: dict) -> None:
        self._post("static", record)

    def put_update(self, record: dict) -> None:
        self._post("update", record)
