"""Training dashboard server + remote stats routing.

Parity: ref deeplearning4j-ui/.../play/PlayUIServer.java (UIServer.getInstance().
attach(statsStorage) + web dashboard) and ui-model's RemoteUIStatsStorageRouter
(HTTP POST of stats records to a remote UI). TPU-first rendering: a stdlib
ThreadingHTTPServer over the JSON StatsStorage records and a self-contained HTML page
that polls and draws the score chart + layer summaries with inline SVG — no Play
framework, no websockets, zero dependencies.
"""
from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional
from urllib.parse import parse_qs, urlparse

from deeplearning4j_tpu import telemetry
from deeplearning4j_tpu.ui.storage import StatsStorage, StatsStorageRouter

_PAGE = """<!DOCTYPE html>
<html><head><title>deeplearning4j_tpu training UI</title>
<style>
body{font-family:sans-serif;margin:20px;background:#fafafa}
h2{margin:8px 0}h3{margin:4px 0}.row{display:flex;gap:24px;flex-wrap:wrap}
.card{background:#fff;border:1px solid #ddd;border-radius:6px;padding:12px}
svg{background:#fff}table{border-collapse:collapse}
td,th{border:1px solid #ccc;padding:3px 8px;font-size:13px}
select{margin:4px 0}
.node{fill:#eef;stroke:#36c}.nodetxt{font-size:11px}
</style></head><body>
<h2>Training sessions</h2><div id="sessions"></div>
<div class="row">
 <div class="card"><h3>Score vs iteration</h3><svg id="chart" width="560" height="280"></svg></div>
 <div class="card"><h3>Model</h3><pre id="info" style="font-size:12px"></pre>
 <h3>Last update</h3><table id="layers"></table></div>
 <div class="card"><h3>Model graph</h3><svg id="graph" width="260" height="420"></svg></div>
</div>
<div class="row">
 <div class="card"><h3>Layer detail <select id="layersel"></select></h3>
  <div class="row">
   <div><h4>mean |param| / |update| vs iteration</h4>
    <svg id="mag" width="420" height="240"></svg></div>
   <div><h4>update : param ratio (log10)</h4>
    <svg id="ratio" width="420" height="240"></svg></div>
   <div><h4>param histogram (latest)</h4>
    <svg id="hist" width="420" height="240"></svg></div>
  </div>
 </div>
</div>
<script>
let sid=null,layerNames=[];
async function j(u){const r=await fetch(u);return r.json()}
function line(svg,series,opts){
 const W=svg.width.baseVal.value,H=svg.height.baseVal.value,P=34;
 let out='';const all=series.flatMap(s=>s.pts.filter(p=>isFinite(p[1])));
 if(!all.length){svg.innerHTML='';return}
 const xs=all.map(p=>p[0]),ys=all.map(p=>p[1]);
 const x0=Math.min(...xs),x1=Math.max(...xs),y0=Math.min(...ys),y1=Math.max(...ys);
 const sx=v=>P+(W-2*P)*(v-x0)/Math.max(1e-12,x1-x0);
 const sy=v=>H-P-(H-2*P)*(v-y0)/Math.max(1e-12,y1-y0);
 series.forEach(s=>{let d='';
  s.pts.forEach(p=>{if(isFinite(p[1]))d+=(d?'L':'M')+sx(p[0])+' '+sy(p[1])});
  out+='<path d="'+d+'" stroke="'+s.color+'" fill="none" stroke-width="1.5"/>'});
 out+='<text x="6" y="'+(P-10)+'" font-size="11">'+y1.toPrecision(3)+'</text>'+
  '<text x="6" y="'+(H-P)+'" font-size="11">'+y0.toPrecision(3)+'</text>';
 let lx=P;series.forEach(s=>{out+='<rect x="'+lx+'" y="4" width="10" height="10" fill="'+s.color+'"/>'+
  '<text x="'+(lx+14)+'" y="13" font-size="11">'+s.label+'</text>';lx+=s.label.length*7+30});
 svg.innerHTML=out;
}
function bars(svg,counts,edges){
 const W=svg.width.baseVal.value,H=svg.height.baseVal.value,P=24;
 if(!counts||!counts.length){svg.innerHTML='';return}
 const m=Math.max(...counts);let out='';
 const bw=(W-2*P)/counts.length;
 counts.forEach((c,i)=>{const h=(H-2*P)*c/Math.max(1,m);
  out+='<rect x="'+(P+i*bw)+'" y="'+(H-P-h)+'" width="'+Math.max(1,bw-1)+
   '" height="'+h+'" fill="#36c"/>'});
 out+='<text x="'+P+'" y="'+(H-6)+'" font-size="11">'+edges[0].toPrecision(2)+'</text>'+
  '<text x="'+(W-P-40)+'" y="'+(H-6)+'" font-size="11">'+
   edges[edges.length-1].toPrecision(2)+'</text>';
 svg.innerHTML=out;
}
function drawGraph(info){
 const svg=document.getElementById('graph');
 if(!info||!info.model||!info.model.layer_names){svg.innerHTML='';return}
 let edges=[],names=info.model.layer_names;
 try{const conf=JSON.parse(info.model.config_json);
  if(conf&&conf.nodes){names=Object.keys(conf.nodes);
   names.forEach(n=>{(conf.nodes[n].inputs||[]).forEach(i=>edges.push([i,n]))});}
  else{for(let i=1;i<names.length;i++)edges.push([names[i-1],names[i]]);}
 }catch(e){for(let i=1;i<names.length;i++)edges.push([names[i-1],names[i]]);}
 const W=260,rowH=34,pos={};let out='';
 const shown=names.slice(0,11);
 shown.forEach((n,i)=>{pos[n]=[W/2,20+i*rowH];
  out+='<rect class="node" x="'+(W/2-80)+'" y="'+(6+i*rowH)+'" width="160" height="22" rx="4"/>'+
   '<text class="nodetxt" x="'+(W/2)+'" y="'+(21+i*rowH)+'" text-anchor="middle">'+
    n.slice(0,26)+'</text>'});
 edges.forEach(e=>{const a=pos[e[0]],b=pos[e[1]];
  if(a&&b)out+='<line x1="'+a[0]+'" y1="'+(a[1]+8)+'" x2="'+b[0]+'" y2="'+(b[1]-14)+
   '" stroke="#999" marker-end="none"/>'});
 if(names.length>shown.length)out+='<text x="'+(W/2)+'" y="'+(16+shown.length*rowH)+
  '" text-anchor="middle" font-size="11">… '+(names.length-shown.length)+' more</text>';
 svg.innerHTML=out;
 svg.setAttribute('height',Math.min(420,30+shown.length*rowH));
}
async function refresh(){
 const sessions=await j('/train/sessions');
 document.getElementById('sessions').textContent=sessions.join(', ');
 if(!sid&&sessions.length)sid=sessions[0];
 if(!sid)return;
 const info=await j('/train/sessions/'+sid+'/info');
 if(info&&info.model){document.getElementById('info').textContent=
   'params: '+info.model.num_params+'\\nlayers: '+info.model.num_layers+
   '\\ndevice: '+(info.hardware?info.hardware.device_kind:'?');
  drawGraph(info);
  const sel=document.getElementById('layersel');
  if(sel.options.length===0&&info.model.layer_names){
   layerNames=info.model.layer_names;
   layerNames.forEach((n,i)=>{const o=document.createElement('option');
    o.value=i;o.textContent=i+': '+n;sel.appendChild(o)})}}
 const ups=await j('/train/sessions/'+sid+'/updates');
 if(!ups.length)return;
 line(document.getElementById('chart'),
   [{pts:ups.map(u=>[u.iteration,u.score]),color:'#36c',label:'score'}]);
 const last=ups[ups.length-1];
 let html='<tr><th>layer</th><th>param |mean|</th><th>update |mean|</th><th>ratio</th></tr>';
 const ps=(last.stats&&last.stats.params)||{};
 const us=(last.stats&&last.stats.updates)||{};
 const rs=(last.stats&&last.stats.update_ratios)||{};
 for(const k of Object.keys(ps)){const s=ps[k];
  html+='<tr><td>'+(layerNames[k]||k)+'</td><td>'+s.mean_magnitude.toExponential(2)+
   '</td><td>'+(us[k]?us[k].mean_magnitude.toExponential(2):'-')+'</td><td>'+
   (rs[k]!=null?rs[k].toExponential(2):'-')+'</td></tr>'}
 document.getElementById('layers').innerHTML=html;
 // per-layer drill-down (ref TrainModule model tab)
 const li=document.getElementById('layersel').value||Object.keys(ps)[0];
 line(document.getElementById('mag'),[
  {pts:ups.map(u=>[u.iteration,(u.stats.params[li]||{}).mean_magnitude]),
   color:'#36c',label:'|param|'},
  {pts:ups.map(u=>[u.iteration,((u.stats.updates||{})[li]||{}).mean_magnitude]),
   color:'#c63',label:'|update|'}]);
 line(document.getElementById('ratio'),[
  {pts:ups.map(u=>{const r=((u.stats.update_ratios||{})[li]);
    return [u.iteration,r>0?Math.log10(r):NaN]}),color:'#383',label:'log10 ratio'}]);
 const h=(last.stats.params[li]||{});
 bars(document.getElementById('hist'),h.histogram_counts,h.histogram_edges||[0,1]);
}
setInterval(refresh,2000);refresh();
</script></body></html>"""


class _Handler(BaseHTTPRequestHandler):
    server_version = "dl4jtpu-ui/1.0"
    storage: Optional[StatsStorage] = None

    def log_message(self, *a):  # quiet
        pass

    def _json(self, obj, code=200):
        body = json.dumps(obj, default=str).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self):
        st = self.server.stats_storage  # type: ignore[attr-defined]
        url = urlparse(self.path)
        parts = [p for p in url.path.split("/") if p]
        if not parts:
            body = _PAGE.encode()
            self.send_response(200)
            self.send_header("Content-Type", "text/html")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
            return
        if parts == ["metrics"]:
            # Prometheus text exposition over the attached registry (default:
            # the process-wide telemetry registry + its per-engine children)
            reg = getattr(self.server, "metrics_registry", None) \
                or telemetry.registry()
            body = reg.prometheus_text().encode()
            self.send_response(200)
            self.send_header("Content-Type",
                             telemetry.PROMETHEUS_CONTENT_TYPE)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
            return
        if parts[0] != "train" or st is None:
            self._json({"error": "not found"}, 404)
            return
        if len(parts) == 2 and parts[1] == "sessions":
            self._json(st.list_session_ids())
        elif len(parts) == 4 and parts[1] == "sessions" and parts[3] == "info":
            self._json(st.get_static_info(parts[2]))
        elif len(parts) == 4 and parts[1] == "sessions" and parts[3] == "updates":
            q = parse_qs(url.query)
            after = float(q.get("after", ["0"])[0])
            ups = st.get_all_updates(parts[2])
            self._json([u for u in ups if u.get("timestamp", 0) > after])
        else:
            self._json({"error": "not found"}, 404)

    def do_POST(self):
        """Remote stats sink (ref RemoteUIStatsStorageRouter receiving endpoint)."""
        st = self.server.stats_storage  # type: ignore[attr-defined]
        if self.path != "/remote/receive" or st is None:
            self._json({"error": "not found"}, 404)
            return
        n = int(self.headers.get("Content-Length", "0"))
        entry = json.loads(self.rfile.read(n).decode())
        if entry.get("kind") == "static":
            st.put_static_info(entry["record"])
        else:
            st.put_update(entry["record"])
        self._json({"ok": True})


class UIServer:
    """(ref api/UIServer.java getInstance/attach/detach) — serves the dashboard and
    the JSON stats API on localhost."""

    _instance: Optional["UIServer"] = None

    def __init__(self, port: int = 9000):
        self._httpd = ThreadingHTTPServer(("localhost", port), _Handler)
        self._httpd.stats_storage = None  # type: ignore[attr-defined]
        self._httpd.metrics_registry = None  # type: ignore[attr-defined]
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        daemon=True)
        self._thread.start()

    @classmethod
    def get_instance(cls, port: int = 9000) -> "UIServer":
        if cls._instance is None:
            cls._instance = cls(port)
        return cls._instance
    getInstance = get_instance

    @property
    def port(self) -> int:
        return self._httpd.server_address[1]

    def attach(self, storage: StatsStorage) -> None:
        self._httpd.stats_storage = storage  # type: ignore[attr-defined]

    def attach_metrics(self, registry) -> None:
        """Scope GET /metrics to a specific MetricsRegistry (e.g. one
        engine's `eng.metrics`) instead of the process-wide default."""
        self._httpd.metrics_registry = registry  # type: ignore[attr-defined]

    def detach(self, storage: StatsStorage = None) -> None:
        self._httpd.stats_storage = None  # type: ignore[attr-defined]

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        if UIServer._instance is self:
            UIServer._instance = None


class RemoteUIStatsStorageRouter(StatsStorageRouter):
    """Client-side router POSTing records to a UIServer's /remote/receive
    (ref impl/RemoteUIStatsStorageRouter.java)."""

    def __init__(self, address: str):
        # address like "http://localhost:9000"
        self.address = address.rstrip("/")

    def _post(self, kind: str, record: dict):
        import urllib.request
        data = json.dumps({"kind": kind, "record": record},
                          default=str).encode()
        req = urllib.request.Request(
            self.address + "/remote/receive", data=data,
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=10) as resp:
            return json.loads(resp.read().decode())

    def put_static_info(self, record: dict) -> None:
        self._post("static", record)

    def put_update(self, record: dict) -> None:
        self._post("update", record)
