"""Legacy UI listeners (ref deeplearning4j-ui/.../ui/weights/
HistogramIterationListener.java + ConvolutionalIterationListener.java and the
Flow listener family).

TPU-first rendering: instead of the reference's Play-served pages, each
listener emits either records into the StatsStorage chain (picked up by the
round-3 dashboard's histogram/graph views) or a self-contained SVG/HTML file —
zero servers required, nothing blocks the device loop (one host transfer per
visualization tick).
"""
from __future__ import annotations

import html
from typing import Optional

import numpy as np

from deeplearning4j_tpu.optimize.listeners import TrainingListener
from deeplearning4j_tpu.ui.stats import StatsListener


class HistogramIterationListener(StatsListener):
    """(ref HistogramIterationListener.java) — parameter/update histograms per
    iteration. The modern StatsListener already collects exactly this; the
    legacy class survives as a preset (histograms on, memory off) so existing
    reference call sites port 1:1."""

    def __init__(self, storage, frequency: int = 1, session_id=None):
        super().__init__(storage, frequency=frequency, session_id=session_id,
                         collect_histograms=True, collect_updates=True,
                         collect_memory=False)


class FlowIterationListener(StatsListener):
    """(ref FlowIterationListener) — model-graph 'flow' view. The static-info
    record carries the layer graph (config_json + layer_names); the dashboard's
    Model-graph panel renders it. Preset: no histograms (the flow view is
    topology + score)."""

    def __init__(self, storage, frequency: int = 1, session_id=None):
        super().__init__(storage, frequency=frequency, session_id=session_id,
                         collect_histograms=False, collect_updates=False,
                         collect_memory=False)


class ConvolutionalIterationListener(TrainingListener):
    """(ref ConvolutionalIterationListener.java:38) — every N iterations,
    render the first convolution layer's activation maps for one input as an
    SVG grid written to `output_dir` (conv_acts_iter_<i>.html)."""

    def __init__(self, output_dir: str, visualization_frequency: int = 10,
                 max_channels: int = 16, sample_input=None):
        import os
        self.output_dir = output_dir
        self.frequency = max(1, int(visualization_frequency))
        self.max_channels = int(max_channels)
        self.sample_input = sample_input
        os.makedirs(output_dir, exist_ok=True)
        self.last_path: Optional[str] = None

    def _first_conv_activations(self, model, x) -> Optional[np.ndarray]:
        from deeplearning4j_tpu.nn.conf.layers.convolutional import (
            ConvolutionLayer)
        acts = model.feed_forward(x, train=False)
        for layer, act in zip(model.layers, acts[1:]):
            if isinstance(layer, ConvolutionLayer):
                return np.asarray(act)
        return None

    def iteration_done(self, model, iteration: int):
        if iteration % self.frequency != 0:
            return
        x = self.sample_input
        if x is None or not hasattr(model, "feed_forward"):
            return
        act = self._first_conv_activations(model, np.asarray(x)[:1])
        if act is None:
            return
        self.last_path = self._render(act[0], iteration)

    def _render(self, act: np.ndarray, iteration: int) -> str:
        import os
        C = min(act.shape[0], self.max_channels)
        h, w = act.shape[1], act.shape[2]
        cell = 4
        cols = min(C, 8)
        rows = (C + cols - 1) // cols
        parts = []
        for c in range(C):
            a = act[c]
            lo, hi = float(a.min()), float(a.max())
            norm = (a - lo) / max(hi - lo, 1e-12)
            ox = (c % cols) * (w * cell + 8)
            oy = (c // cols) * (h * cell + 8)
            # downsample to at most 32x32 rects per map to keep files small
            step = max(1, h // 32, w // 32)
            for i in range(0, h, step):
                for j in range(0, w, step):
                    v = int(255 * float(norm[i, j]))
                    parts.append(
                        f'<rect x="{ox + j * cell}" y="{oy + i * cell}" '
                        f'width="{cell * step}" height="{cell * step}" '
                        f'fill="rgb({v},{v},{v})"/>')
        W = cols * (w * cell + 8)
        H = rows * (h * cell + 8)
        svg = (f'<svg xmlns="http://www.w3.org/2000/svg" width="{W}" '
               f'height="{H}">' + "".join(parts) + "</svg>")
        path = os.path.join(self.output_dir, f"conv_acts_iter_{iteration}.html")
        with open(path, "w") as f:
            f.write(f"<html><body><h3>{html.escape(str(iteration))}: first "
                    f"conv layer activations</h3>{svg}</body></html>")
        return path
