"""Stats storage API + in-memory and file-backed implementations.

Parity: ref deeplearning4j-ui-parent/deeplearning4j-ui-model/.../api/storage/
StatsStorage.java (session/type/worker-keyed static info + time-series updates,
storage event listeners) with InMemoryStatsStorage / FileStatsStorage /
StatsStorageRouter equivalents. Records are plain JSON-able dicts rather than
Persistable blobs — the whole UI pipeline stays language-neutral.
"""
from __future__ import annotations

import json
import os
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Tuple


@dataclass
class StatsStorageEvent:
    """(ref api/storage/StatsStorageEvent.java)"""
    event_type: str  # NewSessionID | NewTypeID | NewWorkerID | PostStaticInfo | PostUpdate
    session_id: str
    type_id: str
    worker_id: str
    timestamp: float = field(default_factory=time.time)


class StatsStorageRouter:
    """Write-side interface (ref api/storage/StatsStorageRouter.java) — training
    processes route records here; a storage is also a router."""

    def put_static_info(self, record: dict) -> None:
        raise NotImplementedError

    def put_update(self, record: dict) -> None:
        raise NotImplementedError

    # camelCase parity
    putStaticInfo = put_static_info
    putUpdate = put_update


def _key_of(record: dict) -> Tuple[str, str, str]:
    return (record.get("session_id", "default"),
            record.get("type_id", "StatsListener"),
            record.get("worker_id", "0"))


class StatsStorage(StatsStorageRouter):
    """Read side (ref api/storage/StatsStorage.java)."""

    def __init__(self):
        self._static: Dict[Tuple[str, str, str], dict] = {}
        self._updates: Dict[Tuple[str, str, str], List[dict]] = {}
        self._listeners: List[Callable[[StatsStorageEvent], None]] = []
        self._lock = threading.RLock()

    # ------------- write -------------
    def put_static_info(self, record: dict) -> None:
        key = _key_of(record)
        with self._lock:
            new_session = key[0] not in {k[0] for k in
                                         list(self._static) + list(self._updates)}
            self._static[key] = dict(record)
            self._persist("static", record)
        if new_session:
            self._emit("NewSessionID", key)
        self._emit("PostStaticInfo", key)

    def put_update(self, record: dict) -> None:
        key = _key_of(record)
        record.setdefault("timestamp", time.time())
        with self._lock:
            self._updates.setdefault(key, []).append(dict(record))
            self._persist("update", record)
        self._emit("PostUpdate", key)

    # ------------- read -------------
    def list_session_ids(self) -> List[str]:
        with self._lock:
            return sorted({k[0] for k in list(self._static) + list(self._updates)})

    def list_type_ids(self, session_id: str) -> List[str]:
        with self._lock:
            return sorted({k[1] for k in list(self._static) + list(self._updates)
                           if k[0] == session_id})

    def list_worker_ids(self, session_id: str, type_id: Optional[str] = None
                        ) -> List[str]:
        with self._lock:
            return sorted({k[2] for k in list(self._static) + list(self._updates)
                           if k[0] == session_id
                           and (type_id is None or k[1] == type_id)})

    def get_static_info(self, session_id: str, type_id: str = "StatsListener",
                        worker_id: str = "0") -> Optional[dict]:
        with self._lock:
            return self._static.get((session_id, type_id, worker_id))

    def get_all_updates(self, session_id: str, type_id: str = "StatsListener",
                        worker_id: str = "0") -> List[dict]:
        with self._lock:
            return list(self._updates.get((session_id, type_id, worker_id), []))

    def get_latest_update(self, session_id: str, type_id: str = "StatsListener",
                          worker_id: str = "0") -> Optional[dict]:
        ups = self.get_all_updates(session_id, type_id, worker_id)
        return ups[-1] if ups else None

    def get_updates_after(self, session_id: str, timestamp: float,
                          type_id: str = "StatsListener", worker_id: str = "0"
                          ) -> List[dict]:
        return [u for u in self.get_all_updates(session_id, type_id, worker_id)
                if u.get("timestamp", 0) > timestamp]

    # ------------- events -------------
    def register_stats_storage_listener(
            self, fn: Callable[[StatsStorageEvent], None]) -> None:
        self._listeners.append(fn)
    registerStatsStorageListener = register_stats_storage_listener

    def _emit(self, event_type: str, key):
        ev = StatsStorageEvent(event_type, *key)
        for fn in self._listeners:
            fn(ev)

    # ------------- persistence hook -------------
    def _persist(self, kind: str, record: dict) -> None:
        pass

    def close(self) -> None:
        pass


class InMemoryStatsStorage(StatsStorage):
    """(ref impl/InMemoryStatsStorage.java) — pure dict-backed."""


class FileStatsStorage(StatsStorage):
    """JSON-lines file persistence (ref impl/FileStatsStorage.java / the J7 MapDB
    variant). Reopening the same path reloads all prior sessions."""

    def __init__(self, path: str):
        super().__init__()
        self._path = path
        self._file = None
        if os.path.exists(path):
            with open(path, "r") as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    entry = json.loads(line)
                    key = _key_of(entry["record"])
                    if entry["kind"] == "static":
                        self._static[key] = entry["record"]
                    else:
                        self._updates.setdefault(key, []).append(entry["record"])
        self._file = open(path, "a")

    def _persist(self, kind: str, record: dict) -> None:
        if self._file is None:
            return

        def default(o):
            try:
                return float(o)
            except Exception:
                return str(o)

        self._file.write(json.dumps({"kind": kind, "record": record},
                                    default=default) + "\n")
        self._file.flush()

    def close(self) -> None:
        if self._file:
            self._file.close()
            self._file = None
