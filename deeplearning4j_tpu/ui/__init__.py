"""Observability (L9): stats capture, storage, and dashboard.

Parity: ref deeplearning4j-ui-parent — ui-model (BaseStatsListener + StatsStorage API),
ui (play-based dashboard server). TPU-first: stats summaries (mean/stdev/magnitude/
histograms) are computed on device in one fused jitted computation per report, then
shipped host-side as plain dicts; the dashboard is a stdlib HTTP server over the same
storage API instead of a Play/Netty stack.
"""
from deeplearning4j_tpu.ui.storage import (
    FileStatsStorage, InMemoryStatsStorage, StatsStorage, StatsStorageEvent,
    StatsStorageRouter)
from deeplearning4j_tpu.ui.stats import ProfilerListener, StatsListener
from deeplearning4j_tpu.ui.server import RemoteUIStatsStorageRouter, UIServer
from deeplearning4j_tpu.ui.components import (
    Component, ComponentChartHistogram, ComponentChartLine, ComponentDiv,
    ComponentHtmlRenderer, ComponentTable, ComponentText)
from deeplearning4j_tpu.ui.legacy_listeners import (
    ConvolutionalIterationListener, FlowIterationListener,
    HistogramIterationListener)

__all__ = [
    "StatsStorage", "StatsStorageRouter", "StatsStorageEvent", "InMemoryStatsStorage",
    "FileStatsStorage", "StatsListener", "ProfilerListener", "UIServer",
    "RemoteUIStatsStorageRouter", "HistogramIterationListener",
    "FlowIterationListener", "ConvolutionalIterationListener",
    "Component", "ComponentText", "ComponentTable", "ComponentChartLine",
    "ComponentChartHistogram", "ComponentDiv", "ComponentHtmlRenderer",
]
