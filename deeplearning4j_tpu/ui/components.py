"""Reusable report components: chart/table/text -> standalone HTML.

Parity: ref deeplearning4j-ui-components/.../components/ (chart/, table/,
text/, component/ + the TypeScript renderer dl4j-ui.js). TPU-first rendering:
components serialize to plain dicts and render to dependency-free inline SVG /
HTML (no TypeScript asset pipeline), which is how every report in this
framework ships (EvaluationTools ROC pages, the training dashboard).

    page = ComponentHtmlRenderer().render(
        ComponentText("Report"),
        ComponentChartLine("loss", [(xs, ys, "train")]),
        ComponentTable(["metric", "value"], [["acc", "0.98"]]))
"""
from __future__ import annotations

import html as _html
from typing import Any, Dict, List, Optional, Sequence, Tuple


class Component:
    """(ref components/component/Component.java) — serializable render node."""
    component_type = "component"

    def to_dict(self) -> Dict[str, Any]:
        raise NotImplementedError

    def render_html(self) -> str:
        raise NotImplementedError


class ComponentText(Component):
    """(ref text/ComponentText.java)"""
    component_type = "text"

    def __init__(self, text: str, heading: bool = True):
        self.text = text
        self.heading = heading

    def to_dict(self):
        return {"type": self.component_type, "text": self.text,
                "heading": self.heading}

    def render_html(self):
        tag = "h3" if self.heading else "p"
        return f"<{tag}>{_html.escape(self.text)}</{tag}>"


class ComponentTable(Component):
    """(ref table/ComponentTable.java)"""
    component_type = "table"

    def __init__(self, header: Sequence[str], rows: Sequence[Sequence[Any]]):
        self.header = list(header)
        self.rows = [list(r) for r in rows]

    def to_dict(self):
        return {"type": self.component_type, "header": self.header,
                "rows": self.rows}

    def render_html(self):
        out = ['<table style="border-collapse:collapse">',
               "<tr>" + "".join(
                   f'<th style="border:1px solid #ccc;padding:3px 8px">'
                   f"{_html.escape(str(h))}</th>" for h in self.header) + "</tr>"]
        for r in self.rows:
            out.append("<tr>" + "".join(
                f'<td style="border:1px solid #ccc;padding:3px 8px">'
                f"{_html.escape(str(v))}</td>" for v in r) + "</tr>")
        out.append("</table>")
        return "".join(out)


_COLORS = ("#36c", "#c63", "#383", "#936", "#693", "#369")


class ComponentChartLine(Component):
    """(ref chart/ChartLine.java) — multi-series line chart."""
    component_type = "chart_line"

    def __init__(self, title: str,
                 series: Sequence[Tuple[Sequence[float], Sequence[float], str]],
                 width: int = 560, height: int = 320,
                 x_label: str = "", y_label: str = ""):
        self.title = title
        self.series = [(list(x), list(y), str(n)) for x, y, n in series]
        self.width, self.height = int(width), int(height)
        self.x_label, self.y_label = x_label, y_label

    def to_dict(self):
        return {"type": self.component_type, "title": self.title,
                "series": [{"x": x, "y": y, "name": n}
                           for x, y, n in self.series]}

    def render_html(self):
        W, H, P = self.width, self.height, 42
        pts = [(x, y) for xs, ys, _ in self.series
               for x, y in zip(xs, ys) if y == y]
        if not pts:
            return f"<h4>{_html.escape(self.title)}</h4><svg/>"
        x0 = min(p[0] for p in pts)
        x1 = max(p[0] for p in pts)
        y0 = min(p[1] for p in pts)
        y1 = max(p[1] for p in pts)

        def sx(v):
            return P + (W - 2 * P) * (v - x0) / max(1e-12, x1 - x0)

        def sy(v):
            return H - P - (H - 2 * P) * (v - y0) / max(1e-12, y1 - y0)

        parts = [f'<rect x="{P}" y="{P}" width="{W - 2 * P}" '
                 f'height="{H - 2 * P}" fill="none" stroke="#ddd"/>']
        legend = []
        for i, (xs, ys, name) in enumerate(self.series):
            color = _COLORS[i % len(_COLORS)]
            d = ""
            for x, y in zip(xs, ys):
                if y == y:
                    d += f"{'L' if d else 'M'}{sx(x):.1f} {sy(y):.1f}"
            parts.append(f'<path d="{d}" stroke="{color}" fill="none" '
                         f'stroke-width="1.5"/>')
            legend.append(f'<tspan fill="{color}">{_html.escape(name)}</tspan>')
        parts.append(f'<text x="{P}" y="16" font-size="12">'
                     + " ".join(legend) + "</text>")
        parts.append(f'<text x="6" y="{P}" font-size="11">{y1:.4g}</text>')
        parts.append(f'<text x="6" y="{H - P}" font-size="11">{y0:.4g}</text>')
        if self.x_label:
            parts.append(f'<text x="{W // 2}" y="{H - 6}" font-size="12">'
                         f"{_html.escape(self.x_label)}</text>")
        return (f"<h4>{_html.escape(self.title)}</h4>"
                f'<svg xmlns="http://www.w3.org/2000/svg" width="{W}" '
                f'height="{H}" style="background:#fff">'
                + "".join(parts) + "</svg>")


class ComponentChartHistogram(Component):
    """(ref chart/ChartHistogram.java)"""
    component_type = "chart_histogram"

    def __init__(self, title: str, bin_edges: Sequence[float],
                 counts: Sequence[float], width: int = 560, height: int = 320):
        self.title = title
        self.bin_edges = list(bin_edges)
        self.counts = list(counts)
        self.width, self.height = int(width), int(height)

    def to_dict(self):
        return {"type": self.component_type, "title": self.title,
                "bin_edges": self.bin_edges, "counts": self.counts}

    def render_html(self):
        W, H, P = self.width, self.height, 30
        if not self.counts:
            return f"<h4>{_html.escape(self.title)}</h4><svg/>"
        m = max(self.counts) or 1.0
        bw = (W - 2 * P) / len(self.counts)
        parts = []
        for i, c in enumerate(self.counts):
            h = (H - 2 * P) * c / m
            parts.append(f'<rect x="{P + i * bw:.1f}" y="{H - P - h:.1f}" '
                         f'width="{max(1.0, bw - 1):.1f}" height="{h:.1f}" '
                         f'fill="#36c"/>')
        parts.append(f'<text x="{P}" y="{H - 8}" font-size="11">'
                     f"{self.bin_edges[0]:.3g}</text>")
        parts.append(f'<text x="{W - P - 40}" y="{H - 8}" font-size="11">'
                     f"{self.bin_edges[-1]:.3g}</text>")
        return (f"<h4>{_html.escape(self.title)}</h4>"
                f'<svg xmlns="http://www.w3.org/2000/svg" width="{W}" '
                f'height="{H}" style="background:#fff">'
                + "".join(parts) + "</svg>")


class ComponentTimeline(Component):
    """(ref the timeline charts StatsUtils.exportStatsAsHtml builds from
    EventStats, dl4j-spark/.../stats/StatsUtils.java:72-86) — horizontal
    lanes of [start, start+length) bars over a shared wall-clock axis;
    hover shows the bar's label + duration (SVG <title>, dependency-free
    like every component here)."""
    component_type = "timeline"

    def __init__(self, title: str,
                 lanes: Sequence[Tuple[str, Sequence[Tuple[float, float, str]]]],
                 width: int = 760, lane_height: int = 26):
        # lanes: [(lane_name, [(start_s, length_s, bar_label), ...]), ...]
        self.title = title
        self.lanes = [(str(n), [(float(s), float(l), str(t)) for s, l, t in bars])
                      for n, bars in lanes]
        self.width = int(width)
        self.lane_height = int(lane_height)

    def to_dict(self):
        return {"type": self.component_type, "title": self.title,
                "lanes": [{"name": n,
                           "bars": [{"start": s, "length": l, "label": t}
                                    for s, l, t in bars]}
                          for n, bars in self.lanes]}

    def render_html(self):
        W, LH, P = self.width, self.lane_height, 110  # left gutter for names
        allb = [b for _, bars in self.lanes for b in bars]
        if not allb:
            return f"<h4>{_html.escape(self.title)}</h4><svg/>"
        t0 = min(s for s, _, _ in allb)
        t1 = max(s + l for s, l, _ in allb)
        span = max(1e-9, t1 - t0)

        def sx(v):
            return P + (W - P - 10) * (v - t0) / span

        H = LH * len(self.lanes) + 34
        parts = []
        for i, (name, bars) in enumerate(self.lanes):
            y = i * LH + 4
            color = _COLORS[i % len(_COLORS)]
            parts.append(f'<text x="4" y="{y + LH - 12}" font-size="11">'
                         f"{_html.escape(name)}</text>")
            parts.append(f'<line x1="{P}" y1="{y + LH - 4}" x2="{W - 10}" '
                         f'y2="{y + LH - 4}" stroke="#eee"/>')
            for s, l, label in bars:
                x = sx(s)
                w = max(1.0, sx(s + l) - x)
                parts.append(
                    f'<rect x="{x:.1f}" y="{y}" width="{w:.1f}" '
                    f'height="{LH - 8}" fill="{color}" fill-opacity="0.75">'
                    f"<title>{_html.escape(label)} "
                    f"({l * 1e3:.1f} ms)</title></rect>")
        axis_y = LH * len(self.lanes) + 12
        parts.append(f'<line x1="{P}" y1="{axis_y}" x2="{W - 10}" '
                     f'y2="{axis_y}" stroke="#999"/>')
        parts.append(f'<text x="{P}" y="{axis_y + 14}" font-size="11">'
                     f"0 s</text>")
        parts.append(f'<text x="{W - 70}" y="{axis_y + 14}" font-size="11">'
                     f"{span:.3g} s</text>")
        return (f"<h4>{_html.escape(self.title)}</h4>"
                f'<svg xmlns="http://www.w3.org/2000/svg" width="{W}" '
                f'height="{H}" style="background:#fff">'
                + "".join(parts) + "</svg>")


class ComponentDiv(Component):
    """(ref component/ComponentDiv.java) — container with child components."""
    component_type = "div"

    def __init__(self, *children: Component, style: str = ""):
        self.children = list(children)
        self.style = style

    def to_dict(self):
        return {"type": self.component_type, "style": self.style,
                "children": [c.to_dict() for c in self.children]}

    def render_html(self):
        inner = "".join(c.render_html() for c in self.children)
        style = f' style="{_html.escape(self.style)}"' if self.style else ""
        return f"<div{style}>{inner}</div>"


class ComponentHtmlRenderer:
    """(ref the dl4j-ui.js renderer + StaticPageUtil) — standalone page."""

    def render(self, *components: Component, title: str = "Report") -> str:
        body = "".join(c.render_html() for c in components)
        return (f"<!DOCTYPE html><html><head><title>{_html.escape(title)}"
                f"</title></head><body style=\"font-family:sans-serif\">"
                f"{body}</body></html>")

    def render_to_file(self, path: str, *components: Component,
                       title: str = "Report") -> None:
        with open(path, "w") as f:
            f.write(self.render(*components, title=title))
