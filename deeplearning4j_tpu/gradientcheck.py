"""Numeric gradient checking — the backbone of the test suite.

Parity: ref gradientcheck/GradientCheckUtil.java:37-88 — central-difference every
parameter (epsilon≈1e-4, maxRelError≈1e-5 in double precision) against the analytic
gradient. Here "analytic" = jax.grad through the traced network; the check validates the
whole forward/loss construction, exactly as the reference's suites do per layer.
Runs in float64 (jax.config x64 must be enabled by the caller/test fixture); the scoring
function is jitted ONCE over the flat parameter vector, so each perturbation is a single
compiled executable call.
"""
from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.util.flat_params import flatten_params, unflatten_params


def check_gradients(net, x, y, *, epsilon: float = 1e-6, max_rel_error: float = 1e-5,
                    min_abs_error: float = 1e-8, fmask=None, lmask=None,
                    subset: Optional[int] = None, seed: int = 0,
                    print_failures: bool = True) -> bool:
    """Finite-difference vs analytic gradient over every (or a random subset of) params.

    `net` must expose params_tree/state_tree/_loss_fn — both MultiLayerNetwork and
    ComputationGraph do.
    """
    def _cast(v):
        if isinstance(v, (list, tuple)):
            return tuple(jnp.asarray(i, net.dtype) for i in v)
        return jnp.asarray(v, net.dtype)

    x = _cast(x)
    y = _cast(y)
    template = net.params_tree
    state = net.state_tree

    def score_flat(flat):
        pt = unflatten_params(template, flat)
        loss, _ = net._loss_fn(pt, state, x, y, fmask, lmask, None, True, None)
        return loss

    score_jit = jax.jit(score_flat)
    grad_jit = jax.jit(jax.grad(score_flat))

    flat0 = np.array(flatten_params(template), np.float64)
    analytic = np.asarray(grad_jit(jnp.asarray(flat0)), np.float64)
    n = flat0.shape[0]

    if subset is not None and subset < n:
        rng = np.random.RandomState(seed)
        indices = rng.choice(n, size=subset, replace=False)
    else:
        indices = range(n)

    failures = 0
    checked = 0
    for i in indices:
        orig = flat0[i]
        flat0[i] = orig + epsilon
        plus = float(score_jit(jnp.asarray(flat0)))
        flat0[i] = orig - epsilon
        minus = float(score_jit(jnp.asarray(flat0)))
        flat0[i] = orig
        numeric = (plus - minus) / (2 * epsilon)
        a = analytic[i]
        denom = abs(a) + abs(numeric)
        rel = abs(a - numeric) / denom if denom > 0 else 0.0
        checked += 1
        if rel > max_rel_error and abs(a - numeric) > min_abs_error:
            failures += 1
            if print_failures:
                print(f"param[{i}]: analytic={a:.8g} numeric={numeric:.8g} rel={rel:.3g}")
    if print_failures and failures:
        print(f"Gradient check FAILED: {failures}/{checked} params exceed tolerance")
    return failures == 0
