"""Fused flash-attention Pallas kernels — the long-context hot path.

Beyond-reference (the 2017 reference has no attention at all; SURVEY §5
long-context). The framework's blockwise/ring attention
(parallel/sequence_parallel.py) implements the flash RECURRENCE as a
lax.scan — correct and O(T*block) memory, but each block step dispatches
thin XLA ops (scores matmul, exp/merge chain on the VPU, rescale) and
training rematerializes the whole scan body. These kernels fuse the
recurrence on-chip (arXiv:2205.14135 / flash-attention-2 schedule):

- forward: grid (B*H, T/bq, T/bk) with the k index FASTEST — the online
  softmax accumulator (acc, m, l) lives in VMEM scratch across each q
  block's k sweep (sequential, grid-order guarantee as in
  lstm_scan_fused), one (bq, bk) score tile at a time; emits o and the
  row logsumexp L = m + log(l) for the backward;
- backward, default "fused" single pass (grid = the dkv sweep, q index
  fastest): p is RECOMPUTED once per score tile from (q, k, L) — nothing
  but o/L is saved — and feeds dv/dk (VMEM scratch) AND dq in the same
  tile visit. dq accumulates across the SLOW grid axis, which TPU output
  revisiting cannot express, so each tile writes a (bq, D) partial to a
  per-k-block HBM buffer summed by one XLA reduction (nk*|dq| extra
  traffic — measured cheaper than paying the exp/softmax VPU chain twice;
  at these head dims the VPU, not the MXU, is the wall). Partials are
  stored in the fp32 accumulator dtype by default (full-precision dq
  accumulation, same as the two-pass scratch; measured +3.7% step cost vs
  bf16 partials — configure(dq_partials="io") buys it back if wanted).
  configure(bwd="two_pass") selects the flash-2 schedule (separate dq and
  dkv kernels, each recomputing p) for A/B.
  D_i = rowsum(dO * o) is one cheap XLA reduction outside.

Causal masking, sliding-window (local) attention, and the framework's
(B, T) key-padding masks are applied per score tile from global row/col
ids; tiles with no valid pair (fully future, fully outside the window)
skip the score math entirely, so windowed cost scales with T*window.
Score/softmax math is fp32 (flash convention); q/k/v stream in their
storage dtype (bf16 on TPU).

Registered as helper "flash_attention" (default-on for TPU);
SelfAttentionLayer's long-context path dispatches here when enabled, with
the lax.scan blockwise recurrence as the universal fallback.
"""
from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.ops.helpers import register_helper

NEG_INF = -1e30


def _interpret() -> bool:
    from deeplearning4j_tpu.ops.helpers import interpret_mode
    return interpret_mode()


# bq/bk = 0 means "auto": 1024 tiles at long T, 512 below (A/B'd on chip,
# experiments/flash_block_ab.py — 1024/1024 is +13% over 512/512 at the
# bench shape T=8192 Dh=64; 256 tiles are 15-28% WORSE, so 512 floors it).
DEFAULT_BQ = 0
DEFAULT_BK = 0

# Backward schedule: "fused" = one pass computing p once per tile (dk/dv in
# scratch, per-k-block dq partials to HBM + XLA reduce); "two_pass" =
# flash-2 style separate dq and dk/dv kernels (each recomputes p).
# dq_partials: dtype the fused schedule stores its per-k-block dq partials
# in before the XLA sum — "acc" (the fp32/fp64 accumulator dtype; default,
# matching the two-pass dq scratch's full-precision accumulation) or "io"
# (q.dtype — halves the partial-buffer HBM traffic at the cost of one bf16
# rounding per k block before the sum).
_CONFIG = {"bwd": os.environ.get("DL4J_TPU_FLASH_BWD", "fused"),
           "dq_partials": os.environ.get("DL4J_TPU_FLASH_DQ_PARTIALS", "acc")}

# HBM ceiling for the fused schedule's (BH, nk, Tp, D) dq-partials buffer —
# it grows O(T^2 * D / bk), so long contexts (T=32k is ~4.3 GB fp32 at the
# bench head count) must not pay it. Above the cap the backward silently
# takes the two_pass schedule (O(T * block) memory, same math). The bench
# shape T=8192 stays comfortably under the default 2 GiB.
DQ_PARTIALS_MAX_BYTES = int(os.environ.get(
    "DL4J_TPU_FLASH_DQP_MAX_BYTES", 2 * 1024 ** 3))


def configure(bwd: str | None = None, dq_partials: str | None = None):
    """Override the default backward schedule ('fused' | 'two_pass') and/or
    the fused-schedule dq-partials dtype ('acc' | 'io'); returns the
    previous (bwd, dq_partials) pair.

    The defaults are resolved when flash_attention() is CALLED (threaded
    through the custom VJP as explicit non-diff arguments), so configure()
    takes effect for every subsequent call — including the backward of a
    forward traced after the change. A jit-compiled CALLER that already
    baked a traced flash_attention keeps its schedule until that outer jit
    retraces (per-call schedule can also be forced explicitly:
    flash_attention(..., bwd='two_pass'))."""
    prev = (_CONFIG["bwd"], _CONFIG["dq_partials"])
    if bwd is not None:
        if bwd not in ("fused", "two_pass"):
            raise ValueError(f"unknown flash bwd mode {bwd!r}")
        _CONFIG["bwd"] = bwd
    if dq_partials is not None:
        if dq_partials not in ("acc", "io"):
            raise ValueError(f"unknown dq_partials mode {dq_partials!r}")
        _CONFIG["dq_partials"] = dq_partials
    return prev


def _resolve_blocks(bq: int, bk: int, T: int) -> tuple[int, int]:
    if not bq:
        bq = 1024 if T >= 4096 else 512
    if not bk:
        bk = 1024 if T >= 4096 else 512
    return bq, bk


def _blocks(T: int, b: int) -> int:
    return -(-T // b)


def _fwd_kernel(q_ref, k_ref, v_ref, km_ref, o_ref, l_ref,
                acc_scr, m_scr, l_scr, *, causal, scale, bq, bk, T, Tp,
                has_mask, acc_dt, window=0):
    from jax.experimental import pallas as pl
    j = pl.program_id(2)
    i = pl.program_id(1)
    nk = pl.num_programs(2)

    @pl.when(j == 0)
    def _():
        acc_scr[:] = jnp.zeros_like(acc_scr)
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)

    def update(masked):
        def body():
            s = jax.lax.dot_general(
                q_ref[0], k_ref[0], (((1,), (1,)), ((), ())),
                preferred_element_type=acc_dt) * scale
            if masked:
                valid = _valid_tile(pl, i, j, bq, bk, T, Tp, causal,
                                    has_mask, km_ref, window)
                s = jnp.where(valid, s, NEG_INF)
            m_new = jnp.maximum(m_scr[:], jnp.max(s, axis=1))
            p = jnp.exp(s - m_new[:, None])
            if masked:
                p = jnp.where(valid, p, 0.0)
            alpha = jnp.exp(m_scr[:] - m_new)
            l_scr[:] = l_scr[:] * alpha + jnp.sum(p, axis=1)
            acc_scr[:] = acc_scr[:] * alpha[:, None] + jax.lax.dot_general(
                p.astype(v_ref.dtype), v_ref[0], (((1,), (0,)), ((), ())),
                preferred_element_type=acc_dt)
            m_scr[:] = m_new
        return body

    _dispatch_tile(pl, update, i, j, nk, bq, bk, T, Tp, causal,
                   has_mask, window)

    @pl.when(j == nk - 1)
    def _():
        l = l_scr[:]
        o_ref[0] = (acc_scr[:] / jnp.maximum(l, 1e-30)[:, None]).astype(
            o_ref.dtype)
        # L for the backward: rows with no visible key keep L = NEG_INF
        # (their recomputed p is masked to 0 anyway)
        l_ref[0, 0, pl.ds(i * bq, bq)] = jnp.where(
            l > 0, m_scr[:] + jnp.log(jnp.maximum(l, 1e-30)), NEG_INF)


def _valid_tile(pl, i, j, bq, bk, T, Tp, causal, has_mask, km_ref,
                window=0):
    """(bq, bk) validity of this score tile — built ONLY for tiles that
    need masking (the dispatcher routes interior tiles to the fast body
    with none of these VPU passes). `window` > 0 limits attention to
    qi - kj < window (causal: a trailing window ending at qi; non-causal:
    additionally kj - qi < window, a symmetric band)."""
    qi = i * bq + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
    kj = j * bk + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
    valid = None

    def _and(a, b):
        return b if a is None else a & b

    if Tp != T:
        valid = _and(valid, kj < T)      # tail-block padding keys drop
    if causal:
        valid = _and(valid, qi >= kj)
    if window:
        valid = _and(valid, qi - kj < window)
        if not causal:
            valid = _and(valid, kj - qi < window)
    if has_mask:
        valid = _and(valid, (km_ref[0, 0, pl.ds(j * bk, bk)] > 0)[None, :])
    if valid is None:                     # dispatcher never does this
        valid = jnp.ones((bq, bk), bool)
    return valid


def _dispatch_tile(pl, update, i, j, nk, bq, bk, T, Tp, causal, has_mask,
                   window=0, on_skip=None):
    """Route this tile to the fast (unmasked) body, the masked body, or
    skip it. Interior tiles — the majority at long T — take the fast body
    with zero mask passes; tiles with NO valid pair (fully future under
    causal, fully outside the sliding window) skip the math entirely (the
    DMA still streams: rectangular grid). `on_skip` runs INSTEAD of the
    body on skipped tiles — kernels whose per-tile output block must
    always be written (the fused backward's dq partials) zero-fill there."""
    q_lo, q_hi = i * bq, i * bq + bq - 1
    k_lo, k_hi = j * bk, j * bk + bk - 1

    # any-valid-pair conditions (tile runs at all)
    run_conds = []
    if causal:
        run_conds.append(k_lo <= q_hi)
    if window:
        run_conds.append(k_hi >= q_lo - (window - 1))
        if not causal:
            run_conds.append(k_lo <= q_hi + (window - 1))
    run = None
    for c in run_conds:
        run = c if run is None else run & c
    if run is not None and on_skip is not None:
        pl.when(jnp.logical_not(run))(on_skip)

    if has_mask:   # key-padding mask: every running tile takes the mask
        if run is None:
            update(True)()
        else:
            pl.when(run)(update(True))
        return

    # edge-crossing conditions (tile needs the masked body)
    mask_conds = []
    if causal:
        mask_conds.append(k_hi > q_lo)                    # crosses diagonal
    if window:
        mask_conds.append(q_hi - k_lo > window - 1)       # crosses back edge
        if not causal:
            mask_conds.append(k_hi - q_lo > window - 1)   # crosses front edge
    if Tp != T:
        mask_conds.append(j == nk - 1)                    # pad-key tail block
    masked = None
    for c in mask_conds:
        masked = c if masked is None else masked | c

    if masked is None:
        if run is None:
            update(False)()
        else:
            pl.when(run)(update(False))
        return
    if run is None:
        pl.when(masked)(update(True))
        pl.when(jnp.logical_not(masked))(update(False))
    else:
        pl.when(run & masked)(update(True))
        pl.when(run & jnp.logical_not(masked))(update(False))


def _dq_kernel(q_ref, k_ref, v_ref, km_ref, do_ref, L_ref, Di_ref,
               dq_ref, dq_scr, *, causal, scale, bq, bk, T, Tp, has_mask,
               acc_dt, window=0):
    from jax.experimental import pallas as pl
    j = pl.program_id(2)
    i = pl.program_id(1)
    nk = pl.num_programs(2)

    @pl.when(j == 0)
    def _():
        dq_scr[:] = jnp.zeros_like(dq_scr)

    def update(masked):
        def body():
            s = jax.lax.dot_general(
                q_ref[0], k_ref[0], (((1,), (1,)), ((), ())),
                preferred_element_type=acc_dt) * scale
            p = jnp.exp(s - L_ref[0, 0, pl.ds(i * bq, bq)][:, None])
            if masked:
                valid = _valid_tile(pl, i, j, bq, bk, T, Tp, causal,
                                    has_mask, km_ref, window)
                p = jnp.where(valid, p, 0.0)
            dp = jax.lax.dot_general(
                do_ref[0], v_ref[0], (((1,), (1,)), ((), ())),
                preferred_element_type=acc_dt)
            ds = p * (dp - Di_ref[0, 0, pl.ds(i * bq, bq)][:, None])
            dq_scr[:] += scale * jax.lax.dot_general(
                ds.astype(k_ref.dtype), k_ref[0], (((1,), (0,)), ((), ())),
                preferred_element_type=acc_dt)
        return body

    _dispatch_tile(pl, update, i, j, nk, bq, bk, T, Tp, causal,
                   has_mask, window)

    @pl.when(j == nk - 1)
    def _():
        dq_ref[0] = dq_scr[:].astype(dq_ref.dtype)


def _dkv_kernel(q_ref, k_ref, v_ref, km_ref, do_ref, L_ref, Di_ref,
                dk_ref, dv_ref, dk_scr, dv_scr, *, causal, scale, bq, bk,
                T, Tp, has_mask, acc_dt, window=0):
    from jax.experimental import pallas as pl
    i = pl.program_id(2)        # q block index — FASTEST (the k sweep)
    j = pl.program_id(1)        # k block index
    nq = pl.num_programs(2)

    @pl.when(i == 0)
    def _():
        dk_scr[:] = jnp.zeros_like(dk_scr)
        dv_scr[:] = jnp.zeros_like(dv_scr)

    def update(masked):
        def body():
            s = jax.lax.dot_general(
                q_ref[0], k_ref[0], (((1,), (1,)), ((), ())),
                preferred_element_type=acc_dt) * scale
            p = jnp.exp(s - L_ref[0, 0, pl.ds(i * bq, bq)][:, None])
            if masked:
                valid = _valid_tile(pl, i, j, bq, bk, T, Tp, causal,
                                    has_mask, km_ref, window)
                p = jnp.where(valid, p, 0.0)
            pl_ = p.astype(do_ref.dtype)
            dv_scr[:] += jax.lax.dot_general(
                pl_, do_ref[0], (((0,), (0,)), ((), ())),
                preferred_element_type=acc_dt)
            dp = jax.lax.dot_general(
                do_ref[0], v_ref[0], (((1,), (1,)), ((), ())),
                preferred_element_type=acc_dt)
            ds = (p * (dp - Di_ref[0, 0, pl.ds(i * bq, bq)][:, None])).astype(
                q_ref.dtype)
            dk_scr[:] += scale * jax.lax.dot_general(
                ds, q_ref[0], (((0,), (0,)), ((), ())),
                preferred_element_type=acc_dt)
        return body

    # note the swapped loop order: i is fastest here; the dispatcher's nk
    # (tail-k-block test) is this grid's dim 1, NOT nq
    _dispatch_tile(pl, update, i, j, pl.num_programs(1), bq, bk, T, Tp,
                   causal, has_mask, window)

    @pl.when(i == nq - 1)
    def _():
        dk_ref[0] = dk_scr[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_scr[:].astype(dv_ref.dtype)


def _fused_bwd_kernel(q_ref, k_ref, v_ref, km_ref, do_ref, L_ref, Di_ref,
                      dk_ref, dv_ref, dqp_ref, dk_scr, dv_scr, *, causal,
                      scale, bq, bk, T, Tp, has_mask, acc_dt, window=0):
    """One-pass backward: p is computed ONCE per score tile and feeds all
    three cotangents (the two-pass schedule pays the exp/softmax VPU chain
    twice — the measured wall at these head dims, not the MXU). dk/dv
    accumulate in VMEM scratch across the q sweep (i fastest, like
    _dkv_kernel); dq cannot share that residency (it accumulates across
    the SLOW axis j, and revisiting an output block on non-consecutive
    grid steps is not legal on TPU), so each tile writes its (bq, D)
    partial to a per-k-block HBM buffer that one XLA reduction sums —
    nk*|dq| extra traffic, far cheaper than a third tile pass."""
    from jax.experimental import pallas as pl
    i = pl.program_id(2)        # q block index — FASTEST (the k sweep)
    j = pl.program_id(1)        # k block index
    nq = pl.num_programs(2)

    @pl.when(i == 0)
    def _():
        dk_scr[:] = jnp.zeros_like(dk_scr)
        dv_scr[:] = jnp.zeros_like(dv_scr)

    def update(masked):
        def body():
            s = jax.lax.dot_general(
                q_ref[0], k_ref[0], (((1,), (1,)), ((), ())),
                preferred_element_type=acc_dt) * scale
            p = jnp.exp(s - L_ref[0, 0, pl.ds(i * bq, bq)][:, None])
            if masked:
                valid = _valid_tile(pl, i, j, bq, bk, T, Tp, causal,
                                    has_mask, km_ref, window)
                p = jnp.where(valid, p, 0.0)
            dv_scr[:] += jax.lax.dot_general(
                p.astype(do_ref.dtype), do_ref[0], (((0,), (0,)), ((), ())),
                preferred_element_type=acc_dt)
            dp = jax.lax.dot_general(
                do_ref[0], v_ref[0], (((1,), (1,)), ((), ())),
                preferred_element_type=acc_dt)
            ds = (p * (dp - Di_ref[0, 0, pl.ds(i * bq, bq)][:, None])).astype(
                q_ref.dtype)
            dk_scr[:] += scale * jax.lax.dot_general(
                ds, q_ref[0], (((0,), (0,)), ((), ())),
                preferred_element_type=acc_dt)
            dqp_ref[0, 0] = (scale * jax.lax.dot_general(
                ds, k_ref[0], (((1,), (0,)), ((), ())),
                preferred_element_type=acc_dt)).astype(dqp_ref.dtype)
        return body

    def skip():
        dqp_ref[0, 0] = jnp.zeros_like(dqp_ref[0, 0])

    # i fastest: the dispatcher's nk (tail-k-block test) is grid dim 1
    _dispatch_tile(pl, update, i, j, pl.num_programs(1), bq, bk, T, Tp,
                   causal, has_mask, window, on_skip=skip)

    @pl.when(i == nq - 1)
    def _():
        dk_ref[0] = dk_scr[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_scr[:].astype(dv_ref.dtype)


def _prep(q, k, v, mask, bq, bk):
    """(B, H, T, D) q and (B, Hk, T, D) k/v -> (B*H, Tp, D) / (B*Hk, Tp, D)
    padded to block multiples + (B*H, 1, Tp) key mask (pad keys masked out;
    pad QUERY rows compute garbage that the caller slices off). Hk may
    divide H (grouped-query attention: each group of H/Hk query heads
    shares one k/v head — the kernels never materialize the repeat, their
    k/v BlockSpecs map the grid's q-head index to its kv row)."""
    B, H, T, D = q.shape
    Hk = k.shape[1]
    if k.shape != v.shape or k.shape[0] != B or k.shape[2] != T \
            or k.shape[3] != D or H % Hk != 0:
        raise ValueError(
            f"bad GQA shapes: q {q.shape}, k {k.shape}, v {v.shape} "
            f"(need k == v, same B/T/D, and n_heads % n_kv_heads == 0)")
    Tqp = _blocks(T, bq) * bq
    Tkp = _blocks(T, bk) * bk
    Tp = max(Tqp, Tkp)

    def r(a):
        a = a.reshape(-1, T, D)
        return jnp.pad(a, ((0, 0), (0, Tp - T), (0, 0)))

    km = jnp.ones((B, T), jnp.int32) if mask is None \
        else (mask > 0).astype(jnp.int32)
    km = jnp.repeat(km, H, axis=0)                       # (BH, T)
    km = jnp.pad(km, ((0, 0), (0, Tp - T)))              # pad keys -> 0
    return r(q), r(k), r(v), km[:, None, :], Tp           # (BH, 1, Tp)


def _kv_row(H: int, Hk: int):
    """Grid q-head index b in [0, B*H) -> its kv row in [0, B*Hk): query
    head h = b % H belongs to kv head h // (H // Hk)."""
    if H == Hk:
        return lambda b: b
    g = H // Hk
    return lambda b: (b // H) * Hk + (b % H) // g


def _call_fwd(qp, kp, vp, km, causal, scale, bq, bk, T, has_mask,
              window=0, H=None, Hk=None):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu
    BH, Tp, D = qp.shape
    kv = _kv_row(H, Hk) if H else (lambda b: b)
    nq, nk = Tp // bq, Tp // bk
    acc_dt = jnp.promote_types(qp.dtype, jnp.float32)
    kern = functools.partial(_fwd_kernel, causal=causal, scale=scale,
                             bq=bq, bk=bk, T=T, Tp=Tp, has_mask=has_mask,
                             acc_dt=acc_dt, window=window)
    o, L = pl.pallas_call(
        kern,
        grid=(BH, nq, nk),
        in_specs=[
            pl.BlockSpec((1, bq, D), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, bk, D), lambda b, i, j: (kv(b), j, 0)),
            pl.BlockSpec((1, bk, D), lambda b, i, j: (kv(b), j, 0)),
            pl.BlockSpec((1, 1, Tp), lambda b, i, j: (b, 0, 0)),
        ],
        out_specs=(
            pl.BlockSpec((1, bq, D), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, 1, Tp), lambda b, i, j: (b, 0, 0)),
        ),
        out_shape=(
            jax.ShapeDtypeStruct((BH, Tp, D), qp.dtype),
            jax.ShapeDtypeStruct((BH, 1, Tp), acc_dt),
        ),
        scratch_shapes=[
            pltpu.VMEM((bq, D), acc_dt),
            pltpu.VMEM((bq,), acc_dt),
            pltpu.VMEM((bq,), acc_dt),
        ],
        interpret=_interpret(),
    )(qp, kp, vp, km)
    return o, L


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7, 8, 9, 10))
def _flash_core(q, k, v, mask, causal, scale, bq, bk, window, bwd,
                dq_partials):
    """custom_vjp core with the backward schedule as explicit non-diff
    arguments (resolved from _CONFIG by the public wrapper at CALL time, so
    configure() is never silently ignored by an already-traced vjp)."""
    out, _ = _fa_fwd(q, k, v, mask, causal, scale, bq, bk, window, bwd,
                     dq_partials)
    return out


def flash_attention(q, k, v, mask=None, causal: bool = False,
                    scale: float | None = None, bq: int = DEFAULT_BQ,
                    bk: int = DEFAULT_BK, window: int = 0,
                    bwd: str | None = None, dq_partials: str | None = None):
    """q/k/v: (B, H, T, D); k/v may carry Hk | H heads (grouped-query
    attention — forward only; the grouped backward is not implemented and
    raises). mask: optional (B, T) key-padding mask. Returns (B, H, T, D).
    Fused online-softmax attention; see module docstring. `window` > 0 =
    sliding-window (local) attention: causal keeps the trailing window
    qi-window < kj <= qi; non-causal keeps the symmetric band |qi-kj| <
    window. Tiles fully outside the window are SKIPPED (no score math), so
    cost scales with T*window, not T^2. bwd/dq_partials: per-call backward
    schedule override (None -> the configure() defaults, read NOW)."""
    if bwd is None:
        bwd = _CONFIG["bwd"]
    if dq_partials is None:
        dq_partials = _CONFIG["dq_partials"]
    return _flash_core(q, k, v, mask, causal, scale, bq, bk, window, bwd,
                       dq_partials)


def _fa_fwd(q, k, v, mask, causal, scale, bq, bk, window, bwd, dq_partials):
    (out, _), res = _fa_lse_fwd(q, k, v, mask, causal, scale, bq, bk,
                                window)
    return out, res


def _fa_bwd(causal, scale, bq, bk, window, bwd, dq_partials, saved, dout):
    return _fa_bwd_impl(causal, scale, bq, bk, saved, dout, None, window,
                        bwd, dq_partials)


def _fa_bwd_impl(causal, scale, bq, bk, saved, dout, dlse, window=0,
                 bwd=None, dq_partials=None):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu
    if bwd is None:
        bwd = _CONFIG["bwd"]
    if dq_partials is None:
        dq_partials = _CONFIG["dq_partials"]
    q, k, v, mask, o, L = saved
    if k.shape[1] != q.shape[1]:
        # the kernels below index the (B*Hk, ...) k/v buffers with the
        # q-head grid index and would return dk/dv with the q aval —
        # silently wrong for grouped-query attention. The grouped backward
        # (head-group segment-sum of dk/dv partials) is not implemented;
        # GQA TRAINING paths must broadcast k/v to full heads first (what
        # SelfAttentionLayer does), GQA INFERENCE may use this forward.
        raise NotImplementedError(
            f"flash_attention backward with grouped k/v heads "
            f"(H={q.shape[1]}, Hk={k.shape[1]}) is not implemented; "
            "repeat k/v to the full head count before differentiating")
    B, H, T, D = q.shape
    bq, bk = _resolve_blocks(bq, bk, T)
    scale_ = float(scale) if scale is not None else 1.0 / float(np.sqrt(D))
    qp, kp, vp, km, Tp = _prep(q, k, v, mask, bq, bk)
    dop = jnp.pad(dout.reshape(B * H, T, D), ((0, 0), (0, Tp - T), (0, 0)))
    acc_dt = jnp.promote_types(qp.dtype, jnp.float32)
    # D_i = rowsum(dO * o) — one cheap XLA reduction, accumulated one width up
    Di = jnp.sum(dop.astype(acc_dt) * o.astype(acc_dt), axis=-1)[:, None, :]
    if dlse is not None:
        # L as an OUTPUT: dL_i/ds_ij = p_ij, so ds gains p * dL - absorbed
        # by shifting the D_i term (ds = p * (dp - (Di - dL)))
        dl = jnp.pad(dlse.reshape(B * H, T).astype(acc_dt),
                     ((0, 0), (0, Tp - T)))[:, None, :]
        Di = Di - dl
    BH = B * H
    nq, nk = Tp // bq, Tp // bk
    if bwd == "fused":
        dqp_dt = acc_dt if dq_partials == "acc" else q.dtype
        # the dq-partials buffer is O(T^2 * D / bk) — above the HBM cap the
        # two_pass schedule (O(T * block) memory) takes over
        dqp_bytes = BH * nk * Tp * D * jnp.dtype(dqp_dt).itemsize
        if dqp_bytes > DQ_PARTIALS_MAX_BYTES:
            bwd = "two_pass"
    if bwd == "fused":
        qspec2 = pl.BlockSpec((1, bq, D), lambda b, j, i: (b, i, 0))
        kspec2 = pl.BlockSpec((1, bk, D), lambda b, j, i: (b, j, 0))
        dk, dv, dqp = pl.pallas_call(
            functools.partial(_fused_bwd_kernel, causal=causal, scale=scale_,
                              bq=bq, bk=bk, T=T, Tp=Tp,
                              has_mask=mask is not None, acc_dt=acc_dt,
                              window=window),
            grid=(BH, nk, nq),
            in_specs=[qspec2, kspec2, kspec2,
                      pl.BlockSpec((1, 1, Tp), lambda b, j, i: (b, 0, 0)),
                      qspec2,
                      pl.BlockSpec((1, 1, Tp), lambda b, j, i: (b, 0, 0)),
                      pl.BlockSpec((1, 1, Tp), lambda b, j, i: (b, 0, 0))],
            out_specs=(kspec2, kspec2,
                       pl.BlockSpec((1, 1, bq, D),
                                    lambda b, j, i: (b, j, i, 0))),
            out_shape=(jax.ShapeDtypeStruct((BH, Tp, D), k.dtype),
                       jax.ShapeDtypeStruct((BH, Tp, D), v.dtype),
                       jax.ShapeDtypeStruct((BH, nk, Tp, D), dqp_dt)),
            scratch_shapes=[pltpu.VMEM((bk, D), acc_dt),
                            pltpu.VMEM((bk, D), acc_dt)],
            interpret=_interpret(),
        )(qp, kp, vp, km, dop, L, Di)
        dq = jnp.sum(dqp.astype(acc_dt), axis=1).astype(q.dtype)
        shp = lambda a: a[:, :T].reshape(B, H, T, D)
        dmask = None if mask is None else jnp.zeros_like(mask)
        return shp(dq), shp(dk), shp(dv), dmask
    qspec = pl.BlockSpec((1, bq, D), lambda b, i, j: (b, i, 0))
    kspec = pl.BlockSpec((1, bk, D), lambda b, i, j: (b, j, 0))
    dq = pl.pallas_call(
        functools.partial(_dq_kernel, causal=causal, scale=scale_,
                          bq=bq, bk=bk, T=T, Tp=Tp,
                          has_mask=mask is not None, acc_dt=acc_dt,
                          window=window),
        grid=(BH, nq, nk),
        in_specs=[qspec, kspec, kspec,
                  pl.BlockSpec((1, 1, Tp), lambda b, i, j: (b, 0, 0)),
                  qspec,
                  pl.BlockSpec((1, 1, Tp), lambda b, i, j: (b, 0, 0)),
                  pl.BlockSpec((1, 1, Tp), lambda b, i, j: (b, 0, 0))],
        out_specs=qspec,
        out_shape=jax.ShapeDtypeStruct((BH, Tp, D), q.dtype),
        scratch_shapes=[pltpu.VMEM((bq, D), acc_dt)],
        interpret=_interpret(),
    )(qp, kp, vp, km, dop, L, Di)
    # dk/dv: q index fastest — grid (BH, nk, nq)
    qspec2 = pl.BlockSpec((1, bq, D), lambda b, j, i: (b, i, 0))
    kspec2 = pl.BlockSpec((1, bk, D), lambda b, j, i: (b, j, 0))
    dk, dv = pl.pallas_call(
        functools.partial(_dkv_kernel, causal=causal, scale=scale_,
                          bq=bq, bk=bk, T=T, Tp=Tp,
                          has_mask=mask is not None, acc_dt=acc_dt,
                          window=window),
        grid=(BH, nk, nq),
        in_specs=[qspec2, kspec2, kspec2,
                  pl.BlockSpec((1, 1, Tp), lambda b, j, i: (b, 0, 0)),
                  qspec2,
                  pl.BlockSpec((1, 1, Tp), lambda b, j, i: (b, 0, 0)),
                  pl.BlockSpec((1, 1, Tp), lambda b, j, i: (b, 0, 0))],
        out_specs=(kspec2, kspec2),
        out_shape=(jax.ShapeDtypeStruct((BH, Tp, D), k.dtype),
                   jax.ShapeDtypeStruct((BH, Tp, D), v.dtype)),
        scratch_shapes=[pltpu.VMEM((bk, D), acc_dt),
                        pltpu.VMEM((bk, D), acc_dt)],
        interpret=_interpret(),
    )(qp, kp, vp, km, dop, L, Di)
    shp = lambda a: a[:, :T].reshape(B, H, T, D)
    dmask = None if mask is None else jnp.zeros_like(mask)
    return shp(dq), shp(dk), shp(dv), dmask


_flash_core.defvjp(_fa_fwd, _fa_bwd)
register_helper("flash_attention", default_on=True)(flash_attention)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7, 8, 9, 10))
def _flash_lse_core(q, k, v, mask, causal, scale, bq, bk, window, bwd,
                    dq_partials):
    (out, lse), _ = _fa_lse_fwd(q, k, v, mask, causal, scale, bq, bk,
                                window)
    return out, lse


def flash_attention_lse(q, k, v, mask=None, causal: bool = False,
                        scale: float | None = None, bq: int = DEFAULT_BQ,
                        bk: int = DEFAULT_BK, window: int = 0,
                        bwd: str | None = None,
                        dq_partials: str | None = None):
    '''Like flash_attention but ALSO returns the per-row logsumexp
    (B, H, T) fp32 - the quantity ring/context-parallel callers need to
    merge partial attention across k/v shards: (out_a, L_a) + (out_b, L_b)
    combine via logaddexp. Differentiable in BOTH outputs.'''
    if bwd is None:
        bwd = _CONFIG["bwd"]
    if dq_partials is None:
        dq_partials = _CONFIG["dq_partials"]
    return _flash_lse_core(q, k, v, mask, causal, scale, bq, bk, window,
                           bwd, dq_partials)


def _fa_lse_fwd_core(q, k, v, mask, causal, scale, bq, bk, window, bwd,
                     dq_partials):
    return _fa_lse_fwd(q, k, v, mask, causal, scale, bq, bk, window)


def _fa_lse_fwd(q, k, v, mask, causal, scale, bq, bk, window=0):
    B, H, T, D = q.shape
    bq, bk = _resolve_blocks(bq, bk, T)
    scale_ = float(scale) if scale is not None else 1.0 / float(np.sqrt(D))
    qp, kp, vp, km, Tp = _prep(q, k, v, mask, bq, bk)
    o, L = _call_fwd(qp, kp, vp, km, causal, scale_, bq, bk, T,
                     mask is not None, window, H, k.shape[1])
    out = o[:, :T].reshape(B, H, T, D)
    lse = L[:, 0, :T].reshape(B, H, T)
    return (out, lse), (q, k, v, mask, o, L)


def _fa_lse_bwd(causal, scale, bq, bk, window, bwd, dq_partials, saved,
                cots):
    dout, dlse = cots
    return _fa_bwd_impl(causal, scale, bq, bk, saved, dout, dlse, window,
                        bwd, dq_partials)


_flash_lse_core.defvjp(_fa_lse_fwd_core, _fa_lse_bwd)


def flash_attention_reference(q, k, v, mask=None, causal=False, scale=None,
                              window=0):
    """Dense oracle with identical mask/window/GQA semantics (tests):
    grouped k/v heads (Hk | H) broadcast to full heads with _kv_row's
    grouping (query head h reads kv head h // (H // Hk))."""
    D = q.shape[-1]
    H, Hk = q.shape[1], k.shape[1]
    if Hk != H:
        k = jnp.repeat(k, H // Hk, axis=1)
        v = jnp.repeat(v, H // Hk, axis=1)
    scale_ = scale if scale is not None else 1.0 / np.sqrt(D)
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k) * scale_
    T = q.shape[2]
    valid = jnp.ones((1, 1, T, T), bool)
    if causal:
        valid = valid & jnp.tril(jnp.ones((T, T), bool))[None, None]
    if window:
        qi = jnp.arange(T)[:, None]
        kj = jnp.arange(T)[None, :]
        w = (qi - kj < window)
        if not causal:
            w = w & (kj - qi < window)
        valid = valid & w[None, None]
    if mask is not None:
        valid = valid & (mask > 0)[:, None, None, :]
    s = jnp.where(valid, s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    p = jnp.where(valid, p, 0.0)  # fully-masked rows -> zero output
    return jnp.einsum("bhqk,bhkv->bhqv", p, v)
