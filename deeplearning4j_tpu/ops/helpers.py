"""Acceleration-helper seam (L2).

Parity: ref nn/layers/LayerHelper + ConvolutionHelper/LSTMHelper/
BatchNormalizationHelper — the reference's pluggable cudnn fast-path interfaces
(e.g. nn/layers/recurrent/LSTMHelper.java). TPU rendering: ops register named
accelerated implementations (Pallas kernels) keyed by op name; call sites dispatch
through `helper_for`, which returns the registered kernel when the seam is enabled
and the platform supports it, else the XLA-fallback the caller supplies. XLA's
default codegen is already excellent — kernels go through this seam only where
hand-tiling beats the compiler, and everything keeps working with the seam off.
"""
from __future__ import annotations

import contextlib
import os
from typing import Callable, Dict, Optional

_REGISTRY: Dict[str, Callable] = {}
_DEFAULT_ON: set = set()
_ENABLED: Optional[bool] = None


def interpret_mode() -> bool:
    """Pallas kernels run interpreted off-TPU so the CPU test mesh exercises
    the same code path (single policy point for every kernel module)."""
    import jax
    return jax.default_backend() != "tpu"


def register_helper(op_name: str, default_on: bool = False):
    """Decorator: register an accelerated implementation for `op_name`.
    `default_on=True` marks kernels that engage automatically on TPU when
    nothing was set explicitly — the reference's 'cuDNN used when supported'
    behavior (ConvolutionLayer.java:72 reflection-load) — reserved for
    kernels with a MEASURED same-session win and exact-parity tests."""
    def deco(fn):
        _REGISTRY[op_name] = fn
        if default_on:
            _DEFAULT_ON.add(op_name)
        return fn
    return deco


def enable_helpers(flag: Optional[bool] = True) -> None:
    """Programmatic switch (env DL4J_TPU_HELPERS=1/0 also works; None resets
    to the default policy: default_on kernels engage on TPU only)."""
    global _ENABLED
    _ENABLED = None if flag is None else bool(flag)


def helpers_override() -> Optional[bool]:
    """The current explicit override, for save/restore around temporary
    enable_helpers() flips (None = default per-op policy active)."""
    return _ENABLED


@contextlib.contextmanager
def helpers_enabled_ctx(flag: Optional[bool]):
    """Scoped enable_helpers: restores the previous override on exit, so a
    temporary flip can never pin the global switch for the rest of the
    process."""
    prev = helpers_override()
    enable_helpers(flag)
    try:
        yield
    finally:
        enable_helpers(prev)


def helpers_enabled() -> bool:
    """The explicit global switch (ignores per-op defaults)."""
    if _ENABLED is not None:
        return _ENABLED
    return os.environ.get("DL4J_TPU_HELPERS", "0") == "1"


def helpers_enabled_for(op_name: str) -> bool:
    """Per-op resolution: explicit switch > env var > per-op TPU default."""
    if _ENABLED is not None:
        return _ENABLED
    env = os.environ.get("DL4J_TPU_HELPERS")
    if env is not None:
        return env == "1"  # same parse as helpers_enabled: only "1" enables
    if op_name in _DEFAULT_ON:
        import jax
        return jax.default_backend() == "tpu"
    return False


def helper_for(op_name: str, fallback: Callable) -> Callable:
    """The seam: accelerated impl if registered+enabled, else the fallback
    (ref LayerHelper selection in BaseLayer.initializeHelper)."""
    engaged = op_name in _REGISTRY and helpers_enabled_for(op_name)
    # seam attribution (ISSUE 6): count which path resolved, at resolve
    # time — under jit that is trace time, never per step. Sanitized: op
    # names are free-form ("conv1x1-bn-relu" would break exposition).
    try:
        from deeplearning4j_tpu import telemetry
        telemetry.registry().counter(
            f"ops.helper.{telemetry.sanitize_component(op_name)}."
            f"{'kernel' if engaged else 'fallback'}",
            "helper-seam resolutions by path (counted at trace time)").inc()
    except Exception:
        pass
    if engaged:
        return _REGISTRY[op_name]
    return fallback


def registered_helpers():
    return dict(_REGISTRY)
