"""Acceleration-helper seam (L2).

Parity: ref nn/layers/LayerHelper + ConvolutionHelper/LSTMHelper/
BatchNormalizationHelper — the reference's pluggable cudnn fast-path interfaces
(e.g. nn/layers/recurrent/LSTMHelper.java). TPU rendering: ops register named
accelerated implementations (Pallas kernels) keyed by op name; call sites dispatch
through `helper_for`, which returns the registered kernel when the seam is enabled
and the platform supports it, else the XLA-fallback the caller supplies. XLA's
default codegen is already excellent — kernels go through this seam only where
hand-tiling beats the compiler, and everything keeps working with the seam off.
"""
from __future__ import annotations

import os
from typing import Callable, Dict, Optional

_REGISTRY: Dict[str, Callable] = {}
_ENABLED: Optional[bool] = None


def register_helper(op_name: str):
    """Decorator: register an accelerated implementation for `op_name`."""
    def deco(fn):
        _REGISTRY[op_name] = fn
        return fn
    return deco


def enable_helpers(flag: bool = True) -> None:
    """Programmatic switch (env DL4J_TPU_HELPERS=1 also enables)."""
    global _ENABLED
    _ENABLED = bool(flag)


def helpers_enabled() -> bool:
    if _ENABLED is not None:
        return _ENABLED
    return os.environ.get("DL4J_TPU_HELPERS", "0") == "1"


def helper_for(op_name: str, fallback: Callable) -> Callable:
    """The seam: accelerated impl if registered+enabled, else the fallback
    (ref LayerHelper selection in BaseLayer.initializeHelper)."""
    if helpers_enabled() and op_name in _REGISTRY:
        return _REGISTRY[op_name]
    return fallback


def registered_helpers():
    return dict(_REGISTRY)
