"""Split-K flash-decode kernel: single-query cached attention for serving.

Beyond-reference (Flash-Decoding, Dao et al. 2023; SURVEY §5 serving). The
serving engine's decode step attends ONE query per slot against that slot's
KV-cache prefix (serving/kv_cache.py). The dense path
(`decode_attention_dense`, the fp64 oracle and universal fallback) builds the
full (S, H, L) score tensor and softmaxes over the whole max_len axis no
matter how short the actual sequences are. At decode there is no query-axis
parallelism to tile over (q is a single position), so the flash trick that
matters is SPLIT-K: partition the cache LENGTH axis into nk chunks of bkv
positions, compute each partition's softmax-weighted partial sum and row
logsumexp independently (one grid cell per (slot, kv-head, partition)), and
merge the partials outside the kernel with the SAME logaddexp algebra that
ring attention and `flash_attention_lse` use:

    out = sum_p exp(L_p - L_tot) * o_p,   L_tot = logsumexp_p L_p.

Partitions entirely beyond a slot's visible length — or entirely behind its
sliding window — are skipped inside the kernel (zero output block, L_p =
NEG_INF, which the merge weighs to zero), so per-slot cost follows the
slot's TRUE length, not max_len: a freshly admitted request in a mostly
empty cache does bkv worth of score math, not max_len worth.

GQA-aware without materializing the head repeat: q arrives reshaped
(S, Hk, G, D) and each grid cell contracts its (G, D) query group against
the (bkv, D) k/v tile of its kv head — the same grouping as
ops/flash_attention._kv_row and serving/decode.decode_attention. Score and
softmax math run in fp32 (fp64 under x64); k/v stream in the cache dtype
(bf16 on TPU).

Registered as helper "decode_attention" (default-on for TPU);
serving/decode.py dispatches here through the helper seam with the dense
path as oracle and fallback. Falls back to dense automatically when the
cache length cannot be partitioned (L not divisible down to a >= 8 block).
Inference-only: no custom VJP (the dense fallback is differentiable if
anyone ever needs gradients through decode).

PAGED variant (ISSUE 7): the serving cache is now block-paged
(serving/kv_cache.py) — k/v live as (num_blocks + 1, block_size, Hk, D)
physical blocks and each slot maps logical blocks through a
(max_seqs, blocks_per_seq) int32 block table. The split-K partition
structure aligns PERFECTLY with paging: one length partition = one
physical block, so `flash_decode_attention_paged` keeps the gather
INSIDE the kernel by feeding the block table through
`pltpu.PrefetchScalarGridSpec` (scalar-prefetch operand) and letting each
grid cell's k/v index_map resolve (slot, logical block j) ->
`bt_ref[s, j]` — no (S, L, Hk, D) contiguous copy of the cache is ever
materialized. The kernel body is the SAME `_decode_kernel` (same math,
same skip logic, bkv = block_size); `decode_attention_dense_paged`
extends the fp64 oracle to resolve block tables (gather + reshape, then
the unchanged dense math) so the parity harness covers the paged path
end to end. Falls back to the dense-paged path when block_size < 8.

Chunked prefill (ISSUE 9) adds no kernel variant: a prefill chunk
attends its predecessor blocks through the SAME block-table gather
semantics the shared-prefix suffix pass uses (serving/decode.py
`_prefill_shared_fn`), and decode iterations interleaved between chunks
hit this kernel unchanged — a partially-prefilled slot is invisible to
it because its `lengths` entry only covers completed chunks.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.ops.helpers import register_helper

NEG_INF = -1e30

# 0 = auto: 256-position partitions (A/B-able; at serving shapes the kernel
# is HBM-bound on the k/v stream, so the block size mostly sets how much
# work the visible-length skip can drop).
DEFAULT_BKV = 0


def _interpret() -> bool:
    from deeplearning4j_tpu.ops.helpers import interpret_mode
    return interpret_mode()


def decode_attention_dense(q, kc, vc, visible, scale, window: int = 0):
    """Dense single-query attention against the cache — the fp64 oracle and
    universal fallback (bit-identical to the pre-split-K serving decode).

    q: (S, H, D) current-position queries; kc/vc: (S, L, Hk, D) cache
    (current position already appended); visible: (S,) number of visible
    positions per slot (= position index + 1); `window` > 0 applies sliding-
    window semantics (query at position visible-1 sees keys j with
    (visible-1) - j < window). Returns (S, H, D) in q.dtype."""
    S, H, D = q.shape
    L, Hk = kc.shape[1], kc.shape[2]
    if H % Hk != 0:
        raise ValueError(f"n_heads {H} % n_kv_heads {Hk} != 0")
    G = H // Hk
    acc = jnp.promote_types(q.dtype, jnp.float32)
    q4 = q.reshape(S, Hk, G, D)
    s = jnp.einsum("shgd,slhd->shgl", q4.astype(acc), kc.astype(acc)) * scale
    j = jnp.arange(L)[None, :]                       # (1, L)
    valid = j < visible[:, None]                     # (S, L)
    if window:
        valid = valid & (visible[:, None] - 1 - j < window)
    s = jnp.where(valid[:, None, None, :], s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    p = jnp.where(valid[:, None, None, :], p, 0.0)   # fully-masked rows -> 0
    out = jnp.einsum("shgl,slhd->shgd", p, vc.astype(acc))
    return out.reshape(S, H, D).astype(q.dtype)


def _decode_kernel(q_ref, k_ref, v_ref, m_ref, vis_ref, o_ref, l_ref, *,
                   bkv, window, scale, acc_dt, ks_ref=None, vs_ref=None):
    """One grid cell = (slot, kv head, length partition): partial
    softmax-weighted sum o_p (G, D) and row logsumexp L_p (G,) over this
    partition's bkv cache positions. Partitions with no visible position
    (fully beyond the slot's length, or fully behind its sliding window)
    skip the score math and emit (0, NEG_INF) — the merge weighs them to
    zero.

    Quantized pool (ISSUE 15): ks_ref/vs_ref, when given, are this cell's
    per-head-per-block scales ((1, 1) SMEM tiles, routed through the same
    block-table index_map as the k/v tiles), and the k/v streams are int8.
    Dequantization is ONE scalar broadcast multiply per tile, applied to
    the (bkv, D) tile right after the dtype widen — structurally the same
    `payload * scale` the dense oracle applies per gathered block, so
    kernel-vs-oracle parity carries over to the int8 path unchanged. The
    pool bytes crossing HBM stay int8; nothing dequantized ever persists
    beyond this cell's registers."""
    from jax.experimental import pallas as pl
    j = pl.program_id(2)
    vis = vis_ref[0, 0]                              # slot's visible length
    lo = j * bkv
    run = lo < vis                                   # any position visible?
    if window:
        run = run & (lo + bkv > vis - window)        # any inside the window?

    @pl.when(run)
    def _():
        q = q_ref[0, 0].astype(acc_dt)               # (G, D)
        k = k_ref[0, :, 0, :].astype(acc_dt)         # (bkv, D)
        if ks_ref is not None:
            k = k * ks_ref[0, 0].astype(acc_dt)
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=acc_dt) * scale
        valid = m_ref[0, :] > 0                      # (bkv,) per-position
        s = jnp.where(valid[None, :], s, NEG_INF)
        m = jnp.max(s, axis=1)                       # (G,)
        p = jnp.exp(s - m[:, None])
        p = jnp.where(valid[None, :], p, 0.0)
        l = jnp.sum(p, axis=1)                       # (G,)
        v = v_ref[0, :, 0, :].astype(acc_dt)         # (bkv, D)
        if vs_ref is not None:
            v = v * vs_ref[0, 0].astype(acc_dt)
        o = jax.lax.dot_general(p, v,
                                (((1,), (0,)), ((), ())),
                                preferred_element_type=acc_dt)
        o_ref[0, 0, 0] = (o / jnp.maximum(l, 1e-30)[:, None]).astype(
            o_ref.dtype)
        l_ref[0, 0, 0] = jnp.where(
            l > 0, m + jnp.log(jnp.maximum(l, 1e-30)), NEG_INF)

    @pl.when(jnp.logical_not(run))
    def _():
        o_ref[0, 0, 0] = jnp.zeros_like(o_ref[0, 0, 0])
        l_ref[0, 0, 0] = jnp.full_like(l_ref[0, 0, 0], NEG_INF)


def _resolve_bkv(bkv: int, L: int) -> int:
    """Largest feasible partition size <= the request that divides L (the
    cache is never copied/padded — partitions must tile max_len exactly)."""
    if not bkv:
        bkv = 256
    bkv = min(bkv, L)
    while bkv > 1 and L % bkv:
        bkv //= 2
    return bkv


def flash_decode_attention(q, kc, vc, visible, scale, window: int = 0,
                           bkv: int = DEFAULT_BKV):
    """Split-K flash-decode: same contract as `decode_attention_dense`
    (q (S, H, D), kc/vc (S, L, Hk, D), visible (S,)), computed as nk
    independent length partitions merged via logaddexp. Falls back to the
    dense path when L cannot be split into >= 8-position partitions."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu
    S, H, D = q.shape
    L, Hk = kc.shape[1], kc.shape[2]
    if H % Hk != 0:
        raise ValueError(f"n_heads {H} % n_kv_heads {Hk} != 0")
    bkv = _resolve_bkv(bkv, L)
    if bkv < 8 or L % bkv:
        return decode_attention_dense(q, kc, vc, visible, scale, window)
    nk = L // bkv
    G = H // Hk
    acc_dt = jnp.promote_types(q.dtype, jnp.float32)
    q4 = q.reshape(S, Hk, G, D)
    visible = jnp.asarray(visible, jnp.int32)
    # per-position visibility (the same mask algebra as the dense path);
    # the kernel reads one (bkv,) stripe per grid cell
    j = jnp.arange(L)[None, :]
    valid = j < visible[:, None]
    if window:
        valid = valid & (visible[:, None] - 1 - j < window)
    valid = valid.astype(jnp.int32)                  # (S, L)
    vis2 = visible[:, None]                          # (S, 1) SMEM scalar feed

    kern = functools.partial(_decode_kernel, bkv=bkv, window=window,
                             scale=float(scale), acc_dt=acc_dt)
    o_p, l_p = pl.pallas_call(
        kern,
        grid=(S, Hk, nk),
        in_specs=[
            pl.BlockSpec((1, 1, G, D), lambda s, h, j: (s, h, 0, 0)),
            pl.BlockSpec((1, bkv, 1, D), lambda s, h, j: (s, j, h, 0)),
            pl.BlockSpec((1, bkv, 1, D), lambda s, h, j: (s, j, h, 0)),
            pl.BlockSpec((1, bkv), lambda s, h, j: (s, j)),
            pl.BlockSpec((1, 1), lambda s, h, j: (s, 0),
                         memory_space=pltpu.SMEM),
        ],
        out_specs=(
            pl.BlockSpec((1, 1, 1, G, D), lambda s, h, j: (s, h, j, 0, 0)),
            pl.BlockSpec((1, 1, 1, G), lambda s, h, j: (s, h, j, 0)),
        ),
        out_shape=(
            jax.ShapeDtypeStruct((S, Hk, nk, G, D), acc_dt),
            jax.ShapeDtypeStruct((S, Hk, nk, G), acc_dt),
        ),
        interpret=_interpret(),
    )(q4, kc, vc, valid, vis2)

    # logaddexp merge across partitions (the flash_attention_lse algebra):
    # out = sum_p exp(L_p - L_tot) * o_p. Skipped partitions carry
    # L_p = NEG_INF -> weight 0; a fully-masked row (cannot happen for
    # visible >= 1, but kept safe) gets denom >= 1 and o_p = 0 -> output 0,
    # matching the dense path's zeroed fully-masked rows.
    m = jnp.max(l_p, axis=2, keepdims=True)          # (S, Hk, 1, G)
    w = jnp.exp(l_p - jnp.maximum(m, NEG_INF))       # (S, Hk, nk, G)
    denom = jnp.maximum(jnp.sum(w, axis=2), 1e-30)   # (S, Hk, G)
    out = jnp.einsum("shkg,shkgd->shgd", w, o_p) / denom[..., None]
    return out.reshape(S, H, D).astype(q.dtype)


register_helper("decode_attention", default_on=True)(flash_decode_attention)


# --------------------------------------------------------------- paged path
def decode_attention_dense_paged(q, kp, vp, block_tables, visible, scale,
                                 window: int = 0, k_scale=None,
                                 v_scale=None):
    """Dense paged oracle: gather each slot's cache through its block table
    into the (S, L, Hk, D) layout, then run the UNCHANGED dense math — so
    paged parity reduces to the already-trusted oracle. q: (S, H, D);
    kp/vp: (num_blocks + 1, block_size, Hk, D) physical blocks (last block
    is the trash block); block_tables: (S, blocks_per_seq) int32.

    Quantized pool: k_scale/v_scale (num_blocks + 1, Hk) dequantize each
    GATHERED block (`payload * scale[block, head]`) before the dense math
    — the quantize -> dequantize reference the int8 kernel is tested
    against. Only per-slot views are ever dequantized, never the pool."""
    S = q.shape[0]
    bs, Hk, D = kp.shape[1], kp.shape[2], kp.shape[3]
    bps = block_tables.shape[1]
    acc = jnp.promote_types(q.dtype, jnp.float32)
    if k_scale is not None:
        ks = k_scale[block_tables]                   # (S, bps, Hk)
        vs = v_scale[block_tables]
        kc = (kp[block_tables].astype(acc)
              * ks[:, :, None, :, None].astype(acc))
        vc = (vp[block_tables].astype(acc)
              * vs[:, :, None, :, None].astype(acc))
        kc = kc.reshape(S, bps * bs, Hk, D)
        vc = vc.reshape(S, bps * bs, Hk, D)
    else:
        kc = kp[block_tables].reshape(S, bps * bs, Hk, D)
        vc = vp[block_tables].reshape(S, bps * bs, Hk, D)
    return decode_attention_dense(q, kc, vc, visible, scale, window)


def flash_decode_attention_paged(q, kp, vp, block_tables, visible, scale,
                                 window: int = 0, k_scale=None,
                                 v_scale=None):
    """Block-table-aware split-K flash-decode: same contract as
    `decode_attention_dense_paged`, computed with one grid cell per
    (slot, kv head, LOGICAL block) and the logical -> physical lookup done
    by the k/v index_maps through the scalar-prefetched block table. A
    partition IS a physical block (bkv = block_size — physical blocks are
    not contiguous in HBM, so larger partitions cannot be one tile); the
    kernel body and the logaddexp merge are shared with the slot-path
    kernel. Falls back to the dense paged path when block_size < 8 (tile
    too small for the TPU layout) — fallback and kernel are value-identical
    either way.

    Quantized pool (ISSUE 15): pass k_scale/v_scale (num_blocks + 1, Hk)
    with int8 kp/vp. The scales ride as two extra (1, 1) SMEM operands
    whose index_map is the SAME block-table lookup as the k/v tiles — each
    grid cell receives exactly its block's per-head scale and dequantizes
    its own int8 tile in-register (`_decode_kernel`). The pool streams at
    the int8 byte count and is never materialized dequantized anywhere."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu
    S, H, D = q.shape
    bs, Hk = kp.shape[1], kp.shape[2]
    bps = block_tables.shape[1]
    quantized = k_scale is not None
    if H % Hk != 0:
        raise ValueError(f"n_heads {H} % n_kv_heads {Hk} != 0")
    if bs < 8:
        return decode_attention_dense_paged(q, kp, vp, block_tables,
                                            visible, scale, window,
                                            k_scale=k_scale,
                                            v_scale=v_scale)
    G = H // Hk
    L = bps * bs
    acc_dt = jnp.promote_types(q.dtype, jnp.float32)
    q4 = q.reshape(S, Hk, G, D)
    visible = jnp.asarray(visible, jnp.int32)
    # per-position visibility over the LOGICAL length axis (identical mask
    # algebra to the slot path — the kernel reads one (bs,) stripe per cell)
    j = jnp.arange(L)[None, :]
    valid = j < visible[:, None]
    if window:
        valid = valid & (visible[:, None] - 1 - j < window)
    valid = valid.astype(jnp.int32)                  # (S, L)
    vis2 = visible[:, None]                          # (S, 1) SMEM scalar feed

    def kern(bt_ref, *refs):
        # the scalar-prefetch operand arrives as the leading kernel ref; the
        # body only needs it in the index_maps — drop it and run the SAME
        # math as the slot-path kernel (with this cell's block scales when
        # the pool is quantized)
        if quantized:
            (q_ref, k_ref, v_ref, ks_ref, vs_ref, m_ref, vis_ref,
             o_ref, l_ref) = refs
            _decode_kernel(q_ref, k_ref, v_ref, m_ref, vis_ref, o_ref,
                           l_ref, bkv=bs, window=window,
                           scale=float(scale), acc_dt=acc_dt,
                           ks_ref=ks_ref, vs_ref=vs_ref)
        else:
            _decode_kernel(*refs, bkv=bs, window=window,
                           scale=float(scale), acc_dt=acc_dt)
    # PrefetchScalarGridSpec: block_tables rides as the scalar-prefetch
    # operand and every index_map takes it as a trailing ref — the k/v maps
    # do the paging gather (logical block j of slot s lives at physical
    # block bt_ref[s, j]); q/mask/visible index on logical coordinates.
    # The scale operands (quantized pool) use the same physical lookup so
    # each cell's SMEM scalar is its own block's per-head scale.
    scale_specs = [
        pl.BlockSpec((1, 1), lambda s, h, j, bt_ref: (bt_ref[s, j], h),
                     memory_space=pltpu.SMEM),
        pl.BlockSpec((1, 1), lambda s, h, j, bt_ref: (bt_ref[s, j], h),
                     memory_space=pltpu.SMEM),
    ] if quantized else []
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(S, Hk, bps),
        in_specs=[
            pl.BlockSpec((1, 1, G, D),
                         lambda s, h, j, bt_ref: (s, h, 0, 0)),
            pl.BlockSpec((1, bs, 1, D),
                         lambda s, h, j, bt_ref: (bt_ref[s, j], 0, h, 0)),
            pl.BlockSpec((1, bs, 1, D),
                         lambda s, h, j, bt_ref: (bt_ref[s, j], 0, h, 0)),
            *scale_specs,
            pl.BlockSpec((1, bs), lambda s, h, j, bt_ref: (s, j)),
            pl.BlockSpec((1, 1), lambda s, h, j, bt_ref: (s, 0),
                         memory_space=pltpu.SMEM),
        ],
        out_specs=(
            pl.BlockSpec((1, 1, 1, G, D),
                         lambda s, h, j, bt_ref: (s, h, j, 0, 0)),
            pl.BlockSpec((1, 1, 1, G),
                         lambda s, h, j, bt_ref: (s, h, j, 0)),
        ),
    )
    scale_ops = (k_scale, v_scale) if quantized else ()
    o_p, l_p = pl.pallas_call(
        kern,
        grid_spec=grid_spec,
        out_shape=(
            jax.ShapeDtypeStruct((S, Hk, bps, G, D), acc_dt),
            jax.ShapeDtypeStruct((S, Hk, bps, G), acc_dt),
        ),
        interpret=_interpret(),
    )(jnp.asarray(block_tables, jnp.int32), q4, kp, vp, *scale_ops,
      valid, vis2)

    # same logaddexp merge as the slot path (see above)
    m = jnp.max(l_p, axis=2, keepdims=True)          # (S, Hk, 1, G)
    w = jnp.exp(l_p - jnp.maximum(m, NEG_INF))       # (S, Hk, bps, G)
    denom = jnp.maximum(jnp.sum(w, axis=2), 1e-30)   # (S, Hk, G)
    out = jnp.einsum("shkg,shkgd->shgd", w, o_p) / denom[..., None]
    return out.reshape(S, H, D).astype(q.dtype)


register_helper("decode_attention_paged",
                default_on=True)(flash_decode_attention_paged)


# ------------------------------------------------- speculative (multi-query)
def decode_attention_dense_spec_paged(q, kp, vp, block_tables, visible,
                                      scale, window: int = 0,
                                      k_scale=None, v_scale=None):
    """Dense paged oracle for SPECULATIVE verification (ISSUE 11): score Q
    consecutive query positions per slot in one call. q: (S, Q, H, D) where
    query i of slot s sits at logical position visible[s] - 1 + i (query 0
    is the ordinary next-token query; queries 1..Q-1 are draft tokens whose
    KV was provisionally appended). Query i therefore sees j < visible + i.

    Implemented as Q calls of the single-query dense paged oracle — the
    per-position math (shapes, einsum order, masking) is IDENTICAL to the
    plain decode path, so a spec step's row i is bit-identical to what the
    sequential decode step would have computed at that position given the
    same cache. That is what makes this both the fp64 oracle AND the
    bit-identical fallback for the multi-query kernel. A quantized pool
    threads k_scale/v_scale straight into the single-query oracle — the
    same quantize -> dequantize reference per gathered block."""
    S, Q = q.shape[0], q.shape[1]
    visible = jnp.asarray(visible, jnp.int32)
    outs = [decode_attention_dense_paged(q[:, i], kp, vp, block_tables,
                                         visible + i, scale, window,
                                         k_scale=k_scale, v_scale=v_scale)
            for i in range(Q)]
    return jnp.stack(outs, axis=1)                   # (S, Q, H, D)


def _spec_decode_kernel(q_ref, k_ref, v_ref, m_ref, vis_ref, o_ref, l_ref, *,
                        nq, bkv, window, scale, acc_dt, ks_ref=None,
                        vs_ref=None):
    """Multi-query generalization of `_decode_kernel`: one grid cell =
    (slot, kv head, length partition), scoring all Q query positions of the
    slot against this partition's bkv cache positions. The FlashAttention-2
    online-softmax algebra is unchanged — the query tile just grows from
    (G, D) to (Q*G, D), with the per-QUERY visibility mask (query i sees
    j < vis + i) applied per (query, position) from the precomputed
    (S, Q, L) mask stripe. Partitions no query can see emit (0, NEG_INF)."""
    from jax.experimental import pallas as pl
    j = pl.program_id(2)
    vis = vis_ref[0, 0]                              # query 0's visible length
    lo = j * bkv
    run = lo < vis + nq - 1                          # any query sees any pos?
    if window:
        run = run & (lo + bkv > vis - window)        # union over queries

    @pl.when(run)
    def _():
        nG, D = q_ref.shape[3], q_ref.shape[4]
        q = q_ref[0, 0].reshape(nq * nG, D).astype(acc_dt)
        k = k_ref[0, :, 0, :].astype(acc_dt)         # (bkv, D)
        if ks_ref is not None:                       # int8 tile dequant
            k = k * ks_ref[0, 0].astype(acc_dt)
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=acc_dt) * scale
        s = s.reshape(nq, nG, bkv)
        valid = m_ref[0, :, :] > 0                   # (Q, bkv)
        s = jnp.where(valid[:, None, :], s, NEG_INF)
        m = jnp.max(s, axis=2)                       # (Q, G)
        p = jnp.exp(s - m[:, :, None])
        p = jnp.where(valid[:, None, :], p, 0.0)
        l = jnp.sum(p, axis=2)                       # (Q, G)
        v = v_ref[0, :, 0, :].astype(acc_dt)         # (bkv, D)
        if vs_ref is not None:
            v = v * vs_ref[0, 0].astype(acc_dt)
        o = jax.lax.dot_general(p.reshape(nq * nG, bkv), v,
                                (((1,), (0,)), ((), ())),
                                preferred_element_type=acc_dt)
        o = o.reshape(nq, nG, D)
        o_ref[0, 0, 0] = (o / jnp.maximum(l, 1e-30)[:, :, None]).astype(
            o_ref.dtype)
        l_ref[0, 0, 0] = jnp.where(
            l > 0, m + jnp.log(jnp.maximum(l, 1e-30)), NEG_INF)

    @pl.when(jnp.logical_not(run))
    def _():
        o_ref[0, 0, 0] = jnp.zeros_like(o_ref[0, 0, 0])
        l_ref[0, 0, 0] = jnp.full_like(l_ref[0, 0, 0], NEG_INF)


def flash_decode_attention_spec_paged(q, kp, vp, block_tables, visible,
                                      scale, window: int = 0,
                                      k_scale=None, v_scale=None):
    """Block-table-aware split-K flash-decode over Q query positions per
    slot (speculative verification): same contract as
    `decode_attention_dense_spec_paged`, same grid as the single-query paged
    kernel — one cell per (slot, kv head, logical block), block table
    scalar-prefetched into the k/v index_maps — with the query tile widened
    to (Q, G, D) so all draft positions are scored in ONE dispatch at
    unchanged k/v bytes moved (the whole point: decode is HBM-bound on the
    cache stream, so Q-for-1 amortizes the stream). Falls back to the dense
    spec oracle when block_size < 8 — value-identical either way.

    Quantized pool: identical scale plumbing to the single-query paged
    kernel — two extra (1, 1) SMEM operands resolved through the block
    table, tile dequant inside `_spec_decode_kernel`. Quantization
    compounds with the Q-for-1 amortization: the int8 stream is the same
    bytes whether one or Q queries consume it."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu
    S, Q, H, D = q.shape
    bs, Hk = kp.shape[1], kp.shape[2]
    bps = block_tables.shape[1]
    quantized = k_scale is not None
    if H % Hk != 0:
        raise ValueError(f"n_heads {H} % n_kv_heads {Hk} != 0")
    if bs < 8:
        return decode_attention_dense_spec_paged(q, kp, vp, block_tables,
                                                 visible, scale, window,
                                                 k_scale=k_scale,
                                                 v_scale=v_scale)
    G = H // Hk
    L = bps * bs
    acc_dt = jnp.promote_types(q.dtype, jnp.float32)
    q5 = q.reshape(S, Q, Hk, G, D).transpose(0, 2, 1, 3, 4)  # (S,Hk,Q,G,D)
    visible = jnp.asarray(visible, jnp.int32)
    # per-(query, position) visibility over the logical length axis: query i
    # sits at position visible - 1 + i, so it sees j < visible + i and (with
    # a sliding window) j within window of its own position
    j = jnp.arange(L)[None, None, :]                 # (1, 1, L)
    i = jnp.arange(Q)[None, :, None]                 # (1, Q, 1)
    vis3 = visible[:, None, None]                    # (S, 1, 1)
    valid = j < vis3 + i
    if window:
        valid = valid & (vis3 + i - 1 - j < window)
    valid = valid.astype(jnp.int32)                  # (S, Q, L)
    vis2 = visible[:, None]                          # (S, 1) SMEM scalar feed

    def kern(bt_ref, *refs):
        if quantized:
            (q_ref, k_ref, v_ref, ks_ref, vs_ref, m_ref, vis_ref,
             o_ref, l_ref) = refs
            _spec_decode_kernel(q_ref, k_ref, v_ref, m_ref, vis_ref,
                                o_ref, l_ref, nq=Q, bkv=bs, window=window,
                                scale=float(scale), acc_dt=acc_dt,
                                ks_ref=ks_ref, vs_ref=vs_ref)
        else:
            _spec_decode_kernel(*refs, nq=Q, bkv=bs, window=window,
                                scale=float(scale), acc_dt=acc_dt)
    scale_specs = [
        pl.BlockSpec((1, 1), lambda s, h, j, bt_ref: (bt_ref[s, j], h),
                     memory_space=pltpu.SMEM),
        pl.BlockSpec((1, 1), lambda s, h, j, bt_ref: (bt_ref[s, j], h),
                     memory_space=pltpu.SMEM),
    ] if quantized else []
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(S, Hk, bps),
        in_specs=[
            pl.BlockSpec((1, 1, Q, G, D),
                         lambda s, h, j, bt_ref: (s, h, 0, 0, 0)),
            pl.BlockSpec((1, bs, 1, D),
                         lambda s, h, j, bt_ref: (bt_ref[s, j], 0, h, 0)),
            pl.BlockSpec((1, bs, 1, D),
                         lambda s, h, j, bt_ref: (bt_ref[s, j], 0, h, 0)),
            *scale_specs,
            pl.BlockSpec((1, Q, bs), lambda s, h, j, bt_ref: (s, 0, j)),
            pl.BlockSpec((1, 1), lambda s, h, j, bt_ref: (s, 0),
                         memory_space=pltpu.SMEM),
        ],
        out_specs=(
            pl.BlockSpec((1, 1, 1, Q, G, D),
                         lambda s, h, j, bt_ref: (s, h, j, 0, 0, 0)),
            pl.BlockSpec((1, 1, 1, Q, G),
                         lambda s, h, j, bt_ref: (s, h, j, 0, 0)),
        ),
    )
    scale_ops = (k_scale, v_scale) if quantized else ()
    o_p, l_p = pl.pallas_call(
        kern,
        grid_spec=grid_spec,
        out_shape=(
            jax.ShapeDtypeStruct((S, Hk, bps, Q, G, D), acc_dt),
            jax.ShapeDtypeStruct((S, Hk, bps, Q, G), acc_dt),
        ),
        interpret=_interpret(),
    )(jnp.asarray(block_tables, jnp.int32), q5, kp, vp, *scale_ops,
      valid, vis2)

    # same logaddexp merge, with the extra Q axis riding along
    m = jnp.max(l_p, axis=2, keepdims=True)          # (S, Hk, 1, Q, G)
    w = jnp.exp(l_p - jnp.maximum(m, NEG_INF))       # (S, Hk, bps, Q, G)
    denom = jnp.maximum(jnp.sum(w, axis=2), 1e-30)   # (S, Hk, Q, G)
    out = jnp.einsum("shkqg,shkqgd->shqgd", w, o_p) / denom[..., None]
    out = out.transpose(0, 2, 1, 3, 4).reshape(S, Q, H, D)
    return out.astype(q.dtype)


register_helper("decode_attention_spec_paged",
                default_on=True)(flash_decode_attention_spec_paged)


def paged_spec_decode_specs(tensor_axis: str = "tensor",
                            quantized: bool = False):
    """shard_map partition specs for the SPECULATIVE paged attention call:
    `(in_specs, out_specs)` for `(q, kp, vp, block_tables, visible)` -> out
    with q/out shaped (S, Q, H, D). Identical head-locality argument to
    `paged_decode_specs` — the Q axis is per-slot and replicates with S, so
    the multi-query kernel stays collective-free under TP: every softmax
    reduction runs over L within one head shard. With `quantized`, two
    trailing (num_blocks + 1, Hk) scale operands shard with their heads."""
    from jax.sharding import PartitionSpec as P
    heads_q = P(None, None, tensor_axis, None)      # q/out: (S, Q, H, D)
    heads_kv = P(None, None, tensor_axis, None)     # kp/vp: (nb+1, bs, Hk, D)
    in_specs = (heads_q, heads_kv, heads_kv, P(None, None), P(None))
    if quantized:
        scales = P(None, tensor_axis)               # (nb+1, Hk)
        in_specs = in_specs + (scales, scales)
    return in_specs, heads_q


def paged_decode_specs(tensor_axis: str = "tensor",
                       quantized: bool = False):
    """shard_map partition specs for the paged decode attention call
    (ISSUE 10): `(in_specs, out_specs)` for the array operands
    `(q, kp, vp, block_tables, visible)` -> out, sharding the HEAD axes
    over `tensor_axis` — q/out over H (axis 1), the physical k/v pools
    over Hk (axis 2), block tables and visible lengths replicated.

    Head-local attention is what makes the kernel TP-viable unchanged:
    with whole (grouped) heads per shard, every softmax/score/value
    reduction runs over the L axis WITHIN one shard, so the shard_map body
    needs NO collective — the Pallas split-K kernel (or the dense paged
    fallback) executes per shard exactly as on one chip. The only
    cross-shard communication in a TP decode step is outside this call,
    in the row-parallel output projection (see PERF.md's cost model).
    Contiguous head splits preserve GQA grouping (head h reads kv head
    h // G) whenever the TP degree divides n_kv_heads.

    With `quantized`, two trailing scale operands (num_blocks + 1, Hk)
    shard over their HEAD axis (axis 1) — a scale lives and dies with the
    kv head it rescales, so TP sharding splits payload and scale along
    the same boundary and the kernel stays collective-free."""
    from jax.sharding import PartitionSpec as P
    heads_q = P(None, tensor_axis, None)            # q/out: (S, H, D)
    heads_kv = P(None, None, tensor_axis, None)     # kp/vp: (nb+1, bs, Hk, D)
    in_specs = (heads_q, heads_kv, heads_kv, P(None, None), P(None))
    if quantized:
        scales = P(None, tensor_axis)               # (nb+1, Hk)
        in_specs = in_specs + (scales, scales)
    return in_specs, heads_q
