"""Fused pointwise-conv + BatchNorm + ReLU (the cuDNN-analog conv kernel).

Parity target: ref deeplearning4j-cuda/.../CudnnConvolutionHelper.java:46 —
the reference's fused conv algos behind the ConvolutionHelper seam. TPU
rendering: ResNet50's bottleneck blocks are 2/3 pointwise (1x1) convolutions,
each followed by BatchNormalization (+ ReLU). XLA compiles that pattern as
  conv(x) -> y ; reduce(y) twice (batch stats) ; elementwise(y) -> out
which reads the conv output y from HBM twice (stats pass + normalize pass).
This module's Pallas kernel computes the matmul AND the per-channel partial
sums (sum y, sum y^2) in one VMEM-resident pass — y is read from HBM once —
then a single XLA elementwise pass normalizes (+ReLU). On an HBM-bound model
(see PERF.md roofline) removing one full activation read per conv+BN pair is
the mechanism by which a hand kernel can beat the compiler at all.

The op is training-complete: a custom VJP implements the analytic
conv1x1+BN(+ReLU) backward with plain XLA matmuls (those are already
MXU-optimal; only the forward's traffic pattern needed hand-scheduling).

Layout: NCHW activations (framework standard), W (C_out, C_in) — the 1x1
kernel's (O, I, 1, 1) squeezed. Spatial stride-2 subsampling happens before
the kernel (a strided slice; the model's stride-2 1x1 convs drop those rows
anyway).
"""
from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.ops.helpers import register_helper


def _interpret() -> bool:
    from deeplearning4j_tpu.ops.helpers import interpret_mode
    return interpret_mode()


def _round_up(n, m):
    return (n + m - 1) // m * m


def _conv1x1_stats_kernel(x_ref, w_ref, y_ref, s1_ref, s2_ref):
    """One (batch b, spatial tile p) grid step: y tile = W @ x tile on the
    MXU, plus per-channel partial sums accumulated across the whole grid
    (the s1/s2 out blocks map to (0, 0) for every step, so they stay
    VMEM-resident and accumulate). Stats accumulate in fp32 regardless of
    activation dtype."""
    from jax.experimental import pallas as pl
    x = x_ref[0]                             # (C_in, P_t)
    w = w_ref[:]                             # (C_out, C_in)
    acc = s1_ref.dtype  # fp32 for <=fp32 activations, fp64 under x64 tests
    y = jnp.dot(w, x, preferred_element_type=acc)  # (C_out, P_t)
    y_ref[0] = y.astype(y_ref.dtype)
    first = (pl.program_id(0) == 0) & (pl.program_id(1) == 0)

    @pl.when(first)
    def _():
        s1_ref[:] = jnp.zeros_like(s1_ref)
        s2_ref[:] = jnp.zeros_like(s2_ref)

    s1_ref[:] += jnp.sum(y, axis=1, keepdims=True)
    s2_ref[:] += jnp.sum(y * y, axis=1, keepdims=True)


def conv1x1_stats_pallas(x3: jnp.ndarray, w: jnp.ndarray,
                         p_tile: int = 1024) -> Tuple[jnp.ndarray, jnp.ndarray,
                                                     jnp.ndarray]:
    """x3 (B, C_in, P), w (C_out, C_in) -> (y (B, C_out, P) in x3.dtype,
    sum_y (C_out,) fp32, sum_y2 (C_out,) fp32). One HBM read of x, one HBM
    write of y, stats for free in the epilogue."""
    from jax.experimental import pallas as pl
    B, C_in, P = x3.shape
    C_out = w.shape[0]
    # stats accumulator: one width ABOVE the activation dtype where possible
    # (sub-fp32 -> fp32; fp32 -> fp64) so the one-pass E[y^2]-E[y]^2 formula
    # cannot cancel catastrophically (the normalization.py / ADVICE r3 low#1
    # rule). fp64 activations stay fp64 (no wider type exists; fp64 is a
    # test-only dtype for this opt-in perf path).
    acc = jnp.float32 if jnp.dtype(x3.dtype).itemsize < 4 else jnp.float64
    p_tile = min(p_tile, _round_up(P, 128))
    Pp = _round_up(P, p_tile)
    if Pp != P:
        x3 = jnp.pad(x3, ((0, 0), (0, 0), (0, Pp - P)))
    grid = (B, Pp // p_tile)
    y, s1, s2 = pl.pallas_call(
        _conv1x1_stats_kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, C_in, p_tile), lambda b, p: (b, 0, p)),
            pl.BlockSpec((C_out, C_in), lambda b, p: (0, 0)),
        ],
        out_specs=(
            pl.BlockSpec((1, C_out, p_tile), lambda b, p: (b, 0, p)),
            pl.BlockSpec((C_out, 1), lambda b, p: (0, 0)),
            pl.BlockSpec((C_out, 1), lambda b, p: (0, 0)),
        ),
        out_shape=(
            jax.ShapeDtypeStruct((B, C_out, Pp), x3.dtype),
            jax.ShapeDtypeStruct((C_out, 1), acc),
            jax.ShapeDtypeStruct((C_out, 1), acc),
        ),
        interpret=_interpret(),
    )(x3, w)
    if Pp != P:
        # padded columns are zeros: they contributed 0 to s1/s2 — exact
        y = y[:, :, :P]
    return y, s1[:, 0], s2[:, 0]


@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6, 7))
def conv1x1_bn_act(x, w, gamma, beta, bias, eps: float, relu: bool,
                   stride: int):
    """Fused 1x1 conv (+bias) + train-mode BatchNorm + optional ReLU.

    x (B, C_in, H, W) NCHW; w (C_out, C_in); gamma/beta/bias (C_out,) (pass
    zeros for bias when the conv has none). Returns (out, mean, var) with
    mean/var the BATCH statistics (fp32) the caller feeds its running
    averages. Training-differentiable via the analytic custom VJP below."""
    out, mean, var, _y = _fwd_impl(x, w, gamma, beta, bias, eps, relu, stride)
    return out, mean, var


def _x64_enabled() -> bool:
    import jax
    return bool(jax.config.jax_enable_x64)


def _fwd_impl(x, w, gamma, beta, bias, eps, relu, stride):
    if jnp.dtype(x.dtype).itemsize >= 4 and not _x64_enabled():
        # fp32 activations with x64 disabled (the production default): the
        # stats accumulator cannot go one width up (float64 silently
        # canonicalizes to float32), so the one-pass formula could cancel
        # catastrophically — take the two-pass XLA composition instead
        # (normalization.py applies the same rule)
        out, mean, var = conv1x1_bn_act_xla(x, w, gamma, beta, bias, eps,
                                            relu, stride)
        return out, mean, var, None
    B, C_in, H, W = x.shape
    if stride != 1:
        x = x[:, :, ::stride, ::stride]
        H, W = x.shape[2], x.shape[3]
    P = H * W
    x3 = x.reshape(B, C_in, P)
    y3, s1, s2 = conv1x1_stats_pallas(x3, w)
    n = B * P
    acc = s1.dtype
    # bias shifts mean only; fold it in after the matmul-stats pass
    mean = s1 / n + bias.astype(acc)
    var = jnp.maximum(s2 / n - (s1 / n) ** 2, 0.0)  # bias cancels in var
    invstd = jax.lax.rsqrt(var + eps)
    scale = (gamma.astype(acc) * invstd)
    shift = beta.astype(acc) - (mean - bias.astype(acc)) * scale
    # NOTE: y3 excludes bias; normalize vs (mean - bias) == mean of y3
    out = y3.astype(acc) * scale[None, :, None] + shift[None, :, None]
    if relu:
        out = jnp.maximum(out, 0.0)
    out = out.astype(x.dtype).reshape(B, -1, H, W)
    return out, mean, var, y3


def _conv1x1_bn_act_fwd(x, w, gamma, beta, bias, eps, relu, stride):
    out, mean, var, y3 = _fwd_impl(x, w, gamma, beta, bias, eps, relu, stride)
    return (out, mean, var), (x, w, gamma, beta, bias, mean, var, out)


def _conv1x1_bn_act_bwd(eps, relu, stride, saved, cots):
    """Analytic backward: ReLU mask -> BN backward -> conv1x1 transposes.
    All matmuls are plain XLA dots (MXU-optimal already)."""
    x, w, gamma, beta, bias, mean, var, out = saved
    g_out, g_mean, g_var = cots  # cotangents for (out, mean, var)
    B, C_in, H0, W0 = x.shape
    xs = x[:, :, ::stride, ::stride] if stride != 1 else x
    H, W = xs.shape[2], xs.shape[3]
    P = H * W
    n = B * P
    f32 = jnp.promote_types(x.dtype, jnp.float32)
    g = g_out.astype(f32).reshape(B, -1, P)
    if relu:
        g = g * (out.reshape(B, -1, P) > 0)
    invstd = jax.lax.rsqrt(var + eps)                       # (C,) f32
    # recompute xhat from out? out = relu(xhat*gamma+beta) loses sign info —
    # recompute y + xhat from x instead (remat: one extra matmul, no saved y).
    # Matmuls stay in the ACTIVATION dtype (bf16 rides the MXU at full rate;
    # an f32 recompute here was 2.5x the whole step, BENCH r4 first cut) and
    # accumulate f32 via preferred_element_type.
    x3 = xs.reshape(B, C_in, P)
    y3 = jnp.einsum("oi,bip->bop", w, x3, preferred_element_type=f32)
    yb = y3 + bias.astype(f32)[None, :, None]
    xhat = (yb - mean[None, :, None]) * invstd[None, :, None]
    dgamma = jnp.sum(g * xhat, axis=(0, 2))
    dbeta = jnp.sum(g, axis=(0, 2))
    dxhat = g * gamma.astype(f32)[None, :, None]
    # BN backward (batch stats), plus pass-through cotangents for mean/var
    # outputs (callers feeding running averages send zeros there; the running-
    # average update is stop-gradiented in the layer, matching normalization.py)
    dy = (dxhat - jnp.mean(dxhat, axis=(0, 2), keepdims=True)
          - xhat * jnp.mean(dxhat * xhat, axis=(0, 2), keepdims=True)) \
        * invstd[None, :, None]
    if g_mean is not None:
        dy = dy + (g_mean.astype(f32) / n)[None, :, None]
    if g_var is not None:
        dy = dy + (g_var.astype(f32) * 2.0 / n)[None, :, None] \
            * (yb - mean[None, :, None])
    dbias = jnp.sum(dy, axis=(0, 2))
    dyl = dy.astype(x.dtype)  # MXU-rate matmuls, f32 accumulation
    dw = jnp.einsum("bop,bip->oi", dyl, x3, preferred_element_type=f32)
    dx3 = jnp.einsum("oi,bop->bip", w, dyl, preferred_element_type=f32)
    dxs = dx3.reshape(B, C_in, H, W)
    if stride != 1:
        dx = jnp.zeros((B, C_in, H0, W0), f32)
        dx = dx.at[:, :, ::stride, ::stride].set(dxs)
    else:
        dx = dxs
    return (dx.astype(x.dtype), dw.astype(w.dtype), dgamma.astype(gamma.dtype),
            dbeta.astype(beta.dtype), dbias.astype(bias.dtype))


conv1x1_bn_act.defvjp(_conv1x1_bn_act_fwd, _conv1x1_bn_act_bwd)
register_helper("conv1x1_bn_act")(conv1x1_bn_act)


def conv1x1_bn_act_xla(x, w, gamma, beta, bias, eps: float, relu: bool,
                       stride: int):
    """Reference composition (what the unfused layers compute today):
    lax-conv -> one-pass fp32 batch stats -> normalize (+ReLU)."""
    if stride != 1:
        x = x[:, :, ::stride, ::stride]
    y = jax.lax.conv_general_dilated(
        x, w[:, :, None, None], window_strides=(1, 1), padding=((0, 0), (0, 0)),
        dimension_numbers=("NCHW", "OIHW", "NCHW"))
    y = y + bias[None, :, None, None]
    if jnp.dtype(x.dtype).itemsize < 4:
        # one-pass stats, fp32-accumulated (XLA fuses the sibling reductions
        # into one read; safe headroom above sub-fp32 activations)
        yf = y.astype(jnp.float32)
        mean = jnp.mean(yf, axis=(0, 2, 3))
        var = jnp.maximum(jnp.mean(yf * yf, axis=(0, 2, 3)) - mean * mean,
                          0.0)
    else:
        # fp32/fp64: shifted two-pass var — the one-pass formula in
        # same-width arithmetic cancels when |mean| >> std (ADVICE r3 low#1)
        yf = y
        mean = jnp.mean(yf, axis=(0, 2, 3))
        var = jnp.var(yf, axis=(0, 2, 3))
    invstd = jax.lax.rsqrt(var + eps)
    pdt = yf.dtype  # fp32 for sub-fp32 activations, fp64 stays fp64
    out = (yf - mean[None, :, None, None]) * invstd[None, :, None, None] \
        * gamma.astype(pdt)[None, :, None, None] \
        + beta.astype(pdt)[None, :, None, None]
    if relu:
        out = jnp.maximum(out, 0.0)
    return out.astype(x.dtype), mean, var
