"""Whole-sequence fused Graves-LSTM scan kernel — the cuDNN-LSTM analog.

Parity target: ref deeplearning4j-cuda/.../CudnnLSTMHelper.java:175 — cuDNN
replaces the reference's per-timestep Java loop (LSTMHelpers.java:200/:403)
with ONE fused sequence kernel. The round-4 per-gate Pallas kernel still left
the `lax.scan` dispatching several XLA kernels per timestep (recurrent
matmul, gate fusion, state select); at bench shapes the scan is
overhead-bound, not FLOP- or bandwidth-bound. This kernel runs the ENTIRE
recurrence as one `pallas_call`:

- grid (B/bt, T): BATCH-major — each batch tile runs its whole time sweep
  before the next tile starts, so only a (bt, H) h/c scratch is resident
  (the recurrent state never touches HBM) and the tile size is limited by
  the streamed blocks alone, not by B;
- per step: xw_t block streams in (double-buffered DMA under the grid
  pipeline), gates = xw_t + h @ RW on the MXU, peephole cell update on the
  VPU, h_t/c_t blocks stream out;
- backward: a second Pallas kernel scans in reverse, RECOMPUTING the gates
  from (xw_t, h_{t-1}, c_{t-1}) — nothing but the (already-emitted) h/c
  sequences is saved — and accumulating dRW / peephole grads in VMEM
  scratch.

The input projection xw = x @ W + b stays OUTSIDE the kernel: it is one big
MXU matmul over all timesteps that XLA already schedules optimally.

Composition: under GSPMD (ShardedTrainer dp x tp) the kernel is an opaque
custom call — XLA reshards its operands around it, so correctness holds at
any sharding (parity-tested on the 8-device mesh). NOTE: default-on applies
to tp runs too; there the custom call implies per-step gathers of the
gate-dim-sharded RW — once real multi-chip hardware is available, measure
that cost and add a sharding-aware guard here if it loses to GSPMD's
partitioned lax.scan.

Gate order [i|f|o|g] matches nn/conf/layers/recurrent.py. Internal math is
fp32 (accumulated one width above bf16 activations); h/c carries are kept in
the activation dtype exactly like the unfused scan, so helpers-on training
matches helpers-off within bf16 rounding (exact in fp32/fp64 tests).
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.ops.helpers import register_helper


def _interpret() -> bool:
    from deeplearning4j_tpu.ops.helpers import interpret_mode
    return interpret_mode()


VMEM_BUDGET = 14 * 1024 * 1024  # headroom under Mosaic's 16 MB scoped limit


def _vmem_cost(H: int, db: int, bt: int, bwd: bool) -> int:
    """Estimated resident VMEM (batch-major grid): (bt, H) h/c carries x2 +
    double-buffered streamed blocks + the (H, 4H) RW block (constant across
    the grid but resident) + the fp32 (bt, 4H) gate intermediates the kernel
    body materializes. Per-row block bytes: fwd = 2x xw(4H) + 2x2x out(H) +
    2x2x init(H) = 16*H*db; bwd adds dxw out and four streamed (bt, H)
    inputs = 28*H*db, plus the fp32 dRW/peephole accumulators."""
    rw = 4 * H * H * db              # streamed (H, 4H) weight block
    # bwd: fp32 dRW scratch + the constant-index-map (H, 4H) fp32 dRW OUTPUT
    # block (both resident for the whole grid) + peephole acc/outputs
    acc = 2 * (4 * H * H * 4) + 2 * (3 * H * 4) if bwd else 0
    interm = bt * 4 * H * 4 * (2 if bwd else 1)      # fp32 gates (+dgates bwd)
    per_row = 2 * H * db + (28 if bwd else 16) * H * db
    return rw + acc + interm + bt * per_row


def _pick_bt(B: int, H: int, dtype_bytes: int = 2, bwd: bool = False) -> int:
    """Largest VMEM-fitting batch tile; B is PADDED up to a tile multiple by
    the callers (zero rows compute garbage that is sliced off; their zero
    cotangents contribute nothing to parameter gradients)."""
    for bt in (2048, 1024, 512, 256, 128, 64, 32, 16, 8):
        if bt > B:
            continue
        if _vmem_cost(H, dtype_bytes, bt, bwd) <= VMEM_BUDGET:
            return bt
    return min(B, 8)


def _pad_batch(a, Bp):
    """Zero-pad dim 1 (batch) of a (T/1, B, ...) array up to Bp rows."""
    if a.shape[1] == Bp:
        return a
    pad = [(0, 0)] * a.ndim
    pad[1] = (0, Bp - a.shape[1])
    return jnp.pad(a, pad)


def fits_vmem(B: int, H: int, dtype_bytes: int = 2) -> bool:
    """Callers fall back to lax.scan when even the smallest tile cannot fit —
    the kernel is default-on, so oversize batches must degrade gracefully,
    not fail to compile."""
    return _vmem_cost(H, dtype_bytes, min(B, 8), bwd=True) <= VMEM_BUDGET


def _fwd_kernel(xw_ref, rw_ref, pi_ref, pf_ref, po_ref, h0_ref, c0_ref,
                ys_ref, cs_ref, h_scr, c_scr):
    """One (b, t) grid step of the forward recurrence. BATCH-major grid:
    tile b finishes its entire time sweep before tile b+1 starts, so the
    (bt, H) scratch is private to the running tile."""
    from jax.experimental import pallas as pl
    t = pl.program_id(1)
    acc = jnp.promote_types(xw_ref.dtype, jnp.float32)
    H = c0_ref.shape[-1]

    @pl.when(t == 0)
    def _():  # adopt the initial state for this batch tile
        h_scr[:] = h0_ref[0]
        c_scr[:] = c0_ref[0]

    h_t = h_scr[:]                                  # (bt, H) storage dtype
    c = c_scr[:].astype(acc)
    gates = xw_ref[0].astype(acc) + jnp.dot(
        h_t, rw_ref[:], preferred_element_type=acc)
    pi = pi_ref[:].astype(acc)
    pf = pf_ref[:].astype(acc)
    po = po_ref[:].astype(acc)
    i = jax.nn.sigmoid(gates[:, :H] + c * pi)
    f = jax.nn.sigmoid(gates[:, H:2 * H] + c * pf)
    g = jnp.tanh(gates[:, 3 * H:])
    c_new = f * c + i * g
    o = jax.nn.sigmoid(gates[:, 2 * H:3 * H] + c_new * po)
    h_new = o * jnp.tanh(c_new)
    h_scr[:] = h_new.astype(h_scr.dtype)
    c_scr[:] = c_new.astype(c_scr.dtype)
    ys_ref[0] = h_new.astype(ys_ref.dtype)
    cs_ref[0] = c_new.astype(cs_ref.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=())
def graves_lstm_scan_pallas(xw, rw, pi, pf, po, h0, c0):
    """xw (T, B, 4H) input projection (x @ W + b precomputed), rw (H, 4H),
    pi/pf/po (H,), h0/c0 (B, H) -> (ys (T, B, H), cs (T, B, H)).

    The whole recurrence as one Pallas call; see module docstring."""
    ys, cs = _scan_fwd_impl(xw, rw, pi, pf, po, h0, c0)
    return ys, cs


def _scan_fwd_impl(xw, rw, pi, pf, po, h0, c0):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu
    T, B, H4 = xw.shape
    H = H4 // 4
    bt = _pick_bt(B, H, jnp.dtype(xw.dtype).itemsize)
    Bp = -(-B // bt) * bt
    nb = Bp // bt
    xw = _pad_batch(xw, Bp)
    h0p = _pad_batch(h0[None], Bp)
    c0p = _pad_batch(c0[None], Bp)
    p2 = lambda v: v.reshape(1, H)
    ys, cs = pl.pallas_call(
        _fwd_kernel,
        grid=(nb, T),
        in_specs=[
            pl.BlockSpec((1, bt, 4 * H), lambda b, t: (t, b, 0)),
            pl.BlockSpec((H, 4 * H), lambda b, t: (0, 0)),
            pl.BlockSpec((1, H), lambda b, t: (0, 0)),
            pl.BlockSpec((1, H), lambda b, t: (0, 0)),
            pl.BlockSpec((1, H), lambda b, t: (0, 0)),
            pl.BlockSpec((1, bt, H), lambda b, t: (0, b, 0)),
            pl.BlockSpec((1, bt, H), lambda b, t: (0, b, 0)),
        ],
        out_specs=(
            pl.BlockSpec((1, bt, H), lambda b, t: (t, b, 0)),
            pl.BlockSpec((1, bt, H), lambda b, t: (t, b, 0)),
        ),
        out_shape=(
            jax.ShapeDtypeStruct((T, Bp, H), xw.dtype),
            jax.ShapeDtypeStruct((T, Bp, H), xw.dtype),
        ),
        scratch_shapes=[
            pltpu.VMEM((bt, H), xw.dtype),
            pltpu.VMEM((bt, H), xw.dtype),
        ],
        interpret=_interpret(),
    )(xw, rw, p2(pi), p2(pf), p2(po), h0p, c0p)
    return ys[:, :B], cs[:, :B]


def _scan_fwd(xw, rw, pi, pf, po, h0, c0):
    ys, cs = _scan_fwd_impl(xw, rw, pi, pf, po, h0, c0)
    return (ys, cs), (xw, rw, pi, pf, po, h0, c0, ys, cs)


def _scan_bwd(saved, cots):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu
    xw, rw, pi, pf, po, h0, c0, ys, cs = saved
    dys, dcs = cots
    T, B, H4 = xw.shape
    H = H4 // 4
    bt = _pick_bt(B, H, jnp.dtype(xw.dtype).itemsize, bwd=True)
    Bp = -(-B // bt) * bt
    nb = Bp // bt
    p2 = lambda v: v.reshape(1, H)
    # dcs cotangents: cs is exposed mainly for the bwd itself; fold any
    # incoming dcs into dys-equivalent handling by adding dcs to the carried
    # dc at each step. For the layer integration dcs is all-zeros except
    # where the final cell state is consumed; support it exactly by folding
    # dcs_t into dc BEFORE the gate backward of step t. Implementation:
    # absorb via an adjusted dys' = dys and initial-carry trick is NOT exact
    # for general dcs, so we add dcs inside the kernel stream instead.
    hprev = _pad_batch(jnp.concatenate([h0[None], ys[:-1]], axis=0), Bp)
    cprev = _pad_batch(jnp.concatenate([c0[None], cs[:-1]], axis=0), Bp)
    xw = _pad_batch(xw, Bp)
    dys = _pad_batch(dys, Bp)
    dcs = _pad_batch(dcs, Bp)
    acc = jnp.promote_types(xw.dtype, jnp.float32)
    rev = lambda b, t: (T - 1 - t, b, 0)
    dxw, drw, dpi, dpf, dpo, dh0, dc0 = pl.pallas_call(
        functools.partial(_bwd_kernel_with_dcs),
        grid=(nb, T),
        in_specs=[
            pl.BlockSpec((1, bt, 4 * H), rev),
            pl.BlockSpec((H, 4 * H), lambda b, t: (0, 0)),
            pl.BlockSpec((1, H), lambda b, t: (0, 0)),
            pl.BlockSpec((1, H), lambda b, t: (0, 0)),
            pl.BlockSpec((1, H), lambda b, t: (0, 0)),
            pl.BlockSpec((1, bt, H), rev),
            pl.BlockSpec((1, bt, H), rev),
            pl.BlockSpec((1, bt, H), rev),
            pl.BlockSpec((1, bt, H), rev),
        ],
        out_specs=(
            pl.BlockSpec((1, bt, 4 * H), rev),
            pl.BlockSpec((H, 4 * H), lambda b, t: (0, 0)),
            pl.BlockSpec((1, H), lambda b, t: (0, 0)),
            pl.BlockSpec((1, H), lambda b, t: (0, 0)),
            pl.BlockSpec((1, H), lambda b, t: (0, 0)),
            pl.BlockSpec((1, bt, H), lambda b, t: (0, b, 0)),
            pl.BlockSpec((1, bt, H), lambda b, t: (0, b, 0)),
        ),
        out_shape=(
            jax.ShapeDtypeStruct((T, Bp, 4 * H), xw.dtype),
            jax.ShapeDtypeStruct((H, 4 * H), acc),
            jax.ShapeDtypeStruct((1, H), acc),
            jax.ShapeDtypeStruct((1, H), acc),
            jax.ShapeDtypeStruct((1, H), acc),
            jax.ShapeDtypeStruct((1, Bp, H), xw.dtype),
            jax.ShapeDtypeStruct((1, Bp, H), xw.dtype),
        ),
        scratch_shapes=[
            pltpu.VMEM((bt, H), xw.dtype),
            pltpu.VMEM((bt, H), xw.dtype),
            pltpu.VMEM((H, 4 * H), acc),
            pltpu.VMEM((3, H), acc),
        ],
        interpret=_interpret(),
    )(xw, rw, p2(pi), p2(pf), p2(po), hprev, cprev, dys, dcs)
    return (dxw[:, :B], drw.astype(rw.dtype),
            dpi.reshape(H).astype(pi.dtype),
            dpf.reshape(H).astype(pf.dtype), dpo.reshape(H).astype(po.dtype),
            dh0[0, :B], dc0[0, :B])


def _bwd_kernel_with_dcs(xw_ref, rw_ref, pi_ref, pf_ref, po_ref,
                         hprev_ref, cprev_ref, dys_ref, dcs_ref,
                         dxw_ref, drw_ref, dpi_ref, dpf_ref, dpo_ref,
                         dh0_ref, dc0_ref, dh_scr, dc_scr, drw_scr, dp_scr):
    """Reverse-step kernel, with cs-cotangents folded into the carried dc."""
    from jax.experimental import pallas as pl
    b = pl.program_id(0)
    t = pl.program_id(1)          # 0 .. T-1, reversed via the index maps
    nb = pl.num_programs(0)
    acc = jnp.promote_types(xw_ref.dtype, jnp.float32)
    H = pi_ref.shape[-1]
    bt = xw_ref.shape[1]

    @pl.when(t == 0)
    def _():  # start of this tile's reversed sweep
        dh_scr[:] = jnp.zeros_like(dh_scr)
        dc_scr[:] = jnp.zeros_like(dc_scr)

    @pl.when((t == 0) & (b == 0))
    def _():
        drw_scr[:] = jnp.zeros_like(drw_scr)
        dp_scr[:] = jnp.zeros_like(dp_scr)

    h_prev = hprev_ref[0]
    c_prev = cprev_ref[0].astype(acc)
    pi = pi_ref[:].astype(acc)
    pf = pf_ref[:].astype(acc)
    po = po_ref[:].astype(acc)
    gates = xw_ref[0].astype(acc) + jnp.dot(
        h_prev, rw_ref[:], preferred_element_type=acc)
    i = jax.nn.sigmoid(gates[:, :H] + c_prev * pi)
    f = jax.nn.sigmoid(gates[:, H:2 * H] + c_prev * pf)
    g = jnp.tanh(gates[:, 3 * H:])
    c_new = f * c_prev + i * g
    o = jax.nn.sigmoid(gates[:, 2 * H:3 * H] + c_new * po)
    t_new = jnp.tanh(c_new)
    dh = dys_ref[0].astype(acc) + dh_scr[:].astype(acc)
    dc_in = dc_scr[:].astype(acc) + dcs_ref[0].astype(acc)
    one = jnp.ones((), acc)
    dzo = dh * t_new * o * (one - o)
    dct = dc_in + dh * o * (one - t_new * t_new) + dzo * po
    dzi = dct * g * i * (one - i)
    dzf = dct * c_prev * f * (one - f)
    dzg = dct * i * (one - g * g)
    dgates = jnp.concatenate([dzi, dzf, dzo, dzg], axis=-1)
    dxw_ref[0] = dgates.astype(dxw_ref.dtype)
    dgl = dgates.astype(h_prev.dtype)
    dh_prev = jnp.dot(dgl, rw_ref[:].T, preferred_element_type=acc)
    dc_prev = dct * f + dzi * pi + dzf * pf
    dh_scr[:] = dh_prev.astype(dh_scr.dtype)
    dc_scr[:] = dc_prev.astype(dc_scr.dtype)
    drw_scr[:] += jnp.dot(h_prev.T, dgl,
                          preferred_element_type=drw_scr.dtype)
    dp_scr[0:1] += jnp.sum(dzi * c_prev, axis=0,
                           keepdims=True).astype(dp_scr.dtype)
    dp_scr[1:2] += jnp.sum(dzf * c_prev, axis=0,
                           keepdims=True).astype(dp_scr.dtype)
    dp_scr[2:3] += jnp.sum(dzo * c_new, axis=0,
                           keepdims=True).astype(dp_scr.dtype)

    T_ = pl.num_programs(1)

    @pl.when((t == T_ - 1) & (b == nb - 1))
    def _():
        drw_ref[:] = drw_scr[:]
        dpi_ref[:] = dp_scr[0:1]
        dpf_ref[:] = dp_scr[1:2]
        dpo_ref[:] = dp_scr[2:3]

    @pl.when(t == T_ - 1)
    def _():  # after processing t=0 (reversed), the carries are dh0/dc0
        dh0_ref[0] = dh_scr[:].astype(dh0_ref.dtype)
        dc0_ref[0] = dc_scr[:].astype(dc0_ref.dtype)


graves_lstm_scan_pallas.defvjp(_scan_fwd, _scan_bwd)
# default-on for TPU: measured +12.9% tokens/s same-session on the bench
# GravesLSTM config, exact fp64 parity + bf16 net-level equivalence tests
register_helper("graves_lstm_scan", default_on=True)(graves_lstm_scan_pallas)


def graves_lstm_scan_xla(xw, rw, pi, pf, po, h0, c0):
    """Reference lax.scan composition (what the layer computes today)."""
    def body(carry, xw_t):
        h, c = carry
        H = c.shape[-1]
        gates = xw_t + h @ rw
        i = jax.nn.sigmoid(gates[:, :H] + c * pi)
        f = jax.nn.sigmoid(gates[:, H:2 * H] + c * pf)
        g = jnp.tanh(gates[:, 3 * H:])
        c_new = f * c + i * g
        o = jax.nn.sigmoid(gates[:, 2 * H:3 * H] + c_new * po)
        h_new = o * jnp.tanh(c_new)
        return (h_new, c_new), (h_new, c_new)

    (_, _), (ys, cs) = jax.lax.scan(body, (h0, c0), xw)
    return ys, cs
