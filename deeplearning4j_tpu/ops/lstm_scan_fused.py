"""Whole-sequence fused Graves-LSTM scan kernel — the cuDNN-LSTM analog.

Parity target: ref deeplearning4j-cuda/.../CudnnLSTMHelper.java:175 — cuDNN
replaces the reference's per-timestep Java loop (LSTMHelpers.java:200/:403)
with ONE fused sequence kernel. The round-4 per-gate Pallas kernel still left
the `lax.scan` dispatching several XLA kernels per timestep (recurrent
matmul, gate fusion, state select); at bench shapes the scan is
overhead-bound, not FLOP- or bandwidth-bound. This kernel runs the ENTIRE
recurrence as one `pallas_call`. Two grid layouts share one kernel body
(`_make_fwd_kernel`/`_make_bwd_kernel`), plus a K-timestep tile factor:

- BATCH-major grid (B/bt, T/K) — THE DEFAULT: each batch tile runs its
  whole time sweep before the next tile starts, so only a (bt, H) h/c
  scratch is resident and the streamed tiles can be as large as VMEM
  allows; works at ANY batch size.
- TIME-major grid (T/K, B/bt): the FULL (B, H) h/c state resident in VMEM
  scratch, batch tiles iterating fastest. The r5 same-session A/B measured
  it SLOWER at every VMEM-feasible tile (43-51 ms vs batch-major's 39.5 ms
  kernel-level at the bench shape; the state scratch crowds out streamed
  tile bytes, adding grid steps). Kept selectable via configure(grid="tm").
- K > 1 processes K consecutive timesteps per grid step (streaming a
  (K, bt, 4H) xw block). Measured: no win — VMEM caps K*bt, so K>1 only
  shrinks bt (39.96-40.91 ms vs 39.51 ms). The auto-picker prefers the
  biggest tiles at K=1 accordingly; K stays available for future chips
  with more VMEM.

The backward reads h_prev/c_prev DIRECTLY from the forward's ys/cs outputs
via a one-step-shifted clamped index map (initial state substituted
in-kernel at the t=0 boundary), deleting two (T, B, H) HBM concat copies
per backward.

- per step: xw_t block streams in (double-buffered DMA under the grid
  pipeline), gates = xw_t + h @ RW on the MXU, peephole cell update on the
  VPU, h_t/c_t blocks stream out;
- backward: a second Pallas kernel scans in reverse, RECOMPUTING the gates
  from (xw_t, h_{t-1}, c_{t-1}) — nothing but the (already-emitted) h/c
  sequences is saved — and accumulating dRW / peephole grads in VMEM
  scratch.

The input projection xw = x @ W + b stays OUTSIDE the kernel: it is one big
MXU matmul over all timesteps that XLA already schedules optimally.

Composition: under GSPMD (ShardedTrainer dp x tp) the kernel is an opaque
custom call — XLA reshards its operands around it, so correctness holds at
any sharding (parity-tested on the 8-device mesh). NOTE: default-on applies
to tp runs too; there the custom call implies per-step gathers of the
gate-dim-sharded RW — once real multi-chip hardware is available, measure
that cost and add a sharding-aware guard here if it loses to GSPMD's
partitioned lax.scan.

Gate order [i|f|o|g] matches nn/conf/layers/recurrent.py. Internal gate math
is fp32 by default (accumulated one width above bf16 activations); h/c
carries round-trip through the activation dtype between steps exactly like
the unfused scan, so helpers-on training matches helpers-off within bf16
rounding (exact in fp32/fp64 tests). `configure(gate_math="native")` keeps
gate math in the activation dtype (A/B'd; see PERF.md).
"""
from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.ops.helpers import register_helper


def _interpret() -> bool:
    from deeplearning4j_tpu.ops.helpers import interpret_mode
    return interpret_mode()


# Headroom under Mosaic's 16 MB scoped VMEM limit, CALIBRATED against real
# compiles (r5 A/B, experiments/lstm_grid_ab*.py): at the bench shape
# (H=256, bf16) the estimate for the largest config that compiles (bwd
# bt=512) is 14.69 MB and the smallest that fails (bwd bt=1024, fwd
# bt=2048, tm 1024/512) estimates >= 19 MB — 15 MB splits them.
VMEM_BUDGET = 15 * 1024 * 1024

_TILES = (2048, 1024, 512, 256, 128, 64, 32, 16, 8)

# Dispatch knobs — production defaults; configure() overrides for A/Bs.
#   grid: "auto" = batch-major (the r5 A/B refuted time-major at every
#         VMEM-feasible tile); "tm" / "bm" force one layout.
#   k_steps: 0 = auto (largest of _K_CANDIDATES dividing T that fits VMEM),
#            n >= 1 forces K=n (requires K | T).
#   gate_math: "fp32" promotes gate math one width up; "native" keeps the
#              activation dtype (bf16 in, bf16 math).
_CONFIG = {
    "grid": os.environ.get("DL4J_TPU_LSTM_GRID", "auto"),
    "k_steps": int(os.environ.get("DL4J_TPU_LSTM_KSTEPS", "0")),
    "gate_math": os.environ.get("DL4J_TPU_LSTM_GATE_MATH", "fp32"),
}

_K_CANDIDATES = (8, 5, 4, 2, 1)


def configure(**kw):
    """Override dispatch knobs (grid / k_steps / gate_math); returns the
    previous values so experiments can restore them.

    NOTE: the knobs are read at TRACE time — a function jitted before the
    configure() call keeps its compiled layout (JAX returns the cached
    executable). A/B harnesses must build a fresh jit per configuration
    (experiments/lstm_grid_ab.py does)."""
    prev = dict(_CONFIG)
    for k, v in kw.items():
        if k not in _CONFIG:
            raise KeyError(f"unknown lstm_scan_fused config key {k!r}")
        _CONFIG[k] = v
    return prev


def _vmem_cost(H: int, db: int, bt: int, bwd: bool, state_rows: int,
               K: int = 1) -> int:
    """Estimated resident VMEM. `state_rows` is the h/c (fwd) or dh/dc (bwd)
    scratch height: bt for batch-major, padded B for time-major. Streamed
    blocks are double-buffered; per K-step row bytes: fwd = xw(2x4H) +
    ys/cs out (2x2xH) = 12*H*db, bwd = xw(2x4H) + 4 streamed H-blocks (2x)
    + dxw out (2x4H) = 24*H*db. The fp32 gate intermediates (bt, 4H) and,
    for bwd, the dRW accumulator + its constant-index-map output block are
    counted explicitly."""
    rw = 4 * H * H * db                      # streamed (H, 4H) weight block
    acc = 2 * (4 * H * H * 4) + 2 * (3 * H * 4) if bwd else 0
    interm = bt * 4 * H * 4 * (2 if bwd else 1)
    state = 2 * state_rows * H * db
    per_k = (24 if bwd else 12) * H * db
    fixed = 4 * H * db                       # h0/c0 or dh0/dc0 blocks (2x)
    return rw + acc + interm + state + bt * (K * per_k + fixed)


def _pick_bt(B: int, H: int, db: int, bwd: bool, time_major: bool,
             K: int = 1):
    """Largest VMEM-fitting batch tile (None if nothing fits in time-major
    mode — the caller then falls back to batch-major). B is PADDED up to a
    tile multiple by the callers (zero rows compute garbage that is sliced
    off; their zero cotangents contribute nothing to parameter grads)."""
    for bt in _TILES:
        if bt > B:
            continue
        sr = (-(-B // bt) * bt) if time_major else bt
        if _vmem_cost(H, db, bt, bwd, sr, K) <= VMEM_BUDGET:
            return bt
    return None if time_major else min(B, 8)


def _pick_layout(T: int, B: int, H: int, db: int):
    """Resolve (time_major, K, bt_fwd, bt_bwd) from the config + shape."""
    mode = _CONFIG["grid"]
    if _CONFIG["k_steps"]:
        ks = (_CONFIG["k_steps"],)
        if T % ks[0]:
            # a FORCED K that does not divide T must fail loudly — silently
            # degrading to the min-tile config would make any A/B forcing K
            # report garbage with no error
            raise ValueError(
                f"forced k_steps={ks[0]} does not divide T={T}")
    else:
        ks = _K_CANDIDATES
    # auto grid = batch-major: the r5 same-session A/B measured tm SLOWER at
    # every VMEM-feasible tile (its full-state scratch shrinks the streamed
    # tiles, adding grid steps — 43-51 ms vs bm's 39.5 ms kernel-level; the
    # r4 "+57.7% tm" config measured 48.6 ms on recheck). tm stays
    # selectable via configure(grid="tm") for future hardware.
    modes = (mode == "tm",) if mode in ("tm", "bm") else (False,)
    best = None
    for tm in modes:
        for K in ks:
            if T % K:
                continue
            bt_f = _pick_bt(B, H, db, False, tm, K)
            bt_b = _pick_bt(B, H, db, True, tm, K)
            if bt_f is None or bt_b is None:
                continue
            # MEASURED objective (r5 A/B): the biggest tiles win — bm K=1
            # 1024/512 at 39.5 ms beat every K>1 config (39.96-40.91 ms)
            # even when K*bt said fewer grid steps; per-step DMA/MXU
            # efficiency of large tiles dominates. Prefer max tile bytes,
            # then smaller K.
            score = (bt_f + bt_b, -K)
            if best is None or score > best[0]:
                best = (score, (tm, K, bt_f, bt_b))
    if best is not None:
        return best[1]
    if mode != "auto" or _CONFIG["k_steps"]:
        raise ValueError(
            f"forced layout grid={mode!r} k_steps={_CONFIG['k_steps']} "
            f"cannot fit VMEM at T={T} B={B} H={H}")
    # nothing fits even batch-major at K=1 with the smallest tile: callers
    # should have gated on fits_vmem; degrade to the smallest config
    return False, 1, min(B, 8), min(B, 8)


def _pad_batch(a, Bp):
    """Zero-pad dim 1 (batch) of a (T/1, B, ...) array up to Bp rows."""
    if a.shape[1] == Bp:
        return a
    pad = [(0, 0)] * a.ndim
    pad[1] = (0, Bp - a.shape[1])
    return jnp.pad(a, pad)


def fits_vmem(B: int, H: int, dtype_bytes: int = 2) -> bool:
    """Callers fall back to lax.scan when even the smallest batch-major tile
    cannot fit — the kernel is default-on, so oversize shapes must degrade
    gracefully, not fail to compile."""
    return _vmem_cost(H, dtype_bytes, min(B, 8), True, min(B, 8)) \
        <= VMEM_BUDGET


def _gate_acc(dtype):
    if _CONFIG["gate_math"] == "native":
        return dtype
    return jnp.promote_types(dtype, jnp.float32)


def _make_fwd_kernel(time_major: bool, K: int):
    """One grid step of the forward recurrence, covering K timesteps of one
    batch tile. Batch-major: tile b finishes its whole time sweep before
    tile b+1 (the (bt, H) scratch is private to the running tile).
    Time-major: the scratch holds the FULL padded-(B, H) state and tiles
    iterate fastest; each tile reads/writes only its own row slice."""
    from jax.experimental import pallas as pl

    def kernel(xw_ref, rw_ref, pi_ref, pf_ref, po_ref, h0_ref, c0_ref,
               ys_ref, cs_ref, h_scr, c_scr):
        bt = xw_ref.shape[1]
        if time_major:
            t, b = pl.program_id(0), pl.program_id(1)
            rows = pl.ds(b * bt, bt)
        else:
            t = pl.program_id(1)
            rows = slice(None)
        acc = _gate_acc(xw_ref.dtype)
        H = c0_ref.shape[-1]

        @pl.when(t == 0)
        def _():  # adopt the initial state for this batch tile
            h_scr[rows] = h0_ref[0]
            c_scr[rows] = c0_ref[0]

        h_t = h_scr[rows]                           # (bt, H) storage dtype
        c_t = c_scr[rows]
        pi = pi_ref[:].astype(acc)
        pf = pf_ref[:].astype(acc)
        po = po_ref[:].astype(acc)
        for k in range(K):
            c = c_t.astype(acc)
            gates = xw_ref[k].astype(acc) + jnp.dot(
                h_t, rw_ref[:], preferred_element_type=acc)
            i = jax.nn.sigmoid(gates[:, :H] + c * pi)
            f = jax.nn.sigmoid(gates[:, H:2 * H] + c * pf)
            g = jnp.tanh(gates[:, 3 * H:])
            c_new = f * c + i * g
            o = jax.nn.sigmoid(gates[:, 2 * H:3 * H] + c_new * po)
            h_new = o * jnp.tanh(c_new)
            # round-trip through the storage dtype between sub-steps so K>1
            # matches K=1 (and the lax.scan fallback) bit-for-bit
            h_t = h_new.astype(ys_ref.dtype)
            c_t = c_new.astype(cs_ref.dtype)
            ys_ref[k] = h_t
            cs_ref[k] = c_t
        h_scr[rows] = h_t
        c_scr[rows] = c_t

    return kernel


def _make_bwd_kernel(time_major: bool, K: int, direct_prev: bool = False):
    """Reverse-sweep grid step covering K timesteps, recomputing the gates
    from streamed (xw, h_prev, c_prev) and folding the cs-cotangents into
    the carried dc. dRW / peephole grads accumulate in VMEM scratch across
    the whole grid (zeroed on the first step, flushed on the last).

    direct_prev (K=1 only): h_prev/c_prev are read DIRECTLY from the fwd's
    ys/cs outputs with a one-step-shifted (clamped) index map, selecting the
    streamed h0/c0 block at the time-0 step in-kernel — this deletes the
    hprev/cprev concat materialization (two (T, B, H) HBM copies per
    backward) the non-direct path pays."""
    from jax.experimental import pallas as pl

    def kernel(xw_ref, rw_ref, pi_ref, pf_ref, po_ref,
               hprev_ref, cprev_ref, h0_ref, c0_ref, dys_ref, dcs_ref,
               dxw_ref, drw_ref, dpi_ref, dpf_ref, dpo_ref,
               dh0_ref, dc0_ref, dh_scr, dc_scr, drw_scr, dp_scr):
        bt = xw_ref.shape[1]
        if time_major:
            t, b = pl.program_id(0), pl.program_id(1)
            nb = pl.num_programs(1)
            nt = pl.num_programs(0)
            rows = pl.ds(b * bt, bt)
        else:
            b, t = pl.program_id(0), pl.program_id(1)
            nb = pl.num_programs(0)
            nt = pl.num_programs(1)
            rows = slice(None)
        acc = _gate_acc(xw_ref.dtype)
        H = pi_ref.shape[-1]

        @pl.when(t == 0)
        def _():  # start of this tile's reversed sweep
            dh_scr[rows] = jnp.zeros((bt, H), dh_scr.dtype)
            dc_scr[rows] = jnp.zeros((bt, H), dc_scr.dtype)

        @pl.when((t == 0) & (b == 0))
        def _():
            drw_scr[:] = jnp.zeros_like(drw_scr)
            dp_scr[:] = jnp.zeros_like(dp_scr)

        pi = pi_ref[:].astype(acc)
        pf = pf_ref[:].astype(acc)
        po = po_ref[:].astype(acc)
        dh_c = dh_scr[rows].astype(acc)
        dc_c = dc_scr[rows].astype(acc)
        one = jnp.ones((), acc)
        # the block holds K timesteps in ascending time order; the reversed
        # sweep processes them k = K-1 .. 0
        for k in reversed(range(K)):
            if direct_prev:
                # grid step t (reversed) handles time nt-1-t; its h_prev is
                # ys[time-1], streamed via the clamped shifted index map —
                # at time 0 (t == nt-1) substitute the initial state
                is_first = (t == nt - 1)
                h_prev = jnp.where(is_first, h0_ref[0], hprev_ref[k])
                c_prev = jnp.where(is_first, c0_ref[0],
                                   cprev_ref[k]).astype(acc)
            else:
                h_prev = hprev_ref[k]
                c_prev = cprev_ref[k].astype(acc)
            gates = xw_ref[k].astype(acc) + jnp.dot(
                h_prev, rw_ref[:], preferred_element_type=acc)
            i = jax.nn.sigmoid(gates[:, :H] + c_prev * pi)
            f = jax.nn.sigmoid(gates[:, H:2 * H] + c_prev * pf)
            g = jnp.tanh(gates[:, 3 * H:])
            c_new = f * c_prev + i * g
            o = jax.nn.sigmoid(gates[:, 2 * H:3 * H] + c_new * po)
            t_new = jnp.tanh(c_new)
            dh = dys_ref[k].astype(acc) + dh_c
            dc_in = dc_c + dcs_ref[k].astype(acc)
            dzo = dh * t_new * o * (one - o)
            dct = dc_in + dh * o * (one - t_new * t_new) + dzo * po
            dzi = dct * g * i * (one - i)
            dzf = dct * c_prev * f * (one - f)
            dzg = dct * i * (one - g * g)
            dgates = jnp.concatenate([dzi, dzf, dzo, dzg], axis=-1)
            dxw_ref[k] = dgates.astype(dxw_ref.dtype)
            dgl = dgates.astype(h_prev.dtype)
            dh_c = jnp.dot(dgl, rw_ref[:].T, preferred_element_type=acc)
            dc_c = dct * f + dzi * pi + dzf * pf
            drw_scr[:] += jnp.dot(h_prev.T, dgl,
                                  preferred_element_type=drw_scr.dtype)
            dp_scr[0:1] += jnp.sum(dzi * c_prev, axis=0,
                                   keepdims=True).astype(dp_scr.dtype)
            dp_scr[1:2] += jnp.sum(dzf * c_prev, axis=0,
                                   keepdims=True).astype(dp_scr.dtype)
            dp_scr[2:3] += jnp.sum(dzo * c_new, axis=0,
                                   keepdims=True).astype(dp_scr.dtype)
        dh_scr[rows] = dh_c.astype(dh_scr.dtype)
        dc_scr[rows] = dc_c.astype(dc_scr.dtype)

        @pl.when((t == nt - 1) & (b == nb - 1))
        def _():
            drw_ref[:] = drw_scr[:]
            dpi_ref[:] = dp_scr[0:1]
            dpf_ref[:] = dp_scr[1:2]
            dpo_ref[:] = dp_scr[2:3]

        @pl.when(t == nt - 1)
        def _():  # after processing t=0 (reversed), the carries are dh0/dc0
            dh0_ref[0] = dh_scr[rows].astype(dh0_ref.dtype)
            dc0_ref[0] = dc_scr[rows].astype(dc0_ref.dtype)

    return kernel


@functools.partial(jax.custom_vjp, nondiff_argnums=())
def graves_lstm_scan_pallas(xw, rw, pi, pf, po, h0, c0):
    """xw (T, B, 4H) input projection (x @ W + b precomputed), rw (H, 4H),
    pi/pf/po (H,), h0/c0 (B, H) -> (ys (T, B, H), cs (T, B, H)).

    The whole recurrence as one Pallas call; see module docstring."""
    ys, cs = _scan_fwd_impl(xw, rw, pi, pf, po, h0, c0)
    return ys, cs


def _scan_fwd_impl(xw, rw, pi, pf, po, h0, c0):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu
    T, B, H4 = xw.shape
    H = H4 // 4
    db = jnp.dtype(xw.dtype).itemsize
    tm, K, bt, _ = _pick_layout(T, B, H, db)
    Bp = -(-B // bt) * bt
    nb = Bp // bt
    nt = T // K
    xw = _pad_batch(xw, Bp)
    h0p = _pad_batch(h0[None], Bp)
    c0p = _pad_batch(c0[None], Bp)
    p2 = lambda v: v.reshape(1, H)
    grid = (nt, nb) if tm else (nb, nt)
    if tm:
        xmap = lambda t, b: (t, b, 0)
        cmap = lambda t, b: (0, 0)
        pmap_ = lambda t, b: (0, b, 0)
    else:
        xmap = lambda b, t: (t, b, 0)
        cmap = lambda b, t: (0, 0)
        pmap_ = lambda b, t: (0, b, 0)
    ys, cs = pl.pallas_call(
        _make_fwd_kernel(tm, K),
        grid=grid,
        in_specs=[
            pl.BlockSpec((K, bt, 4 * H), xmap),
            pl.BlockSpec((H, 4 * H), cmap),
            pl.BlockSpec((1, H), cmap),
            pl.BlockSpec((1, H), cmap),
            pl.BlockSpec((1, H), cmap),
            pl.BlockSpec((1, bt, H), pmap_),
            pl.BlockSpec((1, bt, H), pmap_),
        ],
        out_specs=(
            pl.BlockSpec((K, bt, H), xmap),
            pl.BlockSpec((K, bt, H), xmap),
        ),
        out_shape=(
            jax.ShapeDtypeStruct((T, Bp, H), xw.dtype),
            jax.ShapeDtypeStruct((T, Bp, H), xw.dtype),
        ),
        scratch_shapes=[
            pltpu.VMEM((Bp if tm else bt, H), xw.dtype),
            pltpu.VMEM((Bp if tm else bt, H), xw.dtype),
        ],
        interpret=_interpret(),
    )(xw, rw, p2(pi), p2(pf), p2(po), h0p, c0p)
    return ys[:, :B], cs[:, :B]


def _scan_fwd(xw, rw, pi, pf, po, h0, c0):
    ys, cs = _scan_fwd_impl(xw, rw, pi, pf, po, h0, c0)
    return (ys, cs), (xw, rw, pi, pf, po, h0, c0, ys, cs)


def _scan_bwd(saved, cots):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu
    xw, rw, pi, pf, po, h0, c0, ys, cs = saved
    dys, dcs = cots
    T, B, H4 = xw.shape
    H = H4 // 4
    db = jnp.dtype(xw.dtype).itemsize
    tm, K, _, bt = _pick_layout(T, B, H, db)
    Bp = -(-B // bt) * bt
    nb = Bp // bt
    nt = T // K
    p2 = lambda v: v.reshape(1, H)
    # dcs cotangents: cs is exposed mainly for the bwd itself; for the layer
    # integration dcs is all-zeros except where the final cell state is
    # consumed; support general dcs exactly by folding dcs_t into the
    # carried dc BEFORE the gate backward of step t, inside the kernel.
    acc = jnp.promote_types(xw.dtype, jnp.float32)
    grid = (nt, nb) if tm else (nb, nt)
    if tm:
        rev = lambda t, b: (nt - 1 - t, b, 0)
        cmap = lambda t, b: (0, 0)
        pmap_ = lambda t, b: (0, b, 0)
    else:
        rev = lambda b, t: (nt - 1 - t, b, 0)
        cmap = lambda b, t: (0, 0)
        pmap_ = lambda b, t: (0, b, 0)
    direct = K == 1
    if direct:
        # read h_prev/c_prev straight from ys/cs via the one-step-shifted
        # clamped map (the t==0 boundary substitutes h0/c0 in-kernel) —
        # no (T, B, H) concat copies
        hsrc = _pad_batch(ys, Bp)
        csrc = _pad_batch(cs, Bp)
        if tm:
            prev_map = lambda t, b: (jnp.maximum(nt - 2 - t, 0), b, 0)
        else:
            prev_map = lambda b, t: (jnp.maximum(nt - 2 - t, 0), b, 0)
    else:
        hsrc = _pad_batch(jnp.concatenate([h0[None], ys[:-1]], axis=0), Bp)
        csrc = _pad_batch(jnp.concatenate([c0[None], cs[:-1]], axis=0), Bp)
        prev_map = rev
    h0p = _pad_batch(h0[None], Bp)
    c0p = _pad_batch(c0[None], Bp)
    xw = _pad_batch(xw, Bp)
    dys = _pad_batch(dys, Bp)
    dcs = _pad_batch(dcs, Bp)
    dxw, drw, dpi, dpf, dpo, dh0, dc0 = pl.pallas_call(
        _make_bwd_kernel(tm, K, direct_prev=direct),
        grid=grid,
        in_specs=[
            pl.BlockSpec((K, bt, 4 * H), rev),
            pl.BlockSpec((H, 4 * H), cmap),
            pl.BlockSpec((1, H), cmap),
            pl.BlockSpec((1, H), cmap),
            pl.BlockSpec((1, H), cmap),
            pl.BlockSpec((K, bt, H), prev_map),
            pl.BlockSpec((K, bt, H), prev_map),
            pl.BlockSpec((1, bt, H), pmap_),
            pl.BlockSpec((1, bt, H), pmap_),
            pl.BlockSpec((K, bt, H), rev),
            pl.BlockSpec((K, bt, H), rev),
        ],
        out_specs=(
            pl.BlockSpec((K, bt, 4 * H), rev),
            pl.BlockSpec((H, 4 * H), cmap),
            pl.BlockSpec((1, H), cmap),
            pl.BlockSpec((1, H), cmap),
            pl.BlockSpec((1, H), cmap),
            pl.BlockSpec((1, bt, H), pmap_),
            pl.BlockSpec((1, bt, H), pmap_),
        ),
        out_shape=(
            jax.ShapeDtypeStruct((T, Bp, 4 * H), xw.dtype),
            jax.ShapeDtypeStruct((H, 4 * H), acc),
            jax.ShapeDtypeStruct((1, H), acc),
            jax.ShapeDtypeStruct((1, H), acc),
            jax.ShapeDtypeStruct((1, H), acc),
            jax.ShapeDtypeStruct((1, Bp, H), xw.dtype),
            jax.ShapeDtypeStruct((1, Bp, H), xw.dtype),
        ),
        scratch_shapes=[
            pltpu.VMEM((Bp if tm else bt, H), xw.dtype),
            pltpu.VMEM((Bp if tm else bt, H), xw.dtype),
            pltpu.VMEM((H, 4 * H), acc),
            pltpu.VMEM((3, H), acc),
        ],
        interpret=_interpret(),
    )(xw, rw, p2(pi), p2(pf), p2(po), hsrc, csrc, h0p, c0p, dys, dcs)
    return (dxw[:, :B], drw.astype(rw.dtype),
            dpi.reshape(H).astype(pi.dtype),
            dpf.reshape(H).astype(pf.dtype), dpo.reshape(H).astype(po.dtype),
            dh0[0, :B], dc0[0, :B])


graves_lstm_scan_pallas.defvjp(_scan_fwd, _scan_bwd)
# default-on for TPU: the r5 full-bench artifact measures 11.14M tokens/s
# helpers-on vs 6.47M off (+72%; batch-major fwd-1024/bwd-512 K=1 with the
# direct-prev backward). The r4 "+57.7% time-major" result was REFUTED on
# recheck (48.6 ms vs batch-major's 39.5 ms kernel-level) — auto dispatch
# is batch-major; tm stays selectable via configure(grid="tm"). Exact fp64
# parity + bf16 net-level equivalence tests gate every layout.
register_helper("graves_lstm_scan", default_on=True)(graves_lstm_scan_pallas)


def graves_lstm_scan_xla(xw, rw, pi, pf, po, h0, c0):
    """Reference lax.scan composition (what the layer computes today)."""
    def body(carry, xw_t):
        h, c = carry
        H = c.shape[-1]
        gates = xw_t + h @ rw
        i = jax.nn.sigmoid(gates[:, :H] + c * pi)
        f = jax.nn.sigmoid(gates[:, H:2 * H] + c * pf)
        g = jnp.tanh(gates[:, 3 * H:])
        c_new = f * c + i * g
        o = jax.nn.sigmoid(gates[:, 2 * H:3 * H] + c_new * po)
        h_new = o * jnp.tanh(c_new)
        return (h_new, c_new), (h_new, c_new)

    (_, _), (ys, cs) = jax.lax.scan(body, (h0, c0), xw)
    return ys, cs
