"""Accelerated-kernel plug-ins (L2): the helper seam + Pallas TPU kernels.

Parity: ref nn/layers/LayerHelper + the cudnn helper interfaces
(ConvolutionHelper, LSTMHelper, BatchNormalizationHelper) — here a registry of
Pallas kernels that call sites reach through `helper_for`, disabled by default
(XLA fusion is the baseline; enable with enable_helpers()/DL4J_TPU_HELPERS=1).
"""
from deeplearning4j_tpu.ops.helpers import (
    enable_helpers, helper_for, helpers_enabled, register_helper,
    registered_helpers)
from deeplearning4j_tpu.ops import pallas_kernels  # registers kernels on import
from deeplearning4j_tpu.ops import conv_fused  # registers conv1x1_bn_act
from deeplearning4j_tpu.ops import lstm_scan_fused  # registers graves_lstm_scan
from deeplearning4j_tpu.ops import flash_attention  # registers flash_attention
from deeplearning4j_tpu.ops import decode_attention  # registers decode_attention

__all__ = ["enable_helpers", "helpers_enabled", "helper_for", "register_helper",
           "registered_helpers", "pallas_kernels", "conv_fused",
           "lstm_scan_fused", "flash_attention", "decode_attention"]
