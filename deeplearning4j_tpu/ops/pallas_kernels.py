"""Pallas TPU kernels behind the helper seam.

Two hot ops where hand-tiling pays (everything else is left to XLA fusion):

- `lstm_gates`: the per-timestep gate nonlinearity + cell update of the LSTM scan
  body (ref nn/layers/recurrent/LSTMHelpers.java:200 — the reference's cudnn
  fast path). One VMEM-resident kernel computes sigmoid/tanh gates and the new
  (c, h) for a batch tile, replacing four separate slice+activation HLOs between
  the two MXU matmuls.
- `threshold_encode`: the gradient-compression quantizer of the SHARED_GRADIENTS
  path (ref EncodingHandler / threshold encoding) — elementwise ternarize with
  residual carry, the "quantization kernels" pattern from the Pallas guide.

Both run with `interpret=True` off-TPU so the CPU test mesh exercises the same
code path, and both have pure-jnp fallbacks wired through the seam.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.ops.helpers import register_helper


def _interpret() -> bool:
    from deeplearning4j_tpu.ops.helpers import interpret_mode
    return interpret_mode()


# ------------------------------------------------------------------ lstm gates


def _lstm_gates_kernel(gates_ref, c_ref, c_out_ref, h_out_ref):
    import jax.nn as jnn
    acc = jnp.promote_types(gates_ref.dtype, jnp.float32)
    g = gates_ref[:].astype(acc)           # (TB, 4H)
    c = c_ref[:].astype(acc)               # (TB, H)
    H = c.shape[-1]
    zi = jnn.sigmoid(g[:, :H])
    zf = jnn.sigmoid(g[:, H:2 * H])
    zo = jnn.sigmoid(g[:, 2 * H:3 * H])
    zg = jnp.tanh(g[:, 3 * H:])
    c_new = zf * c + zi * zg
    c_out_ref[:] = c_new.astype(c_out_ref.dtype)
    h_out_ref[:] = (zo * jnp.tanh(c_new)).astype(h_out_ref.dtype)


def _lstm_gates_bwd_kernel(gates_ref, c_ref, dc_ref, dh_ref,
                           dgates_ref, dcprev_ref):
    """Backward: recompute activations from the saved inputs (remat-style — no
    forward activations are kept in HBM), then the closed-form gate gradients."""
    import jax.nn as jnn
    acc = jnp.promote_types(gates_ref.dtype, jnp.float32)
    g = gates_ref[:].astype(acc)
    c = c_ref[:].astype(acc)
    dc_new = dc_ref[:].astype(acc)
    dh = dh_ref[:].astype(acc)
    H = c.shape[-1]
    one = jnp.ones((), g.dtype)
    i = jnn.sigmoid(g[:, :H])
    f = jnn.sigmoid(g[:, H:2 * H])
    o = jnn.sigmoid(g[:, 2 * H:3 * H])
    gg = jnp.tanh(g[:, 3 * H:])
    c_new = f * c + i * gg
    t = jnp.tanh(c_new)
    do = dh * t
    dct = dc_new + dh * o * (one - t * t)
    dzi = dct * gg * i * (one - i)
    dzf = dct * c * f * (one - f)
    dzo = do * o * (one - o)
    dzg = dct * i * (one - gg * gg)
    dgates_ref[:] = jnp.concatenate([dzi, dzf, dzo, dzg],
                                    axis=-1).astype(dgates_ref.dtype)
    dcprev_ref[:] = (dct * f).astype(dcprev_ref.dtype)


def _batch_grid(B: int, tile: int = 512):
    """(grid, tile, padded_B) for tiling a batch dim into VMEM-sized rows."""
    tb = min(B, tile)
    Bp = (B + tb - 1) // tb * tb
    return (Bp // tb,), tb, Bp


def _pad_rows(a, Bp):
    return a if a.shape[0] == Bp else jnp.pad(
        a, ((0, Bp - a.shape[0]),) + ((0, 0),) * (a.ndim - 1))


@jax.custom_vjp
def lstm_gates_pallas(gates: jnp.ndarray, c: jnp.ndarray):
    """gates (B, 4H) pre-activations [i|f|o|g], c (B, H) -> (c_new, h_new).

    Gate order matches nn/conf/layers/recurrent.py:67-70 (zi, zf, zo, zg).
    Tiled over the batch (VMEM-sized row blocks); internally computed in
    fp32 for sub-fp32 activations (transcendentals in one pass, cast once at
    the boundary). Differentiable via a custom VJP whose backward is itself
    a Pallas kernel (the guide's Custom VJP pattern)."""
    from jax.experimental import pallas as pl
    B, H = c.shape
    grid, tb, Bp = _batch_grid(B)
    gates_p, c_p = _pad_rows(gates, Bp), _pad_rows(c, Bp)
    c_new, h_new = pl.pallas_call(
        _lstm_gates_kernel,
        grid=grid,
        in_specs=[pl.BlockSpec((tb, 4 * H), lambda b: (b, 0)),
                  pl.BlockSpec((tb, H), lambda b: (b, 0))],
        out_specs=(pl.BlockSpec((tb, H), lambda b: (b, 0)),
                   pl.BlockSpec((tb, H), lambda b: (b, 0))),
        out_shape=(jax.ShapeDtypeStruct((Bp, H), c.dtype),
                   jax.ShapeDtypeStruct((Bp, H), c.dtype)),
        interpret=_interpret(),
    )(gates_p, c_p)
    return c_new[:B], h_new[:B]


def _lstm_gates_fwd(gates, c):
    return lstm_gates_pallas(gates, c), (gates, c)


def _lstm_gates_bwd(saved, cotangents):
    from jax.experimental import pallas as pl
    gates, c = saved
    dc_new, dh = cotangents
    B, H = c.shape
    grid, tb, Bp = _batch_grid(B)
    args = [_pad_rows(a, Bp) for a in (gates, c, dc_new, dh)]
    dgates, dc_prev = pl.pallas_call(
        _lstm_gates_bwd_kernel,
        grid=grid,
        in_specs=[pl.BlockSpec((tb, 4 * H), lambda b: (b, 0)),
                  pl.BlockSpec((tb, H), lambda b: (b, 0)),
                  pl.BlockSpec((tb, H), lambda b: (b, 0)),
                  pl.BlockSpec((tb, H), lambda b: (b, 0))],
        out_specs=(pl.BlockSpec((tb, 4 * H), lambda b: (b, 0)),
                   pl.BlockSpec((tb, H), lambda b: (b, 0))),
        out_shape=(jax.ShapeDtypeStruct((Bp, 4 * H), gates.dtype),
                   jax.ShapeDtypeStruct((Bp, H), c.dtype)),
        interpret=_interpret(),
    )(*args)
    return dgates[:B], dc_prev[:B]


lstm_gates_pallas.defvjp(_lstm_gates_fwd, _lstm_gates_bwd)
register_helper("lstm_gates")(lstm_gates_pallas)


def lstm_gates_xla(gates: jnp.ndarray, c: jnp.ndarray):
    """Fallback: plain jnp (what the layer inlines today)."""
    H = c.shape[-1]
    zi = jax.nn.sigmoid(gates[:, :H])
    zf = jax.nn.sigmoid(gates[:, H:2 * H])
    zo = jax.nn.sigmoid(gates[:, 2 * H:3 * H])
    zg = jnp.tanh(gates[:, 3 * H:])
    c_new = zf * c + zi * zg
    return c_new, zo * jnp.tanh(c_new)


# --------------------------------------------------- graves (peephole) gates


def _graves_gates_kernel(gates_ref, c_ref, pi_ref, pf_ref, po_ref,
                         c_out_ref, h_out_ref):
    """Graves-2013 peephole cell update (ref CudnnLSTMHelper.java:175 — the
    reference's GravesLSTM fast path; math mirrors
    nn/conf/layers/recurrent.py:_step peephole branch)."""
    import jax.nn as jnn
    acc = jnp.promote_types(gates_ref.dtype, jnp.float32)
    g = gates_ref[:].astype(acc)           # (TB, 4H)
    c = c_ref[:].astype(acc)               # (TB, H)
    H = c.shape[-1]
    pi, pf, po = (r[:].astype(acc) for r in (pi_ref, pf_ref, po_ref))
    i = jnn.sigmoid(g[:, :H] + c * pi)
    f = jnn.sigmoid(g[:, H:2 * H] + c * pf)
    gg = jnp.tanh(g[:, 3 * H:])
    c_new = f * c + i * gg
    o = jnn.sigmoid(g[:, 2 * H:3 * H] + c_new * po)
    c_out_ref[:] = c_new.astype(c_out_ref.dtype)
    h_out_ref[:] = (o * jnp.tanh(c_new)).astype(h_out_ref.dtype)


def _graves_gates_bwd_kernel(gates_ref, c_ref, pi_ref, pf_ref, po_ref,
                             dc_ref, dh_ref,
                             dgates_ref, dcprev_ref, dpi_ref, dpf_ref,
                             dpo_ref):
    """Backward with remat-style recompute (no forward activations kept)."""
    import jax.nn as jnn
    acc = jnp.promote_types(gates_ref.dtype, jnp.float32)
    g = gates_ref[:].astype(acc)
    c = c_ref[:].astype(acc)
    H = c.shape[-1]
    pi, pf, po = (r[:].astype(acc) for r in (pi_ref, pf_ref, po_ref))
    dc_new_in = dc_ref[:].astype(acc)
    dh = dh_ref[:].astype(acc)
    one = jnp.ones((), g.dtype)
    i = jnn.sigmoid(g[:, :H] + c * pi)
    f = jnn.sigmoid(g[:, H:2 * H] + c * pf)
    gg = jnp.tanh(g[:, 3 * H:])
    c_new = f * c + i * gg
    o = jnn.sigmoid(g[:, 2 * H:3 * H] + c_new * po)
    t = jnp.tanh(c_new)
    dzo = dh * t * o * (one - o)           # grad wrt zo + c_new*po
    dct = dc_new_in + dh * o * (one - t * t) + dzo * po
    dzi = dct * gg * i * (one - i)         # grad wrt zi + c*pi
    dzf = dct * c * f * (one - f)          # grad wrt zf + c*pf
    dzg = dct * i * (one - gg * gg)
    from jax.experimental import pallas as pl
    dgates_ref[:] = jnp.concatenate([dzi, dzf, dzo, dzg],
                                    axis=-1).astype(dgates_ref.dtype)
    dcprev_ref[:] = (dct * f + dzi * pi + dzf * pf).astype(dcprev_ref.dtype)

    @pl.when(pl.program_id(0) == 0)
    def _():
        dpi_ref[:] = jnp.zeros_like(dpi_ref)
        dpf_ref[:] = jnp.zeros_like(dpf_ref)
        dpo_ref[:] = jnp.zeros_like(dpo_ref)

    dpi_ref[:] += jnp.sum(dzi * c, axis=0, keepdims=True)
    dpf_ref[:] += jnp.sum(dzf * c, axis=0, keepdims=True)
    dpo_ref[:] += jnp.sum(dzo * c_new, axis=0, keepdims=True)


@jax.custom_vjp
def graves_gates_pallas(gates, c, pi, pf, po):
    """gates (B, 4H) pre-activations [i|f|o|g] (NO peephole terms added),
    c (B, H), pi/pf/po (H,) peephole weights -> (c_new, h_new).

    One VMEM-resident kernel for the whole Graves cell update — the
    elementwise chain between the scan's two MXU matmuls (ref
    LSTMHelpers.java:200 fwd; cuDNN fuses exactly this span)."""
    from jax.experimental import pallas as pl
    B, H = c.shape
    p2 = lambda v: v.reshape(1, H)
    grid, tb, Bp = _batch_grid(B)
    c_new, h_new = pl.pallas_call(
        _graves_gates_kernel,
        grid=grid,
        in_specs=[pl.BlockSpec((tb, 4 * H), lambda b: (b, 0)),
                  pl.BlockSpec((tb, H), lambda b: (b, 0)),
                  pl.BlockSpec((1, H), lambda b: (0, 0)),
                  pl.BlockSpec((1, H), lambda b: (0, 0)),
                  pl.BlockSpec((1, H), lambda b: (0, 0))],
        out_specs=(pl.BlockSpec((tb, H), lambda b: (b, 0)),
                   pl.BlockSpec((tb, H), lambda b: (b, 0))),
        out_shape=(jax.ShapeDtypeStruct((Bp, H), c.dtype),
                   jax.ShapeDtypeStruct((Bp, H), c.dtype)),
        interpret=_interpret(),
    )(_pad_rows(gates, Bp), _pad_rows(c, Bp), p2(pi), p2(pf), p2(po))
    return c_new[:B], h_new[:B]


def _graves_gates_fwd(gates, c, pi, pf, po):
    return graves_gates_pallas(gates, c, pi, pf, po), (gates, c, pi, pf, po)


def _graves_gates_bwd(saved, cotangents):
    from jax.experimental import pallas as pl
    gates, c, pi, pf, po = saved
    dc_new, dh = cotangents
    B, H = c.shape
    p2 = lambda v: v.reshape(1, H)
    grid, tb, Bp = _batch_grid(B)
    acc = jnp.promote_types(c.dtype, jnp.float32)
    # padded cotangent rows are zero, so they contribute nothing to the
    # accumulated peephole gradients
    dgates, dc_prev, dpi, dpf, dpo = pl.pallas_call(
        _graves_gates_bwd_kernel,
        grid=grid,
        in_specs=[pl.BlockSpec((tb, 4 * H), lambda b: (b, 0)),
                  pl.BlockSpec((tb, H), lambda b: (b, 0)),
                  pl.BlockSpec((1, H), lambda b: (0, 0)),
                  pl.BlockSpec((1, H), lambda b: (0, 0)),
                  pl.BlockSpec((1, H), lambda b: (0, 0)),
                  pl.BlockSpec((tb, H), lambda b: (b, 0)),
                  pl.BlockSpec((tb, H), lambda b: (b, 0))],
        out_specs=(pl.BlockSpec((tb, 4 * H), lambda b: (b, 0)),
                   pl.BlockSpec((tb, H), lambda b: (b, 0)),
                   pl.BlockSpec((1, H), lambda b: (0, 0)),
                   pl.BlockSpec((1, H), lambda b: (0, 0)),
                   pl.BlockSpec((1, H), lambda b: (0, 0))),
        out_shape=(jax.ShapeDtypeStruct((Bp, 4 * H), gates.dtype),
                   jax.ShapeDtypeStruct((Bp, H), c.dtype),
                   jax.ShapeDtypeStruct((1, H), acc),
                   jax.ShapeDtypeStruct((1, H), acc),
                   jax.ShapeDtypeStruct((1, H), acc)),
        interpret=_interpret(),
    )(_pad_rows(gates, Bp), _pad_rows(c, Bp), p2(pi), p2(pf), p2(po),
      _pad_rows(dc_new, Bp), _pad_rows(dh, Bp))
    return (dgates[:B], dc_prev[:B], dpi.reshape(H).astype(pi.dtype),
            dpf.reshape(H).astype(pf.dtype), dpo.reshape(H).astype(po.dtype))


graves_gates_pallas.defvjp(_graves_gates_fwd, _graves_gates_bwd)
register_helper("graves_lstm_gates")(graves_gates_pallas)


def graves_gates_xla(gates, c, pi, pf, po):
    """Fallback: plain jnp peephole cell update (same math as the layer)."""
    H = c.shape[-1]
    i = jax.nn.sigmoid(gates[:, :H] + c * pi)
    f = jax.nn.sigmoid(gates[:, H:2 * H] + c * pf)
    gg = jnp.tanh(gates[:, 3 * H:])
    c_new = f * c + i * gg
    o = jax.nn.sigmoid(gates[:, 2 * H:3 * H] + c_new * po)
    return c_new, o * jnp.tanh(c_new)


# ------------------------------------------------------------ threshold encode


def _make_threshold_kernel(thr: float):
    def kernel(acc_ref, msg_ref, res_ref):
        acc = acc_ref[:]
        mask = jnp.abs(acc) >= thr
        msg = jnp.where(mask, jnp.sign(acc) * thr, 0.0).astype(acc.dtype)
        msg_ref[:] = msg
        res_ref[:] = acc - msg
    return kernel


@register_helper("threshold_encode")
@functools.partial(jax.jit, static_argnames=("threshold",))
def threshold_encode_pallas(update: jnp.ndarray, residual: jnp.ndarray,
                            threshold: float):
    """Ternarize update+residual to {-t, 0, +t} with residual carry — same
    contract as parallel/accumulation.threshold_encode. The threshold is a
    compile-time constant (one compiled kernel per threshold value, exactly like
    the reference's fixed EncodingHandler threshold)."""
    from jax.experimental import pallas as pl
    n = update.shape[0]
    lanes = 128
    rows = max(8, (n + lanes - 1) // lanes)
    acc = update + residual
    acc2d = jnp.zeros((rows * lanes,), update.dtype).at[:n].set(acc) \
        .reshape(rows, lanes)
    msg2d, res2d = pl.pallas_call(
        _make_threshold_kernel(float(threshold)),
        out_shape=(jax.ShapeDtypeStruct((rows, lanes), update.dtype),
                   jax.ShapeDtypeStruct((rows, lanes), update.dtype)),
        interpret=_interpret(),
    )(acc2d)
    return msg2d.reshape(-1)[:n], res2d.reshape(-1)[:n]
