"""Pallas TPU kernels behind the helper seam.

Two hot ops where hand-tiling pays (everything else is left to XLA fusion):

- `lstm_gates`: the per-timestep gate nonlinearity + cell update of the LSTM scan
  body (ref nn/layers/recurrent/LSTMHelpers.java:200 — the reference's cudnn
  fast path). One VMEM-resident kernel computes sigmoid/tanh gates and the new
  (c, h) for a batch tile, replacing four separate slice+activation HLOs between
  the two MXU matmuls.
- `threshold_encode`: the gradient-compression quantizer of the SHARED_GRADIENTS
  path (ref EncodingHandler / threshold encoding) — elementwise ternarize with
  residual carry, the "quantization kernels" pattern from the Pallas guide.

Both run with `interpret=True` off-TPU so the CPU test mesh exercises the same
code path, and both have pure-jnp fallbacks wired through the seam.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.ops.helpers import register_helper


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


# ------------------------------------------------------------------ lstm gates


def _lstm_gates_kernel(gates_ref, c_ref, c_out_ref, h_out_ref):
    import jax.nn as jnn
    g = gates_ref[:]                       # (TB, 4H)
    c = c_ref[:]                           # (TB, H)
    H = c.shape[-1]
    zi = jnn.sigmoid(g[:, :H])
    zf = jnn.sigmoid(g[:, H:2 * H])
    zo = jnn.sigmoid(g[:, 2 * H:3 * H])
    zg = jnp.tanh(g[:, 3 * H:])
    c_new = zf * c + zi * zg
    c_out_ref[:] = c_new
    h_out_ref[:] = zo * jnp.tanh(c_new)


def _lstm_gates_bwd_kernel(gates_ref, c_ref, dc_ref, dh_ref,
                           dgates_ref, dcprev_ref):
    """Backward: recompute activations from the saved inputs (remat-style — no
    forward activations are kept in HBM), then the closed-form gate gradients."""
    import jax.nn as jnn
    g = gates_ref[:]
    c = c_ref[:]
    dc_new = dc_ref[:]
    dh = dh_ref[:]
    H = c.shape[-1]
    i = jnn.sigmoid(g[:, :H])
    f = jnn.sigmoid(g[:, H:2 * H])
    o = jnn.sigmoid(g[:, 2 * H:3 * H])
    gg = jnp.tanh(g[:, 3 * H:])
    c_new = f * c + i * gg
    t = jnp.tanh(c_new)
    do = dh * t
    dct = dc_new + dh * o * (1.0 - t * t)
    dzi = dct * gg * i * (1.0 - i)
    dzf = dct * c * f * (1.0 - f)
    dzo = do * o * (1.0 - o)
    dzg = dct * i * (1.0 - gg * gg)
    dgates_ref[:] = jnp.concatenate([dzi, dzf, dzo, dzg], axis=-1)
    dcprev_ref[:] = dct * f


@jax.custom_vjp
def lstm_gates_pallas(gates: jnp.ndarray, c: jnp.ndarray):
    """gates (B, 4H) pre-activations [i|f|o|g], c (B, H) -> (c_new, h_new).

    Gate order matches nn/conf/layers/recurrent.py:67-70 (zi, zf, zo, zg).
    Differentiable via a custom VJP whose backward is itself a Pallas kernel
    (the guide's Custom VJP pattern)."""
    from jax.experimental import pallas as pl
    B, H = c.shape
    c_new, h_new = pl.pallas_call(
        _lstm_gates_kernel,
        out_shape=(jax.ShapeDtypeStruct((B, H), c.dtype),
                   jax.ShapeDtypeStruct((B, H), c.dtype)),
        interpret=_interpret(),
    )(gates, c)
    return c_new, h_new


def _lstm_gates_fwd(gates, c):
    return lstm_gates_pallas(gates, c), (gates, c)


def _lstm_gates_bwd(saved, cotangents):
    from jax.experimental import pallas as pl
    gates, c = saved
    dc_new, dh = cotangents
    B, H = c.shape
    dgates, dc_prev = pl.pallas_call(
        _lstm_gates_bwd_kernel,
        out_shape=(jax.ShapeDtypeStruct((B, 4 * H), gates.dtype),
                   jax.ShapeDtypeStruct((B, H), c.dtype)),
        interpret=_interpret(),
    )(gates, c, dc_new, dh)
    return dgates, dc_prev


lstm_gates_pallas.defvjp(_lstm_gates_fwd, _lstm_gates_bwd)
register_helper("lstm_gates")(lstm_gates_pallas)


def lstm_gates_xla(gates: jnp.ndarray, c: jnp.ndarray):
    """Fallback: plain jnp (what the layer inlines today)."""
    H = c.shape[-1]
    zi = jax.nn.sigmoid(gates[:, :H])
    zf = jax.nn.sigmoid(gates[:, H:2 * H])
    zo = jax.nn.sigmoid(gates[:, 2 * H:3 * H])
    zg = jnp.tanh(gates[:, 3 * H:])
    c_new = zf * c + zi * zg
    return c_new, zo * jnp.tanh(c_new)


# ------------------------------------------------------------ threshold encode


def _make_threshold_kernel(thr: float):
    def kernel(acc_ref, msg_ref, res_ref):
        acc = acc_ref[:]
        mask = jnp.abs(acc) >= thr
        msg = jnp.where(mask, jnp.sign(acc) * thr, 0.0).astype(acc.dtype)
        msg_ref[:] = msg
        res_ref[:] = acc - msg
    return kernel


@register_helper("threshold_encode")
@functools.partial(jax.jit, static_argnames=("threshold",))
def threshold_encode_pallas(update: jnp.ndarray, residual: jnp.ndarray,
                            threshold: float):
    """Ternarize update+residual to {-t, 0, +t} with residual carry — same
    contract as parallel/accumulation.threshold_encode. The threshold is a
    compile-time constant (one compiled kernel per threshold value, exactly like
    the reference's fixed EncodingHandler threshold)."""
    from jax.experimental import pallas as pl
    n = update.shape[0]
    lanes = 128
    rows = max(8, (n + lanes - 1) // lanes)
    acc = update + residual
    acc2d = jnp.zeros((rows * lanes,), update.dtype).at[:n].set(acc) \
        .reshape(rows, lanes)
    msg2d, res2d = pl.pallas_call(
        _make_threshold_kernel(float(threshold)),
        out_shape=(jax.ShapeDtypeStruct((rows, lanes), update.dtype),
                   jax.ShapeDtypeStruct((rows, lanes), update.dtype)),
        interpret=_interpret(),
    )(acc2d)
    return msg2d.reshape(-1)[:n], res2d.reshape(-1)[:n]
