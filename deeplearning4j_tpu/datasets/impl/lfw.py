"""LFW (Labeled Faces in the Wild) dataset iterator.

Parity: ref deeplearning4j-core/.../datasets/iterator/impl/LFWDataSetIterator.java +
base/LFWLoader.java (per-person directories of face jpgs; label = person).
Resolution: a real lfw image tree under $LFW_DIR or ~/.deeplearning4j/lfw (decoded
through the datavec ImageRecordReader), else deterministic synthetic "faces"
(per-identity smooth eigenface-ish blobs) with the requested shape.
"""
from __future__ import annotations

import os
from pathlib import Path
from typing import Optional, Sequence, Tuple

import numpy as np

from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.datasets.iterators import DataSetIterator


def _synthetic_faces(n: int, num_people: int, h: int, w: int, channels: int,
                     seed: int) -> Tuple[np.ndarray, np.ndarray]:
    rng = np.random.RandomState(seed)
    proto_rng = np.random.RandomState(777)
    yy, xx = np.mgrid[0:h, 0:w]
    protos = []
    for p in range(num_people):
        img = np.zeros((h, w), np.float32)
        # oval head + two "eyes" + identity-specific blobs
        cy, cx = h / 2 + proto_rng.uniform(-3, 3), w / 2 + proto_rng.uniform(-3, 3)
        img += np.exp(-(((yy - cy) / (h * 0.32)) ** 2
                        + ((xx - cx) / (w * 0.24)) ** 2) * 3)
        for _ in range(4):
            by, bx = proto_rng.uniform(0.2, 0.8, 2)
            bs = proto_rng.uniform(0.04, 0.12)
            img += 0.5 * np.exp(-(((yy / h - by) / bs) ** 2
                                  + ((xx / w - bx) / bs) ** 2))
        protos.append(np.clip(img / img.max(), 0, 1))
    labels = rng.randint(0, num_people, n)
    imgs = np.zeros((n, channels, h, w), np.float32)
    for i, p in enumerate(labels):
        base = protos[p] + rng.normal(0, 0.05, (h, w))
        imgs[i] = np.clip(np.broadcast_to(base, (channels, h, w)), 0, 1)
    return imgs, labels.astype(np.int64)


def load_lfw(num_examples: Optional[int] = None, image_shape=(1, 28, 28),
             num_people: int = 10, seed: int = 888):
    channels, h, w = image_shape
    base = Path(os.environ.get("LFW_DIR", "~/.deeplearning4j/lfw")).expanduser()
    if base.is_dir() and any(base.iterdir()):
        from deeplearning4j_tpu.datavec import FileSplit, ImageRecordReader
        rr = ImageRecordReader(h, w, channels)
        rr.initialize(FileSplit(str(base),
                                allowed_extensions=(".jpg", ".jpeg", ".png")))
        xs, ys = [], []
        for rec in rr:
            xs.append(rec[0] / 255.0)
            ys.append(int(rec[1]))
            if num_examples is not None and len(xs) >= num_examples:
                break
        return (np.stack(xs).astype(np.float32), np.asarray(ys, np.int64),
                rr.num_labels())
    n = num_examples or 2048
    imgs, labels = _synthetic_faces(n, num_people, h, w, channels, seed)
    return imgs, labels, num_people


class LFWDataSetIterator(DataSetIterator):
    """(ref LFWDataSetIterator(batch, numExamples, imgDim...))"""

    def __init__(self, batch: int = 64, num_examples: Optional[int] = None,
                 image_shape=(1, 28, 28), num_people: int = 10, seed: int = 888):
        self._batch = int(batch)
        self.x, y, self.num_people = load_lfw(num_examples, image_shape,
                                              num_people, seed)
        self.y = np.eye(self.num_people, dtype=np.float32)[y]

    def __iter__(self):
        for s in range(0, self.x.shape[0], self._batch):
            yield DataSet(self.x[s:s + self._batch], self.y[s:s + self._batch])

    def reset(self):
        pass

    def batch(self):
        return self._batch

    def total_outcomes(self):
        return self.num_people

    def input_columns(self):
        return int(np.prod(self.x.shape[1:]))
