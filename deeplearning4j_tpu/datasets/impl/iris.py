"""Iris dataset iterator.

Parity: ref deeplearning4j-core/.../datasets/iterator/impl/IrisDataSetIterator.java
+ base/IrisUtils.java (embedded 150-sample Fisher iris table). Resolution order:
scikit-learn's bundled copy of the REAL dataset (local, zero egress), else a
deterministic Gaussian stand-in built from the published per-class feature means.
"""
from __future__ import annotations

from typing import Tuple

import numpy as np

from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.datasets.iterators import DataSetIterator

# published per-class means/stds (setosa, versicolor, virginica) x 4 features
_MEANS = np.asarray([[5.006, 3.428, 1.462, 0.246],
                     [5.936, 2.770, 4.260, 1.326],
                     [6.588, 2.974, 5.552, 2.026]])
_STDS = np.asarray([[0.352, 0.379, 0.174, 0.105],
                    [0.516, 0.314, 0.470, 0.198],
                    [0.636, 0.322, 0.552, 0.275]])


def load_iris() -> Tuple[np.ndarray, np.ndarray]:
    """(features (150,4) float32, labels (150,) int) — 50 per class, class-major
    order like the reference's embedded table."""
    try:
        from sklearn.datasets import load_iris as _sk
        d = _sk()
        return d.data.astype(np.float32), d.target.astype(np.int64)
    except Exception:
        rng = np.random.RandomState(123)
        xs, ys = [], []
        for c in range(3):
            xs.append(_MEANS[c] + _STDS[c] * rng.randn(50, 4))
            ys.append(np.full(50, c))
        return (np.concatenate(xs).astype(np.float32),
                np.concatenate(ys).astype(np.int64))


class IrisDataSetIterator(DataSetIterator):
    """(ref IrisDataSetIterator(batch, numExamples))"""

    def __init__(self, batch: int = 150, num_examples: int = 150):
        self._batch = int(batch)
        x, y = load_iris()
        n = min(int(num_examples), x.shape[0])
        self.x = x[:n]
        self.y = np.eye(3, dtype=np.float32)[y[:n]]

    def __iter__(self):
        for s in range(0, self.x.shape[0], self._batch):
            yield DataSet(self.x[s:s + self._batch], self.y[s:s + self._batch])

    def reset(self):
        pass

    def batch(self):
        return self._batch

    def total_outcomes(self):
        return 3

    def input_columns(self):
        return 4
