"""EMNIST dataset iterator.

Parity: ref deeplearning4j-core/.../datasets/iterator/impl/EmnistDataSetIterator.java
(Set enum: COMPLETE/MERGE/BALANCED/LETTERS/DIGITS/MNIST with per-set class counts).
Resolution: real EMNIST IDX files under $EMNIST_DIR or ~/.deeplearning4j/emnist
(gzip or raw, reusing the MNIST IDX reader), else the deterministic synthetic
pattern generator with the set's class count.
"""
from __future__ import annotations

import os
from pathlib import Path
from typing import Optional

import numpy as np

from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.datasets.impl.mnist import (
    _find_idx, _read_idx, _synthetic_digits)
from deeplearning4j_tpu.datasets.iterators import DataSetIterator


class EmnistSet:
    """(ref EmnistDataSetIterator.Set + numLabels mapping)"""
    COMPLETE = "complete"      # 62 classes
    MERGE = "merge"            # 47
    BALANCED = "balanced"      # 47
    LETTERS = "letters"        # 26
    DIGITS = "digits"          # 10
    MNIST = "mnist"            # 10

    NUM_LABELS = {COMPLETE: 62, MERGE: 47, BALANCED: 47, LETTERS: 26,
                  DIGITS: 10, MNIST: 10}


def num_labels(dataset_set: str) -> int:
    return EmnistSet.NUM_LABELS[dataset_set]


def load_emnist(dataset_set: str = EmnistSet.BALANCED, train: bool = True,
                num_examples: Optional[int] = None, seed: int = 321):
    classes = num_labels(dataset_set)
    base = Path(os.environ.get("EMNIST_DIR",
                               "~/.deeplearning4j/emnist")).expanduser()
    split = "train" if train else "test"
    ip = _find_idx(base, [f"emnist-{dataset_set}-{split}-images-idx3-ubyte"])
    lp = _find_idx(base, [f"emnist-{dataset_set}-{split}-labels-idx1-ubyte"])
    if ip is not None and lp is not None:
        imgs = _read_idx(ip).astype(np.float32) / 255.0
        labels = _read_idx(lp).astype(np.int64)
        # EMNIST labels can be 1-based (letters); shift to 0-based
        if labels.min() == 1:
            labels = labels - 1
        imgs = imgs.reshape(imgs.shape[0], -1)
    else:
        n = num_examples or (8192 if train else 2048)
        imgs, labels = _synthetic_digits(n, seed if train else seed + 1,
                                         classes=classes)
    if num_examples is not None:
        imgs, labels = imgs[:num_examples], labels[:num_examples]
    return imgs, labels, classes


class EmnistDataSetIterator(DataSetIterator):
    """(ref EmnistDataSetIterator(Set, batch, train))"""

    def __init__(self, dataset_set: str = EmnistSet.BALANCED, batch: int = 128,
                 train: bool = True, num_examples: Optional[int] = None,
                 seed: int = 321):
        self._batch = int(batch)
        self.x, y, self.classes = load_emnist(dataset_set, train, num_examples,
                                              seed)
        self.y = np.eye(self.classes, dtype=np.float32)[y]

    def __iter__(self):
        for s in range(0, self.x.shape[0], self._batch):
            yield DataSet(self.x[s:s + self._batch], self.y[s:s + self._batch])

    def reset(self):
        pass

    def batch(self):
        return self._batch

    def total_outcomes(self):
        return self.classes

    def input_columns(self):
        return self.x.shape[1]
