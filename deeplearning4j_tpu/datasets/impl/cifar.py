"""CIFAR-10 dataset iterator.

Parity: ref deeplearning4j-core/.../datasets/iterator/impl/CifarDataSetIterator.java
+ base/CifarLoader.java (binary-batch format: 1 label byte + 3072 pixel bytes per
record). Resolution: real data_batch_*.bin / test_batch.bin under $CIFAR_DIR or
~/.deeplearning4j/cifar10 (the cifar-10-batches-bin layout), else a deterministic
synthetic set (class-dependent color gradients + texture) with identical shapes.
"""
from __future__ import annotations

import os
from pathlib import Path
from typing import Optional, Tuple

import numpy as np

from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.datasets.iterators import DataSetIterator

NUM_LABELS = 10
RECORD_BYTES = 1 + 3072


def _read_bin(path: Path) -> Tuple[np.ndarray, np.ndarray]:
    raw = np.frombuffer(path.read_bytes(), np.uint8)
    recs = raw.reshape(-1, RECORD_BYTES)
    labels = recs[:, 0].astype(np.int64)
    imgs = recs[:, 1:].reshape(-1, 3, 32, 32).astype(np.float32) / 255.0
    return imgs, labels


def _synthetic_cifar(n: int, seed: int) -> Tuple[np.ndarray, np.ndarray]:
    """Class = distinctive mean color + oriented sinusoidal texture."""
    rng = np.random.RandomState(seed)
    proto_rng = np.random.RandomState(999)
    yy, xx = np.mgrid[0:32, 0:32] / 32.0
    protos = []
    for c in range(NUM_LABELS):
        color = proto_rng.rand(3, 1, 1)
        freq, angle = proto_rng.uniform(2, 8), proto_rng.uniform(0, np.pi)
        tex = 0.25 * np.sin(2 * np.pi * freq *
                            (np.cos(angle) * xx + np.sin(angle) * yy))
        protos.append(np.clip(color + tex[None], 0, 1).astype(np.float32))
    labels = rng.randint(0, NUM_LABELS, n)
    imgs = np.zeros((n, 3, 32, 32), np.float32)
    for i, c in enumerate(labels):
        imgs[i] = np.clip(protos[c] + rng.normal(0, 0.08, (3, 32, 32)), 0, 1)
    return imgs, labels.astype(np.int64)


def load_cifar(train: bool = True, num_examples: Optional[int] = None,
               seed: int = 555) -> Tuple[np.ndarray, np.ndarray]:
    """((n,3,32,32) float32 CHW in [0,1], labels (n,))."""
    base = Path(os.environ.get("CIFAR_DIR",
                               "~/.deeplearning4j/cifar10")).expanduser()
    for sub in ("", "cifar-10-batches-bin"):
        d = base / sub if sub else base
        names = ([f"data_batch_{i}.bin" for i in range(1, 6)] if train
                 else ["test_batch.bin"])
        paths = [d / nm for nm in names]
        if all(p.exists() for p in paths):
            parts = [_read_bin(p) for p in paths]
            imgs = np.concatenate([p[0] for p in parts])
            labels = np.concatenate([p[1] for p in parts])
            break
    else:
        n = num_examples or (8192 if train else 2048)
        imgs, labels = _synthetic_cifar(n, seed if train else seed + 1)
    if num_examples is not None:
        imgs, labels = imgs[:num_examples], labels[:num_examples]
    return imgs, labels


class CifarDataSetIterator(DataSetIterator):
    """(ref CifarDataSetIterator(batch, numExamples, train)) — CHW features for
    InputType.convolutional(32, 32, 3)."""

    def __init__(self, batch: int = 128, num_examples: Optional[int] = None,
                 train: bool = True, seed: int = 555):
        self._batch = int(batch)
        self.x, y = load_cifar(train, num_examples, seed)
        self.y = np.eye(NUM_LABELS, dtype=np.float32)[y]

    def __iter__(self):
        for s in range(0, self.x.shape[0], self._batch):
            yield DataSet(self.x[s:s + self._batch], self.y[s:s + self._batch])

    def reset(self):
        pass

    def batch(self):
        return self._batch

    def total_outcomes(self):
        return NUM_LABELS

    def input_columns(self):
        return 3 * 32 * 32
