"""MNIST fetcher + iterator.

Parity: ref deeplearning4j-core base/MnistFetcher.java (download+cache) and
datasets/iterator/impl/MnistDataSetIterator.java, datasets/mnist/ (IDX readers).

This environment has zero network egress, so the fetcher resolves data in order:
1. real IDX files under $MNIST_DIR or ~/.deeplearning4j/mnist (same cache layout the
   reference uses) — gzip or raw;
2. a deterministic procedurally-generated digit set (class-dependent stroke patterns +
   noise + jitter) with the same shapes/dtypes, adequate for convergence tests.
"""
from __future__ import annotations

import gzip
import os
import struct
from pathlib import Path
from typing import Optional, Tuple

import numpy as np

from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.datasets.iterators import DataSetIterator


def _read_idx(path: Path) -> np.ndarray:
    opener = gzip.open if path.suffix == ".gz" else open
    with opener(path, "rb") as f:
        data = f.read()
    zeros, dtype_code, ndim = data[0:2], data[2], data[3]
    dims = struct.unpack(f">{ndim}I", data[4:4 + 4 * ndim])
    return np.frombuffer(data, np.uint8, offset=4 + 4 * ndim).reshape(dims)


def _find_idx(base: Path, names) -> Optional[Path]:
    for n in names:
        for suffix in ("", ".gz"):
            p = base / (n + suffix)
            if p.exists():
                return p
    return None


def load_mnist(train: bool = True, num_examples: Optional[int] = None, seed: int = 123
               ) -> Tuple[np.ndarray, np.ndarray]:
    """Returns (images (n, 784) float32 in [0,1], labels (n,) int)."""
    base = Path(os.environ.get("MNIST_DIR", "~/.deeplearning4j/mnist")).expanduser()
    img_names = (["train-images-idx3-ubyte", "train-images.idx3-ubyte"] if train
                 else ["t10k-images-idx3-ubyte", "t10k-images.idx3-ubyte"])
    lbl_names = (["train-labels-idx1-ubyte", "train-labels.idx1-ubyte"] if train
                 else ["t10k-labels-idx1-ubyte", "t10k-labels.idx1-ubyte"])
    ip, lp = _find_idx(base, img_names), _find_idx(base, lbl_names)
    if ip is not None and lp is not None:
        imgs = labels = None
        if ip.suffix != ".gz" and lp.suffix != ".gz":
            try:  # native C++ codec fast path (native/dl4jtpu_io.cpp)
                from deeplearning4j_tpu.native import (native_available,
                                                       read_idx_native)
                if native_available():
                    imgs = read_idx_native(str(ip), normalize=True)
                    labels = read_idx_native(
                        str(lp), normalize=False).reshape(-1).astype(np.int64)
            except Exception:
                imgs = labels = None
        if imgs is None:
            imgs = _read_idx(ip).astype(np.float32) / 255.0
            imgs = imgs.reshape(imgs.shape[0], -1)
            labels = _read_idx(lp).astype(np.int64)
    else:
        n = num_examples or (8192 if train else 2048)
        imgs, labels = _synthetic_digits(n, seed if train else seed + 1)
    if num_examples is not None:
        imgs, labels = imgs[:num_examples], labels[:num_examples]
    return imgs, labels


def _synthetic_digits(n: int, seed: int, classes: int = 10
                      ) -> Tuple[np.ndarray, np.ndarray]:
    """Deterministic learnable stand-in: each class = fixed smooth prototype pattern,
    samples add pixel noise and ±2px translation. `classes` supports the EMNIST
    splits (up to 62 classes)."""
    rng = np.random.RandomState(seed)
    proto_rng = np.random.RandomState(1234)  # prototypes fixed across train/test
    protos = []
    yy, xx = np.mgrid[0:28, 0:28]
    for c in range(classes):
        img = np.zeros((28, 28), np.float32)
        for _ in range(3):  # a few gaussian strokes per class
            cy, cx = proto_rng.uniform(6, 22, 2)
            sy, sx = proto_rng.uniform(2, 6, 2)
            img += np.exp(-(((yy - cy) / sy) ** 2 + ((xx - cx) / sx) ** 2))
        protos.append(np.clip(img / img.max(), 0, 1))
    labels = rng.randint(0, classes, n)
    imgs = np.zeros((n, 28, 28), np.float32)
    for i, c in enumerate(labels):
        dy, dx = rng.randint(-2, 3, 2)
        img = np.roll(np.roll(protos[c], dy, axis=0), dx, axis=1)
        img = img + rng.normal(0, 0.15, (28, 28)).astype(np.float32)
        imgs[i] = np.clip(img, 0, 1)
    return imgs.reshape(n, 784), labels.astype(np.int64)


class MnistDataSetIterator(DataSetIterator):
    """(ref datasets/iterator/impl/MnistDataSetIterator.java) — yields flat 784 features
    + one-hot 10-class labels, matching InputType.convolutionalFlat consumption."""

    def __init__(self, batch: int, train: bool = True, num_examples: Optional[int] = None,
                 seed: int = 123, shuffle: bool = True):
        self._batch = int(batch)
        imgs, labels = load_mnist(train, num_examples, seed)
        self.features = imgs
        self.labels = np.eye(10, dtype=np.float32)[labels]
        self._shuffle = shuffle
        self._seed = seed
        self._epoch = 0

    def __iter__(self):
        n = self.features.shape[0]
        idx = np.arange(n)
        if self._shuffle:
            np.random.RandomState(self._seed + self._epoch).shuffle(idx)
        self._epoch += 1
        for i in range(0, n - self._batch + 1, self._batch):
            sel = idx[i:i + self._batch]
            yield DataSet(self.features[sel], self.labels[sel])

    def batch(self):
        return self._batch

    def total_outcomes(self):
        return 10

    def input_columns(self):
        return 784
