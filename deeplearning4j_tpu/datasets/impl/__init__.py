"""Dataset fetchers/iterators (ref deeplearning4j-core datasets/iterator/impl/)."""
from deeplearning4j_tpu.datasets.impl.mnist import MnistDataSetIterator
from deeplearning4j_tpu.datasets.impl.iris import IrisDataSetIterator, load_iris
from deeplearning4j_tpu.datasets.impl.emnist import (
    EmnistDataSetIterator, EmnistSet, load_emnist)
from deeplearning4j_tpu.datasets.impl.cifar import (
    CifarDataSetIterator, load_cifar)
from deeplearning4j_tpu.datasets.impl.lfw import LFWDataSetIterator, load_lfw

__all__ = ["MnistDataSetIterator", "IrisDataSetIterator", "load_iris",
           "EmnistDataSetIterator", "EmnistSet", "load_emnist",
           "CifarDataSetIterator", "load_cifar", "LFWDataSetIterator",
           "load_lfw"]
