"""Dataset iterator algebra + async prefetch.

Parity: ref datasets/iterator/ — AsyncDataSetIterator.java:30 (AsyncPrefetchThread
:382-406), ListDataSetIterator, ExistingDataSetIterator, EarlyTerminationDataSetIterator,
MultipleEpochsIterator, SamplingDataSetIterator, INDArrayDataSetIterator,
impl/BenchmarkDataSetIterator.java:20. Iterators are plain Python iterables yielding
`DataSet`s; AsyncDataSetIterator runs a background thread that stages host→device
transfer ahead of the training loop (the TPU infeed double-buffer).
"""
from __future__ import annotations

import queue
import threading
from typing import Iterable, Iterator, List, Optional

import numpy as np

from deeplearning4j_tpu.datasets.dataset import DataSet


class DataSetIterator:
    """Base: iterable over DataSets with reset()."""
    async_supported = True

    def __iter__(self) -> Iterator[DataSet]:
        raise NotImplementedError

    def reset(self):
        pass

    def batch(self) -> int:
        return -1

    def total_outcomes(self) -> int:
        return -1

    def input_columns(self) -> int:
        return -1


class ListDataSetIterator(DataSetIterator):
    """(ref datasets/iterator/impl/ListDataSetIterator.java)"""

    def __init__(self, datasets: List[DataSet], batch: Optional[int] = None):
        if batch is not None and len(datasets) == 1:
            datasets = datasets[0].batch_by(batch)
        self._list = list(datasets)
        self._batch = batch or (self._list[0].num_examples() if self._list else -1)

    def __iter__(self):
        return iter(self._list)

    def batch(self):
        return self._batch

    def __len__(self):
        return len(self._list)


class INDArrayDataSetIterator(DataSetIterator):
    """Iterate (features, labels) pairs in minibatches
    (ref datasets/iterator/INDArrayDataSetIterator.java)."""

    def __init__(self, features, labels, batch_size: int):
        self.features = np.asarray(features)
        self.labels = np.asarray(labels)
        self.batch_size = int(batch_size)

    def __iter__(self):
        n = self.features.shape[0]
        for i in range(0, n, self.batch_size):
            yield DataSet(self.features[i:i + self.batch_size],
                          self.labels[i:i + self.batch_size])

    def batch(self):
        return self.batch_size


class ExistingDataSetIterator(DataSetIterator):
    """Wrap any iterable of DataSets (ref ExistingDataSetIterator.java)."""

    def __init__(self, iterable: Iterable[DataSet]):
        self._iterable = iterable

    def __iter__(self):
        return iter(self._iterable)


class EarlyTerminationDataSetIterator(DataSetIterator):
    """Cap the number of minibatches (ref EarlyTerminationDataSetIterator.java)."""

    def __init__(self, underlying: DataSetIterator, max_batches: int):
        self.underlying = underlying
        self.max_batches = int(max_batches)

    def __iter__(self):
        for i, ds in enumerate(self.underlying):
            if i >= self.max_batches:
                break
            yield ds

    def reset(self):
        self.underlying.reset()


class MultipleEpochsIterator(DataSetIterator):
    """Repeat an iterator N times (ref MultipleEpochsIterator.java)."""

    def __init__(self, epochs: int, underlying: DataSetIterator):
        self.epochs = int(epochs)
        self.underlying = underlying

    def __iter__(self):
        for _ in range(self.epochs):
            self.underlying.reset()
            yield from self.underlying

    def reset(self):
        self.underlying.reset()


class SamplingDataSetIterator(DataSetIterator):
    """Sample with replacement from a base DataSet (ref SamplingDataSetIterator.java)."""

    def __init__(self, base: DataSet, batch_size: int, total_samples: int, seed: int = 123):
        self.base = base
        self.batch_size = int(batch_size)
        self.total_samples = int(total_samples)
        self.seed = seed
        self._epoch = 0

    def __iter__(self):
        rng = np.random.RandomState(self.seed + self._epoch)
        self._epoch += 1
        n = self.base.num_examples()
        emitted = 0
        while emitted < self.total_samples:
            take = min(self.batch_size, self.total_samples - emitted)
            idx = rng.randint(0, n, size=take)
            yield DataSet(np.asarray(self.base.features)[idx],
                          np.asarray(self.base.labels)[idx])
            emitted += take


class BenchmarkDataSetIterator(DataSetIterator):
    """Synthetic random tensors for benchmarking — isolates compute from ETL
    (ref datasets/iterator/impl/BenchmarkDataSetIterator.java:20)."""

    def __init__(self, feature_shape, num_classes: int, num_batches: int, seed: int = 42,
                 label_shape=None):
        rng = np.random.RandomState(seed)
        self.features = rng.rand(*feature_shape).astype(np.float32)
        if label_shape is None:
            label_shape = (feature_shape[0], num_classes)
        labels = np.zeros(label_shape, np.float32)
        cls = rng.randint(0, num_classes, size=feature_shape[0])
        if len(label_shape) == 2:
            labels[np.arange(feature_shape[0]), cls] = 1.0
        else:
            labels[np.arange(feature_shape[0]), cls, :] = 1.0
        self.labels = labels
        self.num_batches = int(num_batches)

    def __iter__(self):
        for _ in range(self.num_batches):
            yield DataSet(self.features, self.labels)


class AsyncDataSetIterator(DataSetIterator):
    """Background-thread prefetch with a bounded queue
    (ref AsyncDataSetIterator.java:30, AsyncPrefetchThread :382-406). Stages device_put
    so host→HBM transfer overlaps the previous step's compute."""
    async_supported = False  # don't double-wrap

    def __init__(self, underlying, queue_size: int = 4, device_prefetch: bool = True):
        self.underlying = underlying
        self.queue_size = int(queue_size)
        self.device_prefetch = device_prefetch

    def __iter__(self):
        q: "queue.Queue" = queue.Queue(maxsize=self.queue_size)
        _END = object()
        err: List[BaseException] = []
        stop = threading.Event()

        def _put(item) -> bool:
            # bounded put that aborts if the consumer went away — otherwise a full
            # queue would park this thread forever holding the underlying iterator
            while not stop.is_set():
                try:
                    q.put(item, timeout=0.1)
                    return True
                except queue.Full:
                    continue
            return False

        def producer():
            try:
                for ds in self.underlying:
                    if stop.is_set():
                        return
                    if self.device_prefetch:
                        try:
                            import jax
                            ds = DataSet(jax.device_put(np.asarray(ds.features)),
                                         jax.device_put(np.asarray(ds.labels)),
                                         ds.features_mask if ds.features_mask is None
                                         else jax.device_put(np.asarray(ds.features_mask)),
                                         ds.labels_mask if ds.labels_mask is None
                                         else jax.device_put(np.asarray(ds.labels_mask)))
                        except Exception:
                            pass
                    if not _put(ds):
                        return
            except BaseException as e:  # propagate into consumer
                err.append(e)
            finally:
                _put(_END)

        t = threading.Thread(target=producer, daemon=True)
        t.start()
        try:
            while True:
                item = q.get()
                if item is _END:
                    break
                yield item
        finally:
            # consumer abandoned (exception/early break): release the producer
            stop.set()
            t.join(timeout=5.0)
        if err:
            raise err[0]

    def reset(self):
        self.underlying.reset()
