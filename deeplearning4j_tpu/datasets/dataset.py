"""DataSet / MultiDataSet containers.

Parity: ND4J's DataSet/MultiDataSet consumed throughout the reference (features, labels,
optional per-example/per-timestep masks). Arrays are host numpy or device jnp; the
network's jitted step moves them to HBM on first use (async prefetch can pre-stage).
"""
from __future__ import annotations

from typing import List, Optional, Sequence

import numpy as np


class DataSet:
    def __init__(self, features, labels, features_mask=None, labels_mask=None):
        self.features = features
        self.labels = labels
        self.features_mask = features_mask
        self.labels_mask = labels_mask

    def num_examples(self) -> int:
        return int(self.features.shape[0])

    def split_test_and_train(self, n_train: int):
        a = DataSet(self.features[:n_train], self.labels[:n_train],
                    None if self.features_mask is None else self.features_mask[:n_train],
                    None if self.labels_mask is None else self.labels_mask[:n_train])
        b = DataSet(self.features[n_train:], self.labels[n_train:],
                    None if self.features_mask is None else self.features_mask[n_train:],
                    None if self.labels_mask is None else self.labels_mask[n_train:])
        return a, b

    def shuffle(self, seed: Optional[int] = None):
        rng = np.random.RandomState(seed)
        idx = rng.permutation(self.num_examples())
        self.features = np.asarray(self.features)[idx]
        self.labels = np.asarray(self.labels)[idx]
        if self.features_mask is not None:
            self.features_mask = np.asarray(self.features_mask)[idx]
        if self.labels_mask is not None:
            self.labels_mask = np.asarray(self.labels_mask)[idx]

    def batch_by(self, batch_size: int) -> List["DataSet"]:
        n = self.num_examples()
        return [DataSet(self.features[i:i + batch_size], self.labels[i:i + batch_size],
                        None if self.features_mask is None else self.features_mask[i:i + batch_size],
                        None if self.labels_mask is None else self.labels_mask[i:i + batch_size])
                for i in range(0, n, batch_size)]

    @staticmethod
    def merge(datasets: Sequence["DataSet"]) -> "DataSet":
        f = np.concatenate([np.asarray(d.features) for d in datasets])
        l = np.concatenate([np.asarray(d.labels) for d in datasets])
        fm = None
        lm = None
        if datasets and datasets[0].features_mask is not None:
            fm = np.concatenate([np.asarray(d.features_mask) for d in datasets])
        if datasets and datasets[0].labels_mask is not None:
            lm = np.concatenate([np.asarray(d.labels_mask) for d in datasets])
        return DataSet(f, l, fm, lm)


class MultiDataSet:
    """Multiple-input/multiple-output container (ref ND4J MultiDataSet; consumed by
    ComputationGraph.fit)."""

    def __init__(self, features, labels, features_masks=None, labels_masks=None):
        self.features = list(features) if isinstance(features, (list, tuple)) else [features]
        self.labels = list(labels) if isinstance(labels, (list, tuple)) else [labels]
        self.features_masks = features_masks
        self.labels_masks = labels_masks

    def num_examples(self) -> int:
        return int(self.features[0].shape[0])
