"""deeplearning4j_tpu — a TPU-native deep learning framework with the capability surface
of Deeplearning4j (reference: hafizusman530/deeplearning4j), redesigned for JAX/XLA:
declarative configs trace to single XLA computations, autodiff replaces hand-written
backprop, and parallelism is pjit/shard_map over a device mesh.
"""
from deeplearning4j_tpu.common.enums import (
    Activation, BackpropType, CacheMode, ConvolutionMode, GradientNormalization,
    LossFunction, OptimizationAlgorithm, PoolingType, WeightInit, WorkspaceMode)
from deeplearning4j_tpu.nn.conf.configuration import (
    ListBuilder, MultiLayerConfiguration, NeuralNetConfiguration)
from deeplearning4j_tpu.nn.conf.input_type import InputType
from deeplearning4j_tpu.nn.conf.layers.base import BaseLayerConf
from deeplearning4j_tpu.nn.conf.layers.feedforward import (
    ActivationLayer, AutoEncoder, DenseLayer, DropoutLayer, EmbeddingLayer, LossLayer,
    OutputLayer)
from deeplearning4j_tpu.nn.conf.layers.convolutional import (
    Convolution1DLayer, ConvolutionLayer, Cropping2D, Deconvolution2D,
    DepthwiseConvolutionLayer, GlobalPoolingLayer, SeparableConvolution2D,
    SpaceToDepthLayer, Subsampling1DLayer, SubsamplingLayer, Upsampling2D,
    ZeroPaddingLayer)
from deeplearning4j_tpu.nn.conf.layers.attention import SelfAttentionLayer
from deeplearning4j_tpu.nn.conf.layers.moe import MixtureOfExperts
from deeplearning4j_tpu.nn.conf.layers.normalization import (
    BatchNormalization, LocalResponseNormalization)
from deeplearning4j_tpu.nn.conf.layers.recurrent import (
    Bidirectional, GravesBidirectionalLSTM, GravesLSTM, LastTimeStep, LSTM,
    RnnOutputLayer, SimpleRnn)
from deeplearning4j_tpu.nn.conf.layers.variational import (
    BernoulliReconstructionDistribution, CenterLossOutputLayer,
    CompositeReconstructionDistribution, ExponentialReconstructionDistribution,
    GaussianReconstructionDistribution, LossFunctionWrapper, RBM,
    ReconstructionDistribution, VariationalAutoencoder)
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.nn.conf.graph_configuration import (
    ComputationGraphConfiguration, GraphBuilder)
from deeplearning4j_tpu.nn.graph.computation_graph import ComputationGraph
from deeplearning4j_tpu.nn.graph.vertices import (
    DuplicateToTimeSeriesVertex, ElementWiseVertex, GraphVertex, L2NormalizeVertex,
    L2Vertex, LastTimeStepVertex, MergeVertex, PoolHelperVertex, ReshapeVertex,
    ScaleVertex, ShiftVertex, StackVertex, SubsetVertex, UnstackVertex)
from deeplearning4j_tpu.nn.updater.updaters import (
    AdaDelta, AdaGrad, AdaMax, Adam, BaseUpdater, Nadam, Nesterovs, NoOp, RmsProp, Sgd)
from deeplearning4j_tpu.datasets.dataset import DataSet, MultiDataSet

__version__ = "0.1.0"
