"""Int8 quantization seam for the serving hot path (ISSUE 15).

Two independent knobs, both default-off:

- KV-cache quantization (`DL4J_TPU_KV_QUANT` / `ServingEngine(kv_quant=)`):
  the paged pool stores int8 payloads with PER-HEAD-PER-BLOCK symmetric
  scales (`scale = amax / 127` over each block's (block_size, head_dim)
  slice) kept in side arrays shaped (n_layers, num_blocks + 1, n_kv_heads)
  alongside the pool. Quantization happens at WRITE time inside the jitted
  cache mutations (serving/kv_cache.py routes every write — prefill,
  positional scatter, decode append, speculative append — through the
  helpers here); dequantization happens at READ time inside the paged
  flash-decode kernel (ops/decode_attention.py) or per gathered block in
  the dense oracles. A dequantized pool is never materialized.

- Weight-only int8 (`DL4J_TPU_W8` / `ServingEngine(quant_weights=)`): the
  decode-path attention projections (w_q/w_k/w_v/w_o) store int8 weights
  with per-OUTPUT-CHANNEL scales; activations stay float and the matmul
  dequantizes via one row-broadcast multiply on the (small) output —
  `y = (x @ w_int8) * scale` — so the weight stream moves 1/2 (vs bf16)
  to 1/8 (vs fp64) of the bytes at unchanged activation precision.

Both paths are pure jnp device math with ZERO host syncs (this module is
pinned in tests/test_sync_discipline.py). All quantize/dequantize
arithmetic runs in fp32 regardless of the session dtype so the int8
payload is platform- and x64-independent; the load-bearing bit-exactness
property the read-modify-write cache mutations rely on is

    round((q * s) / s) == q  for every int8 q and fp32 s > 0

(|q * s / s - q| is a few ulps of q <= 127, far below 0.5), so a
dequantize -> requantize round trip at an UNCHANGED scale reproduces the
payload bit-exactly. Cost model and accuracy gates: PERF.md "Quantized
KV cost model"; paper notes: PAPERS.md (KVQuant, AWQ).
"""
from __future__ import annotations

import os
from typing import Optional

import jax.numpy as jnp

SCALE_DTYPE = jnp.float32
PAYLOAD_DTYPE = jnp.int8
QMAX = 127.0


def resolve_kv_quant(kv_quant: Optional[bool]) -> bool:
    """Effective KV-quantization flag: explicit ctor value beats the
    `DL4J_TPU_KV_QUANT` env knob (default off)."""
    if kv_quant is None:
        return os.environ.get("DL4J_TPU_KV_QUANT", "0") \
            not in ("", "0", "off")
    return bool(kv_quant)


def resolve_quant_weights(quant_weights: Optional[bool]) -> bool:
    """Effective weight-only-int8 flag: explicit ctor value beats the
    `DL4J_TPU_W8` env knob (default off)."""
    if quant_weights is None:
        return os.environ.get("DL4J_TPU_W8", "0") not in ("", "0", "off")
    return bool(quant_weights)


# ------------------------------------------------------------- KV payloads
def kv_quantize(x):
    """Quantize KV blocks x (..., block_size, Hk, D) to int8 with
    per-head-per-block symmetric scales.

    Returns (payload int8 same shape, scales (..., Hk) fp32). The scale is
    amax / 127 over each block's (block_size, D) slice per kv head; an
    all-zero slice gets scale 1.0 (payload 0 dequantizes to 0 either way,
    and a nonzero scale keeps the requantize division well-defined)."""
    xf = jnp.asarray(x, SCALE_DTYPE)
    amax = jnp.max(jnp.abs(xf), axis=(-3, -1))            # (..., Hk)
    scale = jnp.where(amax > 0, amax / QMAX, jnp.ones_like(amax))
    q = jnp.clip(jnp.round(xf / scale[..., None, :, None]), -QMAX, QMAX)
    return q.astype(PAYLOAD_DTYPE), scale


def kv_dequantize(q, scale, dtype=None):
    """Dequantize int8 KV blocks q (..., block_size, Hk, D) with scales
    (..., Hk) back to float (fp32 unless `dtype` says otherwise)."""
    out = q.astype(SCALE_DTYPE) * scale[..., None, :, None].astype(
        SCALE_DTYPE)
    return out if dtype is None else out.astype(dtype)


# ------------------------------------------------------- weight-only int8
def quantize_weight(w):
    """Quantize a (n_in, n_out) projection weight to int8 with
    per-output-channel symmetric scales: (w_int8, (n_out,) fp32 scales)."""
    wf = jnp.asarray(w, SCALE_DTYPE)
    amax = jnp.max(jnp.abs(wf), axis=0)                   # (n_out,)
    scale = jnp.where(amax > 0, amax / QMAX, jnp.ones_like(amax))
    q = jnp.clip(jnp.round(wf / scale[None, :]), -QMAX, QMAX)
    return q.astype(PAYLOAD_DTYPE), scale


def int8_matmul(x, w_q, scale):
    """Weight-only int8 matmul: y = (x @ w_int8) * scale, the algebraic
    equal of x @ dequant(w) with the per-channel dequant folded into one
    broadcast multiply on the output. Activations and accumulation stay
    float (>= fp32); returns x.dtype."""
    acc = jnp.promote_types(x.dtype, jnp.float32)
    y = jnp.matmul(x.astype(acc), w_q.astype(acc))
    return (y * scale.astype(acc)).astype(x.dtype)
