"""Slot-based preallocated KV cache for autoregressive decode.

Beyond-reference (the 2017 reference has no incremental-decode path at all;
the attention stack recomputes all T x T scores per generated token). This is
the vLLM/Orca-shaped cache the serving engine (serving/engine.py) schedules
over: ONE preallocated pair of buffers

    k, v: (n_layers, max_seqs, max_len, n_kv_heads, head_dim)

plus a per-slot `lengths` vector. Every request lives in one SLOT for its
whole lifetime (prefill writes positions [0, prompt_len); decode appends one
position per iteration), so admission/eviction never reshapes device memory —
the jitted prefill/decode steps see fixed shapes and NEVER retrace as
requests come and go (the whole point: per-token XLA retracing costs more
than the decode math).

Device-side mutation is functional and jit-friendly:
- prefill: `lax.dynamic_update_slice` of a (T_pad, Hk, D) block at
  (layer, slot, 0) — slot is a TRACED index, so one compiled prefill serves
  every slot;
- decode append: a batched scatter `k.at[layer, arange(S), pos].set(k_t)` —
  each slot writes at its OWN position (ragged lengths), one op for the
  whole batch.

Safety invariant (why padded/stale writes are harmless): a position p of
slot s is VISIBLE to attention iff p < lengths[s], and lengths[s] only ever
reaches p+1 in the same decode step that wrote fresh k/v at p. Prefill may
therefore write its whole padded block and a freed slot needs no zeroing on
reuse — stale garbage beyond `lengths` is never attended to.

The same invariant is what licenses the engine's CHUNKED and OVERLAPPED
scheduling (engine decode_chunk / overlap): a slot that finishes mid-chunk
keeps appending for the rest of the chunk — and, under overlap, for up to
one more whole chunk, because the host scheduler runs on a one-chunk-stale
active mask — but every one of those appends is MASKED (`advance_lengths`
only advances active slots), so the write lands at a position `lengths`
never reaches and is invisible forever. Freeing and reusing the slot resets
`lengths` to 0 and the new occupant's prefill overwrites from position 0
up; no readback barrier between chunks is ever needed for correctness.

Host-side slot management (free list, eviction) lives in `KVCache`; the
device arrays are a plain dict pytree (`state`) threaded through the jitted
steps, so the engine can donate the buffers and update in place.

KV-cache HBM footprint = n_layers * max_seqs * max_len * n_kv_heads *
head_dim * 2 (k+v) * itemsize — with grouped-query attention (n_kv_heads <
n_heads) the cache shrinks by the group factor, which is why the decode path
is GQA-aware end to end (PERF.md note).
"""
from __future__ import annotations

from typing import Dict, List, Optional

import jax
import jax.numpy as jnp


def init_cache_state(n_layers: int, max_seqs: int, max_len: int,
                     n_kv_heads: int, head_dim: int,
                     dtype=jnp.bfloat16) -> Dict[str, jnp.ndarray]:
    """Allocate the device-side cache pytree (all-zero, all slots free)."""
    shape = (n_layers, max_seqs, max_len, n_kv_heads, head_dim)
    return {
        "k": jnp.zeros(shape, dtype),
        "v": jnp.zeros(shape, dtype),
        # number of CACHED positions per slot; position p is visible iff
        # p < lengths[slot]
        "lengths": jnp.zeros((max_seqs,), jnp.int32),
    }


def write_prefill(state: Dict[str, jnp.ndarray], layer: int, slot,
                  k_block: jnp.ndarray, v_block: jnp.ndarray
                  ) -> Dict[str, jnp.ndarray]:
    """Write one layer's prompt k/v block (T_pad, Hk, D) into `slot` at
    positions [0, T_pad). `slot` may be a traced scalar — one compiled
    prefill serves every slot. Padded tail positions are fine to write (see
    module invariant); the caller sets `lengths` to the TRUE prompt length
    via set_length()."""
    blk = lambda b: b[None, None].astype(state["k"].dtype)
    start = (jnp.asarray(layer, jnp.int32), jnp.asarray(slot, jnp.int32),
             jnp.asarray(0, jnp.int32), jnp.asarray(0, jnp.int32),
             jnp.asarray(0, jnp.int32))
    return {**state,
            "k": jax.lax.dynamic_update_slice(state["k"], blk(k_block), start),
            "v": jax.lax.dynamic_update_slice(state["v"], blk(v_block), start)}


def set_length(state: Dict[str, jnp.ndarray], slot, length
               ) -> Dict[str, jnp.ndarray]:
    return {**state, "lengths": state["lengths"].at[slot].set(
        jnp.asarray(length, jnp.int32))}


def append_token(state: Dict[str, jnp.ndarray], layer: int,
                 k_t: jnp.ndarray, v_t: jnp.ndarray
                 ) -> Dict[str, jnp.ndarray]:
    """Batched one-position append for ALL slots: k_t/v_t (S, Hk, D) land at
    each slot's current `lengths` position (ragged scatter). Does NOT bump
    `lengths` — the decode step advances lengths ONCE after all layers wrote
    (see advance_lengths), so every layer of one iteration writes at the
    same position."""
    s = jnp.arange(state["k"].shape[1])
    pos = state["lengths"]
    return {**state,
            "k": state["k"].at[layer, s, pos].set(k_t.astype(state["k"].dtype)),
            "v": state["v"].at[layer, s, pos].set(v_t.astype(state["v"].dtype))}


def advance_lengths(state: Dict[str, jnp.ndarray], active: jnp.ndarray
                    ) -> Dict[str, jnp.ndarray]:
    """lengths += 1 on active slots only (inactive slots may have received
    harmless scatter writes at their stale position — never visible)."""
    return {**state, "lengths": state["lengths"] + active.astype(jnp.int32)}


class KVCache:
    """Host-side slot allocator around the device `state` pytree.

    The engine owns one KVCache; the jitted steps consume/return
    `cache.state`. Allocation and eviction are host decisions made BETWEEN
    decode iterations (iteration-level scheduling), so they need no device
    sync: freeing is just host bookkeeping plus a lengths[slot]=0 write."""

    def __init__(self, n_layers: int, max_seqs: int, max_len: int,
                 n_kv_heads: int, head_dim: int, dtype=jnp.bfloat16):
        if max_seqs < 1 or max_len < 1:
            raise ValueError(f"bad cache shape: max_seqs={max_seqs}, "
                             f"max_len={max_len}")
        self.n_layers = int(n_layers)
        self.max_seqs = int(max_seqs)
        self.max_len = int(max_len)
        self.n_kv_heads = int(n_kv_heads)
        self.head_dim = int(head_dim)
        self.dtype = jnp.dtype(dtype)
        self.state = init_cache_state(n_layers, max_seqs, max_len,
                                      n_kv_heads, head_dim, dtype)
        self._free: List[int] = list(range(max_seqs))
        self._owner: Dict[int, object] = {}   # slot -> opaque request handle

    # ---------------- slot management ----------------
    def allocate(self, owner=None) -> Optional[int]:
        """Claim a free slot (lowest id first) or None when full."""
        if not self._free:
            return None
        slot = self._free.pop(0)
        self._owner[slot] = owner
        return slot

    def free(self, slot: int) -> None:
        """Return a slot to the free list and hide its contents
        (lengths[slot]=0 — the buffers themselves need no zeroing, see the
        module invariant)."""
        if slot in self._free:
            raise ValueError(f"slot {slot} already free")
        self._owner.pop(slot, None)
        self.state = set_length(self.state, slot, 0)
        self._free.append(slot)
        self._free.sort()

    def owner(self, slot: int):
        return self._owner.get(slot)

    @property
    def n_free(self) -> int:
        return len(self._free)

    @property
    def n_active(self) -> int:
        return self.max_seqs - len(self._free)

    def active_slots(self) -> List[int]:
        return sorted(self._owner)

    def bytes(self) -> int:
        """Device HBM held by the k/v buffers (the PERF.md formula)."""
        return 2 * self.n_layers * self.max_seqs * self.max_len * \
            self.n_kv_heads * self.head_dim * self.dtype.itemsize
