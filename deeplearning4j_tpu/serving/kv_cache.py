"""Paged preallocated KV cache for autoregressive decode.

Beyond-reference (PagedAttention, Kwon et al. SOSP 2023; the 2017 reference
has no incremental-decode path at all). The serving engine
(serving/engine.py) schedules over ONE preallocated pair of buffers carved
into fixed-size physical blocks of `block_size` positions:

    k, v: (n_layers, num_blocks + 1, block_size, n_kv_heads, head_dim)

plus a per-slot `lengths` vector and a fixed-shape device BLOCK TABLE

    block_tables: (max_seqs, max_len // block_size) int32

mapping each slot's logical block index to a physical block. Admission is
block allocation: a request reserves ceil((prompt + max_new) / block_size)
blocks instead of a whole max_len row, so short requests stop paying the
max_len reservation and resident concurrency is bounded by TOTAL BLOCKS,
not slot count (`num_blocks` defaults to max_seqs * max_len / block_size —
the same HBM as the old slot cache — but can be set independently).
Copy-on-write prefix sharing (serving/block_table.py) maps a new request's
leading blocks onto already-resident ones with refcounts, skipping both
the KV bytes and the prefill compute for the shared prefix; the block
containing the first divergent write is copied at admission.

Env knobs: `DL4J_TPU_KV_BLOCK` (block size in positions, default 16),
`DL4J_TPU_PREFIX_SHARE` (0 disables sharing; default on),
`DL4J_TPU_KV_QUANT` (int8 pool, default off — see below).

QUANTIZED POOL (ISSUE 15): with kv_quant on, k/v store int8 payloads and
the state pytree gains per-head-per-block symmetric scales

    k_scale, v_scale: (n_layers, num_blocks + 1, n_kv_heads) fp32

quantized at WRITE time through serving/quant.py (one seam for prefill,
positional scatter, decode append and speculative append) and dequantized
at READ time inside the flash-decode kernel — a dequantized pool is never
materialized. Presence of "k_scale" in the state dict is the static
dispatch flag (a Python `in`, resolved at trace time — zero device cost).
Sub-block writes become block-granular read-modify-writes: gather the
affected blocks, dequantize, insert the new positions, requantize, and
write back ONLY the touched blocks (`jnp.where(touched, new, old)` on
payload AND scale). The untouched-block write-back path is bit-exact by
construction and the touched-mask is load-bearing, not an optimization:
requantizing an unchanged block would RESCALE it (new scale = old *
max|q|/127 unless some |q| == 127), silently moving shared/COW bytes. The
same trash-routing rules apply — invalid RMW lanes target the trash block
(or a dummy gather row), where the unspecified scatter winner is garbage
writing over garbage.

Device-side mutation stays functional and jit-friendly — every write
resolves logical positions through the block table INSIDE the traced fn,
so one compiled prefill/decode serves every slot and every block mapping:

- prefill: the padded prompt reshapes to whole blocks and scatters to the
  slot's mapped physical blocks (`write_prefill`); shared-prefix suffixes
  scatter per position (`write_positions`);
- decode append: a batched scatter at each slot's `lengths` position,
  gathered through the block table (`append_token`), one op per batch.

Safety invariants:
- VISIBILITY (unchanged from the slot cache): position p of slot s is
  visible to attention iff p < lengths[s], and lengths[s] only reaches
  p+1 in the decode step that wrote fresh k/v at p. Padded prefill tails
  and post-EOS masked appends are therefore harmless.
- TRASH ROUTING (new under paging): with block indirection a stale write
  through a freed slot's table row could land in a block already REUSED
  by another request — physical confinement no longer comes free. Every
  write therefore routes inactive slots (and out-of-range positions) to a
  dedicated TRASH block (physical index num_blocks, outside the allocator
  pool), so a masked append can never corrupt live data no matter how the
  block was re-mapped. Freed slots also get their device row reset to
  trash. This is what keeps the engine's CHUNKED and OVERLAPPED
  scheduling (finished slots ride out up to one extra chunk on a stale
  active mask) exactly as safe as it was under slot granularity.
- SHARED BLOCKS ARE READ-ONLY: a request writes only positions >= its
  shared prefix length; admission maps the block containing the first
  such write as a fresh copy (COW), so refcount >= 2 implies no writer.

CHUNKED prefill (ISSUE 9) leans on the exact same primitives: each chunk
scatters through `write_positions` (start..end of the slot's mapped
blocks) and advances `lengths` to the chunk end via `set_length`, so a
partially-prefilled slot is just a resident slot whose visible length
lags its reservation — decode iterations running between chunks can
never see (VISIBILITY) or clobber (TRASH ROUTING) its pending tail.
`register_prefix` is only called once the FULL prompt is resident, so a
half-prefilled sequence is never offered as a sharing donor.

Host-side management (slot free list, block allocator, prefix registry,
eviction) lives in `KVCache`; the device arrays are a plain dict pytree
(`state`) threaded through the jitted steps. Both free lists are heapqs —
O(log n) alloc/free where the old `pop(0)` + per-free `sort()` idiom
would cost O(n log n) on the much larger block list.

KV-cache HBM footprint = 2 (k+v) * n_layers * (num_blocks + 1 trash) *
block_size * n_kv_heads * head_dim * itemsize; `bytes_per_position` =
2 * n_layers * n_kv_heads * head_dim * itemsize is the per-token cost the
engine's residency/waste gauges use (PERF.md's paged cost model).
"""
from __future__ import annotations

import heapq
import os
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.serving import quant
from deeplearning4j_tpu.serving import radix_tree
from deeplearning4j_tpu.serving.block_table import (BlockAllocator,
                                                    PrefixRegistry)

DEFAULT_BLOCK = 16


def is_quantized(state: Dict[str, jnp.ndarray]) -> bool:
    """Static (trace-time) dispatch: does this state carry an int8 pool
    with per-head-per-block scales?"""
    return "k_scale" in state


def resolve_block_size(block_size: Optional[int], max_len: int) -> int:
    """Effective block size: the env/default request clamped to the largest
    divisor of max_len not exceeding it (block tables must tile max_len
    exactly — the table shape is fixed at max_len // block_size)."""
    if block_size is None:
        block_size = int(os.environ.get("DL4J_TPU_KV_BLOCK",
                                        str(DEFAULT_BLOCK)))
    bs = max(1, min(int(block_size), int(max_len)))
    while max_len % bs:
        bs -= 1
    return bs


def init_cache_state(n_layers: int, max_seqs: int, max_len: int,
                     n_kv_heads: int, head_dim: int, dtype=jnp.bfloat16,
                     block_size: Optional[int] = None,
                     num_blocks: Optional[int] = None,
                     kv_quant: bool = False) -> Dict[str, jnp.ndarray]:
    """Allocate the device-side paged cache pytree (all-zero, all slots
    free, every table entry pointing at the trash block). With kv_quant
    the payload is int8 and the pytree gains k_scale/v_scale (scale 1.0
    everywhere — payload 0 dequantizes to 0 either way, and quantizing an
    all-zero block also yields scale 1.0, see serving/quant.py)."""
    bs = resolve_block_size(block_size, max_len)
    bps = max_len // bs
    nb = int(num_blocks) if num_blocks is not None else max_seqs * bps
    shape = (n_layers, nb + 1, bs, n_kv_heads, head_dim)   # +1: trash block
    pdt = quant.PAYLOAD_DTYPE if kv_quant else dtype
    state = {
        "k": jnp.zeros(shape, pdt),
        "v": jnp.zeros(shape, pdt),
        # number of CACHED positions per slot; position p is visible iff
        # p < lengths[slot]
        "lengths": jnp.zeros((max_seqs,), jnp.int32),
        # logical block -> physical block per slot; trash (= nb) everywhere
        # a slot has no reservation
        "block_tables": jnp.full((max_seqs, bps), nb, jnp.int32),
    }
    if kv_quant:
        state["k_scale"] = jnp.ones((n_layers, nb + 1, n_kv_heads),
                                    quant.SCALE_DTYPE)
        state["v_scale"] = jnp.ones((n_layers, nb + 1, n_kv_heads),
                                    quant.SCALE_DTYPE)
    return state


def _dims(state):
    n_phys, bs = state["k"].shape[1], state["k"].shape[2]
    return bs, state["block_tables"].shape[1], n_phys - 1   # bs, bps, trash


def write_prefill(state: Dict[str, jnp.ndarray], layer: int, slot,
                  k_block: jnp.ndarray, v_block: jnp.ndarray
                  ) -> Dict[str, jnp.ndarray]:
    """Write one layer's prompt k/v block (T_pad, Hk, D) into `slot` at
    logical positions [0, T_pad). T_pad must be a multiple of block_size;
    the block reshapes to whole blocks and scatters to the slot's mapped
    physical blocks. `slot` may be a traced scalar — one compiled prefill
    serves every slot and every block mapping. Padding blocks beyond the
    slot's reservation hit table entries that still point at trash (see
    module invariants); the caller sets `lengths` to the TRUE prompt
    length via set_length()."""
    bs, _, _ = _dims(state)
    T = k_block.shape[0]
    if T % bs:
        raise ValueError(f"prefill block length {T} not a multiple of "
                         f"block_size {bs}")
    nb = T // bs
    phys = state["block_tables"][jnp.asarray(slot, jnp.int32)][:nb]  # (nb,)
    kb = k_block.reshape((nb, bs) + k_block.shape[1:])
    vb = v_block.reshape((nb, bs) + v_block.shape[1:])
    if is_quantized(state):
        # Whole blocks: quantize per (block, head) and scatter payload +
        # scale. Padding blocks beyond the reservation collapse onto the
        # trash index — the payload/scale scatter winners there may come
        # from DIFFERENT padding blocks, which is harmless: trash is never
        # read visible and any scale dequantizes finite garbage.
        kq, ks = quant.kv_quantize(kb)                    # int8, (nb, Hk)
        vq, vs = quant.kv_quantize(vb)
        return {**state,
                "k": state["k"].at[layer, phys].set(kq),
                "v": state["v"].at[layer, phys].set(vq),
                "k_scale": state["k_scale"].at[layer, phys].set(ks),
                "v_scale": state["v_scale"].at[layer, phys].set(vs)}
    return {**state,
            "k": state["k"].at[layer, phys].set(kb.astype(state["k"].dtype)),
            "v": state["v"].at[layer, phys].set(vb.astype(state["v"].dtype))}


def write_positions(state: Dict[str, jnp.ndarray], layer: int, slot,
                    positions: jnp.ndarray, valid: jnp.ndarray,
                    k_seq: jnp.ndarray, v_seq: jnp.ndarray
                    ) -> Dict[str, jnp.ndarray]:
    """Scatter k/v (T, Hk, D) to arbitrary logical `positions` (T,) of
    `slot`, resolved through its block table. Rows with valid=False (the
    padded tail of a shared-prefix suffix prefill) route to the trash
    block — they must NEVER alias a real (block, offset) pair, because a
    duplicate scatter index has an unspecified winner and a garbage
    padding row could otherwise clobber a just-written real position.

    Quantized pool: a sub-block scatter becomes a block-granular RMW over
    the slot's WHOLE row (this is a prefill-time call, not the per-token
    path): gather the row's blocks, dequantize, insert the new positions
    — invalid rows land in a dummy gather row, the RMW analog of trash
    routing — requantize, and write back only the TOUCHED blocks, so
    untouched (including shared read-only) blocks keep their exact
    payload and scale bytes."""
    bs, bps, trash = _dims(state)
    row = state["block_tables"][jnp.asarray(slot, jnp.int32)]     # (bps,)
    bidx = jnp.clip(positions // bs, 0, bps - 1)
    off = positions % bs
    if is_quantized(state):
        kq = state["k"][layer, row]                       # (bps, bs, Hk, D)
        vq = state["v"][layer, row]
        ks = state["k_scale"][layer, row]                 # (bps, Hk)
        vs = state["v_scale"][layer, row]
        kf = quant.kv_dequantize(kq, ks)
        vf = quant.kv_dequantize(vq, vs)
        kf = jnp.concatenate([kf, jnp.zeros_like(kf[:1])], axis=0)
        vf = jnp.concatenate([vf, jnp.zeros_like(vf[:1])], axis=0)
        tgt = jnp.where(valid, bidx, bps)                 # bps = dummy row
        kf = kf.at[tgt, off].set(k_seq.astype(kf.dtype))
        vf = vf.at[tgt, off].set(v_seq.astype(vf.dtype))
        kq2, ks2 = quant.kv_quantize(kf[:bps])
        vq2, vs2 = quant.kv_quantize(vf[:bps])
        touched = jnp.zeros((bps + 1,), jnp.int32).at[tgt].add(
            valid.astype(jnp.int32))[:bps] > 0            # (bps,)
        return {**state,
                "k": state["k"].at[layer, row].set(
                    jnp.where(touched[:, None, None, None], kq2, kq)),
                "v": state["v"].at[layer, row].set(
                    jnp.where(touched[:, None, None, None], vq2, vq)),
                "k_scale": state["k_scale"].at[layer, row].set(
                    jnp.where(touched[:, None], ks2, ks)),
                "v_scale": state["v_scale"].at[layer, row].set(
                    jnp.where(touched[:, None], vs2, vs))}
    phys = jnp.where(valid, row[bidx], trash)
    return {**state,
            "k": state["k"].at[layer, phys, off].set(
                k_seq.astype(state["k"].dtype)),
            "v": state["v"].at[layer, phys, off].set(
                v_seq.astype(state["v"].dtype))}


def set_length(state: Dict[str, jnp.ndarray], slot, length
               ) -> Dict[str, jnp.ndarray]:
    return {**state, "lengths": state["lengths"].at[slot].set(
        jnp.asarray(length, jnp.int32))}


def append_token(state: Dict[str, jnp.ndarray], layer: int,
                 k_t: jnp.ndarray, v_t: jnp.ndarray, active: jnp.ndarray
                 ) -> Dict[str, jnp.ndarray]:
    """Batched one-position append for ALL slots: k_t/v_t (S, Hk, D) land
    at each slot's current `lengths` position, gathered through its block
    table (ragged scatter). INACTIVE slots route to the trash block — a
    freed slot's stale table row may point at blocks already reused by
    another request, so the mask is load-bearing here, not an
    optimization. Does NOT bump `lengths` — the decode step advances
    lengths ONCE after all layers wrote (advance_lengths), so every layer
    of one iteration writes at the same position."""
    bs, bps, trash = _dims(state)
    pos = state["lengths"]                                    # (S,)
    bidx = jnp.clip(pos // bs, 0, bps - 1)
    phys = jnp.take_along_axis(state["block_tables"], bidx[:, None],
                               axis=1)[:, 0]
    phys = jnp.where(active, phys, trash)
    off = pos % bs
    if is_quantized(state):
        # Block-granular RMW of each slot's CURRENT block. Trash routing
        # happens before the gather, so an inactive slot reads trash and
        # writes trash back — it can never write back (even bit-identical)
        # bytes of a block its stale table row points at, which matters
        # because that block's new owner may be appending into it in this
        # very scatter. Active slots' current blocks are private and
        # distinct (shared blocks are read-only; admission COWs the first
        # written block), so touched targets never collide.
        S = pos.shape[0]
        kq = state["k"][layer, phys]                      # (S, bs, Hk, D)
        vq = state["v"][layer, phys]
        ks = state["k_scale"][layer, phys]                # (S, Hk)
        vs = state["v_scale"][layer, phys]
        kf = quant.kv_dequantize(kq, ks).at[jnp.arange(S), off].set(
            k_t.astype(quant.SCALE_DTYPE))
        vf = quant.kv_dequantize(vq, vs).at[jnp.arange(S), off].set(
            v_t.astype(quant.SCALE_DTYPE))
        kq2, ks2 = quant.kv_quantize(kf)
        vq2, vs2 = quant.kv_quantize(vf)
        act = active.astype(bool)
        return {**state,
                "k": state["k"].at[layer, phys].set(
                    jnp.where(act[:, None, None, None], kq2, kq)),
                "v": state["v"].at[layer, phys].set(
                    jnp.where(act[:, None, None, None], vq2, vq)),
                "k_scale": state["k_scale"].at[layer, phys].set(
                    jnp.where(act[:, None], ks2, ks)),
                "v_scale": state["v_scale"].at[layer, phys].set(
                    jnp.where(act[:, None], vs2, vs))}
    return {**state,
            "k": state["k"].at[layer, phys, off].set(
                k_t.astype(state["k"].dtype)),
            "v": state["v"].at[layer, phys, off].set(
                v_t.astype(state["v"].dtype))}


def append_tokens(state: Dict[str, jnp.ndarray], layer: int,
                  k_t: jnp.ndarray, v_t: jnp.ndarray,
                  positions: jnp.ndarray, valid: jnp.ndarray
                  ) -> Dict[str, jnp.ndarray]:
    """Batched MULTI-position append for all slots (speculative decode,
    ISSUE 11): k_t/v_t (S, Q, Hk, D) land at logical `positions` (S, Q) of
    each slot, gathered through its block table. Rows with valid=False
    (inactive slots, and query rows beyond a slot's draft length) route to
    the trash block — same load-bearing mask as `append_token`, extended
    per query row so a short draft's padding writes can never land in live
    blocks. Valid rows of one slot are distinct consecutive positions and
    slots own disjoint blocks, so no two valid rows alias one
    (block, offset) pair; invalid rows may collide inside trash, where the
    unspecified scatter winner is harmless by construction. Does NOT move
    `lengths` — rollback after verification is pure `set_length` (rejected
    positions stay invisible forever under the visibility invariant)."""
    bs, bps, trash = _dims(state)
    S, Q = positions.shape
    bidx = jnp.clip(positions // bs, 0, bps - 1)              # (S, Q)
    if is_quantized(state):
        # Block-granular RMW over a STATIC window of blocks per slot: Q
        # consecutive positions starting at positions[:, 0] span at most
        # (Q + bs - 2) // bs + 1 blocks, so the gather shape is fixed at
        # trace time. Slots with no valid row (inactive) gather — and
        # therefore write back — only trash: a stale table row's blocks
        # may be owned by another slot appending in this same scatter, so
        # even a bit-identical write-back through the stale row would race
        # it (unspecified scatter winner). Window entries past the table
        # edge also collapse to trash for the same reason.
        nblk = min(bps, (Q + bs - 2) // bs + 1)
        b0 = jnp.clip(positions[:, 0] // bs, 0, bps - 1)      # (S,)
        lidx = b0[:, None] + jnp.arange(nblk)                 # (S, nblk)
        in_range = lidx < bps
        physw = jnp.take_along_axis(state["block_tables"],
                                    jnp.clip(lidx, 0, bps - 1), axis=1)
        live = jnp.any(valid, axis=1)                         # (S,)
        physw = jnp.where(live[:, None] & in_range, physw, trash)
        kq = state["k"][layer, physw]                     # (S,nblk,bs,Hk,D)
        vq = state["v"][layer, physw]
        ks = state["k_scale"][layer, physw]               # (S, nblk, Hk)
        vs = state["v_scale"][layer, physw]
        kf = quant.kv_dequantize(kq, ks)
        vf = quant.kv_dequantize(vq, vs)
        kf = jnp.concatenate([kf, jnp.zeros_like(kf[:, :1])], axis=1)
        vf = jnp.concatenate([vf, jnp.zeros_like(vf[:, :1])], axis=1)
        rel = bidx - b0[:, None]                              # (S, Q)
        ok = valid & (rel >= 0) & (rel < nblk)
        tgt = jnp.where(ok, rel, nblk)                    # nblk = dummy col
        sidx = jnp.broadcast_to(jnp.arange(S)[:, None], (S, Q))
        off = positions % bs
        kf = kf.at[sidx, tgt, off].set(k_t.astype(kf.dtype))
        vf = vf.at[sidx, tgt, off].set(v_t.astype(vf.dtype))
        kq2, ks2 = quant.kv_quantize(kf[:, :nblk])
        vq2, vs2 = quant.kv_quantize(vf[:, :nblk])
        touched = jnp.zeros((S, nblk + 1), jnp.int32).at[sidx, tgt].add(
            ok.astype(jnp.int32))[:, :nblk] > 0           # (S, nblk)
        return {**state,
                "k": state["k"].at[layer, physw].set(
                    jnp.where(touched[..., None, None, None], kq2, kq)),
                "v": state["v"].at[layer, physw].set(
                    jnp.where(touched[..., None, None, None], vq2, vq)),
                "k_scale": state["k_scale"].at[layer, physw].set(
                    jnp.where(touched[..., None], ks2, ks)),
                "v_scale": state["v_scale"].at[layer, physw].set(
                    jnp.where(touched[..., None], vs2, vs))}
    phys = jnp.take_along_axis(state["block_tables"], bidx, axis=1)
    phys = jnp.where(valid, phys, trash).reshape(S * Q)
    off = (positions % bs).reshape(S * Q)
    kf = k_t.reshape((S * Q,) + k_t.shape[2:])
    vf = v_t.reshape((S * Q,) + v_t.shape[2:])
    return {**state,
            "k": state["k"].at[layer, phys, off].set(
                kf.astype(state["k"].dtype)),
            "v": state["v"].at[layer, phys, off].set(
                vf.astype(state["v"].dtype))}


def advance_lengths(state: Dict[str, jnp.ndarray], active: jnp.ndarray
                    ) -> Dict[str, jnp.ndarray]:
    """lengths += 1 on active slots only (inactive slots' appends were
    trash-routed and their lengths never move — invisible forever)."""
    return {**state, "lengths": state["lengths"] + active.astype(jnp.int32)}


def set_block_table(state: Dict[str, jnp.ndarray], slot: int,
                    row: np.ndarray) -> Dict[str, jnp.ndarray]:
    """Install a slot's logical->physical row (host-built, admission/free
    time — a scheduling event, not the hot path)."""
    return {**state, "block_tables": state["block_tables"].at[slot].set(
        jnp.asarray(row, jnp.int32))}


def copy_block(state: Dict[str, jnp.ndarray], src: int, dst: int
               ) -> Dict[str, jnp.ndarray]:
    """Copy one physical block across ALL layers (the COW copy a shared
    tail block pays at admission — one device op, no readback). A
    quantized block's scales travel with its payload: the copy is
    bit-exact, never a dequantize/requantize."""
    out = {**state,
           "k": state["k"].at[:, dst].set(state["k"][:, src]),
           "v": state["v"].at[:, dst].set(state["v"][:, src])}
    if is_quantized(state):
        out["k_scale"] = state["k_scale"].at[:, dst].set(
            state["k_scale"][:, src])
        out["v_scale"] = state["v_scale"].at[:, dst].set(
            state["v_scale"][:, src])
    return out


def gather_blocks(state: Dict[str, jnp.ndarray], blocks: Sequence[int],
                  with_scales: bool = False) -> tuple:
    """Gather the k/v bytes of physical `blocks` across all layers — the
    device half of a swap-out (serving/lifecycle.py). Returns
    (k_blk, v_blk), each (n_layers, len(blocks), block_size, n_kv_heads,
    head_dim) — plus (k_scale, v_scale), each (n_layers, len(blocks),
    n_kv_heads), when `with_scales` is set on a quantized pool. This
    DISPATCHES an async gather and returns device
    arrays; the bytes only cross to the host when the caller
    materializes them. Because every cache mutation is functional (no
    donation, no in-place update), the gathered value is pinned at
    dispatch order — writes issued after it, including a new owner
    reusing these physical blocks, cannot retroactively corrupt it."""
    idx = jnp.asarray(list(blocks), jnp.int32)
    if with_scales and is_quantized(state):
        return (state["k"][:, idx], state["v"][:, idx],
                state["k_scale"][:, idx], state["v_scale"][:, idx])
    return state["k"][:, idx], state["v"][:, idx]


def restore_blocks(state: Dict[str, jnp.ndarray], blocks: Sequence[int],
                   k_blk, v_blk, k_scale=None, v_scale=None
                   ) -> Dict[str, jnp.ndarray]:
    """Scatter previously gathered block bytes back into physical
    `blocks` across all layers (swap-in / prefix-store restore): one
    batched scatter per buffer, the exact inverse of `gather_blocks`, so
    a swap round-trip is bit-identical by construction. A quantized pool
    requires the matching scales — int8 payload without its scale is not
    restorable, and silently keeping stale scales would rescale the
    content."""
    idx = jnp.asarray(list(blocks), jnp.int32)
    out = {**state,
           "k": state["k"].at[:, idx].set(
               jnp.asarray(k_blk).astype(state["k"].dtype)),
           "v": state["v"].at[:, idx].set(
               jnp.asarray(v_blk).astype(state["v"].dtype))}
    if is_quantized(state):
        if k_scale is None or v_scale is None:
            raise ValueError(
                "restore_blocks on a quantized pool requires k_scale/"
                "v_scale (gather with with_scales=True)")
        out["k_scale"] = state["k_scale"].at[:, idx].set(
            jnp.asarray(k_scale).astype(state["k_scale"].dtype))
        out["v_scale"] = state["v_scale"].at[:, idx].set(
            jnp.asarray(v_scale).astype(state["v_scale"].dtype))
    return out


@dataclass
class AdmissionPlan:
    """What `KVCache.admit` decided for one request: where it lives, how
    much of its prompt KV (and prefill compute) sharing already covers,
    and whether a COW copy was issued."""
    slot: int
    n_blocks: int               # blocks mapped (shared + owned)
    shared_len: int             # prompt positions covered by shared KV
    n_shared_blocks: int        # fully-shared (refcounted, read-only) blocks
    cow: bool                   # a divergent-write block copy was issued


class KVCache:
    """Host-side slot + block allocator around the device `state` pytree.

    The engine owns one KVCache; the jitted steps consume/return
    `cache.state`. Admission, eviction, and prefix matching are host
    decisions made BETWEEN decode iterations (iteration-level scheduling),
    so they need no device sync: freeing is host bookkeeping plus a
    lengths[slot]=0 / table-row-reset write."""

    def __init__(self, n_layers: int, max_seqs: int, max_len: int,
                 n_kv_heads: int, head_dim: int, dtype=jnp.bfloat16,
                 block_size: Optional[int] = None,
                 num_blocks: Optional[int] = None,
                 prefix_share: Optional[bool] = None,
                 prefix_registry: Optional[PrefixRegistry] = None,
                 kv_quant: Optional[bool] = None,
                 prefix_radix: Optional[bool] = None):
        if max_seqs < 1 or max_len < 1:
            raise ValueError(f"bad cache shape: max_seqs={max_seqs}, "
                             f"max_len={max_len}")
        self.n_layers = int(n_layers)
        self.max_seqs = int(max_seqs)
        self.max_len = int(max_len)
        self.n_kv_heads = int(n_kv_heads)
        self.head_dim = int(head_dim)
        self.dtype = jnp.dtype(dtype)
        self.block_size = resolve_block_size(block_size, self.max_len)
        self.blocks_per_seq = self.max_len // self.block_size
        self.num_blocks = int(num_blocks) if num_blocks is not None \
            else self.max_seqs * self.blocks_per_seq
        if self.num_blocks < 1:
            raise ValueError(f"num_blocks must be >= 1, got {self.num_blocks}")
        self.trash_block = self.num_blocks        # extra block past the pool
        if prefix_share is None:
            prefix_share = os.environ.get("DL4J_TPU_PREFIX_SHARE", "1") != "0"
        self.prefix_share = bool(prefix_share)
        self.kv_quant = quant.resolve_kv_quant(kv_quant)
        self.state = init_cache_state(n_layers, max_seqs, max_len,
                                      n_kv_heads, head_dim, dtype,
                                      block_size=self.block_size,
                                      num_blocks=self.num_blocks,
                                      kv_quant=self.kv_quant)
        # list(range(n)) is already a valid min-heap
        self._free_slots: List[int] = list(range(max_seqs))
        self.allocator = BlockAllocator(self.num_blocks)
        # ISSUE 10: the registry handle is injectable so routers (replica
        # groups) can run read-only match() affinity queries against it;
        # bind_pool rejects handing one registry to a second pool (block
        # ids are pool-scoped).
        if prefix_registry is not None:
            if prefix_registry.block_size != self.block_size:
                raise ValueError(
                    f"injected PrefixRegistry block_size "
                    f"{prefix_registry.block_size} != cache block_size "
                    f"{self.block_size}")
            self.registry = prefix_registry
        elif radix_tree.resolve_prefix_radix(prefix_radix):
            # radix prefix cache (ISSUE 16): drop-in registry whose tree
            # RETAINS registered prompt blocks past their owners'
            # retirement (the tree holds its own allocator reference), so
            # follow-up turns and forks COW-share retired histories.
            # admit() reclaims cold retained blocks under pool pressure.
            self.registry = radix_tree.RadixPrefixTree(self.block_size)
        else:
            self.registry = PrefixRegistry(self.block_size)
        self.registry.bind_pool(self)
        # keyed off the ACTUAL registry (an injected radix tree enables
        # retention semantics too, e.g. from a ShardedServingGroup)
        self.prefix_radix = bool(getattr(self.registry, "is_radix", False))
        self._owner: Dict[int, object] = {}   # slot -> opaque request handle
        self._slot_blocks: Dict[int, List[int]] = {}   # slot -> mapped blocks
        # reverse index for attribution (ISSUE 12): block -> slots mapping
        # it. Invariant (stress-tested): len(_block_sharers[b]) ==
        # allocator.refcount(b) for every mapped block b.
        self._block_sharers: Dict[int, set] = {}
        # lifetime counters (bench/stats: the sharing win, observable)
        self.shared_blocks_total = 0    # shared mappings ever granted
        self.shared_tokens_total = 0    # prompt positions served from shares
        self.cow_copies_total = 0       # divergent-write block copies issued

    # ---------------- admission (slot + block allocation) ----------------
    def allocate(self, owner=None, n_positions: Optional[int] = None,
                 prompt: Optional[Sequence[int]] = None) -> Optional[int]:
        """Claim a slot with enough blocks for `n_positions` (default: a
        full max_len reservation — the slot-cache-compatible call) or None
        when slots or blocks run out. See admit() for the full plan."""
        plan = self.admit(owner, n_positions=n_positions, prompt=prompt)
        return None if plan is None else plan.slot

    def admit(self, owner=None, n_positions: Optional[int] = None,
              prompt: Optional[Sequence[int]] = None
              ) -> Optional[AdmissionPlan]:
        """Admission = block allocation: reserve ceil(n_positions /
        block_size) blocks for a slot, mapping leading blocks onto
        already-resident shared-prefix blocks when `prompt` matches the
        registry (refcounted, read-only), COW-copying the block that holds
        the first divergent write. All-or-nothing: returns None (no side
        effects) when the slot or the non-shared blocks aren't available —
        the engine requeues and retries next iteration."""
        if not self._free_slots:
            return None
        bs = self.block_size
        if n_positions is None:
            n_positions = self.max_len
        n_positions = max(1, min(int(n_positions), self.max_len))
        need = -(-n_positions // bs)                  # ceil
        shared_len, shared_blocks, cow_src = 0, [], None
        if self.prefix_share and prompt is not None and len(prompt) > 1:
            matched, mblocks = self.registry.match(prompt)
            # always recompute at least the LAST prompt position — prefill
            # must produce the first-token logprobs from a live activation
            shared_len = min(matched, len(prompt) - 1)
            if shared_len >= 1:
                n_full = shared_len // bs
                shared_blocks = mblocks[:n_full]
                if matched > n_full * bs:
                    # the block holding position shared_len is resident but
                    # about to diverge (this request writes it) -> COW
                    cow_src = mblocks[n_full]
            else:
                shared_len = 0
        n_fresh = need - len(shared_blocks)
        fresh = self.allocator.alloc_many(n_fresh)
        if fresh is None and self.prefix_radix:
            # radix retention (ISSUE 16): retired prompt blocks stay in
            # the pool under the tree's reference — under pressure the
            # cache eats its own cold cache (coldest leaves first) before
            # rejecting an admission. Blocks this admission is about to
            # map are protected. Evicting cache is a benign side effect
            # of a failed reservation; the all-or-nothing contract still
            # holds for SLOT/block state.
            protect = set(shared_blocks)
            if cow_src is not None:
                protect.add(cow_src)
            short = n_fresh - self.allocator.n_free
            if short > 0 and self.registry.reclaim(short,
                                                   protect=protect) > 0:
                fresh = self.allocator.alloc_many(n_fresh)
        if fresh is None:
            return None
        slot = heapq.heappop(self._free_slots)
        for b in shared_blocks:
            self.allocator.incref(b)
        row_blocks = list(shared_blocks) + fresh
        if cow_src is not None:
            self.state = copy_block(self.state, cow_src, fresh[0])
            self.cow_copies_total += 1
        row = np.full((self.blocks_per_seq,), self.trash_block, np.int32)
        row[:len(row_blocks)] = row_blocks
        self.state = set_block_table(self.state, slot, row)
        self._owner[slot] = owner
        self._slot_blocks[slot] = row_blocks
        for b in row_blocks:
            self._block_sharers.setdefault(b, set()).add(slot)
        self.shared_blocks_total += len(shared_blocks)
        self.shared_tokens_total += shared_len
        return AdmissionPlan(slot=slot, n_blocks=len(row_blocks),
                             shared_len=shared_len,
                             n_shared_blocks=len(shared_blocks),
                             cow=cow_src is not None)

    def ensure_writable(self, slot: int, start: int, end: int) -> int:
        """Copy-on-reject guard (ISSUE 11): make every block of `slot`
        covering logical positions [start, end) PRIVATE before a
        speculative write lands there. A block with refcount >= 2 is mapped
        by other slots too (a COW-shared prefix); writing draft KV into it
        — even KV that later gets rolled back by `set_length` — would
        corrupt the donors, because rollback makes rejected positions
        INVISIBLE, not unwritten. Each such block is replaced by a fresh
        copy in the slot's table (device `copy_block`, one op per block,
        no readback) and the shared original is decref'd, never mutated.

        Under the engine's admission semantics a slot's write range starts
        at its own prompt tail, past every shared block, so this guard is
        expected to copy nothing — it exists to make the invariant
        STRUCTURAL rather than an accident of current admission behavior,
        and is stress-tested directly in tests/test_block_table.py.
        Returns the number of blocks copied. Raises when the pool cannot
        supply a replacement block (the caller reserved these positions at
        admission, so this indicates allocator corruption, not load)."""
        if end <= start:
            return 0
        bs = self.block_size
        row_blocks = self._slot_blocks.get(slot)
        if row_blocks is None:
            raise ValueError(f"slot {slot} is not resident")
        copied = 0
        for li in range(max(0, start // bs),
                        min(len(row_blocks), -(-end // bs))):
            old = row_blocks[li]
            if self.allocator.refcount(old) < 2:
                continue
            fresh = self.allocator.alloc_many(1)
            if fresh is None:
                raise RuntimeError(
                    f"copy-on-reject for slot {slot} block {li}: no free "
                    "block despite an admission-time reservation")
            self.state = copy_block(self.state, old, fresh[0])
            row_blocks[li] = fresh[0]
            row = np.full((self.blocks_per_seq,), self.trash_block, np.int32)
            row[:len(row_blocks)] = row_blocks
            self.state = set_block_table(self.state, slot, row)
            self._block_sharers[old].discard(slot)
            if not self._block_sharers[old]:
                # possible under radix retention: refcount 2 = one slot +
                # the tree's own reference, so the last SLOT just left
                del self._block_sharers[old]
            self._block_sharers.setdefault(fresh[0], set()).add(slot)
            self.allocator.decref(old)     # refcount >= 2: never frees here
            self.cow_copies_total += 1
            copied += 1
        return copied

    def register_prefix(self, slot: int, prompt: Sequence[int]) -> int:
        """File the slot's prompt blocks in the prefix registry (call AFTER
        dispatching the prefill — by the time any sharer's device reads
        run, the writes are ordered ahead of them). Under a radix registry
        this is also the retention point: the tree increfs newly claimed
        full prompt blocks so they outlive the slot. Returns the lineage
        hits recorded (re-registrations of already-claimed digests)."""
        if self.prefix_share and len(prompt) >= 2:
            return int(self.registry.register(
                prompt, self._slot_blocks[slot]) or 0)
        return 0

    def free(self, slot: int) -> None:
        """Return a slot and its block reservations. Shared blocks only
        reach the free list when their LAST mapping drops (refcounts); a
        block that does free drops its registry claims — its content is
        about to be overwritten by an unrelated request. The device row is
        reset to trash and lengths[slot]=0 (the buffers themselves need no
        zeroing: stale writes are trash-routed and stale content is
        invisible, see the module invariants)."""
        if slot not in self._slot_blocks:
            raise ValueError(f"slot {slot} already free")
        for b in self._slot_blocks.pop(slot):
            sharers = self._block_sharers.get(b)
            if sharers is not None:
                sharers.discard(slot)
                if not sharers:
                    del self._block_sharers[b]
            if self.allocator.decref(b):
                self.registry.forget(b)
        self._owner.pop(slot, None)
        self.state = set_length(self.state, slot, 0)
        self.state = set_block_table(
            self.state, slot,
            np.full((self.blocks_per_seq,), self.trash_block, np.int32))
        heapq.heappush(self._free_slots, slot)

    def owner(self, slot: int):
        return self._owner.get(slot)

    # ------------------------------------------- heat / attribution (12)
    def touch_blocks(self, slot: int, start: int, end: int) -> None:
        """Stamp every block of `slot` covering logical positions
        [start, end) as touched at the allocator's current clock. Called
        by the engine when it CREDITS writes (prefill chunk, decode
        append, spec commit) — the host already knows these ranges from
        its counted readbacks, so the stamp adds zero device syncs."""
        if end <= start:
            return
        bs = self.block_size
        row_blocks = self._slot_blocks.get(slot)
        if not row_blocks:
            return
        for li in range(max(0, start // bs),
                        min(len(row_blocks), -(-end // bs))):
            self.allocator.touch(row_blocks[li])

    def sharers(self, block: int) -> frozenset:
        """Slots currently mapping `block` (empty when free)."""
        return frozenset(self._block_sharers.get(block, ()))

    def pool_snapshot(self, live_positions: Optional[Dict[int, int]] = None,
                      include_blocks: bool = True) -> Dict[str, object]:
        """ONE consistent host-side view of the whole pool (ISSUE 12).

        Callers previously read `blocks_free` / `blocks_shared` (and
        per-slot reservations) as separate probes; between two such reads
        the scheduler can admit or retire a request, so the pair could
        describe no state the pool was ever actually in. This method
        builds everything in one pass with no device reads and no yields
        — under the engine lock it is atomic by construction.

        `live_positions` (slot -> KV positions actually written, host
        bookkeeping the engine owns) is threaded through verbatim so the
        observatory can split reservation bytes into live vs waste.
        `include_blocks=False` skips the per-block table for cheap gauge
        refreshes (totals + slots only)."""
        alloc = self.allocator
        slots: Dict[int, Dict[str, object]] = {}
        for slot in sorted(self._slot_blocks):
            owner = self._owner.get(slot)
            req_id = getattr(owner, "req_id", None)
            if req_id is None and isinstance(owner, (int, str)):
                req_id = owner
            blocks = self._slot_blocks[slot]
            slots[slot] = {
                "req_id": req_id,
                "blocks": list(blocks),
                "reserved_positions": len(blocks) * self.block_size,
                "live_positions": None if live_positions is None
                else int(live_positions.get(slot, 0)),
                # lifecycle stamps (PR 8) when the owner is an engine
                # request record — the SLO-aware eviction scorer's signal
                "deadline": getattr(owner, "deadline", None),
                "t_submit": getattr(owner, "t_submit", None),
            }
        # radix retention (ISSUE 16): blocks held ONLY by the tree's own
        # reference belong to no slot — they surface here so attribution
        # (cached_prefix_bytes) and conservation stay exact. Empty under
        # the linear registry, keeping pre-radix snapshots bit-identical
        # aside from the constant "blocks_cached": 0 total.
        cached = (self.registry.retained_blocks()
                  if self.prefix_radix else frozenset())
        snap: Dict[str, object] = {
            "clock": alloc.clock,
            "num_blocks": self.num_blocks,
            "block_size": self.block_size,
            "bytes_per_position": self.bytes_per_position,
            "block_overhead_bytes": self.block_overhead_bytes,
            "blocks_free": alloc.n_free,
            "blocks_shared": alloc.n_shared,
            "blocks_cached": len(cached),
            "slots_free": len(self._free_slots),
            "slots_active": self.max_seqs - len(self._free_slots),
            "slots": slots,
        }
        if include_blocks:
            snap["blocks"] = {
                b: {
                    "refcount": alloc.refcount(b),
                    "last_touch": alloc.last_touch(b),
                    "alloc_epoch": alloc.alloc_epoch(b),
                    "sharers": sorted(self._block_sharers.get(b, ())),
                    "cached": b in cached,
                    "lineage": self.registry.lineage(b),
                }
                for b in sorted(set(self._block_sharers) | set(cached))
            }
        return snap

    # ------------------------------------------------------------- stats
    @property
    def n_free(self) -> int:
        return len(self._free_slots)

    @property
    def n_active(self) -> int:
        return self.max_seqs - len(self._free_slots)

    @property
    def blocks_free(self) -> int:
        return self.allocator.n_free

    @property
    def blocks_shared(self) -> int:
        return self.allocator.n_shared

    @property
    def blocks_cached(self) -> int:
        """Blocks retained by the radix tree's own reference (0 under the
        linear registry)."""
        return len(self.registry.retained_blocks()) \
            if self.prefix_radix else 0

    def active_slots(self) -> List[int]:
        return sorted(self._owner)

    def reserved_positions(self, slot: int) -> int:
        """Positions this slot's block reservation holds (block
        granularity — the engine's kv_bytes_waste gauge subtracts the live
        prompt+generated count from this)."""
        return len(self._slot_blocks.get(slot, ())) * self.block_size

    @property
    def bytes_per_position(self) -> int:
        """Per-token KV PAYLOAD cost (k+v, all layers) — the PERF.md
        unit. Derived from the ACTUAL pool array dtypes (int8 when
        quantized, whatever the ctor got otherwise), not the ctor
        `self.dtype` assumption — a non-bf16 pool used to misreport every
        downstream byte gauge. Scale bytes are per-BLOCK, not
        per-position (and fractional per position), so they live in
        `block_overhead_bytes` — every byte consumer adds
        blocks * block_overhead_bytes to keep accounting integral and
        exactly conserved."""
        return self.n_layers * self.n_kv_heads * self.head_dim * (
            self.state["k"].dtype.itemsize + self.state["v"].dtype.itemsize)

    @property
    def block_overhead_bytes(self) -> int:
        """Scale bytes carried per physical block (0 on an unquantized
        pool): one fp32 per (layer, kv head) for each of k and v."""
        if not is_quantized(self.state):
            return 0
        return self.n_layers * self.n_kv_heads * (
            self.state["k_scale"].dtype.itemsize +
            self.state["v_scale"].dtype.itemsize)

    @property
    def block_bytes(self) -> int:
        """Swap/transfer payload bytes of ONE physical block: positions
        times the dtype-derived per-position cost plus the per-block
        scale overhead (quantized pools, ISSUE 15). The single formula
        every byte consumer on the pressure path shares (ISSUE 18) —
        eviction cost terms, preempt accounting, and the host/disk tier
        caps all agree because they multiply this, so the int8 shrink
        (~4x vs fp32) threads through `choose_mode` automatically."""
        return self.block_size * self.bytes_per_position \
            + self.block_overhead_bytes

    def bytes(self) -> int:
        """Device HBM held by the k/v buffers (num_blocks + the trash
        block), scales included — the PERF.md paged footprint formula."""
        return (self.num_blocks + 1) * self.block_bytes
