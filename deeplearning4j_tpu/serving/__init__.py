"""Autoregressive serving: paged (block-table) KV cache with copy-on-write
prefix sharing, cached single-query decode, continuous-batching engine,
sampling. See serving/engine.py for the design overview;
`ParallelInference(inference_mode=InferenceMode.GENERATE)` exposes the
engine behind the existing inference API."""
from deeplearning4j_tpu.serving.block_table import (BlockAllocator,
                                                    PrefixRegistry)
from deeplearning4j_tpu.serving.decode import (StackDecoder, decode_attention,
                                               decode_attention_paged,
                                               decode_attention_spec_paged,
                                               one_hot_embedder)
from deeplearning4j_tpu.serving.engine import (GenerationResult, Request,
                                               ServingEngine)
from deeplearning4j_tpu.serving.kv_cache import KVCache, init_cache_state
from deeplearning4j_tpu.serving.lifecycle import (HostBlockPool,
                                                  KVLifecycleManager,
                                                  PersistentPrefixStore,
                                                  resolve_lifecycle,
                                                  resolve_prefix_store)
from deeplearning4j_tpu.serving.loadgen import (LoadResult, LoadSpec,
                                                RequestOutcome,
                                                ScheduledRequest,
                                                build_schedule, run_spec)
from deeplearning4j_tpu.serving.sampler import (Sampler, sample_tokens,
                                                spec_accept_tokens)
from deeplearning4j_tpu.serving.sharding import (ShardedServingEngine,
                                                 ShardedServingGroup,
                                                 build_serving_mesh,
                                                 cache_partition_specs,
                                                 head_sharded_paged_attention,
                                                 head_sharded_spec_attention,
                                                 make_shard_and_gather_fns,
                                                 match_partition_rules,
                                                 resolve_replicas, resolve_tp,
                                                 serving_partition_rules)
from deeplearning4j_tpu.serving.spec import (NgramDraftIndex,
                                             resolve_spec_decode,
                                             resolve_spec_draft)

__all__ = [
    "KVCache", "init_cache_state", "BlockAllocator", "PrefixRegistry",
    "HostBlockPool", "KVLifecycleManager", "PersistentPrefixStore",
    "resolve_lifecycle", "resolve_prefix_store",
    "StackDecoder", "decode_attention", "decode_attention_paged",
    "decode_attention_spec_paged",
    "one_hot_embedder", "ServingEngine", "Request", "GenerationResult",
    "Sampler", "sample_tokens", "spec_accept_tokens",
    "NgramDraftIndex", "resolve_spec_decode", "resolve_spec_draft",
    "LoadSpec", "LoadResult", "RequestOutcome", "ScheduledRequest",
    "build_schedule", "run_spec",
    "ShardedServingEngine", "ShardedServingGroup", "build_serving_mesh",
    "cache_partition_specs", "head_sharded_paged_attention",
    "head_sharded_spec_attention",
    "make_shard_and_gather_fns", "match_partition_rules",
    "resolve_replicas", "resolve_tp", "serving_partition_rules",
]
