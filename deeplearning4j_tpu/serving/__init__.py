"""Autoregressive serving: paged (block-table) KV cache with copy-on-write
prefix sharing, cached single-query decode, continuous-batching engine,
sampling. See serving/engine.py for the design overview;
`ParallelInference(inference_mode=InferenceMode.GENERATE)` exposes the
engine behind the existing inference API."""
from deeplearning4j_tpu.serving.block_table import (BlockAllocator,
                                                    PrefixRegistry)
from deeplearning4j_tpu.serving.decode import (StackDecoder, decode_attention,
                                               decode_attention_paged,
                                               one_hot_embedder)
from deeplearning4j_tpu.serving.engine import (GenerationResult, Request,
                                               ServingEngine)
from deeplearning4j_tpu.serving.kv_cache import KVCache, init_cache_state
from deeplearning4j_tpu.serving.loadgen import (LoadResult, LoadSpec,
                                                RequestOutcome,
                                                ScheduledRequest,
                                                build_schedule, run_spec)
from deeplearning4j_tpu.serving.sampler import Sampler, sample_tokens

__all__ = [
    "KVCache", "init_cache_state", "BlockAllocator", "PrefixRegistry",
    "StackDecoder", "decode_attention", "decode_attention_paged",
    "one_hot_embedder", "ServingEngine", "Request", "GenerationResult",
    "Sampler", "sample_tokens",
    "LoadSpec", "LoadResult", "RequestOutcome", "ScheduledRequest",
    "build_schedule", "run_spec",
]
