"""Multi-chip sharded serving: tensor-parallel decode + replica groups.

ISSUE 10 (beyond-reference; Orca OSDI '22 replica scheduling +
PagedAttention SOSP '23 KV framing; fmengine-style partition rules from
SNIPPETS.md). Two orthogonal axes on one `(replica, tensor)` device mesh:

- TENSOR parallelism (`ShardedServingEngine`): one engine whose params
  and paged KV pool are head-sharded over the `tensor` axis. Attention is
  head-local — q/k/v projections are column-parallel (whole heads per
  shard, GQA grouping preserved by contiguous splits whenever the TP
  degree divides n_kv_heads), the paged decode kernel runs unchanged per
  shard under shard_map (ops/decode_attention.paged_decode_specs), and
  the only cross-chip collective per decode step is the all-reduce GSPMD
  inserts for the row-parallel output projection. Block tables, lengths,
  and every scheduler-visible array stay replicated, so the host
  scheduler is UNTOUCHED: same admission, same chunking, same sync
  count per token (tests assert host-sync bit-parity).

- DATA parallelism (`ShardedServingGroup`): N independent engine
  replicas, each on its own row of the mesh (parallel/mesh.py
  `replica_submeshes`), behind one submit()/step()/stats() facade that
  ParallelInference drives exactly like a single engine. Routing is
  prefix-affinity first (read-only PrefixRegistry.match against each
  replica's registry, so identical prompts land where their KV already
  lives), then cohort affinity for not-yet-resident prompts, then
  least-loaded with a round-robin tie-break over existing stats()
  snapshots. Each replica gets a child telemetry registry parented to
  the group's (the parent/child adoption in telemetry/registry.py was
  built for this), so per-replica metrics stay isolated while the
  process-wide /metrics exposition aggregates all of them.

Env knobs: `DL4J_TPU_TP` (tensor-parallel degree) and
`DL4J_TPU_REPLICAS` (engine replicas); both default 1 and multiply to
the device requirement. All shapes are CPU-testable via
`XLA_FLAGS=--xla_force_host_platform_device_count=8`.
"""
from __future__ import annotations

import os
import re
import threading
from concurrent.futures import ThreadPoolExecutor
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from deeplearning4j_tpu import telemetry
from deeplearning4j_tpu.telemetry import journal as _journal
from deeplearning4j_tpu.ops.decode_attention import (paged_decode_specs,
                                                     paged_spec_decode_specs)
from deeplearning4j_tpu.parallel.mesh import (compat_shard_map, make_mesh,
                                              replica_submeshes)
from deeplearning4j_tpu.serving.block_table import PrefixRegistry
from deeplearning4j_tpu.serving.radix_tree import (RadixPrefixTree,
                                                   resolve_prefix_radix)
from deeplearning4j_tpu.serving.decode import (StackDecoder,
                                               decode_attention_paged,
                                               decode_attention_spec_paged)
from deeplearning4j_tpu.serving.engine import Request, ServingEngine
from deeplearning4j_tpu.serving.kv_cache import resolve_block_size
from deeplearning4j_tpu.serving.lifecycle import resolve_prefix_store
from deeplearning4j_tpu.serving.policy import resolve_policy

__all__ = [
    "match_partition_rules", "make_shard_and_gather_fns", "named_tree_map",
    "serving_partition_rules", "cache_partition_specs",
    "resolve_tp", "resolve_replicas", "build_serving_mesh",
    "head_sharded_paged_attention", "head_sharded_spec_attention",
    "ShardedServingEngine", "ShardedServingGroup", "GROUP_SUMMED_KEYS",
]


def _is_spec(x) -> bool:
    return isinstance(x, P)


# Engine-lifetime counters and point-in-time gauges that
# ShardedServingGroup.stats() sums replica-wise into the fleet view.
# Pinned by tests/test_sharded_serving.py: every key must exist in
# ServingEngine.stats(), and new fleet-meaningful counters belong HERE —
# PR 11's spec-decode counters were silently dropped from the aggregate
# exactly because this list was inlined and easy to forget.
GROUP_SUMMED_KEYS: Tuple[str, ...] = (
    "host_syncs", "tokens_out", "queue_depth", "active_slots",
    "free_slots", "kv_blocks_free", "kv_blocks_shared", "kv_rejections",
    "prefix_hits", "prefix_shared_tokens", "prefill_chunks",
    "nonfinite_chunks", "admission_retries",
    "spec_tokens_accepted", "spec_tokens_rejected",
    "kv_evictions_recompute", "kv_evictions_swap", "kv_preemptions",
    "kv_swap_out_bytes", "kv_swap_in_bytes", "kv_host_pool_bytes",
    "prefix_store_hits", "prefix_store_tokens",
    # ISSUE 16: radix-tree residency + popular-prefix signal, fleet-wide
    "prefix_lineage_hits", "kv_blocks_cached",
    # ISSUE 17: disaggregated prefill/decode — cross-replica KV
    # migration volume and the per-role admission split
    "kv_transfer_out", "kv_transfer_in", "kv_transfer_bytes",
    "role_prefill_requests", "role_decode_requests",
    # ISSUE 18: hierarchical KV storage — disk-tier traffic, async
    # swap-out harvests, and lost-spill recompute fallbacks, fleet-wide
    "kv_disk_pool_bytes", "kv_disk_demotions", "kv_disk_promotions",
    "kv_swap_harvests", "kv_pending_swaps", "kv_swap_lost",
    # ISSUE 14: group snapshot_seq = per-replica scheduler-iteration
    # counters summed — still strictly monotonic while any replica steps,
    # so scrapers can detect stale/torn fleet snapshots the same way
    "snapshot_seq",
    # ISSUE 19: SLO verdicts and burn-rate alerts, fleet-wide (both are
    # plain counters that read 0 on engines without a budget/monitor)
    "slo_violations", "alerts_total",
)


# --------------------------------------------------------- partition rules
def _path_name(path) -> str:
    """'/'-joined name for a pytree key path ("0/w_q" for params[0]["w_q"])."""
    parts = []
    for k in path:
        if isinstance(k, jax.tree_util.SequenceKey):
            parts.append(str(k.idx))
        elif isinstance(k, jax.tree_util.DictKey):
            parts.append(str(k.key))
        elif isinstance(k, jax.tree_util.GetAttrKey):
            parts.append(k.name)
        else:
            parts.append(str(k))
    return "/".join(parts)


def named_tree_map(fn, tree, is_leaf=None):
    """tree_map where `fn(name, leaf)` sees the '/'-joined key path — the
    addressing scheme the regex partition rules match against."""
    return jax.tree_util.tree_map_with_path(
        lambda path, x: fn(_path_name(path), x), tree, is_leaf=is_leaf)


def match_partition_rules(rules: Sequence[Tuple[str, P]], params):
    """Map every param leaf to a PartitionSpec by regex over its path name
    (the fmengine pattern, SNIPPETS.md): scalars and single-element leaves
    are always replicated, otherwise the FIRST rule whose pattern
    re.search-matches the '/'-joined path wins, and an unmatched leaf is a
    hard error — silent replication of a tensor someone meant to shard is
    how HBM budgets quietly blow up."""
    def match(name, leaf):
        if getattr(leaf, "ndim", 0) == 0 or int(np.prod(np.shape(leaf))) == 1:
            return P()
        for pattern, spec in rules:
            if re.search(pattern, name) is not None:
                return spec
        raise ValueError(f"no partition rule matched param {name!r} "
                         f"(shape {np.shape(leaf)}); add a rule or an "
                         "explicit catch-all")
    return named_tree_map(match, params)


def make_shard_and_gather_fns(partition_specs, mesh: Mesh):
    """Per-leaf `(shard_fns, gather_fns)` trees for a spec tree: shard_fns
    device_put leaves onto `mesh` under their spec; gather_fns pull a
    sharded leaf back to a single host ndarray (checkpoint/debug path —
    NEVER the decode hot loop)."""
    def make_shard(spec):
        sharding = NamedSharding(mesh, spec)

        def shard_fn(x):
            return jax.device_put(x, sharding)
        return shard_fn

    def make_gather(spec):
        del spec    # gather is always to fully-replicated host memory

        def gather_fn(x):
            # sync-ok: explicit gather-to-host entry point (checkpointing)
            return np.asarray(jax.device_put(x, NamedSharding(mesh, P())))
        return gather_fn

    shard_fns = jax.tree_util.tree_map(make_shard, partition_specs,
                                       is_leaf=_is_spec)
    gather_fns = jax.tree_util.tree_map(make_gather, partition_specs,
                                        is_leaf=_is_spec)
    return shard_fns, gather_fns


def serving_partition_rules(tensor_axis: str = "tensor"):
    """Partition rules for a StackDecoder param stack (list-of-dicts,
    paths like "0/w_q"). Megatron-style within each attention layer:
    q/k/v projections column-parallel (the head dim is the contiguous
    column tail, so a contiguous split is a whole-heads split), the
    output projection row-parallel (its all-reduce is THE per-step
    collective), biases and every position-wise layer replicated."""
    col = P(None, tensor_axis)
    row = P(tensor_axis, None)
    return [
        (r"w_q$", col),
        (r"w_k$", col),
        (r"w_v$", col),
        (r"w_o$", row),
        # everything else (attention bias, output head W/b, position-wise
        # layers) is small relative to the KV pool: replicate
        (r".", P()),
    ]


def cache_partition_specs(tensor_axis: str = "tensor",
                          quantized: bool = False) -> Dict[str, P]:
    """Specs for the paged cache pytree (kv_cache.init_cache_state):
    k/v pools `(n_layers, num_blocks+1, block_size, Hk, D)` sharded on the
    kv-head axis, lengths and block tables replicated (the host scheduler
    reads and writes them; they are bytes-trivial). A quantized pool
    (ISSUE 15) adds per-head-per-block scale arrays
    `(n_layers, num_blocks+1, Hk)` — scales shard WITH their heads, so
    each chip dequantizes its own head slice with zero collectives."""
    heads = P(None, None, None, tensor_axis, None)
    specs = {"k": heads, "v": heads, "lengths": P(), "block_tables": P()}
    if quantized:
        specs["k_scale"] = P(None, None, tensor_axis)
        specs["v_scale"] = P(None, None, tensor_axis)
    return specs


# ------------------------------------------------------------- env knobs
def _resolve_degree(explicit, env: str) -> int:
    v = int(explicit) if explicit is not None \
        else int(os.environ.get(env, "1"))
    if v < 1:
        raise ValueError(f"{env} must be >= 1, got {v}")
    return v


def resolve_tp(explicit: Optional[int] = None) -> int:
    """Tensor-parallel degree: explicit arg, else $DL4J_TPU_TP, else 1."""
    return _resolve_degree(explicit, "DL4J_TPU_TP")


def resolve_replicas(explicit: Optional[int] = None) -> int:
    """Engine replica count: explicit arg, else $DL4J_TPU_REPLICAS, else 1."""
    return _resolve_degree(explicit, "DL4J_TPU_REPLICAS")


def build_serving_mesh(replicas: int, tp: int,
                       replica_axis: str = "replica",
                       tensor_axis: str = "tensor") -> Mesh:
    """The `(replica, tensor)` serving mesh: row r = replica r's TP group."""
    return make_mesh(replicas * tp, axes=(replica_axis, tensor_axis),
                     shape=(replicas, tp))


# ------------------------------------------------- head-sharded attention
def head_sharded_paged_attention(mesh: Mesh, tensor_axis: str = "tensor"):
    """A drop-in for serving.decode.decode_attention_paged that runs the
    SAME kernel (Pallas split-K on TPU, dense paged fallback elsewhere)
    per head-shard under shard_map. Head-local attention needs no
    collective in the body (see paged_decode_specs), so TP changes only
    WHERE heads run, not what they compute. A quantized pool (ISSUE 15)
    passes k_scale/v_scale — the scale arrays split on THEIR head axis
    alongside the pool, so dequant stays chip-local too."""
    in_specs, out_spec = paged_decode_specs(tensor_axis)
    in_specs_q, _ = paged_decode_specs(tensor_axis, quantized=True)

    def attention(q, kp, vp, block_tables, visible, scale, window: int = 0,
                  k_scale=None, v_scale=None):
        if k_scale is None:
            def local(qs, kps, vps, bt, vis):
                return decode_attention_paged(qs, kps, vps, bt, vis, scale,
                                              window)
            sharded = compat_shard_map(local, mesh, in_specs, out_spec)
            return sharded(q, kp, vp, block_tables, visible)

        def local_q(qs, kps, vps, bt, vis, ks, vs):
            return decode_attention_paged(qs, kps, vps, bt, vis, scale,
                                          window, k_scale=ks, v_scale=vs)
        sharded = compat_shard_map(local_q, mesh, in_specs_q, out_spec)
        return sharded(q, kp, vp, block_tables, visible, k_scale, v_scale)

    return attention


def head_sharded_spec_attention(mesh: Mesh, tensor_axis: str = "tensor"):
    """Head-sharded multi-query VERIFY attention for speculative decoding
    (ISSUE 11): the widened query tile (S, Q, H, D) splits on the head
    axis exactly like single-query decode, so the spec kernel runs
    head-local under shard_map with ZERO new collectives — verification
    costs the same communication as one plain decode step. Quantized
    pools (ISSUE 15) ride k_scale/v_scale head-sharded the same way."""
    in_specs, out_spec = paged_spec_decode_specs(tensor_axis)
    in_specs_q, _ = paged_spec_decode_specs(tensor_axis, quantized=True)

    def attention(q, kp, vp, block_tables, visible, scale, window: int = 0,
                  k_scale=None, v_scale=None):
        if k_scale is None:
            def local(qs, kps, vps, bt, vis):
                return decode_attention_spec_paged(qs, kps, vps, bt, vis,
                                                   scale, window)
            sharded = compat_shard_map(local, mesh, in_specs, out_spec)
            return sharded(q, kp, vp, block_tables, visible)

        def local_q(qs, kps, vps, bt, vis, ks, vs):
            return decode_attention_spec_paged(qs, kps, vps, bt, vis, scale,
                                               window, k_scale=ks,
                                               v_scale=vs)
        sharded = compat_shard_map(local_q, mesh, in_specs_q, out_spec)
        return sharded(q, kp, vp, block_tables, visible, k_scale, v_scale)

    return attention


# ------------------------------------------------------ tensor-parallel TP
class ShardedServingEngine(ServingEngine):
    """A ServingEngine whose decoder params and paged KV pool live
    head-sharded on a single-axis tensor mesh.

    Same host scheduler, same API, same token stream (greedy decode is
    bit-identical to the single-chip engine; fp64 oracle parity holds to
    1e-9): the only differences are WHERE tensors live and the per-chip
    byte accounting — `serving.kv_bytes_resident` / `kv_cache_bytes`
    report PER-DEVICE bytes (1/TP of the logical pool), which is the
    number capacity planning actually needs."""

    def __init__(self, net, max_seqs: int, max_len: int, *,
                 tp: Optional[int] = None, mesh: Optional[Mesh] = None,
                 tensor_axis: str = "tensor", **kw):
        # mesh/tp must exist before super().__init__ runs _build_decoder
        self.tensor_axis = tensor_axis
        if mesh is not None:
            if mesh.axis_names != (tensor_axis,):
                raise ValueError(f"expected a 1-axis ({tensor_axis!r},) "
                                 f"mesh, got axes {mesh.axis_names}")
            self.mesh = mesh
            self.tp = int(mesh.devices.size)
        else:
            self.tp = resolve_tp(tp)
            self.mesh = make_mesh(self.tp, axes=(tensor_axis,))
        super().__init__(net, max_seqs, max_len, **kw)
        cache = self.decoder.cache
        # per-DEVICE byte semantics: the pool is head-sharded, so each chip
        # holds 1/TP of every position's KV bytes (Hk % tp == 0 makes the
        # division exact)
        self._kv_bytes_per_pos = cache.bytes_per_position // self.tp
        # quantized-pool scale bytes split with their heads (Hk % tp == 0)
        self._kv_block_overhead = cache.block_overhead_bytes // self.tp
        self._g_kv_total.set(cache.bytes() // self.tp)
        self._g_params.set(self._sharded_param_bytes())
        self._g_tp = self.metrics.gauge(
            "serving.tensor_parallel", "tensor-parallel degree (heads are "
            "sharded over this many chips)")
        self._g_tp.set(self.tp)
        # pin the per-slot device state to the mesh (replicated) so eager
        # slot updates between iterations stay on the engine's devices
        rep = NamedSharding(self.mesh, P())
        self._hist = jax.device_put(self._hist, rep)
        self._last = jax.device_put(self._last, rep)
        self._plens = jax.device_put(self._plens, rep)
        self._eos = jax.device_put(self._eos, rep)
        self._maxgen = jax.device_put(self._maxgen, rep)

    # ------------------------------------------------------------- seams
    def _build_decoder(self, net, max_seqs, max_len, **kw) -> StackDecoder:
        dec = StackDecoder(
            net, max_seqs, max_len,
            paged_attention=head_sharded_paged_attention(self.mesh,
                                                         self.tensor_axis),
            paged_spec_attention=head_sharded_spec_attention(
                self.mesh, self.tensor_axis),
            **kw)
        tp = self.tp
        if dec.n_kv_heads % tp:
            raise ValueError(
                f"tensor-parallel degree {tp} does not divide n_kv_heads "
                f"{dec.n_kv_heads} — GQA head sharding needs whole kv "
                "heads per chip (lower DL4J_TPU_TP or widen the model)")
        for i in dec.attn_idx:
            layer = dec.layers[i]
            if layer.n_heads % tp:
                raise ValueError(
                    f"tensor-parallel degree {tp} does not divide layer "
                    f"{i}'s n_heads {layer.n_heads}")
        self._param_specs = match_partition_rules(
            serving_partition_rules(self.tensor_axis), dec.params)
        self._cache_specs = cache_partition_specs(
            self.tensor_axis, quantized=dec.cache.kv_quant)
        to_sharding = lambda spec: NamedSharding(self.mesh, spec)
        self._param_shardings = jax.tree_util.tree_map(
            to_sharding, self._param_specs, is_leaf=_is_spec)
        self._cache_shardings = {k: to_sharding(s)
                                 for k, s in self._cache_specs.items()}
        shard_fns, self._gather_fns = make_shard_and_gather_fns(
            self._param_specs, self.mesh)
        dec.params = jax.tree_util.tree_map(lambda f, x: f(x), shard_fns,
                                            dec.params)
        dec.cache.state = jax.device_put(dec.cache.state,
                                         self._cache_shardings)
        # pin pjit shardings on the decoder's own entry points so the
        # prefill and suffix/chunk passes are tensor-parallel end to end
        # (the scatter into the head-sharded pool partitions on Hk; the
        # dense prompt attention replicates — prompt activations are tiny
        # next to the pool)
        ps, cs = self._param_shardings, self._cache_shardings
        rep = NamedSharding(self.mesh, P())
        dec._prefill_jit = jax.jit(
            dec._prefill_fn,
            in_shardings=(ps, cs, rep, rep, rep),
            out_shardings=(cs, rep))
        # older pjit rejects kwargs alongside in_shardings, and the decoder
        # calls the shared prefill with kv_blocks=...: route the keyword
        # through a positional static arg
        _shared_positional = jax.jit(
            lambda p, c, x, s, pl, sh, kvb: dec._prefill_shared_fn(
                p, c, x, s, pl, sh, kv_blocks=kvb),
            static_argnums=(6,),
            in_shardings=(ps, cs, rep, rep, rep, rep),
            out_shardings=(cs, rep))

        def _shared_jit(p, c, x, s, pl, sh, *, kv_blocks):
            return _shared_positional(p, c, x, s, pl, sh, kv_blocks)

        _shared_jit.lower = (  # profiler.register lowers for cost analysis
            lambda p, c, x, s, pl, sh, *, kv_blocks:
            _shared_positional.lower(p, c, x, s, pl, sh, kv_blocks))
        dec._prefill_shared_jit = _shared_jit
        dec._decode_jit = jax.jit(
            dec._decode_fn,
            in_shardings=(ps, cs, rep, rep),
            out_shardings=(cs, rep))
        return dec

    def _jit_decode(self, fn, kind: str):
        """Pin the engine step/chunk pjit shardings: cache pytree keeps its
        head-sharded placement across dispatches (no resharding between
        iterations), every scheduler array replicated."""
        rep = NamedSharding(self.mesh, P())
        # spec (ISSUE 11) takes two extra replicated inputs (draft ids +
        # per-slot draft lengths) and returns the commit bundle
        n_out = {"step": 6, "chunk": 7, "spec": 9}[kind]
        n_in = 10 if kind == "spec" else 8
        in_s = (self._param_shardings, self._cache_shardings) + \
            (rep,) * n_in
        out_s = (self._cache_shardings,) + (rep,) * (n_out - 1)
        return jax.jit(fn, in_shardings=in_s, out_shardings=out_s)

    def _sharded_param_bytes(self) -> int:
        """Per-device param bytes: tensor-sharded leaves count 1/TP."""
        total = 0
        leaves = jax.tree_util.tree_leaves(self.decoder.params)
        specs = jax.tree_util.tree_leaves(self._param_specs,
                                          is_leaf=_is_spec)
        for leaf, spec in zip(leaves, specs):
            nb = leaf.size * leaf.dtype.itemsize
            total += nb // self.tp if self.tensor_axis in spec else nb
        return total

    def stats(self) -> Dict[str, float]:
        s = super().stats()
        s["tp"] = self.tp
        return s


# --------------------------------------------------- data-parallel group
class ShardedServingGroup:
    """N independent (optionally tensor-parallel) engine replicas behind
    one engine-shaped facade: submit/step/drain/generate/start/shutdown/
    stats match ServingEngine, so ParallelInference and loadgen.run drive
    a group unchanged.

    Replicas share NOTHING on device — each owns a mesh row, its params,
    its KV pool, and its scheduler. What spans replicas is host-side:
    the admission router and the group telemetry registry (each engine's
    child registry is parented here, so the process /metrics exposition
    aggregates the fleet while per-replica stats stay isolated).

    Scheduling decisions live on ONE policy object (ISSUE 17,
    serving/policy.py), consulted under the group lock (host-only —
    zero device syncs). The default `ColocatedPolicy` routes exactly as
    the group always did: prefix affinity (the replica whose
    PrefixRegistry already holds the longest matching resident prefix)
    -> cohort affinity (prompts sharing a leading KV block follow the
    first of their kind, so a cohort's FIRST prompt seeds the registry
    the rest will hit) -> published-heat affinity (ISSUE 17 satellite:
    lineage heat replicas publish through the shared prefix store) ->
    least-loaded (queue_depth + active_slots) with a rotating
    round-robin tie-break. `DisaggregatedPolicy` (serving/disagg.py,
    or env DL4J_TPU_DISAGG=<n>) splits the replicas into PREFILL and
    DECODE roles: new requests route to prefill rows only, and each
    finished prefill's live KV ships to a decode row through the
    engines' transfer seam (`_transfer_from`)."""

    def __init__(self, net, max_seqs: int, max_len: int, *,
                 replicas: Optional[int] = None, tp: Optional[int] = None,
                 seed: int = 0, replica_axis: str = "replica",
                 tensor_axis: str = "tensor", metrics_parent=None,
                 **engine_kw):
        self.replicas = resolve_replicas(replicas)
        self.tp = resolve_tp(tp)
        self.mesh = build_serving_mesh(self.replicas, self.tp,
                                       replica_axis, tensor_axis)
        self.metrics = telemetry.MetricsRegistry(
            parent=metrics_parent if metrics_parent is not None
            else telemetry.registry())
        self._g_replicas = self.metrics.gauge(
            "serving.replicas", "data-parallel engine replicas in the group")
        self._g_replicas.set(self.replicas)
        self._c_routed = self.metrics.counter(
            "serving.router_requests", "requests routed by the group")
        self._c_affinity = self.metrics.counter(
            "serving.router_prefix_affinity", "requests routed to a replica "
            "because its registry already held a matching resident prefix")
        self._c_heat = self.metrics.counter(
            "serving.router_heat_affinity", "requests routed to a replica "
            "by published lineage heat (no resident match anywhere, but "
            "this replica recently served the prefix — ISSUE 17)")
        self._c_transfers = self.metrics.counter(
            "serving.router_transfers", "finished prefills handed from a "
            "prefill-role replica to a decode-role replica (ISSUE 17)")
        # fleet KV gauges (ISSUE 12): group-level names are disjoint from
        # the per-engine serving.kv.* observatory gauges, so the parented
        # prometheus exposition shows both layers without double counting
        self._g_fleet_free = self.metrics.gauge(
            "serving.kv.fleet_bytes_free", "free KV bytes summed across "
            "every replica's pool")
        self._g_fleet_shared = self.metrics.gauge(
            "serving.kv.fleet_bytes_shared", "prefix-shared KV bytes "
            "(each shared block counted once) summed across replicas")
        self._g_fleet_live = self.metrics.gauge(
            "serving.kv.fleet_bytes_private_live", "privately owned live "
            "KV bytes summed across replicas")
        self._g_fleet_waste = self.metrics.gauge(
            "serving.kv.fleet_bytes_waste", "tail + reserved-but-unwritten "
            "KV bytes summed across replicas")
        self._g_fleet_imbal = self.metrics.gauge(
            "serving.kv.fleet_imbalance", "max-min spread of per-replica "
            "used-block fraction (0 = perfectly balanced fleet)")
        block_size = resolve_block_size(engine_kw.get("kv_block"), max_len)
        # per-replica registry handles: owned (bound) by each replica's KV
        # pool, read by the router for affinity — block ids never cross
        # replicas (see block_table.PrefixRegistry.bind_pool). With the
        # radix tree on (ISSUE 16) each replica gets its own tree; the
        # router's longest-prefix affinity then routes a session's next
        # turn to the replica RETAINING its history, which is what makes
        # cross-turn reuse survive replica fan-out.
        reg_cls = (RadixPrefixTree
                   if resolve_prefix_radix(engine_kw.get("prefix_radix"))
                   else PrefixRegistry)
        self.registries = [reg_cls(block_size)
                           for _ in range(self.replicas)]
        # ONE persistent prefix store for the whole group (ISSUE 13):
        # unlike PrefixRegistry entries, store entries are content-keyed
        # BYTES (no pool-scoped block ids), so a prompt prefilled on one
        # replica is restorable on every other — resolved here so all
        # replicas share the same instance instead of each resolving its
        # own from the environment
        self.prefix_store = resolve_prefix_store(
            engine_kw.pop("prefix_store", None))
        # ONE scheduling-policy object for the whole group (ISSUE 17):
        # routing state (cohort map, rotation cursors) lives on it, and
        # every engine consults the SAME instance at its own decision
        # points (admission, TTL eviction)
        self.policy = resolve_policy(engine_kw.pop("policy", None)) \
            .bind(self.replicas)
        # ONE group-level decision journal (ISSUE 20, replica=-1): it owns
        # the cross-replica records (route/transfer) while each engine
        # journals its own admission/preempt/spec stream into a child
        # journal (a replica<r> subdirectory when persisting).
        # fleet_journal() merges them ordered by (tick, replica, seq).
        self.journal = _journal.resolve_journal(
            engine_kw.pop("journal", None), replica=-1)
        # group-journal records arrive from submit (group lock held) AND
        # from prefill engines' scheduler threads (_transfer_from, engine
        # lock held — taking the group lock there would deadlock against
        # submit's group-lock -> engine-lock order), so they serialize on
        # a dedicated leaf lock instead
        self._jlock = threading.Lock()
        # serial_step (ISSUE 20, env DL4J_TPU_GROUP_SERIAL): force
        # index-ordered serial stepping so cross-replica interactions
        # (prefill->decode KV adoption) land at a deterministic point in
        # every replica's tick stream — both journal recording and replay
        # of a group run require it
        serial = engine_kw.pop("serial_step", None)
        if serial is None:
            serial = os.environ.get(
                "DL4J_TPU_GROUP_SERIAL", "") not in ("", "0", "off")
        self.serial_step = bool(serial)
        self.engines: List[ShardedServingEngine] = []
        base_name = engine_kw.pop("name", None) or "replica"
        for r, submesh in enumerate(replica_submeshes(self.mesh,
                                                      tensor_axis)):
            eng = ShardedServingEngine(
                net, max_seqs, max_len, mesh=submesh,
                tensor_axis=tensor_axis, seed=seed + r,
                metrics_parent=self.metrics,
                prefix_registry=self.registries[r],
                prefix_store=self.prefix_store,
                policy=self.policy,
                name=f"{base_name}{r}",
                journal=(_journal.child_journal(self.journal, r)
                         if self.journal is not None else False),
                **engine_kw)
            # replica identity (ISSUE 14 satellite): labels the engine's
            # tracer track and flight-recorder records so multi-replica
            # Perfetto dumps are distinguishable
            eng.replica_id = r
            # disaggregation wiring (ISSUE 17): prefill-role engines get
            # the transfer callback that ships each finished prefill's
            # live KV to the decode row the policy picks
            eng.role = self.policy.role(r)
            if eng.role == "prefill":
                eng._transfer_cb = \
                    lambda act, _r=r: self._transfer_from(_r, act)
            self.engines.append(eng)
        self._lock = threading.Lock()
        # replicas are independent chips: drive them CONCURRENTLY per
        # step() so one replica's chunk dispatch never serializes behind
        # another's (each engine is only ever stepped by one worker at a
        # time — step() joins before returning). On a single-core host the
        # threads would only time-slice one processor and the contention
        # is pure loss, so the fan-out is capped at the core count.
        workers = 1 if self.serial_step \
            else min(self.replicas, os.cpu_count() or 1)
        self._pool = (ThreadPoolExecutor(
            max_workers=workers, thread_name_prefix="dl4j-replica")
            if workers > 1 else None)

    # ------------------------------------------------------------ routing
    def _fleet_view(self) -> Dict[str, object]:
        """The host-bookkeeping view the policy's route/transfer
        decisions read. `stats_fn` is lazy (one engine-lock snapshot
        per replica the policy actually inspects), so affinity hits
        never pay a stats() sweep — exactly the pre-policy behavior."""
        return {"registries": self.registries,
                "block_size": self.registries[0].block_size,
                "n": self.replicas,
                "store": self.prefix_store,
                "stats_fn": lambda r: self.engines[r].stats()}

    def _route(self, req: Request) -> int:
        replica, reason = self.policy.route(req, self._fleet_view())
        if reason == "prefix_affinity":
            self._c_affinity.inc()
        elif reason == "heat":
            self._c_heat.inc()
        if self.journal is not None:
            # tick = the ROUTED replica's allocator clock: the replayer
            # paces this arrival against that same clock (host attribute
            # read — no device touch)
            with self._jlock:
                self.journal.record(
                    "route",
                    tick=self.engines[replica].decoder.cache.allocator.clock,
                    dst=replica, reason=reason, plen=len(req.tokens))
        return replica

    def _transfer_from(self, src: int, act) -> None:
        """Prefill->decode hand-off (ISSUE 17), called from the SOURCE
        engine's scheduler thread with that engine's lock held: consult
        the policy for the decode target and adopt the request there.
        Deliberately takes NO group lock — the only lock acquired is
        the TARGET engine's (`_adopt`), keeping lock order prefill ->
        decode, one-directional (decode engines never call into prefill
        engines), so no cycle with submit's group-lock -> engine-lock
        path exists."""
        view = self._fleet_view()
        view["tokens"] = list(act.req.tokens)
        view["src"] = src
        target = self.policy.transfer(view)
        self._c_transfers.inc()
        dst = src if target is None else target
        if self.journal is not None:
            # journaled BEFORE the adopt so the transfer verdict precedes
            # the destination's xfer_in record in seq order
            with self._jlock:
                self.journal.record(
                    "transfer",
                    tick=self.engines[src].decoder.cache.allocator.clock,
                    src=src, dst=dst, req=act.req_id)
        # target is always a decode row when the callback is wired; the
        # src fallback is a safety net (src engine's RLock re-enters)
        self.engines[dst]._adopt(act)

    # --------------------------------------------------- engine-shaped API
    def submit(self, request):
        """Route to a replica and queue there; returns that engine's
        future."""
        req = request if isinstance(request, Request) else Request(request)
        with self._lock:
            replica = self._route(req)
            self._c_routed.inc()
        return self.engines[replica].submit(req)

    def step(self) -> bool:
        """One scheduler iteration on EVERY replica, concurrently (one
        worker per replica, joined before returning — the engines' own
        device streams already run independently; this keeps their HOST
        scheduling from serializing too). Returns True while any replica
        has active or queued work."""
        busy = False
        if self._pool is None:
            for engine in self.engines:
                busy = engine.step() or busy
            return busy
        for done in [self._pool.submit(e.step) for e in self.engines]:
            busy = done.result() or busy
        return busy

    def drain(self) -> None:
        while self.step():
            pass

    def generate(self, prompts, **kw):
        futs = [self.submit(p if isinstance(p, Request) else Request(p, **kw))
                for p in prompts]
        self.drain()
        return [f.get(timeout=0) for f in futs]

    def start(self) -> "ShardedServingGroup":
        for engine in self.engines:
            engine.start()
        return self

    def shutdown(self, wait: bool = True) -> None:
        for engine in self.engines:
            engine.shutdown(wait=wait)
        if self._pool is not None:
            self._pool.shutdown(wait=wait)
        if self.journal is not None:
            self.journal.flush()

    def fleet_journal(self) -> List[dict]:
        """The merged fleet decision stream (ISSUE 20): group-level
        route/transfer records (replica=-1) interleaved with every
        replica's own journal, ordered by (tick, replica, seq) — the
        input serving/replay.py's group replayer consumes."""
        journals = [j for j in [self.journal]
                    + [e.journal for e in self.engines] if j is not None]
        return _journal.merge_fleet(journals)

    def stats(self) -> Dict[str, object]:
        """Fleet view: lifetime counters summed across replicas
        (GROUP_SUMMED_KEYS), group-wide derived ratios recomputed from the
        sums (a mean of per-replica ratios would weight an idle replica
        like a saturated one), plus the per-replica snapshots (each taken
        under its engine's lock)."""
        per = [engine.stats() for engine in self.engines]
        agg: Dict[str, object] = {
            "replicas": self.replicas, "tp": self.tp,
            "router_requests": self._c_routed.value,
            "router_prefix_affinity": self._c_affinity.value,
            "router_heat_affinity": self._c_heat.value,
            "router_transfers": self._c_transfers.value,
            "policy": type(self.policy).__name__,
            "roles": [self.policy.role(r) for r in range(self.replicas)],
            "journal": (self.journal.stats()
                        if self.journal is not None else None),
            "per_replica": per,
        }
        for key in GROUP_SUMMED_KEYS:
            agg[key] = sum(s.get(key, 0) for s in per)
        agg["host_syncs_per_token"] = \
            agg["host_syncs"] / max(1, agg["tokens_out"])
        agg["spec_accept_rate"] = agg["spec_tokens_accepted"] / max(
            1, agg["spec_tokens_accepted"] + agg["spec_tokens_rejected"])
        agg["resident_seqs_max"] = max(
            (s.get("resident_seqs_max", 0) for s in per), default=0)
        # used-block imbalance straight from the per-replica snapshots —
        # num_blocks is a host attribute, so this stays sync-free
        fracs = [(e.decoder.cache.num_blocks - s["kv_blocks_free"])
                 / max(1, e.decoder.cache.num_blocks)
                 for e, s in zip(self.engines, per)]
        agg["kv_used_imbalance"] = \
            (max(fracs) - min(fracs)) if fracs else 0.0
        return agg

    def kv_fleet_snapshot(self) -> Dict[str, object]:
        """Fleet-wide KV memory attribution (ISSUE 12): one atomic pool
        snapshot per replica (each under its engine's scheduler lock),
        attributed via telemetry.kv_observatory.attribute_pool, summed
        into the group's serving.kv.fleet_* gauges. Per-replica entries
        keep their own attribution so a hot replica is visible next to an
        idle one; `imbalance` is the max-min spread of used-block
        fraction. Host-side bookkeeping only — zero device reads."""
        from deeplearning4j_tpu.telemetry.kv_observatory import \
            attribute_pool
        fleet = {"pool_bytes": 0, "free_bytes": 0, "shared_bytes": 0,
                 "private_live_bytes": 0, "waste_tail_bytes": 0,
                 "waste_reserved_bytes": 0, "cached_prefix_bytes": 0}
        per: List[Dict[str, object]] = []
        fracs: List[float] = []
        for r, engine in enumerate(self.engines):
            snap = engine.kv_pool_snapshot()
            att = attribute_pool(snap)
            for key in fleet:
                fleet[key] += att[key]
            n = snap["num_blocks"]
            used = n - snap["blocks_free"]
            fracs.append(used / max(1, n))
            per.append({"replica": r, "blocks_used": used,
                        "blocks_free": snap["blocks_free"],
                        "blocks_shared": snap["blocks_shared"],
                        "clock": snap["clock"],
                        "attribution": att})
        imbalance = (max(fracs) - min(fracs)) if fracs else 0.0
        self._g_fleet_free.set(fleet["free_bytes"])
        self._g_fleet_shared.set(fleet["shared_bytes"])
        self._g_fleet_live.set(fleet["private_live_bytes"])
        self._g_fleet_waste.set(fleet["waste_tail_bytes"]
                                + fleet["waste_reserved_bytes"])
        self._g_fleet_imbal.set(imbalance)
        return {**fleet, "imbalance": imbalance, "per_replica": per,
                "conserved": all(p["attribution"]["conserved"]
                                 for p in per)}

    def fleet_timeseries(self) -> Dict[str, object]:
        """Fleet time-series view (ISSUE 19): merge every timeseries-
        enabled replica's windowed summary into ONE fleet row —
        rates/queue depths SUM (fleet throughput is the sum of replica
        throughputs), quantiles/ages take the MAX (the fleet tail is its
        worst replica) — published as serving.ts.fleet_* gauges on the
        group registry next to the per-replica serving.ts.* gauges the
        engines publish themselves. Per-replica summaries ride along
        under `per_replica` so a hot replica is visible next to an idle
        one. Host-side arithmetic only — zero device reads."""
        from deeplearning4j_tpu.telemetry.timeseries import fleet_summary
        summaries = []
        for engine in self.engines:
            if engine.timeseries is not None:
                with engine._lock:
                    summaries.append(engine.timeseries.summary())
        fleet = fleet_summary(summaries)
        for key in ("tokens_per_s", "retirements_per_s",
                    "preemptions_per_s", "queue_depth", "oldest_wait_s",
                    "ttft_p99_s", "tpot_p99_s"):
            if key in fleet:
                self.metrics.gauge(
                    f"serving.ts.fleet_{key}", "fleet-merged windowed "
                    "time-series reading (ISSUE 19)").set(fleet[key])
        fleet["per_replica"] = summaries
        return fleet

    def blame_report(self, results, slo=None, top: int = 3
                     ) -> Dict[str, object]:
        """Fleet blame report (ISSUE 14): run the blame ledger over the
        given finished results/outcomes (from `generate`, a loadgen run,
        or flight-recorder records), join the SLO evaluator's violator
        set, and publish the violators-vs-attainers and per-cohort cause
        breakdowns as serving.blame.* gauges on the group registry.

        Iteration ids in the timelines are process-globally unique, so
        interference edges never pair requests from different replicas
        even though the ledger sees the whole fleet at once. Host-side
        arithmetic over timestamps the engines already took — zero
        device syncs."""
        from deeplearning4j_tpu.telemetry import blame as _blame
        report = _blame.blame_report(results, slo=slo, top=top)
        _blame.publish(report, self.metrics)
        return report
