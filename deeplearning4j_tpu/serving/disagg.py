"""Disaggregated prefill/decode serving (ISSUE 17; DistServe, OSDI'24).

Chunked prefill (PR 9, Sarathi-Serve) bounds prefill/decode
interference by slicing prompts; disaggregation ELIMINATES it by
dedicating whole replica-group rows to one phase. `DisaggregatedPolicy`
splits the group's replicas into PREFILL rows and DECODE rows:

- route: new requests land on a prefill row (prefix affinity -> cohort
  -> published heat -> least-loaded, all restricted to prefill rows).
- The prefill row runs admission + prefill (chunked or monolithic) and
  samples the FIRST token there — TTFT is paid where prefill capacity
  lives, never behind another request's decode batch.
- transfer: the finished request's LIVE KV blocks leave the prefill row
  through the PR 13 gather seam (int8 scales from PR 15 ride along) and
  restore into a decode row's pool (`ServingEngine._resume_transfer`,
  the swap-in path re-aimed across replicas); decode continues there
  bit-identically — replicas share weights, so greedy streams match the
  colocated run token for token. The decode row is chosen hot-first:
  published lineage heat through the shared `PersistentPrefixStore`
  (ISSUE 17 satellite), then resident-prefix match, then least-loaded.

The tradeoff this buys (and the bench_disagg_ab A/B measures): decode
rows never stall behind prefill dispatches — TPOT tails tighten — at
the cost of transfer bytes (live blocks x bytes/block over the host
path) and HALVED per-phase capacity (a TTFT-heavy long-prompt mix
saturates the lone prefill row while colocated prefills on every row).
TTFT-heavy and TPOT-heavy mixes therefore pick DIFFERENT winners;
PERF.md carries the cost model.

Every transfer lands a `kv_transfer` timeline span (bytes, blocks,
queue depth, wall) and blame cause on BOTH sides, so the PR 14
conservation invariant closes over disaggregated requests too.

Sync discipline: pure host bookkeeping — no jax import, no device
access (tests/test_sync_discipline.py scans this module). The device
work (gather/restore) stays in engine.py where it is counted.

Determinism contract (ISSUE 20): route/transfer verdicts are pure
functions of the fleet view and the policy's own cursor state — no wall
clock, no RNG (the test_sync_discipline determinism scan pins this), so
a journaled group run replays bit-exactly by forcing the recorded
verdicts through serving/replay.py's ReplayPolicy in consult order.
"""
from __future__ import annotations

import os
from typing import List, Optional, Tuple

from deeplearning4j_tpu.serving.policy import ColocatedPolicy

__all__ = ["DisaggregatedPolicy", "resolve_prefill_replicas"]


def resolve_prefill_replicas(prefill_replicas=None) -> int:
    """Constructor resolution of the prefill-row count: explicit
    argument wins, else a numeric `DL4J_TPU_DISAGG` value, else 1."""
    if prefill_replicas is None:
        env = os.environ.get("DL4J_TPU_DISAGG", "")
        prefill_replicas = int(env) if env.isdigit() and int(env) > 0 else 1
    return max(1, int(prefill_replicas))


class DisaggregatedPolicy(ColocatedPolicy):
    """Prefill/decode role split over a ShardedServingGroup.

    Rows [0, prefill_replicas) serve PREFILL, the rest DECODE. A group
    with fewer than 2 replicas cannot split — the policy degrades to
    colocated behavior (no roles, no transfer), keeping single-replica
    construction safe."""

    def __init__(self, prefill_replicas: Optional[int] = None, *,
                 slo=None, ttl: Optional[int] = None,
                 ttl_s: Optional[float] = None):
        super().__init__(slo=slo, ttl=ttl, ttl_s=ttl_s)
        self.prefill_replicas = resolve_prefill_replicas(prefill_replicas)
        self.prefill: Tuple[int, ...] = ()
        self.decode: Tuple[int, ...] = ()
        self._t_rr = 0                  # transfer-target rotation cursor

    def bind(self, n_replicas: int) -> "DisaggregatedPolicy":
        super().bind(n_replicas)
        if n_replicas < 2:
            self.prefill = self.decode = ()
            return self
        n_pref = min(self.prefill_replicas, n_replicas - 1)
        self.prefill = tuple(range(n_pref))
        self.decode = tuple(range(n_pref, n_replicas))
        return self

    @property
    def disaggregated(self) -> bool:
        return bool(self.prefill and self.decode)

    def role(self, replica: int) -> str:
        if not self.disaggregated:
            return "colocated"
        return "prefill" if replica in self.prefill else "decode"

    # ------------------------------------------------------------ routing
    def route_candidates(self, fleet_view: dict) -> List[int]:
        if not self.disaggregated:
            return super().route_candidates(fleet_view)
        return list(self.prefill)

    # ----------------------------------------------------------- transfer
    def transfer(self, finished_prefill_view: dict) -> Optional[int]:
        """Pick the DECODE row a finished prefill continues on: hottest
        published lineage first (the row most likely to still hold — or
        cheaply restore — this prefix), else the row whose registry
        holds a resident match, else least-loaded with rotation."""
        if not self.disaggregated:
            return None
        cands = [r for r in self.decode
                 if r != finished_prefill_view.get("src")]
        if not cands:
            cands = list(self.decode)
        tokens = list(finished_prefill_view["tokens"])
        hot = self._heat_choice(tokens, finished_prefill_view, cands)
        if hot is not None:
            return hot
        regs = finished_prefill_view["registries"]
        best, best_len = -1, 0
        for r in cands:
            matched = regs[r].match(tokens)[0]
            if matched > best_len:
                best, best_len = r, matched
        if best >= 0:
            return best
        stats_fn = finished_prefill_view["stats_fn"]
        order = [cands[(self._t_rr + i) % len(cands)]
                 for i in range(len(cands))]
        self._t_rr = (self._t_rr + 1) % len(cands)
        chosen, chosen_load = order[0], None
        for r in order:
            snap = stats_fn(r)
            load = snap["queue_depth"] + snap["active_slots"]
            if chosen_load is None or load < chosen_load:
                chosen, chosen_load = r, load
        return chosen
