"""KV lifecycle under memory pressure: real eviction/preemption, a
host-RAM offload tier, and a persistent prefix store (ISSUE 13).

The ROADMAP named KV lifecycle as the scaling ceiling: at 2-5x
resident-block capacity the engine just queued rejected admissions
forever. PR 12 built the measurement half — telemetry/kv_observatory.py
ranks victims under lru / slo_deadline / refcount_weighted policies with
marginal reclaim and per-candidate recompute-vs-swap costs — but
`dry_run()` evicted nothing. This module makes it real, as a layer
between admission and the block pool:

- `KVLifecycleManager`: policy + cost-model state for REAL eviction.
  When admission fails, the engine asks the manager for a victim plan;
  the plan comes from the observatory's `plan_eviction` — the SAME
  ranking + marginal-reclaim simulation the dry-run forensics record, so
  what the rejection ring says would be evicted and what actually gets
  preempted can never disagree. Per victim the manager picks RECOMPUTE
  (free the blocks; the engine requeues the request with its generated
  history and rebuilds KV via prefill — greedy token streams are
  bit-identical to a never-evicted run because temperature-0 sampling is
  key-free argmax) or SWAP (the victim's block bytes migrate to the
  `HostBlockPool` and are restored on reactivation — bit-identical KV by
  construction, gather/scatter round-trip). `mode="auto"` follows the
  observatory cost model's per-candidate `cheaper` verdict, capped by
  host-pool capacity.

- `HostBlockPool`: a capacity-capped host-RAM tier for swapped-out KV
  block bytes. `put()` accepts LAZY device arrays — the engine hands it
  the output of `kv_cache.gather_blocks`, an async device gather whose
  value is pinned at dispatch order because cache updates are functional
  (no donation); the device->host copy happens only at `fetch()`, on the
  swap-in path, where the manager times it (the measured host-link
  bandwidth PERF.md reports). Shared COW blocks ride along with
  refcounts intact: the gather snapshots their bytes read-only, and
  `KVCache.free` only returns a block when its LAST sharer drops.

- `PersistentPrefixStore`: a content-addressed host store of full
  prefix-block KV bytes keyed by the registry's sha1 chain digests
  (block_table.chain_digests — digest i certifies tokens
  [0, (i+1)*block_size), the same safety certificate resident sharing
  uses). Unlike the pool-scoped `PrefixRegistry`, entries carry BYTES,
  not physical block ids, so one store can back every replica of a
  `ShardedServingGroup` and survive engine restarts via
  `save()`/`load()` (an npz spill file; env `DL4J_TPU_PREFIX_STORE`).
  On admission the engine restores stored blocks that extend the
  registry's resident coverage and prefills only the remaining suffix.

Sync discipline: with the lifecycle disabled (the default) no code here
runs — the no-pressure path is host-sync bit-identical to a build
without it (parity-tested). Enabled, the only added materializations are
on the PRESSURE paths (preemption history readback, swap-in fetch,
prefix-store fetch), every one `# sync-ok`-annotated and counted.

Blame attribution (ISSUE 14): every lifecycle action leaves a timeline
span the blame ledger (telemetry/blame.py) charges exactly — "preempt"
spans and "swap_in" restores to `preempt_swap_io` (swap mode) or
`preempt_recompute` (recompute mode), the resumed re-prefill
(`resume: True`) to `preempt_recompute`, and the requeue wait between
preemption and readmission tiles from `resume["t_requeue"]` so the
partition of submit->retire stays exact under pressure.

Env knobs: `DL4J_TPU_KV_EVICT` (policy name, empty/0/off disables),
`DL4J_TPU_KV_SWAP_BYTES` (host-pool cap in bytes; 0 = recompute-only),
`DL4J_TPU_PREFIX_STORE` (spill-file path, also enables the store).
"""
from __future__ import annotations

import os
import time
import warnings
import zipfile
from collections import OrderedDict
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from deeplearning4j_tpu.telemetry.kv_observatory import (
    DEFAULT_FLOPS_PER_SEC, DEFAULT_POLICIES, DEFAULT_SWAP_BYTES_PER_SEC,
    plan_eviction)


class HostBlockPool:
    """Capacity-capped host-RAM tier for swapped-out KV block bytes.

    Entries are (k, v) per swap key — lazy device arrays from
    `kv_cache.gather_blocks` (the swap-out dispatch) that only cross to
    the host when `fetch()` materializes them on the swap-in path. Byte
    accounting is nominal (the blocks' device size), charged at put()
    so `can_fit` back-pressures admission-time swap decisions even
    while the bytes are still in flight."""

    def __init__(self, capacity_bytes: int = 0):
        self.capacity_bytes = max(0, int(capacity_bytes))
        self._entries: Dict[object, Tuple[object, object, int]] = {}
        # quantized pools (ISSUE 15) ride their per-head-per-block scales
        # alongside the payload; a side dict keeps `_entries` 3-tuples
        self._scales: Dict[object, Tuple[object, object]] = {}
        self.bytes_used = 0

    def can_fit(self, nbytes: int) -> bool:
        return (self.capacity_bytes > 0
                and self.bytes_used + int(nbytes) <= self.capacity_bytes)

    def put(self, key, k_blocks, v_blocks, nbytes: int,
            k_scale=None, v_scale=None) -> None:
        if key in self._entries:
            raise ValueError(f"swap key {key!r} already held")
        self._entries[key] = (k_blocks, v_blocks, int(nbytes))
        if k_scale is not None:
            self._scales[key] = (k_scale, v_scale)
        self.bytes_used += int(nbytes)

    def fetch_scales(self, key) -> Optional[Tuple[np.ndarray, np.ndarray]]:
        """Materialized (k_scale, v_scale) for a quantized entry, or None
        for an unquantized one. Non-destructive peek — call before
        `fetch()` (which drops the scales with the payload)."""
        sc = self._scales.get(key)
        if sc is None:
            return None
        # sync-ok: swap-in materialization (pressure path)
        return np.asarray(sc[0]), np.asarray(sc[1])

    def fetch(self, key) -> Tuple[np.ndarray, np.ndarray]:
        """Remove and MATERIALIZE one entry (the swap-in device->host
        copy happens here; the caller times it and counts the sync)."""
        k, v, n = self._entries.pop(key)
        self._scales.pop(key, None)
        self.bytes_used -= n
        # counted+timed by the engine via KVLifecycleManager.swap_in
        # sync-ok: swap-in materialization (pressure path)
        return np.asarray(k), np.asarray(v)

    def drop(self, key) -> None:
        ent = self._entries.pop(key, None)
        self._scales.pop(key, None)
        if ent is not None:
            self.bytes_used -= ent[2]

    def __contains__(self, key) -> bool:
        return key in self._entries

    @property
    def n_entries(self) -> int:
        return len(self._entries)


class PersistentPrefixStore:
    """Content-addressed host store of full prefix-block KV bytes.

    Keys are the registry's sha1 chain digests (`chain_digests`): entry
    `d` holds one block's (k, v) bytes, shape (n_layers, block_size,
    n_kv_heads, head_dim) each, valid for ANY pool whose geometry
    matches — unlike physical block ids, bytes transfer across replicas
    and restarts. LRU-capped; `save()`/`load()` spill to an npz file so
    system prompts and multi-turn histories survive the process."""

    def __init__(self, capacity_bytes: int = 256 << 20,
                 path: Optional[str] = None):
        self.capacity_bytes = max(0, int(capacity_bytes))
        self.path = path
        # digest -> (k_block, v_block, nbytes); k/v may be lazy device
        # arrays until save()/fetch() materializes them
        self._entries: "OrderedDict[bytes, Tuple[object, object, int]]" = \
            OrderedDict()
        # quantized pools (ISSUE 15): per-entry (k_scale, v_scale) side
        # dict, spilled as ks_<hex>/vs_<hex> npz arrays
        self._scales: Dict[bytes, Tuple[object, object]] = {}
        self.bytes_used = 0
        self.block_shape: Optional[tuple] = None
        # payload dtype string, established at first put — the engine's
        # geometry guard also compares this so an int8 (quantized) spill
        # is never restored into a float pool or vice versa
        self.block_dtype: Optional[str] = None
        # optional victim chooser (ISSUE 16): called with the live
        # OrderedDict of entries, returns the digest to evict (or None
        # for the default LRU head). The engine wires the radix tree's
        # `store_victim` here so ONE tree-wide heat order governs both
        # device-pool reclaim and store eviction, replacing the store's
        # private recency order.
        self.evict_policy: Optional[Callable[..., Optional[bytes]]] = None
        # cross-replica heat bus (ISSUE 17 satellite): per-digest,
        # per-replica publication counts — replicas stamp the lineages
        # they prefill, the router's prefix affinity reads them. A
        # routing HINT, not content: ephemeral (never spilled to npz)
        # and unguarded like the entry dict (GIL-atomic dict ops; the
        # engines already share this object across replica threads).
        self._heat: Dict[bytes, Dict[int, float]] = {}

    # ----------------------------------------------- cross-replica heat
    def publish_heat(self, digest: bytes, replica: int,
                     inc: float = 1.0) -> None:
        """Record that `replica` just served (prefilled or restored) the
        lineage block addressed by `digest`."""
        per = self._heat.setdefault(digest, {})
        # sync-ok: inc is a host float (heat increments, never a buffer)
        per[int(replica)] = per.get(int(replica), 0.0) + float(inc)

    def route_heat(self, digests: Sequence[bytes]) -> Dict[int, float]:
        """Accumulated published heat per replica over the LEADING
        digests of a prompt's chain (stops at the first digest no
        replica ever published — a longer match never hides behind a
        gap). The router picks the max; empty dict = no signal."""
        out: Dict[int, float] = {}
        for d in digests:
            per = self._heat.get(d)
            if not per:
                break
            for r, h in per.items():
                out[r] = out.get(r, 0.0) + h
        return out

    # ------------------------------------------------------------ lookup
    def covered(self, digests: Sequence[bytes]) -> int:
        """How many LEADING digests the store holds (chain property: a
        usable restore is always a prefix of the chain). Touches the hit
        entries' LRU position."""
        n = 0
        for d in digests:
            if d not in self._entries:
                break
            self._entries.move_to_end(d)
            n += 1
        return n

    def missing(self, digests: Sequence[bytes]) -> List[int]:
        """Indices of `digests` not yet stored (the offer path gathers
        bytes only for these)."""
        return [i for i, d in enumerate(digests) if d not in self._entries]

    # ------------------------------------------------------------- write
    def put(self, digest: bytes, k_block, v_block, nbytes: int,
            block_shape: Optional[tuple] = None,
            k_scale=None, v_scale=None) -> None:
        """File one block's bytes under its chain digest (first write
        wins — identical content by the chain-hash certificate). Evicts
        LRU entries to stay under the byte cap. Quantized pools pass the
        block's (k_scale, v_scale) pair; int8 payload + fp32 scales
        restore bit-exactly."""
        if digest in self._entries:
            self._entries.move_to_end(digest)
            return
        if block_shape is not None:
            if self.block_shape is None:
                self.block_shape = tuple(block_shape)
            elif tuple(block_shape) != self.block_shape:
                raise ValueError(
                    f"prefix-store block shape {tuple(block_shape)} != "
                    f"established {self.block_shape}")
        if self.block_dtype is None:
            dt = str(getattr(k_block, "dtype", "")) or None
            self.block_dtype = dt
        nbytes = int(nbytes)
        if self.capacity_bytes and nbytes > self.capacity_bytes:
            return
        while self.capacity_bytes and self._entries \
                and self.bytes_used + nbytes > self.capacity_bytes:
            old_d = None
            if self.evict_policy is not None:
                old_d = self.evict_policy(self._entries)
                if old_d is not None and old_d not in self._entries:
                    old_d = None   # stale advice → fall back to LRU head
            if old_d is None:
                old_d, (_, _, old) = self._entries.popitem(last=False)
            else:
                _, _, old = self._entries.pop(old_d)
            self._scales.pop(old_d, None)
            self.bytes_used -= old
        self._entries[digest] = (k_block, v_block, nbytes)
        if k_scale is not None:
            self._scales[digest] = (k_scale, v_scale)
        self.bytes_used += nbytes

    def fetch(self, digests: Sequence[bytes]
              ) -> Tuple[np.ndarray, np.ndarray]:
        """Materialized (k, v) stacks for `digests` (all must be held):
        shape (n_layers, len(digests), block_size, n_kv_heads, head_dim)
        — the layout `kv_cache.restore_blocks` scatters."""
        ks, vs = [], []
        for d in digests:
            k, v, _ = self._entries[d]
            # sync-ok: prefix-store restore (counted by the engine)
            ks.append(np.asarray(k))
            vs.append(np.asarray(v))  # sync-ok: prefix-store restore
        return np.stack(ks, axis=1), np.stack(vs, axis=1)

    def fetch_scales(self, digests: Sequence[bytes]
                     ) -> Optional[Tuple[np.ndarray, np.ndarray]]:
        """Materialized (k_scale, v_scale) stacks, shape (n_layers,
        len(digests), n_kv_heads) each, or None when any digest lacks
        scales (unquantized entries). Non-destructive."""
        if any(d not in self._scales for d in digests):
            return None
        # sync-ok: prefix-store restore (counted by the engine)
        ks = [np.asarray(self._scales[d][0]) for d in digests]
        vs = [np.asarray(self._scales[d][1])  # sync-ok: restore path
              for d in digests]
        return np.stack(ks, axis=1), np.stack(vs, axis=1)

    # ----------------------------------------------------- persistence
    def save(self, path: Optional[str] = None) -> Optional[str]:
        """Spill every entry to an npz file (digests hex-encoded in the
        array names). Materializes lazy device entries — a phase
        boundary (shutdown), never the serve loop."""
        path = path or self.path
        if not path:
            return None
        arrays: Dict[str, np.ndarray] = {}
        for d, (k, v, _) in self._entries.items():
            # sync-ok: shutdown spill (phase boundary)
            arrays[f"k_{d.hex()}"] = np.asarray(k)
            arrays[f"v_{d.hex()}"] = np.asarray(v)  # sync-ok: shutdown spill
            sc = self._scales.get(d)
            if sc is not None:
                # sync-ok: shutdown spill (phase boundary)
                arrays[f"ks_{d.hex()}"] = np.asarray(sc[0])
                arrays[f"vs_{d.hex()}"] = np.asarray(sc[1])  # sync-ok: spill
        # write through a handle: np.savez(str) appends ".npz" to a bare
        # path, which load() (os.path.exists on the SAME string) would miss.
        # Crash-safe spill (ISSUE 16 satellite): write a sibling temp file
        # and rename into place — a crash mid-write leaves the previous
        # spill intact instead of a truncated zip at the canonical path.
        tmp = path + ".tmp"
        try:
            with open(tmp, "wb") as f:
                np.savez(f, **arrays)
            os.replace(tmp, path)
        finally:
            if os.path.exists(tmp):
                try:
                    os.remove(tmp)
                except OSError:
                    pass
        return path

    def load(self, path: Optional[str] = None) -> int:
        """Load entries from an npz spill file (missing file = empty
        store, not an error). A truncated or corrupt spill — a crash that
        predates the atomic rename, disk-full, bit rot — warns and starts
        empty rather than killing engine construction (ISSUE 16
        satellite: the store is a cache; losing it costs recompute, not
        correctness). Returns the number of blocks loaded."""
        path = path or self.path
        if not path or not os.path.exists(path):
            return 0
        loaded = 0
        try:
            with np.load(path) as z:
                for name in z.files:
                    if not name.startswith("k_"):
                        continue
                    hexd = name[2:]
                    vname = f"v_{hexd}"
                    if vname not in z.files:
                        continue
                    k = z[name]
                    v = z[vname]
                    nbytes = k.nbytes + v.nbytes
                    kw = {}
                    ksn, vsn = f"ks_{hexd}", f"vs_{hexd}"
                    if ksn in z.files and vsn in z.files:
                        kw = {"k_scale": z[ksn], "v_scale": z[vsn]}
                        nbytes += z[ksn].nbytes + z[vsn].nbytes
                    self.put(bytes.fromhex(hexd), k, v, nbytes,
                             block_shape=k.shape, **kw)
                    loaded += 1
        except (zipfile.BadZipFile, ValueError, OSError, EOFError,
                KeyError) as e:
            warnings.warn(
                f"prefix-store spill at {path!r} unreadable ({e!r}); "
                "starting with an empty store", stacklevel=2)
            # drop any partially ingested entries — a half-loaded chain
            # would satisfy covered() for a prefix it can't fully restore
            self._entries.clear()
            self._scales.clear()
            self.bytes_used = 0
            return 0
        return loaded

    @property
    def n_entries(self) -> int:
        return len(self._entries)


class KVLifecycleManager:
    """Victim selection + recompute/swap execution state for one engine.

    `plan()` delegates to the observatory's `plan_eviction` — the single
    source of truth shared with the dry-run forensics. The manager owns
    the `HostBlockPool` and the swap byte/wall accounting the bench
    reads; the ENGINE owns the actual preemption (slots, masks, history)
    because those live under its scheduler lock."""

    MODES = ("auto", "recompute", "swap")

    def __init__(self, policy: str = "lru", swap_bytes: int = 0,
                 mode: str = "auto", *, flops_per_token: float = 0.0,
                 swap_bytes_per_sec: float = DEFAULT_SWAP_BYTES_PER_SEC,
                 flops_per_sec: float = DEFAULT_FLOPS_PER_SEC,
                 score_fn: Optional[Callable] = None):
        if score_fn is None:
            if policy not in DEFAULT_POLICIES:
                raise ValueError(
                    f"unknown eviction policy {policy!r}; known: "
                    f"{sorted(DEFAULT_POLICIES)}")
            score_fn = DEFAULT_POLICIES[policy]
        if mode not in self.MODES:
            raise ValueError(f"kv_evict_mode {mode!r} not in {self.MODES}")
        self.policy = policy
        self.score_fn = score_fn
        self.mode = mode
        self.flops_per_token = float(flops_per_token)    # sync-ok: scalar
        self.swap_bytes_per_sec = float(swap_bytes_per_sec)  # sync-ok: scalar
        self.flops_per_sec = float(flops_per_sec)       # sync-ok: scalar
        self.host_pool = HostBlockPool(swap_bytes)
        # accounting the engine mirrors into serving.kv.* metrics
        self.evictions_recompute = 0
        self.evictions_swap = 0
        self.swap_out_bytes = 0
        self.swap_in_bytes = 0
        self.swap_wall_s = 0.0      # measured swap-in materialization wall

    # ------------------------------------------------------------- plan
    def plan(self, snapshot: Dict[str, object], needed_blocks: int, *,
             eligible: Optional[set] = None,
             now: Optional[float] = None) -> dict:
        """The victims this manager's policy would preempt to reclaim
        `needed_blocks` — exactly what the dry-run ring would log."""
        return plan_eviction(snapshot, needed_blocks, self.score_fn, now,
                             flops_per_token=self.flops_per_token,
                             swap_bytes_per_sec=self.swap_bytes_per_sec,
                             flops_per_sec=self.flops_per_sec,
                             eligible=eligible, policy=self.policy)

    def choose_mode(self, victim: dict, nbytes: int) -> str:
        """recompute vs swap for one plan entry: forced by `mode`, or
        (auto) the cost model's `cheaper` verdict — either way swap is
        only taken when the host pool can hold the bytes."""
        if self.mode == "recompute":
            return "recompute"
        fits = self.host_pool.can_fit(nbytes)
        if self.mode == "swap":
            return "swap" if fits else "recompute"
        return "swap" if (victim.get("cheaper") == "swap" and fits) \
            else "recompute"

    # ------------------------------------------------------------- swap
    def swap_out(self, key, k_blocks, v_blocks, nbytes: int,
                 k_scale=None, v_scale=None) -> None:
        """File a victim's gathered block bytes (lazy device arrays) in
        the host pool; bytes are charged now, copied at swap-in. A
        quantized pool (ISSUE 15) hands over per-head-per-block scales
        with the int8 payload so the restore is bit-exact."""
        self.host_pool.put(key, k_blocks, v_blocks, nbytes,
                           k_scale=k_scale, v_scale=v_scale)
        self.evictions_swap += 1
        self.swap_out_bytes += int(nbytes)

    def swap_in(self, key, nbytes: int) -> Tuple[np.ndarray, np.ndarray]:
        """Materialize a swapped request's bytes for restore, timing the
        device->host copy (the measured host-link bandwidth)."""
        t0 = time.perf_counter()
        k, v = self.host_pool.fetch(key)
        self.swap_wall_s += time.perf_counter() - t0
        self.swap_in_bytes += int(nbytes)
        return k, v

    def measured_swap_gbps(self) -> Optional[float]:
        """Swap-in bytes / materialization wall, in GB/s — None until a
        swap round-trip has actually run."""
        if self.swap_in_bytes <= 0 or self.swap_wall_s <= 0:
            return None
        return self.swap_in_bytes / self.swap_wall_s / 1e9


def resolve_lifecycle(kv_evict, kv_swap_bytes, kv_evict_mode: str = "auto",
                      *, flops_per_token: float = 0.0
                      ) -> Optional[KVLifecycleManager]:
    """Engine-constructor resolution of the lifecycle knobs: `kv_evict`
    is a policy name (or True for the default lru), None defers to
    `DL4J_TPU_KV_EVICT`; empty/"0"/"off" disables — and disabled means
    NO manager, no code on any path (the bit-parity guarantee)."""
    if kv_evict is None:
        kv_evict = os.environ.get("DL4J_TPU_KV_EVICT", "")
    if isinstance(kv_evict, KVLifecycleManager):
        return kv_evict
    if isinstance(kv_evict, bool):
        kv_evict = "lru" if kv_evict else ""
    if not kv_evict or kv_evict in ("0", "off"):
        return None
    if kv_swap_bytes is None:
        kv_swap_bytes = int(os.environ.get("DL4J_TPU_KV_SWAP_BYTES", "0"))
    return KVLifecycleManager(policy=str(kv_evict),
                              swap_bytes=int(kv_swap_bytes),
                              mode=kv_evict_mode,
                              flops_per_token=flops_per_token)


def resolve_prefix_store(prefix_store) -> Optional[PersistentPrefixStore]:
    """Engine-constructor resolution of the prefix-store knob: an
    instance passes through (the ShardedServingGroup hands ONE store to
    every replica), True builds a RAM-only store, a string is a spill
    path; None defers to `DL4J_TPU_PREFIX_STORE` (path, empty = off).
    A path-backed store auto-loads its spill file when it exists."""
    if prefix_store is None:
        path = os.environ.get("DL4J_TPU_PREFIX_STORE", "")
        if not path or path == "0":
            return None
        prefix_store = path
    if isinstance(prefix_store, PersistentPrefixStore):
        return prefix_store
    if prefix_store is True:
        return PersistentPrefixStore()
    store = PersistentPrefixStore(path=str(prefix_store))
    store.load()
    return store
