"""KV lifecycle under memory pressure: real eviction/preemption, a
host-RAM offload tier, and a persistent prefix store (ISSUE 13).

The ROADMAP named KV lifecycle as the scaling ceiling: at 2-5x
resident-block capacity the engine just queued rejected admissions
forever. PR 12 built the measurement half — telemetry/kv_observatory.py
ranks victims under lru / slo_deadline / refcount_weighted policies with
marginal reclaim and per-candidate recompute-vs-swap costs — but
`dry_run()` evicted nothing. This module makes it real, as a layer
between admission and the block pool:

- `KVLifecycleManager`: policy + cost-model state for REAL eviction.
  When admission fails, the engine asks the manager for a victim plan;
  the plan comes from the observatory's `plan_eviction` — the SAME
  ranking + marginal-reclaim simulation the dry-run forensics record, so
  what the rejection ring says would be evicted and what actually gets
  preempted can never disagree. Per victim the manager picks RECOMPUTE
  (free the blocks; the engine requeues the request with its generated
  history and rebuilds KV via prefill — greedy token streams are
  bit-identical to a never-evicted run because temperature-0 sampling is
  key-free argmax) or SWAP (the victim's block bytes migrate to the
  `HostBlockPool` and are restored on reactivation — bit-identical KV by
  construction, gather/scatter round-trip). `mode="auto"` follows the
  observatory cost model's per-candidate `cheaper` verdict, capped by
  host-pool capacity.

- `HostBlockPool`: a capacity-capped host-RAM tier for swapped-out KV
  block bytes. `put()` accepts LAZY device arrays — the engine hands it
  the output of `kv_cache.gather_blocks`, an async device gather whose
  value is pinned at dispatch order because cache updates are functional
  (no donation); the device->host copy happens only at `fetch()`, on the
  swap-in path, where the manager times it (the measured host-link
  bandwidth PERF.md reports). Shared COW blocks ride along with
  refcounts intact: the gather snapshots their bytes read-only, and
  `KVCache.free` only returns a block when its LAST sharer drops.

- `PersistentPrefixStore`: a content-addressed host store of full
  prefix-block KV bytes keyed by the registry's sha1 chain digests
  (block_table.chain_digests — digest i certifies tokens
  [0, (i+1)*block_size), the same safety certificate resident sharing
  uses). Unlike the pool-scoped `PrefixRegistry`, entries carry BYTES,
  not physical block ids, so one store can back every replica of a
  `ShardedServingGroup` and survive engine restarts via
  `save()`/`load()` (an npz spill file; env `DL4J_TPU_PREFIX_STORE`).
  On admission the engine restores stored blocks that extend the
  registry's resident coverage and prefills only the remaining suffix.

Sync discipline: with the lifecycle disabled (the default) no code here
runs — the no-pressure path is host-sync bit-identical to a build
without it (parity-tested). Enabled, the only added materializations are
on the PRESSURE paths (preemption history readback, swap-in fetch,
prefix-store fetch), every one `# sync-ok`-annotated and counted.

Blame attribution (ISSUE 14): every lifecycle action leaves a timeline
span the blame ledger (telemetry/blame.py) charges exactly — "preempt"
spans and "swap_in" restores to `preempt_swap_io` (swap mode) or
`preempt_recompute` (recompute mode), the resumed re-prefill
(`resume: True`) to `preempt_recompute`, and the requeue wait between
preemption and readmission tiles from `resume["t_requeue"]` so the
partition of submit->retire stays exact under pressure.

Hierarchical storage (ISSUE 18): below the `HostBlockPool` sits an
optional `DiskBlockPool` (serving/kv_disk.py) — LRU host entries DEMOTE
to npz spill files under host-pool pressure (`rebalance()`), and a
swap-in whose entry went cold PROMOTES it disk -> host -> device; the
`PersistentPrefixStore` spills through the same tier. Swap-out itself
is ASYNC: the engine defers the victim's history readback and payload
materialization to the next chunk boundary (`harvest()`), so preemption
never stalls the scheduler on a device->host copy, and the engine's
init-time warmup round-trip `calibrate()`s the cost model's
swap bandwidth instead of trusting DEFAULT_SWAP_BYTES_PER_SEC.

Env knobs: `DL4J_TPU_KV_EVICT` (policy name, empty/0/off disables),
`DL4J_TPU_KV_SWAP_BYTES` (host-pool cap in bytes; 0 = recompute-only
unless the disk tier is armed), `DL4J_TPU_PREFIX_STORE` (spill-file
path, also enables the store), `DL4J_TPU_KV_DISK` (spill directory,
arms the disk tier), `DL4J_TPU_KV_DISK_BYTES` (disk cap, default
1 GiB), `DL4J_TPU_KV_SWAP_ASYNC` (engine knob: deferred harvest on/off,
default on).
"""
from __future__ import annotations

import os
import time
import warnings
import zipfile
from collections import OrderedDict
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from deeplearning4j_tpu.telemetry.kv_observatory import (
    DEFAULT_FLOPS_PER_SEC, DEFAULT_POLICIES, DEFAULT_SWAP_BYTES_PER_SEC,
    plan_eviction)


class HostBlockPool:
    """Capacity-capped host-RAM tier for swapped-out KV block bytes.

    Entries are (k, v) per swap key — lazy device arrays from
    `kv_cache.gather_blocks` (the swap-out dispatch) that only cross to
    the host when `fetch()` materializes them on the swap-in path. Byte
    accounting is nominal (the blocks' device size), charged at put()
    so `can_fit` back-pressures admission-time swap decisions even
    while the bytes are still in flight."""

    def __init__(self, capacity_bytes: int = 0):
        self.capacity_bytes = max(0, int(capacity_bytes))
        # insertion-ordered (OrderedDict): the demotion path spills the
        # LRU entry to the disk tier under host-pool pressure (ISSUE 18)
        self._entries: "OrderedDict[object, Tuple[object, object, int]]" = \
            OrderedDict()
        # quantized pools (ISSUE 15) ride their per-head-per-block scales
        # alongside the payload; a side dict keeps `_entries` 3-tuples
        self._scales: Dict[object, Tuple[object, object]] = {}
        self.bytes_used = 0

    def can_fit(self, nbytes: int) -> bool:
        return (self.capacity_bytes > 0
                and self.bytes_used + int(nbytes) <= self.capacity_bytes)

    def put(self, key, k_blocks, v_blocks, nbytes: int,
            k_scale=None, v_scale=None) -> None:
        if key in self._entries:
            raise ValueError(f"swap key {key!r} already held")
        self._entries[key] = (k_blocks, v_blocks, int(nbytes))
        if k_scale is not None:
            self._scales[key] = (k_scale, v_scale)
        self.bytes_used += int(nbytes)

    def fetch_scales(self, key) -> Optional[Tuple[np.ndarray, np.ndarray]]:
        """Materialized (k_scale, v_scale) for a quantized entry, or None
        for an unquantized one. Non-destructive peek — call before
        `fetch()` (which drops the scales with the payload)."""
        sc = self._scales.get(key)
        if sc is None:
            return None
        # sync-ok: swap-in materialization (pressure path)
        return np.asarray(sc[0]), np.asarray(sc[1])

    def fetch(self, key) -> Tuple[np.ndarray, np.ndarray]:
        """Remove and MATERIALIZE one entry (the swap-in device->host
        copy happens here; the caller times it and counts the sync).

        The materialization PEEKS before it pops (ISSUE 18 satellite):
        a restore that raises mid-flight — device OOM, a poisoned lazy
        array — used to lose the entry permanently because the pop and
        the byte decrement ran first; now the entry survives and the
        swap-in can be retried or fall back to recompute."""
        k, v, n = self._entries[key]
        # counted+timed by the engine via KVLifecycleManager.swap_in
        # sync-ok: swap-in materialization (pressure path)
        k_np = np.asarray(k)
        v_np = np.asarray(v)  # sync-ok: swap-in materialization
        del self._entries[key]
        self._scales.pop(key, None)
        self.bytes_used -= n
        return k_np, v_np

    def materialize(self, key) -> int:
        """Convert one entry's lazy device arrays into real host numpy
        IN PLACE — the deferred swap-out harvest (ISSUE 18): the engine
        calls this at the next chunk boundary after preemption, so the
        device->host copy overlaps scheduling instead of stalling it,
        and later demotion to disk never touches the device. Idempotent
        (already-materialized entries are a no-op copy; an entry a
        rebalance already demoted to disk is a no-op — the disk put
        materialized it). Returns the entry's nominal bytes."""
        if key not in self._entries:
            return 0
        k, v, n = self._entries[key]
        # sync-ok: deferred swap-out harvest (pressure path only)
        self._entries[key] = (np.asarray(k), np.asarray(v), n)
        sc = self._scales.get(key)
        if sc is not None:
            # sync-ok: deferred swap-out harvest (pressure path only)
            self._scales[key] = (np.asarray(sc[0]), np.asarray(sc[1]))
        return n

    def pop_lru(self) -> Tuple[object, object, object, int,
                               Optional[Tuple[object, object]]]:
        """Remove and return the least-recently-inserted entry as
        (key, k, v, nbytes, scales-or-None) — the demotion path hands
        it to the disk tier."""
        key, (k, v, n) = self._entries.popitem(last=False)
        sc = self._scales.pop(key, None)
        self.bytes_used -= n
        return key, k, v, n, sc

    def drop(self, key) -> None:
        ent = self._entries.pop(key, None)
        self._scales.pop(key, None)
        if ent is not None:
            self.bytes_used -= ent[2]

    def __contains__(self, key) -> bool:
        return key in self._entries

    @property
    def n_entries(self) -> int:
        return len(self._entries)


class PersistentPrefixStore:
    """Content-addressed host store of full prefix-block KV bytes.

    Keys are the registry's sha1 chain digests (`chain_digests`): entry
    `d` holds one block's (k, v) bytes, shape (n_layers, block_size,
    n_kv_heads, head_dim) each, valid for ANY pool whose geometry
    matches — unlike physical block ids, bytes transfer across replicas
    and restarts. LRU-capped; `save()`/`load()` spill to an npz file so
    system prompts and multi-turn histories survive the process."""

    def __init__(self, capacity_bytes: int = 256 << 20,
                 path: Optional[str] = None):
        self.capacity_bytes = max(0, int(capacity_bytes))
        self.path = path
        # digest -> (k_block, v_block, nbytes); k/v may be lazy device
        # arrays until save()/fetch() materializes them
        self._entries: "OrderedDict[bytes, Tuple[object, object, int]]" = \
            OrderedDict()
        # quantized pools (ISSUE 15): per-entry (k_scale, v_scale) side
        # dict, spilled as ks_<hex>/vs_<hex> npz arrays
        self._scales: Dict[bytes, Tuple[object, object]] = {}
        self.bytes_used = 0
        self.block_shape: Optional[tuple] = None
        # payload dtype string, established at first put — the engine's
        # geometry guard also compares this so an int8 (quantized) spill
        # is never restored into a float pool or vice versa
        self.block_dtype: Optional[str] = None
        # optional victim chooser (ISSUE 16): called with the live
        # OrderedDict of entries, returns the digest to evict (or None
        # for the default LRU head). The engine wires the radix tree's
        # `store_victim` here so ONE tree-wide heat order governs both
        # device-pool reclaim and store eviction, replacing the store's
        # private recency order.
        self.evict_policy: Optional[Callable[..., Optional[bytes]]] = None
        # disk spill-through (ISSUE 18): when set (the engine wires the
        # lifecycle manager's DiskBlockPool here), byte-cap eviction
        # DEMOTES the victim entry to disk instead of discarding it, and
        # covered() PROMOTES disk-resident digests back — so cold
        # prefixes survive at ~zero host-RAM cost. Counters are
        # lifetime, mirrored into engine stats.
        self.disk = None
        self.disk_demotions = 0
        self.disk_promotions = 0
        # cross-replica heat bus (ISSUE 17 satellite): per-digest,
        # per-replica publication counts — replicas stamp the lineages
        # they prefill, the router's prefix affinity reads them. A
        # routing HINT, not content: ephemeral (never spilled to npz)
        # and unguarded like the entry dict (GIL-atomic dict ops; the
        # engines already share this object across replica threads).
        self._heat: Dict[bytes, Dict[int, float]] = {}

    # ----------------------------------------------- cross-replica heat
    def publish_heat(self, digest: bytes, replica: int,
                     inc: float = 1.0) -> None:
        """Record that `replica` just served (prefilled or restored) the
        lineage block addressed by `digest`."""
        per = self._heat.setdefault(digest, {})
        # sync-ok: inc is a host float (heat increments, never a buffer)
        per[int(replica)] = per.get(int(replica), 0.0) + float(inc)

    def route_heat(self, digests: Sequence[bytes]) -> Dict[int, float]:
        """Accumulated published heat per replica over the LEADING
        digests of a prompt's chain (stops at the first digest no
        replica ever published — a longer match never hides behind a
        gap). The router picks the max; empty dict = no signal."""
        out: Dict[int, float] = {}
        for d in digests:
            per = self._heat.get(d)
            if not per:
                break
            for r, h in per.items():
                out[r] = out.get(r, 0.0) + h
        return out

    # ------------------------------------------------------------ lookup
    def covered(self, digests: Sequence[bytes]) -> int:
        """How many LEADING digests the store holds (chain property: a
        usable restore is always a prefix of the chain). Touches the hit
        entries' LRU position. With a disk tier wired (ISSUE 18), a
        digest missing from RAM but spilled on disk is PROMOTED back
        into the store — disk -> host here, host -> device at the
        caller's fetch()+restore — so coverage extends through the
        spill; a corrupt spill file simply ends the covered prefix (the
        chain property keeps a partial promotion safe)."""
        n = 0
        for d in digests:
            if d not in self._entries and not self._promote(d):
                break
            self._entries.move_to_end(d)
            n += 1
        return n

    def _promote(self, digest: bytes) -> bool:
        """Try to pull one digest's bytes back from the disk tier into
        the RAM store (pressure path — the disk read is the promotion
        cost `covered()` pays to extend a restore). Returns False when
        there is no disk tier, the digest isn't spilled, or its file is
        unreadable (fetch drops it and warns)."""
        if self.disk is None or digest not in self.disk:
            return False
        try:
            k, v, sc = self.disk.fetch(digest)
        except KeyError:
            return False
        nbytes = k.nbytes + v.nbytes
        if sc is not None:
            nbytes += sc[0].nbytes + sc[1].nbytes
        kw = {} if sc is None else {"k_scale": sc[0], "v_scale": sc[1]}
        self.put(digest, k, v, nbytes, block_shape=k.shape, **kw)
        if digest not in self._entries:      # put refused (cap too small)
            return False
        self.disk_promotions += 1
        return True

    def missing(self, digests: Sequence[bytes]) -> List[int]:
        """Indices of `digests` not yet stored (the offer path gathers
        bytes only for these)."""
        return [i for i, d in enumerate(digests) if d not in self._entries]

    # ------------------------------------------------------------- write
    def put(self, digest: bytes, k_block, v_block, nbytes: int,
            block_shape: Optional[tuple] = None,
            k_scale=None, v_scale=None) -> None:
        """File one block's bytes under its chain digest (first write
        wins — identical content by the chain-hash certificate). Evicts
        LRU entries to stay under the byte cap. Quantized pools pass the
        block's (k_scale, v_scale) pair; int8 payload + fp32 scales
        restore bit-exactly."""
        if digest in self._entries:
            self._entries.move_to_end(digest)
            return
        if block_shape is not None:
            if self.block_shape is None:
                self.block_shape = tuple(block_shape)
            elif tuple(block_shape) != self.block_shape:
                raise ValueError(
                    f"prefix-store block shape {tuple(block_shape)} != "
                    f"established {self.block_shape}")
        if self.block_dtype is None:
            dt = str(getattr(k_block, "dtype", "")) or None
            self.block_dtype = dt
        nbytes = int(nbytes)
        if self.capacity_bytes and nbytes > self.capacity_bytes:
            return
        while self.capacity_bytes and self._entries \
                and self.bytes_used + nbytes > self.capacity_bytes:
            old_d = None
            if self.evict_policy is not None:
                old_d = self.evict_policy(self._entries)
                if old_d is not None and old_d not in self._entries:
                    old_d = None   # stale advice → fall back to LRU head
            if old_d is None:
                old_d, (old_k, old_v, old) = self._entries.popitem(last=False)
            else:
                old_k, old_v, old = self._entries.pop(old_d)
            old_sc = self._scales.pop(old_d, None)
            self.bytes_used -= old
            if self.disk is not None and self.disk.can_fit(old):
                # spill-through (ISSUE 18): the byte-cap victim demotes
                # to the disk tier instead of vanishing; covered() can
                # promote it back later. disk.put materializes lazy
                # entries — store eviction is a pressure path.
                self.disk.put(old_d, old_k, old_v, old,
                              k_scale=None if old_sc is None else old_sc[0],
                              v_scale=None if old_sc is None else old_sc[1])
                self.disk_demotions += 1
        self._entries[digest] = (k_block, v_block, nbytes)
        if k_scale is not None:
            self._scales[digest] = (k_scale, v_scale)
        self.bytes_used += nbytes

    def fetch(self, digests: Sequence[bytes]
              ) -> Tuple[np.ndarray, np.ndarray]:
        """Materialized (k, v) stacks for `digests` (all must be held):
        shape (n_layers, len(digests), block_size, n_kv_heads, head_dim)
        — the layout `kv_cache.restore_blocks` scatters."""
        ks, vs = [], []
        for d in digests:
            k, v, _ = self._entries[d]
            # sync-ok: prefix-store restore (counted by the engine)
            ks.append(np.asarray(k))
            vs.append(np.asarray(v))  # sync-ok: prefix-store restore
        return np.stack(ks, axis=1), np.stack(vs, axis=1)

    def fetch_scales(self, digests: Sequence[bytes]
                     ) -> Optional[Tuple[np.ndarray, np.ndarray]]:
        """Materialized (k_scale, v_scale) stacks, shape (n_layers,
        len(digests), n_kv_heads) each, or None when any digest lacks
        scales (unquantized entries). Non-destructive."""
        if any(d not in self._scales for d in digests):
            return None
        # sync-ok: prefix-store restore (counted by the engine)
        ks = [np.asarray(self._scales[d][0]) for d in digests]
        vs = [np.asarray(self._scales[d][1])  # sync-ok: restore path
              for d in digests]
        return np.stack(ks, axis=1), np.stack(vs, axis=1)

    # ----------------------------------------------------- persistence
    def save(self, path: Optional[str] = None) -> Optional[str]:
        """Spill every entry to an npz file (digests hex-encoded in the
        array names). Materializes lazy device entries — a phase
        boundary (shutdown), never the serve loop."""
        path = path or self.path
        if not path:
            return None
        arrays: Dict[str, np.ndarray] = {}
        for d, (k, v, _) in self._entries.items():
            # sync-ok: shutdown spill (phase boundary)
            arrays[f"k_{d.hex()}"] = np.asarray(k)
            arrays[f"v_{d.hex()}"] = np.asarray(v)  # sync-ok: shutdown spill
            sc = self._scales.get(d)
            if sc is not None:
                # sync-ok: shutdown spill (phase boundary)
                arrays[f"ks_{d.hex()}"] = np.asarray(sc[0])
                arrays[f"vs_{d.hex()}"] = np.asarray(sc[1])  # sync-ok: spill
        # write through a handle: np.savez(str) appends ".npz" to a bare
        # path, which load() (os.path.exists on the SAME string) would miss.
        # Crash-safe spill (ISSUE 16 satellite): write a sibling temp file
        # and rename into place — a crash mid-write leaves the previous
        # spill intact instead of a truncated zip at the canonical path.
        tmp = path + ".tmp"
        try:
            with open(tmp, "wb") as f:
                np.savez(f, **arrays)
            os.replace(tmp, path)
        finally:
            if os.path.exists(tmp):
                try:
                    os.remove(tmp)
                except OSError:
                    pass
        return path

    def load(self, path: Optional[str] = None) -> int:
        """Load entries from an npz spill file (missing file = empty
        store, not an error). A truncated or corrupt spill — a crash that
        predates the atomic rename, disk-full, bit rot — warns and starts
        empty rather than killing engine construction (ISSUE 16
        satellite: the store is a cache; losing it costs recompute, not
        correctness). Returns the number of blocks loaded."""
        path = path or self.path
        if not path or not os.path.exists(path):
            return 0
        loaded = 0
        try:
            with np.load(path) as z:
                for name in z.files:
                    if not name.startswith("k_"):
                        continue
                    hexd = name[2:]
                    vname = f"v_{hexd}"
                    if vname not in z.files:
                        continue
                    k = z[name]
                    v = z[vname]
                    nbytes = k.nbytes + v.nbytes
                    kw = {}
                    ksn, vsn = f"ks_{hexd}", f"vs_{hexd}"
                    if ksn in z.files and vsn in z.files:
                        kw = {"k_scale": z[ksn], "v_scale": z[vsn]}
                        nbytes += z[ksn].nbytes + z[vsn].nbytes
                    self.put(bytes.fromhex(hexd), k, v, nbytes,
                             block_shape=k.shape, **kw)
                    loaded += 1
        except (zipfile.BadZipFile, ValueError, OSError, EOFError,
                KeyError) as e:
            warnings.warn(
                f"prefix-store spill at {path!r} unreadable ({e!r}); "
                "starting with an empty store", stacklevel=2)
            # drop any partially ingested entries — a half-loaded chain
            # would satisfy covered() for a prefix it can't fully restore
            self._entries.clear()
            self._scales.clear()
            self.bytes_used = 0
            return 0
        return loaded

    @property
    def n_entries(self) -> int:
        return len(self._entries)


class KVLifecycleManager:
    """Victim selection + recompute/swap execution state for one engine.

    `plan()` delegates to the observatory's `plan_eviction` — the single
    source of truth shared with the dry-run forensics. The manager owns
    the `HostBlockPool` and the swap byte/wall accounting the bench
    reads; the ENGINE owns the actual preemption (slots, masks, history)
    because those live under its scheduler lock."""

    MODES = ("auto", "recompute", "swap")

    def __init__(self, policy: str = "lru", swap_bytes: int = 0,
                 mode: str = "auto", *, flops_per_token: float = 0.0,
                 swap_bytes_per_sec: float = DEFAULT_SWAP_BYTES_PER_SEC,
                 flops_per_sec: float = DEFAULT_FLOPS_PER_SEC,
                 score_fn: Optional[Callable] = None,
                 disk_pool=None):
        if score_fn is None:
            if policy not in DEFAULT_POLICIES:
                raise ValueError(
                    f"unknown eviction policy {policy!r}; known: "
                    f"{sorted(DEFAULT_POLICIES)}")
            score_fn = DEFAULT_POLICIES[policy]
        if mode not in self.MODES:
            raise ValueError(f"kv_evict_mode {mode!r} not in {self.MODES}")
        self.policy = policy
        self.score_fn = score_fn
        self.mode = mode
        self.flops_per_token = float(flops_per_token)    # sync-ok: scalar
        self.swap_bytes_per_sec = float(swap_bytes_per_sec)  # sync-ok: scalar
        self.flops_per_sec = float(flops_per_sec)       # sync-ok: scalar
        self.host_pool = HostBlockPool(swap_bytes)
        # disk tier (ISSUE 18): a DiskBlockPool below the host pool —
        # None means no tier, no disk code on any path
        self.disk_pool = disk_pool
        # accounting the engine mirrors into serving.kv.* metrics
        self.evictions_recompute = 0
        self.evictions_swap = 0
        self.swap_out_bytes = 0
        self.swap_in_bytes = 0
        self.swap_wall_s = 0.0      # measured swap-in materialization wall
        # hierarchical-tier accounting (ISSUE 18)
        self.harvests = 0           # deferred swap-out materializations
        self.harvest_wall_s = 0.0
        self.disk_demotions = 0     # host -> disk spills
        self.disk_promotions = 0    # disk -> host restores
        self.disk_wall_s = 0.0      # disk read+write wall
        self.demoted_bytes = 0
        # init-time calibrated host-link bandwidth (GB/s), None until
        # the engine's warmup round-trip ran (ISSUE 18 satellite)
        self.calibrated_gbps: Optional[float] = None

    # ------------------------------------------------------------- plan
    def plan(self, snapshot: Dict[str, object], needed_blocks: int, *,
             eligible: Optional[set] = None,
             now: Optional[float] = None) -> dict:
        """The victims this manager's policy would preempt to reclaim
        `needed_blocks` — exactly what the dry-run ring would log."""
        return plan_eviction(snapshot, needed_blocks, self.score_fn, now,
                             flops_per_token=self.flops_per_token,
                             swap_bytes_per_sec=self.swap_bytes_per_sec,
                             flops_per_sec=self.flops_per_sec,
                             eligible=eligible, policy=self.policy)

    def can_absorb(self, nbytes: int) -> bool:
        """Can the storage hierarchy hold `nbytes` more of swap payload?
        True when the host pool fits it directly, or (disk tier armed,
        ISSUE 18) when demoting LRU host entries to disk makes room —
        the swap-cost term `choose_mode` consults, so a quantized pool's
        ~4x smaller payloads fit (and swap wins the cost race) long
        after float payloads stopped fitting."""
        if self.host_pool.can_fit(nbytes):
            return True
        if self.disk_pool is None:
            return False
        nbytes = int(nbytes)
        disk_free = self.disk_pool.capacity_bytes - self.disk_pool.bytes_used
        if nbytes <= self.host_pool.capacity_bytes:
            # demotion makes room: the overflow moves to disk
            overflow = self.host_pool.bytes_used + nbytes \
                - self.host_pool.capacity_bytes
            return overflow <= disk_free
        # payload larger than the whole host pool: spill straight to disk
        return nbytes <= disk_free

    def choose_mode(self, victim: dict, nbytes: int) -> str:
        """recompute vs swap for one plan entry: forced by `mode`, or
        (auto) the cost model's `cheaper` verdict — either way swap is
        only taken when the storage hierarchy (host pool, plus the disk
        tier via demotion when armed) can hold the bytes."""
        if self.mode == "recompute":
            return "recompute"
        fits = self.can_absorb(nbytes)
        if self.mode == "swap":
            return "swap" if fits else "recompute"
        return "swap" if (victim.get("cheaper") == "swap" and fits) \
            else "recompute"

    # ------------------------------------------------------------- swap
    def swap_out(self, key, k_blocks, v_blocks, nbytes: int,
                 k_scale=None, v_scale=None) -> None:
        """File a victim's gathered block bytes (lazy device arrays) in
        the host pool; bytes are charged now, copied at harvest/swap-in.
        A quantized pool (ISSUE 15) hands over per-head-per-block scales
        with the int8 payload so the restore is bit-exact. NEVER
        materializes — the pool may run transiently over cap until the
        next `rebalance()` demotes LRU entries to disk (ISSUE 18), so
        the preempt-time dispatch stays stall-free."""
        self.host_pool.put(key, k_blocks, v_blocks, nbytes,
                           k_scale=k_scale, v_scale=v_scale)
        self.evictions_swap += 1
        self.swap_out_bytes += int(nbytes)

    def rebalance(self) -> dict:
        """Demote LRU host-pool entries to the disk tier until the pool
        is back under its byte cap (no-op without a disk tier, or when
        already under cap). Materializes lazy entries — a pressure path;
        the engine calls this at preempt time (sync swap mode) or at the
        deferred harvest (async), and charges the wall to the blame
        ledger's disk-IO cause. Returns {demotions, bytes, wall_s}."""
        out = {"demotions": 0, "bytes": 0, "wall_s": 0.0}
        if self.disk_pool is None \
                or self.host_pool.bytes_used <= self.host_pool.capacity_bytes:
            return out
        t0 = time.perf_counter()   # det-ok: blame-ledger disk-IO wall
        while self.host_pool.bytes_used > self.host_pool.capacity_bytes \
                and self.host_pool.n_entries:
            key, k, v, n, sc = self.host_pool.pop_lru()
            if not self.disk_pool.can_fit(n):
                # disk full: keep the entry host-resident (re-file at the
                # LRU head position is lost, but bytes stay correct)
                self.host_pool.put(key, k, v, n,
                                   k_scale=None if sc is None else sc[0],
                                   v_scale=None if sc is None else sc[1])
                break
            self.disk_pool.put(key, k, v, n,
                               k_scale=None if sc is None else sc[0],
                               v_scale=None if sc is None else sc[1])
            out["demotions"] += 1
            out["bytes"] += n
        out["wall_s"] = time.perf_counter() - t0   # det-ok: measurement
        self.disk_demotions += out["demotions"]
        self.demoted_bytes += out["bytes"]
        self.disk_wall_s += out["wall_s"]
        return out

    def harvest(self, key) -> None:
        """Deferred swap-out harvest (ISSUE 18): materialize a swapped
        entry's bytes host-side at a chunk boundary — the device->host
        copy the synchronous path paid inside the preemption stall."""
        t0 = time.perf_counter()   # det-ok: harvest wall measurement
        self.host_pool.materialize(key)
        self.harvest_wall_s += time.perf_counter() - t0   # det-ok: same
        self.harvests += 1

    def has_swap(self, key) -> bool:
        """Is `key`'s swap payload restorable from ANY tier? False means
        the entry was lost (e.g. a corrupt disk spill) — the engine
        falls back to recompute-resume, costing compute, not tokens."""
        return key in self.host_pool or (
            self.disk_pool is not None and key in self.disk_pool)

    def drop(self, key) -> None:
        """Forget a swapped entry on every tier (timeout / shutdown of a
        swapped-out request — its bytes will never be restored)."""
        self.host_pool.drop(key)
        if self.disk_pool is not None:
            self.disk_pool.drop(key)

    def swap_in(self, key, nbytes: int
                ) -> Tuple[np.ndarray, np.ndarray,
                           Optional[Tuple[np.ndarray, np.ndarray]], dict]:
        """Materialize a swapped request's bytes for restore from
        whichever tier holds them: (k, v, scales-or-None, info).
        info = {"tier": "host"|"disk", "wall_s", "disk_wall_s"} — the
        engine splits the blame span on it (device-gather vs disk-IO).
        A disk hit is the promotion path (disk -> host here, host ->
        device at the caller's scatter). Raises KeyError when no tier
        holds the entry (lost spill)."""
        # the wall here feeds choose_mode's measured GB/s, whose verdict
        # replay forces from the journal
        t0 = time.perf_counter()   # det-ok: bandwidth calibration
        tier, disk_wall = "host", 0.0
        if key in self.host_pool:
            scales = self.host_pool.fetch_scales(key)
            k, v = self.host_pool.fetch(key)
        elif self.disk_pool is not None and key in self.disk_pool:
            tier = "disk"
            k, v, scales = self.disk_pool.fetch(key)   # KeyError if corrupt
            disk_wall = time.perf_counter() - t0   # det-ok: measurement
            self.disk_wall_s += disk_wall
            self.disk_promotions += 1
        else:
            raise KeyError(key)
        wall = time.perf_counter() - t0   # det-ok: measurement
        self.swap_wall_s += wall
        self.swap_in_bytes += int(nbytes)
        return k, v, scales, {"tier": tier, "wall_s": wall,
                              "disk_wall_s": disk_wall}

    # ------------------------------------------------------ measurement
    def calibrate(self, nbytes: int, wall_s: float) -> float:
        """Install the engine-init warmup round-trip measurement
        (ISSUE 18 satellite): one tiny gather+materialize replaces the
        hardcoded DEFAULT_SWAP_BYTES_PER_SEC guess in every subsequent
        `plan()`/`choose_mode()` cost verdict. Returns the bandwidth in
        bytes/sec (floored to keep the cost model finite)."""
        # sync-ok: host ints/floats from the caller's timer, no device read
        bps = max(1e6, float(nbytes) / max(1e-9, float(wall_s)))
        self.swap_bytes_per_sec = bps
        self.calibrated_gbps = bps / 1e9
        return bps

    def measured_swap_gbps(self) -> Optional[float]:
        """Swap-in bytes / materialization wall (harvest wall included —
        an async-harvested entry's device->host copy happened there), in
        GB/s — None until a swap round-trip has actually run."""
        wall = self.swap_wall_s + self.harvest_wall_s
        if self.swap_in_bytes <= 0 or wall <= 0:
            return None
        return self.swap_in_bytes / wall / 1e9


def resolve_lifecycle(kv_evict, kv_swap_bytes, kv_evict_mode: str = "auto",
                      *, flops_per_token: float = 0.0,
                      kv_disk=None, kv_disk_bytes: Optional[int] = None
                      ) -> Optional[KVLifecycleManager]:
    """Engine-constructor resolution of the lifecycle knobs: `kv_evict`
    is a policy name (or True for the default lru), None defers to
    `DL4J_TPU_KV_EVICT`; empty/"0"/"off" disables — and disabled means
    NO manager, no code on any path (the bit-parity guarantee).
    `kv_disk`/`kv_disk_bytes` (ISSUE 18) arm the disk tier below the
    host pool — a DiskBlockPool instance, a spill directory, or None to
    defer to `DL4J_TPU_KV_DISK`/`DL4J_TPU_KV_DISK_BYTES`."""
    if kv_evict is None:
        kv_evict = os.environ.get("DL4J_TPU_KV_EVICT", "")
    if isinstance(kv_evict, KVLifecycleManager):
        return kv_evict
    if isinstance(kv_evict, bool):
        kv_evict = "lru" if kv_evict else ""
    if not kv_evict or kv_evict in ("0", "off"):
        return None
    if kv_swap_bytes is None:
        kv_swap_bytes = int(os.environ.get("DL4J_TPU_KV_SWAP_BYTES", "0"))
    from deeplearning4j_tpu.serving.kv_disk import resolve_disk_pool
    return KVLifecycleManager(policy=str(kv_evict),
                              swap_bytes=int(kv_swap_bytes),
                              mode=kv_evict_mode,
                              flops_per_token=flops_per_token,
                              disk_pool=resolve_disk_pool(kv_disk,
                                                          kv_disk_bytes))


def resolve_prefix_store(prefix_store) -> Optional[PersistentPrefixStore]:
    """Engine-constructor resolution of the prefix-store knob: an
    instance passes through (the ShardedServingGroup hands ONE store to
    every replica), True builds a RAM-only store, a string is a spill
    path; None defers to `DL4J_TPU_PREFIX_STORE` (path, empty = off).
    A path-backed store auto-loads its spill file when it exists."""
    if prefix_store is None:
        path = os.environ.get("DL4J_TPU_PREFIX_STORE", "")
        if not path or path == "0":
            return None
        prefix_store = path
    if isinstance(prefix_store, PersistentPrefixStore):
        return prefix_store
    if prefix_store is True:
        return PersistentPrefixStore()
    store = PersistentPrefixStore(path=str(prefix_store))
    store.load()
    return store
