"""Deterministic open-loop load generator for the serving engine (ISSUE 8).

OPEN-LOOP means arrivals are scheduled by an external clock and keep
coming at the offered rate whether or not the engine keeps up — the only
protocol under which queueing actually bites and a TTFT/goodput claim
means anything. (A closed-loop client waits for its previous request
before issuing the next, so offered load self-throttles to whatever the
engine can do and tail latency looks flat right up to collapse; PERF.md
"Goodput & SLO methodology".)

Two layers, split so the schedule is reproducible independent of the run:

- `build_schedule(spec)` — a PURE function of `LoadSpec` + seed (argument,
  else $DL4J_TPU_LOADGEN_SEED, else 0) producing the full arrival list:
  Poisson (exponential gaps) or bursty ON-OFF arrivals (exponential gaps
  at `rate / duty` inside ON windows, silence in OFF windows — same mean
  rate, much nastier queueing), prompt/output length mixes, and
  shared-prefix cohorts whose members draw a common prompt prefix so the
  paged cache's COW sharing (PR 7) is exercised under load. Identical
  spec + seed => identical schedule, byte for byte (regression-tested).
- `run(engine, schedule)` — submits each request when the wall clock
  passes its arrival time while driving `engine.step()` between
  submissions, then collects per-request `RequestOutcome`s from the
  engine's own lifecycle timestamps (queue_wait_s, ttft_s, timeline).
  Single-threaded and chunk-paced: a request arriving mid-chunk is
  submitted as soon as that chunk's sync returns, and the induced skew is
  recorded per request (`lateness_s`) instead of silently shifting the
  schedule.

The loadgen reads only host-side values (futures, host timestamps) — it
adds zero device syncs of its own (tests/test_sync_discipline.py scans
this module).

SESSION WORKLOADS (ISSUE 16). Multi-turn chat and agent tool-call loops
are what a prefix cache is FOR, and neither is expressible as an
open-loop arrival list: turn N+1's prompt embeds turn N's generated
reply, so the schedule cannot be precomputed. A third layer models them:

- `build_sessions(spec)` — a PURE function of `SessionSpec` + seed
  producing `SessionPlan`s: per-session Poisson start times, turn counts
  and user-message lengths from mixes, a shared system-prompt template
  drawn per cohort (the cross-SESSION sharing a radix tree also
  captures), and seeded fork decisions — a forked session replays the
  same conversation up to `fork_at` completed turns, then branches
  (the agent tree-search shape; fork turns share every pre-fork block).
- `run_sessions(engine, plans)` — CLOSED-LOOP per session (a user reads
  the reply before typing the next message; an agent consumes the tool
  result before the next call), open across sessions. Each branch
  resubmits its full grown history + the next user/tool message as a
  fresh `Request` carrying (session_id, turn_idx); with the radix prefix
  tree on, everything but the new suffix is served from retained blocks,
  and that is exactly the cross-turn KV reuse `bench.py` measures.
"""
from __future__ import annotations

import os
import time
from dataclasses import dataclass
from typing import List, Optional, Sequence, Tuple

import numpy as np

from deeplearning4j_tpu.serving.engine import Request

#: (value, weight) pairs; weights are normalized at draw time
LengthMix = Sequence[Tuple[int, float]]


@dataclass(frozen=True)
class LoadSpec:
    """Workload description: everything `build_schedule` needs."""
    rate: float                          # mean offered rate, req/s
    n_requests: int
    process: str = "poisson"             # "poisson" | "bursty"
    seed: Optional[int] = None           # None -> $DL4J_TPU_LOADGEN_SEED
    vocab: int = 32                      # prompt token ids in [0, vocab)
    prompt_len_mix: LengthMix = ((8, 1.0),)
    max_new_tokens_mix: LengthMix = ((8, 1.0),)
    temperature: float = 0.0
    # shared-prefix cohorts: this fraction of requests draw a common
    # prompt prefix from one of n_cohorts fixed templates (COW sharing)
    shared_frac: float = 0.0
    shared_prefix_len: int = 0
    n_cohorts: int = 1
    # ON-OFF burst shape (process="bursty"); duty = on / (on + off)
    burst_on_s: float = 1.0
    burst_off_s: float = 1.0
    timeout_s: Optional[float] = None    # per-request wall deadline


@dataclass(frozen=True)
class ScheduledRequest:
    t_arrival: float                     # seconds from schedule start
    tokens: Tuple[int, ...]
    max_new_tokens: int
    cohort: Optional[int] = None         # shared-prefix cohort index
    temperature: float = 0.0
    timeout_s: Optional[float] = None


@dataclass
class RequestOutcome:
    """One request's open-loop result, on the duck type slo.py evaluates
    (finish_reason / ttft_s / latency_s / n_tokens / queue_wait_s)."""
    req_id: int
    t_offered: float                     # scheduled arrival (schedule clock)
    t_submit: float                      # actual submit (s since run start)
    lateness_s: float                    # t_submit - t_offered (chunk skew)
    finish_reason: str = "shutdown"
    n_tokens: int = 0
    ttft_s: Optional[float] = None
    queue_wait_s: Optional[float] = None
    admission_retries: int = 0
    latency_s: Optional[float] = None    # submit -> retire (engine stamps)
    tokens_per_sec: Optional[float] = None
    cohort: Optional[int] = None
    timeline: Optional[List[dict]] = None
    # session fields (ISSUE 16): set by run_sessions, default-None for
    # the open-loop path so slo.py's duck type is unchanged
    session_id: Optional[str] = None
    turn_idx: Optional[int] = None
    prompt_len: int = 0
    shared_prefix_tokens: int = 0        # engine-reported prefix hit
    tokens: Optional[List[int]] = None   # generated row (parity checks)


@dataclass
class LoadResult:
    outcomes: List[RequestOutcome]
    offered_rate: float                  # empirical: n / last arrival
    achieved_rate: float                 # completed requests / wall
    wall_s: float                        # first submit -> all retired
    lateness_p99_s: float


def resolve_seed(seed: Optional[int]) -> int:
    if seed is not None:
        return int(seed)
    return int(os.environ.get("DL4J_TPU_LOADGEN_SEED", "0"))


def _draw(rng: np.random.RandomState, mix: LengthMix) -> int:
    vals = [int(v) for v, _ in mix]
    # sync-ok: mix weights are python floats from the spec literal
    w = np.asarray([float(w) for _, w in mix], np.float64)
    return int(vals[rng.choice(len(vals), p=w / w.sum())])


def _arrivals(rng: np.random.RandomState, spec: LoadSpec) -> np.ndarray:
    if spec.rate <= 0 or spec.n_requests < 1:
        raise ValueError("rate > 0 and n_requests >= 1 required")
    if spec.process == "poisson":
        return np.cumsum(rng.exponential(1.0 / spec.rate,
                                         size=spec.n_requests))
    if spec.process == "bursty":
        duty = spec.burst_on_s / (spec.burst_on_s + spec.burst_off_s)
        rate_on = spec.rate / duty       # long-run mean stays spec.rate
        out: List[float] = []
        t = 0.0
        while len(out) < spec.n_requests:
            on_end = t + spec.burst_on_s
            while len(out) < spec.n_requests:
                # sync-ok: host RNG draw, never a device buffer
                t += float(rng.exponential(1.0 / rate_on))
                if t >= on_end:
                    break
                out.append(t)
            t = on_end + spec.burst_off_s
        return np.asarray(out)  # sync-ok: host-built arrival list
    raise ValueError(f"unknown arrival process {spec.process!r}")


def build_schedule(spec: LoadSpec) -> List[ScheduledRequest]:
    """The full arrival schedule as a pure function of (spec, seed): all
    randomness flows through one seeded RandomState in a fixed draw order,
    so the same spec + seed reproduces the same schedule exactly."""
    # the ONE seeded generator — every draw flows through it in a fixed
    # order, so the schedule is a pure function of (spec, seed)
    rng = np.random.RandomState(resolve_seed(spec.seed))   # det-ok: seeded
    arrivals = _arrivals(rng, spec)
    cohorts: List[Tuple[int, ...]] = []
    if spec.shared_frac > 0 and spec.shared_prefix_len > 0:
        cohorts = [tuple(rng.randint(0, spec.vocab,
                                     size=spec.shared_prefix_len).tolist())
                   for _ in range(max(1, spec.n_cohorts))]
    out: List[ScheduledRequest] = []
    for i in range(spec.n_requests):
        plen = _draw(rng, spec.prompt_len_mix)
        mnew = _draw(rng, spec.max_new_tokens_mix)
        cohort = None
        # sync-ok: host RNG draw
        if cohorts and float(rng.uniform()) < spec.shared_frac:
            cohort = int(rng.randint(len(cohorts)))
            # fixed prefix + >=1 fresh suffix token: cohort members share
            # their leading blocks exactly (what COW admission matches on)
            suffix = rng.randint(0, spec.vocab,
                                 size=max(1, plen - spec.shared_prefix_len))
            toks = cohorts[cohort] + tuple(suffix.tolist())
        else:
            toks = tuple(rng.randint(0, spec.vocab, size=plen).tolist())
        # sync-ok: arrivals is a host numpy array built above
        out.append(ScheduledRequest(float(arrivals[i]), toks, mnew,
                                    cohort=cohort,
                                    temperature=spec.temperature,
                                    timeout_s=spec.timeout_s))
    return out


def run(engine, schedule: Sequence[ScheduledRequest]) -> LoadResult:
    """Open-loop run: submit each scheduled request once the wall clock
    passes its arrival time, drive `engine.step()` in between, and return
    per-request outcomes built from the engine's lifecycle timestamps.

    `engine` is duck-typed on submit(Request) -> future and step() ->
    bool, so a serving.sharding.ShardedServingGroup (ISSUE 10) plugs in
    unchanged: submits route across replicas, step() advances every
    replica one scheduler iteration, and the outcomes — hence the SLO
    evaluation built on them — span the whole fleet."""
    n = len(schedule)
    outs: List[Optional[RequestOutcome]] = [None] * n
    futs: List[Optional[object]] = [None] * n
    # the open-loop submit loop IS a wall-clock pacer by design —
    # arrivals land on real time; replay reproduces them on the tick
    # clock from the engine's journaled arrival records instead
    t0 = time.monotonic()   # det-ok: open-loop pacer origin
    i = 0
    busy = True
    while i < n or busy:
        now = time.monotonic() - t0   # det-ok: submit pacing
        while i < n and schedule[i].t_arrival <= now:
            sr = schedule[i]
            t_sub = time.monotonic() - t0   # det-ok: submit stamp
            futs[i] = engine.submit(Request(
                list(sr.tokens), max_new_tokens=sr.max_new_tokens,
                temperature=sr.temperature, timeout_s=sr.timeout_s))
            outs[i] = RequestOutcome(
                req_id=-1, t_offered=sr.t_arrival, t_submit=t_sub,
                lateness_s=t_sub - sr.t_arrival, cohort=sr.cohort)
            i += 1
            now = time.monotonic() - t0   # det-ok: submit pacing
        busy = engine.step()
        if not busy and i < n:
            # det-ok: idle-nap pacing
            wait = schedule[i].t_arrival - (time.monotonic() - t0)
            if wait > 0:                 # idle engine: nap until the next
                time.sleep(min(wait, 0.002))   # arrival, in small slices
    wall_s = time.monotonic() - t0   # det-ok: run-wall measurement
    n_done = 0
    lateness: List[float] = []
    for k, fut in enumerate(futs):
        res = fut.get(timeout=0)         # engine idle => all resolved
        o = outs[k]
        o.req_id = res.req_id
        o.finish_reason = res.finish_reason
        o.n_tokens = len(res.tokens)
        o.ttft_s = res.ttft_s
        o.queue_wait_s = res.queue_wait_s
        o.admission_retries = res.admission_retries
        o.tokens_per_sec = res.tokens_per_sec
        o.timeline = res.timeline
        if res.timeline:
            o.latency_s = (max(e["t1"] for e in res.timeline)
                           - min(e["t0"] for e in res.timeline))
        if res.finish_reason in ("eos", "length"):
            n_done += 1
        lateness.append(o.lateness_s)
    offered = n / max(schedule[-1].t_arrival, 1e-9) if n else 0.0
    # sync-ok: lateness is a host list of wall-clock deltas
    p99 = float(np.percentile(np.asarray(lateness), 99)) if lateness else 0.0
    return LoadResult(outcomes=[o for o in outs if o is not None],
                      offered_rate=offered,
                      achieved_rate=n_done / max(wall_s, 1e-9),
                      wall_s=wall_s, lateness_p99_s=p99)


def run_spec(engine, spec: LoadSpec) -> LoadResult:
    """Convenience: build the schedule and run it."""
    return run(engine, build_schedule(spec))


# ====================================================== session workloads
@dataclass(frozen=True)
class SessionTurn:
    """One user (or tool-result) message in a session and the reply cap."""
    user_tokens: Tuple[int, ...]
    max_new_tokens: int


@dataclass(frozen=True)
class SessionPlan:
    """One planned session: the deterministic half of a multi-turn
    conversation (what the 'user' will say; the replies come from the
    engine at run time). `fork_at` > 0 plans an agent-style branch: after
    `fork_at` turns complete, a second branch continues from a COPY of
    the history with its own turns — with the radix tree on, every
    pre-fork block is shared between the branches."""
    session_id: str
    t_start: float                       # seconds from run start
    turns: Tuple[SessionTurn, ...]
    cohort: int = 0                      # which system-prompt template
    fork_at: int = 0                     # completed turns at branch point
    fork_turns: Tuple[SessionTurn, ...] = ()
    think_time_s: float = 0.0            # reply -> next-message gap
    temperature: float = 0.0
    timeout_s: Optional[float] = None
    scenario: str = "chat"               # "chat" | "agent" (labeling)


@dataclass(frozen=True)
class SessionSpec:
    """Session workload description: everything `build_sessions` needs."""
    n_sessions: int
    rate: float = 4.0                    # session starts / s (Poisson)
    turns_mix: LengthMix = ((3, 1.0),)   # turns per session
    user_len_mix: LengthMix = ((24, 1.0),)
    max_new_tokens_mix: LengthMix = ((16, 1.0),)
    # shared system prompt: every session's turn-0 message is prefixed
    # with one of n_system_prompts fixed templates (cross-session reuse)
    system_prompt_len: int = 0
    n_system_prompts: int = 1
    # agent forking: this fraction of multi-turn sessions branch after a
    # seeded number of completed turns (tree-search / tool-retry shape)
    fork_frac: float = 0.0
    fork_turns_mix: LengthMix = ((1, 1.0),)
    scenario: str = "chat"
    think_time_s: float = 0.0
    seed: Optional[int] = None           # None -> $DL4J_TPU_LOADGEN_SEED
    vocab: int = 32
    temperature: float = 0.0
    timeout_s: Optional[float] = None


@dataclass
class SessionLoadResult:
    outcomes: List[RequestOutcome]       # one per completed turn
    n_sessions: int                      # branches counted separately
    n_turns: int                         # completed turns across branches
    wall_s: float
    prompt_tokens: int                   # total submitted prompt tokens
    followup_prompt_tokens: int          # prompt tokens on turn_idx > 0
    shared_prefix_tokens: int            # engine-reported prefix hits
    new_tokens: int                      # generated tokens


def build_sessions(spec: SessionSpec) -> List[SessionPlan]:
    """Session plans as a pure function of (spec, seed): one seeded
    RandomState, fixed draw order — identical spec + seed reproduces the
    same session graph (starts, turn counts, every message, every fork)
    exactly, which is what lets bench.py replay the SAME workload with
    the radix tree on and off."""
    if spec.n_sessions < 1 or spec.rate <= 0:
        raise ValueError("n_sessions >= 1 and rate > 0 required")
    # det-ok: single seeded generator, fixed draw order (see docstring)
    rng = np.random.RandomState(resolve_seed(spec.seed))
    starts = np.cumsum(rng.exponential(1.0 / spec.rate,
                                       size=spec.n_sessions))
    sys_prompts: List[Tuple[int, ...]] = []
    if spec.system_prompt_len > 0:
        sys_prompts = [
            tuple(rng.randint(0, spec.vocab,
                              size=spec.system_prompt_len).tolist())
            for _ in range(max(1, spec.n_system_prompts))]

    def _turn(first: bool, cohort: int) -> SessionTurn:
        ulen = _draw(rng, spec.user_len_mix)
        toks = tuple(rng.randint(0, spec.vocab, size=ulen).tolist())
        if first and sys_prompts:
            toks = sys_prompts[cohort] + toks
        return SessionTurn(toks, _draw(rng, spec.max_new_tokens_mix))

    plans: List[SessionPlan] = []
    for s in range(spec.n_sessions):
        n_turns = max(1, _draw(rng, spec.turns_mix))
        cohort = int(rng.randint(len(sys_prompts))) if sys_prompts else 0
        turns = tuple(_turn(i == 0, cohort) for i in range(n_turns))
        fork_at, fork_turns = 0, ()
        # sync-ok: host RNG draw
        if n_turns >= 2 and float(rng.uniform()) < spec.fork_frac:
            fork_at = int(rng.randint(1, n_turns))
            n_fork = max(1, _draw(rng, spec.fork_turns_mix))
            fork_turns = tuple(_turn(False, cohort)
                               for _ in range(n_fork))
        t_start = float(starts[s])  # sync-ok: host numpy array built above
        plans.append(SessionPlan(
            session_id=f"s{s}", t_start=t_start, turns=turns,
            cohort=cohort, fork_at=fork_at, fork_turns=fork_turns,
            think_time_s=spec.think_time_s, temperature=spec.temperature,
            timeout_s=spec.timeout_s, scenario=spec.scenario))
    return plans


def _poll(fut) -> Optional[object]:
    """Non-blocking future read: the result if retired, else None."""
    try:
        return fut.get(timeout=0)
    except TimeoutError:
        return None


class _Branch:
    """Run-time state of one conversation branch (a session, or the
    forked continuation of one)."""

    __slots__ = ("sid", "plan", "turns", "history", "next_turn",
                 "turn_base", "ready_t", "fut", "t_submit", "done")

    def __init__(self, sid: str, plan: SessionPlan,
                 turns: Tuple[SessionTurn, ...], history: List[int],
                 turn_base: int, ready_t: float):
        self.sid = sid
        self.plan = plan
        self.turns = turns
        self.history = history           # full conversation so far
        self.next_turn = 0               # index into `turns`
        self.turn_base = turn_base       # global turn_idx of turns[0]
        self.ready_t = ready_t
        self.fut = None
        self.t_submit = 0.0
        self.done = False


def run_sessions(engine, plans: Sequence[SessionPlan]
                 ) -> SessionLoadResult:
    """Closed-loop session driver: each branch waits for its reply (and
    think time) before the next turn; branches and sessions overlap
    freely. Every turn resubmits the FULL grown history + the next
    message as a fresh Request stamped (session_id, turn_idx) — the
    prefix cache, not the loadgen, is responsible for not recomputing
    the shared past. Host-side only: futures and wall clocks."""
    outcomes: List[RequestOutcome] = []
    branches: List[_Branch] = []
    pending = sorted(plans, key=lambda p: p.t_start)
    pi = 0
    t0 = time.monotonic()   # det-ok: session pacer (see run() note)
    while pi < len(pending) or any(not b.done for b in branches):
        now = time.monotonic() - t0   # det-ok: submit pacing
        while pi < len(pending) and pending[pi].t_start <= now:
            p = pending[pi]
            branches.append(_Branch(p.session_id, p, p.turns, [], 0,
                                    p.t_start))
            pi += 1
        progressed = False
        for b in branches:
            if b.done or b.fut is not None:
                continue
            if b.next_turn >= len(b.turns):
                b.done = True
                continue
            if b.ready_t > now:
                continue
            turn = b.turns[b.next_turn]
            b.history.extend(turn.user_tokens)
            b.t_submit = time.monotonic() - t0   # det-ok: submit stamp
            b.fut = engine.submit(Request(
                list(b.history), max_new_tokens=turn.max_new_tokens,
                temperature=b.plan.temperature,
                timeout_s=b.plan.timeout_s, session_id=b.sid,
                turn_idx=b.turn_base + b.next_turn))
            progressed = True
        busy = engine.step()
        now = time.monotonic() - t0   # det-ok: think-time pacing
        for b in branches:
            if b.fut is None:
                continue
            res = _poll(b.fut)
            if res is None:
                continue
            b.fut = None
            tidx = b.turn_base + b.next_turn
            outcomes.append(RequestOutcome(
                req_id=res.req_id, t_offered=b.plan.t_start,
                t_submit=b.t_submit, lateness_s=0.0,
                finish_reason=res.finish_reason,
                n_tokens=len(res.tokens), ttft_s=res.ttft_s,
                queue_wait_s=res.queue_wait_s,
                admission_retries=res.admission_retries,
                tokens_per_sec=res.tokens_per_sec,
                cohort=b.plan.cohort, timeline=res.timeline,
                session_id=b.sid, turn_idx=tidx,
                prompt_len=res.prompt_len,
                shared_prefix_tokens=res.shared_prefix_tokens,
                tokens=list(res.tokens)))
            if res.timeline:
                outcomes[-1].latency_s = (
                    max(e["t1"] for e in res.timeline)
                    - min(e["t0"] for e in res.timeline))
            if res.finish_reason not in ("eos", "length"):
                b.done = True            # timeout/shutdown: abandon branch
                continue
            b.history.extend(res.tokens)
            b.next_turn += 1
            b.ready_t = now + b.plan.think_time_s
            progressed = True
            if (b.sid == b.plan.session_id and b.plan.fork_at
                    and b.next_turn == b.plan.fork_at):
                # branch point: the fork continues from a COPY of the
                # history — pre-fork blocks are shared, not recomputed
                branches.append(_Branch(
                    b.plan.session_id + "f", b.plan, b.plan.fork_turns,
                    list(b.history), b.plan.fork_at, b.ready_t))
            if b.next_turn >= len(b.turns):
                b.done = True
        if not busy and not progressed:
            time.sleep(0.0005)           # everyone thinking / waiting
    wall_s = time.monotonic() - t0   # det-ok: run-wall measurement
    return SessionLoadResult(
        outcomes=outcomes,
        n_sessions=len(branches),
        n_turns=len(outcomes),
        wall_s=wall_s,
        prompt_tokens=sum(o.prompt_len for o in outcomes),
        followup_prompt_tokens=sum(o.prompt_len for o in outcomes
                                   if o.turn_idx),
        shared_prefix_tokens=sum(o.shared_prefix_tokens
                                 for o in outcomes),
        new_tokens=sum(o.n_tokens for o in outcomes))


def run_session_spec(engine, spec: SessionSpec) -> SessionLoadResult:
    """Convenience: build the session plans and run them."""
    return run_sessions(engine, build_sessions(spec))
