"""Deterministic open-loop load generator for the serving engine (ISSUE 8).

OPEN-LOOP means arrivals are scheduled by an external clock and keep
coming at the offered rate whether or not the engine keeps up — the only
protocol under which queueing actually bites and a TTFT/goodput claim
means anything. (A closed-loop client waits for its previous request
before issuing the next, so offered load self-throttles to whatever the
engine can do and tail latency looks flat right up to collapse; PERF.md
"Goodput & SLO methodology".)

Two layers, split so the schedule is reproducible independent of the run:

- `build_schedule(spec)` — a PURE function of `LoadSpec` + seed (argument,
  else $DL4J_TPU_LOADGEN_SEED, else 0) producing the full arrival list:
  Poisson (exponential gaps) or bursty ON-OFF arrivals (exponential gaps
  at `rate / duty` inside ON windows, silence in OFF windows — same mean
  rate, much nastier queueing), prompt/output length mixes, and
  shared-prefix cohorts whose members draw a common prompt prefix so the
  paged cache's COW sharing (PR 7) is exercised under load. Identical
  spec + seed => identical schedule, byte for byte (regression-tested).
- `run(engine, schedule)` — submits each request when the wall clock
  passes its arrival time while driving `engine.step()` between
  submissions, then collects per-request `RequestOutcome`s from the
  engine's own lifecycle timestamps (queue_wait_s, ttft_s, timeline).
  Single-threaded and chunk-paced: a request arriving mid-chunk is
  submitted as soon as that chunk's sync returns, and the induced skew is
  recorded per request (`lateness_s`) instead of silently shifting the
  schedule.

The loadgen reads only host-side values (futures, host timestamps) — it
adds zero device syncs of its own (tests/test_sync_discipline.py scans
this module).
"""
from __future__ import annotations

import os
import time
from dataclasses import dataclass
from typing import List, Optional, Sequence, Tuple

import numpy as np

from deeplearning4j_tpu.serving.engine import Request

#: (value, weight) pairs; weights are normalized at draw time
LengthMix = Sequence[Tuple[int, float]]


@dataclass(frozen=True)
class LoadSpec:
    """Workload description: everything `build_schedule` needs."""
    rate: float                          # mean offered rate, req/s
    n_requests: int
    process: str = "poisson"             # "poisson" | "bursty"
    seed: Optional[int] = None           # None -> $DL4J_TPU_LOADGEN_SEED
    vocab: int = 32                      # prompt token ids in [0, vocab)
    prompt_len_mix: LengthMix = ((8, 1.0),)
    max_new_tokens_mix: LengthMix = ((8, 1.0),)
    temperature: float = 0.0
    # shared-prefix cohorts: this fraction of requests draw a common
    # prompt prefix from one of n_cohorts fixed templates (COW sharing)
    shared_frac: float = 0.0
    shared_prefix_len: int = 0
    n_cohorts: int = 1
    # ON-OFF burst shape (process="bursty"); duty = on / (on + off)
    burst_on_s: float = 1.0
    burst_off_s: float = 1.0
    timeout_s: Optional[float] = None    # per-request wall deadline


@dataclass(frozen=True)
class ScheduledRequest:
    t_arrival: float                     # seconds from schedule start
    tokens: Tuple[int, ...]
    max_new_tokens: int
    cohort: Optional[int] = None         # shared-prefix cohort index
    temperature: float = 0.0
    timeout_s: Optional[float] = None


@dataclass
class RequestOutcome:
    """One request's open-loop result, on the duck type slo.py evaluates
    (finish_reason / ttft_s / latency_s / n_tokens / queue_wait_s)."""
    req_id: int
    t_offered: float                     # scheduled arrival (schedule clock)
    t_submit: float                      # actual submit (s since run start)
    lateness_s: float                    # t_submit - t_offered (chunk skew)
    finish_reason: str = "shutdown"
    n_tokens: int = 0
    ttft_s: Optional[float] = None
    queue_wait_s: Optional[float] = None
    admission_retries: int = 0
    latency_s: Optional[float] = None    # submit -> retire (engine stamps)
    tokens_per_sec: Optional[float] = None
    cohort: Optional[int] = None
    timeline: Optional[List[dict]] = None


@dataclass
class LoadResult:
    outcomes: List[RequestOutcome]
    offered_rate: float                  # empirical: n / last arrival
    achieved_rate: float                 # completed requests / wall
    wall_s: float                        # first submit -> all retired
    lateness_p99_s: float


def resolve_seed(seed: Optional[int]) -> int:
    if seed is not None:
        return int(seed)
    return int(os.environ.get("DL4J_TPU_LOADGEN_SEED", "0"))


def _draw(rng: np.random.RandomState, mix: LengthMix) -> int:
    vals = [int(v) for v, _ in mix]
    # sync-ok: mix weights are python floats from the spec literal
    w = np.asarray([float(w) for _, w in mix], np.float64)
    return int(vals[rng.choice(len(vals), p=w / w.sum())])


def _arrivals(rng: np.random.RandomState, spec: LoadSpec) -> np.ndarray:
    if spec.rate <= 0 or spec.n_requests < 1:
        raise ValueError("rate > 0 and n_requests >= 1 required")
    if spec.process == "poisson":
        return np.cumsum(rng.exponential(1.0 / spec.rate,
                                         size=spec.n_requests))
    if spec.process == "bursty":
        duty = spec.burst_on_s / (spec.burst_on_s + spec.burst_off_s)
        rate_on = spec.rate / duty       # long-run mean stays spec.rate
        out: List[float] = []
        t = 0.0
        while len(out) < spec.n_requests:
            on_end = t + spec.burst_on_s
            while len(out) < spec.n_requests:
                # sync-ok: host RNG draw, never a device buffer
                t += float(rng.exponential(1.0 / rate_on))
                if t >= on_end:
                    break
                out.append(t)
            t = on_end + spec.burst_off_s
        return np.asarray(out)  # sync-ok: host-built arrival list
    raise ValueError(f"unknown arrival process {spec.process!r}")


def build_schedule(spec: LoadSpec) -> List[ScheduledRequest]:
    """The full arrival schedule as a pure function of (spec, seed): all
    randomness flows through one seeded RandomState in a fixed draw order,
    so the same spec + seed reproduces the same schedule exactly."""
    rng = np.random.RandomState(resolve_seed(spec.seed))
    arrivals = _arrivals(rng, spec)
    cohorts: List[Tuple[int, ...]] = []
    if spec.shared_frac > 0 and spec.shared_prefix_len > 0:
        cohorts = [tuple(rng.randint(0, spec.vocab,
                                     size=spec.shared_prefix_len).tolist())
                   for _ in range(max(1, spec.n_cohorts))]
    out: List[ScheduledRequest] = []
    for i in range(spec.n_requests):
        plen = _draw(rng, spec.prompt_len_mix)
        mnew = _draw(rng, spec.max_new_tokens_mix)
        cohort = None
        # sync-ok: host RNG draw
        if cohorts and float(rng.uniform()) < spec.shared_frac:
            cohort = int(rng.randint(len(cohorts)))
            # fixed prefix + >=1 fresh suffix token: cohort members share
            # their leading blocks exactly (what COW admission matches on)
            suffix = rng.randint(0, spec.vocab,
                                 size=max(1, plen - spec.shared_prefix_len))
            toks = cohorts[cohort] + tuple(suffix.tolist())
        else:
            toks = tuple(rng.randint(0, spec.vocab, size=plen).tolist())
        # sync-ok: arrivals is a host numpy array built above
        out.append(ScheduledRequest(float(arrivals[i]), toks, mnew,
                                    cohort=cohort,
                                    temperature=spec.temperature,
                                    timeout_s=spec.timeout_s))
    return out


def run(engine, schedule: Sequence[ScheduledRequest]) -> LoadResult:
    """Open-loop run: submit each scheduled request once the wall clock
    passes its arrival time, drive `engine.step()` in between, and return
    per-request outcomes built from the engine's lifecycle timestamps.

    `engine` is duck-typed on submit(Request) -> future and step() ->
    bool, so a serving.sharding.ShardedServingGroup (ISSUE 10) plugs in
    unchanged: submits route across replicas, step() advances every
    replica one scheduler iteration, and the outcomes — hence the SLO
    evaluation built on them — span the whole fleet."""
    n = len(schedule)
    outs: List[Optional[RequestOutcome]] = [None] * n
    futs: List[Optional[object]] = [None] * n
    t0 = time.monotonic()
    i = 0
    busy = True
    while i < n or busy:
        now = time.monotonic() - t0
        while i < n and schedule[i].t_arrival <= now:
            sr = schedule[i]
            t_sub = time.monotonic() - t0
            futs[i] = engine.submit(Request(
                list(sr.tokens), max_new_tokens=sr.max_new_tokens,
                temperature=sr.temperature, timeout_s=sr.timeout_s))
            outs[i] = RequestOutcome(
                req_id=-1, t_offered=sr.t_arrival, t_submit=t_sub,
                lateness_s=t_sub - sr.t_arrival, cohort=sr.cohort)
            i += 1
            now = time.monotonic() - t0
        busy = engine.step()
        if not busy and i < n:
            wait = schedule[i].t_arrival - (time.monotonic() - t0)
            if wait > 0:                 # idle engine: nap until the next
                time.sleep(min(wait, 0.002))   # arrival, in small slices
    wall_s = time.monotonic() - t0
    n_done = 0
    lateness: List[float] = []
    for k, fut in enumerate(futs):
        res = fut.get(timeout=0)         # engine idle => all resolved
        o = outs[k]
        o.req_id = res.req_id
        o.finish_reason = res.finish_reason
        o.n_tokens = len(res.tokens)
        o.ttft_s = res.ttft_s
        o.queue_wait_s = res.queue_wait_s
        o.admission_retries = res.admission_retries
        o.tokens_per_sec = res.tokens_per_sec
        o.timeline = res.timeline
        if res.timeline:
            o.latency_s = (max(e["t1"] for e in res.timeline)
                           - min(e["t0"] for e in res.timeline))
        if res.finish_reason in ("eos", "length"):
            n_done += 1
        lateness.append(o.lateness_s)
    offered = n / max(schedule[-1].t_arrival, 1e-9) if n else 0.0
    # sync-ok: lateness is a host list of wall-clock deltas
    p99 = float(np.percentile(np.asarray(lateness), 99)) if lateness else 0.0
    return LoadResult(outcomes=[o for o in outs if o is not None],
                      offered_rate=offered,
                      achieved_rate=n_done / max(wall_s, 1e-9),
                      wall_s=wall_s, lateness_p99_s=p99)


def run_spec(engine, spec: LoadSpec) -> LoadResult:
    """Convenience: build the schedule and run it."""
    return run(engine, build_schedule(spec))
