"""Host-side paged-KV bookkeeping: block allocator + prefix registry.

Beyond-reference (PagedAttention, Kwon et al. SOSP 2023; PAPERS.md). The
paged KV cache (serving/kv_cache.py) carves the preallocated k/v buffers
into fixed-size physical BLOCKS of `block_size` positions and gives every
slot a device block table mapping logical block index -> physical block.
This module is the host half of that design — pure Python bookkeeping that
runs between decode iterations (iteration-level scheduling), never on the
hot path and never touching the device:

- `BlockAllocator`: a refcounted free list over physical block ids. The
  free list is a `heapq` (lowest id first, like the slot free list), so
  alloc/free are O(log n) — with hundreds of blocks per cache the old
  slot-list idiom (`pop(0)` + per-free `sort()`) would actually show up.
  Refcounts exist for copy-on-write prefix sharing: a block mapped by R
  slots has refcount R and only returns to the free list when the last
  mapping drops.

  For the KV observatory (ISSUE 12) the allocator also keeps per-block
  HEAT metadata: a monotonically increasing iteration `clock` (advanced
  by the engine once per scheduler iteration via `tick()`), a
  `last_touch` stamp (updated at alloc/incref and whenever the engine
  credits a write into the block via `touch()`), and an `alloc_epoch`
  (the clock value when the current residency began). All of it is plain
  host integers riding bookkeeping the scheduler already does — no
  device reads, so the host-sync bit-parity guarantee is untouched.

- `PrefixRegistry`: a content-addressed index of RESIDENT prompt blocks.
  Keys are chain hashes — the digest of block i covers prompt tokens
  [0, (i+1)*block_size), so a hit guarantees the whole prefix matches,
  not just one block. Full prompt blocks are registered under their chain
  digest; a prompt that ends mid-block additionally registers its partial
  tail under an exact-prompt digest, so two identical prompts share right
  up to the last token (the tail block is then copy-on-write, never
  mapped shared — the new request's own writes land in it). Entries are
  valid exactly while the backing block is resident: the cache calls
  `forget(block)` the moment a block's refcount reaches zero.

Safety argument for sharing (why a shared block is never wrong): a block
is only registered for prompt positions its owner's prefill (or COW copy
+ suffix prefill) actually wrote, the KV projection of a token sequence
is deterministic in the model params, and fully-shared blocks are never
written by any sharer — a request writes only positions >= its shared
prefix length, and admission maps the block containing the first such
write as a fresh COPY (copy-on-write), never as a shared mapping.
"""
from __future__ import annotations

import hashlib
import heapq
import weakref
from typing import Dict, List, Optional, Sequence, Tuple


class BlockAllocator:
    """Refcounted heapq free list over physical block ids [0, num_blocks)."""

    def __init__(self, num_blocks: int):
        if num_blocks < 1:
            raise ValueError(f"need at least one block, got {num_blocks}")
        self.num_blocks = int(num_blocks)
        # list(range(n)) is already a valid min-heap — no heapify needed
        self._free: List[int] = list(range(self.num_blocks))
        self._ref: List[int] = [0] * self.num_blocks
        self._n_shared = 0          # blocks with refcount >= 2
        # heat metadata (ISSUE 12): iteration clock + per-block stamps.
        # Stamps are only meaningful while a block is mapped (refcount>=1).
        self.clock = 0
        self._last_touch: List[int] = [0] * self.num_blocks
        self._alloc_epoch: List[int] = [0] * self.num_blocks

    # ------------------------------------------------------------- heat
    def tick(self) -> int:
        """Advance the iteration clock (one scheduler iteration). Pure
        host arithmetic — the clock is the unit of every heat stamp."""
        self.clock += 1
        return self.clock

    def touch(self, block: int) -> None:
        """Stamp `block` as touched at the current clock. Called by the
        cache when the engine credits a write (prefill chunk, decode
        append, spec commit) into a position the block covers."""
        if self._ref[block] < 1:
            raise ValueError(f"touch on free block {block}")
        self._last_touch[block] = self.clock

    def last_touch(self, block: int) -> int:
        return self._last_touch[block]

    def alloc_epoch(self, block: int) -> int:
        return self._alloc_epoch[block]

    # ------------------------------------------------------------ alloc
    def alloc(self) -> Optional[int]:
        """Claim one free block (lowest id first, refcount 1) or None."""
        if not self._free:
            return None
        b = heapq.heappop(self._free)
        self._ref[b] = 1
        self._alloc_epoch[b] = self._last_touch[b] = self.clock
        return b

    def alloc_many(self, n: int) -> Optional[List[int]]:
        """Claim `n` blocks all-or-nothing (admission never half-succeeds);
        returns None without side effects when fewer than `n` are free."""
        if n < 0:
            raise ValueError(f"negative block count {n}")
        if len(self._free) < n:
            return None
        return [self.alloc() for _ in range(n)]

    # ---------------------------------------------------------- refcount
    def incref(self, block: int) -> None:
        """One more mapping of an already-resident block (prefix sharing)."""
        if self._ref[block] < 1:
            raise ValueError(f"incref on free block {block}")
        self._ref[block] += 1
        if self._ref[block] == 2:
            self._n_shared += 1
        self._last_touch[block] = self.clock   # a new mapping is a touch

    def decref(self, block: int) -> bool:
        """Drop one mapping; returns True when the block just became free
        (the caller must then invalidate any registry entries it backs)."""
        if self._ref[block] < 1:
            raise ValueError(f"double free of block {block}")
        self._ref[block] -= 1
        if self._ref[block] == 1:
            self._n_shared -= 1
        if self._ref[block] == 0:
            heapq.heappush(self._free, block)
            return True
        return False

    def refcount(self, block: int) -> int:
        return self._ref[block]

    @property
    def n_free(self) -> int:
        return len(self._free)

    @property
    def n_shared(self) -> int:
        """Blocks currently mapped by 2+ slots (the sharing win gauge)."""
        return self._n_shared


def _block_digest(prev: Optional["hashlib._Hash"], tokens: Sequence[int],
                  tail: bool = False) -> "hashlib._Hash":
    """Extend a chain hash by one block of prompt tokens. The digest of
    block i commits to every token in [0, (i+1)*block_size) — a registry
    hit therefore certifies the WHOLE prefix. Tail digests get a distinct
    domain tag so a partial block can never collide with a full one."""
    h = prev.copy() if prev is not None else hashlib.sha1(b"kvprefix:")
    h.update(b"t:" if tail else b"b:")
    h.update(",".join(str(int(t)) for t in tokens).encode())
    h.update(b";")
    return h


def chain_digests(tokens: Sequence[int], block_size: int) -> List[bytes]:
    """Chain digests of every FULL block of `tokens` — digest i commits
    to tokens [0, (i+1)*block_size), exactly the keys `PrefixRegistry`
    files full blocks under. The `PersistentPrefixStore`
    (serving/lifecycle.py) uses these as content addresses for host-side
    KV block bytes: a digest hit certifies the whole covered prefix, the
    same safety certificate the resident registry gives, so restored
    bytes can be mapped without re-running prefill."""
    bs = int(block_size)
    out: List[bytes] = []
    h = None
    for i in range(len(tokens) // bs):
        h = _block_digest(h, tokens[i * bs:(i + 1) * bs])
        out.append(h.digest())
    return out


class PrefixRegistry:
    """Content-addressed index of resident prompt KV blocks.

    match() walks a prompt block by block down the chain-hash index and
    returns the longest registered prefix plus the physical blocks holding
    it; register() files a freshly prefilled prompt's blocks; forget()
    removes every claim backed by a block the allocator just freed.

    A registry may be constructed by its KV cache (the default) or handed
    in from outside (`KVCache(prefix_registry=...)`, ISSUE 10) so routers
    can run read-only `match()` affinity queries against it. Physical
    block ids are meaningful only within the ONE pool that allocated them,
    so every cache claims its registry via `bind_pool` — sharing one
    registry between two pools would hand pool B garbage block ids from
    pool A, and is rejected."""

    def __init__(self, block_size: int):
        self.block_size = int(block_size)
        self._full: Dict[bytes, int] = {}    # chain digest -> physical block
        self._tail: Dict[bytes, int] = {}    # exact-prompt digest -> block
        self._claims: Dict[int, List[Tuple[str, bytes]]] = {}  # invalidation
        self._pool: Optional[weakref.ref] = None
        # per-lineage hit counting (ISSUE 16 satellite): first registration
        # wins used to SHADOW re-registrations silently — a popular prefix
        # re-filed by every sharer looked exactly as cold as a one-off. A
        # re-registered already-claimed digest now counts one hit (the
        # mapping is unchanged; the new copy holds identical content).
        self.lineage_hits_total = 0
        self._lineage_hits: Dict[str, int] = {}

    def bind_pool(self, pool: object) -> "PrefixRegistry":
        """Claim this registry for one block pool (idempotent per pool).
        Raises if a DIFFERENT live pool already owns it — block ids do not
        transfer between pools, so cross-pool sharing is always a bug."""
        if self._pool is not None:
            owner = self._pool()
            if owner is not None and owner is not pool:
                raise ValueError(
                    "PrefixRegistry is already bound to another KV pool; "
                    "physical block ids are pool-scoped, so one registry "
                    "cannot serve two pools (give each replica its own)")
        self._pool = weakref.ref(pool)
        return self

    def match(self, tokens: Sequence[int]) -> Tuple[int, List[int]]:
        """(matched_len, physical blocks covering it) for the longest
        registered prefix of `tokens` — full blocks first, then (only when
        every full block matched) the exact-prompt partial tail."""
        bs = self.block_size
        n_full = len(tokens) // bs
        blocks: List[int] = []
        h = None
        for i in range(n_full):
            h = _block_digest(h, tokens[i * bs:(i + 1) * bs])
            b = self._full.get(h.digest())
            if b is None:
                return i * bs, blocks
            blocks.append(b)
        tail = tokens[n_full * bs:]
        if tail:
            b = self._tail.get(_block_digest(h, tail, tail=True).digest())
            if b is not None:
                blocks.append(b)
                return len(tokens), blocks
        return n_full * bs, blocks

    def register(self, tokens: Sequence[int], phys_blocks: Sequence[int]
                 ) -> int:
        """File every prompt block of a just-prefilled request.
        `phys_blocks` is the slot's logical->physical row (it may extend
        past the prompt into decode reservation — only prompt blocks are
        read). First registration wins: an already-claimed digest keeps
        its existing block (the new copy holds identical content) and
        counts one LINEAGE HIT. Returns the number of hits recorded."""
        bs = self.block_size
        n_full = len(tokens) // bs
        h = None
        hits = 0
        for i in range(n_full):
            h = _block_digest(h, tokens[i * bs:(i + 1) * bs])
            hits += self._claim("full", h.digest(), phys_blocks[i])
        tail = tokens[n_full * bs:]
        if tail:
            d = _block_digest(h, tail, tail=True).digest()
            hits += self._claim("tail", d, phys_blocks[n_full])
        self.lineage_hits_total += hits
        return hits

    def _claim(self, kind: str, digest: bytes, block: int) -> int:
        index = self._full if kind == "full" else self._tail
        if digest in index:
            # first registration wins — but the shadowed re-registration
            # IS the popularity signal (ISSUE 16 satellite): tally it
            hx = digest.hex()
            self._lineage_hits[hx] = self._lineage_hits.get(hx, 0) + 1
            return 1
        index[digest] = block
        self._claims.setdefault(block, []).append((kind, digest))
        return 0

    def lineage_hit_counts(self) -> Dict[str, int]:
        """Per-digest re-registration tallies (the popular-prefix signal
        an eviction policy can weight by)."""
        return dict(self._lineage_hits)

    def forget(self, block: int) -> None:
        """Invalidate every claim backed by `block` (called the moment the
        allocator frees it — a freed block's content is about to be
        overwritten by an unrelated request)."""
        for kind, digest in self._claims.pop(block, ()):
            index = self._full if kind == "full" else self._tail
            if index.get(digest) == block:
                del index[digest]

    def lineage(self, block: int) -> Optional[str]:
        """Hex digest of the prefix chain `block` serves (its FIRST claim
        — chain digests commit to the whole prefix, so the first claim is
        the canonical identity of the content the block holds), or None
        when the block backs no registry entry. Observability accessor
        (ISSUE 12): which sharing lineage a shared block belongs to."""
        claims = self._claims.get(block)
        if not claims:
            return None
        return claims[0][1].hex()

    @property
    def n_entries(self) -> int:
        return len(self._full) + len(self._tail)
