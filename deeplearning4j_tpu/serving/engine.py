"""Continuous-batching autoregressive serving engine.

Orca-style ITERATION-LEVEL scheduling over the slot-based KV cache
(serving/kv_cache.py): the unit of scheduling is one decode iteration, not a
static batch. Between iterations the engine (host side, no device sync
needed beyond the per-iteration active-mask read) admits queued requests
into free slots, retires finished ones, and frees their slots — so a long
generation never holds short requests hostage and new arrivals start
decoding on the very next iteration.

Hot-loop design (why this never retraces and rarely syncs):
- ONE jitted step function over fixed shapes (S slots, vocab V): embeds each
  slot's last token (on-device token feedback — sampled ids never round-trip
  through the host per token), runs StackDecoder's cached single-query
  attention, samples under a threaded PRNG key, scatters the new token into
  a device-side history buffer, and updates the active mask (EOS /
  max-token tests happen ON DEVICE).
- The host reads back only the small (S,) active mask each iteration (the
  minimum any continuous-batching scheduler needs to learn about
  completions) and a request's history row ONCE at completion.
- Prefill runs per admission via StackDecoder.prefill (power-of-two length
  buckets -> bounded trace count).

Per-request controls: max_new_tokens, temperature (0 = greedy), eos_id,
timeout_s (wall-clock, checked between iterations). Results are delivered
through the same observable-future shape as parallel/parallel_inference.py;
`ParallelInference(inference_mode=InferenceMode.GENERATE)` wraps this engine
behind the existing output()/output_async() API.
"""
from __future__ import annotations

import functools
import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.serving.decode import StackDecoder, one_hot_embedder
from deeplearning4j_tpu.serving.sampler import Sampler, sample_tokens


@dataclass
class Request:
    """One generation request (token ids in, token ids out)."""
    tokens: Sequence[int]
    max_new_tokens: int = 64
    temperature: float = 0.0
    eos_id: Optional[int] = None
    timeout_s: Optional[float] = None


@dataclass
class GenerationResult:
    tokens: List[int]                 # generated ids (prompt NOT included)
    finish_reason: str                # "length" | "eos" | "timeout" | "shutdown"
    prompt_len: int
    # per-generated-token (V,) logprob rows, only when the engine was built
    # with capture_logprobs=True (parity tests); row i conditions token i
    logprobs: Optional[List[np.ndarray]] = None


class _Future:
    """Observable-future result holder (same shape as
    parallel_inference._Observable)."""

    def __init__(self):
        self._event = threading.Event()
        self._value: Optional[GenerationResult] = None
        self._error: Optional[BaseException] = None

    def _set(self, value):
        self._value = value
        self._event.set()

    def _set_error(self, e: BaseException):
        self._error = e
        self._event.set()

    def get(self, timeout: Optional[float] = None) -> GenerationResult:
        if not self._event.wait(timeout):
            raise TimeoutError("generation result not ready")
        if self._error is not None:
            raise self._error
        return self._value


@dataclass
class _Active:
    """Host-side bookkeeping for a request occupying a slot."""
    req: Request
    fut: _Future
    slot: int
    n_generated: int                  # includes the prefill-sampled token
    deadline: Optional[float]
    logprobs: Optional[List[np.ndarray]] = None


def _build_step(decoder: StackDecoder, embed: Callable, top_k: int,
                cap: int):
    """The single jitted decode iteration (see module docstring)."""

    def step(params, cache_state, hist, last, plens, eos, maxgen, active,
             key, temps):
        x = embed(last)                                      # (S, n_in)
        cache_state, lp = decoder._decode_fn(params, cache_state, x, active)
        toks = sample_tokens(key, lp, temps, top_k)
        gen_idx = cache_state["lengths"] - plens             # post-advance
        gi = jnp.clip(gen_idx, 0, cap - 1)
        s = jnp.arange(hist.shape[0])
        hist = hist.at[s, gi].set(jnp.where(active, toks, hist[s, gi]))
        last = jnp.where(active, toks, last)
        new_active = active & (toks != eos) & (gen_idx + 1 < maxgen)
        return cache_state, hist, last, new_active, lp

    return jax.jit(step)


class ServingEngine:
    """Continuous-batching generation over a StackDecoder.

    Drive it either synchronously (`generate`, or `submit` + `step` in a
    loop — deterministic, what the tests use) or via the background thread
    (`start`, then `submit` from any thread; `shutdown` to stop)."""

    def __init__(self, net, max_seqs: int, max_len: int, *, dtype=None,
                 seed: int = 0, top_k: int = 0,
                 max_new_tokens_cap: int = 512,
                 embed: Optional[Callable] = None,
                 capture_logprobs: bool = False):
        self.decoder = StackDecoder(net, max_seqs, max_len, dtype=dtype)
        if embed is None:
            if self.decoder.n_in is None:
                raise ValueError("stack has no n_in; pass embed=")
            embed = one_hot_embedder(self.decoder.n_in, self.decoder.dtype)
        self.embed = embed
        self.sampler = Sampler(seed, top_k)
        self.capture_logprobs = bool(capture_logprobs)
        self._cap = int(max_new_tokens_cap)
        S = self.decoder.cache.max_seqs
        self._step_jit = _build_step(self.decoder, embed, self.sampler.top_k,
                                     self._cap)
        # device-side per-slot state (fixed shapes, threaded through the jit)
        self._hist = jnp.zeros((S, self._cap), jnp.int32)
        self._last = jnp.zeros((S,), jnp.int32)
        self._plens = jnp.zeros((S,), jnp.int32)
        self._eos = jnp.full((S,), -1, jnp.int32)
        self._maxgen = jnp.ones((S,), jnp.int32)
        # host-side
        self._active_mask = np.zeros((S,), bool)
        self._temps = np.zeros((S,), np.float32)
        self._by_slot: Dict[int, _Active] = {}
        self._queue: List[_Active] = []
        self._lock = threading.RLock()
        self._work = threading.Condition(self._lock)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # ------------------------------------------------------------- submit
    def submit(self, request) -> _Future:
        """Queue a request; returns a future resolving to GenerationResult."""
        req = request if isinstance(request, Request) else Request(request)
        plen = len(req.tokens)
        if plen < 1 or plen >= self.decoder.cache.max_len:
            raise ValueError(f"prompt length {plen} outside [1, max_len)")
        if not 1 <= req.max_new_tokens <= self._cap:
            raise ValueError(f"max_new_tokens {req.max_new_tokens} outside "
                             f"[1, {self._cap}] (max_new_tokens_cap)")
        if plen + req.max_new_tokens > self.decoder.cache.max_len:
            raise ValueError(
                f"prompt ({plen}) + max_new_tokens ({req.max_new_tokens}) "
                f"exceeds cache max_len {self.decoder.cache.max_len}")
        fut = _Future()
        deadline = None if req.timeout_s is None else \
            time.monotonic() + req.timeout_s
        with self._work:
            if self._stop.is_set():
                raise RuntimeError("engine is shut down")
            self._queue.append(_Active(req, fut, -1, 0, deadline))
            self._work.notify()
        return fut

    # ---------------------------------------------------------- iteration
    def _admit(self) -> None:
        """Move queued requests into free cache slots (prefill + first
        token). Called with the lock held."""
        cache = self.decoder.cache
        while self._queue and cache.n_free > 0:
            act = self._queue.pop(0)
            if act.deadline is not None and time.monotonic() > act.deadline:
                act.fut._set(GenerationResult([], "timeout",
                                              len(act.req.tokens)))
                continue
            slot = cache.allocate(act)
            act.slot = slot
            req = act.req
            toks = np.asarray(req.tokens, np.int32)
            feats = np.asarray(self.embed(jnp.asarray(toks))).T  # (n_in, T)
            lp = self.decoder.prefill(slot, feats)
            t0 = sample_tokens(self.sampler.next_key(), lp[None],
                               jnp.full((1,), req.temperature, jnp.float32),
                               self.sampler.top_k)[0]
            act.n_generated = 1
            if self.capture_logprobs:
                act.logprobs = [np.asarray(lp)]
            self._hist = self._hist.at[slot, 0].set(t0)
            self._last = self._last.at[slot].set(t0)
            self._plens = self._plens.at[slot].set(len(req.tokens))
            self._eos = self._eos.at[slot].set(
                -1 if req.eos_id is None else int(req.eos_id))
            self._maxgen = self._maxgen.at[slot].set(int(req.max_new_tokens))
            self._temps[slot] = req.temperature
            self._active_mask[slot] = True
            self._by_slot[slot] = act
            # single-token request: finished at admission
            if req.max_new_tokens == 1 or (req.eos_id is not None
                                           and int(t0) == req.eos_id):
                self._active_mask[slot] = False
                self._retire(slot, "shutdown")  # reason fixed inside

    def _retire(self, slot: int, default_reason: str) -> None:
        """Resolve the request in `slot` and free it. Lock held."""
        act = self._by_slot.pop(slot)
        n = act.n_generated
        row = np.asarray(self._hist[slot])[:n].tolist()
        req = act.req
        if req.eos_id is not None and n and row[-1] == req.eos_id:
            reason = "eos"
        elif n >= req.max_new_tokens:
            reason = "length"
        else:
            reason = default_reason
        lps = act.logprobs[:n] if act.logprobs is not None else None
        self.decoder.cache.free(slot)
        act.fut._set(GenerationResult(row, reason, len(req.tokens), lps))

    def step(self) -> bool:
        """One scheduler iteration: admit, decode one token for every active
        slot, retire completions/timeouts. Returns True while any request is
        active or queued."""
        with self._lock:
            self._admit()
            if not self._by_slot:
                return bool(self._queue)
            # expire timed-out requests before spending device time on them
            now = time.monotonic()
            for slot, act in list(self._by_slot.items()):
                if act.deadline is not None and now > act.deadline:
                    self._active_mask[slot] = False
                    self._retire(slot, "timeout")
            if not self._by_slot:
                return bool(self._queue)
            active = jnp.asarray(self._active_mask)
            (self.decoder.cache.state, self._hist, self._last, new_active,
             lp) = self._step_jit(
                self.decoder.params, self.decoder.cache.state, self._hist,
                self._last, self._plens, self._eos, self._maxgen, active,
                self.sampler.next_key(), jnp.asarray(self._temps))
            new_np = np.asarray(new_active)        # the per-iteration sync
            if self.capture_logprobs:
                lp_np = np.asarray(lp)
            for slot, act in list(self._by_slot.items()):
                if not self._active_mask[slot]:
                    continue
                act.n_generated += 1
                if self.capture_logprobs:
                    act.logprobs.append(lp_np[slot])
                if not new_np[slot]:
                    self._active_mask[slot] = False
                    self._retire(slot, "length")
            self._active_mask &= new_np
            return bool(self._by_slot or self._queue)

    def drain(self) -> None:
        """Run iterations until no active or queued work remains."""
        while self.step():
            pass

    def generate(self, prompts, **kw) -> List[GenerationResult]:
        """Synchronous convenience: submit every prompt (a Request or a
        token-id sequence; **kw applies to bare sequences), drain, return
        results in submission order."""
        futs = [self.submit(p if isinstance(p, Request) else Request(p, **kw))
                for p in prompts]
        self.drain()
        return [f.get(timeout=0) for f in futs]

    # --------------------------------------------------- background thread
    def start(self) -> "ServingEngine":
        if self._thread is not None:
            return self
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()
        return self

    def _loop(self):
        while not self._stop.is_set():
            with self._work:
                while not (self._queue or self._by_slot
                           or self._stop.is_set()):
                    self._work.wait(timeout=0.1)
                if self._stop.is_set():
                    break
            self.step()
        # graceful drain: finish in-flight work unless told to abandon it
        if self._drain_on_stop:
            self.drain()

    def shutdown(self, wait: bool = True) -> None:
        """Stop the background loop. wait=True finishes in-flight requests
        first; wait=False resolves them with finish_reason='shutdown'."""
        self._drain_on_stop = wait
        self._stop.set()
        with self._work:
            self._work.notify_all()
        if self._thread is not None:
            self._thread.join()
            self._thread = None
        with self._lock:
            if not wait:
                for slot in list(self._by_slot):
                    self._active_mask[slot] = False
                    self._retire(slot, "shutdown")
                for act in self._queue:
                    act.fut._set(GenerationResult([], "shutdown",
                                                  len(act.req.tokens)))
                self._queue.clear()
            elif self._by_slot or self._queue:
                self.drain()

    _drain_on_stop = True
