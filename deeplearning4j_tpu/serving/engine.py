"""Continuous-batching autoregressive serving engine.

Orca-style ITERATION-LEVEL scheduling over the PAGED KV cache
(serving/kv_cache.py): the unit of scheduling is one decode iteration, not a
static batch. Between iterations the engine (host side) admits queued
requests, retires finished ones, and frees their reservations — so a long
generation never holds short requests hostage and new arrivals start
decoding on the very next scheduling opportunity. Admission is BLOCK
allocation (PagedAttention-style, ISSUE 7): a request reserves
ceil((prompt + max_new_tokens) / block_size) fixed-size KV blocks instead
of a whole max_len row, and prefix sharing maps leading prompt blocks onto
already-resident KV (copy-on-write), skipping the shared positions' KV
bytes and prefill compute. Constructor knobs `kv_block` / `kv_blocks` /
`prefix_share` (env: DL4J_TPU_KV_BLOCK, DL4J_TPU_PREFIX_SHARE).

Hot-loop design (why this never retraces and rarely syncs):
- ONE jitted step function over fixed shapes (S slots, vocab V): embeds each
  slot's last token (on-device token feedback — sampled ids never round-trip
  through the host per token), runs StackDecoder's cached single-query
  attention, samples under a threaded PRNG key, scatters the new token into
  a device-side history buffer, and updates the active mask (EOS /
  max-token tests happen ON DEVICE).
- CHUNKED decode (Orca needs a sync per scheduling OPPORTUNITY, not per
  token): `decode_chunk` = K micro-steps run as one `lax.scan` inside one
  dispatch, so the host reads back one small mask bundle per K tokens
  instead of per token — syncs/token = 1/K. Finished slots ride out at most
  K-1 masked micro-steps (their cache/history writes are invisible under
  the lengths-visibility invariant). K adapts DOWN: to 1 whenever the
  admission queue is non-empty (time-to-first-token stays bounded by one
  iteration, the Orca property), and to a power-of-two bucket of the
  largest remaining token budget (bounded trace count, no over-run waste
  at the tail). K=1 takes the original single-step function — bit-for-bit
  the pre-chunking behavior.
- Sampler keys are threaded so chunking never changes tokens: the host
  PEEKS K subkeys from the PRNG chain for a chunk (micro-step i uses
  exactly the key the i-th sequential step would have), then COMMITS only
  the number of micro-steps that ran with any active slot — so K in
  {2,4,8} is token-for-token identical to K=1 even when EOS lands
  mid-chunk (sampler.Sampler.peek_keys/advance).
- OVERLAPPED scheduling (`overlap=True`, the drain/background path):
  chunk i+1 is dispatched BEFORE chunk i's masks are materialized — the
  device-side active mask threads chunk-to-chunk without a host round-trip
  (JAX async dispatch), and the host consumes a one-chunk-stale mask for
  bookkeeping. Stale scheduling is safe: a finished slot decodes at most
  one extra chunk with active=False (all writes invisible), and host
  events (admissions, timeouts) patch the device mask functionally.
  Overlap consumes keys unconditionally (no rewind — the strict cross-K
  key schedule is a synchronous-step guarantee), so it is used only when
  token-level capture is off.
- Prefill runs per admission via StackDecoder.prefill (power-of-two length
  buckets -> bounded trace count).
- CHUNKED prefill (Sarathi-Serve-style, ISSUE 9): a long prompt no longer
  runs its whole prefill in one dispatch — that stalls every resident
  decode stream for the duration, the main TPOT-tail pathology the
  adaptive K->1 policy does not cover. When a prompt's unshared suffix
  exceeds `prefill_chunk` tokens (env `DL4J_TPU_PREFILL_CHUNK`, default
  256, 0 disables; rounded to KV-block granularity), admission only
  reserves its blocks; the prefill itself becomes a queue of fixed-budget
  chunks, AT MOST ONE of which runs per scheduler iteration, interleaved
  with the resident slots' decode chunks. A partially-prefilled sequence
  holds its reservation, writes each chunk's K/V through the block table,
  and later chunks attend its own earlier blocks via the same gather as
  prefix-shared prefill (`_prefill_shared_fn` with chunk start/end in the
  shared_len/plen seats — one jit, one compile cache, pow2/block-granular
  buckets). Prefix-shared admissions chunk only their unshared suffix.
  The first token samples after the final chunk, so chunking consumes the
  admission PRNG key later in the chain than monolithic prefill would —
  greedy decoding is token-identical either way (the parity tests), and
  steady-state counted host syncs are bit-identical chunked on or off.

- SPECULATIVE decoding (ISSUE 11, `spec_decode=True` / env
  DL4J_TPU_SPEC_DECODE=1): draft-model-free prompt-lookup speculation.
  A host-side per-slot n-gram index (serving/spec.py) proposes up to
  `spec_draft` continuation tokens from the request's own prompt +
  generated history; ONE widened decode dispatch verifies all of them
  (multi-query paged flash attention), and the accepted prefix commits via
  a single lengths move — rejected KV stays invisible under the
  lengths-visibility invariant (block-granular rollback, copy-on-reject
  for COW-shared tail blocks). Greedy spec output is token-identical to
  plain decode (the point-mass accept rule samples each row from the
  TARGET distribution), still one counted sync per iteration, and 1..K+1
  tokens committed per sync. Spec replaces chunking and forces
  synchronous stepping (the draft index needs the committed token values
  the readback already carries).

Per-request controls: max_new_tokens, temperature (0 = greedy), eos_id,
timeout_s (wall-clock, checked between iterations). Results carry cheap
host-timestamp stats (ttft_s, tokens_per_sec) and are delivered through the
same observable-future shape as parallel/parallel_inference.py;
`ParallelInference(inference_mode=InferenceMode.GENERATE)` wraps this engine
behind the existing output()/output_async() API. Engine-wide counters
(`stats()`): host_syncs, tokens_out — bench.py publishes
host_syncs_per_token from their ratio.
"""
from __future__ import annotations

import functools
import itertools
import os
import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu import telemetry
from deeplearning4j_tpu.telemetry import memory as _tmemory
from deeplearning4j_tpu.telemetry import profiler as _profiler
from deeplearning4j_tpu.serving import kv_cache as _kvc
from deeplearning4j_tpu.serving import spec as spec_mod
from deeplearning4j_tpu.serving.block_table import chain_digests
from deeplearning4j_tpu.serving.decode import StackDecoder, one_hot_embedder
from deeplearning4j_tpu.serving.lifecycle import (resolve_lifecycle,
                                                  resolve_prefix_store)
from deeplearning4j_tpu.serving.policy import (ColocatedPolicy,
                                               resolve_radix_ttl)
from deeplearning4j_tpu.serving.sampler import (Sampler, sample_tokens,
                                                spec_accept_tokens)

# per-iteration prefill token budget (chunked prefill, ISSUE 9); env
# DL4J_TPU_PREFILL_CHUNK overrides, 0 disables chunking entirely
DEFAULT_PREFILL_CHUNK = 256

#: Scheduler-iteration ids, unique ACROSS engines in the process: chunk
#: timeline events carry one so the blame ledger (telemetry/blame.py)
#: only pairs requests that truly shared an iteration — fleet-level
#: ledgers never build interference edges across replicas.
_ITER_IDS = itertools.count(1)


@dataclass
class Request:
    """One generation request (token ids in, token ids out)."""
    tokens: Sequence[int]
    max_new_tokens: int = 64
    temperature: float = 0.0
    eos_id: Optional[int] = None
    timeout_s: Optional[float] = None
    # multi-turn attribution (ISSUE 16 satellite): opaque session handle +
    # turn index stamped by the caller (loadgen session driver); threaded
    # onto the GenerationResult / timeline so per-session latency is
    # joinable in the blame ledger. Never read by the scheduler.
    session_id: Optional[str] = None
    turn_idx: Optional[int] = None


@dataclass
class GenerationResult:
    tokens: List[int]                 # generated ids (prompt NOT included)
    finish_reason: str                # "length" | "eos" | "timeout" | "shutdown"
    prompt_len: int
    # per-generated-token (V,) logprob rows, only when the engine was built
    # with capture_logprobs=True (parity tests); row i conditions token i
    logprobs: Optional[List[np.ndarray]] = None
    # cheap host-timestamp stats (no extra device syncs): submit -> first
    # token, and generated tokens (after the first) / decode span
    ttft_s: Optional[float] = None
    tokens_per_sec: Optional[float] = None
    # lifecycle observability (ISSUE 8): engine-assigned request id (the
    # same id the Tracer spans carry as `req=`), submit -> admission-start
    # queue wait (separated from TTFT, which also spans prefill), and the
    # number of admission attempts that failed for lack of a slot / KV
    # blocks before this request got in
    req_id: int = -1
    queue_wait_s: Optional[float] = None
    admission_retries: int = 0
    # per-request lifecycle timeline: ordered event dicts {"phase", "t0",
    # "t1", ...extras} on the host monotonic clock ("queue" -> "admission"
    # -> zero or more "prefill_chunk" spans when chunked prefill split the
    # prompt (chunk index, tokens, shared-skip) -> "prefill" -> one
    # "decode_chunk" per scheduler iteration the slot entered -> "retire").
    # Built from timestamps the scheduler already takes — recording it
    # adds zero device syncs. flight_recorder.py turns retained timelines
    # into a Perfetto trace.
    timeline: List[dict] = field(default_factory=list)
    # per-request KV-byte attribution (ISSUE 12), stamped at retirement
    # from block-table bookkeeping (no device reads): the block-granular
    # reservation held, the positions actually written, and the shared-
    # prefix positions served from another request's resident blocks.
    # Under tensor parallelism these are per-device bytes, matching the
    # serving.kv_bytes_* gauges.
    kv_bytes_reserved: int = 0
    kv_bytes_live: int = 0
    kv_bytes_shared_prefix: int = 0
    # prompt positions served from resident shared-prefix blocks at the
    # LAST admission (ISSUE 16): the token-level view of
    # kv_bytes_shared_prefix, what the radix A/B bench sums per turn
    shared_prefix_tokens: int = 0
    # multi-turn attribution (ISSUE 16 satellite): copied from the Request
    session_id: Optional[str] = None
    turn_idx: Optional[int] = None

    def timeline_phases(self) -> Dict[str, float]:
        """Total seconds per phase (post-hoc latency decomposition)."""
        out: Dict[str, float] = {}
        for ev in self.timeline:
            out[ev["phase"]] = out.get(ev["phase"], 0.0) + \
                (ev["t1"] - ev["t0"])
        return out


class _Future:
    """Observable-future result holder (same shape as
    parallel_inference._Observable)."""

    def __init__(self):
        self._event = threading.Event()
        self._value: Optional[GenerationResult] = None
        self._error: Optional[BaseException] = None

    def _set(self, value):
        self._value = value
        self._event.set()

    def _set_error(self, e: BaseException):
        self._error = e
        self._event.set()

    def get(self, timeout: Optional[float] = None) -> GenerationResult:
        if not self._event.wait(timeout):
            raise TimeoutError("generation result not ready")
        if self._error is not None:
            raise self._error
        return self._value


@dataclass
class _Active:
    """Host-side bookkeeping for a request occupying a slot."""
    req: Request
    fut: _Future
    slot: int
    n_generated: int                  # includes the prefill-sampled token
    deadline: Optional[float]
    logprobs: Optional[List[np.ndarray]] = None
    t_submit: float = 0.0
    t_first: float = 0.0              # first token materialized (admission)
    req_id: int = -1                  # engine-assigned lifecycle id (ISSUE 8)
    retries: int = 0                  # failed block-reservation attempts
    t_admit: float = 0.0              # admission (block plan) succeeded
    timeline: List[dict] = field(default_factory=list)
    # chunked prefill (ISSUE 9): prompt positions [0, prefilled) are
    # KV-resident (== shared_len right after admission, == plen once the
    # prefill — monolithic or final chunk — completes)
    prefilled: int = 0
    shared_len: int = 0
    n_chunks: int = 0                 # prefill chunks executed so far
    # KV lifecycle (ISSUE 13): set while the request sits requeued after
    # a preemption — {"mode": "recompute"|"swap", "tokens": generated-so-
    # far ids, "t_requeue": monotonic, and for swap the stashed block
    # count/live length}. Cleared when the resume completes.
    resume: Optional[dict] = None
    preemptions: int = 0              # times this request was evicted
    # first-rejection forensics held until the "queue" timeline event
    # exists, so the Perfetto instant lands INSIDE the queue span and
    # timeline[0] stays "queue"
    kv_rejection: Optional[dict] = None


def _build_step(decoder: StackDecoder, embed: Callable, top_k: int,
                cap: int):
    """The single decode iteration (the K=1 path — kept verbatim so
    decode_chunk=1 preserves the pre-chunking behavior bit-for-bit).
    Returns the RAW pure function; the engine jits it via `_jit_decode`
    (the seam where the sharded engine pins pjit in/out shardings)."""

    def step(params, cache_state, hist, last, plens, eos, maxgen, active,
             key, temps):
        x = embed(last)                                      # (S, n_in)
        cache_state, lp = decoder._decode_fn(params, cache_state, x, active)
        toks = sample_tokens(key, lp, temps, top_k)
        gen_idx = cache_state["lengths"] - plens             # post-advance
        gi = jnp.clip(gen_idx, 0, cap - 1)
        s = jnp.arange(hist.shape[0])
        hist = hist.at[s, gi].set(jnp.where(active, toks, hist[s, gi]))
        last = jnp.where(active, toks, last)
        new_active = active & (toks != eos) & (gen_idx + 1 < maxgen)
        # nonfinite-logits sentinel (ISSUE 5): scalar OR over active rows,
        # computed on device and read back WITH the mask (no extra sync)
        nf = jnp.any(active & jnp.any(~jnp.isfinite(lp), axis=-1))
        return cache_state, hist, last, new_active, lp, nf

    return step


def _build_chunk(decoder: StackDecoder, embed: Callable, top_k: int,
                 cap: int):
    """K micro-steps as ONE dispatch: `lax.scan` over a (K, ...) stack of
    per-micro-step PRNG keys. Each micro-step is exactly the K=1 step body;
    the scan additionally stacks each micro-step's ENTRY active mask (the
    host learns per-slot token counts and the effective step count from one
    (K, S) readback) and the (K, S, V) logprob rows (materialized only under
    capture_logprobs). Finished slots run masked: their sampled tokens are
    discarded by the same `where(active, ...)` writes as the K=1 path, and
    their cache appends land at a stale, never-visible position."""

    def chunk(params, cache_state, hist, last, plens, eos, maxgen, active,
              keys, temps):
        def micro(carry, key):
            cache_state, hist, last, active, nf = carry
            x = embed(last)                                  # (S, n_in)
            cache_state, lp = decoder._decode_fn(params, cache_state, x,
                                                 active)
            toks = sample_tokens(key, lp, temps, top_k)
            gen_idx = cache_state["lengths"] - plens         # post-advance
            gi = jnp.clip(gen_idx, 0, cap - 1)
            s = jnp.arange(hist.shape[0])
            hist = hist.at[s, gi].set(jnp.where(active, toks, hist[s, gi]))
            new_last = jnp.where(active, toks, last)
            new_active = active & (toks != eos) & (gen_idx + 1 < maxgen)
            # nonfinite-logits sentinel (ISSUE 5): OR-reduced across the
            # chunk's micro-steps, masked to rows that entered active
            nf = nf | jnp.any(active & jnp.any(~jnp.isfinite(lp), axis=-1))
            return ((cache_state, hist, new_last, new_active, nf),
                    (active, lp))

        (cache_state, hist, last, active, nf), (entries, lps) = jax.lax.scan(
            micro, (cache_state, hist, last, active, jnp.zeros((), bool)),
            keys)
        return cache_state, hist, last, active, entries, lps, nf

    return chunk


def _build_spec_step(decoder: StackDecoder, embed: Callable, top_k: int,
                     cap: int):
    """One SPECULATIVE decode iteration (ISSUE 11) as a single dispatch:
    verify [last, draft_0..draft_{Q-2}] at Q consecutive positions per slot
    (multi-query paged attention, StackDecoder._spec_decode_fn), accept via
    the point-mass rejection-sampling collapse (sampler.spec_accept_tokens
    — committed tokens are bit-identical to plain K=1 stepping on the same
    key chain), then COMMIT exactly the accepted prefix. The lengths update
    below is the WHOLE rollback story: rejected rows' KV sits at positions
    >= the new `lengths` and is invisible forever under the
    lengths-visibility invariant (the next iteration simply overwrites
    those offsets). `keys` is (Q, ...) PEEKED chain subkeys (key i = chain
    position i); `draft` (S, Q-1) proposed token ids; `draft_len` (S,) how
    many leading draft rows are real per slot — 0 degrades the slot to a
    plain decode step with Q-1 dead verify lanes, committing exactly one
    token."""

    def spec_step(params, cache_state, hist, last, plens, eos, maxgen,
                  active, keys, temps, draft, draft_len):
        S, Dm = draft.shape
        Q = Dm + 1
        toks_in = jnp.concatenate([last[:, None], draft], axis=1)  # (S, Q)
        x = jax.vmap(embed, in_axes=1, out_axes=1)(toks_in)  # (S, Q, n_in)
        pos = cache_state["lengths"]                         # pre-commit
        cache_state, lp = decoder._spec_decode_fn(params, cache_state, x,
                                                  active, draft_len)
        toks, n_accept, n_commit = spec_accept_tokens(
            keys, lp, draft, draft_len, temps, top_k)        # (S,Q),(S,),(S,)
        i = jnp.arange(Q, dtype=jnp.int32)[None, :]
        gen0 = pos - plens + 1      # generation index of the row-0 token
        # EOS inside the accepted prefix truncates the commit to include it
        com = i < n_commit[:, None]
        eos_hit = com & (toks == eos[:, None])
        has_eos = jnp.any(eos_hit, axis=1)
        first_eos = jnp.argmax(eos_hit, axis=1).astype(jnp.int32)
        c_eff = jnp.where(has_eos, first_eos + 1, n_commit)
        # never commit past max_new_tokens (the host caps draft_len to the
        # remaining budget, so this is a backstop), and inactive slots
        # commit nothing at all
        c_eff = jnp.minimum(c_eff, jnp.maximum(maxgen - gen0, 1))
        c_eff = jnp.where(active, c_eff, 0).astype(jnp.int32)
        # the ONLY lengths move — spec rollback is this set-length commit
        cache_state = {**cache_state,
                       "lengths": (pos + c_eff).astype(jnp.int32)}
        # history: committed offset j lands at column gen0 + j (mask-based
        # update, not a scatter — dead lanes can't clobber a kept column)
        col = jnp.arange(hist.shape[1], dtype=jnp.int32)[None, :]
        j = col - gen0[:, None]                              # (S, cap)
        sel = active[:, None] & (j >= 0) & (j < c_eff[:, None])
        vals = jnp.take_along_axis(toks, jnp.clip(j, 0, Q - 1), axis=1)
        hist = jnp.where(sel, vals, hist)
        last_c = jnp.take_along_axis(
            toks, jnp.clip(c_eff - 1, 0, Q - 1)[:, None], axis=1)[:, 0]
        last = jnp.where(active, last_c, last)
        new_active = active & (last_c != eos) & (gen0 + c_eff < maxgen)
        # nonfinite-logits sentinel (ISSUE 5): only rows that fed the
        # accept/commit decision count (lanes past draft_len are dead)
        row_ok = i <= draft_len[:, None]
        nf = jnp.any(active[:, None] & row_ok
                     & jnp.any(~jnp.isfinite(lp), axis=-1))
        return (cache_state, hist, last, new_active, toks, c_eff,
                n_accept, lp, nf)

    return spec_step


class ServingEngine:
    """Continuous-batching generation over a StackDecoder.

    Drive it either synchronously (`generate`, or `submit` + `step` in a
    loop — deterministic, what the tests use) or via the background thread
    (`start`, then `submit` from any thread; `shutdown` to stop).

    `decode_chunk` (default 8; env `DL4J_TPU_DECODE_CHUNK`) sets the number
    of decode micro-steps per host scheduling opportunity — syncs/token =
    1/K, with K adapting to 1 whenever requests are queued. `overlap`
    (default True) lets `drain`/`generate` dispatch the next chunk before
    reading the previous chunk's mask, hiding host scheduling under device
    compute (disabled automatically under capture_logprobs).

    `prefill_chunk` (default 256; env `DL4J_TPU_PREFILL_CHUNK`; 0 disables)
    is the per-iteration prefill token budget: an admitted prompt whose
    unshared suffix exceeds it is prefilled one bounded chunk per scheduler
    iteration, interleaved with resident decode, instead of in one
    decode-stalling dispatch (Sarathi-style; see the module docstring).
    The budget rounds to KV-block granularity so chunk shapes bucket to
    the same bounded compile-key set as prefix-shared prefill."""

    def __init__(self, net, max_seqs: int, max_len: int, *, dtype=None,
                 seed: int = 0, top_k: int = 0,
                 max_new_tokens_cap: int = 512,
                 embed: Optional[Callable] = None,
                 capture_logprobs: bool = False,
                 decode_chunk: Optional[int] = None,
                 overlap: bool = True,
                 prefill_chunk: Optional[int] = None,
                 kv_block: Optional[int] = None,
                 kv_blocks: Optional[int] = None,
                 prefix_share: Optional[bool] = None,
                 flight_recorder=None,
                 prefix_registry=None,
                 metrics_parent=None,
                 spec_decode: Optional[bool] = None,
                 spec_draft: Optional[int] = None,
                 kv_observatory=None,
                 kv_evict=None,
                 kv_swap_bytes: Optional[int] = None,
                 kv_evict_mode: str = "auto",
                 kv_disk=None,
                 kv_disk_bytes: Optional[int] = None,
                 kv_swap_async: Optional[bool] = None,
                 prefix_store=None,
                 kv_quant: Optional[bool] = None,
                 quant_weights: Optional[bool] = None,
                 prefix_radix: Optional[bool] = None,
                 policy=None,
                 radix_ttl: Optional[int] = None,
                 timeseries=None,
                 ts_window: Optional[int] = None,
                 alerts=None,
                 journal=None,
                 name: Optional[str] = None):
        self.decoder = self._build_decoder(net, max_seqs, max_len,
                                           dtype=dtype,
                                           block_size=kv_block,
                                           num_blocks=kv_blocks,
                                           prefix_share=prefix_share,
                                           prefix_registry=prefix_registry,
                                           kv_quant=kv_quant,
                                           quant_weights=quant_weights,
                                           prefix_radix=prefix_radix)
        if embed is None:
            if self.decoder.n_in is None:
                raise ValueError("stack has no n_in; pass embed=")
            embed = one_hot_embedder(self.decoder.n_in, self.decoder.dtype)
        self.embed = embed
        self.sampler = Sampler(seed, top_k)
        self.capture_logprobs = bool(capture_logprobs)
        self._cap = int(max_new_tokens_cap)
        if decode_chunk is None:
            decode_chunk = int(os.environ.get("DL4J_TPU_DECODE_CHUNK", "8"))
        if decode_chunk < 1:
            raise ValueError(f"decode_chunk must be >= 1, got {decode_chunk}")
        self.decode_chunk = int(decode_chunk)
        self.overlap = bool(overlap)
        if prefill_chunk is None:
            prefill_chunk = int(os.environ.get(
                "DL4J_TPU_PREFILL_CHUNK", str(DEFAULT_PREFILL_CHUNK)))
        if prefill_chunk < 0:
            raise ValueError(
                f"prefill_chunk must be >= 0 (0 disables), got "
                f"{prefill_chunk}")
        bs_kv = self.decoder.cache.block_size
        if prefill_chunk:
            # block-granular budget: chunk boundaries land on block edges
            # (aside from a shared-prefix offset), so chunk shapes bucket
            # to the same pow2 set as prefix-shared suffixes
            prefill_chunk = max(bs_kv, (prefill_chunk // bs_kv) * bs_kv)
        self.prefill_chunk = int(prefill_chunk)
        S = self.decoder.cache.max_seqs
        self._step_jit = self._jit_decode(
            _build_step(self.decoder, embed, self.sampler.top_k, self._cap),
            "step")
        self._chunk_jit = self._jit_decode(
            _build_chunk(self.decoder, embed, self.sampler.top_k, self._cap),
            "chunk")
        # speculative decoding (ISSUE 11): draft-free n-gram drafts verified
        # in one widened decode dispatch. Spec mode replaces chunking (a
        # spec step IS one scheduling opportunity committing 1..Q tokens)
        # and forces synchronous stepping — the accept decision needs the
        # committed token VALUES host-side anyway (they feed the draft
        # index), riding the per-iteration readback at zero extra syncs.
        self.spec_decode = spec_mod.resolve_spec_decode(spec_decode)
        self.spec_draft = spec_mod.resolve_spec_draft(spec_draft)
        self._spec_index = (spec_mod.NgramDraftIndex()
                            if self.spec_decode else None)
        if self.spec_decode:
            self._spec_jit = self._jit_decode(
                _build_spec_step(self.decoder, embed, self.sampler.top_k,
                                 self._cap),
                "spec")
        # device-side per-slot state (fixed shapes, threaded through the jit)
        self._hist = jnp.zeros((S, self._cap), jnp.int32)
        self._last = jnp.zeros((S,), jnp.int32)
        self._plens = jnp.zeros((S,), jnp.int32)
        self._eos = jnp.full((S,), -1, jnp.int32)
        self._maxgen = jnp.ones((S,), jnp.int32)
        # device-side active mask — only threaded while the overlapped drain
        # pipeline is live (None = synchronous mode, host mask authoritative)
        self._dev_active: Optional[jnp.ndarray] = None
        # host-side
        self._active_mask = np.zeros((S,), bool)
        self._temps = np.zeros((S,), np.float32)
        self._by_slot: Dict[int, _Active] = {}
        self._queue: List[_Active] = []
        # admitted-but-partially-prefilled requests, FIFO; the head gets at
        # most one chunk per scheduler iteration (also in _by_slot, with
        # _active_mask False until the final chunk samples the first token)
        self._prefilling: List[_Active] = []
        self._lock = threading.RLock()
        self._work = threading.Condition(self._lock)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # telemetry (ISSUE 4): a per-engine child registry — visible from
        # the process-wide /metrics exposition, isolated for stats()/tests.
        # Every metric below is fed from values the scheduler already holds
        # on the host (counters, materialized masks, host timestamps):
        # recording adds ZERO device syncs, so counts are bit-identical with
        # telemetry on or off (tests/test_telemetry.py asserts this). The
        # sync counters themselves live here too: every materialization of
        # device data in the serve loop counts as one sync — per-chunk mask
        # reads AND per-admission first-token reads (scheduling events).
        self.metrics = telemetry.MetricsRegistry(
            parent=metrics_parent if metrics_parent is not None
            else telemetry.registry())
        self._c_syncs = self.metrics.counter(
            "serving.host_syncs", "device->host materializations in the "
            "serve loop")
        self._c_tokens = self.metrics.counter(
            "serving.tokens_out", "generated tokens delivered")
        self._c_admits = self.metrics.counter(
            "serving.admissions", "requests admitted into slots")
        self._c_retires = self.metrics.counter(
            "serving.retirements", "requests retired")
        self._c_timeouts = self.metrics.counter(
            "serving.timeouts", "requests expired before completion")
        self._c_nonfinite = self.metrics.counter(
            "serving.nonfinite_chunks", "decode chunks whose logits held "
            "nonfinite values in an active row (sentinel rides the existing "
            "mask readback — zero added syncs)")
        self._c_compiles = self.metrics.counter(
            "serving.jit_compiles", "first-use compiled shapes (prefill "
            "buckets + chunk scan lengths)")
        self._c_prefix_hits = self.metrics.counter(
            "serving.prefix_hits", "admissions that mapped shared prefix "
            "KV blocks (paged cache, ISSUE 7)")
        self._c_prefix_tokens = self.metrics.counter(
            "serving.prefix_shared_tokens", "prompt positions whose KV "
            "bytes AND prefill compute were skipped via prefix sharing")
        self._c_lineage_hits = self.metrics.counter(
            "serving.kv.prefix_lineage_hits", "prefix re-registrations "
            "that landed on an already-claimed digest (first registration "
            "wins; the shadowed re-file is the popular-prefix signal the "
            "eviction policy reads, ISSUE 16)")
        self._h_ttft = self.metrics.histogram(
            "serving.ttft_s", "submit -> first token (s)",
            buckets=telemetry.DEFAULT_S_BUCKETS)
        self._h_queue_wait = self.metrics.histogram(
            "serving.queue_wait_s", "submit -> admission start (s): the "
            "queueing component that TTFT conflates with prefill (ISSUE 8)",
            buckets=telemetry.DEFAULT_S_BUCKETS)
        self._c_adm_retries = self.metrics.counter(
            "serving.admission_retries", "scheduler iterations the head-of-"
            "queue request waited because its block reservation failed")
        self._h_tps = self.metrics.histogram(
            "serving.tokens_per_sec", "per-request decode throughput",
            buckets=(1, 5, 10, 25, 50, 100, 250, 500, 1000, 2500, 5000,
                     10000, 50000))
        self._h_chunk_k = self.metrics.histogram(
            "serving.chunk_k", "adaptive chunk size chosen per iteration",
            buckets=(1, 2, 4, 8, 16, 32, 64))
        self._h_chunk_ms = self.metrics.histogram(
            "serving.decode_chunk_ms", "dispatch+readback wall per chunk")
        self._c_pf_chunks = self.metrics.counter(
            "serving.prefill_chunks", "interleaved prefill chunks executed "
            "(chunked prefill, ISSUE 9; monolithic prefills count zero)")
        self._h_pf_chunk_tokens = self.metrics.histogram(
            "serving.prefill_chunk_tokens", "prompt tokens per interleaved "
            "prefill chunk",
            buckets=(16, 32, 64, 128, 256, 512, 1024, 2048))
        self._h_stall = self.metrics.histogram(
            "serving.decode_stall_ms", "prefill wall (whole prompt, or one "
            "chunk under chunked prefill) spent while decode-active slots "
            "sat waiting — the stall chunking bounds")
        self._c_spec_acc = self.metrics.counter(
            "serving.spec_tokens_accepted", "draft tokens accepted by "
            "speculative verification (ISSUE 11)")
        self._c_spec_rej = self.metrics.counter(
            "serving.spec_tokens_rejected", "draft tokens rejected by "
            "speculative verification")
        self._h_spec_accept = self.metrics.histogram(
            "serving.spec_accept_rate", "per-slot accepted/drafted ratio "
            "per spec step (steps that proposed at least one draft)",
            buckets=(0.01, 0.125, 0.25, 0.5, 0.75, 0.9, 1.0))
        self._h_spec_draft = self.metrics.histogram(
            "serving.spec_draft_len", "draft tokens proposed per slot per "
            "spec step (zero-draft slots run as plain decode rows)",
            buckets=(1, 2, 4, 8, 16))
        self._g_queue = self.metrics.gauge(
            "serving.queue_depth", "requests waiting for a slot")
        self._g_occ = self.metrics.gauge(
            "serving.slot_occupancy", "slots holding an active request")
        self._seen_shapes: set = set()   # jit cache-miss attribution
        # HBM accounting (ISSUE 6): param and KV-cache bytes are geometry
        # the host already knows; residency is updated only at scheduling
        # events (admit/retire/chunk bookkeeping) from host counters —
        # never a device read. memory.poll() runs at phase boundaries only
        # (construction here, end of drain).
        cache = self.decoder.cache
        self.decoder.metrics = self.metrics   # prefill cost gauges land on
        # the same child registry as the engine's observe() gauges
        self._kv_bytes_per_pos = cache.bytes_per_position
        # quantized pool (ISSUE 15): fp32 scale bytes per block, counted
        # in swap/prefix-store accounting next to the int8 payload
        self._kv_block_overhead = cache.block_overhead_bytes
        self._g_kv_total = self.metrics.gauge(
            "serving.kv_cache_bytes", "preallocated KV cache footprint")
        self._g_kv_total.set(cache.bytes())
        self._g_kv_res = self.metrics.gauge(
            "serving.kv_bytes_resident", "KV bytes holding live "
            "prompt+generated positions across active slots")
        self._g_kv_waste = self.metrics.gauge(
            "serving.kv_bytes_waste", "reserved-but-unused KV bytes "
            "(block-granular reservations minus live positions)")
        self._g_blocks_free = self.metrics.gauge(
            "serving.kv_blocks_free", "physical KV blocks on the free list")
        self._g_blocks_free.set(cache.blocks_free)
        self._g_blocks_shared = self.metrics.gauge(
            "serving.kv_blocks_shared", "physical KV blocks mapped by 2+ "
            "slots (prefix sharing)")
        self._resident_seqs_max = 0   # high-water mark of concurrent slots
        self._g_params = self.metrics.gauge(
            "serving.param_bytes", "decoder parameter bytes")
        self._g_params.set(_tmemory.param_bytes(self.decoder.params))
        # lifecycle ids + tail-latency flight recorder (ISSUE 8): the
        # recorder retains full timelines for SLO-violating / worst-TTFT
        # requests only, fed at retirement from host bookkeeping the
        # scheduler already holds — zero added device syncs (parity-tested).
        # Enable by passing flight_recorder= or via DL4J_TPU_FLIGHT_RECORDER.
        self._next_req_id = 0
        # blame/observability identity (ISSUE 14): `name` labels flight-
        # recorder records and tracer tracks (a ShardedServingGroup passes
        # "replica<r>"); `_snapshot_seq` is a lock-held iteration counter
        # exposed via stats() so scrapers can detect stale/torn snapshots;
        # `_iter_id` stamps chunk events with a process-globally unique
        # scheduler-iteration id for cross-request interference matching.
        self.name = name
        self.replica_id: Optional[int] = None
        self._snapshot_seq = 0
        self._iter_id = 0
        if flight_recorder is None:
            fr = os.environ.get("DL4J_TPU_FLIGHT_RECORDER", "")
            if fr and fr != "0":
                from deeplearning4j_tpu.telemetry.flight_recorder import \
                    FlightRecorder
                flight_recorder = FlightRecorder()
        self.flight_recorder = flight_recorder
        # KV-pressure observatory (ISSUE 12): serving.kv.* heat/attribution
        # gauges, admission-rejection forensics, eviction dry-run scoring.
        # Pass kv_observatory=True (or a KVObservatory instance) or set
        # DL4J_TPU_KV_OBS=1. Host-side only — it consumes pool snapshots
        # and the scheduler's own live-position bookkeeping, so enabling
        # it cannot change the counted sync sequence (bit-parity-tested).
        if kv_observatory is None:
            kv_observatory = os.environ.get("DL4J_TPU_KV_OBS", "") \
                not in ("", "0")
        # recompute cost unit for the eviction scorers: ~2*params FLOPs
        # per token (param counts are host shape metadata, no device read)
        n_params = sum(int(np.size(x)) for x in
                       jax.tree_util.tree_leaves(self.decoder.params))
        if isinstance(kv_observatory, bool):
            obs = None
            if kv_observatory:
                from deeplearning4j_tpu.telemetry.kv_observatory import \
                    KVObservatory
                obs = KVObservatory(self.metrics,
                                    flops_per_token=2.0 * n_params)
        else:
            obs = kv_observatory
        self.kv_observatory = obs
        # KV lifecycle manager (ISSUE 13): REAL eviction/preemption when
        # admission fails under block pressure, selecting victims with
        # the same plan_eviction the observatory's dry-run forensics log.
        # Disabled by default (kv_evict=None and no DL4J_TPU_KV_EVICT):
        # disabled means NO manager and no code on any scheduler path, so
        # the no-pressure sync sequence is bit-identical (parity-tested).
        self.lifecycle = resolve_lifecycle(kv_evict, kv_swap_bytes,
                                           kv_evict_mode,
                                           flops_per_token=2.0 * n_params,
                                           kv_disk=kv_disk,
                                           kv_disk_bytes=kv_disk_bytes)
        # async swap-out (ISSUE 18): preemption dispatches the gather and
        # DEFERS the history readback + payload materialization to the
        # next chunk boundary (_harvest_swaps) instead of stalling the
        # scheduler mid-pressure. On by default; kv_swap_async=False (or
        # DL4J_TPU_KV_SWAP_ASYNC=0) restores the synchronous preempt —
        # the bench A/B baseline.
        if kv_swap_async is None:
            kv_swap_async = os.environ.get(
                "DL4J_TPU_KV_SWAP_ASYNC", "1") not in ("", "0")
        self.kv_swap_async = bool(kv_swap_async)
        # swap-preempted victims awaiting their chunk-boundary harvest:
        # not in _queue, not in _by_slot — limbo entries the harvest
        # requeues (records carry the pinned lazy hist reference)
        self._pending_swaps: List[dict] = []
        # persistent prefix store (ISSUE 13): content-addressed host KV
        # block bytes keyed by the registry's chain digests — survives
        # restarts (npz spill) and spans ShardedServingGroup replicas
        # (one instance handed to every engine).
        self.prefix_store = resolve_prefix_store(prefix_store)
        if self.prefix_store is not None:
            expect = (cache.n_layers, cache.block_size, cache.n_kv_heads,
                      cache.head_dim)
            expect_dt = str(cache.state["k"].dtype)
            if self.prefix_store.block_shape is None:
                self.prefix_store.block_shape = expect
            elif self.prefix_store.block_shape != expect:
                # a spill file from another model geometry: ignore it
                # rather than restore garbage bytes
                self.prefix_store = None
            if self.prefix_store is not None:
                # payload dtype must match too (ISSUE 15): an int8
                # quantized spill scattered into a float pool — or the
                # reverse — would be garbage even at matching geometry
                if self.prefix_store.block_dtype is None:
                    self.prefix_store.block_dtype = expect_dt
                elif self.prefix_store.block_dtype != expect_dt:
                    self.prefix_store = None
        if (self.prefix_store is not None and cache.prefix_radix
                and getattr(self.prefix_store, "evict_policy", None)
                is None):
            # ONE tree-wide LRU (ISSUE 16): the radix tree's heat decides
            # store eviction instead of the store's private byte-cap LRU —
            # orphan digests (no known lineage) go first, then the coldest
            # lineage. On a group-shared store the first radix replica to
            # construct wins the hook; digests that replica never saw
            # evict as orphans, which is the desired cold-first order.
            self.prefix_store.evict_policy = cache.registry.store_victim
        if self.prefix_store is not None \
                and getattr(self.prefix_store, "disk", None) is None:
            # hierarchical spill-through (ISSUE 18): the store's byte-cap
            # victims demote into the SAME disk tier the lifecycle
            # manager rebalances into, so one cap governs everything
            # below host RAM. A store-only engine (no lifecycle) still
            # gets a tier when the kv_disk knobs are set.
            if self.lifecycle is not None \
                    and self.lifecycle.disk_pool is not None:
                self.prefix_store.disk = self.lifecycle.disk_pool
            elif self.lifecycle is None:
                from deeplearning4j_tpu.serving.kv_disk import \
                    resolve_disk_pool
                self.prefix_store.disk = resolve_disk_pool(kv_disk,
                                                           kv_disk_bytes)
        # scheduling policy (ISSUE 17): ONE object consulted at every
        # scheduling decision point — admission (preempt vs deny-with-
        # hint), background eviction (radix TTL), and — on a group —
        # routing and prefill->decode transfer. A bare engine defaults
        # to ColocatedPolicy, the exact pre-ISSUE-17 inline behavior;
        # a ShardedServingGroup hands every engine ITS policy instance.
        self.policy = policy if policy is not None else ColocatedPolicy()
        self._radix_ttl = resolve_radix_ttl(radix_ttl)
        # disaggregation seams (ISSUE 17): role is a label the group
        # stamps ("prefill"/"decode"); _transfer_cb, when set, receives
        # each freshly-prefilled request so the group can ship its live
        # KV to a decode replica (ColocatedPolicy leaves both unset and
        # the hot path is unchanged).
        self.role = "colocated"
        self._transfer_cb: Optional[Callable] = None
        self._c_evict_rec = self.metrics.counter(
            "serving.kv.evictions_recompute", "preemptions reclaimed by "
            "freeing blocks and replaying prefill at readmission")
        self._c_evict_swap = self.metrics.counter(
            "serving.kv.evictions_swap", "preemptions reclaimed by "
            "migrating block bytes to the host pool")
        self._c_preempt = self.metrics.counter(
            "serving.kv.preemptions", "resident requests preempted for a "
            "rejected admission (recompute + swap)")
        self._c_swap_out = self.metrics.counter(
            "serving.kv.swap_out_bytes", "KV bytes migrated device->host "
            "at eviction")
        self._c_swap_in = self.metrics.counter(
            "serving.kv.swap_in_bytes", "KV bytes restored host->device "
            "at reactivation")
        self._g_host_pool = self.metrics.gauge(
            "serving.kv.host_pool_bytes", "host-RAM bytes currently held "
            "by swapped-out KV blocks")
        self._c_pstore_hits = self.metrics.counter(
            "serving.prefix_store_hits", "admissions that restored prefix "
            "blocks from the persistent store past the resident registry")
        self._c_pstore_tokens = self.metrics.counter(
            "serving.prefix_store_tokens", "prompt positions restored from "
            "the persistent prefix store (prefill compute skipped)")
        self._c_xfer_out = self.metrics.counter(
            "serving.kv.transfer_out", "finished prefills whose live KV "
            "left this replica for a decode replica (ISSUE 17)")
        self._c_xfer_in = self.metrics.counter(
            "serving.kv.transfer_in", "transferred requests whose live KV "
            "restored into this replica's pool for decode")
        self._c_xfer_bytes = self.metrics.counter(
            "serving.kv.transfer_bytes", "KV bytes migrated across "
            "replicas by prefill->decode disaggregation")
        self._c_ttl_expired = self.metrics.counter(
            "serving.kv.ttl_expired_blocks", "radix-retained prefix blocks "
            "released by the policy's TTL drain (ISSUE 17 satellite)")
        self._c_role_pf = self.metrics.counter(
            "serving.role_prefill_requests", "admissions served while this "
            "replica held the PREFILL role")
        self._c_role_dec = self.metrics.counter(
            "serving.role_decode_requests", "admissions served while this "
            "replica held the DECODE role (transferred continuations)")
        self._g_disk_pool = self.metrics.gauge(
            "serving.kv.disk_pool_bytes", "spill-directory bytes currently "
            "held by the disk tier (swap + prefix-store entries)")
        self._c_disk_demote = self.metrics.counter(
            "serving.kv.disk_demotions", "host-pool entries demoted to the "
            "disk tier under host-RAM pressure")
        self._c_disk_promote = self.metrics.counter(
            "serving.kv.disk_promotions", "disk-tier entries promoted back "
            "through host RAM at swap-in / prefix restore")
        self._c_swap_harvest = self.metrics.counter(
            "serving.kv.swap_async_harvests", "async swap-outs whose bytes "
            "were harvested at a later chunk boundary (deferred syncs)")
        self._c_swap_lost = self.metrics.counter(
            "serving.kv.swap_lost", "swap-preempted requests whose payload "
            "vanished (corrupt spill) and fell back to recompute")
        self._g_swap_gbps = self.metrics.gauge(
            "serving.kv.measured_swap_gbps", "measured device<->host swap "
            "bandwidth in GB/s (init calibration, then the running "
            "swap-in/harvest average)")
        if self.lifecycle is not None:
            # swap-bandwidth calibration (ISSUE 18 satellite): one tiny
            # warmup gather round-trip replaces DEFAULT_SWAP_BYTES_PER_SEC
            # in every recompute-vs-swap verdict with what THIS host
            # actually moves. Init is a phase boundary: the readback is
            # deliberately NOT counted in host_syncs so the no-pressure
            # serve loop stays bit-identical to a lifecycle-off engine.
            t_cal = time.perf_counter()
            _cal_k, _cal_v = _kvc.gather_blocks(cache.state, [0])
            # sync-ok: init-time bandwidth calibration (phase boundary)
            cal_bytes = np.asarray(_cal_k).nbytes + np.asarray(_cal_v).nbytes
            self.lifecycle.calibrate(cal_bytes,
                                     time.perf_counter() - t_cal)
            self._g_swap_gbps.set(self.lifecycle.calibrated_gbps)
        # windowed time-series + burn-rate alerts (ISSUE 19): one sample
        # per scheduler iteration over host-visible state only — counter
        # values, histogram ring quantiles, queue bookkeeping — so the
        # on-vs-off token/sync sequence is BIT-identical (parity-tested).
        # Disabled (the default) means None objects and zero code on any
        # scheduler path. Enable via timeseries=/alerts= or DL4J_TPU_TS /
        # DL4J_TPU_ALERTS; alerts imply the series they evaluate over.
        from deeplearning4j_tpu.telemetry import alerts as _alerts_mod
        from deeplearning4j_tpu.telemetry import timeseries as _ts_mod
        self.alerts = _alerts_mod.resolve_alerts(
            alerts, slo=getattr(self.policy, "slo", None),
            short_window=ts_window)
        if self.alerts is not None and timeseries is None:
            timeseries = True
        if isinstance(timeseries, bool) or timeseries is None:
            self.timeseries = _ts_mod.ServingTimeSeries(
                short_window=ts_window) \
                if _ts_mod.resolve_ts_enabled(timeseries) else None
        else:
            self.timeseries = timeseries
        # the budget `serving.slo_violations` counts against: the
        # monitor's, else the admission policy's, else the flight
        # recorder's — whichever budget this engine already knows
        self._slo_budget = None
        for src in (self.alerts, self.policy, self.flight_recorder):
            budget = getattr(src, "slo", None)
            if budget is not None:
                self._slo_budget = budget
                break
        self._c_slo_viol = self.metrics.counter(
            "serving.slo_violations", "retired requests that violated "
            "the configured SLO budget (counted host-side at retirement; "
            "0 when no budget is configured)")
        self._c_alerts = self.metrics.counter(
            "serving.alerts_total", "burn-rate monitor alerts emitted "
            "(ISSUE 19)")
        self._h_tpot = self.metrics.histogram(
            "serving.tpot_s", "decode time-per-output-token per retired "
            "request (latency minus TTFT over tokens after the first)",
            buckets=telemetry.DEFAULT_S_BUCKETS)
        self._ts_gauges: Dict[str, object] = {}
        self._ts_blame_gauges: Dict[str, object] = {}
        if self.timeseries is not None:
            for key in (self.timeseries.RATE_KEYS
                        + self.timeseries.LEVEL_KEYS
                        + ("tokens_per_s_long",)):
                self._ts_gauges[key] = self.metrics.gauge(
                    f"serving.ts.{key}", "windowed time-series reading "
                    "(ISSUE 19; short-window rates, rolling quantiles)")
        if self.alerts is not None:
            self._g_burn_short = self.metrics.gauge(
                "serving.alerts.burn_rate_short", "SLO burn rate over "
                "the short (page-worthy) window")
            self._g_burn_long = self.metrics.gauge(
                "serving.alerts.burn_rate_long", "SLO burn rate over "
                "the long (ticket-worthy) window")
            self._c_alert_kind = {
                kind: self.metrics.counter(
                    f"serving.alerts.{kind}",
                    f"'{kind}' alerts emitted by the burn-rate monitor")
                for kind in _alerts_mod.ALERT_KINDS}
        # scheduler decision journal (ISSUE 20): every nondeterministic
        # input and policy verdict as a typed record keyed to the
        # allocator tick clock, replayable via serving/replay.py. Same
        # contract as the layers above: off (the default) is None and
        # zero code on scheduler paths — journaling on-vs-off is
        # host-sync and token bit-parity. Enable via journal= or
        # DL4J_TPU_JOURNAL; DL4J_TPU_JOURNAL_BYTES caps retention.
        from deeplearning4j_tpu.telemetry import journal as _journal_mod
        self.journal = _journal_mod.resolve_journal(journal)
        self._incidents: List[str] = []   # frozen incident-bundle paths
        # replay director seam (serving/replay.py): when installed, the
        # wall-deadline shed/expire predicates and the measured-bandwidth
        # preempt-mode choice read journaled outcomes instead
        self._replay = None
        _tmemory.poll("serving.engine_init", registry=self.metrics)

    # ----------------------------------------------- sharding seams (ISSUE 10)
    def _build_decoder(self, net, max_seqs, max_len, **kw) -> StackDecoder:
        """Decoder construction seam: ShardedServingEngine
        (serving/sharding.py) overrides this to swap in head-sharded paged
        attention and place params/cache on its tensor-parallel mesh."""
        return StackDecoder(net, max_seqs, max_len, **kw)

    def _jit_decode(self, fn, kind: str):
        """Jit seam for the decode step ("step") / chunk ("chunk") pure
        functions: the sharded engine pins pjit in/out shardings here so
        the cache pytree stays head-sharded across dispatches."""
        return jax.jit(fn)

    # host_syncs / tokens_out live on the registry (ISSUE 4 satellite) but
    # stay assignable attributes for callers that reset them (bench.py)
    @property
    def host_syncs(self) -> int:
        return self._c_syncs.value

    @host_syncs.setter
    def host_syncs(self, v: int) -> None:
        self._c_syncs.reset(int(v))

    @property
    def tokens_out(self) -> int:
        return self._c_tokens.value

    @tokens_out.setter
    def tokens_out(self, v: int) -> None:
        self._c_tokens.reset(int(v))

    def stats(self) -> Dict[str, float]:
        """One consistent snapshot (taken under the scheduler lock) of the
        engine-lifetime perf counters plus the live queue/slot state
        (bench.py publishes the ratio as host_syncs_per_token)."""
        with self._lock:
            syncs, toks = self._c_syncs.value, self._c_tokens.value
            # one atomic pool snapshot (ISSUE 12 satellite) — the free /
            # shared / slot totals all describe the same instant, where
            # separate property reads could straddle an admission
            snap = self.decoder.cache.pool_snapshot(include_blocks=False)
            # windowed time-series summary + per-metric last-update
            # stamps (ISSUE 19): `ts` is None when the layer is off;
            # `metric_stamps` carries {name: {wall_s, iter}} for every
            # written metric (the snapshot-side `_last_update` sibling)
            ts_summary = (self.timeseries.summary()
                          if self.timeseries is not None else None)
            return {"host_syncs": syncs, "tokens_out": toks,
                    "slo_violations": self._c_slo_viol.value,
                    "alerts_total": self._c_alerts.value,
                    "ts": ts_summary,
                    "metric_stamps": self.metrics.stamps(),
                    "snapshot_seq": self._snapshot_seq,
                    "decode_chunk": self.decode_chunk,
                    "prefill_chunk": self.prefill_chunk,
                    "prefill_chunks": self._c_pf_chunks.value,
                    "host_syncs_per_token": syncs / max(1, toks),
                    "nonfinite_chunks": self._c_nonfinite.value,
                    "queue_depth": len(self._queue),
                    "free_slots": snap["slots_free"],
                    "active_slots": len(self._by_slot),
                    "kv_blocks_free": snap["blocks_free"],
                    "kv_blocks_shared": snap["blocks_shared"],
                    "kv_clock": snap["clock"],
                    "kv_rejections": (self.kv_observatory.n_rejections
                                      if self.kv_observatory is not None
                                      else 0),
                    "kv_bytes_waste": self._g_kv_waste.value,
                    "prefix_hits": self._c_prefix_hits.value,
                    "prefix_shared_tokens": self._c_prefix_tokens.value,
                    "prefix_lineage_hits": self._c_lineage_hits.value,
                    "kv_blocks_cached": snap.get("blocks_cached", 0),
                    "prefix_radix": int(self.decoder.cache.prefix_radix),
                    "admission_retries": self._c_adm_retries.value,
                    "resident_seqs_max": self._resident_seqs_max,
                    "spec_decode": int(self.spec_decode),
                    "spec_draft": self.spec_draft,
                    "spec_tokens_accepted": self._c_spec_acc.value,
                    "spec_tokens_rejected": self._c_spec_rej.value,
                    "spec_accept_rate": self._c_spec_acc.value / max(
                        1, self._c_spec_acc.value + self._c_spec_rej.value),
                    "kv_evictions_recompute": self._c_evict_rec.value,
                    "kv_evictions_swap": self._c_evict_swap.value,
                    "kv_preemptions": self._c_preempt.value,
                    "kv_swap_out_bytes": self._c_swap_out.value,
                    "kv_swap_in_bytes": self._c_swap_in.value,
                    "kv_host_pool_bytes": (
                        self.lifecycle.host_pool.bytes_used
                        if self.lifecycle is not None else 0),
                    "prefix_store_hits": self._c_pstore_hits.value,
                    "prefix_store_tokens": self._c_pstore_tokens.value,
                    "kv_transfer_out": self._c_xfer_out.value,
                    "kv_transfer_in": self._c_xfer_in.value,
                    "kv_transfer_bytes": self._c_xfer_bytes.value,
                    "role_prefill_requests": self._c_role_pf.value,
                    "role_decode_requests": self._c_role_dec.value,
                    "kv_disk_pool_bytes": (
                        self.lifecycle.disk_pool.bytes_used
                        if self.lifecycle is not None
                        and self.lifecycle.disk_pool is not None else 0),
                    "kv_disk_demotions": self._c_disk_demote.value,
                    "kv_disk_promotions": self._c_disk_promote.value,
                    "kv_swap_harvests": self._c_swap_harvest.value,
                    "kv_pending_swaps": len(self._pending_swaps),
                    "kv_swap_lost": self._c_swap_lost.value,
                    "kv_measured_swap_gbps": self._g_swap_gbps.value,
                    "journal": (self.journal.stats()
                                if self.journal is not None else None),
                    "incidents": list(self._incidents)}

    def kv_pool_snapshot(self, include_blocks: bool = True
                         ) -> Dict[str, object]:
        """Atomic KV pool snapshot (under the scheduler lock) with the
        per-slot LIVE write positions filled in, so callers can feed it
        straight to telemetry.kv_observatory.attribute_pool / dry_run.
        Host-side bookkeeping only — zero device reads."""
        with self._lock:
            return self.decoder.cache.pool_snapshot(
                live_positions=self._live_kv_positions(),
                include_blocks=include_blocks)

    def export_trace(self, path: str) -> str:
        """Write the global tracer's Chrome-trace JSON (prefill / decode
        chunk / host sync / compile spans) to `path`."""
        return telemetry.tracer().export(path)

    # ------------------------------------------------------------- submit
    def submit(self, request) -> _Future:
        """Queue a request; returns a future resolving to GenerationResult."""
        req = request if isinstance(request, Request) else Request(request)
        plen = len(req.tokens)
        if plen < 1 or plen >= self.decoder.cache.max_len:
            raise ValueError(f"prompt length {plen} outside [1, max_len)")
        if not 1 <= req.max_new_tokens <= self._cap:
            raise ValueError(f"max_new_tokens {req.max_new_tokens} outside "
                             f"[1, {self._cap}] (max_new_tokens_cap)")
        if plen + req.max_new_tokens > self.decoder.cache.max_len:
            raise ValueError(
                f"prompt ({plen}) + max_new_tokens ({req.max_new_tokens}) "
                f"exceeds cache max_len {self.decoder.cache.max_len}")
        fut = _Future()
        deadline = None if req.timeout_s is None else \
            time.monotonic() + req.timeout_s
        with self._work:
            if self._stop.is_set():
                raise RuntimeError("engine is shut down")
            self._next_req_id += 1
            act = _Active(req, fut, -1, 0, deadline,
                          t_submit=time.monotonic(),
                          req_id=self._next_req_id)
            self._queue.append(act)
            if self.journal is not None:
                # the one nondeterministic INPUT (everything else the
                # journal holds is a decision): token ids + knobs + the
                # submit tick, enough for replay to re-create the request
                self._jrec("arrival", req=act.req_id,
                           tokens=[int(t) for t in req.tokens],
                           max_new=int(req.max_new_tokens),
                           temp=req.temperature, eos=req.eos_id,
                           timeout_s=req.timeout_s,
                           session=req.session_id, turn=req.turn_idx)
            telemetry.instant("submit", req=act.req_id, plen=plen,
                              queued=len(self._queue))
            self._work.notify()
        return fut

    # ---------------------------------------------------------- iteration
    def _admit(self) -> None:
        """Move queued requests into cache slots (prefill + first token).
        Admission is BLOCK allocation (paged cache, ISSUE 7): the head
        request needs ceil((prompt + max_new) / block_size) blocks, with
        leading prompt blocks mapped onto already-resident shared-prefix KV
        when the registry matches — those positions skip prefill compute
        entirely (prefill_shared embeds and computes only the suffix). The
        head request is PEEKED, not popped, until its plan succeeds: when
        blocks run short we keep FIFO order and retry next iteration (a
        retirement frees blocks). Called with the lock held."""
        cache = self.decoder.cache
        evicted_for: set = set()       # one eviction round per request/call
        while self._queue:
            act = self._queue[0]
            # queue-shed deadline: one of the two wall-clock predicates in
            # the scheduler (the other is _expire_timeouts) — under replay
            # the director supplies the journaled outcome instead, which
            # is what removes the wall clock from the loop (ISSUE 20)
            timed_out = act.deadline is not None \
                and time.monotonic() > act.deadline
            if self._replay is not None:
                timed_out = self._replay.should_shed(
                    act.req_id, cache.allocator.clock)
            if timed_out:
                self._queue.pop(0)
                now = time.monotonic()
                jseq = self._jrec("shed", req=act.req_id,
                                  retries=act.retries)
                # a preempted request that times out while requeued still
                # returns the tokens it had generated before eviction
                toks_out = [int(t) for t in act.resume["tokens"]] \
                    if act.resume is not None else []
                # a requeued request's pre-preemption life is already
                # tiled up to t_requeue — starting this queue span at
                # t_submit would overlap it (ISSUE 14 satellite)
                t_q0 = act.resume["t_requeue"] if act.resume is not None \
                    else act.t_submit
                act.timeline.append({"phase": "queue", "t0": t_q0,
                                     "t1": now, "retries": act.retries})
                if act.kv_rejection is not None:
                    act.timeline.append(act.kv_rejection)
                    act.kv_rejection = None
                ev = {"phase": "retire", "t0": now, "t1": now,
                      "reason": "timeout", "tokens": len(toks_out)}
                if jseq is not None:
                    ev["journal_seq"] = jseq
                act.timeline.append(ev)
                res = GenerationResult(toks_out, "timeout",
                                       len(act.req.tokens),
                                       req_id=act.req_id,
                                       admission_retries=act.retries,
                                       timeline=act.timeline)
                act.fut._set(res)
                # a queue-shed request IS a retirement — and, under an SLO,
                # always a violation (it expired before its first token, so
                # the TTFT budget is blown by definition). Without these the
                # burn-rate monitor is blind to the canonical overload
                # signature: load shedding out of the queue (ISSUE 19)
                self._c_retires.inc()
                self._c_timeouts.inc()
                if self._slo_budget is not None:
                    self._c_slo_viol.inc()
                self._record_flight(res)
                if act.resume is not None and act.resume["mode"] == "swap" \
                        and self.lifecycle is not None:
                    # the timed-out victim's parked bytes can never be
                    # restored — forget them on every tier (they would
                    # otherwise leak host-pool / disk capacity forever)
                    self.lifecycle.drop(act.req_id)
                    self._g_host_pool.set(
                        self.lifecycle.host_pool.bytes_used)
                continue
            req = act.req
            plen = len(req.tokens)
            # admission/prefill sequence: the prompt, or — resuming a
            # preempted request — prompt + generated history minus the
            # last token (its KV is written by its own next decode step)
            pseq = self._admission_sequence(act)
            plen_eff = len(pseq)
            t_adm0 = time.monotonic()
            plan = cache.admit(act, n_positions=plen + req.max_new_tokens,
                               prompt=pseq)
            if plan is None:           # no slot / not enough blocks: wait
                # one retry per scheduler iteration the head request spends
                # blocked on its block reservation (ISSUE 8 satellite)
                act.retries += 1
                self._c_adm_retries.inc()
                if act.retries == 1:
                    bs = cache.block_size
                    needed = -(-(plen + req.max_new_tokens) // bs)
                    # the rejection as a Perfetto instant on the request's
                    # own track (ISSUE 13 satellite — the forensics ring
                    # alone is a host-side list): dur-0 timeline events
                    # render as "i" phases; held on the request until its
                    # "queue" event exists so it lands inside that span
                    act.kv_rejection = {
                        "phase": "kv_rejection", "t0": t_adm0, "t1": t_adm0,
                        "blocks_needed": needed,
                        "blocks_free": cache.blocks_free,
                        "shortfall": max(0, needed - cache.blocks_free)}
                if self.kv_observatory is not None and act.retries == 1:
                    # rejection forensics (ISSUE 12), first rejection per
                    # request only (a head-of-queue request blocked for N
                    # iterations is one record, not N). blocks_needed is
                    # the full reservation — the upper bound admission
                    # would shrink via prefix sharing.
                    bs = cache.block_size
                    self.kv_observatory.on_rejection(
                        cache.pool_snapshot(
                            live_positions=self._live_kv_positions()),
                        req_id=act.req_id, prompt_len=plen,
                        max_new_tokens=req.max_new_tokens,
                        blocks_needed=-(-(plen + req.max_new_tokens) // bs),
                        queue_depth=len(self._queue), retries=act.retries)
                # scheduling-policy consult (ISSUE 17): REAL eviction
                # (ISSUE 13) moved behind the policy's `admit` decision
                # point — ColocatedPolicy preserves the plan-then-preempt
                # behavior exactly (and, with an `slo`, holds preemption
                # back while the admittee still has TTFT slack). At most
                # one preemption round per request per _admit call
                # (victims requeue at the back, so the retried admission
                # holds its reservation and the loop always terminates).
                if act.req_id not in evicted_for:
                    decision = self.policy.admit(
                        act.req, self._admission_view(act, t_adm0))
                    if self.journal is not None:
                        # the verdict with enough of the eviction plan to
                        # re-execute it: ReplayPolicy (serving/replay.py)
                        # replays these instead of consulting heuristics
                        vs = [{"slot": v["slot"], "req_id": v.get("req_id"),
                               "blocks_total": v.get("blocks_total"),
                               "blocks_freed": v.get("blocks_freed")}
                              for v in (decision.victims or
                                        {}).get("evicted", ())]
                        hint = decision.hint or {}
                        self._jrec("admission", req=act.req_id,
                                   verdict=decision.kind, victims=vs,
                                   reclaimable_bytes=hint.get(
                                       "reclaimable_bytes", 0),
                                   retry_after_s=hint.get(
                                       "retry_after_s", 0.0))
                    if decision.kind == "preempt" \
                            and self._execute_evictions(decision.victims):
                        evicted_for.add(act.req_id)
                        continue
                    if decision.hint and act.kv_rejection is not None:
                        # deny-with-hint forensics ride the rejection
                        # instant: what a reclaim round could free, and
                        # the backoff after which preemption would fire
                        act.kv_rejection.setdefault(
                            "hint_reclaimable_bytes",
                            decision.hint.get("reclaimable_bytes", 0))
                        act.kv_rejection["hint_retry_after_s"] = \
                            decision.hint.get("retry_after_s", 0.0)
                break
            self._queue.pop(0)
            slot = plan.slot
            act.slot = slot
            if act.resume is None:
                self._h_queue_wait.observe(t_adm0 - act.t_submit)
            act.t_admit = t_adm0
            t_q0 = act.resume["t_requeue"] if act.resume is not None \
                else act.t_submit
            act.timeline.append({"phase": "queue", "t0": t_q0,
                                 "t1": t_adm0, "retries": act.retries})
            if act.kv_rejection is not None:
                act.timeline.append(act.kv_rejection)
                act.kv_rejection = None
            shared = plan.shared_len
            act.prefilled = act.shared_len = shared
            if shared:
                self._c_prefix_hits.inc()
                self._c_prefix_tokens.inc(shared)
            # decode-side slot state is prefill-order independent — install
            # it at admission for both the monolithic and chunked paths
            # (the slot stays decode-inactive until the first token exists).
            # _plens stays the ORIGINAL prompt length even on resume: the
            # decode step derives history columns from lengths - plens, and
            # a resumed slot's lengths already account the regenerated part
            self._plens = self._plens.at[slot].set(plen)
            self._eos = self._eos.at[slot].set(
                -1 if req.eos_id is None else int(req.eos_id))
            self._maxgen = self._maxgen.at[slot].set(int(req.max_new_tokens))
            self._temps[slot] = req.temperature
            self._by_slot[slot] = act
            self._resident_seqs_max = max(self._resident_seqs_max,
                                          len(self._by_slot))
            self._c_admits.inc()
            if self.role == "prefill":
                self._c_role_pf.inc()
            elif self.role == "decode":
                self._c_role_dec.inc()
            telemetry.instant("admit", req=act.req_id, slot=slot, plen=plen,
                              retries=act.retries, queued=len(self._queue))
            jseq_admit = self._jrec("admit", req=act.req_id, slot=slot,
                                    blocks=plan.n_blocks, shared=shared,
                                    retries=act.retries,
                                    resume=(act.resume["mode"]
                                            if act.resume is not None
                                            else None))
            if act.resume is not None and act.resume["mode"] == "swap" \
                    and not self.lifecycle.has_swap(act.req_id):
                # lost spill (e.g. a disk entry that rotted after the
                # demotion): fall back to recompute-resume — re-prefill
                # over prompt + history costs compute, never tokens
                self._c_swap_lost.inc()
                act.resume["mode"] = "recompute"
            if act.resume is not None and act.resume["mode"] == "swap":
                # swap reactivation: restore block bytes, no prefill at all
                self._resume_swap(act, plan, t_adm0)
                continue
            if act.resume is not None and act.resume["mode"] == "transfer":
                # disaggregated continuation (ISSUE 17): this replica is
                # the DECODE side — scatter the transferred live KV into
                # the fresh reservation, no prefill at all
                self._resume_transfer(act, plan, t_adm0)
                continue
            if self.prefix_store is not None and act.resume is None:
                # persistent prefix store (ISSUE 13): restore stored blocks
                # that extend the resident registry's coverage, so only the
                # remaining suffix pays prefill compute
                shared = self._restore_from_store(act, plan, shared)
            if self.prefill_chunk and plen_eff - shared > self.prefill_chunk:
                # chunked prefill (ISSUE 9): the reservation is held but
                # the prompt pass is deferred — one bounded chunk per
                # scheduler iteration (_prefill_step) interleaved with
                # resident decode, instead of one decode-stalling dispatch
                ev = {"phase": "admission", "t0": t_adm0,
                      "t1": time.monotonic(), "slot": slot,
                      "blocks": plan.n_blocks, "shared": shared,
                      "iter": self._iter_id}
                if jseq_admit is not None:
                    ev["journal_seq"] = jseq_admit
                act.timeline.append(ev)
                self._prefilling.append(act)
                self._update_kv_resident()
                continue
            toks = np.asarray(pseq, np.int32)  # sync-ok: host list
            # compile attribution: each prefill jit retraces once per
            # power-of-two bucket — first sighting is a cache miss. The
            # shared path buckets on (suffix length, gathered blocks).
            if shared:
                skey = self.decoder.shared_buckets(plen_eff, shared)
                bucket = skey[0]
                miss = ("prefill_shared", skey) not in self._seen_shapes
                if miss:
                    self._seen_shapes.add(("prefill_shared", skey))
            else:
                bucket = self.decoder.prefill_bucket(plen_eff)
                miss = ("prefill", bucket) not in self._seen_shapes
                if miss:
                    self._seen_shapes.add(("prefill", bucket))
            if miss:
                self._c_compiles.inc()
            cm = telemetry.span("jit_compile", kind="prefill",
                                bucket=bucket) if miss else telemetry.NULL_SPAN
            t_pf = time.perf_counter()
            t_pf_mono = time.monotonic()
            ev_adm = {"phase": "admission", "t0": t_adm0,
                      "t1": t_pf_mono, "slot": slot,
                      "blocks": plan.n_blocks, "shared": shared,
                      "iter": self._iter_id}
            if jseq_admit is not None:
                ev_adm["journal_seq"] = jseq_admit
            act.timeline.append(ev_adm)
            had_active = bool(self._active_mask.any())
            with cm, telemetry.span("prefill", req=act.req_id, slot=slot,
                                    plen=plen_eff, bucket=bucket,
                                    shared=shared):
                if shared:
                    # suffix tokens only: the shared prefix's embedding +
                    # projection + score math never runs
                    # sync-ok: admission prefill input prep (scheduling event)
                    feats = np.asarray(
                        self.embed(jnp.asarray(toks[shared:]))).T
                    lp = self.decoder.prefill_shared(slot, feats, plen_eff,
                                                     shared)
                else:
                    # sync-ok: admission prefill input prep (scheduling event)
                    feats = np.asarray(self.embed(jnp.asarray(toks))).T
                    lp = self.decoder.prefill(slot, feats)
            if had_active:
                # a monolithic prefill ran while decode-active slots sat
                # waiting — the full-prompt stall chunked prefill bounds
                self._h_stall.observe((time.perf_counter() - t_pf) * 1e3)
            # heat stamp the positions this dispatch wrote (shared-prefix
            # blocks were stamped by their incref at admission)
            cache.touch_blocks(slot, shared, plen_eff)
            name = f"prefill_shared_b{skey[0]}k{skey[1]}" if shared \
                else f"prefill_b{bucket}"
            extras = {"plen": plen_eff, "bucket": bucket, "shared": shared,
                      "iter": self._iter_id}
            if miss:
                extras["compile"] = True   # blame: whole span is jit_compile
            self._finish_first_token(act, lp, t_pf, t_pf_mono, extras,
                                     prof_name=name)

    def _finish_first_token(self, act: _Active, lp, t_pf: float,
                            t_pf_mono: float, extras: dict,
                            prof_name: Optional[str] = None) -> None:
        """Prefill completed for `act` (monolithic, or the final chunk):
        register the now-resident prompt with the prefix registry, sample
        the first token, activate the slot's decode state, and stamp the
        "prefill" timeline event [t_pf_mono, first-token readback]. The
        single counted admission readback (first token) lives here. A
        recompute RESUME (preempted request re-prefilled over prompt +
        generated history) instead restores the stashed decode state and
        samples nothing — the resumed tokens were already sampled and
        counted before preemption, so no sampler key and no counted sync
        are consumed. Lock held."""
        req, slot = act.req, act.slot
        seq = self._admission_sequence(act)
        hits = self.decoder.cache.register_prefix(slot, seq)
        if hits:
            self._c_lineage_hits.inc(hits)
        self._offer_prefix_store(act, seq)
        self._publish_heat(seq)
        if act.resume is not None:
            self._finish_resume(act, t_pf_mono, extras)
            return
        t0 = sample_tokens(self.sampler.next_key(), lp[None],
                           jnp.full((1,), req.temperature, jnp.float32),
                           self.sampler.top_k)[0]
        act.n_generated = 1
        act.prefilled = len(req.tokens)
        if self.capture_logprobs:
            act.logprobs = [np.asarray(lp)]  # sync-ok: capture_logprobs mode
        self._hist = self._hist.at[slot, 0].set(t0)
        self._last = self._last.at[slot].set(t0)
        self._active_mask[slot] = True
        if self._dev_active is not None:
            self._dev_active = self._dev_active.at[slot].set(True)
        with telemetry.span("host_sync", what="first_token", slot=slot):
            first = int(t0)        # admission readback (scheduling event)
        self._c_syncs.inc()
        self._c_tokens.inc()
        if self._spec_index is not None:
            # seed the draft index: prompt + the first token are both
            # host-visible right here — no added device reads
            self._spec_index.reset(slot, req.tokens)
            self._spec_index.extend(slot, [first])
        act.t_first = time.monotonic()
        act.timeline.append({"phase": "prefill", "t0": t_pf_mono,
                             "t1": act.t_first, **extras})
        if prof_name is not None and _profiler.enabled():
            # the admission's device work (prefill dispatch + first
            # sample + the counted readback), from the host wall the
            # scheduler already measures — no added sync
            _profiler.observe(prof_name, (time.perf_counter() - t_pf) * 1e3,
                              registry=self.metrics)
        self._update_kv_resident()
        self._h_ttft.observe(act.t_first - act.t_submit)
        # single-token request: finished at first token
        if req.max_new_tokens == 1 or (req.eos_id is not None
                                       and first == req.eos_id):
            self._active_mask[slot] = False
            if self._dev_active is not None:
                self._dev_active = self._dev_active.at[slot].set(False)
            self._retire(slot, "shutdown")  # reason fixed inside
            return
        if self._transfer_cb is not None:
            # disaggregated prefill (ISSUE 17): this replica only
            # prefills — ship the live KV + first token to the decode
            # replica the policy picks (the group's callback)
            self._transfer_out(slot, act, first)

    def _prefill_step(self) -> None:
        """Run AT MOST ONE prefill chunk per scheduler iteration (the head
        of the partially-prefilled FIFO): embed prompt positions
        [prefilled, prefilled + budget), run the shared-prefix pass with
        chunk start/end in the shared_len/plen seats — the chunk scatters
        its K/V through the block table and attends the slot's own earlier
        blocks via the same gather as prefix-shared prefill — then advance
        the resident mark. The final chunk samples the first token and
        activates the slot for decode. The chunk's timeline event tiles
        from the request's previous event, so partially-prefilled requests
        keep gap-free coverage while they wait their turn behind other
        prefills. Lock held."""
        if not self._prefilling:
            return
        act = self._prefilling[0]
        req, slot = act.req, act.slot
        seq = self._admission_sequence(act)   # prompt (+ resumed history)
        plen = len(seq)
        start = act.prefilled
        end = min(plen, start + self.prefill_chunk)
        skey = self.decoder.shared_buckets(end, start)
        miss = ("prefill_shared", skey) not in self._seen_shapes
        if miss:
            self._seen_shapes.add(("prefill_shared", skey))
            self._c_compiles.inc()
        cm = telemetry.span("jit_compile", kind="prefill",
                            bucket=skey[0]) if miss else telemetry.NULL_SPAN
        had_active = bool(self._active_mask.any())
        t0_mono = act.timeline[-1]["t1"]   # tile: gap-free while waiting
        t_pf = time.perf_counter()
        toks = np.asarray(seq[start:end], np.int32)  # sync-ok: host list
        with cm, telemetry.span("prefill_chunk", req=act.req_id, slot=slot,
                                chunk=act.n_chunks, start=start,
                                tokens=end - start):
            # sync-ok: prefill-chunk input prep (scheduling event)
            feats = np.asarray(self.embed(jnp.asarray(toks))).T
            lp = self.decoder.prefill_chunk(slot, feats, start, end)
        wall_ms = (time.perf_counter() - t_pf) * 1e3
        if had_active:
            # decode-active slots waited on this chunk's dispatch — the
            # bounded stall that replaces the whole-prompt one
            self._h_stall.observe(wall_ms)
        now = time.monotonic()
        ev = {"phase": "prefill_chunk", "t0": t0_mono,
              "t1": now, "chunk": act.n_chunks,
              "tokens": end - start,
              "shared": act.shared_len if act.n_chunks == 0 else 0,
              "iter": self._iter_id, "wall_s": wall_ms / 1e3}
        if miss:
            ev["compile"] = True
        jseq = self._jrec("pf_chunk", req=act.req_id, slot=slot,
                          chunk=act.n_chunks, start=start, end=end)
        if jseq is not None:
            ev["journal_seq"] = jseq
        act.timeline.append(ev)
        act.n_chunks += 1
        act.prefilled = end
        # heat stamp exactly this chunk's positions — earlier chunks were
        # stamped in their own iterations, so block heat tracks when each
        # block was actually written, not when the prefill finished
        self.decoder.cache.touch_blocks(slot, start, end)
        self._c_pf_chunks.inc()
        self._h_pf_chunk_tokens.observe(end - start)
        if _profiler.enabled():
            _profiler.observe(f"prefill_shared_b{skey[0]}k{skey[1]}",
                              wall_ms, registry=self.metrics)
        if end >= plen:
            self._prefilling.pop(0)
            self._finish_first_token(
                act, lp, t_pf, now,
                {"plen": plen, "chunks": act.n_chunks,
                 "shared": act.shared_len, "bucket": skey[0],
                 "iter": self._iter_id})
        self._update_kv_resident()

    def _retire(self, slot: int, default_reason: str, hist=None) -> None:
        """Resolve the request in `slot` and free it. Lock held. `hist`
        overrides the history source (the overlapped pipeline reads a
        finished slot's row from the chunk that finished it, so the read
        does not block on the chunk already in flight)."""
        act = self._by_slot.pop(slot)
        if act in self._prefilling:    # timeout/shutdown mid-prefill
            self._prefilling.remove(act)
        if self._spec_index is not None:
            self._spec_index.drop(slot)
        t_ret0 = time.monotonic()
        n = act.n_generated
        src = self._hist if hist is None else hist
        row = np.asarray(src[slot])[:n].tolist()  # sync-ok: retirement readback
        req = act.req
        if req.eos_id is not None and n and row[-1] == req.eos_id:
            reason = "eos"
        elif n >= req.max_new_tokens:
            reason = "length"
        else:
            reason = default_reason
        lps = act.logprobs[:n] if act.logprobs is not None else None
        # KV-byte attribution (ISSUE 12), taken BEFORE the free while the
        # reservation still exists: reserved = block-granular hold, live =
        # positions actually written (device lengths), shared = prefix
        # positions served from another request's blocks
        kv_reserved = self.decoder.cache.reserved_positions(slot) * \
            self._kv_bytes_per_pos
        kv_live = (act.prefilled + max(0, n - 1)) * self._kv_bytes_per_pos
        kv_shared = act.shared_len * self._kv_bytes_per_pos
        self.decoder.cache.free(slot)
        now = time.monotonic()
        ttft = act.t_first - act.t_submit if act.t_first else None
        span = now - act.t_first if act.t_first else 0.0
        total = now - act.t_submit if act.t_submit else 0.0
        if n > 1 and span > 0:
            tps = (n - 1) / span       # decode-span rate (post-first-token)
        elif n >= 1 and total > 0:
            # 1-token generations (and sub-resolution decode spans) fall
            # back to tokens / whole-request wall — never None for a
            # request that produced output (ISSUE 4 satellite)
            tps = n / total
        else:
            tps = None
        # a span, not an instant: covers the history-row readback + block
        # free, so timeline coverage stays gap-free through retirement
        ret_ev = {"phase": "retire", "t0": t_ret0, "t1": now,
                  "reason": reason, "tokens": n,
                  "kv_bytes_reserved": kv_reserved,
                  "kv_bytes_live": kv_live,
                  "kv_bytes_shared": kv_shared}
        if req.session_id is not None:
            # session join key (ISSUE 16 satellite): lets the blame ledger
            # and flight recorder group turns of one conversation
            ret_ev["session_id"] = req.session_id
            ret_ev["turn_idx"] = req.turn_idx
        act.timeline.append(ret_ev)
        qw = act.t_admit - act.t_submit if act.t_admit else None
        res = GenerationResult(row, reason, len(req.tokens), lps,
                               ttft_s=ttft, tokens_per_sec=tps,
                               req_id=act.req_id, queue_wait_s=qw,
                               admission_retries=act.retries,
                               timeline=act.timeline,
                               kv_bytes_reserved=kv_reserved,
                               kv_bytes_live=kv_live,
                               kv_bytes_shared_prefix=kv_shared,
                               shared_prefix_tokens=act.shared_len,
                               session_id=req.session_id,
                               turn_idx=req.turn_idx)
        act.fut._set(res)
        self._c_retires.inc()
        if tps is not None:
            self._h_tps.observe(tps)
        # TPOT + SLO verdict (ISSUE 19): host arithmetic over timestamps
        # already taken — the burn-rate monitor's violation feed. The
        # verdict mirrors telemetry.slo.request_attains: completed
        # normally, TTFT within budget, decode TPOT within budget.
        tpot = span / (n - 1) if n > 1 and span > 0 else None
        if tpot is not None:
            self._h_tpot.observe(tpot)
        budget = self._slo_budget
        if budget is not None:
            attained = (reason in ("eos", "length")
                        and ttft is not None and ttft <= budget.ttft_s
                        and (tpot is None or tpot <= budget.tpot_s))
            if not attained:
                self._c_slo_viol.inc()
        self._update_kv_resident()
        telemetry.instant("retire", req=act.req_id, slot=slot, reason=reason,
                          tokens=n)
        self._record_flight(res)

    def _record_flight(self, result: GenerationResult) -> None:
        """Offer a finished request to the flight recorder (host-side list
        bookkeeping only — the timeline was built from timestamps the
        scheduler already took, so recording adds zero device syncs)."""
        if self.flight_recorder is not None:
            self.flight_recorder.record(result, source=self.name)

    def _ts_sample(self) -> None:
        """One windowed-time-series sample per scheduler iteration
        (ISSUE 19; lock held, called on every `step()` exit path so
        queue-only iterations still sample — starvation is visible
        precisely when nothing decodes). Reads HOST state only: counter
        values, histogram ring quantiles, queue bookkeeping, the
        allocator's iteration clock — zero device syncs, so timeseries/
        alerts on-vs-off stays token- and sync-bit-identical
        (parity-tested at K=1 and K=8)."""
        ts = self.timeseries
        if ts is None:
            return
        now = time.monotonic()
        clock = self.decoder.cache.allocator.clock
        self.metrics.iter_clock = clock   # last-update stamps (satellite)
        oldest = 0.0
        if self._queue:
            t0 = min(a.resume["t_requeue"] if a.resume is not None
                     else a.t_submit for a in self._queue)
            oldest = max(0.0, now - t0)

        def _q(h, q, default=0.0):
            v = h.quantile(q)
            return default if v is None else v

        ts.sample({
            "iter": clock, "wall_s": now,
            "tokens_out": self._c_tokens.value,
            "admissions": self._c_admits.value,
            "retirements": self._c_retires.value,
            "preemptions": self._c_preempt.value,
            "admission_retries": self._c_adm_retries.value,
            "host_syncs": self._c_syncs.value,
            "slo_violations": self._c_slo_viol.value,
            "queue_wait_sum_s": self._h_queue_wait.sum,
            "decode_stall_sum_ms": self._h_stall.sum,
            "decode_chunk_sum_ms": self._h_chunk_ms.sum,
            "queue_depth": len(self._queue),
            "active_slots": len(self._by_slot),
            "oldest_wait_s": oldest,
            "ttft_p50_s": _q(self._h_ttft, 0.5),
            "ttft_p99_s": _q(self._h_ttft, 0.99),
            "tpot_p50_s": _q(self._h_tpot, 0.5),
            "tpot_p99_s": _q(self._h_tpot, 0.99),
            "decode_stall_p99_ms": _q(self._h_stall, 0.99),
            "queue_wait_p99_s": _q(self._h_queue_wait, 0.99),
        })
        summ = ts.summary()
        for key, g in self._ts_gauges.items():
            g.set(summ[key])
        for cause, frac in summ["blame_shares"].items():
            g = self._ts_blame_gauges.get(cause)
            if g is None:
                g = self.metrics.gauge(
                    f"serving.ts.blame_share_{cause}", "windowed blame-"
                    "cause share of attributed wall (ISSUE 19)")
                self._ts_blame_gauges[cause] = g
            g.set(frac)
        mon = self.alerts
        if mon is None:
            return
        fired = mon.evaluate(ts, iter_id=clock, wall_s=now)
        self._g_burn_short.set(mon.burn_rate_short)
        self._g_burn_long.set(mon.burn_rate_long)
        for a in fired:
            self._c_alerts.inc()
            self._c_alert_kind[a.kind].inc()
            telemetry.instant("alert", kind=a.kind, severity=a.severity,
                              value=round(a.value, 4),
                              threshold=a.threshold, iter=a.iter)
            if self.flight_recorder is not None:
                note = a.to_dict()
                note["source"] = self.name
                if self.journal is not None:
                    # cross-link: the journal record boundary at firing
                    # time — every record with seq <= this belongs to the
                    # history that produced the alert
                    note["journal_seq"] = self.journal.seq
                self.flight_recorder.note_alert(note)
        if fired and self.journal is not None:
            # incident capture (ISSUE 20): freeze the journal tail (the
            # monitor's long window of iterations) into a replayable
            # bundle next to the flight-recorder Perfetto dump. No-op
            # unless an incident root is configured (journal dir or
            # DL4J_TPU_INCIDENT_DIR) — and pure host file I/O when it is.
            notes = [dict(a.to_dict(), source=self.name) for a in fired]
            bundle = self.journal.freeze_incident(
                notes, tail_iters=mon.long_window,
                flight_recorder=self.flight_recorder)
            if bundle is not None:
                self._incidents.append(bundle)
                telemetry.instant("incident", bundle=bundle,
                                  kinds=[a.kind for a in fired])

    # ------------------------------------------- decision journal (ISSUE 20)
    def _jrec(self, kind: str, **fields):
        """Append one typed record to the decision journal, keyed to the
        allocator tick clock; returns its seq (the timeline cross-link)
        or None when journaling is off. Pure host dict bookkeeping —
        zero device syncs, so journaling on-vs-off is token and
        host-sync bit-parity (the tentpole invariant)."""
        j = self.journal
        if j is None:
            return None
        return j.record(kind, tick=self.decoder.cache.allocator.clock,
                        **fields)

    def _journal_iter(self) -> None:
        """One per-iteration state row per scheduler iteration (every
        `step()` exit path, like _ts_sample): pool blocks free, queue /
        active depth, cumulative counted syncs and tokens. Replay
        compares these rows tick-for-tick — per-iteration pool-byte
        conservation and host-sync parity fall out of record equality."""
        j = self.journal
        if j is None:
            return
        cache = self.decoder.cache
        j.record("iter", tick=cache.allocator.clock,
                 q=len(self._queue), act=len(self._by_slot),
                 free=cache.blocks_free, syncs=self._c_syncs.value,
                 toks=self._c_tokens.value)

    def _live_kv_positions(self) -> Dict[int, int]:
        """Per-slot KV positions actually WRITTEN, matching the device's
        `lengths` (prefilled + n_generated - 1 once decode starts — the
        last sampled token's KV lands next iteration; a mid-prefill slot
        holds exactly its prefilled positions). Host bookkeeping only;
        this is the live-vs-waste split the observatory attributes."""
        return {a.slot: a.prefilled + max(0, a.n_generated - 1)
                for a in self._by_slot.values()}

    # ----------------------------------------------------- KV lifecycle
    def _admission_sequence(self, act: _Active) -> List[int]:
        """The token sequence admission and prefill run over: the raw
        prompt, or — for a request resuming after preemption — prompt +
        every generated token but the LAST. The last sampled token's KV
        is written by its own next decode step (exactly as in the
        original run), so re-prefilling over this sequence lands device
        lengths at prefilled + n_generated - 1, the same place the
        never-evicted run had them."""
        if act.resume is None:
            return list(act.req.tokens)
        return list(act.req.tokens) + \
            [int(t) for t in act.resume["tokens"][:-1]]

    def _admission_view(self, act: _Active, t_adm0: float) -> dict:
        """Pool-pressure view for the policy's `admit` decision point
        (lock held): the head-of-queue block shortfall, the preemptable
        slot set — DECODE-ACTIVE slots only (a mid-prefill slot holds no
        resumable decode state and is never preempted) — the admittee's
        queue age (the SLO-slack input), and the bytes a reclaim round
        could free (the deny hint). `snapshot_fn` is LAZY: the pool
        snapshot is only taken when the policy actually plans victims,
        so a lifecycle-less engine pays dict arithmetic and nothing
        else."""
        cache = self.decoder.cache
        req = act.req
        bs = cache.block_size
        need = -(-(len(req.tokens) + req.max_new_tokens) // bs)
        shortfall = need - cache.blocks_free
        if cache.n_free == 0:
            # slot (not block) exhaustion: any one victim frees a slot
            shortfall = max(shortfall, 1)
        eligible = {s for s, a in self._by_slot.items()
                    if self._active_mask[s] and a.n_generated >= 1}
        reclaimable = (cache.num_blocks - cache.blocks_free) * (
            bs * self._kv_bytes_per_pos + self._kv_block_overhead)
        return {"lifecycle": self.lifecycle,
                "shortfall": shortfall,
                "eligible": eligible,
                # consult identity (ISSUE 20): which request on which
                # replica is asking — ReplayPolicy matches the journaled
                # admission stream by these (group engines consult
                # concurrently under their own locks)
                "req_id": getattr(act, "req_id",
                                  getattr(req, "req_id", None)),
                "replica": self.replica_id,
                "now": t_adm0,
                "t_submit": act.resume["t_requeue"]
                if act.resume is not None else act.t_submit,
                "reclaimable_bytes": reclaimable,
                # live short-window burn rate (ISSUE 19): the policy's
                # deny hint stretches its retry_after_s under overload
                # instead of quoting the static SLO slack (None when no
                # monitor runs — the hint falls back to plain slack)
                "burn_rate_short": (self.alerts.burn_rate_short
                                    if self.alerts is not None else None),
                "snapshot_fn": lambda: cache.pool_snapshot(
                    live_positions=self._live_kv_positions())}

    def _execute_evictions(self, plan: dict) -> bool:
        """Execute a policy preemption plan (lock held). Victim
        selection was the observatory's `plan_eviction` — the exact
        scoring the dry-run reports, now acting for real. Returns True
        when at least one victim was preempted; the caller retries
        admission immediately. Victims requeue at the BACK of the
        queue, so the retried head holds its full reservation and
        always progresses — no preemption livelock."""
        cache = self.decoder.cache
        preempted = False
        for victim in plan["evicted"]:
            slot = victim["slot"]
            a = self._by_slot.get(slot)
            if a is None or not self._active_mask[slot]:
                continue
            # block_bytes threads the int8 payload shrink AND the
            # per-block scale overhead through the recompute-vs-swap
            # verdict — the same formula _preempt charges the pool with
            nbytes = victim["blocks_total"] * cache.block_bytes
            # recompute-vs-swap rides MEASURED swap bandwidth — the one
            # lifecycle verdict wall time leaks into. Replay forces the
            # journaled mode (the journal's "preempt" record) instead of
            # re-deciding from this host's calibration (ISSUE 20).
            if self._replay is not None:
                mode = self._replay.preempt_mode(a.req_id)
            else:
                mode = self.lifecycle.choose_mode(victim, nbytes)
            self._preempt(slot, mode, victim)
            preempted = True
        return preempted

    def _preempt(self, slot: int, mode: str, victim: dict) -> None:
        """Preempt the resident request in `slot` under the scheduler
        lock: deactivate, stash its generated history (recompute) or its
        block bytes (swap: async device gather into the host pool —
        functional cache updates pin the gathered values at dispatch
        order, so a chunk still in flight cannot corrupt them), free the
        reservation, requeue at the back. Pending overlapped results for
        this slot are discarded by _finish_steps' identity check; under
        greedy sampling a token lost to a one-chunk-stale readback
        regenerates bit-identically on resume.

        ASYNC swap-out (ISSUE 18, kv_swap_async): the history readback
        — which in overlapped mode blocks on the chunk still in flight —
        and the host-pool payload materialization are both DEFERRED: the
        victim parks in `_pending_swaps` holding the pinned lazy hist
        reference, and `_harvest_swaps` collects the bytes at the next
        chunk boundary. The preempt itself is then pure dispatch +
        bookkeeping: zero device syncs at the pressure moment."""
        cache = self.decoder.cache
        act = self._by_slot.pop(slot)
        self._active_mask[slot] = False
        if self._dev_active is not None:
            self._dev_active = self._dev_active.at[slot].set(False)
        if self._spec_index is not None:
            self._spec_index.drop(slot)
        n = act.n_generated
        t_prev = act.timeline[-1]["t1"] if act.timeline else act.t_submit
        # block_bytes folds in the int8 shrink + per-block scale overhead
        # — the identical formula _execute_evictions fed choose_mode
        nbytes = victim["blocks_total"] * cache.block_bytes
        async_swap = mode == "swap" and self.kv_swap_async
        if mode == "swap":
            # gather BEFORE free: the dispatch pins the blocks' bytes
            # even though the ids return to the free list right after
            blocks = list(cache._slot_blocks[slot])
            ks_blk = vs_blk = None
            if _kvc.is_quantized(cache.state):
                k_blk, v_blk, ks_blk, vs_blk = _kvc.gather_blocks(
                    cache.state, blocks, with_scales=True)
            else:
                k_blk, v_blk = _kvc.gather_blocks(cache.state, blocks)
            self.lifecycle.swap_out(act.req_id, k_blk, v_blk, nbytes,
                                    k_scale=ks_blk, v_scale=vs_blk)
            self._c_evict_swap.inc()
            self._c_swap_out.inc(nbytes)
        else:
            self.lifecycle.evictions_recompute += 1
            self._c_evict_rec.inc()
        if async_swap:
            gen = None   # deferred: _harvest_swaps reads the pinned row
            hist_ref = self._hist
        else:
            with telemetry.span("host_sync", what="preempt_hist",
                                slot=slot):
                # the no-pressure sync sequence never reaches here
                # sync-ok: preemption history readback (pressure path only)
                gen = np.asarray(self._hist[slot])[:n].tolist()
            self._c_syncs.inc()
        self._c_preempt.inc()
        self._g_host_pool.set(self.lifecycle.host_pool.bytes_used)
        cache.free(slot)
        now = time.monotonic()
        act.n_generated = 0
        act.prefilled = 0
        act.shared_len = 0
        act.preemptions += 1
        # a span tiling from the request's previous event; the requeued
        # "queue" phase (or the async victim's "swap_pending" limbo)
        # starts at this t1, keeping coverage gap-free
        jseq = self._jrec("preempt", req=act.req_id, slot=slot, mode=mode,
                          bytes=nbytes,
                          blocks_freed=victim.get("blocks_freed"))
        ev = {"phase": "preempt", "t0": t_prev, "t1": now,
              "mode": mode, "score": victim.get("score"),
              "blocks_freed": victim.get("blocks_freed"),
              "bytes": nbytes,
              "policy": self.lifecycle.policy}
        if jseq is not None:
            # Perfetto cross-link (ISSUE 20 satellite): the span carries
            # the seq of the journal record that scheduled it
            ev["journal_seq"] = jseq
        act.timeline.append(ev)
        telemetry.instant("preempt", req=act.req_id, slot=slot, mode=mode,
                          deferred=async_swap)
        if async_swap:
            # limbo: not queued, not resident — harvested at the next
            # chunk boundary, requeued there
            self._pending_swaps.append({
                "act": act, "slot": slot, "hist": hist_ref, "n": n,
                "nbytes": nbytes, "t0": now})
        else:
            if mode == "swap":
                # sync mode pays demotion INSIDE the preemption stall —
                # the baseline the bench A/B measures async against
                now = self._rebalance_disk(act, now)
            act.resume = {"mode": mode, "tokens": gen, "t_requeue": now,
                          "nbytes": nbytes}
            self._queue.append(act)
        self._update_kv_resident()

    def _rebalance_disk(self, act: Optional[_Active], t0: float) -> float:
        """Demote cold host-pool entries to the disk tier until the pool
        is back under its byte cap (lock held; no-op without a disk tier
        or under cap). Materializes + writes npz files — a pressure
        path, counted as one sync when anything demoted. Appends a
        "disk_demote" span tiling [t0, end] to `act`'s timeline (blamed
        to preempt_disk_io) and returns the end wall clock, so callers
        keep the victim's coverage gap-free."""
        if self.lifecycle is None or self.lifecycle.disk_pool is None:
            return t0
        res = self.lifecycle.rebalance()
        if not res["demotions"]:
            return t0
        self._c_syncs.inc()
        self._c_disk_demote.inc(res["demotions"])
        self._g_disk_pool.set(self.lifecycle.disk_pool.bytes_used)
        self._g_host_pool.set(self.lifecycle.host_pool.bytes_used)
        t1 = time.monotonic()
        if act is not None:
            act.timeline.append({"phase": "disk_demote", "t0": t0,
                                 "t1": t1, "demotions": res["demotions"],
                                 "bytes": res["bytes"]})
        return t1

    def _harvest_swaps(self) -> None:
        """Chunk-boundary harvest of async swap-outs (lock held): each
        parked victim's pinned history row and host-pool payload are
        materialized HERE — after the chunk that was in flight at
        preempt time retired — then the host pool rebalances into the
        disk tier and the victim requeues at the back. Same counted
        sync budget as the synchronous path, moved off the pressure
        moment. Spans tile the limbo gap-free: "swap_pending" (waiting
        for the boundary; the scheduler was NOT stalled, blamed to
        queue_wait) then "swap_out_async" (the deferred materialization,
        blamed to preempt_swap_io), then disk demotion if any."""
        if not self._pending_swaps:
            return
        pendings, self._pending_swaps = self._pending_swaps, []
        for rec in pendings:
            act = rec["act"]
            t_h0 = time.monotonic()
            with telemetry.span("host_sync", what="swap_harvest",
                                slot=rec["slot"]):
                # sync-ok: deferred swap-out harvest (pressure path only)
                gen = np.asarray(
                    rec["hist"][rec["slot"]])[:rec["n"]].tolist()
                self.lifecycle.harvest(act.req_id)
            self._c_syncs.inc()
            self._c_swap_harvest.inc()
            t_h1 = time.monotonic()
            act.timeline.append({"phase": "swap_pending", "t0": rec["t0"],
                                 "t1": t_h0})
            act.timeline.append({"phase": "swap_out_async", "t0": t_h0,
                                 "t1": t_h1, "bytes": rec["nbytes"],
                                 "tokens": len(gen)})
            t_req = self._rebalance_disk(act, t_h1)
            act.resume = {"mode": "swap", "tokens": gen,
                          "t_requeue": t_req, "nbytes": rec["nbytes"]}
            self._queue.append(act)
            telemetry.instant("swap_harvest", req=act.req_id,
                              bytes=rec["nbytes"])
        self._g_host_pool.set(self.lifecycle.host_pool.bytes_used)
        gbps = self.lifecycle.measured_swap_gbps()
        if gbps:
            self._g_swap_gbps.set(gbps)

    def _resume_swap(self, act: _Active, plan, t_adm0: float) -> None:
        """Reactivate a swap-preempted request with NO prefill: the
        re-admitted row's private blocks get their bytes scattered back
        from the host pool, device lengths jump straight to the
        preemption point, and decode continues. Leading blocks the new
        admission mapped SHARED (refcount >= 2) are skipped — the
        registry certifies they already hold this exact prefix — as are
        reservation blocks past the live length (nothing visible there;
        the VISIBILITY invariant masks whatever they hold until this
        request's own writes land). Bit-identity: gather/scatter of the
        same dtype round-trips exactly. Lock held."""
        cache = self.decoder.cache
        req, slot = act.req, act.slot
        plen = len(req.tokens)
        gen = [int(t) for t in act.resume["tokens"]]
        n = len(gen)
        live = plen + n - 1
        nbytes = act.resume["nbytes"]
        with telemetry.span("host_sync", what="swap_in", slot=slot):
            # whichever tier holds the bytes: host fetch, or disk
            # promotion (disk -> host here, host -> device below)
            # sync-ok: swap-in materialization (pressure path only)
            k_host, v_host, scales, sw_info = self.lifecycle.swap_in(
                act.req_id, nbytes)
        self._c_syncs.inc()
        self._c_swap_in.inc(nbytes)
        self._g_host_pool.set(self.lifecycle.host_pool.bytes_used)
        if sw_info["tier"] == "disk":
            self._c_disk_promote.inc()
            self._g_disk_pool.set(
                self.lifecycle.disk_pool.bytes_used)
        gbps = self.lifecycle.measured_swap_gbps()
        if gbps:
            self._g_swap_gbps.set(gbps)
        row = cache._slot_blocks[slot]
        bs = cache.block_size
        lis = [li for li in range(min(len(row), k_host.shape[1]))
               if li * bs < live and cache.allocator.refcount(row[li]) == 1]
        if lis:
            skw = {} if scales is None else {
                "k_scale": scales[0][:, lis], "v_scale": scales[1][:, lis]}
            cache.state = _kvc.restore_blocks(
                cache.state, [row[li] for li in lis],
                k_host[:, lis], v_host[:, lis], **skw)
        cache.state = _kvc.set_length(cache.state, slot, live)
        cache.touch_blocks(slot, 0, live)
        hits = cache.register_prefix(slot, self._admission_sequence(act))
        if hits:
            self._c_lineage_hits.inc(hits)
        act.resume = None
        act.n_generated = n
        act.prefilled = plen
        self._hist = self._hist.at[slot, :n].set(
            jnp.asarray(np.asarray(gen, np.int32)))  # sync-ok: host list
        self._last = self._last.at[slot].set(int(gen[-1]))
        self._active_mask[slot] = True
        if self._dev_active is not None:
            self._dev_active = self._dev_active.at[slot].set(True)
        if self._spec_index is not None:
            self._spec_index.reset(slot, req.tokens)
            self._spec_index.extend(slot, gen)
        now = time.monotonic()
        t_mid = t_adm0
        if sw_info["tier"] == "disk":
            # split the restore: the npz read is disk-IO blame
            # (preempt_disk_io), the remainder is the device restore
            # (preempt_swap_io) — together they tile [t_adm0, now]
            t_mid = min(now, t_adm0 + sw_info["disk_wall_s"])
            act.timeline.append({"phase": "disk_promote", "t0": t_adm0,
                                 "t1": t_mid, "bytes": nbytes})
        act.timeline.append({"phase": "swap_in", "t0": t_mid, "t1": now,
                             "blocks": len(lis), "bytes": nbytes,
                             "resumed_tokens": n,
                             "tier": sw_info["tier"]})
        self._update_kv_resident()

    def _finish_resume(self, act: _Active, t_pf_mono: float,
                       extras: dict) -> None:
        """Recompute-resume epilogue: the re-prefill over prompt +
        generated history just completed, so device lengths already sit
        at the preemption point — restore the host-side decode state
        (history row, last token, spec index) and reactivate. The
        prefill's final logprob row predicts the already-known last
        generated token and is discarded; nothing is sampled. Lock
        held."""
        req, slot = act.req, act.slot
        gen = [int(t) for t in act.resume["tokens"]]
        n = len(gen)
        act.resume = None
        act.n_generated = n
        act.prefilled = len(req.tokens)
        self._hist = self._hist.at[slot, :n].set(
            jnp.asarray(np.asarray(gen, np.int32)))  # sync-ok: host list
        self._last = self._last.at[slot].set(int(gen[-1]))
        self._active_mask[slot] = True
        if self._dev_active is not None:
            self._dev_active = self._dev_active.at[slot].set(True)
        if self._spec_index is not None:
            self._spec_index.reset(slot, req.tokens)
            self._spec_index.extend(slot, gen)
        act.timeline.append({"phase": "prefill", "t0": t_pf_mono,
                             "t1": time.monotonic(), "resume": True,
                             "resumed_tokens": n, **extras})
        self._update_kv_resident()
        # backstop: a preempted slot was decode-active, so it normally
        # still has tokens to generate — but retire cleanly if not
        if n >= req.max_new_tokens or (req.eos_id is not None
                                       and gen[-1] == req.eos_id):
            self._active_mask[slot] = False
            if self._dev_active is not None:
                self._dev_active = self._dev_active.at[slot].set(False)
            self._retire(slot, "length")

    def _transfer_out(self, slot: int, act: _Active, first: int) -> None:
        """Disaggregated hand-off, export side (ISSUE 17): prefill and
        the first token are done on THIS (prefill-role) replica — gather
        the request's live KV blocks (int8 scales ride along on a
        quantized pool, exactly as swap-out), free the slot, and hand
        the request to the group's transfer callback, which routes it
        into a decode replica's queue (`_adopt` -> `_resume_transfer`).
        The gathers are lazy device slices pinned by functional cache
        updates — dispatches, not syncs; the import side counts the one
        transfer materialization. Lock held (this engine's only)."""
        cache = self.decoder.cache
        bs = cache.block_size
        self._by_slot.pop(slot)
        self._active_mask[slot] = False
        if self._dev_active is not None:
            self._dev_active = self._dev_active.at[slot].set(False)
        if self._spec_index is not None:
            self._spec_index.drop(slot)
        # live KV = prompt positions only: the first token's KV is
        # written by its own next decode step on the TARGET replica,
        # exactly where the colocated run would write it
        live = len(act.req.tokens)
        n_live = -(-live // bs)
        blocks = list(cache._slot_blocks[slot])[:n_live]
        ks_blk = vs_blk = None
        if _kvc.is_quantized(cache.state):
            k_blk, v_blk, ks_blk, vs_blk = _kvc.gather_blocks(
                cache.state, blocks, with_scales=True)
        else:
            k_blk, v_blk = _kvc.gather_blocks(cache.state, blocks)
        nbytes = n_live * (bs * self._kv_bytes_per_pos
                           + self._kv_block_overhead)
        cache.free(slot)
        now = time.monotonic()
        act.resume = {"mode": "transfer", "tokens": [first],
                      "t_requeue": now, "nbytes": nbytes,
                      "k": k_blk, "v": v_blk,
                      "k_scale": ks_blk, "v_scale": vs_blk,
                      "blocks": n_live, "src": self.replica_id}
        act.n_generated = 0
        act.prefilled = 0
        act.shared_len = 0
        act.slot = -1
        # a span tiling first-token -> hand-off: the target's "queue"
        # span starts at this t1, so the ISSUE 14 conservation
        # invariant stays closed across the migration
        jseq = self._jrec("xfer_out", req=act.req_id, slot=slot,
                          bytes=nbytes, blocks=n_live)
        ev = {"phase": "kv_transfer", "t0": act.t_first,
              "t1": now, "dir": "out", "bytes": nbytes,
              "blocks": n_live}
        if jseq is not None:
            ev["journal_seq"] = jseq
        act.timeline.append(ev)
        self._c_xfer_out.inc()
        self._c_xfer_bytes.inc(nbytes)
        self._update_kv_resident()
        telemetry.instant("kv_transfer_out", req=act.req_id, slot=slot,
                          bytes=nbytes)
        # hand off LAST: once adopted, the target engine's scheduler
        # thread owns `act` — nothing here may touch it after this call
        self._transfer_cb(act)

    def _adopt(self, act: _Active) -> None:
        """Accept a transferred request into this replica's queue (the
        DECODE side of a disaggregated hand-off). Called from the
        SOURCE replica's scheduler thread; takes only THIS engine's
        lock, and the group wiring keeps prefill->decode lock order
        one-directional (decode engines never call into prefill
        engines), so no lock cycle exists."""
        with self._work:
            if self._stop.is_set():
                # fleet shutting down mid-flight: resolve the future
                # with what exists rather than strand the client
                act.fut._set(GenerationResult(
                    [int(t) for t in act.resume["tokens"]], "shutdown",
                    len(act.req.tokens), req_id=act.req_id,
                    timeline=act.timeline))
                return
            self._queue.append(act)
            telemetry.instant("kv_transfer_adopt", req=act.req_id,
                              queued=len(self._queue))
            self._work.notify()

    def _resume_transfer(self, act: _Active, plan, t_adm0: float) -> None:
        """Disaggregated hand-off, import side (ISSUE 17): the freshly
        admitted row's private blocks get the transferred bytes
        scattered in (scales too on a quantized pool), device lengths
        land exactly where the colocated run's post-prefill lengths sit
        (prompt positions — the first token's KV is written by its own
        next decode step), and decode continues bit-identically under
        greedy sampling. Blocks the new admission mapped SHARED
        (refcount >= 2) are skipped — the registry certifies they hold
        this exact prefix — as in swap-in. The np.asarray
        materialization here is THE counted sync of the whole transfer
        (the export side only dispatched lazy gathers). Lock held."""
        cache = self.decoder.cache
        req, slot = act.req, act.slot
        plen = len(req.tokens)
        gen = [int(t) for t in act.resume["tokens"]]
        n = len(gen)               # 1: the prefill-side first token
        live = plen + n - 1        # == plen
        nbytes = act.resume["nbytes"]
        qd = len(self._queue)
        with telemetry.span("host_sync", what="kv_transfer_in", slot=slot):
            # sync-ok: transfer-import materialization (disagg path only)
            k_host = np.asarray(act.resume["k"])
            # sync-ok: same transfer materialization (one counted sync)
            v_host = np.asarray(act.resume["v"])
            scales = None
            if act.resume["k_scale"] is not None:
                # sync-ok: int8 scales ride the same counted transfer sync
                scales = (np.asarray(act.resume["k_scale"]),
                          # sync-ok: same counted transfer sync
                          np.asarray(act.resume["v_scale"]))
        self._c_syncs.inc()
        row = cache._slot_blocks[slot]
        bs = cache.block_size
        lis = [li for li in range(min(len(row), k_host.shape[1]))
               if li * bs < live and cache.allocator.refcount(row[li]) == 1]
        if lis:
            skw = {} if scales is None else {
                "k_scale": scales[0][:, lis], "v_scale": scales[1][:, lis]}
            cache.state = _kvc.restore_blocks(
                cache.state, [row[li] for li in lis],
                k_host[:, lis], v_host[:, lis], **skw)
        cache.state = _kvc.set_length(cache.state, slot, live)
        cache.touch_blocks(slot, 0, live)
        hits = cache.register_prefix(slot, self._admission_sequence(act))
        if hits:
            self._c_lineage_hits.inc(hits)
        self._publish_heat(list(req.tokens))
        src = act.resume["src"]
        t_requeue = act.resume["t_requeue"]
        act.resume = None
        act.n_generated = n
        act.prefilled = plen
        self._hist = self._hist.at[slot, :n].set(
            jnp.asarray(np.asarray(gen, np.int32)))  # sync-ok: host list
        self._last = self._last.at[slot].set(int(gen[-1]))
        self._active_mask[slot] = True
        if self._dev_active is not None:
            self._dev_active = self._dev_active.at[slot].set(True)
        if self._spec_index is not None:
            self._spec_index.reset(slot, req.tokens)
            self._spec_index.extend(slot, gen)
        now = time.monotonic()
        jseq = self._jrec("xfer_in", req=act.req_id, slot=slot,
                          bytes=nbytes, blocks=len(lis), src=src)
        ev = {"phase": "kv_transfer", "t0": t_adm0,
              "t1": now, "dir": "in", "blocks": len(lis),
              "bytes": nbytes, "src": src,
              "queue_depth": qd,
              "wall_s": now - t_requeue}
        if jseq is not None:
            ev["journal_seq"] = jseq
        act.timeline.append(ev)
        self._c_xfer_in.inc()
        telemetry.instant("kv_transfer_in", req=act.req_id, slot=slot,
                          src=src, bytes=nbytes)
        self._update_kv_resident()
        # backstop: a transferred request wanted >= 2 tokens (1-token
        # requests retire on the prefill side) — but retire cleanly
        if n >= req.max_new_tokens or (req.eos_id is not None
                                       and gen[-1] == req.eos_id):
            self._active_mask[slot] = False
            if self._dev_active is not None:
                self._dev_active = self._dev_active.at[slot].set(False)
            self._retire(slot, "length")

    def _publish_heat(self, seq: List[int]) -> None:
        """Publish this replica's lineage heat on the group-shared
        store's routing bus (ISSUE 17 satellite): one increment per
        full prompt-block digest, read back by the policies'
        `_heat_choice` routing stage. Host dict arithmetic only — a
        bare engine (replica_id None) or a store without the bus skips
        in two attribute reads."""
        store = self.prefix_store
        if store is None or self.replica_id is None \
                or not hasattr(store, "publish_heat"):
            return
        bs = self.decoder.cache.block_size
        if len(seq) < bs:
            return
        for d in chain_digests(seq, bs):
            store.publish_heat(d, self.replica_id)

    def _policy_evict(self) -> None:
        """Background-eviction decision point (ISSUE 17), consulted
        once per scheduler iteration between the heat tick and
        admission: ColocatedPolicy drains radix-retained prefix blocks
        whose lineage went cold past the TTL (ISSUE 17 satellite).
        Zero-cost when no TTL is armed anywhere — the common case
        short-circuits on attribute reads. Lock held."""
        pol = self.policy
        if self._radix_ttl is None and getattr(pol, "ttl", None) is None \
                and getattr(pol, "ttl_s", None) is None:
            return
        cache = self.decoder.cache
        reg = getattr(cache, "registry", None)
        if reg is None or not getattr(reg, "is_radix", False):
            return
        freed = pol.evict({"registry": reg,
                           "clock": cache.allocator.clock,
                           # det-ok: wall TTL (ttl_s) input; the default
                           # tick TTL never reads it, and replay verifies
                           # the sweep via the journaled "ttl" record
                           "now": time.monotonic(),
                           "ttl": self._radix_ttl})
        if freed:
            self._c_ttl_expired.inc(freed)
            self._jrec("ttl", freed=freed)

    def _restore_from_store(self, act: _Active, plan, shared: int) -> int:
        """Extend the resident registry's shared coverage with blocks
        restored from the persistent prefix store (ISSUE 13). Only a
        full-block, non-COW extension past the registry match is taken:
        a COW admission already copied a divergent block, and the target
        blocks must be this admission's FRESH private blocks (refcount
        1) — restoring never touches shared content. Returns the new
        shared length (prefill then runs only the remaining suffix).
        Lock held."""
        cache = self.decoder.cache
        bs = cache.block_size
        pseq = self._admission_sequence(act)
        if plan.cow or shared % bs or len(pseq) <= bs:
            return shared
        digs = chain_digests(pseq, bs)
        k_cov = self.prefix_store.covered(digs)
        k_cov = min(k_cov, (len(pseq) - 1) // bs)  # prefill needs a suffix
        n_sh = shared // bs
        if k_cov <= n_sh:
            return shared
        lis = list(range(n_sh, k_cov))
        row = cache._slot_blocks[act.slot]
        if any(cache.allocator.refcount(row[li]) != 1 for li in lis):
            return shared
        if _kvc.is_quantized(cache.state) and \
                self.prefix_store.fetch_scales([digs[i] for i in lis]) \
                is None:
            # quantized pool but a scale-less (pre-quant) store entry:
            # restoring the payload without its scales would rescale
            # content — skip, prefill covers the suffix as usual
            return shared
        with telemetry.span("host_sync", what="prefix_store_restore",
                            slot=act.slot, blocks=len(lis)):
            # sync-ok: prefix-store fetch materialization (restore path)
            k_host, v_host = self.prefix_store.fetch(
                [digs[i] for i in lis])
            sc = self.prefix_store.fetch_scales([digs[i] for i in lis]) \
                if _kvc.is_quantized(cache.state) else None
        self._c_syncs.inc()
        skw = {} if sc is None else {"k_scale": sc[0], "v_scale": sc[1]}
        cache.state = _kvc.restore_blocks(
            cache.state, [row[li] for li in lis], k_host, v_host, **skw)
        new_shared = k_cov * bs
        cache.touch_blocks(act.slot, shared, new_shared)
        act.prefilled = act.shared_len = new_shared
        self._c_pstore_hits.inc()
        self._c_pstore_tokens.inc(new_shared - shared)
        return new_shared

    def _offer_prefix_store(self, act: _Active, seq: List[int]) -> None:
        """File the just-prefilled sequence's full-block KV bytes in the
        persistent store under their chain digests. The gathers are lazy
        device slices — dispatches, not syncs; bytes cross to the host
        only at store save()/fetch(). Safe to capture here: a request
        writes only positions >= its prompt length, so full prompt
        blocks are final the moment prefill completes, and functional
        cache updates pin the gathered values. Lock held."""
        store = self.prefix_store
        cache = self.decoder.cache
        bs = cache.block_size
        if store is None or len(seq) < bs:
            return
        digs = chain_digests(seq, bs)
        missing = store.missing(digs)
        if not missing:
            return
        row = cache._slot_blocks[act.slot]
        ks_blk = vs_blk = None
        if _kvc.is_quantized(cache.state):
            k_blk, v_blk, ks_blk, vs_blk = _kvc.gather_blocks(
                cache.state, [row[i] for i in missing], with_scales=True)
        else:
            k_blk, v_blk = _kvc.gather_blocks(cache.state,
                                              [row[i] for i in missing])
        nb = bs * self._kv_bytes_per_pos + self._kv_block_overhead
        shape = (cache.n_layers, bs, cache.n_kv_heads, cache.head_dim)
        for j, i in enumerate(missing):
            skw = {} if ks_blk is None else {
                "k_scale": ks_blk[:, j], "v_scale": vs_blk[:, j]}
            store.put(digs[i], k_blk[:, j], v_blk[:, j], nb,
                      block_shape=shape, **skw)

    def _update_kv_resident(self) -> None:
        """Publish resident KV bytes: cache positions actually holding a
        live prompt+generated token across active slots, from the host's
        own bookkeeping (no device read). Lock held. The free/shared
        block gauges come from ONE pool snapshot (ISSUE 12 satellite:
        no torn free-vs-shared pairs); the same snapshot feeds the KV
        observatory when enabled."""
        cache = self.decoder.cache
        obs = self.kv_observatory
        snap = cache.pool_snapshot(live_positions=self._live_kv_positions(),
                                   include_blocks=obs is not None)
        pos = sum(a.prefilled + a.n_generated
                  for a in self._by_slot.values())
        self._g_kv_res.set(pos * self._kv_bytes_per_pos)
        reserved = sum(info["reserved_positions"]
                       for info in snap["slots"].values())
        self._g_kv_waste.set(max(0, reserved - pos) * self._kv_bytes_per_pos)
        self._g_blocks_free.set(snap["blocks_free"])
        self._g_blocks_shared.set(snap["blocks_shared"])
        if obs is not None:
            obs.observe(snap)

    def _register_chunk_costs(self, k: int, active) -> None:
        """File the decode-chunk jit's XLA cost_analysis under
        `decode_chunk_k<K>` (ISSUE 6) — called on a compile-cache miss,
        BEFORE the dispatch, only when profiling is on. AOT lower/compile:
        nothing executes, nothing is donated, no sync — the counted sync
        sequence is bit-identical with profiling on or off."""
        try:
            temps = jnp.asarray(self._temps)
            common = (self.decoder.params, self.decoder.cache.state,
                      self._hist, self._last, self._plens, self._eos,
                      self._maxgen, active)
            if k == 1:
                _profiler.register("decode_chunk_k1", self._step_jit,
                                   common + (self.sampler.peek_keys(1)[0],
                                             temps),
                                   meta={"k": 1}, registry=self.metrics)
            else:
                _profiler.register(f"decode_chunk_k{k}", self._chunk_jit,
                                   common + (self.sampler.peek_keys(k),
                                             temps),
                                   meta={"k": k}, registry=self.metrics)
        except Exception:
            pass

    def _expire_timeouts(self) -> None:
        """Retire timed-out requests before spending device time on them.
        The second wall-clock predicate in the scheduler (with the queue
        shed) — a replay director supplies the journaled outcome instead
        (ISSUE 20). Lock held."""
        now = time.monotonic()
        clock = self.decoder.cache.allocator.clock
        for slot, act in list(self._by_slot.items()):
            expired = act.deadline is not None and now > act.deadline
            if self._replay is not None:
                expired = self._replay.should_expire(act.req_id, clock)
            if expired:
                self._active_mask[slot] = False
                if self._dev_active is not None:
                    self._dev_active = self._dev_active.at[slot].set(False)
                self._c_timeouts.inc()
                self._jrec("expire", req=act.req_id, slot=slot)
                self._retire(slot, "timeout")

    def _chunk_size(self) -> int:
        """Adaptive K: 1 while the admission queue is non-empty (a freed
        slot is detected within one token — bounded time-to-first-token)
        or a prefill is mid-chunking (prefill chunks interleave at
        per-iteration granularity, the Sarathi property), else decode_chunk
        capped at the largest remaining token budget, rounded down to a
        power of two (bounded set of compiled scan lengths, no over-run
        waste at the tail)."""
        if self._queue or self._prefilling or self.decode_chunk <= 1:
            return 1
        rems = [act.req.max_new_tokens - act.n_generated
                for slot, act in self._by_slot.items()
                if self._active_mask[slot]]
        if not rems:
            return 1
        k = min(self.decode_chunk, max(1, max(rems)))
        if k < self.decode_chunk:
            k = 1 << (k.bit_length() - 1)
        return k

    def _finish_steps(self, snapshot: Dict[int, _Active], entry_np, new_np,
                      lp_np, hist=None, span=None) -> None:
        """Host bookkeeping after a chunk's masks materialize: credit each
        slot one token per micro-step it entered active, retire slots whose
        final mask dropped. `snapshot` is the slot->request map AT DISPATCH
        — the overlapped pipeline may have retired/reassigned a slot since,
        and a stale mask must never touch the new occupant (identity
        check). `span` = {"t0", "k", "wall_s", "iter", "compile"}:
        iteration start on the monotonic clock, chunk size, the chunk's
        measured dispatch wall, the scheduler-iteration id and cache-miss
        flag (blame attribution, ISSUE 14) — appended to each
        participating request's timeline as its "decode_chunk" event with
        t1 stamped HERE, per slot — the
        iteration span rather than pure device wall, and late enough that
        another slot's slow retirement readback earlier in this loop stays
        inside the remaining slots' coverage (no timeline gaps). Lock
        held."""
        K = entry_np.shape[0]
        for slot, act in snapshot.items():
            if self._by_slot.get(slot) is not act \
                    or not self._active_mask[slot]:
                continue
            n_new = int(entry_np[:, slot].sum())
            act.n_generated += n_new
            # the chunk appended KV at [lengths_before, lengths_after) =
            # the n_new positions ending at prefilled + n_generated - 1
            # (the last sampled token's KV is written NEXT iteration) —
            # heat stamps ride this host arithmetic, zero added syncs
            p_end = act.prefilled + act.n_generated - 1
            self.decoder.cache.touch_blocks(slot, p_end - n_new, p_end)
            self._c_tokens.inc(n_new)
            if span is not None:
                ev = {"phase": "decode_chunk", "t0": span["t0"],
                      "t1": time.monotonic(), "k": span["k"],
                      "tokens": n_new, "iter": span["iter"],
                      "wall_s": span["wall_s"]}
                if span.get("compile"):
                    ev["compile"] = True
                act.timeline.append(ev)
            if lp_np is not None and act.logprobs is not None:
                act.logprobs.extend(lp_np[i, slot] for i in range(K)
                                    if entry_np[i, slot])
            if not new_np[slot]:
                self._active_mask[slot] = False
                self._retire(slot, "length", hist=hist)
        self._update_kv_resident()

    def step(self) -> bool:
        """One scheduler iteration: admit, run at most one prefill chunk
        for the head partially-prefilled request, decode ONE CHUNK
        (adaptive K micro-steps, one host sync) for every active slot,
        retire completions/timeouts. Returns True while any request is
        active or queued. Synchronous: cross-K token parity is exact
        (peeked keys, effective-step commit)."""
        with self._lock:
            t_iter0 = time.monotonic()   # iteration start: timeline anchor
            self._snapshot_seq += 1      # stats() torn-read detector
            self._iter_id = next(_ITER_IDS)   # blame interference stamp
            if self.name is not None:
                telemetry.set_track(self.name, replica_id=self.replica_id,
                                    engine=type(self).__name__)
            # heat clock: one tick per scheduler iteration (a host int —
            # the unit every block heat stamp is expressed in)
            self.decoder.cache.allocator.tick()
            self._policy_evict()
            self._admit()
            if not self._by_slot:
                # no chunk will run this iteration — this IS the boundary
                # for any victim parked by the admission's preemptions
                self._harvest_swaps()
                self._ts_sample()
                self._journal_iter()
                return bool(self._queue)
            self._expire_timeouts()
            self._prefill_step()
            if not self._active_mask.any():
                # nothing decode-active: every resident slot is mid-prefill
                # (or the final chunk's 1-token request just retired)
                self._harvest_swaps()
                self._ts_sample()
                self._journal_iter()
                return bool(self._by_slot or self._queue)
            # decode-active slots only: a partially-prefilled slot must not
            # be judged by a chunk dispatched while it was still inactive
            # (its all-False mask would retire it the moment the final
            # prefill chunk activates it)
            snapshot = {s: a for s, a in self._by_slot.items()
                        if self._active_mask[s]}
            active = jnp.asarray(self._active_mask)
            if self.spec_decode:
                more = self._spec_step(snapshot, active, t_iter0)
                self._harvest_swaps()
                self._ts_sample()
                self._journal_iter()
                return more or bool(self._queue)
            k_eff = self._chunk_size()
            t_chunk = time.perf_counter()
            self._h_chunk_k.observe(k_eff)
            self._g_queue.set(len(self._queue))
            self._g_occ.set(len(self._by_slot))
            miss = ("chunk", k_eff) not in self._seen_shapes
            if miss:
                self._seen_shapes.add(("chunk", k_eff))
                self._c_compiles.inc()
                if _profiler.enabled():
                    self._register_chunk_costs(k_eff, active)
            cm = telemetry.span("jit_compile", kind="chunk",
                                k=k_eff) if miss else telemetry.NULL_SPAN
            with cm, telemetry.span("decode_chunk", k=k_eff,
                                    active=int(self._active_mask.sum())):
                if k_eff == 1:         # the pre-chunking path, bit-for-bit
                    (self.decoder.cache.state, self._hist, self._last,
                     new_active, lp, nf) = self._step_jit(
                        self.decoder.params, self.decoder.cache.state,
                        self._hist, self._last, self._plens, self._eos,
                        self._maxgen, active, self.sampler.next_key(),
                        jnp.asarray(self._temps))
                    entry_np = self._active_mask.copy()[None]    # (1, S)
                    lps = lp[None]
                else:
                    keys = self.sampler.peek_keys(k_eff)
                    (self.decoder.cache.state, self._hist, self._last,
                     new_active, entries, lps, nf) = self._chunk_jit(
                        self.decoder.params, self.decoder.cache.state,
                        self._hist, self._last, self._plens, self._eos,
                        self._maxgen, active, keys, jnp.asarray(self._temps))
                    # sync-ok: the counted per-chunk readback
                    entry_np = np.asarray(entries)               # (K, S)
                    # commit exactly the micro-steps that ran with active
                    # work — a chunk over-running the last completion
                    # consumes no chain state, so K>1 stays token-identical
                    # to K=1 stepping
                    self.sampler.advance(int(entry_np.any(axis=1).sum()))
            with telemetry.span("host_sync", what="chunk_masks", k=k_eff):
                # sync-ok: the counted per-iteration sync
                new_np = np.asarray(new_active)
                # nf is an output of the SAME dispatch: once the mask above
                # materialized the whole chunk completed, so this bool() is
                # a copy of a finished scalar, not an added sync
                if bool(nf):
                    self._c_nonfinite.inc()
            self._c_syncs.inc()
            chunk_ms = (time.perf_counter() - t_chunk) * 1e3
            self._h_chunk_ms.observe(chunk_ms)
            if _profiler.enabled():
                _profiler.observe(f"decode_chunk_k{k_eff}", chunk_ms,
                                  registry=self.metrics)
            # sync-ok: capture_logprobs mode only
            lp_np = np.asarray(lps) if self.capture_logprobs else None
            self._finish_steps(snapshot, entry_np, new_np, lp_np,
                               span={"t0": t_iter0, "k": k_eff,
                                     "wall_s": chunk_ms / 1e3,
                                     "iter": self._iter_id,
                                     "compile": miss})
            # chunk boundary: the dispatch above retired, so any victim
            # parked at this iteration's preemptions harvests WITHOUT
            # waiting on in-flight work (async swap-out, ISSUE 18)
            self._harvest_swaps()
            self._ts_sample()
            self._journal_iter()
            return bool(self._by_slot or self._queue)

    def _spec_step(self, snapshot: Dict[int, _Active], active,
                   t_iter0: float) -> bool:
        """One SPECULATIVE scheduler iteration (ISSUE 11), replacing the
        chunked decode dispatch: propose per-slot n-gram drafts host-side
        (zero device reads — the index only ever sees tokens the scheduler
        already read back), verify all of them plus the mandatory bonus
        token in ONE widened decode dispatch, and commit the accepted
        prefix. Still exactly ONE counted host sync per iteration — the
        committed-token readback replaces the chunk-mask readback, so spec
        with zero n-gram matches is sync-for-sync identical to K=1
        stepping while every accepted draft amortizes further. Lock
        held."""
        cache = self.decoder.cache
        S = cache.max_seqs
        drafts: Dict[int, List[int]] = {}
        d_max = 0
        for s, a in snapshot.items():
            rem = a.req.max_new_tokens - a.n_generated
            cap_s = min(self.spec_draft, rem - 1)
            prop = self._spec_index.propose(s, cap_s) if cap_s > 0 else []
            drafts[s] = prop
            d_max = max(d_max, len(prop))
        # bucket the draft width to a power of two (bounded compile-key
        # set, like prefill buckets / chunk scan lengths): Q in {2,3,5,9}
        d_bucket = 1
        while d_bucket < d_max:
            d_bucket *= 2
        q_eff = d_bucket + 1
        draft_np = np.zeros((S, d_bucket), np.int32)
        dl_np = np.zeros((S,), np.int32)
        for s, prop in drafts.items():
            draft_np[s, :len(prop)] = prop
            dl_np[s] = len(prop)
            if prop:
                # copy-on-reject guard: the verify rows [pos, pos+d] must
                # not land in COW-shared blocks (possible when a shared
                # prefix ends past the prompt). Host-side refcount check,
                # block copies only in the rare shared-tail case.
                act = snapshot[s]
                pos = act.prefilled + act.n_generated - 1
                cache.ensure_writable(s, pos, pos + len(prop) + 1)
        t_chunk = time.perf_counter()
        self._h_chunk_k.observe(q_eff)
        self._g_queue.set(len(self._queue))
        self._g_occ.set(len(self._by_slot))
        miss = ("spec", q_eff) not in self._seen_shapes
        if miss:
            self._seen_shapes.add(("spec", q_eff))
            self._c_compiles.inc()
        cm = telemetry.span("jit_compile", kind="spec",
                            q=q_eff) if miss else telemetry.NULL_SPAN
        keys = self.sampler.peek_keys(q_eff)
        with cm, telemetry.span("spec_step", q=q_eff,
                                active=int(self._active_mask.sum())):
            (self.decoder.cache.state, self._hist, self._last, new_active,
             toks, c_eff, n_accept, lps, nf) = self._spec_jit(
                self.decoder.params, self.decoder.cache.state, self._hist,
                self._last, self._plens, self._eos, self._maxgen, active,
                keys, jnp.asarray(self._temps), jnp.asarray(draft_np),
                jnp.asarray(dl_np))
        with telemetry.span("host_sync", what="spec_commit", q=q_eff):
            # sync-ok: the counted per-iteration sync — one dispatch's
            # outputs; token VALUES ride along to feed the draft index
            toks_np = np.asarray(toks)        # sync-ok: the counted sync
            c_np = np.asarray(c_eff)          # sync-ok: same dispatch
            acc_np = np.asarray(n_accept)     # sync-ok: same dispatch
            new_np = np.asarray(new_active)   # sync-ok: same dispatch
            if bool(nf):
                self._c_nonfinite.inc()
        self._c_syncs.inc()
        # chain keys consumed = deepest commit across slots (chunk
        # semantics: shared per-offset keys, effective-depth advance)
        self.sampler.advance(int(c_np.max()))
        if self.journal is not None:
            # draft proposals + accept counts per slot: recomputed live
            # on replay (the n-gram index is deterministic given the
            # committed history), journaled so divergence checking
            # covers the speculative path too. String slot keys keep
            # in-memory records identical to their JSONL round-trip.
            self._jrec("spec",
                       drafts={str(s): int(dl_np[s]) for s in snapshot},
                       accepted={str(s): int(acc_np[s]) for s in snapshot},
                       committed={str(s): int(c_np[s]) for s in snapshot})
        chunk_ms = (time.perf_counter() - t_chunk) * 1e3
        self._h_chunk_ms.observe(chunk_ms)
        if _profiler.enabled():
            _profiler.observe(f"spec_step_q{q_eff}", chunk_ms,
                              registry=self.metrics)
        # sync-ok: capture_logprobs mode only
        lp_np = np.asarray(lps) if self.capture_logprobs else None
        for slot, act in snapshot.items():
            if self._by_slot.get(slot) is not act \
                    or not self._active_mask[slot]:
                continue
            n_new = int(c_np[slot])
            d_s = int(dl_np[slot])
            acc = int(acc_np[slot])
            act.n_generated += n_new
            # committed spec rows span [pos, pos + n_new); rejected rows
            # past the commit are invisible and deliberately NOT stamped
            p_end = act.prefilled + act.n_generated - 1
            cache.touch_blocks(slot, p_end - n_new, p_end)
            self._c_tokens.inc(n_new)
            self._spec_index.extend(slot, toks_np[slot, :n_new])
            if d_s > 0:
                self._c_spec_acc.inc(acc)
                self._c_spec_rej.inc(d_s - acc)
                self._h_spec_accept.observe(acc / d_s)
                self._h_spec_draft.observe(d_s)
            # tiles from iteration start like "decode_chunk" — resident
            # requests keep gap-free timeline coverage under spec
            ev = {"phase": "spec_step", "t0": t_iter0,
                  "t1": time.monotonic(), "draft": d_s,
                  "accepted": acc, "tokens": n_new,
                  "iter": self._iter_id, "wall_s": chunk_ms / 1e3}
            if miss:
                ev["compile"] = True
            act.timeline.append(ev)
            if lp_np is not None and act.logprobs is not None:
                act.logprobs.extend(lp_np[slot, j] for j in range(n_new))
            if not new_np[slot]:
                self._active_mask[slot] = False
                self._retire(slot, "length")
        self._update_kv_resident()
        return bool(self._by_slot or self._queue)

    # ------------------------------------------------- overlapped pipeline
    def _drain_overlapped(self) -> None:
        """Run chunks with one-chunk-deep pipelining: dispatch chunk i+1
        (consuming the DEVICE-side active mask — no host round-trip), then
        materialize chunk i's masks while the device computes. Scheduling
        decisions run one chunk stale, which is safe: finished slots decode
        at most one extra chunk fully masked, and admissions/timeouts patch
        the device mask before the next dispatch. Keys are consumed
        unconditionally here (throughput mode — the strict cross-K key
        schedule is a synchronous-step guarantee)."""
        pending = None  # (snapshot, entries_dev, final_dev, hist_dev, nf,
        #                  t_disp, k_eff, t_iter0, iter_id, compile_miss)
        with self._lock:
            self._dev_active = jnp.asarray(self._active_mask)
        try:
            while True:
                with self._lock:
                    t_iter0 = time.monotonic()   # timeline anchor: covers
                    # this iteration's admissions + the dispatch it issues
                    self._snapshot_seq += 1      # stats() torn-read detector
                    self._iter_id = next(_ITER_IDS)  # blame stamp
                    if self.name is not None:
                        telemetry.set_track(self.name,
                                            replica_id=self.replica_id,
                                            engine=type(self).__name__)
                    self.decoder.cache.allocator.tick()   # heat clock
                    self._admit()
                    self._expire_timeouts()
                    # at most one prefill chunk per iteration: the chunk's
                    # dispatch threads cache_state, so it serializes with
                    # the decode chunks on device without blocking the host
                    self._prefill_step()
                    dispatched = None
                    if self._active_mask.any():
                        k_eff = self._chunk_size()
                        self._h_chunk_k.observe(k_eff)
                        self._g_queue.set(len(self._queue))
                        self._g_occ.set(len(self._by_slot))
                        miss = ("chunk", k_eff) not in self._seen_shapes
                        if miss:
                            self._seen_shapes.add(("chunk", k_eff))
                            self._c_compiles.inc()
                            if _profiler.enabled():
                                self._register_chunk_costs(
                                    k_eff, self._dev_active)
                        cm = telemetry.span(
                            "jit_compile", kind="chunk",
                            k=k_eff) if miss else telemetry.NULL_SPAN
                        keys = self.sampler.peek_keys(k_eff)
                        self.sampler.advance(k_eff)
                        # decode-active slots only (see step()): a slot whose
                        # final prefill chunk lands between this dispatch and
                        # its mask readback must not be retired by the stale
                        # all-False mask it never participated in
                        snapshot = {s: a for s, a in self._by_slot.items()
                                    if self._active_mask[s]}
                        with cm, telemetry.span(
                                "decode_chunk", k=k_eff, overlap=True,
                                active=int(self._active_mask.sum())):
                            (self.decoder.cache.state, self._hist,
                             self._last, self._dev_active, entries,
                             _lps, nf) = self._chunk_jit(
                                self.decoder.params, self.decoder.cache.state,
                                self._hist, self._last, self._plens,
                                self._eos, self._maxgen, self._dev_active,
                                keys, jnp.asarray(self._temps))
                        dispatched = (snapshot, entries, self._dev_active,
                                      self._hist, nf, time.perf_counter(),
                                      k_eff, t_iter0, self._iter_id, miss)
                    # chunk i+1 is enqueued; materializing chunk i's masks
                    # now overlaps host bookkeeping with device compute
                    if pending is not None:
                        (snapshot, entries, final, hist, nf, t_disp,
                         k_prev, t_disp_mono, it_prev, miss_prev) = pending
                        with telemetry.span("host_sync", what="chunk_masks",
                                            overlap=True):
                            # sync-ok: the counted per-chunk readback
                            entry_np = np.asarray(entries)
                            new_np = np.asarray(final)  # sync-ok: same dispatch
                            # same dispatch as the masks just materialized —
                            # reading the sentinel scalar adds no sync
                            if bool(nf):
                                self._c_nonfinite.inc()
                        self._c_syncs.inc()
                        chunk_ms = (time.perf_counter() - t_disp) * 1e3
                        self._h_chunk_ms.observe(chunk_ms)
                        if _profiler.enabled():
                            # overlapped wall spans dispatch->readback of
                            # the SAME chunk (one pipeline stage) — still a
                            # host value the loop already computes
                            _profiler.observe(f"decode_chunk_k{k_prev}",
                                              chunk_ms,
                                              registry=self.metrics)
                        # the timeline event spans dispatch -> readback of
                        # the SAME chunk; chunk i+1 was dispatched before
                        # this readback, so consecutive events overlap —
                        # resident requests keep gap-free coverage
                        self._finish_steps(snapshot, entry_np, new_np, None,
                                           hist=hist,
                                           span={"t0": t_disp_mono,
                                                 "k": k_prev,
                                                 "wall_s": chunk_ms / 1e3,
                                                 "iter": it_prev,
                                                 "compile": miss_prev})
                    # chunk boundary: the masks above just materialized, so
                    # any victim parked by this iteration's preemptions has
                    # its pinned hist (the output of that same chunk) ready
                    # — the harvest is a copy, not a stall — and requeued
                    # victims are visible to the exit check below
                    self._harvest_swaps()
                    self._journal_iter()
                    pending = dispatched
                    if pending is None and not (self._by_slot or self._queue):
                        return
        finally:
            with self._lock:
                self._dev_active = None

    def drain(self) -> None:
        """Run iterations until no active or queued work remains. Uses the
        overlapped pipeline when enabled (and token-level logprob capture is
        off — capture needs the synchronous per-chunk readback)."""
        if self.overlap and self.decode_chunk > 1 \
                and not self.capture_logprobs and not self.spec_decode:
            self._drain_overlapped()
        else:
            while self.step():
                pass
        # $DL4J_TPU_TRACE_PATH: export the recorded spans after every full
        # drain (last writer wins) — cheap host I/O, outside the hot loop
        telemetry.maybe_export_trace()
        # HBM phase-boundary probe (ISSUE 6): the drain just ended, the
        # host owns this boundary — never polled per token/step
        _tmemory.poll("serving.drain", registry=self.metrics)

    def generate(self, prompts, **kw) -> List[GenerationResult]:
        """Synchronous convenience: submit every prompt (a Request or a
        token-id sequence; **kw applies to bare sequences), drain, return
        results in submission order."""
        futs = [self.submit(p if isinstance(p, Request) else Request(p, **kw))
                for p in prompts]
        self.drain()
        return [f.get(timeout=0) for f in futs]

    # --------------------------------------------------- background thread
    def start(self) -> "ServingEngine":
        if self._thread is not None:
            return self
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()
        return self

    def _loop(self):
        while not self._stop.is_set():
            with self._work:
                while not (self._queue or self._by_slot
                           or self._stop.is_set()):
                    self._work.wait(timeout=0.1)
                if self._stop.is_set():
                    break
            self.step()
        # graceful drain: finish in-flight work unless told to abandon it
        if self._drain_on_stop:
            self.drain()

    def shutdown(self, wait: bool = True) -> None:
        """Stop the background loop. wait=True finishes in-flight requests
        first; wait=False resolves them with finish_reason='shutdown'."""
        self._drain_on_stop = wait
        self._stop.set()
        with self._work:
            self._work.notify_all()
        if self._thread is not None:
            self._thread.join()
            self._thread = None
        with self._lock:
            if not wait:
                for slot in list(self._by_slot):
                    self._active_mask[slot] = False
                    self._retire(slot, "shutdown")
                # limbo victims (async swap-out awaiting harvest): resolve
                # WITHOUT materializing — their bytes are never needed —
                # and forget the parked payload on every tier
                for rec in self._pending_swaps:
                    act = rec["act"]
                    act.fut._set(GenerationResult(
                        [], "shutdown", len(act.req.tokens),
                        req_id=act.req_id, admission_retries=act.retries,
                        timeline=act.timeline))
                    if self.lifecycle is not None:
                        self.lifecycle.drop(act.req_id)
                self._pending_swaps.clear()
                for act in self._queue:
                    now = time.monotonic()
                    # requeued-after-preemption: tile from t_requeue, the
                    # pre-preemption life is already covered (ISSUE 14)
                    t_q0 = act.resume["t_requeue"] \
                        if act.resume is not None else act.t_submit
                    act.timeline.append({"phase": "queue",
                                         "t0": t_q0, "t1": now,
                                         "retries": act.retries})
                    act.fut._set(GenerationResult(
                        [], "shutdown", len(act.req.tokens),
                        req_id=act.req_id, admission_retries=act.retries,
                        timeline=act.timeline))
                    if act.resume is not None \
                            and act.resume["mode"] == "swap" \
                            and self.lifecycle is not None:
                        # a swapped-out queued request's parked bytes
                        # would otherwise leak host-pool/disk capacity
                        self.lifecycle.drop(act.req_id)
                self._queue.clear()
            elif self._by_slot or self._queue:
                self.drain()
        if self.prefix_store is not None and self.prefix_store.path:
            # spill the prefix store so prompts survive the restart
            # (ISSUE 13) — shutdown is a phase boundary, syncs are fine
            self.prefix_store.save()
        if self.journal is not None:
            # seal the buffered tail segment so a post-shutdown load sees
            # every record (tmp+rename, same crash discipline as DiskBlockPool)
            self.journal.flush()

    _drain_on_stop = True
