"""Pluggable scheduler policy (ISSUE 17): ONE interface for every
scheduling decision the serving stack makes.

Before this module, the four scheduling decisions lived as if-chains
spread across three files: routing in `ShardedServingGroup._route`
(sharding.py), preempt-or-wait in `ServingEngine._make_room`
(engine.py), radix reclaim inside `KVCache.admit` (kv_cache.py), and no
transfer decision at all. `SchedulingPolicy` names them as four
decision points consulted by the engine/group at the exact places the
if-chains used to run:

- ``route(request, fleet_view) -> (replica, reason)`` — which replica a
  new request lands on (group scope).
- ``admit(request, pool_view) -> AdmissionDecision`` — what to do when
  the head-of-queue block reservation FAILS: keep waiting
  (``deny_with_hint``, carrying the forensics hint = reclaimable bytes +
  suggested retry), or ``preempt`` residents (the lifecycle plan rides
  the decision), or ``accept`` = retry immediately with no action.
- ``evict(pressure_view) -> int`` — background cache-pressure /
  idle-drain work, consulted once per scheduler iteration; this is
  where the radix TTL (ISSUE 17 satellite) lives: retained prefix
  blocks whose lineage went cold for longer than ``ttl`` allocator
  ticks (or ``ttl_s`` wall seconds) are released, so an idle fleet
  drains its cached-prefix bytes without admission pressure.
- ``transfer(finished_prefill_view) -> replica | None`` — where a
  just-prefilled request should DECODE. `ColocatedPolicy` returns None
  (decode where prefill ran); `DisaggregatedPolicy` (serving/disagg.py)
  returns a decode-role replica and the engine ships the live KV there.

`ColocatedPolicy` re-expresses the existing behaviors EXACTLY (the
refactor is behavior-preserving by test): resident-prefix affinity ->
cohort -> least-loaded routing (PR 10), plan-then-preempt under KV
exhaustion (PR 13), radix reclaim + the new TTL (PR 16/17). The one
addition every policy shares is published-heat affinity (ISSUE 17
satellite): when no replica holds a RESIDENT matching prefix, the
router consults the lineage heat replicas publish through the shared
`PersistentPrefixStore` — a replica that recently served this lineage
(bytes restorable from the store, tree possibly still warm) beats a
colder least-loaded one.

Views are plain dicts built by the engine/group from host bookkeeping
it already holds — consulting a policy adds zero device syncs. Routing
state (cohort map, round-robin cursors) lives ON the policy instance:
one policy object serves one group for its lifetime.

Sync discipline: pure host bookkeeping — no jax import, no device
access (tests/test_sync_discipline.py scans this module).
"""
from __future__ import annotations

import os
from collections import OrderedDict
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

from deeplearning4j_tpu.serving.block_table import chain_digests
from deeplearning4j_tpu.telemetry.alerts import retry_after_from_burn

__all__ = [
    "AdmissionDecision", "SchedulingPolicy", "ColocatedPolicy",
    "resolve_policy", "resolve_radix_ttl",
]


def resolve_radix_ttl(ttl=None) -> Optional[int]:
    """Constructor resolution of the radix-retention TTL knob
    (allocator ticks = scheduler iterations): explicit argument wins,
    else `DL4J_TPU_RADIX_TTL` (empty/0 = no TTL — retained blocks live
    until pressure reclaim, the pre-ISSUE-17 behavior)."""
    if ttl is None:
        env = os.environ.get("DL4J_TPU_RADIX_TTL", "")
        ttl = int(env) if env not in ("", "0", "off") else 0
    ttl = int(ttl)
    return ttl if ttl > 0 else None


@dataclass
class AdmissionDecision:
    """Outcome of the ``admit`` decision point.

    kind = "accept": retry the reservation next iteration, no action.
    kind = "deny_with_hint": keep the request queued; `hint` carries
        the forensics the caller files on the rejection record —
        ``reclaimable_bytes`` (pool bytes a reclaim/preemption round
        could free) and ``retry_after_s`` (suggested client backoff:
        the admittee's remaining SLO slack, after which the policy
        would escalate to preemption).
    kind = "preempt": `victims` is the lifecycle eviction plan
        (telemetry.kv_observatory.plan_eviction shape) the engine
        executes, then retries the reservation immediately.
    """
    kind: str
    victims: Optional[dict] = None
    hint: Optional[dict] = None

    @classmethod
    def accept(cls) -> "AdmissionDecision":
        return cls("accept")

    @classmethod
    def deny(cls, hint: Optional[dict] = None) -> "AdmissionDecision":
        return cls("deny_with_hint", hint=hint)

    @classmethod
    def preempt(cls, plan: dict) -> "AdmissionDecision":
        return cls("preempt", victims=plan)


class SchedulingPolicy:
    """Base interface. Subclasses override the four decision points;
    the defaults are the no-op choices (route round-robin-less to 0,
    deny on pressure, no eviction, no transfer) so a minimal custom
    policy only implements what it cares about."""

    def bind(self, n_replicas: int) -> "SchedulingPolicy":
        """Called once by the group that adopts this policy, before any
        routing. Default: record the fleet size."""
        self.n_replicas = int(n_replicas)
        return self

    def role(self, replica: int) -> str:
        """Replica role label: "colocated" (prefill AND decode),
        "prefill", or "decode"."""
        return "colocated"

    # ---------------------------------------------------- decision points
    def route(self, request, fleet_view: dict) -> Tuple[int, str]:
        return 0, "static"

    def admit(self, request, pool_view: dict) -> AdmissionDecision:
        return AdmissionDecision.deny()

    def evict(self, pressure_view: dict) -> int:
        return 0

    def transfer(self, finished_prefill_view: dict) -> Optional[int]:
        return None


class ColocatedPolicy(SchedulingPolicy):
    """The default policy: every replica both prefills and decodes.

    Re-expresses the pre-ISSUE-17 inline behaviors:

    * route — resident-prefix affinity (the replica whose registry
      holds the longest matching RESIDENT prefix) -> cohort affinity
      (prompts sharing a leading block follow the first of their kind)
      -> published-heat affinity (ISSUE 17 satellite; skipped when the
      group has no shared store or nothing was published) ->
      least-loaded with a rotating round-robin tie-break.
    * admit — with no lifecycle manager: deny (wait in FIFO order).
      With one: plan victims via `lifecycle.plan` and preempt when the
      plan satisfies the shortfall — UNLESS an `slo` was given and the
      admittee still has TTFT slack (`SLO.slack_s`), in which case the
      cheap choice is deny-with-hint and preemption is saved for
      requests about to blow their budget (ISSUE 17 satellite: the
      PR 13 eviction-aware-admission leftover).
    * evict — radix TTL drain: release retained prefix blocks whose
      node went untouched for > ttl allocator ticks / ttl_s seconds.
    """

    _COHORT_CAP = 4096      # FIFO bound on the cohort-affinity map

    def __init__(self, *, slo=None, ttl: Optional[int] = None,
                 ttl_s: Optional[float] = None):
        self.slo = slo
        self.ttl = resolve_radix_ttl(ttl)
        self.ttl_s = ttl_s
        self.n_replicas = 1
        self._cohorts: "OrderedDict[tuple, int]" = OrderedDict()
        self._rr = 0

    # ------------------------------------------------------------ routing
    def route_candidates(self, fleet_view: dict) -> List[int]:
        """Replicas a NEW request may land on (disagg narrows this to
        the prefill rows)."""
        return list(range(fleet_view["n"]))

    def _heat_choice(self, tokens: List[int], fleet_view: dict,
                     cands: List[int]) -> Optional[int]:
        """Hottest replica by published lineage heat (shared-store bus),
        restricted to `cands`. None when nothing was published."""
        store = fleet_view.get("store")
        bs = fleet_view["block_size"]
        if store is None or len(tokens) < bs \
                or not hasattr(store, "route_heat"):
            return None
        heat = store.route_heat(chain_digests(tokens, bs))
        heat = {r: h for r, h in heat.items() if r in cands and h > 0}
        if not heat:
            return None
        # deterministic: max heat, lowest replica index breaking ties
        return min(sorted(heat), key=lambda r: -heat[r])

    def route(self, request, fleet_view: dict) -> Tuple[int, str]:
        tokens = list(request.tokens)
        cands = self.route_candidates(fleet_view)
        regs = fleet_view["registries"]
        best, best_len = -1, 0
        for r in cands:
            matched = regs[r].match(tokens)[0]
            if matched > best_len:
                best, best_len = r, matched
        if best >= 0:
            return best, "prefix_affinity"
        bs = fleet_view["block_size"]
        cohort = tuple(tokens[:bs]) if len(tokens) > bs else None
        if cohort is not None and cohort in self._cohorts:
            chosen = self._cohorts[cohort]
            if chosen in cands:
                self._cohorts.move_to_end(cohort)
                return chosen, "cohort"
            del self._cohorts[cohort]   # stale entry from another role set
        hot = self._heat_choice(tokens, fleet_view, cands)
        if hot is not None:
            self._remember_cohort(cohort, hot)
            return hot, "heat"
        chosen = self._least_loaded(fleet_view, cands)
        self._remember_cohort(cohort, chosen)
        return chosen, "least_loaded"

    def _least_loaded(self, fleet_view: dict, cands: List[int]) -> int:
        stats_fn = fleet_view["stats_fn"]
        order = [cands[(self._rr + i) % len(cands)]
                 for i in range(len(cands))]
        self._rr = (self._rr + 1) % len(cands)
        chosen, chosen_load = order[0], None
        for r in order:
            snap = stats_fn(r)
            load = snap["queue_depth"] + snap["active_slots"]
            if chosen_load is None or load < chosen_load:
                chosen, chosen_load = r, load
        return chosen

    def _remember_cohort(self, cohort, replica: int) -> None:
        if cohort is None:
            return
        self._cohorts[cohort] = replica
        while len(self._cohorts) > self._COHORT_CAP:
            self._cohorts.popitem(last=False)

    # ---------------------------------------------------------- admission
    def admit(self, request, pool_view: dict) -> AdmissionDecision:
        lifecycle = pool_view.get("lifecycle")
        hint = {"reclaimable_bytes": pool_view.get("reclaimable_bytes", 0),
                "retry_after_s": 0.0}
        # SLO-slack backoff hint FIRST (ISSUE 20 satellite): computed
        # before the lifecycle gate so a no-lifecycle engine's denies —
        # and, with the timeseries monitor off (DL4J_TPU_TS=0,
        # burn_rate_short absent), a burn-less engine's denies — still
        # carry the static PR 17 slack hint instead of 0.0.
        slack = 0.0
        if self.slo is not None and pool_view.get("now") is not None:
            waited = pool_view["now"] - pool_view["t_submit"]
            slack = self.slo.slack_s(waited)
            if slack > 0:
                # the admittee can still make its TTFT budget by waiting
                # for a natural retirement — deny is the cheap branch;
                # escalate to preemption once the slack is gone. The
                # backoff hint reads the LIVE short-window burn rate
                # (ISSUE 19) when a monitor runs: an overloaded engine
                # stretches retry_after_s beyond the static SLO slack so
                # client retries don't pile onto the overload; with no
                # monitor it degrades to the slack itself.
                hint["retry_after_s"] = retry_after_from_burn(
                    slack, pool_view.get("burn_rate_short"))
        if lifecycle is None:
            return AdmissionDecision.deny(hint)
        # hierarchical-storage headroom (ISSUE 18): bytes the swap
        # ladder (host pool free + disk tier free) could still absorb —
        # forensics for the deny record, telling operators whether a
        # preemption round would land on swap or degrade to recompute
        hp = lifecycle.host_pool
        headroom = max(0, hp.capacity_bytes - hp.bytes_used)
        if getattr(lifecycle, "disk_pool", None) is not None:
            headroom += max(0, lifecycle.disk_pool.capacity_bytes
                            - lifecycle.disk_pool.bytes_used)
        hint["swap_headroom_bytes"] = headroom
        if self.slo is not None and slack > 0:
            return AdmissionDecision.deny(hint)
        shortfall = pool_view["shortfall"]
        eligible = pool_view["eligible"]
        if shortfall <= 0 or not eligible:
            return AdmissionDecision.deny(hint)
        plan = lifecycle.plan(pool_view["snapshot_fn"](), shortfall,
                              eligible=eligible)
        if not plan["evicted"] or not plan["satisfies"]:
            return AdmissionDecision.deny(hint)
        return AdmissionDecision.preempt(plan)

    # ----------------------------------------------------------- eviction
    def evict(self, pressure_view: dict) -> int:
        reg = pressure_view.get("registry")
        ttl = self.ttl if self.ttl is not None else pressure_view.get("ttl")
        ttl_s = self.ttl_s if self.ttl_s is not None \
            else pressure_view.get("ttl_s")
        if reg is None or not hasattr(reg, "expire") \
                or (ttl is None and ttl_s is None):
            return 0
        return reg.expire(ttl, ttl_s=ttl_s,
                          clock=pressure_view.get("clock"),
                          now=pressure_view.get("now"))


def resolve_policy(policy=None, *, slo=None) -> SchedulingPolicy:
    """Constructor resolution of the group/engine policy knob: an
    instance passes through; "colocated"/"disagg" name the built-ins;
    None consults `DL4J_TPU_DISAGG` (empty/0/off = colocated; a
    positive integer = disaggregated with that many PREFILL rows)."""
    if policy is None:
        env = os.environ.get("DL4J_TPU_DISAGG", "")
        if env not in ("", "0", "off"):
            from deeplearning4j_tpu.serving.disagg import DisaggregatedPolicy
            n_pref = int(env) if env.isdigit() else 1
            return DisaggregatedPolicy(prefill_replicas=max(1, n_pref),
                                       slo=slo)
        return ColocatedPolicy(slo=slo)
    if isinstance(policy, str):
        if policy == "colocated":
            return ColocatedPolicy(slo=slo)
        if policy == "disagg":
            from deeplearning4j_tpu.serving.disagg import DisaggregatedPolicy
            return DisaggregatedPolicy(slo=slo)
        raise ValueError(f"unknown scheduling policy {policy!r} "
                         "(expected 'colocated', 'disagg', or an instance)")
    return policy
