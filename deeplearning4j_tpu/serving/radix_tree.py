"""Radix-tree prefix cache over token KV blocks (ISSUE 16).

Beyond-reference (RadixAttention, Zheng et al. 2023 / SGLang; PAPERS.md).
The linear-chain `PrefixRegistry` (serving/block_table.py) indexes
RESIDENT prompt blocks under sha1 chain digests: it already returns the
longest registered prefix of an arbitrary prompt, but it forgets a block
the moment its last slot mapping drops — so the KV a retired chat turn
prefilled is gone by the time the follow-up turn arrives, and only the
host-side `PersistentPrefixStore` (a device->host->device round trip)
can bridge turns. This module closes that gap with the RadixAttention
design:

- `RadixPrefixTree`: a radix tree whose nodes own PATH-COMPRESSED runs
  of full token blocks (children branch at block granularity, keyed by
  the next block's token content). It is a drop-in for `PrefixRegistry`
  — same `bind_pool` / `match` / `register` / `forget` / `lineage` /
  `n_entries` duck type — so the KV cache, the engine, and the
  `ShardedServingGroup` router consume it unchanged.

- DEVICE-RESIDENT RETENTION: at `register` time the tree takes its OWN
  allocator reference on every newly claimed full prompt block. When the
  owning request retires and `KVCache.free` drops the slot's mapping,
  the tree's reference keeps the block in the pool — refcount >= 1, so
  the block never returns to the free list and a later turn (or a
  mid-conversation fork) `match()`es it and COW-shares it exactly like
  a concurrently resident prefix. Partial tail blocks are NOT retained:
  a tail certifies only one exact prompt, so pinning a whole block for
  it buys one rare rematch — tails keep the linear registry's
  resident-only lifetime.

- `reclaim(n)`: cache-pressure eviction. When admission cannot allocate,
  the cache asks the tree to release up to `n` retained blocks whose
  ONLY reference is the tree's — coldest node first, deepest block
  first. `match()` stamps every traversed node, so an ancestor's
  `last_touch` is always >= its descendants' and cold-first order frees
  leaves before the prefixes they depend on.

- `store_victim(entries)`: the ONE tree-wide LRU the persistent prefix
  store plugs in as its `evict_policy` (serving/lifecycle.py), replacing
  the store's private byte-cap LRU: digests belonging to no known
  lineage (orphans from a previous process) evict first in store LRU
  order, then the digest whose tree node is coldest.

Chain digests (`block_table._block_digest`) stay the content addresses:
node digest i commits to tokens [0, (i+1)*block_size), so tree nodes,
`PersistentPrefixStore` keys, and observatory lineage labels all agree
across restarts and replicas by construction.

Per-lineage hit counting (ISSUE 16 satellite): `register` returns how
many of the prompt's digests were ALREADY claimed (first registration
wins; the re-registration is the popularity signal), and
`lineage_hit_counts()` exposes the per-digest tallies the eviction
policy reads. The linear `PrefixRegistry` counts the same way.

Sync discipline: pure host bookkeeping over python ints/bytes — no jax
import, no device access (tests/test_sync_discipline.py scans this
module). The only allocator calls are incref/decref/refcount: host
integers.
"""
from __future__ import annotations

import os
import time
import weakref
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

from deeplearning4j_tpu.serving.block_table import _block_digest

_BlockKey = Tuple[int, ...]


def resolve_prefix_radix(prefix_radix: Optional[bool] = None) -> bool:
    """Constructor resolution of the radix knob: explicit argument wins,
    else `DL4J_TPU_PREFIX_RADIX` (default OFF — radix off keeps the
    linear-chain registry and is bit-identical to the pre-radix engine)."""
    if prefix_radix is None:
        return os.environ.get("DL4J_TPU_PREFIX_RADIX", "0") \
            not in ("", "0", "off")
    return bool(prefix_radix)


class _Node:
    """One radix node: a path-compressed run of full token blocks.

    `tok_blocks[j]` is the j-th block's token content (the edge label at
    block granularity), `phys[j]` the physical block currently holding
    its KV (None = evicted/never resident — the digest and structure
    outlive the residency), `digests[j]` its chain digest, `hobjs[j]`
    the live sha1 chain object AFTER block j (needed to extend the chain
    into tail digests without re-hashing the whole prefix)."""

    __slots__ = ("parent", "tok_blocks", "phys", "digests", "hobjs",
                 "children", "last_touch", "last_touch_wall", "hits")

    def __init__(self, parent: Optional["_Node"]):
        self.parent = parent
        self.tok_blocks: List[_BlockKey] = []
        self.phys: List[Optional[int]] = []
        self.digests: List[bytes] = []
        self.hobjs: List[object] = []
        self.children: Dict[_BlockKey, "_Node"] = {}
        self.last_touch = 0
        # wall-clock twin of last_touch (ISSUE 17): the TTL expiry knob
        # can be expressed in seconds as well as allocator ticks
        self.last_touch_wall = time.monotonic()
        self.hits = 0

    def depth(self) -> int:
        d, n = 0, self.parent
        while n is not None:
            d, n = d + 1, n.parent
        return d


class RadixPrefixTree:
    """Radix tree over token blocks, `PrefixRegistry`-compatible.

    Like the linear registry, a tree is bound to exactly ONE block pool
    (`bind_pool`) because physical block ids are pool-scoped; routers may
    run read-only `match()` affinity queries against it. Unlike the
    linear registry it RETAINS registered full prompt blocks in the pool
    after their owners retire (see module docstring), so consumers that
    free blocks must budget for `reclaim()` under pressure."""

    #: duck-typed marker the KV cache keys retention behavior off
    is_radix = True

    def __init__(self, block_size: int):
        self.block_size = int(block_size)
        self._root = _Node(None)
        # full-claim reverse map: physical block -> (node, index in run)
        self._by_block: Dict[int, Tuple[_Node, int]] = {}
        # digest -> node holding it (kept while the NODE lives, even when
        # the block was evicted — the store eviction policy reads lineage
        # heat through this map)
        self._by_digest: Dict[bytes, _Node] = {}
        # exact-prompt partial tails: same shape as the linear registry
        self._tail: Dict[bytes, int] = {}
        self._tail_claims: Dict[int, List[bytes]] = {}
        # blocks the tree itself holds an allocator reference on
        self._retained: set = set()
        self._pool: Optional[weakref.ref] = None
        self.lineage_hits_total = 0
        self._lineage_hits: Dict[str, int] = {}

    # ------------------------------------------------------------ binding
    def bind_pool(self, pool: object) -> "RadixPrefixTree":
        """Claim this tree for one block pool (idempotent per pool) —
        same contract as PrefixRegistry.bind_pool."""
        if self._pool is not None:
            owner = self._pool()
            if owner is not None and owner is not pool:
                raise ValueError(
                    "RadixPrefixTree is already bound to another KV pool; "
                    "physical block ids are pool-scoped, so one tree "
                    "cannot serve two pools (give each replica its own)")
        self._pool = weakref.ref(pool)
        return self

    def _pool_obj(self):
        return self._pool() if self._pool is not None else None

    def _clock(self) -> int:
        pool = self._pool_obj()
        return pool.allocator.clock if pool is not None else 0

    # ------------------------------------------------------------- lookup
    def match(self, tokens: Sequence[int]) -> Tuple[int, List[int]]:
        """(matched_len, physical blocks covering it) for the longest
        RESIDENT prefix of `tokens`: walk the tree block by block,
        stopping at the first token mismatch or evicted (phys=None)
        block — admission needs contiguous coverage — then try the
        exact-prompt partial tail when every full block matched. Stamps
        every traversed node at the allocator clock (tree LRU heat)."""
        bs = self.block_size
        n_full = len(tokens) // bs
        blocks: List[int] = []
        clock = self._clock()
        node, j = self._root, 0
        h = None
        i = 0
        while i < n_full:
            key = tuple(int(t) for t in tokens[i * bs:(i + 1) * bs])
            if j == len(node.tok_blocks):
                nxt = node.children.get(key)
                if nxt is None:
                    break
                node, j = nxt, 0
            if node.tok_blocks[j] != key or node.phys[j] is None:
                break
            blocks.append(node.phys[j])
            h = node.hobjs[j]
            node.last_touch = clock
            node.last_touch_wall = time.monotonic()
            i += 1
            j += 1
        if i == n_full:
            tail = tokens[n_full * bs:]
            if tail:
                b = self._tail.get(
                    _block_digest(h, tail, tail=True).digest())
                if b is not None:
                    blocks.append(b)
                    return len(tokens), blocks
        return i * bs, blocks

    # ----------------------------------------------------------- register
    def register(self, tokens: Sequence[int],
                 phys_blocks: Sequence[int]) -> int:
        """File every prompt block of a just-prefilled request, inserting
        tree structure (descend / leaf-extend / split) as needed. First
        registration wins — an already-claimed position keeps its block
        (identical content by the chain-hash certificate) and counts one
        LINEAGE HIT. Newly claimed blocks are RETAINED: the tree increfs
        them on the bound pool's allocator so they survive their owner's
        retirement. Returns the number of lineage hits recorded."""
        bs = self.block_size
        n_full = len(tokens) // bs
        clock = self._clock()
        hits = 0
        node, j = self._root, 0
        h = None
        for i in range(n_full):
            seg = tokens[i * bs:(i + 1) * bs]
            key = tuple(int(t) for t in seg)
            h = _block_digest(h, seg)
            if j == len(node.tok_blocks):
                child = node.children.get(key)
                if child is not None:
                    node, j = child, 0
                elif node.children or node is self._root:
                    # a branch point: start a new child run here
                    child = _Node(node)
                    node.children[key] = child
                    node, j = child, 0
                # else: leaf — extend its run in place (path compression)
            elif node.tok_blocks[j] != key:
                # divergence INSIDE a run: split, then branch a new child
                self._split(node, j)
                child = _Node(node)
                node.children[key] = child
                node, j = child, 0
            if j == len(node.tok_blocks):
                node.tok_blocks.append(key)
                node.phys.append(None)
                node.digests.append(h.digest())
                node.hobjs.append(h)
                self._by_digest[h.digest()] = node
            if node.phys[j] is None:
                self._claim_full(node, j, int(phys_blocks[i]))
            else:
                hits += 1
                node.hits += 1
                hx = node.digests[j].hex()
                self._lineage_hits[hx] = self._lineage_hits.get(hx, 0) + 1
            node.last_touch = clock
            node.last_touch_wall = time.monotonic()
            j += 1
        tail = tokens[n_full * bs:]
        if tail:
            d = _block_digest(h, tail, tail=True).digest()
            if d in self._tail:
                hits += 1
                self._lineage_hits[d.hex()] = \
                    self._lineage_hits.get(d.hex(), 0) + 1
            else:
                b = int(phys_blocks[n_full])
                self._tail[d] = b
                self._tail_claims.setdefault(b, []).append(d)
        self.lineage_hits_total += hits
        return hits

    def _claim_full(self, node: _Node, j: int, block: int) -> None:
        node.phys[j] = block
        self._by_block[block] = (node, j)
        pool = self._pool_obj()
        if pool is not None:
            # the tree's OWN reference — retention past slot lifetime
            pool.allocator.incref(block)
            self._retained.add(block)

    def _split(self, node: _Node, j: int) -> _Node:
        """Split `node`'s run at index j: node keeps run[:j], a new child
        takes run[j:] plus the children. Returns the new child."""
        child = _Node(node)
        child.tok_blocks = node.tok_blocks[j:]
        child.phys = node.phys[j:]
        child.digests = node.digests[j:]
        child.hobjs = node.hobjs[j:]
        child.children = node.children
        child.last_touch = node.last_touch
        child.last_touch_wall = node.last_touch_wall
        child.hits = node.hits
        for c in child.children.values():
            c.parent = child
        node.tok_blocks = node.tok_blocks[:j]
        node.phys = node.phys[:j]
        node.digests = node.digests[:j]
        node.hobjs = node.hobjs[:j]
        node.children = {child.tok_blocks[0]: child}
        for idx, b in enumerate(child.phys):
            if b is not None:
                self._by_block[b] = (child, idx)
        for d in child.digests:
            self._by_digest[d] = child
        return child

    # ------------------------------------------------------- invalidation
    def forget(self, block: int) -> None:
        """Invalidate every claim backed by `block` — called when the
        allocator actually frees it (its content is about to be
        overwritten). Under retention that only happens for tail blocks,
        for blocks the tree itself released via `reclaim`, and for
        never-registered blocks."""
        ent = self._by_block.pop(block, None)
        if ent is not None:
            node, j = ent
            node.phys[j] = None
            self._retained.discard(block)
            self._maybe_prune(node)
        for d in self._tail_claims.pop(block, ()):
            if self._tail.get(d) == block:
                del self._tail[d]

    def _maybe_prune(self, node: _Node) -> None:
        """Drop nodes that hold no resident block and no children —
        structure is only worth keeping while it can serve a match or
        carries live descendants. Recurses upward."""
        while (node is not self._root and not node.children
               and all(p is None for p in node.phys)):
            parent = node.parent
            if node.tok_blocks:
                parent.children.pop(node.tok_blocks[0], None)
            for d in node.digests:
                if self._by_digest.get(d) is node:
                    del self._by_digest[d]
            node.parent = None
            node = parent

    # ---------------------------------------------------------- retention
    def retained_blocks(self) -> frozenset:
        """Blocks the tree currently holds its own allocator reference
        on (the `cached_prefix` attribution category when no slot maps
        them)."""
        return frozenset(self._retained)

    @property
    def n_retained(self) -> int:
        return len(self._retained)

    def release(self, block: int) -> bool:
        """Drop the tree's reference on one retained block. Returns True
        when that freed the block (no slot was mapping it) — the claim is
        then forgotten; otherwise the claim stays valid exactly like a
        linear-registry entry (it dies when the last slot drops it)."""
        if block not in self._retained:
            return False
        self._retained.discard(block)
        pool = self._pool_obj()
        if pool is None:
            return False
        if pool.allocator.decref(block):
            self.forget(block)
            return True
        return False

    def reclaim(self, n_blocks: int, protect: Iterable[int] = ()) -> int:
        """Free up to `n_blocks` retained blocks whose ONLY reference is
        the tree's (freeing a slot-mapped block reclaims nothing).
        Victims are taken coldest-node-first; within a node, deepest
        block first — match() stamps every node on the path, so
        ancestors are never colder than the descendants that need them.
        `protect` exempts blocks an in-flight admission is about to map.
        Returns the number of blocks actually freed."""
        pool = self._pool_obj()
        if pool is None or n_blocks <= 0 or not self._retained:
            return 0
        alloc = pool.allocator
        protect = set(protect)
        cand = [b for b in self._retained
                if b not in protect and alloc.refcount(b) == 1]

        def _score(b):
            node, j = self._by_block[b]
            return (node.last_touch, -node.depth(), -j)

        cand.sort(key=_score)
        freed = 0
        for b in cand:
            if freed >= n_blocks:
                break
            if self.release(b):
                freed += 1
        return freed

    def expire(self, ttl: Optional[int] = None, *,
               ttl_s: Optional[float] = None,
               clock: Optional[int] = None,
               now: Optional[float] = None) -> int:
        """TTL drain (ISSUE 17 satellite): release every retained block
        whose owning node went UNTOUCHED for more than `ttl` allocator
        ticks (scheduler iterations) — and/or `ttl_s` wall-clock
        seconds — so an idle fleet eventually returns its cached-prefix
        bytes to the free list without admission pressure. A block is
        expired when ANY enabled dimension exceeds its budget; blocks a
        slot still maps (refcount > 1) are never touched, and a node
        re-stamped by match()/register() heat survives. Returns the
        number of blocks freed."""
        if self._pool is None or not self._retained \
                or (ttl is None and ttl_s is None):
            return 0
        alloc = self._pool_obj().allocator
        clk = alloc.clock if clock is None else clock
        wall = time.monotonic() if now is None else now
        freed = 0
        for b in list(self._retained):
            ent = self._by_block.get(b)
            if ent is None or alloc.refcount(b) != 1:
                continue
            node, _j = ent
            stale = (ttl is not None and clk - node.last_touch > ttl) or \
                (ttl_s is not None and wall - node.last_touch_wall > ttl_s)
            if stale and self.release(b):
                freed += 1
        return freed

    def reclaim_all(self) -> int:
        """Release every retained block (teardown/drain helper)."""
        freed = 0
        for b in list(self._retained):
            if self.release(b):
                freed += 1
        return freed

    # ------------------------------------------------- store eviction hook
    def store_victim(self, entries) -> Optional[bytes]:
        """`PersistentPrefixStore.evict_policy` hook: the ONE tree-wide
        LRU. `entries` is the store's digest-keyed mapping in its own LRU
        order; pick the first digest belonging to NO known lineage (an
        orphan from a previous process — the tree has never seen it), else
        the digest whose node is coldest."""
        victim, victim_touch = None, None
        for d in entries:
            node = self._by_digest.get(d)
            if node is None:
                return d
            if victim_touch is None or node.last_touch < victim_touch:
                victim, victim_touch = d, node.last_touch
        return victim

    # ------------------------------------------------------ observability
    def lineage(self, block: int) -> Optional[str]:
        """Hex digest of the prefix chain `block` serves (the full-claim
        digest, else the first tail claim), or None — same contract as
        PrefixRegistry.lineage."""
        ent = self._by_block.get(block)
        if ent is not None:
            node, j = ent
            return node.digests[j].hex()
        tails = self._tail_claims.get(block)
        if tails:
            return tails[0].hex()
        return None

    def lineage_hit_counts(self) -> Dict[str, int]:
        """Per-digest re-registration tallies (the popular-prefix signal
        the eviction policy reads)."""
        return dict(self._lineage_hits)

    @property
    def n_entries(self) -> int:
        """Resident claims: full blocks currently holding KV + tails."""
        return len(self._by_block) + len(self._tail)

    @property
    def n_nodes(self) -> int:
        stack, n = [self._root], -1       # root is structural, not counted
        while stack:
            node = stack.pop()
            n += 1
            stack.extend(node.children.values())
        return n

    @property
    def n_blocks_indexed(self) -> int:
        """Token blocks the tree knows (resident or evicted)."""
        stack, n = [self._root], 0
        while stack:
            node = stack.pop()
            n += len(node.tok_blocks)
            stack.extend(node.children.values())
        return n

    def overhead_bytes(self) -> int:
        """Rough host-side footprint of the tree structure (PERF.md cost
        model): per indexed block one token tuple (~8B/token), a digest
        (20B sha1), a chain-hash object (~100B), and per node a fixed
        ~200B of slots/dict overhead. An estimate, not an allocation
        measurement."""
        return (self.n_blocks_indexed * (self.block_size * 8 + 120)
                + self.n_nodes * 200)
