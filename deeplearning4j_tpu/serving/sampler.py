"""Token sampling for the serving engine: greedy / temperature / top-k.

One jittable pure function (`sample_tokens`) over a BATCH of logprob rows
with per-request temperatures, plus a tiny `Sampler` that threads a PRNG key
functionally (`jax.random.split` per step — the framework's rng convention,
never reusing a key).

Greedy is expressed as temperature == 0 so a single compiled step serves a
mixed batch of greedy and sampling requests (continuous batching admits both
into the same decode iteration): the categorical draw happens for every row,
and `jnp.where(temp > 0, draw, argmax)` selects per row. `top_k` is a STATIC
python int (part of the jit cache key) — the engine fixes it per-engine, not
per-request, to keep one compiled decode step.
"""
from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp

NEG_INF = -1e30


def sample_tokens(key, logprobs, temperature, top_k: int = 0):
    """Draw one token per row.

    key: PRNG key; logprobs: (S, V) float rows (any log-space scores work —
    normalization cancels); temperature: (S,) per-row, 0 -> greedy;
    top_k: static int, 0/>=V -> disabled. Returns (S,) int32 tokens."""
    logprobs = logprobs.astype(jnp.float32)
    S, V = logprobs.shape
    greedy = jnp.argmax(logprobs, axis=-1).astype(jnp.int32)
    temperature = jnp.asarray(temperature, jnp.float32)
    # guard temp=0 rows: scaled logits are never *selected* there, but must
    # not produce NaNs that poison the whole categorical draw
    safe_t = jnp.where(temperature > 0, temperature, 1.0)
    scaled = logprobs / safe_t[:, None]
    if top_k and top_k < V:
        kth = jax.lax.top_k(scaled, top_k)[0][:, -1]        # (S,)
        scaled = jnp.where(scaled >= kth[:, None], scaled, NEG_INF)
    drawn = jax.random.categorical(key, scaled, axis=-1).astype(jnp.int32)
    return jnp.where(temperature > 0, drawn, greedy)


def spec_accept_tokens(keys, logprobs, draft, draft_len, temperature,
                       top_k: int = 0):
    """Speculative accept/resample (ISSUE 11) for DETERMINISTIC (n-gram)
    drafts, jittable, batched.

    keys: (Q, ...) stacked PRNG keys — key i is chain position i, exactly
    what the i-th sequential decode step would consume (Sampler.peek_keys);
    logprobs: (S, Q, V) verified target rows — row i is conditioned on the
    last committed token plus drafts 0..i-1; draft: (S, Q-1) proposed
    tokens; draft_len: (S,) how many leading draft rows are real (0 = plain
    decode step); temperature/top_k as in `sample_tokens`.

    Standard speculative sampling accepts draft d_i with probability
    p_i(d_i) and samples the residual on reject. With a POINT-MASS draft
    distribution both halves collapse into one categorical draw from the
    target row: t_i = sample(key_i, p_i) accepts (t_i == d_i) with exactly
    p_i(d_i), and conditioned on mismatch t_i IS the normalized residual.
    So the commit is simply the sampled tokens up to and including the
    first mismatch — distribution-exact by the Leviathan et al. argument,
    and stronger: because every row uses its sequential chain key and, on
    the accepted prefix, identical conditioning, the committed tokens are
    BIT-IDENTICAL to plain decode on the same key chain (greedy is the
    temperature == 0 special case — key-free argmax comparison).

    Returns (tokens (S, Q) — row j is the committed token at generation
    offset j for j < n_commit, rows past that are dead; n_accept (S,) —
    drafts accepted; n_commit (S,) = n_accept + 1 — tokens to commit, the
    amount the caller must Sampler.advance() by for live slots)."""
    S, Q, V = logprobs.shape
    toks = jax.vmap(
        lambda k, lp: sample_tokens(k, lp, temperature, top_k),
        in_axes=(0, 1), out_axes=1)(keys, logprobs)             # (S, Q)
    i = jnp.arange(Q - 1)[None, :]
    ok = (toks[:, :-1] == draft) & (i < draft_len[:, None])     # (S, Q-1)
    n_accept = jnp.sum(jnp.cumprod(ok.astype(jnp.int32), axis=1), axis=1)
    return toks, n_accept, n_accept + 1


class Sampler:
    """Holds the sampling config and threads the PRNG key across steps.

    Chunked decode (engine decode_chunk > 1) needs K per-micro-step keys up
    front, but only the micro-steps that actually ran with active slots may
    consume chain state — otherwise a chunk that over-runs past the last
    completion would leave the chain in a different state than K=1
    stepping, breaking cross-K token parity. `peek_keys` materializes the
    next K subkeys WITHOUT advancing, and `advance` commits exactly the
    effective number of steps afterwards; `next_key` == peek_keys(1)[0] +
    advance(1), so K=1 remains bit-for-bit the pre-chunking behavior."""

    def __init__(self, seed: int = 0, top_k: int = 0):
        if top_k < 0:
            raise ValueError(f"top_k must be >= 0, got {top_k}")
        self.top_k = int(top_k)
        self._key = jax.random.PRNGKey(seed)

    def next_key(self):
        """Split off a fresh per-step key (functional; never reused)."""
        self._key, sub = jax.random.split(self._key)
        return sub

    def peek_keys(self, n: int):
        """The next `n` subkeys of the chain, stacked (n, ...), WITHOUT
        advancing the chain — subkey i is exactly what the i-th future
        next_key() call would return."""
        k = self._key
        subs = []
        for _ in range(n):
            k, sub = jax.random.split(k)
            subs.append(sub)
        return jnp.stack(subs)

    def advance(self, n: int) -> None:
        """Commit `n` splits to the chain (pairs with peek_keys: peek K,
        consume the first n <= K on device, advance by n)."""
        for _ in range(n):
            self._key, _ = jax.random.split(self._key)

    def sample(self, logprobs, temperature):
        return sample_tokens(self.next_key(), logprobs, temperature,
                             self.top_k)
