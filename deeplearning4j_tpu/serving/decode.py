"""Incremental (KV-cached) decode for SelfAttentionLayer transformer stacks.

Beyond-reference: the attention stack recomputes all T x T scores per token
(O(T^2) per generated token); this module makes generation O(T) per token by
attending a SINGLE query position against the slot-based cache
(serving/kv_cache.py).

Two pieces:

- `decode_attention`: the masked single-query dot-product against the cache,
  GQA-aware without materializing the head repeat (q is reshaped to
  (S, Hk, G, D) and contracted directly against the (S, L, Hk, D) cache —
  query head h = hk*G + g reads kv head hk, the SAME grouping as
  ops/flash_attention._kv_row and the layer's jnp.repeat fallback). Scores
  and softmax run in fp32 (fp64 under x64), streams stay in the cache dtype
  (bf16 on TPU). Dispatches through the helper seam to the split-K
  flash-decode Pallas kernel (ops/decode_attention.py, default-on for TPU)
  which partitions the cache length axis and merges partials via logaddexp;
  the dense einsum path here is the fp64 oracle and universal fallback.

- `StackDecoder`: a stateful prefill-then-decode wrapper over an already
  initialized MultiLayerNetwork / ComputationGraph whose hidden layers are
  causal SelfAttentionLayers (plus position-wise layers). It re-derives each
  attention layer's q/k/v from the layer's OWN params with the exact math of
  SelfAttentionLayer.forward, so cached decode is position-for-position
  equal to the full-recompute forward oracle (tests/test_serving.py pins
  this in fp64). Both steps are pure functions of (params, cache state,
  activations) with FIXED shapes — the serving engine jits them ONCE and
  never retraces per token (prompt lengths are bucketed to powers of two;
  padded tail writes are harmless, see kv_cache.py's visibility invariant).

The cache is PAGED (ISSUE 7, serving/kv_cache.py): every slot resolves
logical positions through its device block-table row, so the decode step
attends via `decode_attention_paged` (block-table-aware split-K kernel,
ops/decode_attention.py) and prefill scatters whole blocks through the
table. Prompt buckets are rounded up to whole blocks so prefill writes
block-granular; padding past a slot's reservation trash-routes (see
kv_cache.py's trash invariant). Prefix sharing adds a third pure step,
`_prefill_shared_fn`: when admission mapped a request's leading prompt
blocks onto resident shared KV, only the SUFFIX is embedded and computed —
suffix queries attend the slot's full gathered prefix (shared blocks
included), skipping the shared positions' projection and score math
entirely. That is the prefill-FLOPs saving the bench measures.

CHUNKED prefill (ISSUE 9, Sarathi-style) is the same pure function under a
second stateful entry point, `prefill_chunk`: a prompt split into
fixed-budget chunks runs chunk i as a "suffix" whose already-resident
prefix is chunks 0..i-1 — `start` plays shared_len, `end` plays plen, the
chunk's k/v scatter through the block table and its queries attend the
slot's first gathered blocks (earlier chunks included), causal within the
chunk. One jit, one compile cache, for both features.
"""
from __future__ import annotations

import functools
from typing import Callable, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.common.enums import Activation
from deeplearning4j_tpu.nn.conf.layers.attention import SelfAttentionLayer
from deeplearning4j_tpu.nn.conf.layers.feedforward import (
    ActivationLayer, DropoutLayer, LossLayer)
from deeplearning4j_tpu.nn.conf.layers.recurrent import RnnOutputLayer
from deeplearning4j_tpu.ops.decode_attention import (
    decode_attention_dense, decode_attention_dense_paged,
    decode_attention_dense_spec_paged)
from deeplearning4j_tpu.ops.helpers import helper_for
from deeplearning4j_tpu.serving import kv_cache, quant

NEG_INF = -1e30

# Non-attention layers a decode step may apply one position at a time.
# Anything else (LSTM state, normalization statistics over time, pooling)
# is NOT position-wise and must fail loudly rather than decode garbage.
_POSITIONWISE = (RnnOutputLayer, ActivationLayer, DropoutLayer, LossLayer)


def decode_attention(q, kc, vc, visible, scale, window: int = 0):
    """Single-query attention against the cache.

    q: (S, H, D) current-position queries; kc/vc: (S, L, Hk, D) cache
    (current position already appended); visible: (S,) number of visible
    positions per slot (= position index + 1); `window` > 0 applies the
    layer's sliding-window semantics (query at position visible-1 sees keys
    j with (visible-1) - j < window). Returns (S, H, D) in q.dtype.

    Resolved through the helper seam at trace time: the split-K
    flash-decode Pallas kernel (ops/decode_attention.flash_decode_attention,
    default-on for TPU) when enabled, else the dense einsum oracle
    (ops/decode_attention.decode_attention_dense)."""
    fn = helper_for("decode_attention", decode_attention_dense)
    return fn(q, kc, vc, visible, scale, window)


def decode_attention_paged(q, kp, vp, block_tables, visible, scale,
                           window: int = 0, k_scale=None, v_scale=None):
    """Single-query attention against the PAGED cache: same contract as
    `decode_attention`, but kc/vc are the (num_blocks + 1, block_size, Hk,
    D) physical blocks and each slot's positions resolve through its
    (blocks_per_seq,) block-table row. Resolved through the helper seam:
    the block-table-aware split-K kernel
    (ops/decode_attention.flash_decode_attention_paged, default-on for
    TPU — the gather stays INSIDE the kernel via scalar prefetch) when
    enabled, else the dense paged oracle (gather + the dense einsum).
    k_scale/v_scale (num_blocks + 1, Hk): per-head-per-block scales of an
    int8 pool — both kernel and oracle dequantize per block, natively."""
    fn = helper_for("decode_attention_paged", decode_attention_dense_paged)
    return fn(q, kp, vp, block_tables, visible, scale, window,
              k_scale=k_scale, v_scale=v_scale)


def decode_attention_spec_paged(q, kp, vp, block_tables, visible, scale,
                                window: int = 0, k_scale=None,
                                v_scale=None):
    """Multi-query (speculative verification) attention against the PAGED
    cache: q (S, Q, H, D) — query i of slot s sits at logical position
    visible[s] - 1 + i and sees j < visible + i. Resolved through the
    helper seam: the multi-query split-K kernel
    (ops/decode_attention.flash_decode_attention_spec_paged, default-on for
    TPU) when enabled, else the dense spec paged oracle, whose per-position
    math is bit-identical to the single-query dense path. k_scale/v_scale:
    same int8-pool contract as `decode_attention_paged`."""
    fn = helper_for("decode_attention_spec_paged",
                    decode_attention_dense_spec_paged)
    return fn(q, kp, vp, block_tables, visible, scale, window,
              k_scale=k_scale, v_scale=v_scale)


def _attn_heads(layer: SelfAttentionLayer, params, xt):
    """(.., n_in) -> q (.., H, Dh), k/v (.., Hk, Dh) with the layer's exact
    projection math (SelfAttentionLayer.forward's `heads`). When the layer
    dict carries `w_*_scale` leaves (weight-only int8, ISSUE 15) the
    projection runs as (x @ w_int8) * scale — static key-presence
    dispatch, resolved at trace time."""
    H = layer.n_heads
    Hk = getattr(layer, "n_kv_heads", 0) or H
    Dh = layer.n_out // H

    def proj(name, h):
        w = params[name]
        sc = params.get(name + "_scale")
        y = xt @ w if sc is None else quant.int8_matmul(xt, w, sc)
        return jnp.reshape(y, xt.shape[:-1] + (h, Dh))

    return (proj("w_q", H), proj("w_k", Hk), proj("w_v", Hk))


def _out_proj(params, out):
    """The attention output projection out @ w_o + b, int8-aware the same
    way as `_attn_heads`."""
    sc = params.get("w_o_scale")
    y = out @ params["w_o"] if sc is None \
        else quant.int8_matmul(out, params["w_o"], sc)
    return y + params["b"]


def quantize_attention_weights(params, layers):
    """Weight-only int8 for every SelfAttentionLayer's q/k/v/o projections
    (per-output-channel scales, serving/quant.py): each weight leaf is
    replaced by its int8 payload plus a `<name>_scale` sibling. The output
    head (RnnOutputLayer W) deliberately stays float — logits are the
    accuracy-critical surface and its matmul is one row per token, not a
    bandwidth bottleneck. Returns a new params list; layer dicts are
    copied, never mutated (the net still owns the float originals)."""
    out = list(params)
    for i, layer in enumerate(layers):
        if not isinstance(layer, SelfAttentionLayer):
            continue
        p = dict(out[i])
        for name in ("w_q", "w_k", "w_v", "w_o"):
            wq, sc = quant.quantize_weight(p[name])
            p[name] = wq
            p[name + "_scale"] = sc
        out[i] = p
    return out


def _dense_causal_attention(layer, q, k, v):
    """Prefill attention: dense causal scores over the padded prompt block
    (B=1). q (T, H, Dh); k/v (T, Hk, Dh). Padded tail keys are masked by
    causality alone for the valid rows, so no key-padding mask is needed
    (see kv_cache.py's visibility invariant)."""
    T, H, Dh = q.shape
    Hk = k.shape[1]
    G = H // Hk
    if G > 1:   # same grouping as the layer's jnp.repeat fallback
        k = jnp.repeat(k, G, axis=1)
        v = jnp.repeat(v, G, axis=1)
    acc = jnp.promote_types(q.dtype, jnp.float32)
    s = jnp.einsum("qhd,khd->hqk", q.astype(acc), k.astype(acc)) \
        / np.sqrt(Dh)
    qi = jnp.arange(T)[:, None]
    kj = jnp.arange(T)[None, :]
    valid = qi >= kj
    if layer.attention_window:
        valid = valid & (qi - kj < layer.attention_window)
    s = jnp.where(valid[None], s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    out = jnp.einsum("hqk,khd->qhd", p, v.astype(acc))
    return out.astype(q.dtype)


class StackDecoder:
    """Prefill-then-decode wrapper for a causal SelfAttentionLayer stack.

    Owns the KVCache geometry and the two jitted pure steps; the serving
    engine composes them with token embedding and sampling. `net` may be a
    MultiLayerNetwork or a linear-chain ComputationGraph."""

    def __init__(self, net, max_seqs: int, max_len: int,
                 dtype=None, block_size: Optional[int] = None,
                 num_blocks: Optional[int] = None,
                 prefix_share: Optional[bool] = None,
                 prefix_registry=None, paged_attention=None,
                 paged_spec_attention=None, kv_quant: Optional[bool] = None,
                 quant_weights: Optional[bool] = None,
                 prefix_radix: Optional[bool] = None):
        layers, params = _extract_stack(net)
        self.layers = layers
        self.dtype = jnp.dtype(dtype) if dtype is not None else net.dtype
        from deeplearning4j_tpu.util.dtypes import cast_floats
        self.params = cast_floats(params, self.dtype) \
            if self.dtype != net.dtype else params
        self.quant_weights = quant.resolve_quant_weights(quant_weights)
        if self.quant_weights:
            self.params = quantize_attention_weights(self.params, layers)

        self.attn_idx = [i for i, l in enumerate(layers)
                         if isinstance(l, SelfAttentionLayer)]
        if not self.attn_idx:
            raise ValueError("StackDecoder needs at least one "
                             "SelfAttentionLayer in the stack")
        shapes = set()
        for i in self.attn_idx:
            l = layers[i]
            if not l.causal:
                raise ValueError(
                    f"layer {i} ({type(l).__name__}) is not causal — "
                    "autoregressive decode needs causal attention")
            Hk = getattr(l, "n_kv_heads", 0) or l.n_heads
            shapes.add((Hk, l.n_out // l.n_heads))
        if len(shapes) != 1:
            raise ValueError(f"attention layers disagree on (n_kv_heads, "
                             f"head_dim): {sorted(shapes)} — the stacked "
                             "cache needs a uniform shape")
        for i, l in enumerate(layers[:-1]):
            if not isinstance(l, (SelfAttentionLayer,) + _POSITIONWISE):
                raise NotImplementedError(
                    f"layer {i} ({type(l).__name__}) has no incremental "
                    "decode path (not position-wise)")
        (self.n_kv_heads, self.head_dim), = shapes
        self.n_in = layers[0].n_in if hasattr(layers[0], "n_in") else None
        self.cache = kv_cache.KVCache(len(self.attn_idx), max_seqs, max_len,
                                      self.n_kv_heads, self.head_dim,
                                      self.dtype, block_size=block_size,
                                      num_blocks=num_blocks,
                                      prefix_share=prefix_share,
                                      prefix_registry=prefix_registry,
                                      kv_quant=kv_quant,
                                      prefix_radix=prefix_radix)
        # Attention seam (ISSUE 10): the sharded engine swaps in a
        # shard_map-wrapped kernel with the same signature as
        # decode_attention_paged; the default is the single-mesh helper.
        self._paged_attention = (paged_attention if paged_attention
                                 is not None else decode_attention_paged)
        self._paged_spec_attention = (
            paged_spec_attention if paged_spec_attention is not None
            else decode_attention_spec_paged)
        self._prefill_jit = jax.jit(self._prefill_fn)
        self._prefill_shared_jit = jax.jit(self._prefill_shared_fn,
                                           static_argnames=("kv_blocks",))
        self._decode_jit = jax.jit(self._decode_fn)
        self._profiled_buckets: set = set()   # prefill cost-registry dedup
        self.metrics = None    # engine installs its child registry here so
        # prefill cost gauges land next to the engine's observe() gauges

    # ------------------------------------------------------------ pure fns
    def _positionwise(self, layer, params, x):
        """Apply a non-attention layer per position: x (..., n_feat) is fed
        as a 1-timestep recurrent activation (B, n_feat, 1)."""
        out, _, _ = layer.forward(params, {}, x[..., None], train=False,
                                  rng=None, mask=None)
        return out[..., 0]

    def _head_logprobs(self, h):
        """Log-probabilities from the final (output) layer given its input
        activations h (S, n_feat): preout -> log_softmax, the numerically
        exact log of the layer's softmax output."""
        out_layer = self.layers[-1]
        p = self.params[-1]
        if isinstance(out_layer, RnnOutputLayer):
            z = h @ p["W"]
            if out_layer.has_bias:
                z = z + p["b"]
        elif hasattr(out_layer, "preout"):
            z = out_layer.preout(p, h)
        else:
            z = self._positionwise(out_layer, p, h)
            if out_layer.activation == Activation.SOFTMAX:
                return jnp.log(jnp.clip(z, 1e-30, None))
            return jax.nn.log_softmax(z, axis=-1)
        if out_layer.activation != Activation.SOFTMAX:
            z = out_layer._act(z)
        return jax.nn.log_softmax(z, axis=-1)

    def _prefill_fn(self, params, cache_state, x, slot, plen):
        """Prompt pass: x (n_in, T_pad) features of ONE request; writes every
        attention layer's k/v block into `slot`, sets lengths[slot] = plen,
        returns (new_cache_state, (vocab,) logprobs at position plen-1).
        Positions >= plen are padding — their k/v writes are harmless and
        their outputs are discarded."""
        xt = jnp.swapaxes(x, 0, 1).astype(self.dtype)       # (T_pad, n_in)
        li = 0
        for i, layer in enumerate(self.layers[:-1]):
            p = params[i]
            if isinstance(layer, SelfAttentionLayer):
                q, k, v = _attn_heads(layer, p, xt)
                cache_state = kv_cache.write_prefill(cache_state, li, slot,
                                                     k, v)
                li += 1
                out = _dense_causal_attention(layer, q, k, v)
                out = out.reshape(xt.shape[0], layer.n_out)
                out = layer._act(_out_proj(p, out))
                xt = out
            else:
                xt = self._positionwise(layer, p, xt)
        cache_state = kv_cache.set_length(cache_state, slot, plen)
        h_last = jax.lax.dynamic_index_in_dim(xt, plen - 1, axis=0,
                                              keepdims=False)
        return cache_state, self._head_logprobs(h_last[None])[0]

    def _prefill_shared_fn(self, params, cache_state, x, slot, plen,
                           shared_len, *, kv_blocks):
        """Shared-prefix prompt pass: x (n_in, Ts_pad) features of the
        SUFFIX only (logical positions [shared_len, plen)) — the prefix KV
        is already resident in blocks admission mapped shared. Scatters the
        suffix k/v through the block table, then attends each suffix query
        against the slot's first `kv_blocks` blocks gathered back through
        the table (shared prefix included). Padding rows (position >= plen)
        trash-route their writes and their outputs are discarded.
        `kv_blocks` is static (engine-bucketed) so the gathered length is
        ~plen, not max_len — the compute skipped for the shared positions
        is the whole point.

        Chunked prefill (ISSUE 9) reuses this pass verbatim with the chunk
        START in the shared_len seat and the chunk END in the plen seat:
        chunk i's queries attend the slot's earlier chunks through the same
        block-table gather, causal within the chunk, and set_length(end)
        makes the chunk visible to subsequent decode/chunk iterations."""
        xt = jnp.swapaxes(x, 0, 1).astype(self.dtype)       # (Ts_pad, n_in)
        Ts = xt.shape[0]
        bs = self.cache.block_size
        qpos = jnp.asarray(shared_len, jnp.int32) + jnp.arange(Ts,
                                                               dtype=jnp.int32)
        valid = qpos < plen
        L = kv_blocks * bs
        j = jnp.arange(L, dtype=jnp.int32)[None, :]          # (1, L)
        li = 0
        for i, layer in enumerate(self.layers[:-1]):
            p = params[i]
            if isinstance(layer, SelfAttentionLayer):
                q, k, v = _attn_heads(layer, p, xt)
                cache_state = kv_cache.write_positions(
                    cache_state, li, slot, qpos, valid, k, v)
                row = cache_state["block_tables"][
                    jnp.asarray(slot, jnp.int32)][:kv_blocks]
                kb = cache_state["k"][li, row]       # (kvb, bs, Hk, D)
                vb = cache_state["v"][li, row]
                if kv_cache.is_quantized(cache_state):
                    # dequantize per GATHERED block (slot view, never the
                    # pool) — same reference math as the paged oracle
                    kb = quant.kv_dequantize(
                        kb, cache_state["k_scale"][li, row])
                    vb = quant.kv_dequantize(
                        vb, cache_state["v_scale"][li, row])
                kl = kb.reshape(L, self.n_kv_heads, self.head_dim)
                vl = vb.reshape(L, self.n_kv_heads, self.head_dim)
                li += 1
                H, Dh = layer.n_heads, self.head_dim
                G = H // self.n_kv_heads
                acc = jnp.promote_types(q.dtype, jnp.float32)
                q4 = q.reshape(Ts, self.n_kv_heads, G, Dh)
                s = jnp.einsum("thgd,lhd->thgl", q4.astype(acc),
                               kl.astype(acc)) / np.sqrt(Dh)
                causal = j <= qpos[:, None]                  # (Ts, L)
                if layer.attention_window:
                    causal = causal & (qpos[:, None] - j
                                       < layer.attention_window)
                s = jnp.where(causal[:, None, None, :], s, NEG_INF)
                pattn = jax.nn.softmax(s, axis=-1)
                out = jnp.einsum("thgl,lhd->thgd", pattn, vl.astype(acc))
                out = out.reshape(Ts, layer.n_out).astype(self.dtype)
                xt = layer._act(_out_proj(p, out))
            else:
                xt = self._positionwise(layer, p, xt)
        cache_state = kv_cache.set_length(cache_state, slot, plen)
        h_last = jax.lax.dynamic_index_in_dim(
            xt, plen - 1 - shared_len, axis=0, keepdims=False)
        return cache_state, self._head_logprobs(h_last[None])[0]

    def _decode_fn(self, params, cache_state, x, active):
        """One decode iteration for ALL slots: x (S, n_in) current-token
        features, active (S,) bool. Appends each attention layer's k/v at
        the slot's current position, attends the single query against the
        cache, advances lengths on active slots, returns
        (new_cache_state, (S, vocab) logprobs)."""
        h = x.astype(self.dtype)                            # (S, n_in)
        pos = cache_state["lengths"]                        # pre-advance
        li = 0
        for i, layer in enumerate(self.layers[:-1]):
            p = params[i]
            if isinstance(layer, SelfAttentionLayer):
                q, k_t, v_t = _attn_heads(layer, p, h)      # (S, H/Hk, Dh)
                cache_state = kv_cache.append_token(cache_state, li, k_t,
                                                    v_t, active)
                qkw = {} if not kv_cache.is_quantized(cache_state) else {
                    "k_scale": cache_state["k_scale"][li],
                    "v_scale": cache_state["v_scale"][li]}
                out = self._paged_attention(
                    q, cache_state["k"][li], cache_state["v"][li],
                    cache_state["block_tables"],
                    pos + 1, 1.0 / np.sqrt(self.head_dim),
                    layer.attention_window, **qkw)
                li += 1
                out = out.reshape(h.shape[0], layer.n_out)
                h = layer._act(_out_proj(p, out))
            else:
                h = self._positionwise(layer, p, h)
        cache_state = kv_cache.advance_lengths(cache_state, active)
        return cache_state, self._head_logprobs(h)

    def _spec_decode_fn(self, params, cache_state, x, active, draft_len):
        """One SPECULATIVE decode iteration (ISSUE 11) for all slots:
        x (S, Q, n_in) features of [last committed token, draft 0, ...,
        draft Q-2], active (S,) bool, draft_len (S,) int32 in [0, Q-1].
        Row i's k/v land at logical position lengths + i (trash-routed for
        inactive slots and rows past the slot's draft length — a short
        draft's padding can never dirty live blocks), and all Q queries are
        verified against the paged cache in ONE multi-query attention
        dispatch per layer. Returns (new_cache_state, (S, Q, vocab)
        logprobs); row i is the target distribution for the token AFTER
        position lengths + i - 1. Does NOT move `lengths` — the engine
        commits the accepted count afterwards (set-length semantics), which
        is the whole rollback story: rejected rows simply stay invisible.
        draft_len == 0 everywhere degenerates to `_decode_fn` semantics
        with Q - 1 dead verify lanes."""
        S, Q = x.shape[0], x.shape[1]
        h = x.astype(self.dtype)                            # (S, Q, n_in)
        pos = cache_state["lengths"]                        # pre-commit
        i = jnp.arange(Q, dtype=jnp.int32)[None, :]
        positions = pos[:, None] + i                        # (S, Q)
        valid = active[:, None] & (i <= draft_len[:, None])
        li = 0
        for idx, layer in enumerate(self.layers[:-1]):
            p = params[idx]
            if isinstance(layer, SelfAttentionLayer):
                q, k_t, v_t = _attn_heads(layer, p, h)      # (S, Q, ., Dh)
                cache_state = kv_cache.append_tokens(
                    cache_state, li, k_t, v_t, positions, valid)
                qkw = {} if not kv_cache.is_quantized(cache_state) else {
                    "k_scale": cache_state["k_scale"][li],
                    "v_scale": cache_state["v_scale"][li]}
                out = self._paged_spec_attention(
                    q, cache_state["k"][li], cache_state["v"][li],
                    cache_state["block_tables"],
                    pos + 1, 1.0 / np.sqrt(self.head_dim),
                    layer.attention_window, **qkw)
                li += 1
                out = out.reshape(S, Q, layer.n_out)
                h = layer._act(_out_proj(p, out))
            else:
                h = self._positionwise(
                    layer, p, h.reshape(S * Q, -1)).reshape(S, Q, -1)
        lp = self._head_logprobs(h.reshape(S * Q, -1))
        return cache_state, lp.reshape(S, Q, -1)

    # ------------------------------------------------------- stateful API
    def prefill(self, slot: int, x) -> jnp.ndarray:
        """Write a prompt into `slot`; returns the (vocab,) logprobs of the
        next-token distribution. x: (n_in, T) features. T is padded up to
        the next power of two so ragged prompts hit a bounded set of
        compiled shapes (ParallelInference._run's bucketing)."""
        x = jnp.asarray(x, self.dtype)
        T = x.shape[1]
        if T < 1 or T >= self.cache.max_len:
            raise ValueError(f"prompt length {T} outside [1, max_len)")
        Tp = self.prefill_bucket(T)
        if Tp != T:
            x = jnp.pad(x, ((0, 0), (0, Tp - T)))
        slot_a = jnp.asarray(slot, jnp.int32)
        plen_a = jnp.asarray(T, jnp.int32)
        # profiler cost registry (ISSUE 6): file this bucket's XLA
        # cost_analysis once per compiled shape when profiling is on — AOT
        # lower/compile, nothing executes, no buffer donated
        from deeplearning4j_tpu.telemetry import profiler
        if profiler.enabled() and Tp not in self._profiled_buckets:
            self._profiled_buckets.add(Tp)
            try:
                profiler.register(f"prefill_b{Tp}", self._prefill_jit,
                                  (self.params, self.cache.state, x,
                                   slot_a, plen_a),
                                  meta={"bucket": Tp},
                                  registry=self.metrics)
            except Exception:
                pass
        self.cache.state, logprobs = self._prefill_jit(
            self.params, self.cache.state, x, slot_a, plen_a)
        return logprobs

    def prefill_bucket(self, plen: int) -> int:
        """Padded prompt length for an unshared prefill: next power of two,
        rounded UP to KV-block granularity (paged prefill scatters WHOLE
        blocks; writes past the slot's reservation trash-route), capped at
        max_len. The engine uses this as the compile-miss key — it must
        match the shape `prefill` actually compiles."""
        Tp = min(self.cache.max_len, 1 << max(0, (plen - 1)).bit_length())
        bs = self.cache.block_size
        return min(self.cache.max_len, -(-Tp // bs) * bs)

    def shared_buckets(self, plen: int, shared_len: int):
        """(suffix bucket Ts_pad, static gathered-block count) for a
        shared-prefix prefill — the engine uses this pair as the compile
        key for jit-compile-miss attribution. Both dimensions bucket to
        powers of two (capped at max_len / blocks_per_seq) so ragged
        suffixes hit a bounded set of compiled shapes."""
        Ts = plen - shared_len
        Tsp = min(self.cache.max_len, 1 << max(0, (Ts - 1)).bit_length())
        nb = -(-plen // self.cache.block_size)       # blocks holding prompt
        kvb = min(self.cache.blocks_per_seq,
                  1 << max(0, (nb - 1)).bit_length())
        return Tsp, kvb

    def prefill_shared(self, slot: int, x, plen: int,
                       shared_len: int) -> jnp.ndarray:
        """Write a prompt whose first `shared_len` positions are already
        resident (admission mapped them shared); x: (n_in, Ts) features of
        the SUFFIX tokens only, Ts = plen - shared_len. Returns the
        (vocab,) next-token logprobs — identical to what prefill() would
        return for the full prompt, minus the shared positions' compute."""
        x = jnp.asarray(x, self.dtype)
        Ts = x.shape[1]
        if Ts != plen - shared_len or Ts < 1 or shared_len < 1:
            raise ValueError(f"bad shared prefill: plen={plen}, "
                             f"shared_len={shared_len}, suffix={Ts}")
        Tsp, kvb = self.shared_buckets(plen, shared_len)
        if Tsp != Ts:
            x = jnp.pad(x, ((0, 0), (0, Tsp - Ts)))
        slot_a = jnp.asarray(slot, jnp.int32)
        plen_a = jnp.asarray(plen, jnp.int32)
        shared_a = jnp.asarray(shared_len, jnp.int32)
        from deeplearning4j_tpu.telemetry import profiler
        key = ("shared", Tsp, kvb)
        if profiler.enabled() and key not in self._profiled_buckets:
            self._profiled_buckets.add(key)
            try:
                profiler.register(
                    f"prefill_shared_b{Tsp}k{kvb}", self._prefill_shared_jit,
                    (self.params, self.cache.state, x, slot_a, plen_a,
                     shared_a),
                    kwargs={"kv_blocks": kvb},
                    meta={"bucket": Tsp, "kv_blocks": kvb},
                    registry=self.metrics)
            except Exception:
                pass
        self.cache.state, logprobs = self._prefill_shared_jit(
            self.params, self.cache.state, x, slot_a, plen_a, shared_a,
            kv_blocks=kvb)
        return logprobs

    def prefill_chunk(self, slot: int, x, start: int,
                      end: int) -> jnp.ndarray:
        """One chunk of an incremental prefill: x (n_in, Tc) features of
        prompt positions [start, end), Tc = end - start. Writes the chunk's
        k/v through the block table, attends each chunk query against the
        slot's earlier resident positions (prior chunks and any shared
        prefix) plus the causal part of the chunk itself, and advances
        lengths[slot] to `end`. Returns the (vocab,) logprobs at position
        end-1 — meaningful only on the final chunk (end == plen), where it
        equals what a monolithic prefill() would have returned.

        This is `_prefill_shared_fn` with (start, end) in the
        (shared_len, plen) seats — same jit, same compile cache as
        prefix-shared prefill."""
        x = jnp.asarray(x, self.dtype)
        Tc = x.shape[1]
        if Tc != end - start or Tc < 1 or start < 0 \
                or end > self.cache.max_len:
            raise ValueError(f"bad prefill chunk: start={start}, "
                             f"end={end}, chunk={Tc}")
        Tsp, kvb = self.shared_buckets(end, start)
        if Tsp != Tc:
            x = jnp.pad(x, ((0, 0), (0, Tsp - Tc)))
        slot_a = jnp.asarray(slot, jnp.int32)
        end_a = jnp.asarray(end, jnp.int32)
        start_a = jnp.asarray(start, jnp.int32)
        from deeplearning4j_tpu.telemetry import profiler
        key = ("shared", Tsp, kvb)                  # same compiled shape
        if profiler.enabled() and key not in self._profiled_buckets:
            self._profiled_buckets.add(key)
            try:
                profiler.register(
                    f"prefill_shared_b{Tsp}k{kvb}", self._prefill_shared_jit,
                    (self.params, self.cache.state, x, slot_a, end_a,
                     start_a),
                    kwargs={"kv_blocks": kvb},
                    meta={"bucket": Tsp, "kv_blocks": kvb},
                    registry=self.metrics)
            except Exception:
                pass
        self.cache.state, logprobs = self._prefill_shared_jit(
            self.params, self.cache.state, x, slot_a, end_a, start_a,
            kv_blocks=kvb)
        return logprobs

    def decode_step(self, x, active) -> jnp.ndarray:
        """One cached decode iteration over all slots; returns (S, vocab)
        logprobs. Advances lengths on active slots."""
        self.cache.state, logprobs = self._decode_jit(
            self.params, self.cache.state, jnp.asarray(x, self.dtype),
            jnp.asarray(active, bool))
        return logprobs


def _extract_stack(net) -> Tuple[List, List]:
    """(layers, params_tree) for a MultiLayerNetwork or a linear-chain
    ComputationGraph. Anything with branching/merging or preprocessors has
    no incremental path yet — fail loudly."""
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
    if isinstance(net, MultiLayerNetwork):
        if getattr(net.conf, "preprocessors", None):
            raise NotImplementedError(
                "StackDecoder does not support input preprocessors")
        if not net._initialized:
            raise RuntimeError("Call net.init() before building a decoder")
        return net.layers, net.params_tree
    from deeplearning4j_tpu.nn.graph.computation_graph import ComputationGraph
    if isinstance(net, ComputationGraph):
        if not net._initialized:
            raise RuntimeError("Call net.init() before building a decoder")
        conf = net.conf
        order = [n for n in conf.topo_order]
        for name in order:
            node = conf.nodes[name]
            if node.kind != "layer":
                raise NotImplementedError(
                    f"graph vertex {name!r} is not a layer — only linear "
                    "layer chains decode incrementally")
            if len(node.inputs) != 1 or node.preprocessor is not None:
                raise NotImplementedError(
                    f"graph node {name!r} is not a single-input chain link")
        # topo order == layer_names order for a chain; params align with it
        return net.layers, net.params_tree
    raise TypeError(f"unsupported model type {type(net).__name__}")


def one_hot_embedder(n_in: int, dtype=jnp.float32) -> Callable:
    """Default token->features map: one-hot into the stack's n_in (the
    framework's char-RNN convention). Jit-safe."""
    def embed(tokens):
        return jax.nn.one_hot(tokens, n_in, dtype=dtype)
    return embed
