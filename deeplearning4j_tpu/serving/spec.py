"""Draft-model-free speculative drafting: per-slot n-gram suffix lookup.

ISSUE 11 (prompt lookup / n-gram drafting, Saxena 2023; speculative
decoding, Leviathan et al. 2023 — see PAPERS.md). Decode is
memory-bandwidth-bound: every token re-reads the slot's whole resident KV
(PERF.md cost model), so verifying k drafted tokens in ONE kernel pass
buys up to ~(k+1)x tokens/step at essentially unchanged bytes moved. The
cheapest useful draft source needs no model at all: natural-language and
code generations repeat their own prompt and history (quotes, identifiers,
boilerplate), so the continuation of the current suffix n-gram's most
recent earlier occurrence is a strong proposal on repetitive text and a
harmless one elsewhere (a wrong draft costs only the wasted verify lanes —
rollback is `set_length`, see serving/kv_cache.py).

`NgramDraftIndex` holds, per slot, the token history (prompt + committed
tokens — both already host-visible at the scheduling boundary, so drafting
adds ZERO device syncs) and a bounded map from recent n-grams to their
occurrence positions. `propose(slot, k)` matches the longest suffix gram
(n = max_ngram..min_ngram) that recurs earlier WITH a continuation and
returns up to k continuation tokens. Pure host-side dict/list work, O(1)
per committed token; the per-gram position list is capped so adversarially
repetitive histories cannot grow the index superlinearly.

Env knobs (read by the engine):
- `DL4J_TPU_SPEC_DECODE=1` enables speculative decode (default off);
- `DL4J_TPU_SPEC_DRAFT`    max draft tokens per step (default 4);
- `DL4J_TPU_SPEC_NGRAM`    longest suffix gram to match (default 3).

Determinism contract (ISSUE 20): proposals are a pure function of the
committed token history — no wall clock, no RNG (the
test_sync_discipline determinism scan pins this) — so a replayed run
re-derives identical drafts from identical histories; the engine still
journals per-iteration draft/accept/commit counts ("spec" records) so
the divergence localizer can pinpoint a drafting change directly.
"""
from __future__ import annotations

import os
from typing import Dict, List, Optional, Sequence, Tuple

DEFAULT_DRAFT = 4
DEFAULT_NGRAM = 3


def resolve_spec_decode(spec_decode: Optional[bool] = None) -> bool:
    """Engine-level enable: explicit argument wins, else the env knob."""
    if spec_decode is not None:
        return bool(spec_decode)
    return os.environ.get("DL4J_TPU_SPEC_DECODE", "0") == "1"


def resolve_spec_draft(spec_draft: Optional[int] = None) -> int:
    """Max draft tokens proposed per spec step (>= 1)."""
    if spec_draft is None:
        spec_draft = int(os.environ.get("DL4J_TPU_SPEC_DRAFT",
                                        str(DEFAULT_DRAFT)))
    return max(1, int(spec_draft))


class NgramDraftIndex:
    """Per-slot suffix-match index over host-visible token history.

    max_ngram/min_ngram: the suffix gram lengths tried, longest first
    (longer matches are more specific, so their continuations accept
    more often). positions_per_gram: retention cap per gram — proposal
    wants the MOST RECENT occurrence that still has a continuation, so a
    short most-recent-first list suffices and bounds memory."""

    def __init__(self, max_ngram: Optional[int] = None, min_ngram: int = 1,
                 positions_per_gram: int = 4):
        if max_ngram is None:
            max_ngram = int(os.environ.get("DL4J_TPU_SPEC_NGRAM",
                                           str(DEFAULT_NGRAM)))
        self.max_ngram = max(1, int(max_ngram))
        self.min_ngram = max(1, min(int(min_ngram), self.max_ngram))
        self.positions_per_gram = max(1, int(positions_per_gram))
        self._tokens: Dict[int, List[int]] = {}
        # slot -> gram tuple -> start positions, most recent first
        self._grams: Dict[int, Dict[Tuple[int, ...], List[int]]] = {}

    # ------------------------------------------------------------ lifecycle
    def reset(self, slot: int, tokens: Sequence[int]) -> None:
        """(Re)build the slot's index from its prompt (admission time)."""
        self._tokens[slot] = []
        self._grams[slot] = {}
        self.extend(slot, tokens)

    def drop(self, slot: int) -> None:
        """Forget a retired slot's history."""
        self._tokens.pop(slot, None)
        self._grams.pop(slot, None)

    def extend(self, slot: int, tokens: Sequence[int]) -> None:
        """Append committed tokens (prompt at reset, then each spec/decode
        readback), indexing every gram ending at each new position. Token
        values arrive from the per-iteration scheduler readback the engine
        already pays for — the index itself never touches the device."""
        if slot not in self._tokens:
            self._tokens[slot] = []
            self._grams[slot] = {}
        hist = self._tokens[slot]
        grams = self._grams[slot]
        for t in tokens:
            # sync-ok: host-side draft index — `t` is a host int from the
            # scheduler's existing per-iteration readback, not a device sync
            hist.append(int(t))
            p_end = len(hist)
            for n in range(self.min_ngram, self.max_ngram + 1):
                if p_end < n:
                    break
                g = tuple(hist[p_end - n:p_end])
                lst = grams.setdefault(g, [])
                lst.insert(0, p_end - n)
                del lst[self.positions_per_gram:]

    # ------------------------------------------------------------- proposal
    def history_len(self, slot: int) -> int:
        return len(self._tokens.get(slot, ()))

    def propose(self, slot: int, max_tokens: int) -> List[int]:
        """Draft up to `max_tokens` continuation tokens for the slot's
        current suffix: longest gram first, most recent occurrence that is
        NOT the suffix itself (it must have at least one following token).
        Returns [] when nothing matches — the engine then runs the slot as
        a plain decode row (draft_len 0) at zero extra cost."""
        hist = self._tokens.get(slot)
        if not hist or max_tokens < 1:
            return []
        T = len(hist)
        grams = self._grams[slot]
        for n in range(min(self.max_ngram, T), self.min_ngram - 1, -1):
            suffix = tuple(hist[T - n:T])
            for start in grams.get(suffix, ()):
                cont = start + n
                if cont >= T:
                    continue            # the suffix occurrence itself
                return hist[cont:cont + max_tokens]
        return []
