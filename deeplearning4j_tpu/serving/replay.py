"""Deterministic replay of a journaled serving run (ISSUE 20).

The decision journal (telemetry/journal.py) records every
nondeterministic input the scheduler consumed: arrivals with their
submit tick, routing choices, admission verdicts with eviction plans,
preempt modes (the one lifecycle decision fed by measured bandwidth),
queue sheds and slot timeouts (the two wall-deadline predicates), and
transfer destinations. Everything else the engine does — block
reservation plans, chunked-prefill splitting, spec-decode drafts and
accepts, radix TTL sweeps by tick — is a deterministic function of
engine state once those inputs are pinned, so it is journaled for
VERIFICATION (divergence checking) but recomputed live on replay.

``Replayer`` reconstructs a run on a FRESH engine or group built with
the same configuration: arrivals are re-submitted when the allocator
tick clock reaches their recorded submit tick (wall clock is out of the
loop entirely), recorded verdicts are forced through two seams —

- ``ReplayPolicy`` wraps the run's SchedulingPolicy and answers
  route/admit/transfer from the journal instead of consulting the inner
  policy's heuristics;
- an ``EngineDirector`` installed as ``engine._replay`` replaces the
  wall-deadline shed/expire predicates and the measured-bandwidth
  preempt-mode choice with the journaled outcomes.

The replayed engine journals its own decision stream; comparing it
against the recording (``localize_divergence``) verifies per-iteration
pool-byte conservation and host-sync counts, and — when a live run
really does diverge (an injected policy change, a code regression) —
binary-searches the first iteration whose cumulative decision digest
differs, then reports the first mismatching record pair.

Replay requires synchronous stepping (``overlap=False`` — overlapped
dispatch consumes sampler keys unconditionally) and, for groups, serial
stepping (``serial_step=True``) so cross-replica transfer adoption
order is a function of replica index, not thread scheduling.

Sync discipline: pure host bookkeeping — no jax import, no device
access, no wall-clock reads (tests/test_sync_discipline.py scans this
module).
"""
from __future__ import annotations

import hashlib
import json
from collections import deque
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence

from deeplearning4j_tpu.telemetry.journal import (DecisionJournal,
                                                  canonical)
from deeplearning4j_tpu.serving.policy import (AdmissionDecision,
                                               SchedulingPolicy)

__all__ = ["ReplayMismatch", "EngineDirector", "ReplayPolicy",
           "Replayer", "ReplayReport", "localize_divergence",
           "replay_incident"]

GROUP_REPLICA = -1      # journal.replica of the group-level journal


class ReplayMismatch(AssertionError):
    """The live engine asked for a decision the journal cannot supply —
    the run diverged from the recording before this consult."""


class EngineDirector:
    """Journaled outcomes for ONE engine's in-engine decision points.

    Installed as ``engine._replay``; the engine consults it instead of
    the two wall-deadline predicates (queue shed, slot timeout) and the
    measured-bandwidth preempt-mode choice. Consults are matched in
    journal order — iteration-level scheduling guarantees the replayed
    engine asks in exactly the recorded sequence as long as its state
    has not diverged."""

    def __init__(self, records: Sequence[dict]):
        self.admissions = deque(r for r in records
                                if r["kind"] == "admission")
        self.sheds = deque(r for r in records if r["kind"] == "shed")
        self.expires = deque(r for r in records if r["kind"] == "expire")
        self.preempts = deque(r for r in records
                              if r["kind"] == "preempt")

    def should_shed(self, req_id: int, tick: int) -> bool:
        q = self.sheds
        if q and q[0]["tick"] == tick and q[0]["req"] == req_id:
            q.popleft()
            return True
        return False

    def should_expire(self, req_id: int, tick: int) -> bool:
        q = self.expires
        if q and q[0]["tick"] == tick and q[0]["req"] == req_id:
            q.popleft()
            return True
        return False

    def preempt_mode(self, req_id: int) -> str:
        if not self.preempts:
            raise ReplayMismatch(
                f"preemption of req {req_id} not in journal")
        rec = self.preempts.popleft()
        if rec["req"] != req_id:
            raise ReplayMismatch(
                f"preempt order diverged: journal has req {rec['req']} "
                f"(seq {rec['seq']}), live engine preempts req {req_id}")
        return rec["mode"]

    def next_admission(self, req_id: int) -> dict:
        if not self.admissions:
            raise ReplayMismatch(
                f"admission consult for req {req_id} past end of journal")
        rec = self.admissions.popleft()
        if rec["req"] != req_id:
            raise ReplayMismatch(
                f"admission order diverged: journal has req {rec['req']} "
                f"(seq {rec['seq']}), live engine consults for "
                f"req {req_id}")
        return rec


class ReplayPolicy(SchedulingPolicy):
    """SchedulingPolicy that answers route/admit/transfer from the
    journal. bind/role/evict delegate to the recorded run's policy (the
    TTL sweep is tick-deterministic; roles shape engine construction),
    and SLO/TTL attributes mirror the inner policy because the engine
    reads them for budget accounting."""

    def __init__(self, inner: SchedulingPolicy, *,
                 routes: Sequence[dict] = (),
                 transfers: Sequence[dict] = (),
                 directors: Optional[Dict[Optional[int],
                                          EngineDirector]] = None):
        self.inner = inner
        self.slo = getattr(inner, "slo", None)
        self.ttl = getattr(inner, "ttl", None)
        self.ttl_s = getattr(inner, "ttl_s", None)
        self.n_replicas = getattr(inner, "n_replicas", 1)
        self._routes = deque(routes)
        self._transfers = deque(transfers)
        self._directors = dict(directors or {})

    def bind(self, n_replicas: int) -> "ReplayPolicy":
        self.inner.bind(n_replicas)
        self.n_replicas = int(n_replicas)
        return self

    def role(self, replica: int) -> str:
        return self.inner.role(replica)

    def evict(self, pressure_view: dict) -> int:
        return self.inner.evict(pressure_view)

    def _director(self, replica) -> EngineDirector:
        d = self._directors.get(replica)
        if d is None and len(self._directors) == 1:
            d = next(iter(self._directors.values()))
        if d is None:
            raise ReplayMismatch(
                f"no director for replica {replica!r} "
                f"(have {sorted(map(str, self._directors))})")
        return d

    # ---------------------------------------------------- decision points
    def route(self, request, fleet_view: dict):
        if not self._routes:
            raise ReplayMismatch("route consult past end of journal")
        rec = self._routes.popleft()
        return rec["dst"], rec["reason"]

    def admit(self, request, pool_view: dict) -> AdmissionDecision:
        rec = self._director(pool_view.get("replica")).next_admission(
            pool_view["req_id"])
        if rec["verdict"] == "preempt":
            plan = {"evicted": [dict(v) for v in rec["victims"]],
                    "satisfies": True}
            return AdmissionDecision.preempt(plan)
        hint = {"reclaimable_bytes": rec.get("reclaimable_bytes", 0),
                "retry_after_s": rec.get("retry_after_s", 0.0)}
        return AdmissionDecision.deny(hint)

    def transfer(self, finished_prefill_view: dict) -> Optional[int]:
        if not self._transfers:
            raise ReplayMismatch("transfer consult past end of journal")
        return self._transfers.popleft()["dst"]


@dataclass
class ReplayReport:
    """Outcome of one replay: results in re-submission order, the live
    journal stream, and the recorded-vs-live divergence (None = the
    replay reproduced every decision)."""
    results: List[object] = field(default_factory=list)
    records: List[dict] = field(default_factory=list)
    stats: dict = field(default_factory=dict)
    divergence: Optional[dict] = None

    @property
    def token_streams(self) -> List[List[int]]:
        return [r.tokens for r in self.results]


def _request_from(rec: dict):
    from deeplearning4j_tpu.serving.engine import Request
    return Request(tokens=list(rec["tokens"]),
                   max_new_tokens=rec["max_new"],
                   temperature=rec.get("temp", 0.0),
                   eos_id=rec.get("eos"),
                   timeout_s=rec.get("timeout_s"),
                   session_id=rec.get("session"),
                   turn_idx=rec.get("turn"))


def _ensure_journal(engine) -> None:
    if engine.journal is None:
        engine.journal = DecisionJournal(replica=engine.replica_id)


class Replayer:
    """Re-run a journaled decision stream on a fresh engine or group.

    The caller provides the target built with the SAME model, seed and
    engine knobs as the recording (the journal pins decisions, not
    configuration). ``replay()`` drives a single engine; for a
    ShardedServingGroup use ``replay_group()`` with the merged fleet
    records (``group.fleet_journal()``)."""

    def __init__(self, records: Sequence[dict]):
        self.records = [dict(r) for r in records]

    # ------------------------------------------------------ single engine
    def replay(self, engine) -> ReplayReport:
        recs = self.records
        director = EngineDirector(recs)
        engine._replay = director
        engine.policy = ReplayPolicy(
            engine.policy, directors={engine.replica_id: director})
        _ensure_journal(engine)
        arrivals = deque(sorted(
            (r for r in recs if r["kind"] == "arrival"),
            key=lambda r: r["seq"]))
        futs = []
        busy = True
        while arrivals or busy:
            clock = engine.decoder.cache.allocator.clock
            while arrivals and arrivals[0]["tick"] <= clock:
                futs.append(engine.submit(
                    _request_from(arrivals.popleft())))
            busy = engine.step()
        results = [f.get(timeout=60.0) for f in futs]
        live = engine.journal.records()
        return ReplayReport(results=results, records=live,
                            stats=engine.stats(),
                            divergence=localize_divergence(recs, live))

    # --------------------------------------------------------------- group
    def replay_group(self, group) -> ReplayReport:
        recs = self.records
        group_recs = [r for r in recs
                      if r.get("replica", GROUP_REPLICA) == GROUP_REPLICA]
        routes = [r for r in group_recs if r["kind"] == "route"]
        transfers = [r for r in group_recs if r["kind"] == "transfer"]
        by_rep: Dict[int, List[dict]] = {}
        for r in recs:
            rep = r.get("replica", GROUP_REPLICA)
            if rep != GROUP_REPLICA:
                by_rep.setdefault(rep, []).append(r)
        directors = {rep: EngineDirector(rs)
                     for rep, rs in by_rep.items()}
        rp = ReplayPolicy(group.policy, routes=routes,
                          transfers=transfers, directors=directors)
        group.policy = rp
        if group.journal is None:
            # the replayed group journals its own route/transfer stream
            # (through ReplayPolicy's forced verdicts) so the live fleet
            # merge is comparable record-for-record with the recording
            group.journal = DecisionJournal(replica=GROUP_REPLICA)
        for eng in group.engines:
            eng.policy = rp
            eng._replay = directors.setdefault(
                eng.replica_id, EngineDirector(()))
            _ensure_journal(eng)
        # pair each route record with the routed replica's next arrival:
        # group.submit holds the group lock across route+submit, so the
        # per-replica arrival order in seq order IS the route order
        arr_by_rep = {rep: deque(sorted(
            (r for r in rs if r["kind"] == "arrival"),
            key=lambda r: r["seq"])) for rep, rs in by_rep.items()}
        pending = deque()
        for rt in routes:
            q = arr_by_rep.get(rt["dst"])
            if not q:
                raise ReplayMismatch(
                    f"route to replica {rt['dst']} (seq {rt['seq']}) has "
                    "no matching arrival record")
            pending.append(q.popleft())
        futs = []
        busy = True
        while pending or busy:
            while pending:
                head = pending[0]
                eng = group.engines[head["replica"]]
                if eng.decoder.cache.allocator.clock < head["tick"]:
                    break
                futs.append(group.submit(
                    _request_from(pending.popleft())))
            busy = group.step()
        results = [f.get(timeout=60.0) for f in futs]
        live = group.fleet_journal()
        return ReplayReport(results=results, records=live,
                            stats=group.stats(),
                            divergence=localize_divergence(recs, live))


# ---------------------------------------------------- divergence localizer
def _digest_by_tick(records: Sequence[dict]) -> Dict[int, str]:
    """Cumulative canonical-record digest at the END of each tick: a
    prefix fingerprint the localizer can binary-search."""
    out: Dict[int, str] = {}
    h = hashlib.sha1()
    last = None
    for rec in records:
        t = rec["tick"]
        if last is not None and t != last:
            out[last] = h.hexdigest()
        h.update(json.dumps(canonical(rec), sort_keys=True,
                            separators=(",", ":")).encode())
        last = t
    if last is not None:
        out[last] = h.hexdigest()
    return out


def localize_divergence(recorded: Sequence[dict],
                        live: Sequence[dict], *,
                        snapshot_fn=None) -> Optional[dict]:
    """First iteration where the live decision stream departs from the
    journal, or None when the streams agree record-for-record.

    Binary-searches cumulative per-tick digests for the first tick whose
    prefix fingerprint differs (a missing or extra record surfaces at
    the tick it occurred), then scans that prefix pairwise for the first
    mismatching record. The report carries both records, the per-tick
    "iter" pool rows on each side (pool-byte conservation + host-sync
    forensics), and — when the caller passes ``snapshot_fn`` (e.g. the
    live engine's ``kv_pool_snapshot``) — the KV-observatory snapshot
    at the divergent tick."""
    rec_d = _digest_by_tick(recorded)
    live_d = _digest_by_tick(live)
    ticks = sorted(set(rec_d) | set(live_d))
    if not ticks:
        return None

    def _at(dig: Dict[int, str], order: List[int], t: int) -> str:
        # cumulative digest carried forward over ticks with no records
        best = ""
        for tt in order:
            if tt > t:
                break
            if tt in dig:
                best = dig[tt]
        return best

    lo, hi = 0, len(ticks) - 1
    if _at(rec_d, ticks, ticks[hi]) == _at(live_d, ticks, ticks[hi]):
        if len(recorded) == len(live):
            return None
        bad_tick = ticks[hi]            # same digests, trailing extras
    else:
        while lo < hi:                  # first tick whose prefix differs
            mid = (lo + hi) // 2
            if _at(rec_d, ticks, ticks[mid]) == \
                    _at(live_d, ticks, ticks[mid]):
                lo = mid + 1
            else:
                hi = mid
        bad_tick = ticks[lo]
    rec_pre = [r for r in recorded if r["tick"] <= bad_tick]
    live_pre = [r for r in live if r["tick"] <= bad_tick]
    idx, rec_bad, live_bad = None, None, None
    for i in range(max(len(rec_pre), len(live_pre))):
        a = rec_pre[i] if i < len(rec_pre) else None
        b = live_pre[i] if i < len(live_pre) else None
        if (a is None or b is None
                or canonical(a) != canonical(b)):
            idx, rec_bad, live_bad = i, a, b
            break
    if idx is None:
        return None

    def _iter_rows(stream):
        return [r for r in stream
                if r["kind"] == "iter" and r["tick"] == bad_tick]

    return {
        "tick": bad_tick,
        "index": idx,
        "recorded": rec_bad,
        "live": live_bad,
        "recorded_iter": _iter_rows(recorded),
        "live_iter": _iter_rows(live),
        "snapshot": snapshot_fn() if snapshot_fn is not None else None,
    }


# ------------------------------------------------------- incident replay
def replay_incident(bundle_dir: str, engine) -> ReplayReport:
    """Replay an incident bundle's frozen journal tail
    (``journal_tail.jsonl``) on a fresh engine built with the recorded
    run's configuration — the runnable-regression form of an alert."""
    import os
    records = DecisionJournal.load(
        os.path.join(bundle_dir, "journal_tail.jsonl"))
    return Replayer(records).replay(engine)
