"""Disk tier of the hierarchical KV storage ladder (ISSUE 18).

The lifecycle manager's `HostBlockPool` (serving/lifecycle.py) is a
capacity-capped host-RAM shelf for swapped-out KV block bytes; below it
sits this `DiskBlockPool` — a spill DIRECTORY holding one npz file per
entry, so cold sessions and cold prefix blocks survive host-pool
pressure at ~zero HBM and ~zero host-RAM cost:

    HBM (paged KVCache)  --gather/scatter-->  HostBlockPool (RAM)
                                                   |  demote on pressure
                                                   v  promote on swap-in
                                              DiskBlockPool (npz files)

Two key namespaces share one pool: swap entries (int request ids, files
``swap_<id>.npz``) and prefix-store entries (sha1 chain digests, files
``pfx_<hex>.npz``) — `PersistentPrefixStore` spills through the SAME
tier the lifecycle manager demotes into, so one byte cap governs
everything below RAM.

Crash safety mirrors the PR 16 npz store: every write lands in a
sibling ``.tmp`` file and `os.replace`s into place (kill mid-demotion
leaves the previous entry intact, never a truncated zip at the
canonical path); construction over an existing spill directory sweeps
leftover ``.tmp`` files, drops stale ``swap_`` entries (request ids are
process-scoped — a dead engine's swaps are unrestorable), and ingests
``pfx_`` entries tolerantly (a corrupt or truncated file warns and is
ignored, not fatal); `fetch()` of an entry whose file rotted after the
put warns and raises ``KeyError`` so callers treat it as a miss (the
engine falls back to recompute — losing a spill costs compute, never
correctness).

Sync discipline: `put()` materializes lazy device arrays before the npz
write — only ever reached on PRESSURE paths (host-pool demotion, store
spill-through), annotated and counted by the callers like every other
pressure-path sync.

Env knobs: ``DL4J_TPU_KV_DISK`` (spill directory; setting it enables
the tier), ``DL4J_TPU_KV_DISK_BYTES`` (byte cap, default 1 GiB).
"""
from __future__ import annotations

import os
import warnings
import zipfile
from collections import OrderedDict
from typing import Optional, Tuple

import numpy as np

__all__ = ["DiskBlockPool", "resolve_disk_pool", "DEFAULT_DISK_BYTES"]

#: Default spill-directory byte cap (DL4J_TPU_KV_DISK_BYTES overrides).
DEFAULT_DISK_BYTES = 1 << 30

#: Exception set a rotten npz read can raise — identical to the
#: PersistentPrefixStore.load tolerance (PR 16).
_READ_ERRORS = (zipfile.BadZipFile, ValueError, OSError, EOFError, KeyError)


def _fname(key) -> str:
    """Collision-free filename per key namespace: request ids (ints, or
    caller-supplied strings — hex-encoded to stay filesystem-safe) are
    swap entries, bytes digests are prefix-store entries."""
    if isinstance(key, bytes):
        return f"pfx_{key.hex()}.npz"
    if isinstance(key, str):
        return f"swap_x{key.encode('utf-8').hex()}.npz"
    return f"swap_{int(key)}.npz"


class DiskBlockPool:
    """Byte-capped spill directory of KV block bytes, one npz per entry.

    LRU over entries (an `OrderedDict` of key -> file bytes); `put()`
    evicts cold files to stay under the cap. Accounting uses ACTUAL
    file sizes (what the disk holds), not the nominal device bytes the
    host pool charges — the two differ by npz framing and, on quantized
    pools, by the scale arrays riding along."""

    def __init__(self, directory: str,
                 capacity_bytes: int = DEFAULT_DISK_BYTES):
        self.directory = str(directory)
        self.capacity_bytes = max(0, int(capacity_bytes))
        os.makedirs(self.directory, exist_ok=True)
        self._entries: "OrderedDict[object, int]" = OrderedDict()
        self.bytes_used = 0
        # lifetime counters the lifecycle manager mirrors into stats
        self.n_writes = 0
        self.bytes_written = 0
        self.n_corrupt = 0
        self._scan()

    # --------------------------------------------------------- recovery
    def _scan(self) -> None:
        """Recover an existing spill directory: sweep crash leftovers
        (``.tmp`` from a kill mid-demotion), drop stale ``swap_`` files
        (request ids don't survive the process that minted them), and
        ingest ``pfx_`` entries — tolerantly: a file the zip reader
        rejects warns and is removed rather than poisoning the pool."""
        for name in sorted(os.listdir(self.directory)):
            path = os.path.join(self.directory, name)
            if name.endswith(".tmp") or name.startswith("swap_"):
                try:
                    os.remove(path)
                except OSError:
                    pass
                continue
            if not (name.startswith("pfx_") and name.endswith(".npz")):
                continue
            try:
                with np.load(path) as z:
                    _ = z.files            # forces the zip directory read
                digest = bytes.fromhex(name[len("pfx_"):-len(".npz")])
            except _READ_ERRORS as e:
                self.n_corrupt += 1
                warnings.warn(
                    f"disk KV spill {path!r} unreadable ({e!r}); treating "
                    "as empty and removing it", stacklevel=2)
                try:
                    os.remove(path)
                except OSError:
                    pass
                continue
            nbytes = os.path.getsize(path)
            self._entries[digest] = nbytes
            self.bytes_used += nbytes

    # ------------------------------------------------------------ access
    def _path(self, key) -> str:
        return os.path.join(self.directory, _fname(key))

    def can_fit(self, nbytes: int) -> bool:
        return (self.capacity_bytes > 0
                and self.bytes_used + int(nbytes) <= self.capacity_bytes)

    def __contains__(self, key) -> bool:
        return key in self._entries

    @property
    def n_entries(self) -> int:
        return len(self._entries)

    def put(self, key, k_blocks, v_blocks, nbytes: int,
            k_scale=None, v_scale=None) -> int:
        """Spill one entry to its npz file (crash-safe: sibling tmp +
        atomic rename), evicting LRU entries to stay under the cap.
        Materializes lazy device arrays — demotion is a PRESSURE path,
        callers count the sync. Returns the file bytes written."""
        if key in self._entries:
            self.drop(key)
        # sync-ok: disk demotion materialization (pressure path only)
        arrays = {"k": np.asarray(k_blocks), "v": np.asarray(v_blocks)}
        if k_scale is not None:
            # sync-ok: disk demotion materialization (pressure path only)
            arrays["ks"] = np.asarray(k_scale)
            arrays["vs"] = np.asarray(v_scale)  # sync-ok: demotion path
        path = self._path(key)
        tmp = path + ".tmp"
        try:
            with open(tmp, "wb") as f:
                np.savez(f, **arrays)
            os.replace(tmp, path)
        finally:
            if os.path.exists(tmp):
                try:
                    os.remove(tmp)
                except OSError:
                    pass
        file_bytes = os.path.getsize(path)
        while self.capacity_bytes and self._entries \
                and self.bytes_used + file_bytes > self.capacity_bytes:
            old_key, _ = next(iter(self._entries.items()))
            self.drop(old_key)
        self._entries[key] = file_bytes
        self.bytes_used += file_bytes
        self.n_writes += 1
        self.bytes_written += file_bytes
        return file_bytes

    def fetch(self, key) -> Tuple[np.ndarray, np.ndarray,
                                  Optional[Tuple[np.ndarray, np.ndarray]]]:
        """Remove and read one entry: (k, v, scales-or-None). The whole
        file is decoded into host arrays BEFORE the entry is dropped, so
        a read error leaves no partially-promoted state — the entry is
        removed (it is unrestorable), a warning fires, and ``KeyError``
        tells the caller to treat it as a miss."""
        if key not in self._entries:
            raise KeyError(key)
        path = self._path(key)
        try:
            with np.load(path) as z:
                k, v = z["k"], z["v"]
                sc = None
                if "ks" in z.files and "vs" in z.files:
                    sc = (z["ks"], z["vs"])
        except _READ_ERRORS as e:
            self.n_corrupt += 1
            self.drop(key)
            warnings.warn(
                f"disk KV spill {path!r} unreadable ({e!r}); entry "
                "dropped, caller falls back", stacklevel=2)
            raise KeyError(key) from e
        self.drop(key)
        return k, v, sc

    def peek_nbytes(self, key) -> int:
        """File bytes an entry occupies (LRU-touching peek)."""
        n = self._entries[key]
        self._entries.move_to_end(key)
        return n

    def drop(self, key) -> None:
        n = self._entries.pop(key, None)
        if n is None:
            return
        self.bytes_used -= n
        try:
            os.remove(self._path(key))
        except OSError:
            pass


def resolve_disk_pool(kv_disk=None, kv_disk_bytes: Optional[int] = None
                      ) -> Optional[DiskBlockPool]:
    """Engine-constructor resolution of the disk-tier knobs: an instance
    passes through (a ShardedServingGroup may hand one pool to every
    replica), a string is the spill directory, None defers to
    ``DL4J_TPU_KV_DISK`` (empty/"0" = no disk tier — no pool, no code
    on any path). ``kv_disk_bytes`` caps the directory (None defers to
    ``DL4J_TPU_KV_DISK_BYTES``, default 1 GiB)."""
    if isinstance(kv_disk, DiskBlockPool):
        return kv_disk
    if kv_disk is None:
        kv_disk = os.environ.get("DL4J_TPU_KV_DISK", "")
    if not kv_disk or kv_disk == "0":
        return None
    if kv_disk_bytes is None:
        kv_disk_bytes = int(os.environ.get("DL4J_TPU_KV_DISK_BYTES",
                                           str(DEFAULT_DISK_BYTES)))
    return DiskBlockPool(str(kv_disk), capacity_bytes=int(kv_disk_bytes))
