"""Training listeners.

Parity: ref optimize/api/{IterationListener,TrainingListener}.java:17-71 and
optimize/listeners/{ScoreIterationListener,PerformanceListener.java:21 (:118-124),
CollectScoresIterationListener,TimeIterationListener,EvaluativeListener}.
"""
from __future__ import annotations

import time
from typing import List, Optional, Tuple


class IterationListener:
    def iteration_done(self, model, iteration: int):
        pass


class TrainingListener(IterationListener):
    def on_epoch_start(self, model):
        pass

    def on_epoch_end(self, model):
        pass

    def on_forward_pass(self, model, activations):
        pass

    def on_gradient_calculation(self, model):
        pass

    def on_backward_pass(self, model):
        pass


class ScoreIterationListener(IterationListener):
    """Log score every N iterations (ref ScoreIterationListener)."""

    def __init__(self, print_iterations: int = 10):
        self.print_iterations = max(1, int(print_iterations))

    def iteration_done(self, model, iteration: int):
        if iteration % self.print_iterations == 0:
            print(f"Score at iteration {iteration} is {model.score()}")


class PerformanceListener(IterationListener):
    """Iteration time / samples/sec / batches/sec, ETL time separated
    (ref PerformanceListener.java:118-124).

    Score reporting is SYNC-FREE: `model.score()` would force a device sync
    per iteration (block until the in-flight step's loss materializes), so
    the record instead carries the LAST MATERIALIZED score — the previous
    iteration's loss, whose buffer completed while the current step ran.
    `rec["score"]` is therefore one step stale (None until iteration 2);
    staleness is the price of keeping the training loop fully async."""

    def __init__(self, frequency: int = 1, report: bool = True):
        self.frequency = max(1, int(frequency))
        self.report = report
        self._last = None
        self.history: List[dict] = []

    def iteration_done(self, model, iteration: int):
        from deeplearning4j_tpu.telemetry.training import lagged_score
        now = time.time()
        score = lagged_score(self, model)   # one step stale, no forced sync
        if self._last is not None and iteration % self.frequency == 0:
            dt = now - self._last
            batch = getattr(model, "_last_batch_size", None)
            rec = {
                "iteration": iteration,
                "ms": dt * 1e3,
                "batches_per_sec": 1.0 / dt if dt > 0 else float("inf"),
                "samples_per_sec": (batch / dt) if (batch and dt > 0) else None,
                "etl_ms": getattr(model, "last_etl_ms", 0.0),
                "score": score,             # previous iteration's (stale)
            }
            self.history.append(rec)
            if self.report:
                sps = f", samples/sec: {rec['samples_per_sec']:.1f}" if rec["samples_per_sec"] else ""
                print(f"iteration {iteration}; iteration time: {rec['ms']:.2f} ms; "
                      f"ETL: {rec['etl_ms']:.2f} ms{sps}")
        self._last = now


class CollectScoresIterationListener(IterationListener):
    def __init__(self, frequency: int = 1):
        self.frequency = max(1, int(frequency))
        self.scores: List[Tuple[int, float]] = []

    def iteration_done(self, model, iteration: int):
        if iteration % self.frequency == 0:
            self.scores.append((iteration, model.score()))


class TimeIterationListener(IterationListener):
    """ETA logging based on expected total iterations (ref TimeIterationListener)."""

    def __init__(self, total_iterations: int, frequency: int = 10):
        self.total = int(total_iterations)
        self.frequency = max(1, int(frequency))
        self.start = time.time()

    def iteration_done(self, model, iteration: int):
        if iteration % self.frequency == 0 and iteration > 0:
            elapsed = time.time() - self.start
            per = elapsed / iteration
            remaining = per * max(0, self.total - iteration)
            print(f"iteration {iteration}/{self.total}; ETA {remaining:.0f}s")


class EvaluativeListener(TrainingListener):
    """Periodic evaluation on a held-out iterator (ref EvaluativeListener)."""

    def __init__(self, iterator, frequency: int = 100):
        self.iterator = iterator
        self.frequency = max(1, int(frequency))
        self.last_evaluation = None

    def iteration_done(self, model, iteration: int):
        if iteration % self.frequency == 0:
            self.last_evaluation = model.evaluate(self.iterator)
            print(self.last_evaluation.stats())


class CheckpointListener(TrainingListener):
    """Periodic model checkpointing with keep-last-N retention
    (ref optimize/listeners/CheckpointListener.java: saveEveryNIterations /
    keepLast). `restore_latest` resumes params, updater state, and the step
    counter. Scope note (matches the reference, SURVEY §5: DL4J checkpoints no
    iterator state either): the RNG stream and the data-iterator position are
    NOT part of the checkpoint, so a restarted run replays a different batch
    order — this is restart-from-checkpoint, not exact mid-epoch resume."""

    def __init__(self, directory: str, save_every_n_iterations: int = 100,
                 keep_last: int = 3, save_updater: bool = True):
        import os
        import re
        self.directory = directory
        self.frequency = max(1, int(save_every_n_iterations))
        self.keep_last = max(1, int(keep_last))
        self.save_updater = save_updater
        os.makedirs(directory, exist_ok=True)
        # seed retention state from checkpoints already on disk, so keep_last
        # holds across crash-restarts instead of orphaning prior files
        existing = []
        for name in os.listdir(directory):
            m = re.match(r"checkpoint_iter_(\d+)\.zip$", name)
            if m:
                existing.append((int(m.group(1)),
                                 os.path.join(directory, name)))
        self.saved: List[str] = [p for _, p in sorted(existing)]

    def iteration_done(self, model, iteration: int):
        import os
        if iteration % self.frequency != 0:
            return
        from deeplearning4j_tpu.util.model_serializer import ModelSerializer
        path = os.path.join(self.directory, f"checkpoint_iter_{iteration}.zip")
        ModelSerializer.write_model(model, path, save_updater=self.save_updater)
        self.saved.append(path)
        while len(self.saved) > self.keep_last:
            old = self.saved.pop(0)
            try:
                os.remove(old)
            except OSError:
                pass

    @staticmethod
    def restore_latest(directory: str):
        """Resume point: the newest checkpoint in `directory`, or None."""
        import os
        import re
        if not os.path.isdir(directory):
            return None
        best, best_iter = None, -1
        for name in os.listdir(directory):
            m = re.match(r"checkpoint_iter_(\d+)\.zip$", name)
            if m and int(m.group(1)) > best_iter:
                best_iter = int(m.group(1))
                best = os.path.join(directory, name)
        if best is None:
            return None
        from deeplearning4j_tpu.util.model_serializer import ModelSerializer
        return ModelSerializer.restore(best)
    restoreLatest = restore_latest


class ParamAndGradientIterationListener(IterationListener):
    """Per-iteration parameter/update magnitude stats to console and/or a
    delimited file (ref optimize/listeners/ParamAndGradientIterationListener.java).
    'Gradients' are the applied parameter deltas (post-updater), captured as the
    difference between successive parameter snapshots — exact, no training-path
    instrumentation."""

    def __init__(self, iterations: int = 1, print_mean: bool = True,
                 print_min_max: bool = True, print_mean_abs_value: bool = True,
                 output_to_console: bool = False, output_to_file: bool = False,
                 file_path: Optional[str] = None, delimiter: str = "\t"):
        self.iterations = max(1, int(iterations))
        self.print_mean = print_mean
        self.print_min_max = print_min_max
        self.print_mean_abs_value = print_mean_abs_value
        self.output_to_console = output_to_console
        self.output_to_file = output_to_file
        self.file_path = file_path
        self.delimiter = delimiter
        self.history: List[dict] = []
        self._prev = None
        self._wrote_header = False

    def iteration_done(self, model, iteration: int):
        import numpy as np
        params = np.asarray(model.params())  # sync-ok: listener contract — host param snapshot
        if iteration % self.iterations != 0:
            self._prev = params
            return
        rec = {"iteration": iteration,
               "score": float(model.score())}  # sync-ok: listener contract
        sources = {"param": params}
        if self._prev is not None:
            sources["update"] = params - self._prev
        for kind, arr in sources.items():
            if self.print_mean:
                rec[f"{kind}_mean"] = float(arr.mean())  # sync-ok: host numpy
            if self.print_min_max:
                rec[f"{kind}_min"] = float(arr.min())  # sync-ok: host numpy
                rec[f"{kind}_max"] = float(arr.max())  # sync-ok: host numpy
            if self.print_mean_abs_value:
                rec[f"{kind}_mean_abs"] = \
                    float(np.abs(arr).mean())  # sync-ok: host numpy
        self._prev = params
        self.history.append(rec)
        line = self.delimiter.join(f"{k}={v}" for k, v in rec.items())
        if self.output_to_console:
            print(line)
        if self.output_to_file and self.file_path:
            with open(self.file_path, "a") as f:
                f.write(line + "\n")


class TelemetryListener(TrainingListener):
    """Bridge from the DL4J TrainingListener API onto the telemetry
    subsystem (deeplearning4j_tpu/telemetry/): per-iteration wall time and
    count go to the metrics registry (histogram `training.iteration_ms`,
    counter `training.iterations` — shared, idempotent bookkeeping with
    ui/stats.StatsListener via telemetry.training.mark_iteration), the
    one-step-stale materialized score to gauge `training.score`, and epochs
    become trace spans. NOTHING here forces a device sync: timing is host
    clocks, the score read is the lagged already-materialized buffer.

    Attach like any listener: `net.set_listeners(TelemetryListener())`;
    scrape via the UIServer /metrics endpoint or registry().snapshot(), and
    set DL4J_TPU_TRACE_PATH to get a Chrome trace per epoch."""

    def __init__(self, registry=None):
        from deeplearning4j_tpu import telemetry
        self.registry = registry or telemetry.registry()
        self._epoch_span = None
        self._c_epochs = self.registry.counter(
            "training.epochs", "training epochs completed")
        self._g_score = self.registry.gauge(
            "training.score", "last materialized score (one step stale)")

    def iteration_done(self, model, iteration: int):
        from deeplearning4j_tpu.telemetry.training import (lagged_score,
                                                           mark_iteration)
        mark_iteration(iteration, self.registry, store=model)
        s = lagged_score(self, model)
        if s is not None and s == s:        # skip the initial NaN
            self._g_score.set(s)

    def on_epoch_start(self, model):
        from deeplearning4j_tpu import telemetry
        self._epoch_span = telemetry.span("epoch")
        self._epoch_span.__enter__()

    def on_epoch_end(self, model):
        from deeplearning4j_tpu import telemetry
        if self._epoch_span is not None:
            self._epoch_span.__exit__(None, None, None)
            self._epoch_span = None
        self._c_epochs.inc()
        telemetry.maybe_export_trace()


class SleepyTrainingListener(TrainingListener):
    """Throttling listener (ref SleepyTrainingListener) — mainly for tests."""

    def __init__(self, sleep_ms: float = 0.0):
        self.sleep_ms = sleep_ms

    def iteration_done(self, model, iteration: int):
        if self.sleep_ms > 0:
            time.sleep(self.sleep_ms / 1e3)
