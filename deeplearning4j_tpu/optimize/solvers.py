"""Second-order full-batch solvers: L-BFGS, conjugate gradient, line search.

Parity: ref optimize/solvers/{LBFGS,ConjugateGradient,LineGradientDescent}.java +
BackTrackLineSearch.java and optimize/Solver.java (builder dispatching on
OptimizationAlgorithm). TPU-first: the objective is the network's jitted
loss-over-flat-params function; each solver iteration is a handful of
whole-parameter-vector ops + one compiled loss/grad call, with the backtracking
line search running host-side over compiled evaluations (exactly the reference's
structure, minus the hand-managed workspaces).
"""
from __future__ import annotations

from typing import Callable, List, Optional, Tuple

import time

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu import telemetry
from deeplearning4j_tpu.common.enums import OptimizationAlgorithm


def _objective(net, x, y, fmask=None, lmask=None):
    """Jitted (loss, grad) over the FLAT parameter vector."""
    from deeplearning4j_tpu.util.flat_params import flatten_params, unflatten_params
    template = net.params_tree
    state = net.state_tree
    x = jnp.asarray(x, net.dtype)
    y = jnp.asarray(y, net.dtype)

    def loss_flat(flat):
        pt = unflatten_params(template, flat)
        loss, _ = net._loss_fn(pt, state, x, y, fmask, lmask, None, True, None)
        return loss

    vg = jax.jit(jax.value_and_grad(loss_flat))
    return vg, jax.jit(loss_flat)


def backtrack_line_search(loss_fn, x0: jnp.ndarray, f0: float, g0: np.ndarray,
                          direction: np.ndarray, step0: float = 1.0,
                          c1: float = 1e-4, tau: float = 0.5,
                          max_steps: int = 20) -> Tuple[float, float]:
    """Armijo backtracking (ref BackTrackLineSearch.java). Returns (step, f_new)."""
    slope = float(np.dot(g0, direction))
    step = step0
    evals = telemetry.registry().counter(
        "solver.line_search_evals", "compiled loss evaluations spent in "
        "backtracking line search")
    for _ in range(max_steps):
        evals.inc()
        f_new = float(loss_fn(x0 + step * jnp.asarray(direction)))
        if np.isfinite(f_new) and f_new <= f0 + c1 * step * slope:
            return step, f_new
        step *= tau
    return 0.0, f0  # no acceptable step


class BaseSolver:
    def __init__(self, max_iterations: int = 100, tolerance: float = 1e-6):
        self.max_iterations = int(max_iterations)
        self.tolerance = float(tolerance)
        self.score_history: List[float] = []
        self._t_iter = None

    def _iter_done(self, f: float) -> None:
        """One accepted solver iteration: history + telemetry (counter,
        per-iteration wall histogram, score gauge — all host values the
        solver already holds, no extra syncs)."""
        self.score_history.append(f)
        reg = telemetry.registry()
        reg.counter("solver.iterations",
                    "accepted second-order solver iterations").inc()
        reg.gauge("solver.score", "latest solver objective value").set(f)
        now = time.perf_counter()
        if self._t_iter is not None:
            reg.histogram("solver.iteration_ms",
                          "wall time per solver iteration").observe(
                (now - self._t_iter) * 1e3)
        self._t_iter = now

    def optimize(self, net, x, y, fmask=None, lmask=None) -> float:
        raise NotImplementedError


class LineGradientDescent(BaseSolver):
    """Steepest descent with line search (ref LineGradientDescent.java)."""

    def optimize(self, net, x, y, fmask=None, lmask=None) -> float:
        vg, loss_fn = _objective(net, x, y, fmask, lmask)
        flat = jnp.asarray(net.params())
        f, g = vg(flat)
        f = float(f)
        for _ in range(self.max_iterations):
            g_np = np.asarray(g, np.float64)
            step, f_new = backtrack_line_search(loss_fn, flat, f, g_np, -g_np)
            if step == 0.0 or abs(f - f_new) < self.tolerance:
                break
            flat = flat - step * g
            f, g = vg(flat)
            f = float(f)
            self._iter_done(f)
        net.set_params(flat)
        net._score = f
        return f


class ConjugateGradient(BaseSolver):
    """Nonlinear CG, Polak-Ribiere with automatic restarts
    (ref ConjugateGradient.java)."""

    def optimize(self, net, x, y, fmask=None, lmask=None) -> float:
        vg, loss_fn = _objective(net, x, y, fmask, lmask)
        flat = jnp.asarray(net.params())
        f, g = vg(flat)
        f = float(f)
        g_np = np.asarray(g, np.float64)
        d = -g_np
        for it in range(self.max_iterations):
            step, f_new = backtrack_line_search(loss_fn, flat, f, g_np, d)
            if step == 0.0 or abs(f - f_new) < self.tolerance:
                break
            flat = flat + step * jnp.asarray(d)
            f2, g2 = vg(flat)
            g2_np = np.asarray(g2, np.float64)
            # Polak-Ribiere beta, restart on loss of conjugacy
            beta = float(np.dot(g2_np, g2_np - g_np)
                         / max(np.dot(g_np, g_np), 1e-300))
            if beta < 0 or (it + 1) % flat.shape[0] == 0:
                beta = 0.0  # restart: steepest descent
            d = -g2_np + beta * d
            f, g_np = float(f2), g2_np
            self._iter_done(f)
        net.set_params(flat)
        net._score = f
        return f


class LBFGS(BaseSolver):
    """Limited-memory BFGS with two-loop recursion (ref LBFGS.java, m=10)."""

    def __init__(self, max_iterations: int = 100, tolerance: float = 1e-6,
                 m: int = 10):
        super().__init__(max_iterations, tolerance)
        self.m = int(m)

    def optimize(self, net, x, y, fmask=None, lmask=None) -> float:
        vg, loss_fn = _objective(net, x, y, fmask, lmask)
        flat = jnp.asarray(net.params())
        f, g = vg(flat)
        f = float(f)
        g_np = np.asarray(g, np.float64)
        s_hist: List[np.ndarray] = []
        y_hist: List[np.ndarray] = []
        for _ in range(self.max_iterations):
            # two-loop recursion
            q = g_np.copy()
            alphas = []
            for s, yv in zip(reversed(s_hist), reversed(y_hist)):
                rho = 1.0 / max(np.dot(yv, s), 1e-300)
                a = rho * np.dot(s, q)
                alphas.append((a, rho, s, yv))
                q -= a * yv
            if y_hist:
                gamma = (np.dot(s_hist[-1], y_hist[-1])
                         / max(np.dot(y_hist[-1], y_hist[-1]), 1e-300))
                q *= gamma
            for a, rho, s, yv in reversed(alphas):
                b = rho * np.dot(yv, q)
                q += (a - b) * s
            d = -q
            step, f_new = backtrack_line_search(loss_fn, flat, f, g_np, d)
            if step == 0.0 or abs(f - f_new) < self.tolerance:
                break
            new_flat = flat + step * jnp.asarray(d)
            f2, g2 = vg(new_flat)
            g2_np = np.asarray(g2, np.float64)
            s_hist.append(np.asarray(new_flat - flat, np.float64))
            y_hist.append(g2_np - g_np)
            if len(s_hist) > self.m:
                s_hist.pop(0)
                y_hist.pop(0)
            flat, f, g_np = new_flat, float(f2), g2_np
            self._iter_done(f)
        net.set_params(flat)
        net._score = f
        return f


class Solver:
    """(ref optimize/Solver.java Builder) — dispatches on the configuration's
    OptimizationAlgorithm; SGD stays on the network's own jitted step path."""

    _MAP = {
        OptimizationAlgorithm.LBFGS: LBFGS,
        OptimizationAlgorithm.CONJUGATE_GRADIENT: ConjugateGradient,
        OptimizationAlgorithm.LINE_GRADIENT_DESCENT: LineGradientDescent,
    }

    def __init__(self, net, max_iterations: int = 100, tolerance: float = 1e-6):
        self.net = net
        self.max_iterations = max_iterations
        self.tolerance = tolerance

    def optimize(self, x, y, fmask=None, lmask=None,
                 algorithm: Optional[OptimizationAlgorithm] = None) -> float:
        algo = algorithm or getattr(self.net.conf.global_conf,
                                    "optimization_algo",
                                    OptimizationAlgorithm.STOCHASTIC_GRADIENT_DESCENT)
        algo = OptimizationAlgorithm(algo)
        with telemetry.span("solver.optimize", algorithm=algo.name):
            if algo == OptimizationAlgorithm.STOCHASTIC_GRADIENT_DESCENT:
                self.net.fit_batch(x, y, fmask, lmask)
                return float(self.net.score())
            solver = self._MAP[algo](self.max_iterations, self.tolerance)
            return solver.optimize(self.net, x, y, fmask, lmask)

    class Builder:
        def __init__(self):
            self._net = None
            self._kw = {}

        def model(self, net):
            self._net = net
            return self

        def configure(self, **kw):
            self._kw.update(kw)
            return self

        def build(self) -> "Solver":
            return Solver(self._net, **self._kw)
