"""Streaming ingest (L10): push-based DataSet streams feeding training.

Parity: ref deeplearning4j-streaming (Camel/Kafka routes turning records into
INDArray batches consumed by training). TPU rendering: the broker-specific
plumbing is out of scope in a zero-egress environment, but the SHAPE of the
subsystem — a producer pushing batches into a bounded queue that training
consumes as a DataSetIterator, with backpressure and end-of-stream — is here,
transport-agnostic: any thread/socket/file-tail producer can publish.
"""
from deeplearning4j_tpu.streaming.stream import (
    DataSetStreamPublisher, StreamingDataSetIterator)

__all__ = ["StreamingDataSetIterator", "DataSetStreamPublisher"]
