"""NDArray streaming-ingest client (L10 infra glue).

Parity: ref dl4j-streaming/.../kafka/NDArrayKafkaClient.java (+
NDArrayPublisher.java, NDArrayConsumer.java) — publish NDArrays to a topic
and consume them on the training side. The reference routes through
Camel+Kafka with base64'd Nd4j serde; the TPU rendering keeps the client
shape (client.create_publisher() / client.create_consumer(), publish one or
many arrays, get_arrays()/get_ndarray()) over a pluggable broker. The
default `InProcessBroker` is the zero-dependency bounded-queue broker (the
same backpressure contract as streaming/stream.py); a real Kafka/PubSub
broker plugs in by implementing `send`/`poll` — the wire format (npy bytes)
is already broker-agnostic.
"""
from __future__ import annotations

import io
import queue
from typing import Dict, List, Optional, Sequence

import numpy as np


def ndarray_to_bytes(arr) -> bytes:
    """npy serde — the Nd4j base64 serde analog, but a standard format."""
    buf = io.BytesIO()
    np.save(buf, np.asarray(arr), allow_pickle=False)
    return buf.getvalue()


def ndarray_from_bytes(data: bytes):
    return np.load(io.BytesIO(data), allow_pickle=False)


class InProcessBroker:
    """Bounded per-topic queues — the in-process stand-in for the Kafka
    broker (backpressure like streaming/stream.py: send blocks when the
    consumer lags by `capacity` messages)."""

    def __init__(self, capacity: int = 64):
        self.capacity = int(capacity)
        self._topics: Dict[str, "queue.Queue"] = {}

    def _topic(self, name: str) -> "queue.Queue":
        q = self._topics.get(name)   # hot path: no per-message allocation
        if q is not None:
            return q
        # setdefault is atomic in CPython: concurrent first touches of a
        # topic from publisher + consumer threads must agree on ONE queue
        return self._topics.setdefault(name,
                                       queue.Queue(maxsize=self.capacity))

    def send(self, topic: str, data: bytes,
             timeout: Optional[float] = None) -> None:
        self._topic(topic).put(data, timeout=timeout)

    def poll(self, topic: str, timeout: Optional[float] = None) -> bytes:
        return self._topic(topic).get(timeout=timeout)


class NDArrayPublisher:
    """(ref kafka/NDArrayPublisher.java) — publish(arr) | publish([arrs])."""

    def __init__(self, broker, topic: str):
        self.broker = broker
        self.topic = topic

    def publish(self, arr, timeout: Optional[float] = None) -> None:
        if isinstance(arr, (list, tuple)):
            for a in arr:
                self.broker.send(self.topic, ndarray_to_bytes(a),
                                 timeout=timeout)
        else:
            self.broker.send(self.topic, ndarray_to_bytes(arr),
                             timeout=timeout)


class NDArrayConsumer:
    """(ref kafka/NDArrayConsumer.java) — getArrays(n) / getINDArray()."""

    def __init__(self, broker, topic: str):
        self.broker = broker
        self.topic = topic

    def get_arrays(self, count: int,
                   timeout: Optional[float] = None) -> List[np.ndarray]:
        return [ndarray_from_bytes(self.broker.poll(self.topic,
                                                    timeout=timeout))
                for _ in range(count)]
    getArrays = get_arrays

    def get_ndarray(self, timeout: Optional[float] = None) -> np.ndarray:
        return self.get_arrays(1, timeout=timeout)[0]
    getINDArray = get_ndarray


class NDArrayStreamClient:
    """(ref kafka/NDArrayKafkaClient.java) — the client facade: one broker
    connection + topic, handing out publishers/consumers."""

    def __init__(self, broker=None, topic: str = "ndarrays",
                 capacity: int = 64):
        self.broker = broker if broker is not None \
            else InProcessBroker(capacity)
        self.topic = topic

    def create_publisher(self) -> NDArrayPublisher:
        return NDArrayPublisher(self.broker, self.topic)
    createPublisher = create_publisher

    def create_consumer(self) -> NDArrayConsumer:
        return NDArrayConsumer(self.broker, self.topic)
    createConsumer = create_consumer
