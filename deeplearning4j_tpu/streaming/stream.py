"""Bounded publish/consume DataSet stream.

Parity: ref deeplearning4j-streaming's Camel route -> DataSet conversion
(e.g. Dl4jProcessor/KafkaConnectionInformation plumbing) reduced to its
essential contract: producers publish (features, labels) batches with
backpressure; training consumes them in order as a normal DataSetIterator;
`end()` terminates the epoch cleanly.
"""
from __future__ import annotations

import queue
import threading
from typing import Iterator, Optional

import numpy as np

from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.datasets.iterators import DataSetIterator

_EOS = object()


class DataSetStreamPublisher:
    """Producer handle (the 'Kafka topic' analog): publish() blocks when the
    consumer is behind by `capacity` batches (backpressure)."""

    def __init__(self, capacity: int = 8):
        self._q: "queue.Queue" = queue.Queue(maxsize=int(capacity))
        self._closed = False

    def publish(self, features, labels, features_mask=None, labels_mask=None,
                timeout: Optional[float] = None) -> None:
        if self._closed:
            raise RuntimeError("stream already ended")
        ds = DataSet(np.asarray(features), np.asarray(labels),
                     features_mask, labels_mask)
        self._q.put(ds, timeout=timeout)

    def publish_dataset(self, ds: DataSet, timeout: Optional[float] = None):
        if self._closed:
            raise RuntimeError("stream already ended")
        self._q.put(ds, timeout=timeout)

    def end(self) -> None:
        """Signal end-of-stream; the consuming iterator finishes its epoch."""
        self._closed = True
        self._q.put(_EOS)


class StreamingDataSetIterator(DataSetIterator):
    """Consumer side — a DataSetIterator over a live stream.

    `max_batches` bounds one epoch for finite training runs on infinite
    streams (the EarlyTermination composition done inline, since a stream has
    no reset)."""

    def __init__(self, publisher: DataSetStreamPublisher,
                 max_batches: Optional[int] = None,
                 poll_timeout: Optional[float] = 30.0):
        self._pub = publisher
        self.max_batches = max_batches
        self.poll_timeout = poll_timeout
        self._done = False

    # streams cannot rewind
    async_supported = False

    def reset(self):
        pass

    def __iter__(self) -> Iterator[DataSet]:
        n = 0
        while not self._done and (self.max_batches is None
                                  or n < self.max_batches):
            try:
                item = self._pub._q.get(timeout=self.poll_timeout)
            except queue.Empty:
                raise TimeoutError(
                    f"no batch arrived within {self.poll_timeout}s")
            if item is _EOS:
                self._done = True
                break
            n += 1
            yield item

    def batch(self):
        return 0  # stream-determined
