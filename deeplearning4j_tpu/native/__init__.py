"""Native runtime bindings (ctypes over native/libdl4jtpu_io.so).

Parity: the reference's native data-path (DataVec JavaCPP loaders, libnd4j
codecs) — see native/dl4jtpu_io.cpp. Auto-builds with `make -C native` on first
use when a compiler is present; everything gracefully falls back to the pure
Python readers when the library is unavailable.
"""
from deeplearning4j_tpu.native.io import (
    NativeBatchPrefetcher, native_available, read_cifar_native,
    read_idx_native)

__all__ = ["native_available", "read_idx_native", "read_cifar_native",
           "NativeBatchPrefetcher"]
