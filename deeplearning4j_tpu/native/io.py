"""ctypes bindings for the native IO/ETL library (native/dl4jtpu_io.cpp)."""
from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional, Tuple

import numpy as np

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
_NATIVE_DIR = os.path.join(_REPO_ROOT, "native")
_SO_PATH = os.path.join(_NATIVE_DIR, "libdl4jtpu_io.so")

_lib = None
_lib_lock = threading.Lock()


def _load() -> Optional[ctypes.CDLL]:
    global _lib
    with _lib_lock:
        if _lib is not None:
            return _lib
        if not os.path.exists(_SO_PATH):
            try:  # build on demand; fine to fail (pure-python fallback)
                subprocess.run(["make", "-C", _NATIVE_DIR], check=True,
                               capture_output=True, timeout=120)
            except Exception:
                return None
        try:
            lib = ctypes.CDLL(_SO_PATH)
        except OSError:
            return None
        lib.dl4j_idx_info.argtypes = [ctypes.c_char_p,
                                      ctypes.POINTER(ctypes.c_int64),
                                      ctypes.POINTER(ctypes.c_int64)]
        lib.dl4j_idx_info.restype = ctypes.c_int
        lib.dl4j_idx_read_f32.argtypes = [
            ctypes.c_char_p, ctypes.POINTER(ctypes.c_float), ctypes.c_int64,
            ctypes.c_int]
        lib.dl4j_idx_read_f32.restype = ctypes.c_int
        lib.dl4j_cifar_read.argtypes = [
            ctypes.c_char_p, ctypes.POINTER(ctypes.c_float),
            ctypes.POINTER(ctypes.c_int32), ctypes.c_int64]
        lib.dl4j_cifar_read.restype = ctypes.c_int64
        lib.dl4j_prefetcher_create.argtypes = [
            ctypes.POINTER(ctypes.c_float), ctypes.POINTER(ctypes.c_float),
            ctypes.c_int64, ctypes.c_int64, ctypes.c_int64, ctypes.c_int64,
            ctypes.c_int64, ctypes.c_int, ctypes.c_int]
        lib.dl4j_prefetcher_create.restype = ctypes.c_void_p
        lib.dl4j_prefetcher_next.argtypes = [ctypes.c_void_p,
                                             ctypes.POINTER(ctypes.c_float)]
        lib.dl4j_prefetcher_next.restype = ctypes.c_int64
        lib.dl4j_prefetcher_destroy.argtypes = [ctypes.c_void_p]
        _lib = lib
        return _lib


def native_available() -> bool:
    return _load() is not None


def _fptr(a: np.ndarray):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_float))


def read_idx_native(path: str, normalize: bool = True) -> np.ndarray:
    """IDX file -> (n, item_size) float32 (pixels /255 when normalize)."""
    lib = _load()
    if lib is None:
        raise RuntimeError("native IO library unavailable")
    n = ctypes.c_int64()
    isz = ctypes.c_int64()
    rc = lib.dl4j_idx_info(path.encode(), ctypes.byref(n), ctypes.byref(isz))
    if rc != 0:
        raise IOError(f"dl4j_idx_info({path}) rc={rc}")
    out = np.empty((n.value, max(1, isz.value)), np.float32)
    rc = lib.dl4j_idx_read_f32(path.encode(), _fptr(out), out.size,
                               1 if normalize else 0)
    if rc != 0:
        raise IOError(f"dl4j_idx_read_f32({path}) rc={rc}")
    return out  # (n, item_size); 1-dim label files come back as (n, 1)


def read_cifar_native(path: str, max_records: int = 10000
                      ) -> Tuple[np.ndarray, np.ndarray]:
    """CIFAR binary batch -> ((n,3,32,32) float32, (n,) int32 labels)."""
    lib = _load()
    if lib is None:
        raise RuntimeError("native IO library unavailable")
    x = np.empty((max_records, 3072), np.float32)
    y = np.empty((max_records,), np.int32)
    n = lib.dl4j_cifar_read(path.encode(), _fptr(x),
                            y.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
                            max_records)
    if n < 0:
        raise IOError(f"dl4j_cifar_read({path}) rc={n}")
    return x[:n].reshape(n, 3, 32, 32), y[:n]


class NativeBatchPrefetcher:
    """Threaded shuffle+assemble pipeline over an in-memory (x, y) pool
    (the AsyncDataSetIterator decode stage, off the GIL)."""

    def __init__(self, x: np.ndarray, y: np.ndarray, batch: int,
                 seed: int = 12345, threads: int = 2, shuffle: bool = True):
        lib = _load()
        if lib is None:
            raise RuntimeError("native IO library unavailable")
        self._lib = lib
        # keep C-contiguous float32 copies alive for the native side
        self._x = np.ascontiguousarray(x.reshape(x.shape[0], -1), np.float32)
        self._y = np.ascontiguousarray(y.reshape(y.shape[0], -1), np.float32)
        self.n = self._x.shape[0]
        self.feat = self._x.shape[1]
        self.lab = self._y.shape[1]
        self.batch = int(batch)
        self._buf = np.empty((self.batch * (self.feat + self.lab),), np.float32)
        self._handle = lib.dl4j_prefetcher_create(
            _fptr(self._x), _fptr(self._y), self.n, self.feat, self.lab,
            self.batch, seed, int(threads), 1 if shuffle else 0)

    def __iter__(self):
        while True:
            if self._handle is None:
                raise RuntimeError("prefetcher is closed")
            rows = self._lib.dl4j_prefetcher_next(self._handle,
                                                  _fptr(self._buf))
            if rows == 0:
                break
            xb = self._buf[:rows * self.feat].reshape(rows, self.feat).copy()
            yb = self._buf[rows * self.feat:
                           rows * (self.feat + self.lab)] \
                .reshape(rows, self.lab).copy()
            yield xb, yb

    def close(self):
        if self._handle:
            self._lib.dl4j_prefetcher_destroy(self._handle)
            self._handle = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass
