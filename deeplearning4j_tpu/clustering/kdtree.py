"""KD-tree nearest neighbors (ref clustering/kdtree/KDTree.java:37).

API parity: KDTree(dims), insert(point), delete(point), nn(point) ->
(distance, point), knn(point, radius) -> [(distance, point) within radius],
size(). Host-side index structure (like the reference — it backs small/mid-N
exact queries; the TPU brute-force path in clustering/knn.py owns the large-N
regime, and tsne.py's grid summarizer owns the Barnes-Hut role)."""
from __future__ import annotations

import heapq
from typing import List, Optional, Tuple

import numpy as np


class _Node:
    __slots__ = ("point", "left", "right")

    def __init__(self, point):
        self.point = point
        self.left: Optional["_Node"] = None
        self.right: Optional["_Node"] = None


class KDTree:
    GREATER = 1
    LESS = 0

    def __init__(self, dims: int):
        self.dims = int(dims)
        self._root: Optional[_Node] = None
        self._size = 0

    # ------------------------------------------------------------- build
    def insert(self, point) -> None:
        point = np.asarray(point, np.float64).reshape(-1)
        if point.shape[0] != self.dims:
            raise ValueError(f"point has {point.shape[0]} dims, tree {self.dims}")
        self._size += 1
        if self._root is None:
            self._root = _Node(point)
            return
        node, depth = self._root, 0
        while True:
            axis = depth % self.dims
            if point[axis] < node.point[axis]:
                if node.left is None:
                    node.left = _Node(point)
                    return
                node = node.left
            else:
                if node.right is None:
                    node.right = _Node(point)
                    return
                node = node.right
            depth += 1

    def delete(self, point) -> bool:
        """Remove one node matching `point` exactly (ref delete :98 — rebuilds
        the affected subtree). Iterative traversal: the tree is insertion-
        ordered (unbalanced), so recursion would overflow on sorted inserts."""
        point = np.asarray(point, np.float64).reshape(-1)
        remaining: List[np.ndarray] = []
        found = False
        stack = [self._root] if self._root is not None else []
        while stack:
            node = stack.pop()
            if not found and np.array_equal(node.point, point):
                found = True
            else:
                remaining.append(node.point)
            if node.left is not None:
                stack.append(node.left)
            if node.right is not None:
                stack.append(node.right)
        if not found:
            return False
        self._root = None
        self._size = 0
        for p in remaining:
            self.insert(p)
        return True

    def size(self) -> int:
        return self._size

    # ------------------------------------------------------------ queries
    def nn(self, point) -> Optional[Tuple[float, np.ndarray]]:
        """(ref nn :165) — (euclidean distance, nearest point). Explicit-stack
        traversal (insertion-ordered trees can be deep)."""
        point = np.asarray(point, np.float64).reshape(-1)
        best_d, best_p = np.inf, None
        stack = [(self._root, 0)] if self._root is not None else []
        while stack:
            node, depth = stack.pop()
            d = float(np.linalg.norm(node.point - point))
            if d < best_d:
                best_d, best_p = d, node.point
            axis = depth % self.dims
            delta = point[axis] - node.point[axis]
            near, far = (node.left, node.right) if delta < 0 else \
                (node.right, node.left)
            # push far first so the near side is explored first (tightening
            # best_d before the plane-crossing test below re-runs on pop)
            if far is not None and abs(delta) < best_d:
                stack.append((far, depth + 1))
            if near is not None:
                stack.append((near, depth + 1))
        return None if best_p is None else (best_d, best_p)

    def knn(self, point, distance: float) -> List[Tuple[float, np.ndarray]]:
        """All points within `distance`, closest first (ref knn :129)."""
        point = np.asarray(point, np.float64).reshape(-1)
        out: List[Tuple[float, np.ndarray]] = []
        stack = [(self._root, 0)] if self._root is not None else []
        while stack:
            node, depth = stack.pop()
            d = float(np.linalg.norm(node.point - point))
            if d <= distance:
                out.append((d, node.point))
            axis = depth % self.dims
            delta = point[axis] - node.point[axis]
            near, far = (node.left, node.right) if delta < 0 else \
                (node.right, node.left)
            if far is not None and abs(delta) <= distance:
                stack.append((far, depth + 1))
            if near is not None:
                stack.append((near, depth + 1))
        out.sort(key=lambda t: t[0])
        return out
