"""K-means clustering as jitted Lloyd iterations.

Parity: ref nearestneighbor-core/.../clustering/kmeans/KMeansClustering.java +
algorithm/BaseClusteringAlgorithm.java (setup(k, maxIter, distanceFn),
applyTo(points) -> ClusterSet). TPU-first: the whole Lloyd loop is ONE lax.scan —
assignment is an argmin over an MXU distance matmul, the centroid update is a
segment mean via one-hot matmul (dense, MXU) instead of scatter.
"""
from __future__ import annotations

import functools
from dataclasses import dataclass, field
from typing import List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np


@dataclass
class Point:
    """(ref clustering/cluster/Point.java)"""
    id: int
    array: np.ndarray


@dataclass
class Cluster:
    """(ref clustering/cluster/Cluster.java)"""
    center: np.ndarray
    point_ids: List[int] = field(default_factory=list)


class ClusterSet:
    """(ref clustering/cluster/ClusterSet.java)"""

    def __init__(self, centers: np.ndarray, assignments: np.ndarray,
                 distances: np.ndarray):
        self.centers = centers
        self.assignments = assignments
        self.distances = distances
        self.clusters = [Cluster(centers[c],
                                 np.nonzero(assignments == c)[0].tolist())
                         for c in range(centers.shape[0])]

    def get_clusters(self) -> List[Cluster]:
        return self.clusters
    getClusters = get_clusters

    def get_cluster_count(self) -> int:
        return len(self.clusters)


@functools.partial(jax.jit, static_argnames=("k", "iters"))
def _lloyd(x, init_centers, k: int, iters: int):
    n = x.shape[0]
    xsq = jnp.sum(x * x, axis=1)

    def assign(centers):
        d2 = (xsq[:, None] + jnp.sum(centers * centers, axis=1)[None, :]
              - 2.0 * x @ centers.T)
        return jnp.argmin(d2, axis=1), d2

    def body(centers, _):
        a, _ = assign(centers)
        onehot = jax.nn.one_hot(a, k, dtype=x.dtype)        # (N,k)
        counts = jnp.sum(onehot, axis=0)                    # (k,)
        sums = onehot.T @ x                                 # (k,D) MXU
        new = jnp.where(counts[:, None] > 0,
                        sums / jnp.maximum(counts, 1.0)[:, None], centers)
        return new, None

    centers, _ = jax.lax.scan(body, init_centers, None, length=iters)
    a, d2 = assign(centers)
    dist = jnp.sqrt(jnp.maximum(
        jnp.take_along_axis(d2, a[:, None], axis=1)[:, 0], 0.0))
    return centers, a, dist


class KMeansClustering:
    """(ref KMeansClustering.setup)"""

    def __init__(self, k: int, max_iterations: int = 100,
                 distance: str = "euclidean", seed: int = 12345):
        if distance != "euclidean":
            raise ValueError("k-means here is euclidean (ref default 'euclidean')")
        self.k = int(k)
        self.max_iterations = int(max_iterations)
        self.seed = int(seed)

    @classmethod
    def setup(cls, k: int, max_iterations: int, distance: str = "euclidean",
              seed: int = 12345) -> "KMeansClustering":
        return cls(k, max_iterations, distance, seed)

    def apply_to(self, points) -> ClusterSet:
        """points: (N,D) array or list of Point."""
        if isinstance(points, (list, tuple)) and points \
                and isinstance(points[0], Point):
            x = np.stack([p.array for p in points]).astype(np.float32)
        else:
            x = np.asarray(points, np.float32)
        rng = np.random.RandomState(self.seed)
        # k-means++ seeding (ref uses random initial centers; ++ is strictly better
        # and deterministic under seed)
        centers = [x[rng.randint(x.shape[0])]]
        for _ in range(1, self.k):
            d2 = np.min(
                [np.sum((x - c) ** 2, axis=1) for c in centers], axis=0)
            probs = d2 / max(d2.sum(), 1e-12)
            centers.append(x[rng.choice(x.shape[0], p=probs)])
        init = jnp.asarray(np.stack(centers))
        c, a, d = _lloyd(jnp.asarray(x), init, k=self.k,
                         iters=self.max_iterations)
        return ClusterSet(np.asarray(c), np.asarray(a), np.asarray(d))
    applyTo = apply_to
