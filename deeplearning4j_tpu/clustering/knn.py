"""K-nearest-neighbor search: XLA brute force + host-side VPTree.

Parity: ref nearestneighbor-core/.../vptree/VPTree.java:54 (vantage-point tree with
search(point, k)) and the brute-force path the reference's parameter-server KNN
falls back to. TPU-first: `NearestNeighbors` computes the full distance block as
|x|^2 + |y|^2 - 2 x·y on the MXU and top_k's it — one fused jitted computation,
batched over queries.
"""
from __future__ import annotations

import functools
from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np


@functools.partial(jax.jit, static_argnames=("k", "cosine"))
def _knn_block(data, queries, k: int, cosine: bool = False):
    if cosine:
        dn = data / jnp.clip(jnp.linalg.norm(data, axis=1, keepdims=True), 1e-12)
        qn = queries / jnp.clip(jnp.linalg.norm(queries, axis=1, keepdims=True),
                                1e-12)
        sims = qn @ dn.T
        neg_d, idx = jax.lax.top_k(sims, k)
        return 1.0 - neg_d, idx
    d2 = (jnp.sum(queries * queries, axis=1)[:, None]
          + jnp.sum(data * data, axis=1)[None, :]
          - 2.0 * queries @ data.T)                      # MXU matmul
    neg, idx = jax.lax.top_k(-d2, k)
    return jnp.sqrt(jnp.maximum(-neg, 0.0)), idx


class NearestNeighbors:
    """Brute-force exact KNN on device."""

    def __init__(self, data, distance: str = "euclidean"):
        self.data = jnp.asarray(data, jnp.float32)
        if distance not in ("euclidean", "cosine"):
            raise ValueError(f"unsupported distance {distance!r}")
        self.cosine = distance == "cosine"

    def search(self, queries, k: int) -> Tuple[np.ndarray, np.ndarray]:
        """Returns (distances (Q,k), indices (Q,k)), nearest first."""
        q = jnp.atleast_2d(jnp.asarray(queries, jnp.float32))
        d, i = _knn_block(self.data, q, k=int(k), cosine=self.cosine)
        return np.asarray(d), np.asarray(i)


class VPTree:
    """Exact vantage-point tree (ref VPTree.java:54): O(log N) expected search via
    triangle-inequality pruning. Host-side recursive structure; distances are
    numpy — use NearestNeighbors for the TPU path."""

    class _Node:
        __slots__ = ("index", "threshold", "inside", "outside")

        def __init__(self, index, threshold=0.0, inside=None, outside=None):
            self.index = index
            self.threshold = threshold
            self.inside = inside
            self.outside = outside

    def __init__(self, items, distance: str = "euclidean", seed: int = 12345):
        self.items = np.asarray(items, np.float64)
        if distance not in ("euclidean", "cosine"):
            raise ValueError(f"unsupported distance {distance!r}")
        self.distance = distance
        self._rng = np.random.RandomState(seed)
        if self.distance == "cosine":
            norms = np.linalg.norm(self.items, axis=1, keepdims=True)
            self._normed = self.items / np.clip(norms, 1e-12, None)
        self.root = self._build(list(range(self.items.shape[0])))

    def _dist(self, i: int, pts: np.ndarray) -> np.ndarray:
        if self.distance == "cosine":
            return 1.0 - self._normed[pts] @ self._normed[i]
        diff = self.items[pts] - self.items[i]
        return np.sqrt(np.sum(diff * diff, axis=1))

    def _build(self, idxs: List[int]):
        if not idxs:
            return None
        if len(idxs) == 1:
            return VPTree._Node(idxs[0])
        vp = idxs[self._rng.randint(len(idxs))]
        rest = np.asarray([i for i in idxs if i != vp])
        d = self._dist(vp, rest)
        median = float(np.median(d))
        inside = rest[d <= median].tolist()
        outside = rest[d > median].tolist()
        if not inside or not outside:  # degenerate split: fall back to halves
            order = rest[np.argsort(d)]
            half = len(order) // 2
            inside, outside = order[:half + 1].tolist(), order[half + 1:].tolist()
        return VPTree._Node(vp, median, self._build(inside),
                            self._build(outside))

    def search(self, point, k: int) -> Tuple[List[int], List[float]]:
        """(ref VPTree.search(point, k, results, distances))"""
        point = np.asarray(point, np.float64)
        if self.distance == "cosine":
            pn = point / max(np.linalg.norm(point), 1e-12)

            def dist_to(i):
                return float(1.0 - self._normed[i] @ pn)
        else:
            def dist_to(i):
                return float(np.linalg.norm(self.items[i] - point))

        import heapq
        heap: List[Tuple[float, int]] = []  # max-heap via negated distance
        tau = [np.inf]

        def visit(node):
            if node is None:
                return
            d = dist_to(node.index)
            if d < tau[0] or len(heap) < k:
                heapq.heappush(heap, (-d, node.index))
                if len(heap) > k:
                    heapq.heappop(heap)
                if len(heap) == k:
                    tau[0] = -heap[0][0]
            if node.inside is None and node.outside is None:
                return
            if d <= node.threshold:
                visit(node.inside)
                if d + tau[0] > node.threshold:   # ball crosses the boundary
                    visit(node.outside)
            else:
                visit(node.outside)
                if d - tau[0] <= node.threshold:
                    visit(node.inside)

        visit(self.root)
        out = sorted(((-nd, i) for nd, i in heap))
        return [i for _, i in out], [d for d, _ in out]
