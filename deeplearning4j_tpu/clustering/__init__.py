"""Nearest neighbors + clustering + t-SNE (L7).

Parity: ref deeplearning4j-nearestneighbors-parent/nearestneighbor-core
(clustering/kmeans, clustering/vptree/VPTree.java:54) and deeplearning4j-core
plot/BarnesHutTsne.java:65. TPU-first: the default KNN path is brute force on the
MXU (one |x|^2+|y|^2-2xy matmul + top_k beats tree pointer-chasing for any N that
fits in HBM); VPTree/KDTree are kept as host-side exact structures for API
parity; t-SNE runs the EXACT O(N^2) gradient as batched XLA matmuls up to
~4k points and a grid-summarized far field beyond (the TPU-native analog of
the reference's Barnes-Hut sp/quad-tree — see tsne.py); RandomProjectionLSH
provides approximate candidates for huge-N regimes.
"""
from deeplearning4j_tpu.clustering.knn import NearestNeighbors, VPTree
from deeplearning4j_tpu.clustering.kdtree import KDTree
from deeplearning4j_tpu.clustering.kmeans import (
    Cluster, ClusterSet, KMeansClustering, Point)
from deeplearning4j_tpu.clustering.lsh import RandomProjectionLSH
from deeplearning4j_tpu.clustering.tsne import BarnesHutTsne, Tsne
from deeplearning4j_tpu.clustering.server import (
    NearestNeighborsClient, NearestNeighborsServer)

__all__ = ["NearestNeighbors", "VPTree", "KDTree", "RandomProjectionLSH",
           "KMeansClustering", "ClusterSet", "Cluster", "Point",
           "BarnesHutTsne", "Tsne", "NearestNeighborsServer",
           "NearestNeighborsClient"]
