"""Nearest neighbors + clustering + t-SNE (L7).

Parity: ref deeplearning4j-nearestneighbors-parent/nearestneighbor-core
(clustering/kmeans, clustering/vptree/VPTree.java:54) and deeplearning4j-core
plot/BarnesHutTsne.java:65. TPU-first: the default KNN path is brute force on the
MXU (one |x|^2+|y|^2-2xy matmul + top_k beats tree pointer-chasing for any N that
fits in HBM); VPTree is kept as the host-side exact structure for API parity and
huge-N regimes; t-SNE runs the EXACT O(N^2) gradient as batched XLA matmuls —
the Barnes-Hut quadtree is a scalar-workload design that would waste the MXU.
"""
from deeplearning4j_tpu.clustering.knn import NearestNeighbors, VPTree
from deeplearning4j_tpu.clustering.kmeans import (
    Cluster, ClusterSet, KMeansClustering, Point)
from deeplearning4j_tpu.clustering.tsne import BarnesHutTsne, Tsne
from deeplearning4j_tpu.clustering.server import (
    NearestNeighborsClient, NearestNeighborsServer)

__all__ = ["NearestNeighbors", "VPTree", "KMeansClustering", "ClusterSet",
           "Cluster", "Point", "BarnesHutTsne", "Tsne", "NearestNeighborsServer", "NearestNeighborsClient"]
