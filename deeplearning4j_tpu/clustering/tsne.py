"""t-SNE dimensionality reduction: exact and grid-accelerated.

Parity: ref deeplearning4j-core/.../plot/BarnesHutTsne.java:65 (Builder with
perplexity/theta/maxIter/learningRate/momentum, fit(X), getData) and plot/Tsne.

TPU-first redesign, two regimes:

- exact (small/medium N): the O(N^2) gradient is two batched matmuls per
  iteration — on the MXU this beats any tree for N that fits in HBM. The whole
  optimization (gains + momentum + early exaggeration, van der Maaten's
  schedule which the Java code follows) is ONE lax.scan on device.

- grid (large N; the reference's Barnes-Hut regime, BarnesHutTsne.java:65 +
  sptree/SpTree.java): the quadtree/sp-tree is a pointer-chasing scalar
  workload that cannot map to the MXU, so the far-field summarization is
  redesigned as a UNIFORM GRID: embedding points scatter-add into G x G cells
  (centroid + count, both one segment-sum), and every point computes its
  repulsion against the M = G^2 cell summaries — a dense (N, M) Student-t
  kernel, statically shaped, MXU-batched: O(N*M) instead of O(N^2). Attractive
  forces use the standard sparse k-NN conditional P (k = 3*perplexity, exactly
  BarnesHutTsne's computeGaussianPerplexity(..., K) sparsification), with the
  k-NN search itself chunked so memory stays O(chunk * N). This grid
  summarizer is the TPU-native analog of the reference's
  clustering/sptree/SpTree.java + quadtree/QuadTree.java.
"""
from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np


def _hbeta(d2_row, beta):
    p = jnp.exp(-d2_row * beta)
    sum_p = jnp.maximum(jnp.sum(p), 1e-12)
    h = jnp.log(sum_p) + beta * jnp.sum(d2_row * p) / sum_p
    return h, p / sum_p


@functools.partial(jax.jit, static_argnames=("tol_iters",))
def _cond_probs(d2, log_perplexity, tol_iters: int = 50):
    """Per-row binary search for beta = 1/(2 sigma^2) matching the target
    perplexity (ref Tsne/BarnesHutTsne computeGaussianPerplexity) — vectorized
    over all rows at once."""
    n = d2.shape[0]
    eye = jnp.eye(n, dtype=bool)
    d2 = jnp.where(eye, 0.0, d2)

    def row_search(d2_row, mask_row):
        def body(carry, _):
            beta, lo, hi = carry
            p = jnp.where(mask_row, 0.0, jnp.exp(-d2_row * beta))
            sum_p = jnp.maximum(jnp.sum(p), 1e-12)
            h = jnp.log(sum_p) + beta * jnp.sum(d2_row * p) / sum_p
            too_high = h > log_perplexity  # entropy too high -> raise beta
            lo = jnp.where(too_high, beta, lo)
            hi = jnp.where(too_high, hi, beta)
            beta = jnp.where(too_high,
                             jnp.where(jnp.isinf(hi), beta * 2, (beta + hi) / 2),
                             (lo + beta) / 2)
            return (beta, lo, hi), None

        (beta, _, _), _ = jax.lax.scan(
            body, (jnp.asarray(1.0, d2.dtype), jnp.asarray(0.0, d2.dtype),
                   jnp.asarray(jnp.inf, d2.dtype)), None, length=tol_iters)
        p = jnp.where(mask_row, 0.0, jnp.exp(-d2_row * beta))
        return p / jnp.maximum(jnp.sum(p), 1e-12)

    return jax.vmap(row_search)(d2, eye)


@functools.partial(jax.jit, static_argnames=("iters", "exaggeration_iters"))
def _tsne_loop(P, y0, learning_rate, momentum_start, momentum_final,
               iters: int, exaggeration_iters: int):
    n = y0.shape[0]
    eye = jnp.eye(n, dtype=bool)

    def grad_kl(y, P_eff):
        d2 = (jnp.sum(y * y, axis=1)[:, None] + jnp.sum(y * y, axis=1)[None, :]
              - 2.0 * y @ y.T)
        num = 1.0 / (1.0 + d2)              # student-t kernel
        num = jnp.where(eye, 0.0, num)
        Q = jnp.maximum(num / jnp.sum(num), 1e-12)
        PQ = (P_eff - Q) * num              # (N,N)
        g = 4.0 * ((jnp.diag(jnp.sum(PQ, axis=1)) - PQ) @ y)
        # report KL from the UN-exaggerated P (P_eff drives only the
        # gradient) — keeps kl_history a real KL(P||Q), comparable across the
        # exact and grid paths and across the exaggeration boundary
        kl = jnp.sum(P * jnp.log(jnp.maximum(P, 1e-12) / Q))
        return g, kl

    def body(carry, it):
        y, vel, gains = carry
        exag = jnp.where(it < exaggeration_iters, 4.0, 1.0)
        mom = jnp.where(it < exaggeration_iters, momentum_start, momentum_final)
        g, kl = grad_kl(y, P * exag)
        same_sign = jnp.sign(g) == jnp.sign(vel)
        gains = jnp.maximum(
            jnp.where(same_sign, gains * 0.8, gains + 0.2), 0.01)
        vel = mom * vel - learning_rate * gains * g
        y = y + vel
        y = y - jnp.mean(y, axis=0)         # keep centered
        return (y, vel, gains), kl

    (y, _, _), kls = jax.lax.scan(
        body, (y0, jnp.zeros_like(y0), jnp.ones_like(y0)),
        jnp.arange(iters))
    return y, kls


@functools.partial(jax.jit, static_argnames=("k", "chunk"))
def _knn_chunked(x, k: int, chunk: int):
    """(idx, d2) of the k nearest neighbors per row, scanning row chunks so no
    N x N buffer ever materializes (self excluded)."""
    n, d = x.shape
    sq = jnp.sum(x * x, axis=1)
    pad = (-n) % chunk
    xp = jnp.pad(x, ((0, pad), (0, 0)))
    sqp = jnp.pad(sq, (0, pad))
    rows0 = jnp.arange(xp.shape[0]).reshape(-1, chunk)

    def one_chunk(_, rows):
        xc = xp[rows]                                    # (chunk, d)
        d2 = sqp[rows][:, None] + sq[None, :] - 2.0 * xc @ x.T  # (chunk, N)
        d2 = d2.at[jnp.arange(rows.shape[0]), jnp.clip(rows, 0, n - 1)].set(
            jnp.inf)                                     # drop self
        neg, idx = jax.lax.top_k(-d2, k)
        return None, (idx, -neg)

    _, (idx, d2) = jax.lax.scan(one_chunk, None, rows0)
    idx = idx.reshape(-1, k)[:n]
    d2 = jnp.maximum(d2.reshape(-1, k)[:n], 0.0)
    return idx.astype(jnp.int32), d2


@functools.partial(jax.jit, static_argnames=("tol_iters",))
def _cond_probs_knn(d2, log_perplexity, tol_iters: int = 50):
    """Per-row beta search over the k-NN distances only (ref BarnesHutTsne
    computeGaussianPerplexity(D, N, K) sparse branch)."""

    def row_search(d2_row):
        def body(carry, _):
            beta, lo, hi = carry
            p = jnp.exp(-d2_row * beta)
            sum_p = jnp.maximum(jnp.sum(p), 1e-12)
            h = jnp.log(sum_p) + beta * jnp.sum(d2_row * p) / sum_p
            too_high = h > log_perplexity
            lo = jnp.where(too_high, beta, lo)
            hi = jnp.where(too_high, hi, beta)
            beta = jnp.where(too_high,
                             jnp.where(jnp.isinf(hi), beta * 2, (beta + hi) / 2),
                             (lo + beta) / 2)
            return (beta, lo, hi), None

        (beta, _, _), _ = jax.lax.scan(
            body, (jnp.asarray(1.0, d2.dtype), jnp.asarray(0.0, d2.dtype),
                   jnp.asarray(jnp.inf, d2.dtype)), None, length=tol_iters)
        p = jnp.exp(-d2_row * beta)
        return p / jnp.maximum(jnp.sum(p), 1e-12)

    return jax.vmap(row_search)(d2)


@functools.partial(jax.jit,
                   static_argnames=("iters", "exaggeration_iters", "grid"))
def _tsne_loop_grid(rows, cols, pvals, y0, learning_rate, momentum_start,
                    momentum_final, iters: int, exaggeration_iters: int,
                    grid: int):
    """Sparse-attract + grid-repulse optimization loop (see module docstring).
    rows/cols/pvals: symmetrized COO of P (nnz = 2*N*k)."""
    n = y0.shape[0]
    M = grid * grid

    def grad_kl(y, exag):
        # ---- far field: summarize the embedding into grid cells
        lo = jnp.min(y, axis=0)
        hi = jnp.max(y, axis=0)
        span = jnp.maximum(hi - lo, 1e-6)
        cell = jnp.clip(((y - lo) / span * grid).astype(jnp.int32), 0, grid - 1)
        cid = cell[:, 0] * grid + cell[:, 1]
        cnt = jnp.zeros((M,), y.dtype).at[cid].add(1.0)
        cent = jnp.zeros((M, 2), y.dtype).at[cid].add(y) \
            / jnp.maximum(cnt, 1.0)[:, None]
        diff = y[:, None, :] - cent[None, :, :]          # (N, M, 2)
        num = cnt[None, :] / (1.0 + jnp.sum(diff * diff, axis=-1))  # (N, M)
        Z = jnp.maximum(jnp.sum(num) - n, 1e-12)  # minus self pairs (q_ii=0)
        f_rep = jnp.sum((num / cnt.clip(1.0)[None, :] * num)[..., None] * diff,
                        axis=1)                          # sum_m n_m q_im^2 dir

        # ---- near field: exact attraction on the sparse P edges
        dy = y[rows] - y[cols]                           # (nnz, 2)
        enum = 1.0 / (1.0 + jnp.sum(dy * dy, axis=-1))   # (nnz,)
        pe = pvals * exag
        f_attr = jnp.zeros_like(y).at[rows].add((pe * enum)[:, None] * dy)
        g = 4.0 * (f_attr - f_rep / Z)
        # report KL from the UN-exaggerated P (pe drives only the gradient):
        # exaggerated-P "KL" is inflated by ~4*log(4) terms during early
        # exaggeration and is not comparable to the exact path's history
        kl = jnp.sum(pvals * jnp.log(jnp.maximum(pvals, 1e-12)
                                     / jnp.maximum(enum / Z, 1e-12)))
        return g, kl

    def body(carry, it):
        y, vel, gains = carry
        exag = jnp.where(it < exaggeration_iters, 4.0, 1.0)
        mom = jnp.where(it < exaggeration_iters, momentum_start, momentum_final)
        g, kl = grad_kl(y, exag)
        same_sign = jnp.sign(g) == jnp.sign(vel)
        # unlike exact/BH forces, cell-quantization noise makes gradient signs
        # jitter near convergence; unclamped delta-bar-delta gains then grow
        # without bound and the step explodes — clamp gains and trust-region
        # the per-point displacement to a fraction of the embedding span
        gains = jnp.clip(
            jnp.where(same_sign, gains * 0.8, gains + 0.2), 0.01, 10.0)
        vel = mom * vel - learning_rate * gains * g
        span = jnp.maximum(jnp.max(jnp.abs(y)), 1.0)
        step_norm = jnp.linalg.norm(vel, axis=1, keepdims=True)
        max_step = 0.05 * span + 0.5
        vel = vel * jnp.minimum(1.0, max_step / jnp.maximum(step_norm, 1e-12))
        y = y + vel
        y = y - jnp.mean(y, axis=0)
        return (y, vel, gains), kl

    (y, _, _), kls = jax.lax.scan(
        body, (y0, jnp.zeros_like(y0), jnp.ones_like(y0)), jnp.arange(iters))
    return y, kls


class Tsne:
    """Exact t-SNE (ref plot/Tsne.java)."""

    # exact-method cutover for method="auto" (exact needs the N x N buffer)
    AUTO_EXACT_MAX_N = 4096

    def __init__(self, max_iter: int = 500, perplexity: float = 30.0,
                 learning_rate: float = 200.0, num_dimension: int = 2,
                 momentum: float = 0.5, final_momentum: float = 0.8,
                 stop_lying_iteration: int = 100, theta: float = 0.5,
                 seed: int = 12345, method: str = "exact",
                 grid_size: int = 64, knn_chunk: int = 1024):
        self.max_iter = int(max_iter)
        self.perplexity = float(perplexity)
        self.learning_rate = float(learning_rate)
        self.num_dimension = int(num_dimension)
        self.momentum = float(momentum)
        self.final_momentum = float(final_momentum)
        self.stop_lying_iteration = int(stop_lying_iteration)
        self.theta = float(theta)
        self.seed = int(seed)
        if method not in ("exact", "grid", "auto"):
            raise ValueError(f"method must be exact|grid|auto, got {method!r}")
        self.method = method
        self.grid_size = int(grid_size)
        self.knn_chunk = int(knn_chunk)
        self.y: Optional[np.ndarray] = None
        self.kl_history: Optional[np.ndarray] = None

    def _resolved_method(self, n: int) -> str:
        if self.method != "auto":
            return self.method
        return "exact" if n <= self.AUTO_EXACT_MAX_N else "grid"

    def fit(self, x) -> np.ndarray:
        x = jnp.asarray(x, jnp.float32)
        n = x.shape[0]
        if self._resolved_method(n) == "grid":
            return self._fit_grid(x)
        d2 = (jnp.sum(x * x, axis=1)[:, None] + jnp.sum(x * x, axis=1)[None, :]
              - 2.0 * x @ x.T)
        cond = _cond_probs(d2, jnp.log(jnp.asarray(self.perplexity, jnp.float32)))
        P = (cond + cond.T) / (2.0 * n)
        P = jnp.maximum(P, 1e-12)
        rng = np.random.RandomState(self.seed)
        y0 = jnp.asarray(rng.randn(n, self.num_dimension) * 1e-4, jnp.float32)
        y, kls = _tsne_loop(P, y0, jnp.float32(self.learning_rate),
                            jnp.float32(self.momentum),
                            jnp.float32(self.final_momentum),
                            iters=self.max_iter,
                            exaggeration_iters=self.stop_lying_iteration)
        self.y = np.asarray(y)
        self.kl_history = np.asarray(kls)
        return self.y

    def _fit_grid(self, x) -> np.ndarray:
        """Sparse k-NN attraction + G x G grid repulsion (module docstring);
        only 2-D embeddings (the reference's Barnes-Hut is 2-D-only as well)."""
        if self.num_dimension != 2:
            raise ValueError("grid method supports num_dimension=2 "
                             "(like the reference's Barnes-Hut quadtree)")
        n = x.shape[0]
        k = min(n - 1, max(4, int(3 * self.perplexity)))
        chunk = min(self.knn_chunk, n)
        idx, d2 = _knn_chunked(x, k, chunk)
        cond = _cond_probs_knn(
            d2, jnp.log(jnp.asarray(self.perplexity, jnp.float32)))
        # symmetrize the sparse conditional: COO with both orientations
        r = jnp.repeat(jnp.arange(n, dtype=jnp.int32), k)
        c = idx.reshape(-1)
        v = cond.reshape(-1) / (2.0 * n)
        rows = jnp.concatenate([r, c])
        cols = jnp.concatenate([c, r])
        pvals = jnp.concatenate([v, v])
        rng = np.random.RandomState(self.seed)
        y0 = jnp.asarray(rng.randn(n, 2) * 1e-4, jnp.float32)
        y, kls = _tsne_loop_grid(
            rows, cols, pvals, y0, jnp.float32(self.learning_rate),
            jnp.float32(self.momentum), jnp.float32(self.final_momentum),
            iters=self.max_iter,
            exaggeration_iters=self.stop_lying_iteration,
            grid=self.grid_size)
        self.y = np.asarray(y)
        self.kl_history = np.asarray(kls)
        return self.y

    def get_data(self) -> np.ndarray:
        return self.y
    getData = get_data

    def save_as_file(self, path: str, labels=None):
        """(ref BarnesHutTsne.saveAsFile — tab-separated coords [+ label])"""
        with open(path, "w") as f:
            for i, row in enumerate(self.y):
                cols = [f"{v:.6f}" for v in row]
                if labels is not None:
                    cols.append(str(labels[i]))
                f.write("\t".join(cols) + "\n")
    saveAsFile = save_as_file


class BarnesHutTsne(Tsne):
    """(ref plot/BarnesHutTsne.java:65). method='auto': the exact MXU gradient
    up to AUTO_EXACT_MAX_N points (where it beats any tree), the grid-summarized
    far field beyond — the TPU rendition of the reference's theta-controlled
    quadtree approximation (see module docstring)."""

    def __init__(self, **kw):
        kw.setdefault("method", "auto")
        super().__init__(**kw)

    class Builder:
        def __init__(self):
            self._kw = {}

        def method(self, m):
            self._kw["method"] = str(m)
            return self

        def grid_size(self, g):
            self._kw["grid_size"] = int(g)
            return self

        def setMaxIter(self, n):
            self._kw["max_iter"] = int(n)
            return self

        def perplexity(self, p):
            self._kw["perplexity"] = float(p)
            return self

        def theta(self, t):
            self._kw["theta"] = float(t)
            return self

        def learningRate(self, r):
            self._kw["learning_rate"] = float(r)
            return self

        def setMomentum(self, m):
            self._kw["momentum"] = float(m)
            return self

        def setFinalMomentum(self, m):
            self._kw["final_momentum"] = float(m)
            return self

        def stopLyingIteration(self, n):
            self._kw["stop_lying_iteration"] = int(n)
            return self

        def numDimension(self, d):
            self._kw["num_dimension"] = int(d)
            return self

        def seed(self, s):
            self._kw["seed"] = int(s)
            return self

        def build(self) -> "BarnesHutTsne":
            return BarnesHutTsne(**self._kw)
