"""t-SNE dimensionality reduction.

Parity: ref deeplearning4j-core/.../plot/BarnesHutTsne.java:65 (Builder with
perplexity/theta/maxIter/learningRate/momentum, fit(X), getData) and plot/Tsne.

TPU-first redesign: the reference approximates the repulsive forces with a
Barnes-Hut quadtree (theta) because CPU O(N^2) is slow — but the quadtree is a
pointer-chasing scalar workload. On the MXU the EXACT O(N^2) gradient is two batched
matmuls per iteration and wins for any N that fits in HBM, so `theta` is accepted
and ignored (documented delta). The optimization loop (gains + momentum + early
exaggeration, matching van der Maaten's reference schedule the Java code follows)
runs as ONE lax.scan on device.
"""
from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np


def _hbeta(d2_row, beta):
    p = jnp.exp(-d2_row * beta)
    sum_p = jnp.maximum(jnp.sum(p), 1e-12)
    h = jnp.log(sum_p) + beta * jnp.sum(d2_row * p) / sum_p
    return h, p / sum_p


@functools.partial(jax.jit, static_argnames=("tol_iters",))
def _cond_probs(d2, log_perplexity, tol_iters: int = 50):
    """Per-row binary search for beta = 1/(2 sigma^2) matching the target
    perplexity (ref Tsne/BarnesHutTsne computeGaussianPerplexity) — vectorized
    over all rows at once."""
    n = d2.shape[0]
    eye = jnp.eye(n, dtype=bool)
    d2 = jnp.where(eye, 0.0, d2)

    def row_search(d2_row, mask_row):
        def body(carry, _):
            beta, lo, hi = carry
            p = jnp.where(mask_row, 0.0, jnp.exp(-d2_row * beta))
            sum_p = jnp.maximum(jnp.sum(p), 1e-12)
            h = jnp.log(sum_p) + beta * jnp.sum(d2_row * p) / sum_p
            too_high = h > log_perplexity  # entropy too high -> raise beta
            lo = jnp.where(too_high, beta, lo)
            hi = jnp.where(too_high, hi, beta)
            beta = jnp.where(too_high,
                             jnp.where(jnp.isinf(hi), beta * 2, (beta + hi) / 2),
                             (lo + beta) / 2)
            return (beta, lo, hi), None

        (beta, _, _), _ = jax.lax.scan(
            body, (jnp.asarray(1.0, d2.dtype), jnp.asarray(0.0, d2.dtype),
                   jnp.asarray(jnp.inf, d2.dtype)), None, length=tol_iters)
        p = jnp.where(mask_row, 0.0, jnp.exp(-d2_row * beta))
        return p / jnp.maximum(jnp.sum(p), 1e-12)

    return jax.vmap(row_search)(d2, eye)


@functools.partial(jax.jit, static_argnames=("iters", "exaggeration_iters"))
def _tsne_loop(P, y0, learning_rate, momentum_start, momentum_final,
               iters: int, exaggeration_iters: int):
    n = y0.shape[0]
    eye = jnp.eye(n, dtype=bool)

    def grad_kl(y, P_eff):
        d2 = (jnp.sum(y * y, axis=1)[:, None] + jnp.sum(y * y, axis=1)[None, :]
              - 2.0 * y @ y.T)
        num = 1.0 / (1.0 + d2)              # student-t kernel
        num = jnp.where(eye, 0.0, num)
        Q = jnp.maximum(num / jnp.sum(num), 1e-12)
        PQ = (P_eff - Q) * num              # (N,N)
        g = 4.0 * ((jnp.diag(jnp.sum(PQ, axis=1)) - PQ) @ y)
        kl = jnp.sum(P_eff * jnp.log(jnp.maximum(P_eff, 1e-12) / Q))
        return g, kl

    def body(carry, it):
        y, vel, gains = carry
        exag = jnp.where(it < exaggeration_iters, 4.0, 1.0)
        mom = jnp.where(it < exaggeration_iters, momentum_start, momentum_final)
        g, kl = grad_kl(y, P * exag)
        same_sign = jnp.sign(g) == jnp.sign(vel)
        gains = jnp.maximum(
            jnp.where(same_sign, gains * 0.8, gains + 0.2), 0.01)
        vel = mom * vel - learning_rate * gains * g
        y = y + vel
        y = y - jnp.mean(y, axis=0)         # keep centered
        return (y, vel, gains), kl

    (y, _, _), kls = jax.lax.scan(
        body, (y0, jnp.zeros_like(y0), jnp.ones_like(y0)),
        jnp.arange(iters))
    return y, kls


class Tsne:
    """Exact t-SNE (ref plot/Tsne.java)."""

    def __init__(self, max_iter: int = 500, perplexity: float = 30.0,
                 learning_rate: float = 200.0, num_dimension: int = 2,
                 momentum: float = 0.5, final_momentum: float = 0.8,
                 stop_lying_iteration: int = 100, theta: float = 0.5,
                 seed: int = 12345):
        self.max_iter = int(max_iter)
        self.perplexity = float(perplexity)
        self.learning_rate = float(learning_rate)
        self.num_dimension = int(num_dimension)
        self.momentum = float(momentum)
        self.final_momentum = float(final_momentum)
        self.stop_lying_iteration = int(stop_lying_iteration)
        self.theta = float(theta)  # accepted for parity; exact gradient used
        self.seed = int(seed)
        self.y: Optional[np.ndarray] = None
        self.kl_history: Optional[np.ndarray] = None

    def fit(self, x) -> np.ndarray:
        x = jnp.asarray(x, jnp.float32)
        n = x.shape[0]
        d2 = (jnp.sum(x * x, axis=1)[:, None] + jnp.sum(x * x, axis=1)[None, :]
              - 2.0 * x @ x.T)
        cond = _cond_probs(d2, jnp.log(jnp.asarray(self.perplexity, jnp.float32)))
        P = (cond + cond.T) / (2.0 * n)
        P = jnp.maximum(P, 1e-12)
        rng = np.random.RandomState(self.seed)
        y0 = jnp.asarray(rng.randn(n, self.num_dimension) * 1e-4, jnp.float32)
        y, kls = _tsne_loop(P, y0, jnp.float32(self.learning_rate),
                            jnp.float32(self.momentum),
                            jnp.float32(self.final_momentum),
                            iters=self.max_iter,
                            exaggeration_iters=self.stop_lying_iteration)
        self.y = np.asarray(y)
        self.kl_history = np.asarray(kls)
        return self.y

    def get_data(self) -> np.ndarray:
        return self.y
    getData = get_data

    def save_as_file(self, path: str, labels=None):
        """(ref BarnesHutTsne.saveAsFile — tab-separated coords [+ label])"""
        with open(path, "w") as f:
            for i, row in enumerate(self.y):
                cols = [f"{v:.6f}" for v in row]
                if labels is not None:
                    cols.append(str(labels[i]))
                f.write("\t".join(cols) + "\n")
    saveAsFile = save_as_file


class BarnesHutTsne(Tsne):
    """API-parity alias (ref plot/BarnesHutTsne.java:65). The theta knob is
    accepted but the exact MXU gradient is used — see module docstring."""

    class Builder:
        def __init__(self):
            self._kw = {}

        def setMaxIter(self, n):
            self._kw["max_iter"] = int(n)
            return self

        def perplexity(self, p):
            self._kw["perplexity"] = float(p)
            return self

        def theta(self, t):
            self._kw["theta"] = float(t)
            return self

        def learningRate(self, r):
            self._kw["learning_rate"] = float(r)
            return self

        def setMomentum(self, m):
            self._kw["momentum"] = float(m)
            return self

        def setFinalMomentum(self, m):
            self._kw["final_momentum"] = float(m)
            return self

        def stopLyingIteration(self, n):
            self._kw["stop_lying_iteration"] = int(n)
            return self

        def numDimension(self, d):
            self._kw["num_dimension"] = int(d)
            return self

        def seed(self, s):
            self._kw["seed"] = int(s)
            return self

        def build(self) -> "BarnesHutTsne":
            return BarnesHutTsne(**self._kw)
