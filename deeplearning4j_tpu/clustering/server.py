"""Nearest-neighbors REST server + client.

Parity: ref deeplearning4j-nearestneighbors-parent/nearestneighbor-server
(NearestNeighborsServer exposing /knn over HTTP with a vectorized index) and
nearestneighbors-client. Same stdlib-HTTP rendering as the UI server; the index
is the XLA brute-force NearestNeighbors (MXU distance block), so each request is
one jitted call.
"""
from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

import numpy as np

from deeplearning4j_tpu.clustering.knn import NearestNeighbors


class NearestNeighborsServer:
    """(ref server/NearestNeighborsServer.java)"""

    def __init__(self, data, port: int = 0, distance: str = "euclidean"):
        index = NearestNeighbors(data, distance=distance)
        n_points = np.asarray(data).shape[0]

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def _json(self, obj, code=200):
                body = json.dumps(obj).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                if self.path == "/status":
                    self._json({"points": int(n_points), "ok": True})
                else:
                    self._json({"error": "not found"}, 404)

            def do_POST(self):
                if self.path != "/knn":
                    self._json({"error": "not found"}, 404)
                    return
                n = int(self.headers.get("Content-Length", "0"))
                req = json.loads(self.rfile.read(n).decode())
                k = int(req.get("k", 5))
                if "index" in req:   # query by stored point id (ref knn by index)
                    q = np.asarray(index.data[int(req["index"])])
                else:
                    q = np.asarray(req["vector"], np.float32)
                dist, idx = index.search(q, k=k)
                self._json({"indices": idx[0].tolist(),
                            "distances": dist[0].tolist()})

        self._httpd = ThreadingHTTPServer(("localhost", port), Handler)
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        daemon=True)
        self._thread.start()

    @property
    def port(self) -> int:
        return self._httpd.server_address[1]

    @property
    def address(self) -> str:
        return f"http://localhost:{self.port}"

    def stop(self):
        self._httpd.shutdown()
        self._httpd.server_close()


class NearestNeighborsClient:
    """(ref client/NearestNeighborsClient.java)"""

    def __init__(self, address: str, timeout: float = 30.0):
        self.address = address.rstrip("/")
        self.timeout = timeout

    def _post(self, path, payload):
        import urllib.request
        req = urllib.request.Request(
            self.address + path, data=json.dumps(payload).encode(),
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=self.timeout) as r:
            return json.loads(r.read().decode())

    def knn(self, vector, k: int = 5) -> dict:
        return self._post("/knn", {"vector": np.asarray(vector).tolist(),
                                   "k": int(k)})
    knnVector = knn

    def knn_by_index(self, index: int, k: int = 5) -> dict:
        return self._post("/knn", {"index": int(index), "k": int(k)})

    def status(self) -> dict:
        import urllib.request
        with urllib.request.urlopen(self.address + "/status",
                                    timeout=self.timeout) as r:
            return json.loads(r.read().decode())
